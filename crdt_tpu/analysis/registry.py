"""The two self-registration registries behind every static gate.

**Merge kinds** — every op module (``crdt_tpu/ops/*``) registers each
lattice it implements: the merge fn, a small-domain state generator,
and (where raw slot order is join-order dependent) a canonicalizer.
The lattice-law engine (:mod:`.laws`) iterates this registry; a module
that defines a ``join``/``merge`` without registering fails the
completeness test in tests/test_analysis.py. The contract for a new
CRDT kind:

    from ..analysis.registry import register_merge

    register_merge(
        "my_kind", module=__name__,
        join=join,                  # join(a, b) -> state | (state, flags)
        states=_law_states,         # () -> [identity, s1, s2, ...] — the
                                    #   FIRST state must be the join
                                    #   identity (empty); all states must
                                    #   be reachable via CmRDT ops with
                                    #   enough capacity headroom that no
                                    #   overflow flag fires
        canon=_law_canon,           # optional: state -> canonical state
                                    #   (bit-exact comparable); None if
                                    #   raw arrays are already canonical
        big_states=_law_states_big, # optional: () -> larger sampled domain
    )

**Compactors** — every merge kind additionally registers its
causal-stability compaction kernel (crdt_tpu/reclaim/): the compact fn,
the observable-read projection the compaction-invariance law compares,
and (for clocked kinds) the top-clock accessor the law derives its
frontier from. Coverage is total by contract — a merge kind without a
compactor fails tests/test_analysis.py discovery:

    from ..analysis.registry import register_compactor

    register_compactor(
        "my_kind",
        compact=compact,        # (state, frontier) ->
                                #   (state, freed_slots u32, freed_bytes f32)
        observe=_observe,       # state -> observable-read pytree
                                #   (canonical: converged replicas compare
                                #   equal leaf-wise as raw arrays)
        top_of=lambda s: s.top, # None for clockless kinds (frontier is
                                #   then None and compact must no-op
                                #   retirement)
    )

**Decompositions** — every merge kind additionally registers its
join-irreducible decomposition (crdt_tpu/delta_opt/): the
``split(state) -> (rows, residual)`` / ``unsplit`` pair the generic
row-diff decomposition builds on. Coverage is total by contract — a
merge kind without a decomposer fails the ``decomp`` static-check
section and tests/test_delta_opt.py discovery:

    from ..analysis.registry import register_decomposition

    register_decomposition(
        "my_kind", module=__name__,
        split=_decomp_split,      # state -> (rows pytree, residual):
                                  #   rows leaves share a leading lane
                                  #   axis (the per-unit δ granularity)
        unsplit=_decomp_unsplit,  # (rows, residual) -> state (exact
                                  #   inverse of split)
    )

**Mesh entry points** — every public anti-entropy entry
(``mesh_gossip*`` / ``mesh_fold*`` / ``mesh_delta_gossip*``) registers
its jit-cache kind, an example-args builder, an invoker, and how many
leading args it donates. tools/check_aliasing.py and the jit-safety
lint (:mod:`.jit_lint`) iterate this registry, and
:func:`unregistered_entry_points` scans ``crdt_tpu.parallel`` for
matching public names that forgot to register — a new entry point is
auto-discovered or CI fails.

This module must stay import-light (stdlib only): op modules import it
at definition time, so it can never import ``crdt_tpu.ops`` or
``crdt_tpu.parallel`` at module level.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple


@dataclass(frozen=True)
class MergeKind:
    """One registered lattice: the unit the law engine checks.

    ``deltas``/``apply`` are the SCHEDULE-GENERATOR hooks the bounded
    SEC model checker (:mod:`.schedules`) consumes:

    - ``deltas() -> [(origin, δ-state), ...]`` — the δ increments the
      checker delivers under every bounded schedule (reorder /
      duplication / drop-with-resync). Each δ must be a valid state
      (an inflation of the identity); ``origin`` is the minting replica
      (< schedules.MAX_REPLICAS), which orders the causal subset. When
      absent, the checker derives δs from ``states()[1:]`` with
      round-robin origins — sound for every CvRDT kind, since its
      reachable states ARE shippable δ-states.
    - ``apply(state, δ) -> state`` — op-based (CmRDT) application for
      kinds whose ops are not delivered by join. Only causal-order-
      respecting interleavings are required to converge for such kinds
      (exactly-once causal delivery is the CmRDT contract). When
      absent, delivery is the join itself and EVERY bounded schedule
      must converge.
    """

    name: str
    join: Callable[[Any, Any], Any]       # -> state | (state, flags)
    states: Callable[[], list]            # small domain; [0] = identity
    canon: Optional[Callable[[Any], Any]] = None
    big_states: Optional[Callable[[], list]] = None
    module: str = ""
    deltas: Optional[Callable[[], list]] = None   # () -> [(origin, δ), ...]
    apply: Optional[Callable[[Any, Any], Any]] = None


@dataclass(frozen=True)
class EntryPoint:
    """One registered mesh entry point.

    - ``name``: the public symbol in ``crdt_tpu.parallel``.
    - ``kind``: the entry's jit-cache key head
      (``parallel.anti_entropy._FN_CACHE`` key[0]).
    - ``make_args(mesh)``: fresh example args (R == P replica batch of
      join identities — aliasing and jaxpr shape are properties of
      shapes, not content).
    - ``invoke(mesh, args)``: run the entry once (``donate=True`` for
      donatable entries) so the memoised jit exists; consumes ``args``.
    - ``n_donated``: leading donated args (0 = the entry never aliases
      outputs onto inputs — the fold family).
    - ``mesh_axes``: the mesh axis names this entry's collectives are
      allowed to touch — the collective-semantics lint
      (:mod:`.jit_lint`) fails on any ``psum``/``ppermute``/… whose
      axis name is outside this set (a typo'd or stale axis name
      compiles fine under a matching mesh and silently reduces over
      the wrong ranks under any other).
    """

    name: str
    kind: str
    make_args: Callable[[Any], tuple]
    invoke: Callable[[Any, tuple], Any]
    n_donated: int = 0
    mesh_axes: Tuple[str, ...] = ()


@dataclass(frozen=True)
class Compactor:
    """One registered causal-stability compaction kernel (reclaim/)."""

    name: str
    compact: Callable[[Any, Any], tuple]  # (state, frontier) -> (state, n, b)
    observe: Callable[[Any], Any]         # state -> observable read
    top_of: Optional[Callable[[Any], Any]] = None
    module: str = ""


@dataclass(frozen=True)
class Decomposer:
    """One registered join-irreducible decomposition
    (crdt_tpu/delta_opt/): the split/unsplit pair the generic row-diff
    decomposition builds on — ``split(state) -> (rows, residual)`` with
    a shared leading lane axis on every row leaf, ``unsplit(rows,
    residual) -> state`` its exact inverse. Coverage is total by
    contract — a merge kind without a decomposer fails
    tests/test_delta_opt.py discovery and the ``decomp`` static-check
    section. ``decompose``/``reconstruct`` override the generic pair
    (broken-twin fixtures use this; production kinds register
    split/unsplit only)."""

    name: str
    split: Optional[Callable[[Any], Tuple[Any, Any]]] = None
    unsplit: Optional[Callable[[Any, Any], Any]] = None
    module: str = ""
    decompose: Optional[Callable[[Any, Any], Any]] = None
    reconstruct: Optional[Callable[[Any, Any], Any]] = None


@dataclass(frozen=True)
class ScaleoutSurface:
    """One registered elastic-membership surface (crdt_tpu/scaleout/):
    a public operational symbol of the scaleout package — the
    membership controller, the bootstrap shipper, the drain certifier,
    the autoscaler, their detectors. Registration is the coverage
    contract — the ``scaleout`` static-check section
    (tools/run_static_checks.py, via ``crdt_tpu.scaleout.static_checks``)
    fails discovery for any public scaleout symbol that forgot to
    register, exactly like an unregistered join, mesh entry point, or
    fault surface."""

    name: str
    module: str = ""


@dataclass(frozen=True)
class ServeSurface:
    """One registered multi-tenant serving surface (crdt_tpu/serve/):
    a public operational symbol of the serve package — the superblock
    container, the ingest queue, the evictor, the tenant shard map,
    their detectors. Registration is the coverage contract — the
    ``serve`` static-check section (tools/run_static_checks.py, via
    ``crdt_tpu.serve.static_checks``) fails discovery for any public
    serve symbol that forgot to register, exactly like an unregistered
    join, mesh entry point, or fault/scaleout surface."""

    name: str
    module: str = ""


@dataclass(frozen=True)
class FanoutSurface:
    """One registered δ-subscription fan-out surface (crdt_tpu/fanout/):
    a public operational symbol of the fanout package — the
    subscription plane, the cohort push driver, their detectors.
    Registration is the coverage contract — the ``fanout`` static-check
    section (tools/run_static_checks.py, via
    ``crdt_tpu.fanout.static_checks``) fails discovery for any public
    fanout symbol that forgot to register, exactly like an unregistered
    join, mesh entry point, or fault/scaleout/serve surface."""

    name: str
    module: str = ""


@dataclass(frozen=True)
class GeoSurface:
    """One registered geo-federation surface (crdt_tpu/geo/): a public
    operational symbol of the geo package — the region plane, the
    cross-region anti-entropy link, the watermark-read certificate
    path, the failover driver, their detectors. Registration is the
    coverage contract — the ``federation`` static-check section
    (tools/run_static_checks.py, via ``crdt_tpu.geo.static_checks``)
    fails discovery for any public geo symbol that forgot to register,
    exactly like an unregistered join, mesh entry point, or
    fault/scaleout/serve/fanout surface."""

    name: str
    module: str = ""


@dataclass(frozen=True)
class WireSurface:
    """One registered fused-wire kernel instantiation
    (crdt_tpu/parallel/wire.py over crdt_tpu/ops/wire_kernels.py): a δ
    ring kind whose packets ship through the bit-packed wire format.
    Registration is the coverage contract — the ``wire`` static-check
    section (tools/run_static_checks.py, via
    ``crdt_tpu.parallel.wire_checks.static_checks``) fails discovery
    for any δ ring kind without a registered wire surface, exactly
    like an unregistered join, entry point, or fault surface."""

    name: str
    module: str = ""


@dataclass(frozen=True)
class FaultSurface:
    """One registered fault-capable mesh entry (crdt_tpu/faults/): a
    public ``crdt_tpu.parallel`` callable that accepts a ``faults=``
    FaultPlan. Registration is the coverage contract — the ``faults``
    static-check section (tools/run_static_checks.py, via
    ``crdt_tpu.faults.static_checks``) fails discovery for any
    fault-capable public entry that forgot to register, exactly like an
    unregistered join or mesh entry point."""

    name: str
    module: str = ""


@dataclass(frozen=True)
class ObsEvent:
    """One registered flight-recorder event type (crdt_tpu/obs/): the
    schema a ``FlightRecorder.dump`` header carries so the artifact is
    self-describing. Registration is the coverage contract — the
    ``obs`` static-check section (tools/run_static_checks.py, via
    ``crdt_tpu.obs.static_checks``) AST-scans every ``emit("...")``
    site under ``crdt_tpu/`` and fails discovery for any literal event
    type without a registered schema, exactly like an unregistered
    join or mesh entry point. Register NEXT TO the emit site:

        from ..analysis.registry import register_obs_event

        register_obs_event(
            "rank_evicted", subsystem="faults.membership",
            fields=("rank",), module=__name__,
        )
    """

    name: str
    subsystem: str
    fields: Tuple[str, ...] = ()
    module: str = ""


@dataclass(frozen=True)
class SharedField:
    """One registered host-side shared-state field (crdt_tpu/analysis/
    effects.py): a mutable attribute of a serving-runtime object that
    more than one logical task may touch — the lane table, the free
    pool, the dirty flags, the WAL seq, the ack windows. Registration
    is the coverage contract of the ``concurrency`` static-check
    section: the effect-inference pass AST-scans every method of the
    host serving surface and a mutated-but-unregistered field fails
    discovery, exactly like an unregistered join, entry point, or
    flight-recorder event. Register at the BOTTOM of the owning
    module:

        from ..analysis.registry import register_shared_field

        register_shared_field(
            "lane_of", owner="Superblock", module=__name__,
            kind="tenant→lane indirection table",
        )

    ``guard`` declares an always-on ordering mechanism:
    ``"lock:<attr>"`` means every access runs under the named lock
    (the obs tracer's ``_lock`` discipline) — conflicts on such a
    field need no happens-before contract."""

    name: str
    owner: str
    kind: str
    module: str = ""
    guard: str = ""


@dataclass(frozen=True)
class EffectSource:
    """One registered host execution context that runs crdt_tpu code
    concurrently with the driver loop — a daemon thread, a background
    drain. The ``concurrency`` static-check section lints every
    ``threading.Thread`` creation site under ``crdt_tpu/`` against
    this registry: an unregistered spawner fails discovery (a thread
    nobody declared is a thread whose effects nobody analyzed)."""

    name: str
    module: str = ""
    description: str = ""


@dataclass(frozen=True)
class TraceStage:
    """One registered op-journey trace stage (crdt_tpu/obs/trace.py):
    the schema behind every ``stamp("...")`` site in the serving
    pipeline. Registration is the coverage contract — the ``slo``
    static-check section AST-scans every literal ``stamp("...")`` call
    under ``crdt_tpu/`` and fails discovery for any stage name without
    a registration, exactly like an unregistered flight-recorder event
    type. ``chain`` stages form the submit→ack completion chain (in
    ``order``); non-chain stages (evict/restore) are boundary markers
    the invariant audit reads but completion never waits on."""

    name: str
    order: int
    chain: bool = True
    module: str = ""


_MERGE: Dict[str, MergeKind] = {}
_ENTRY: Dict[str, EntryPoint] = {}
_COMPACT: Dict[str, Compactor] = {}
_DECOMP: Dict[str, Decomposer] = {}
_FAULT_SURFACES: Dict[str, FaultSurface] = {}
_WIRE_SURFACES: Dict[str, WireSurface] = {}
_SCALEOUT_SURFACES: Dict[str, ScaleoutSurface] = {}
_SERVE_SURFACES: Dict[str, ServeSurface] = {}
_FANOUT_SURFACES: Dict[str, FanoutSurface] = {}
_GEO_SURFACES: Dict[str, GeoSurface] = {}
_OBS_EVENTS: Dict[str, ObsEvent] = {}
_TRACE_STAGES: Dict[str, TraceStage] = {}
_SHARED_FIELDS: Dict[Tuple[str, str], SharedField] = {}
_EFFECT_SOURCES: Dict[str, EffectSource] = {}

# Public callables in crdt_tpu.parallel matching this are mesh entry
# points and MUST be registered (gossip_elastic/delta_gossip_elastic are
# retry wrappers over already-registered kinds; run_delta_ring is the
# generic engine the registered δ flavors instantiate). mesh_stream*
# covers the replica-streaming fold family (parallel/stream.py): an
# unregistered public mesh_stream symbol fails discovery exactly like a
# forgotten gossip/fold entry — tools/run_static_checks.py's jit-lint
# and aliasing sections both iterate this.
# mesh_serve covers the tenant-packed serving dispatch family
# (parallel/serve_apply.py — ISSUE 15).
# mesh_fanout covers the δ-subscription fan-out family
# (parallel/fanout_push.py — ISSUE 16).
ENTRY_NAME_RE = re.compile(
    r"^mesh_(gossip|fold|delta_gossip|stream|serve|fanout)"
)


def register_merge(
    name: str,
    *,
    join: Callable,
    states: Callable[[], list],
    canon: Optional[Callable] = None,
    big_states: Optional[Callable[[], list]] = None,
    module: str = "",
    deltas: Optional[Callable[[], list]] = None,
    apply: Optional[Callable] = None,
) -> MergeKind:
    kind = MergeKind(
        name=name, join=join, states=states, canon=canon,
        big_states=big_states, module=module, deltas=deltas, apply=apply,
    )
    _MERGE[name] = kind
    return kind


def register_entry_point(
    name: str,
    *,
    kind: str,
    make_args: Callable[[Any], tuple],
    invoke: Callable[[Any, tuple], Any],
    n_donated: int = 0,
    mesh_axes: Optional[Tuple[str, ...]] = None,
) -> EntryPoint:
    if mesh_axes is None:
        # Default = both gate-mesh axes, resolved from the single
        # source of truth. Lazy import: this module must stay
        # import-light (see the module docstring), and registration is
        # only ever called from modules that already import the mesh.
        from ..parallel.mesh import ELEMENT_AXIS, REPLICA_AXIS

        mesh_axes = (REPLICA_AXIS, ELEMENT_AXIS)
    ep = EntryPoint(
        name=name, kind=kind, make_args=make_args, invoke=invoke,
        n_donated=n_donated, mesh_axes=tuple(mesh_axes),
    )
    _ENTRY[name] = ep
    return ep


def register_compactor(
    name: str,
    *,
    compact: Callable,
    observe: Callable,
    top_of: Optional[Callable] = None,
    module: str = "",
) -> Compactor:
    comp = Compactor(
        name=name, compact=compact, observe=observe, top_of=top_of,
        module=module,
    )
    _COMPACT[name] = comp
    return comp


def register_decomposition(
    name: str,
    *,
    split: Optional[Callable] = None,
    unsplit: Optional[Callable] = None,
    module: str = "",
    decompose: Optional[Callable] = None,
    reconstruct: Optional[Callable] = None,
) -> Decomposer:
    if decompose is None and (split is None or unsplit is None):
        raise ValueError(
            f"register_decomposition({name!r}) needs either split+unsplit "
            f"or an explicit decompose/reconstruct override"
        )
    dec = Decomposer(
        name=name, split=split, unsplit=unsplit, module=module,
        decompose=decompose, reconstruct=reconstruct,
    )
    _DECOMP[name] = dec
    return dec


def decomposers() -> Tuple[Decomposer, ...]:
    ensure_registered()
    return tuple(_DECOMP[k] for k in sorted(_DECOMP))


def get_decomposer(name: str) -> Decomposer:
    ensure_registered()
    return _DECOMP[name]


def undecomposable_kinds() -> List[str]:
    """Merge kinds without a registered decomposition — the delta_opt/
    coverage gap list; non-empty fails tests/test_delta_opt.py and the
    ``decomp`` static-check section (the same total-coverage contract
    as joins, compactors, and mesh entry points)."""
    ensure_registered()
    return sorted(set(_MERGE) - set(_DECOMP))


def register_fault_surface(name: str, *, module: str = "") -> FaultSurface:
    fs = FaultSurface(name=name, module=module)
    _FAULT_SURFACES[name] = fs
    return fs


def register_wire_surface(name: str, *, module: str = "") -> WireSurface:
    ws = WireSurface(name=name, module=module)
    _WIRE_SURFACES[name] = ws
    return ws


def wire_surfaces() -> Tuple[WireSurface, ...]:
    import crdt_tpu.parallel.wire  # noqa: F401  (registrations import-time)

    return tuple(_WIRE_SURFACES[k] for k in sorted(_WIRE_SURFACES))


def unwired_delta_kinds() -> List[str]:
    """δ ring kinds (registered entry points whose jit-cache kind ends
    in ``delta_gossip`` — the ``run_delta_ring`` family) without a
    registered wire surface: the coverage gap list of the ``wire``
    static-check section. A new δ flavor that never wired its packets
    through the fused codec fails discovery here — the layered legacy
    path is a compatibility pin, not a place for new flavors to
    live."""
    ensure_registered()
    import crdt_tpu.parallel.wire  # noqa: F401  (registrations import-time)

    delta_kinds = {
        ep.kind for ep in _ENTRY.values()
        if ep.kind.endswith("delta_gossip")
    }
    return sorted(delta_kinds - set(_WIRE_SURFACES))


def register_scaleout_surface(
    name: str, *, module: str = ""
) -> ScaleoutSurface:
    ss = ScaleoutSurface(name=name, module=module)
    _SCALEOUT_SURFACES[name] = ss
    return ss


def scaleout_surfaces() -> Tuple[ScaleoutSurface, ...]:
    import crdt_tpu.scaleout  # noqa: F401  (registrations import-time)

    return tuple(
        _SCALEOUT_SURFACES[k] for k in sorted(_SCALEOUT_SURFACES)
    )


def _unregistered_package_surfaces(pkg_name: str, registered) -> List[str]:
    """Public OPERATIONAL symbols of one package that never registered
    — the shared discovery walk behind the scaleout AND serve surface
    gates (one home, so the data-carrier exemption rules cannot
    drift). Two levels, like the entry-point/fault gates: the package
    surface plus every submodule's own definitions, so a symbol that
    skipped the ``__init__`` re-export list cannot hide. Pure data
    carriers are exempt: NamedTuple reports, frozen dataclass
    certificates, and exception types are results, not surfaces."""
    import dataclasses
    import importlib
    import inspect
    import pkgutil

    pkg = importlib.import_module(pkg_name)

    def is_surface(n: str, obj) -> bool:
        if n.startswith("_") or not callable(obj):
            return False
        if inspect.isclass(obj):
            if issubclass(obj, BaseException):
                return False
            if hasattr(obj, "_fields") or dataclasses.is_dataclass(obj):
                return False
        return getattr(obj, "__module__", "").startswith(pkg_name)

    found = {n for n in dir(pkg) if is_surface(n, getattr(pkg, n))}
    for info in pkgutil.iter_modules(pkg.__path__):
        mod = importlib.import_module(f"{pkg_name}.{info.name}")
        for n in dir(mod):
            obj = getattr(mod, n)
            if (is_surface(n, obj)
                    and getattr(obj, "__module__", "") == mod.__name__):
                found.add(n)
    return sorted(found - set(registered))


def unregistered_scaleout_surfaces() -> List[str]:
    """Public operational ``crdt_tpu.scaleout`` symbols that never
    called :func:`register_scaleout_surface` — the discovery gate of
    the ``scaleout`` static-check section
    (:func:`_unregistered_package_surfaces` is the walk)."""
    return _unregistered_package_surfaces(
        "crdt_tpu.scaleout", _SCALEOUT_SURFACES
    )


def register_serve_surface(name: str, *, module: str = "") -> ServeSurface:
    sv = ServeSurface(name=name, module=module)
    _SERVE_SURFACES[name] = sv
    return sv


def serve_surfaces() -> Tuple[ServeSurface, ...]:
    import crdt_tpu.serve  # noqa: F401  (registrations import-time)

    return tuple(_SERVE_SURFACES[k] for k in sorted(_SERVE_SURFACES))


def unregistered_serve_surfaces() -> List[str]:
    """Public operational ``crdt_tpu.serve`` symbols that never called
    :func:`register_serve_surface` — the discovery gate of the
    ``serve`` static-check section
    (:func:`_unregistered_package_surfaces` is the walk)."""
    return _unregistered_package_surfaces(
        "crdt_tpu.serve", _SERVE_SURFACES
    )


def register_fanout_surface(name: str, *, module: str = "") -> FanoutSurface:
    fo = FanoutSurface(name=name, module=module)
    _FANOUT_SURFACES[name] = fo
    return fo


def fanout_surfaces() -> Tuple[FanoutSurface, ...]:
    import crdt_tpu.fanout  # noqa: F401  (registrations import-time)

    return tuple(_FANOUT_SURFACES[k] for k in sorted(_FANOUT_SURFACES))


def unregistered_fanout_surfaces() -> List[str]:
    """Public operational ``crdt_tpu.fanout`` symbols that never called
    :func:`register_fanout_surface` — the discovery gate of the
    ``fanout`` static-check section
    (:func:`_unregistered_package_surfaces` is the walk)."""
    return _unregistered_package_surfaces(
        "crdt_tpu.fanout", _FANOUT_SURFACES
    )


def register_geo_surface(name: str, *, module: str = "") -> GeoSurface:
    gs = GeoSurface(name=name, module=module)
    _GEO_SURFACES[name] = gs
    return gs


def geo_surfaces() -> Tuple[GeoSurface, ...]:
    import crdt_tpu.geo  # noqa: F401  (registrations import-time)

    return tuple(_GEO_SURFACES[k] for k in sorted(_GEO_SURFACES))


def unregistered_geo_surfaces() -> List[str]:
    """Public operational ``crdt_tpu.geo`` symbols that never called
    :func:`register_geo_surface` — the discovery gate of the
    ``federation`` static-check section
    (:func:`_unregistered_package_surfaces` is the walk)."""
    return _unregistered_package_surfaces(
        "crdt_tpu.geo", _GEO_SURFACES
    )


def register_obs_event(
    name: str, *, subsystem: str, fields: Tuple[str, ...] = (),
    module: str = "",
) -> ObsEvent:
    ev = ObsEvent(
        name=name, subsystem=subsystem, fields=tuple(fields), module=module,
    )
    _OBS_EVENTS[name] = ev
    return ev


def obs_events() -> Tuple[ObsEvent, ...]:
    _import_obs_emitters()
    return tuple(_OBS_EVENTS[k] for k in sorted(_OBS_EVENTS))


def get_obs_event(name: str) -> ObsEvent:
    _import_obs_emitters()
    return _OBS_EVENTS[name]


_EMIT_SCAN_MEMO: Optional[List[Tuple[str, str, str]]] = None


def _scan_emit_sites() -> List[Tuple[str, str, str]]:
    """AST-walk every module under ``crdt_tpu/`` for flight-recorder
    emit sites — calls named ``emit`` (bare or attribute, e.g.
    ``obs.emit``) whose first argument is a string literal. Returns
    ``(event_type, 'relpath:lineno', dotted_module)`` rows. Literal
    scanning IS the contract: an event type minted from a runtime
    string cannot be schema'd in a dump header, so it should not
    exist. Memoised for the process — source files cannot change
    mid-run, and every ``FlightRecorder.dump`` (including the
    auto-dumps on recovery/failure boundaries) reads the registry
    through this walk."""
    global _EMIT_SCAN_MEMO
    if _EMIT_SCAN_MEMO is not None:
        return _EMIT_SCAN_MEMO
    import ast
    import os

    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    rows: List[Tuple[str, str, str]] = []
    for dirpath, dirnames, filenames in os.walk(pkg_root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, os.path.dirname(pkg_root))
            try:
                with open(path) as f:
                    tree = ast.parse(f.read(), filename=path)
            except (OSError, SyntaxError):
                continue
            mod = rel[:-3].replace(os.sep, ".")
            if mod.endswith(".__init__"):
                mod = mod[: -len(".__init__")]
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call) or not node.args:
                    continue
                fname = (
                    node.func.id if isinstance(node.func, ast.Name)
                    else node.func.attr
                    if isinstance(node.func, ast.Attribute) else ""
                )
                if fname != "emit":
                    continue
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and isinstance(
                    arg.value, str
                ):
                    rows.append((arg.value, f"{rel}:{node.lineno}", mod))
    _EMIT_SCAN_MEMO = rows
    return rows


_OBS_EMITTERS_IMPORTED = False


def _import_obs_emitters() -> None:
    """Import every module containing an emit site (plus the recorder,
    which owns the telemetry/auto_dump types) so their import-time
    registrations have run before a coverage diff or a dump header
    reads the table. Once per process — registration is import-time,
    so a second pass can discover nothing new."""
    global _OBS_EMITTERS_IMPORTED
    if _OBS_EMITTERS_IMPORTED:
        return
    import importlib

    for _, _, mod in _scan_emit_sites():
        try:
            importlib.import_module(mod)
        except ImportError:
            pass  # the coverage diff will name the orphan site anyway
    importlib.import_module("crdt_tpu.obs.recorder")
    _OBS_EMITTERS_IMPORTED = True


def unregistered_obs_events() -> List[Tuple[str, str]]:
    """``(event_type, site)`` for every literal flight-recorder emit
    site under ``crdt_tpu/`` whose event type never called
    :func:`register_obs_event` — the discovery gate of the ``obs``
    static-check section. An event-emitting subsystem without a
    registered schema fails here, the same
    registration-is-the-coverage-contract rule as joins, compactors,
    entry points, and fault/scaleout surfaces."""
    _import_obs_emitters()
    return sorted(
        (etype, where)
        for etype, where, _ in _scan_emit_sites()
        if etype not in _OBS_EVENTS
    )


def register_trace_stage(
    name: str, *, order: int, chain: bool = True, module: str = "",
) -> TraceStage:
    st = TraceStage(name=name, order=order, chain=chain, module=module)
    _TRACE_STAGES[name] = st
    return st


def trace_stages() -> Tuple[TraceStage, ...]:
    """Every registered trace stage, in chain order (crdt_tpu/obs/
    trace.py registers all of them at import — ONE home, so a stamp
    site cannot invent a stage the SLO derivations do not know)."""
    import importlib

    importlib.import_module("crdt_tpu.obs.trace")
    return tuple(
        sorted(_TRACE_STAGES.values(), key=lambda s: (s.order, s.name))
    )


_STAMP_SCAN_MEMO: Optional[List[Tuple[str, str, str]]] = None


def _scan_stamp_sites() -> List[Tuple[str, str, str]]:
    """AST-walk every module under ``crdt_tpu/`` for trace-stamp sites
    — calls named ``stamp`` (bare or attribute, e.g. ``trace.stamp``)
    whose first argument is a string literal. Returns
    ``(stage, 'relpath:lineno', dotted_module)`` rows; the same
    literal-scanning contract (and memoisation) as
    :func:`_scan_emit_sites`: a stage minted from a runtime string
    cannot be derived into an SLO latency, so it should not exist."""
    global _STAMP_SCAN_MEMO
    if _STAMP_SCAN_MEMO is not None:
        return _STAMP_SCAN_MEMO
    import ast
    import os

    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    rows: List[Tuple[str, str, str]] = []
    for dirpath, dirnames, filenames in os.walk(pkg_root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, os.path.dirname(pkg_root))
            try:
                with open(path) as f:
                    tree = ast.parse(f.read(), filename=path)
            except (OSError, SyntaxError):
                continue
            mod = rel[:-3].replace(os.sep, ".")
            if mod.endswith(".__init__"):
                mod = mod[: -len(".__init__")]
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call) or not node.args:
                    continue
                fname = (
                    node.func.id if isinstance(node.func, ast.Name)
                    else node.func.attr
                    if isinstance(node.func, ast.Attribute) else ""
                )
                if fname != "stamp":
                    continue
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and isinstance(
                    arg.value, str
                ):
                    rows.append((arg.value, f"{rel}:{node.lineno}", mod))
    _STAMP_SCAN_MEMO = rows
    return rows


def unregistered_trace_stages() -> List[Tuple[str, str]]:
    """``(stage, site)`` for every literal trace-stamp site under
    ``crdt_tpu/`` whose stage name never called
    :func:`register_trace_stage` — the discovery gate of the ``slo``
    static-check section (registration-is-the-coverage-contract, the
    :func:`unregistered_obs_events` rule for the trace plane)."""
    trace_stages()  # import-time registrations (crdt_tpu.obs.trace)
    return sorted(
        (stage, where)
        for stage, where, _ in _scan_stamp_sites()
        if stage not in _TRACE_STAGES
    )


def register_shared_field(
    name: str, *, owner: str, kind: str, module: str = "", guard: str = "",
) -> SharedField:
    sf = SharedField(
        name=name, owner=owner, kind=kind, module=module, guard=guard,
    )
    _SHARED_FIELDS[(owner, name)] = sf
    return sf


def register_effect_source(
    name: str, *, module: str = "", description: str = "",
) -> EffectSource:
    src = EffectSource(name=name, module=module, description=description)
    _EFFECT_SOURCES[name] = src
    return src


_HOST_SURFACE_IMPORTED = False


def _import_host_surface() -> None:
    """Import every host serving-surface module (the survey list lives
    in ``crdt_tpu.analysis.effects`` — ONE home, shared with the AST
    pass) so their bottom-of-module ``register_shared_field`` /
    ``register_effect_source`` calls have run before a coverage diff
    reads the tables. Once per process, same as
    :func:`_import_obs_emitters`."""
    global _HOST_SURFACE_IMPORTED
    if _HOST_SURFACE_IMPORTED:
        return
    import importlib

    effects = importlib.import_module("crdt_tpu.analysis.effects")
    for mod in effects.HOST_SURFACE_MODULES:
        importlib.import_module(mod)
    _HOST_SURFACE_IMPORTED = True


def shared_fields() -> Tuple[SharedField, ...]:
    """Every registered host shared-state field, sorted (owner, name).
    Each host-surface module registers its own fields at the bottom —
    importing the surface first makes 'iterate the registry'
    deterministic regardless of what the caller already imported."""
    _import_host_surface()
    return tuple(_SHARED_FIELDS[k] for k in sorted(_SHARED_FIELDS))


def get_shared_field(owner: str, name: str) -> SharedField:
    _import_host_surface()
    return _SHARED_FIELDS[(owner, name)]


def effect_sources() -> Tuple[EffectSource, ...]:
    """Every registered concurrent host execution context (daemon
    threads and background drains), sorted by name."""
    _import_host_surface()
    return tuple(_EFFECT_SOURCES[k] for k in sorted(_EFFECT_SOURCES))


def fault_surfaces() -> Tuple[FaultSurface, ...]:
    ensure_registered()
    return tuple(_FAULT_SURFACES[k] for k in sorted(_FAULT_SURFACES))


def _discover_public(match) -> set:
    """Two-level discovery over ``crdt_tpu.parallel``: the package
    surface AND every submodule's own definitions (by ``__module__``),
    so a symbol that skipped the ``parallel/__init__`` re-export list
    cannot hide from a coverage gate. ``match(name, obj)`` is the
    predicate — ONE home for the walk, shared by the entry-point and
    fault-surface gates so discovery-rule fixes cannot drift apart."""
    import importlib
    import pkgutil

    import crdt_tpu.parallel as par

    found = {n for n in dir(par) if match(n, getattr(par, n))}
    for info in pkgutil.iter_modules(par.__path__):
        mod = importlib.import_module(f"crdt_tpu.parallel.{info.name}")
        for n in dir(mod):
            obj = getattr(mod, n)
            if (match(n, obj)
                    and getattr(obj, "__module__", "") == mod.__name__):
                found.add(n)
    return found


def unregistered_fault_surfaces() -> List[str]:
    """Fault-capable public callables in ``crdt_tpu.parallel`` (a
    ``faults`` parameter in the signature) that never called
    :func:`register_fault_surface`. Same two-level discovery as
    :func:`unregistered_entry_points` — so a fault-capable entry cannot
    hide from the gate by skipping the re-export list."""
    import inspect

    ensure_registered()

    def takes_faults(n, obj) -> bool:
        if n.startswith("_") or not callable(obj):
            return False
        try:
            return "faults" in inspect.signature(obj).parameters
        except (TypeError, ValueError):
            return False

    return sorted(_discover_public(takes_faults) - set(_FAULT_SURFACES))


def compactors() -> Tuple[Compactor, ...]:
    ensure_registered()
    return tuple(_COMPACT[k] for k in sorted(_COMPACT))


def get_compactor(name: str) -> Compactor:
    ensure_registered()
    return _COMPACT[name]


def uncompactable_kinds() -> List[str]:
    """Merge kinds without a registered compactor — the reclaim/
    coverage gap list; non-empty fails tests/test_analysis.py (the same
    total-coverage contract as joins and mesh entry points)."""
    ensure_registered()
    return sorted(set(_MERGE) - set(_COMPACT))


def merge_kinds() -> Tuple[MergeKind, ...]:
    ensure_registered()
    return tuple(_MERGE[k] for k in sorted(_MERGE))


def get_merge_kind(name: str) -> MergeKind:
    ensure_registered()
    return _MERGE[name]


def entry_points(donatable: Optional[bool] = None) -> Tuple[EntryPoint, ...]:
    ensure_registered()
    eps = tuple(_ENTRY[k] for k in sorted(_ENTRY))
    if donatable is None:
        return eps
    return tuple(ep for ep in eps if (ep.n_donated > 0) == donatable)


def registered_entry_names() -> Tuple[str, ...]:
    ensure_registered()
    return tuple(sorted(_ENTRY))


def unregistered_entry_points() -> List[str]:
    """Mesh-entry-shaped public callables that never registered — each
    one fails the aliasing gate. :func:`_discover_public` scans the
    package surface AND every submodule's own definitions, so an entry
    point that also skipped the ``parallel/__init__`` re-export list
    cannot hide from the gate."""
    ensure_registered()
    found = _discover_public(
        lambda n, obj: bool(ENTRY_NAME_RE.match(n)) and callable(obj)
    )
    return sorted(found - set(_ENTRY))


_ENSURED = False


def ensure_registered() -> None:
    """Import every module carrying registrations (idempotent). Op
    modules and the parallel package self-register at import time; this
    makes 'iterate the registry' deterministic regardless of what the
    caller already imported."""
    global _ENSURED
    if _ENSURED:
        return
    import importlib
    import pkgutil

    # EVERY ops module, discovered not hardcoded — a new ops/foo.py that
    # calls register_merge() is picked up with no registry edit (and one
    # that defines a join without registering fails the completeness
    # test in tests/test_analysis.py).
    import crdt_tpu.ops as ops_pkg

    for info in pkgutil.iter_modules(ops_pkg.__path__):
        importlib.import_module(f"crdt_tpu.ops.{info.name}")
    # Mesh entry points (anti_entropy, delta*, sparse_shard).
    importlib.import_module("crdt_tpu.parallel")
    # Only mark done once EVERY registration module imported — a failed
    # import must retry (and re-raise) on the next call, not leave the
    # registry silently partial for the rest of the process.
    _ENSURED = True
