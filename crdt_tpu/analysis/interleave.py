"""Deterministic interleaving explorer for the host serving runtime.

The PR 7 schedule-space discipline applied to host concurrency: the
device program is proved over EVERY delivery schedule, so the host
program gets the same treatment over every bounded-preemption
interleaving of its logical tasks (serve step, background persist,
fanout push, client acks, pressure eviction). The serving modules are
instrumented with :func:`boundary` markers at exactly the declared
happens-before points (``HB_CONTRACTS`` — WAL group-commit, dispatch
issue/finish, the settled persist window, persist/clear/pick,
push warm/snapshot/dispatch, ack promote); in production the marker
is a no-op attribute read, the ``obs.trace.stamp`` discipline.

Under the explorer each task runs on a lockstep daemon thread —
exactly ONE thread is ever runnable, the scheduler hands control over
at boundary crossings named by the schedule, so every run is fully
deterministic and replayable from its schedule alone. The explorer
enumerates ALL schedules with at most ``preemptions`` (default 2)
context switches at boundary points, requiring every run to (a) raise
nothing, (b) satisfy the world's invariants (acked ⊆ durable, no
dispatch-while-evicted, persist-then-clear residue, monotonic
sub_ver), and (c) finish BIT-IDENTICAL to the serial oracle. A
failure is shrunk to a minimal schedule and reported as a
``concur_counterexample`` flight-recorder event (auto-dumped like
every other loud failure).
"""

from __future__ import annotations

import itertools
import shutil
import threading
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..utils.metrics import metrics

# ---- the production hook -------------------------------------------------

_ACTIVE: Optional["_Run"] = None


def boundary(label: str) -> None:
    """Mark one declared HB boundary point. No-op in production (one
    global read); under an active explorer run this is where a
    schedule may hand control to another task."""
    run = _ACTIVE
    if run is not None:
        run._at_boundary(label)


# ---- lockstep scheduler --------------------------------------------------


class _TaskRunner:
    __slots__ = ("name", "fn", "go", "done", "exc")

    def __init__(self, name: str, fn: Callable[[], None]):
        self.name = name
        self.fn = fn
        self.go = threading.Semaphore(0)
        self.done = False
        self.exc: Optional[BaseException] = None


class _Run:
    """One deterministic execution: tasks in declared order, a
    schedule mapping global boundary-event index -> round-robin offset
    (1 = next alive task). Strict lockstep: the scheduler and exactly
    one task thread alternate via semaphores, so shared state is never
    actually raced — only logically interleaved."""

    def __init__(self, tasks: Sequence[Tuple[str, Callable]],
                 schedule: Dict[int, int]):
        self.tasks = [_TaskRunner(n, f) for n, f in tasks]
        self.schedule = dict(schedule)
        self.event = 0
        self.trace: List[Tuple[str, str]] = []
        self.current = 0
        self._ctl = threading.Semaphore(0)
        self._preempt: Optional[int] = None

    def _alive(self) -> List[int]:
        return [i for i, t in enumerate(self.tasks) if not t.done]

    def _next_alive(self, frm: int, off: int) -> int:
        alive = self._alive()
        if not alive:
            return frm
        later = [i for i in alive if i > frm] + [i for i in alive if i <= frm]
        return later[(off - 1) % len(later)]

    # runs ON the task thread
    def _at_boundary(self, label: str) -> None:
        me = self.tasks[self.current]
        self.trace.append((me.name, label))
        off = self.schedule.pop(self.event, None)
        self.event += 1
        if off is not None and len(self._alive()) > 1:
            self._preempt = off
            self._ctl.release()
            me.go.acquire()

    def _body(self, t: _TaskRunner) -> Callable[[], None]:
        def run() -> None:
            t.go.acquire()
            try:
                t.fn()
            except BaseException as exc:  # reported, never swallowed
                t.exc = exc
            t.done = True
            self._ctl.release()

        return run

    def run(self) -> "_Run":
        global _ACTIVE
        if _ACTIVE is not None:
            raise RuntimeError("nested interleaving runs are not supported")
        for t in self.tasks:
            threading.Thread(
                target=self._body(t), name=f"ilv-{t.name}", daemon=True,
            ).start()
        _ACTIVE = self
        try:
            while self._alive():
                self.current = (
                    self.current if not self.tasks[self.current].done
                    else self._next_alive(self.current, 1)
                )
                t = self.tasks[self.current]
                t.go.release()
                self._ctl.acquire()
                if self._preempt is not None:
                    off, self._preempt = self._preempt, None
                    self.current = self._next_alive(self.current, off)
        finally:
            _ACTIVE = None
        return self

    def errors(self) -> List[str]:
        return [
            f"task '{t.name}' raised {type(t.exc).__name__}: {t.exc}"
            for t in self.tasks if t.exc is not None
        ]


# ---- worlds --------------------------------------------------------------


@dataclass
class World:
    """One explorable workload: ``tasks`` are the logical threads,
    ``check()`` returns invariant violations after all tasks complete
    (run serially — boundaries are inert), ``fingerprint()`` the
    bit-comparable final state, ``cleanup()`` releases disk."""

    name: str
    tasks: List[Tuple[str, Callable[[], None]]]
    check: Callable[[], List[str]]
    fingerprint: Callable[[], tuple]
    cleanup: Callable[[], None] = lambda: None


@dataclass(frozen=True)
class Counterexample:
    schedule: Tuple[Tuple[int, int], ...]  # ((event index, offset), ...)
    trace: Tuple[Tuple[str, str], ...]     # (task, boundary) events
    reasons: Tuple[str, ...]


@dataclass(frozen=True)
class ExploreResult:
    world: str
    schedules: int          # schedules explored (incl. the serial oracle)
    events: int             # boundary events in the serial run
    counterexample: Optional[Counterexample]

    @property
    def ok(self) -> bool:
        return self.counterexample is None


def _run_one(
    make_world: Callable[[], World], schedule: Dict[int, int],
) -> Tuple[World, _Run, List[str]]:
    w = make_world()
    try:
        r = _Run(w.tasks, schedule).run()
        errs = r.errors()
        if not errs:
            errs = list(w.check())
        return w, r, errs
    except BaseException:
        w.cleanup()
        raise


def explore(
    make_world: Callable[[], World],
    *,
    preemptions: int = 2,
    offsets: Optional[Sequence[int]] = None,
) -> ExploreResult:
    """Exhaustively run every schedule with at most ``preemptions``
    boundary-point context switches, checking each against the world's
    invariants and the serial oracle's bit-exact fingerprint.
    Enumeration goes by ascending preemption count, so the first
    failure is already preemption-minimal; it is then shrunk (drop
    each switch that is not needed to reproduce) and returned. Fully
    deterministic: no randomness, no wall clock — the schedule IS the
    reproduction recipe."""
    from ..obs import recorder as _rec

    w0, r0, errs0 = _run_one(make_world, {})
    oracle = w0.fingerprint()
    name = w0.name
    w0.cleanup()
    explored = 1
    if errs0:
        metrics.count("analysis.concur.schedules_explored", explored)
        return ExploreResult(name, explored, r0.event, Counterexample(
            (), tuple(r0.trace), tuple(errs0),
        ))
    n_events = r0.event
    n_tasks = len(r0.tasks)
    offs = tuple(offsets) if offsets else tuple(range(1, n_tasks))

    def fails(sched: Dict[int, int]) -> Optional[Tuple[_Run, List[str]]]:
        w, r, errs = _run_one(make_world, sched)
        try:
            if not errs and w.fingerprint() != oracle:
                errs = [
                    "final state diverged bit-wise from the serial oracle"
                ]
            return (r, errs) if errs else None
        finally:
            w.cleanup()

    def schedules():
        for k in range(1, preemptions + 1):
            for events in itertools.combinations(range(n_events), k):
                for offsets_k in itertools.product(offs, repeat=k):
                    yield dict(zip(events, offsets_k))

    for sched in schedules():
        explored += 1
        bad = fails(sched)
        if bad is None:
            continue
        # shrink: drop any switch not needed to reproduce
        cur = sorted(sched.items())
        changed = True
        while changed and len(cur) > 1:
            changed = False
            for i in range(len(cur)):
                cand = dict(cur[:i] + cur[i + 1:])
                explored += 1
                if fails(cand) is not None:
                    cur = sorted(cand.items())
                    changed = True
                    break
        r, errs = fails(dict(cur)) or (None, ["unreproducible after shrink"])
        explored += 1
        metrics.count("analysis.concur.schedules_explored", explored)
        cx = Counterexample(
            tuple(cur), tuple(r.trace if r else ()), tuple(errs),
        )
        _rec.emit(
            "concur_counterexample", world=name,
            schedule=list(map(list, cx.schedule)),
            reasons=list(cx.reasons)[:4],
        )
        from .. import obs

        obs.auto_dump("concur_counterexample", world=name)
        return ExploreResult(name, explored, n_events, cx)
    metrics.count("analysis.concur.schedules_explored", explored)
    return ExploreResult(name, explored, n_events, None)


# ---- the committed workloads ---------------------------------------------

_DENSE_CAPS = dict(n_elems=8, n_actors=2, deferred_cap=2)
_SPARSE_CAPS = dict(dot_cap=12, n_actors=2, deferred_cap=2, rm_width=4)


def _caps_for(kind: str) -> dict:
    return dict(_DENSE_CAPS if kind == "orswot" else _SPARSE_CAPS)


def _member_for(kind: str, caps: dict, *on):
    import numpy as np

    if kind == "orswot":
        return np.isin(np.arange(caps["n_elems"]), on)
    out = np.full(caps["rm_width"], -1, np.int32)
    out[: len(on)] = on
    return out


def serve_world(kind: str = "orswot", *, ops_per_tenant: int = 2,
                serve_tenants: int = 1) -> World:
    """The serve workload: a WAL'd pipelined loop draining queued ops
    (task ``serve``), a background persister pass over every tenant
    (task ``persist``), and a pressure admission of a cold tenant that
    excludes the serving set (task ``evict`` — the pin discipline a
    production pressure pick follows). Invariants: nothing in flight
    at the end, ops all applied, the lane table/free pool consistent,
    and NO dirty non-resident tenant (dirt may only leave a lane via a
    persist — the persist-≺-clear residue). Fingerprint: every
    tenant's LOGICAL row (resident lane, else durable record, else ⊥)
    — bit-identical however the schedule paged lanes."""
    import os
    import tempfile

    import jax
    import numpy as np

    from ..parallel import make_mesh
    from ..serve.evict import Evictor, restore_tenant
    from ..serve.ingest import IngestQueue
    from ..serve.loop import BackgroundPersister, ServeLoop
    from ..serve.wal import ServeWal

    from ..serve.superblock import Superblock

    caps = _caps_for(kind)
    root = tempfile.mkdtemp(prefix="ilv-serve-")
    mesh = make_mesh(1, 1)
    n_tenants = serve_tenants + 3  # + warm dirty, warm clean, cold
    sb = Superblock(
        n_tenants, mesh, kind=kind, caps=dict(caps),
        n_lanes=serve_tenants + 2,
    )
    ev = Evictor(sb, os.path.join(root, "tier"), pressure_batch=1)
    swal = ServeWal(os.path.join(root, "wal"))
    q = IngestQueue(sb, lanes=1, depth=2, evictor=ev, wal=swal)
    loop = ServeLoop(q, persist_ahead=0)
    bp = BackgroundPersister(ev, batch=4)
    warm_dirty = serve_tenants
    warm_clean = serve_tenants + 1
    cold = serve_tenants + 2
    # settle two warm tenants before the tasks race (boundaries are
    # inert here — no explorer run is active during construction)
    for t in (warm_dirty, warm_clean):
        q.add(t, 0, 1, _member_for(kind, caps, t % 3))
    loop.drain()
    ev.persist([warm_clean])
    sb.dirty[warm_dirty] = True  # the persister's target stays dirty
    serve_set = tuple(range(serve_tenants))
    for t in serve_set:
        for i in range(ops_per_tenant):
            q.add(t, i % caps["n_actors"], 1 + i // caps["n_actors"],
                  _member_for(kind, caps, (t + i) % 3))
    n_ops = serve_tenants * ops_per_tenant

    box = {"applied": 0}

    def serve() -> None:
        rep, _ = loop.drain()
        box["applied"] += rep.ops_applied

    def persist() -> None:
        bp.enqueue(range(n_tenants))
        bp.drain()

    def evict() -> None:
        # Pressure admission of the cold tenant: the pick excludes the
        # serving set, exactly what restore(_exclude=pins) guarantees.
        ev.restore(cold, _exclude=serve_set)

    def check() -> List[str]:
        out: List[str] = []
        if loop.inflight is not None:
            out.append("slab still in flight after drain")
        if box["applied"] != n_ops or q.n_pending:
            out.append(
                f"applied {box['applied']}/{n_ops} ops with "
                f"{q.n_pending} still pending — ingest lost or stalled ops"
            )
        lanes = np.asarray(sb.lane_of)
        resident = np.where(lanes >= 0)[0]
        if len(set(lanes[resident].tolist())) != len(resident):
            out.append("two tenants share a lane")
        for t in resident:
            if int(sb.tenant_of[lanes[t]]) != int(t):
                out.append(f"lane table asymmetric at tenant {int(t)}")
        if len(sb._free) + len(resident) != sb.n_lanes:
            out.append("free pool and resident set disagree on lanes")
        dirty_gone = np.where(np.asarray(sb.dirty) & (lanes < 0))[0]
        if len(dirty_gone):
            out.append(
                f"dirty non-resident tenants {dirty_gone.tolist()} — a "
                f"lane was cleared before its dirt persisted"
            )
        return out

    def fingerprint() -> tuple:
        rows = []
        for t in range(n_tenants):
            if int(sb.lane_of[t]) >= 0:
                row = sb.row(t)
            elif bool(sb.was_evicted[t]):
                row = restore_tenant(
                    os.path.join(root, "tier"), kind, t, sb.empty_row()
                )
            else:
                row = sb.empty_row()
            rows.append(tuple(
                np.asarray(x).tobytes() for x in jax.tree.leaves(row)
            ))
        return tuple(rows)

    def cleanup() -> None:
        swal.close()
        shutil.rmtree(root, ignore_errors=True)

    return World(
        name=f"serve/{kind}",
        tasks=[("serve", serve), ("persist", persist), ("evict", evict)],
        check=check, fingerprint=fingerprint, cleanup=cleanup,
    )


def fanout_world(kind: str = "orswot", *, plane_cls=None,
                 evict_pushed: bool = False) -> World:
    """The fanout workload: one push cycle shipping a dirty tenant to
    two subscribers (task ``push``), the clients acking what they
    decoded (task ``ack``), and an eviction (task ``evict``) — of a
    DISJOINT warm tenant by default (what a pin-honoring pressure pick
    may legally take mid-cycle). ``evict_pushed=True`` aims the
    eviction at the pushed tenant itself and ``plane_cls`` swaps in a
    twin — together they rebuild the PR 16 lane-eviction race as a
    fixture (``analysis.fixtures.racy_fanout_world``). After the
    tasks, a serial settle cycle converges stragglers; every client
    must land bit-identical to the served row and sub_ver must never
    regress."""
    import os
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..fanout.client import ClientReplica
    from ..fanout.plane import FanoutPlane
    from ..ops import superblock as sb_ops
    from ..parallel import make_mesh
    from ..serve.evict import Evictor
    from ..serve.superblock import Superblock

    caps = _caps_for(kind)
    root = tempfile.mkdtemp(prefix="ilv-fanout-")
    mesh = make_mesh(1, 1)
    sb = Superblock(3, mesh, kind=kind, caps=dict(caps), n_lanes=2)
    ev = Evictor(sb, os.path.join(root, "tier"), pressure_batch=1)
    cls = plane_cls or FanoutPlane
    plane = cls(sb, evictor=ev, window_cap=4, dispatch_lanes=1, capacity=4)
    ids = plane.subscribe([0, 0])
    clients = {
        int(i): ClientReplica(kind, sb.empty_row()) for i in ids
    }

    def touch(t: int, *on) -> None:
        lane = sb.ensure_resident(t)
        row = sb_ops.unpack(sb.state, lane)
        row, _ = sb.tk.apply_add(
            row, jnp.int32(0), jnp.uint32(1),
            jnp.asarray(_member_for(kind, caps, *on)),
        )
        sb.state = sb_ops.write_rows(
            sb.state, jnp.asarray([lane], jnp.int32),
            jax.tree.map(lambda x: x[None], row),
        )
        sb.dirty[t] = True
        ev.note_touch(t)

    touch(0, 0, 1)
    plane.note_dirty([0])
    touch(1, 2)           # the disjoint evictable neighbor
    ev.persist([1])       # clean, so the evict task is persist-free

    def deliver(rep) -> None:
        for cp in rep.pushes:
            for s in cp.members:
                clients[int(s)].apply_wire(cp.wire, cp.to_ver)
        for rs in rep.resyncs:
            for s in rs.members:
                clients[int(s)].adopt(rs.state, rs.to_ver)

    sub_ver_seen = {int(i): 0 for i in ids}

    def push() -> None:
        deliver(plane.push())

    def ack() -> None:
        for i in ids:
            clients[int(i)].ack()
        plane.ack(ids, versions=[clients[int(i)].ver for i in ids])
        for i in ids:
            v = int(plane.sub_ver[int(i)])
            if v < sub_ver_seen[int(i)]:
                raise AssertionError(
                    f"sub_ver regressed for subscriber {int(i)}"
                )
            sub_ver_seen[int(i)] = v

    def evict() -> None:
        ev.evict([0 if evict_pushed else 1])

    def check() -> List[str]:
        # serial settle: converge stragglers, then compare bit-exact
        deliver(plane.push())
        for i in ids:
            clients[int(i)].ack()
        plane.ack(ids, versions=[clients[int(i)].ver for i in ids])
        out: List[str] = []
        for i in ids:
            v = int(plane.sub_ver[int(i)])
            if v < sub_ver_seen[int(i)]:
                out.append(f"settle regressed sub_ver for {int(i)}")
        if int(sb.lane_of[0]) < 0:
            ev.restore(0)
        want = sb.row(0)
        for i in ids:
            if not clients[int(i)].equals(want):
                out.append(
                    f"client {int(i)} diverged from the served tenant "
                    f"(wrong δ base shipped mid-race?)"
                )
        return out

    def fingerprint() -> tuple:
        rows = [tuple(
            np.asarray(x).tobytes() for x in jax.tree.leaves(sb.row(0))
        )]
        for i in sorted(clients):
            rows.append((
                int(clients[i].ver),
                tuple(
                    np.asarray(x).tobytes()
                    for x in jax.tree.leaves(clients[i].state)
                ),
            ))
        return tuple(rows)

    def cleanup() -> None:
        shutil.rmtree(root, ignore_errors=True)

    return World(
        name=f"fanout/{kind}",
        tasks=[("push", push), ("ack", ack), ("evict", evict)],
        check=check, fingerprint=fingerprint, cleanup=cleanup,
    )


# ---- observability registration ------------------------------------------

from .registry import register_effect_source as _reg_src  # noqa: E402
from .registry import register_obs_event as _reg_ev  # noqa: E402

_reg_ev(
    "concur_counterexample", subsystem="analysis.concur",
    fields=("world", "schedule", "reasons"), module=__name__,
)
_reg_src(
    "analysis.interleave.explorer", module=__name__,
    description="lockstep task threads of the interleaving explorer — "
    "exactly one runnable at a time, daemon, ilv-<task> named",
)

__all__ = [
    "Counterexample", "ExploreResult", "World", "boundary", "explore",
    "fanout_world", "serve_world",
]
