"""Shared example-state builders for the entry-point gates.

The registration blocks at the bottom of ``parallel/anti_entropy.py``,
``parallel/delta*.py``, and ``parallel/sparse_shard.py`` all need the
same thing: an R == P replica batch of join identities in ONE agreed
gate geometry (aliasing and jaxpr shape are properties of shapes, not
content). Keeping the shapes and builders here — an analysis-side
module with deferred ops imports — gives those five modules one
declared API instead of reaching into each other's privates, and keeps
gate fixtures out of the production anti-entropy code. The constants
mirror the pre-registry ``tools/check_aliasing.py`` gate shapes.
"""

from __future__ import annotations

import jax.numpy as jnp

# Gate geometry: element/actor/deferred widths and the nested key split.
GE, GA, GD = 8, 4, 4
GK1, GK2, GM = 4, 2, 2


def replicas(mesh) -> int:
    """R == P: one replica block row per device on the replica axis."""
    from ..parallel.mesh import REPLICA_AXIS

    return mesh.shape[REPLICA_AXIS]


def mk_dense(p):
    from ..ops import orswot

    return orswot.empty(GE, GA, GD, batch=(p,))


def mk_map(p):
    from ..ops import map as map_ops

    return map_ops.empty(GE, GA, 2, GD, batch=(p,))


def mk_map_orswot(p):
    from ..ops import map_orswot as mo_ops

    return mo_ops.empty(GK1, GM, GA, GD, batch=(p,))


def mk_nested_map(p):
    from ..ops import map_map as nested_ops

    return nested_ops.empty(GK1, GK2, GA, 2, GD, batch=(p,))


def mk_map3(p):
    from ..ops import map3 as map3_ops

    return map3_ops.empty(GK1, GK2, GM, GA, GD, batch=(p,))


def mk_sparse(p):
    from ..ops import sparse_orswot as sp

    return sp.empty(GE, GA, GD, 8, batch=(p,))


def mk_sparse_mvmap(p):
    from ..ops import sparse_mvmap as smv

    return smv.empty(GE, GA, GD, 8, batch=(p,))


def mk_sparse_nested(p):
    from ..ops import sparse_nest as snest

    return snest.empty_map_orswot(GM, GE, GA, GD, 8, GD, 8, batch=(p,))


def sparse_nested_level():
    from ..ops import sparse_nest as snest

    return snest.level_map_orswot(GM)


def mk_gset(p):
    return jnp.zeros((p, GE), bool)


def mk_lww(p):
    from ..ops import lwwreg as lww_ops

    return lww_ops.empty(batch=(p,))


def mk_mvreg(p):
    from ..ops import mvreg as mv

    return mv.empty(GD, GA, batch=(p,))


def mk_clocks(p):
    return jnp.zeros((p, GA), jnp.uint32)
