"""Canonical forms for bit-exact law comparison.

The joins keep their STATE canonical where cheap (sorted segment
tables, valid-first slot compaction), but two buffers are ordered by
join *operand order*, not by content: the masked-epoch deferred buffers
(parked removes concatenate left-then-right before compaction) and the
MVReg sibling slot table. ``join(a, b)`` and ``join(b, a)`` then hold
the same SET of slots in different lanes — semantically equal, raw
arrays unequal. The law engine compares ``canon(state)`` instead:
content-ordered, bit-exact, with dead lanes already zeroed by the
kernels' own compaction.

These helpers are shared by the op modules' ``canon=`` registrations
(registry.py). They are batch-polymorphic (leading axes broadcast) so
the engine can canonicalize whole stacked comparison batches at once.
"""

from __future__ import annotations

import jax.numpy as jnp


def canon_epochs(dcl, payload, dvalid, payload_fill=0):
    """Canonicalize a masked-epoch deferred buffer for comparison: dead
    slots carry no payload (the joins' own ``_compact`` convention —
    the CmRDT applies drop a caught-up slot's ``dvalid`` without
    scrubbing its clock, so op-built states hold semantically-dead
    stale lanes), then valid slots first, ordered lexicographically by
    rm clock (unique among valid slots — every join dedupes equal
    clocks before compacting).

    ``dcl [..., D, A]`` clocks, ``payload [..., D, X]`` member
    masks/key masks/id lists (``payload_fill`` is the kind's dead value
    — 0/False for masks, -1 for id lists), ``dvalid [..., D]``.
    Returns the three canonical arrays."""
    dcl = jnp.where(dvalid[..., None], dcl, jnp.zeros_like(dcl))
    payload = jnp.where(
        dvalid[..., None], payload,
        jnp.full_like(payload, payload_fill),
    )
    a = dcl.shape[-1]
    keys = tuple(dcl[..., i] for i in range(a - 1, -1, -1)) + (~dvalid,)
    order = jnp.lexsort(keys, axis=-1)
    return (
        jnp.take_along_axis(dcl, order[..., None], axis=-2),
        jnp.take_along_axis(payload, order[..., None], axis=-2),
        jnp.take_along_axis(dvalid, order, axis=-1),
    )


def canon_mvreg(state):
    """Content-order an MVReg slot table: valid first, then by witness
    dot (actor, counter) — unique per live slot, so the order is total.
    Dead payload is zeroed (matches ops/map._canon_child, which the map
    kinds already apply inside their joins)."""
    order = jnp.lexsort((state.wctr, state.wact, ~state.valid), axis=-1)
    valid = jnp.take_along_axis(state.valid, order, axis=-1)
    take = lambda x: jnp.take_along_axis(x, order, axis=-1)
    return state._replace(
        wact=jnp.where(valid, take(state.wact), 0),
        wctr=jnp.where(valid, take(state.wctr), 0),
        clk=jnp.where(
            valid[..., None],
            jnp.take_along_axis(state.clk, order[..., None], axis=-2),
            0,
        ),
        val=jnp.where(valid, take(state.val), 0),
        valid=valid,
    )
