"""The bounded SEC model checker — convergence under DELIVERY, not algebra.

The law engine (:mod:`.laws`) proves the join is a semilattice on pairs
and triples; nothing there verifies the property the whole framework
actually sells: **strong eventual consistency** — replicas that receive
the same set of δ/op messages converge, regardless of the order,
duplication, and transient drops the network inflicts (Almeida et al.,
"Delta State Replicated Data Types", PAPERS.md 1603.01529). This module
model-checks that guarantee exhaustively up to a small bound, the
small-scope discipline: real delivery bugs show up at tiny scopes.

**Model.** Each registered kind contributes ≤ :data:`MAX_OPS` δ
increments minted by ≤ :data:`MAX_REPLICAS` origins (the registered
``deltas`` hook, or derived from the kind's reachable-state generator —
registry.py documents the contract). A *schedule* is one replica's
delivery history: a sequence over the δ set. The enumerated schedule
space per kind:

- **reorder** — every permutation of the δ set (≤ 4! = 24);
- **duplication** — every permutation with one δ redelivered, both
  immediately (network-level duplicate) and at the end (a stale replay
  arriving after everything else);
- **drop-with-resync** — every permutation with one δ dropped, then a
  full in-order redelivery (the replica missed a packet and a later
  anti-entropy round replays history). A *permanent* drop violates
  eventual delivery, so convergence is not required and not checked.

Convergence across replicas reduces to convergence across schedules:
if every delivery history folds to the same canonical state, any
assignment of histories to replicas converges — so the checker runs
ALL schedules as ONE vmapped batched scan per kind (the laws.py
pair-table discipline: a handful of compiles, not thousands of
dispatches) and compares bit-exactly on canonical forms against the
in-order fold.

**CmRDT path.** A kind registering an op-based ``apply`` is only
promised convergence under causal, exactly-once delivery — the checker
runs the causal-order-respecting interleavings (per-origin op order
preserved, no dups/drops) through ``apply`` instead. Join-delivered
kinds get the causal subset for free (it is a subset of the reorder
set).

**Counterexamples.** A divergent schedule is greedily shrunk — every
deletion that keeps each δ delivered at least once (eventual delivery)
and still diverges is taken, to a fixpoint — so the reported schedule
is irreducible, and the finding carries the divergent leaf path.

Raising the bound locally::

    from crdt_tpu.analysis import schedules
    schedules.check_all_schedules(max_ops=5)   # 5! perms etc.; slower

The committed gate runs at MAX_OPS=4 (≈ 312 schedules × ≤ 7 joins per
kind) so the whole static chain stays inside its tier-1 budget.
"""

from __future__ import annotations

import itertools
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .registry import MergeKind, merge_kinds
from .laws import _mismatches, _norm_join, _stack
from .report import Finding

MAX_OPS = 4
MAX_REPLICAS = 3


# ---- δ/op derivation ------------------------------------------------------

def derive_ops(kind: MergeKind, max_ops: int = MAX_OPS) -> List[Tuple[int, Any]]:
    """The kind's bounded op set: ``[(origin, δ-state), ...]``. Uses the
    registered schedule generator when present; otherwise the reachable
    states past the identity, origins assigned round-robin (sound for
    CvRDT kinds — reachable states are shippable δ-states)."""
    if kind.deltas is not None:
        ops = list(kind.deltas())
    else:
        ops = [
            (i % MAX_REPLICAS, s)
            for i, s in enumerate(kind.states()[1:])
        ]
    return ops[:max_ops]


# ---- schedule enumeration -------------------------------------------------

def enumerate_schedules(n: int) -> List[Tuple[str, Tuple[int, ...]]]:
    """All bounded δ-path delivery schedules over ``n`` ops:
    ``[(label, op-index sequence), ...]``, deduplicated. Every sequence
    delivers every op at least once (eventual delivery holds; order,
    duplication, and drop-with-resync vary)."""
    out: dict = {}

    def add(label: str, seq: Tuple[int, ...]) -> None:
        out.setdefault(seq, label)

    perms = list(itertools.permutations(range(n)))
    for p in perms:
        add("reorder", p)
        for j in range(n):
            # A stale replay of op j after everything else…
            add("dup-late", p + (p[j],))
            # …and a network-level immediate duplicate.
            add("dup-now", p[: j + 1] + (p[j],) + p[j + 1:])
            # Replica missed op j; a later anti-entropy round replays
            # the full history in mint order.
            dropped = tuple(x for x in p if x != j)
            add("drop-resync", dropped + tuple(range(n)))
    return [(label, seq) for seq, label in out.items()]


def causal_schedules(origins: Sequence[int]) -> List[Tuple[int, ...]]:
    """Exactly-once interleavings respecting per-origin op order — the
    delivery space a CmRDT ``apply`` is promised (causal delivery: op
    k of an origin never arrives before op k-1 of the same origin)."""
    n = len(origins)
    seqs = []
    for p in itertools.permutations(range(n)):
        pos = {op: t for t, op in enumerate(p)}
        ok = all(
            pos[i] < pos[j]
            for i in range(n) for j in range(i + 1, n)
            if origins[i] == origins[j]
        )
        if ok:
            seqs.append(p)
    return seqs


# ---- execution ------------------------------------------------------------

def _state_bytes(state) -> tuple:
    """Bit-exact fingerprint of a (canonicalized) state pytree."""
    return tuple(
        (np.asarray(x).tobytes(), np.asarray(x).shape, str(np.asarray(x).dtype))
        for x in jax.tree.leaves(state)
    )


def _run_batched(deliver, identity, table, sch: np.ndarray):
    """Fold ``deliver`` over every schedule row at once: one jitted
    scan, vmapped over the [B, L] index matrix. The sentinel index
    ``len(table)-1`` pads ragged schedules and is SKIPPED (state
    carried through unchanged), not delivered-as-identity — a broken
    join may not absorb the identity, and the counterexample must
    replay identically without the padding. Returns
    ``(finals, flags[B] bool)``."""
    sentinel = jax.tree.leaves(table)[0].shape[0] - 1

    def one(seq):
        def step(carry, t):
            state, flag = carry
            i = seq[t]
            d = jax.tree.map(lambda x: x[i], table)
            nxt, f = deliver(state, d)
            live = i != sentinel
            nxt = jax.tree.map(
                lambda a, b: jnp.where(live, a, b), nxt, state
            )
            return (nxt, flag | (f & live)), None

        init = (identity, jnp.zeros((), bool))
        (final, flag), _ = jax.lax.scan(
            step, init, jnp.arange(sch.shape[1])
        )
        return final, flag

    return jax.jit(jax.vmap(one))(jnp.asarray(sch, jnp.int32))


def _run_one(deliver_eager, identity, deltas, seq: Sequence[int]):
    """Host-side replay of a single schedule (counterexample shrinking
    — a handful of eager joins on tiny states)."""
    state = identity
    for i in seq:
        state, _ = deliver_eager(state, deltas[i])
    return state


def minimize_schedule(
    seq: Sequence[int],
    n_ops: int,
    diverges,
) -> Tuple[int, ...]:
    """Greedy shrink: repeatedly delete any element whose removal keeps
    every op delivered at least once AND still diverges. The result is
    irreducible — no single deletion preserves the failure."""
    seq = tuple(seq)
    changed = True
    while changed:
        changed = False
        for p in range(len(seq)):
            cand = seq[:p] + seq[p + 1:]
            if set(range(n_ops)) - set(cand):
                continue  # would break eventual delivery
            if diverges(cand):
                seq = cand
                changed = True
                break
    return seq


# ---- the checker ----------------------------------------------------------

def _format_schedule(label: str, seq: Sequence[int], origins) -> str:
    steps = " ".join(f"d{i}@r{origins[i]}" for i in seq)
    return f"[{label}] deliver {steps}"


def check_kind_schedules(
    kind: MergeKind,
    ops: Optional[List[Tuple[int, Any]]] = None,
    max_ops: int = MAX_OPS,
) -> List[Finding]:
    """Model-check one kind's convergence over the bounded schedule
    space; findings carry a minimized counterexample schedule and the
    divergent leaf path."""
    ops = derive_ops(kind, max_ops) if ops is None else ops[:max_ops]
    if len(ops) < 2:
        return [Finding(
            "schedule-domain", kind.name,
            f"schedule generator yields {len(ops)} δ/op(s) — need >= 2 "
            "for a non-trivial delivery space (register a `deltas` hook "
            "or widen the state generator)",
        )]
    origins = [o for o, _ in ops]
    deltas = [d for _, d in ops]
    identity = kind.states()[0]
    join = _norm_join(kind.join)
    canon = jax.jit(kind.canon) if kind.canon else (lambda s: s)

    def _deliver_join(state, d):
        out, flags = join(state, d)
        fired = (
            jnp.zeros((), bool) if flags is None
            else jnp.any(jnp.asarray(flags))
        )
        return out, fired

    findings: List[Finding] = []
    findings += _check_path(
        kind, "sec-divergence", _deliver_join, identity, deltas, origins,
        enumerate_schedules(len(ops)), canon,
        # Reference: the in-order fold — what a replica that saw every
        # δ exactly once, in mint order, holds.
        ref_seq=tuple(range(len(ops))),
    )

    if kind.apply is not None:
        def _deliver_apply(state, d):
            out = kind.apply(state, d)
            return out, jnp.zeros((), bool)

        causal = [
            ("causal", seq) for seq in causal_schedules(origins)
        ]
        findings += _check_path(
            kind, "causal-divergence", _deliver_apply, identity, deltas,
            origins, causal, canon, ref_seq=causal[0][1],
        )
    return findings


def _check_path(
    kind, check, deliver, identity, deltas, origins, labelled, canon,
    ref_seq,
) -> List[Finding]:
    labels = [lb for lb, _ in labelled]
    seqs = [sq for _, sq in labelled]
    L = max(len(s) for s in seqs)
    sentinel = len(deltas)                     # index of the identity row
    table = _stack(deltas + [identity])
    sch = np.full((len(seqs), L), sentinel, np.int32)
    for r, s in enumerate(seqs):
        sch[r, : len(s)] = s

    finals, flags = _run_batched(deliver, identity, table, sch)
    ref = canon(_run_one(deliver, identity, deltas, ref_seq))
    ref_b = jax.tree.map(
        lambda x: jnp.broadcast_to(x[None], (len(seqs),) + x.shape), ref
    )
    # Canon handles leading batch axes (the laws.py discipline — it is
    # applied to whole pair tables there).
    mism = _mismatches(canon(finals), ref_b)
    if not mism:
        if bool(np.asarray(flags).any()):
            return [Finding(
                "schedule-overflow", kind.name,
                "a capacity/conflict flag fired inside the bounded "
                "schedule space — convergence still held, but widen the "
                "δ generator's caps so the check is not vacuous at the "
                "margin", severity="warning",
            )]
        return []

    flags_np = np.asarray(flags)
    ref_bytes = _state_bytes(ref)

    def diverges(seq) -> bool:
        got = canon(_run_one(deliver, identity, deltas, seq))
        return _state_bytes(got) != ref_bytes

    findings: List[Finding] = []
    seen_rows = set()
    seen_paths = set()
    for row, path in mism:
        row = max(row, 0)
        # One finding per DISTINCT divergent leaf path — independent
        # divergences (one leaf broken by reorder, another by dup) each
        # get their own minimized counterexample; further rows smearing
        # the same leaf add no signal.
        if row in seen_rows or path in seen_paths:
            continue
        seen_rows.add(row)
        seen_paths.add(path)
        path = path or "<root>"
        if bool(flags_np[row]):
            findings.append(Finding(
                check, kind.name,
                f"{_format_schedule(labels[row], seqs[row], origins)} "
                f"diverged at leaf {path}, but a capacity flag fired on "
                "this schedule — widen the δ generator's caps to make "
                "the verdict meaningful", severity="warning",
            ))
            continue
        small = minimize_schedule(seqs[row], len(deltas), diverges)
        findings.append(Finding(
            check, kind.name,
            f"minimized counterexample "
            f"{_format_schedule(labels[row], small, origins)} "
            f"diverges from the in-order fold at leaf {path} "
            f"(found as {_format_schedule(labels[row], seqs[row], origins)})",
        ))
    return findings


def check_all_schedules(max_ops: int = MAX_OPS) -> List[Finding]:
    out: List[Finding] = []
    for kind in merge_kinds():
        out.extend(generator_degeneracy(kind))
        out.extend(check_kind_schedules(kind, max_ops=max_ops))
    return out


# ---- generator degeneracy (the vacuity gate) ------------------------------

def generator_degeneracy(kind: MergeKind) -> List[Finding]:
    """A degenerate small-domain generator silently vacuates BOTH the
    law engine and the schedule checker (every law holds trivially on
    one state). Fail loudly instead:

    - empty CmRDT-reachable set (no states at all);
    - fewer than 2 distinct canonical states (all seeds collapse to
      one point — the laws compare a constant against itself).
    """
    states = kind.states()
    if not states:
        return [Finding(
            "generator-degenerate", kind.name,
            "small-domain generator yields NO states — the law engine "
            "and schedule checker have nothing to check",
        )]
    canon = kind.canon or (lambda s: s)
    distinct = {_state_bytes(canon(s)) for s in states}
    if len(distinct) < 2:
        return [Finding(
            "generator-degenerate", kind.name,
            f"small-domain generator yields {len(states)} state(s) but "
            f"only {len(distinct)} distinct canonical point(s) — every "
            "law holds vacuously on a one-point domain; make the "
            "generator mint genuinely different states",
        )]
    return []
