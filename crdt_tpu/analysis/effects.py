"""Effect inference over the host serving surface.

The device program is proved by the law engine and the schedule-space
checker; the *host* program — ServeLoop pipelining, the background
persister, fanout pushes and client acks, pressure eviction — is a
concurrent program in its own right, and its correctness argument
starts with knowing WHO TOUCHES WHAT. This module is that first step:
a pure-AST pass over :data:`HOST_SURFACE_MODULES` classifying every
method's reads and writes of the shared-state fields registered via
:func:`crdt_tpu.analysis.registry.register_shared_field` (the lane
table, the free pool, the dirty flags, the WAL seq, the sub_ver/ack
windows, ...).

Registration is the coverage contract, exactly like joins, entry
points, and flight-recorder events: :func:`unregistered_shared_mutations`
finds every ``self.<field>`` mutated outside ``__init__`` in a
surveyed class whose ``(owner, field)`` never registered — a field
nobody declared is a field whose conflicts nobody analyzed, and it
fails the ``concurrency`` static-check section at discovery time.

The inferred :class:`Effect` rows feed ``analysis/concur.py``, which
checks every cross-thread conflicting pair against the declared
happens-before contracts. Everything here is stdlib-only and parses
source — no instance is constructed, no device code runs.
"""

from __future__ import annotations

import ast
import importlib
import inspect
import os
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Tuple

from . import registry

# The host serving surface surveyed by the ``concurrency`` section.
# ONE home for the list: registry._import_host_surface() imports these
# before reading the shared-field table, and the AST pass below parses
# exactly the same set.
HOST_SURFACE_MODULES: Tuple[str, ...] = (
    "crdt_tpu.serve.loop",
    "crdt_tpu.serve.ingest",
    "crdt_tpu.serve.evict",
    "crdt_tpu.serve.superblock",
    "crdt_tpu.serve.wal",
    "crdt_tpu.fanout.plane",
    "crdt_tpu.obs.trace",
    "crdt_tpu.faults.retry",
)

# Method names that mutate their receiver in place: a call
# ``self.pending.setdefault(...)`` is a WRITE of ``pending`` even
# though no assignment statement names it.
_MUTATOR_CALLS = frozenset({
    "append", "appendleft", "add", "discard", "remove", "clear",
    "update", "extend", "insert", "setdefault", "pop", "popleft",
    "fill", "rotate",
})


@dataclass(frozen=True)
class Effect:
    """One inferred access: ``owner.method`` reads or writes shared
    field ``field`` at ``site`` (``relpath:lineno``). ``via_self`` is
    True for a direct ``self.field`` access and False for a
    cross-object access reaching the field through another handle
    (``self.sb.dirty[...] = ...`` from the evictor)."""

    owner: str
    method: str
    field: str
    mode: str  # "read" | "write"
    site: str
    via_self: bool = True


def _module_tree(mod_name: str) -> Tuple[ast.AST, str]:
    mod = importlib.import_module(mod_name)
    src = inspect.getsource(mod)
    rel = os.path.relpath(inspect.getsourcefile(mod) or "", os.getcwd())
    return ast.parse(src), rel


def _obj_tree(obj) -> Tuple[ast.AST, str]:
    src = inspect.getsource(obj)
    # Dedent (methods handed in directly may be indented).
    import textwrap

    rel = os.path.relpath(inspect.getsourcefile(obj) or "<obj>", os.getcwd())
    return ast.parse(textwrap.dedent(src)), rel


def _attr_chain(node: ast.AST) -> List[str]:
    """``self.sb.dirty`` -> ["self", "sb", "dirty"]; [] if the chain
    bottoms out in anything but a bare Name."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return []


class _FieldAccessVisitor(ast.NodeVisitor):
    """Collect reads/writes of a fixed name set within one function
    body. Writes: Store/Del-context attributes, attributes inside
    assignment targets (subscript stores like ``self.dirty[t] = x``),
    and in-place mutator calls (``self._free.append(lane)``)."""

    def __init__(self, names: frozenset):
        self.names = names
        self.writes: List[Tuple[str, int, bool]] = []  # (field, line, self?)
        self.reads: List[Tuple[str, int, bool]] = []
        self._written_ids: set = set()

    def _mark_target(self, node: ast.AST) -> None:
        # Only the OUTERMOST attribute of each assigned chain is the
        # written field: ``self.sb.dirty[t] = v`` writes ``dirty``
        # (cross-object), not ``sb``.
        for sub in ast.walk(node):
            tgt: Optional[ast.Attribute] = None
            if (isinstance(sub, ast.Attribute)
                    and not isinstance(sub.ctx, ast.Load)):
                tgt = sub
            elif (isinstance(sub, ast.Subscript)
                    and not isinstance(sub.ctx, ast.Load)):
                inner = sub.value
                while isinstance(inner, ast.Subscript):
                    inner = inner.value
                if isinstance(inner, ast.Attribute):
                    tgt = inner
            if tgt is not None and tgt.attr in self.names:
                chain = _attr_chain(tgt)
                if chain and chain[0] == "self":
                    self.writes.append(
                        (tgt.attr, tgt.lineno, len(chain) == 2)
                    )
                    self._written_ids.add(id(tgt))

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._mark_target(t)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._mark_target(node.target)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        self._mark_target(node.target)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for t in node.targets:
            self._mark_target(t)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if (isinstance(f, ast.Attribute) and f.attr in _MUTATOR_CALLS
                and isinstance(f.value, ast.Attribute)
                and f.value.attr in self.names):
            chain = _attr_chain(f.value)
            if chain and chain[0] == "self":
                via_self = len(chain) == 2
                self.writes.append((f.value.attr, f.value.lineno, via_self))
                self._written_ids.add(id(f.value))
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if (node.attr in self.names and id(node) not in self._written_ids
                and isinstance(node.ctx, ast.Load)):
            chain = _attr_chain(node)
            if chain and chain[0] == "self":
                via_self = len(chain) == 2
                self.reads.append((node.attr, node.lineno, via_self))
        self.generic_visit(node)


def _iter_methods(tree: ast.AST) -> Iterable[Tuple[str, str, ast.AST]]:
    """Yield ``(class_name, method_name, func_node)`` for every method
    of every class in the module tree, plus ``("", name, node)`` for
    module-level functions."""
    for node in ast.iter_child_nodes(tree):
        if isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield node.name, sub.name, sub
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield "", node.name, node


_INIT_METHODS = frozenset({"__init__", "__post_init__"})


def infer_effects(extra: Tuple = ()) -> Tuple[Effect, ...]:
    """The inferred effect table: one :class:`Effect` per (method,
    field, mode, line) access of a REGISTERED shared field across the
    surveyed host surface. ``extra`` takes classes or functions (the
    broken twins in ``analysis/fixtures.py``) whose source is scanned
    the same way — their class name is the owner, so a twin's rogue
    writes land in the table without registering anything."""
    names = frozenset(sf.name for sf in registry.shared_fields())
    rows: List[Effect] = []
    trees = [(_module_tree(m)) for m in HOST_SURFACE_MODULES]
    for obj in extra:
        trees.append(_obj_tree(obj))
    for tree, rel in trees:
        for cls, meth, fn in _iter_methods(tree):
            if meth in _INIT_METHODS:
                continue
            v = _FieldAccessVisitor(names)
            for stmt in fn.body if hasattr(fn, "body") else []:
                v.visit(stmt)
            for field, line, via_self in v.writes:
                rows.append(Effect(cls, meth, field, "write",
                                   f"{rel}:{line}", via_self))
            for field, line, via_self in v.reads:
                rows.append(Effect(cls, meth, field, "read",
                                   f"{rel}:{line}", via_self))
    return tuple(rows)


def unregistered_shared_mutations(extra: Tuple = ()) -> List[Tuple[str, str]]:
    """``("Owner.field", site)`` for every DIRECT ``self.<field>``
    mutation outside ``__init__`` in a surveyed class whose
    ``(owner, field)`` never called
    :func:`~crdt_tpu.analysis.registry.register_shared_field` — the
    discovery gate of the ``concurrency`` static-check section
    (registration-is-the-coverage-contract, the
    :func:`~crdt_tpu.analysis.registry.unregistered_obs_events` rule
    for host shared state)."""
    registered = {(sf.owner, sf.name) for sf in registry.shared_fields()}
    out: List[Tuple[str, str]] = []
    trees = [(_module_tree(m)) for m in HOST_SURFACE_MODULES]
    for obj in extra:
        trees.append(_obj_tree(obj))
    for tree, rel in trees:
        for cls, meth, fn in _iter_methods(tree):
            if not cls or meth in _INIT_METHODS:
                continue
            # Match EVERY attribute name (the open-world scan), then
            # keep only direct self.<field> mutations.
            v = _FieldAccessVisitor(frozenset())
            v.names = _AnyName()
            for stmt in fn.body:
                v.visit(stmt)
            for field, line, via_self in v.writes:
                if via_self and (cls, field) not in registered:
                    out.append((f"{cls}.{field}", f"{rel}:{line}"))
    return sorted(set(out))


class _AnyName:
    """A name set that contains every string — lets the discovery gate
    reuse :class:`_FieldAccessVisitor` with an open world."""

    def __contains__(self, item) -> bool:
        return isinstance(item, str)


def shared_field_names() -> frozenset:
    return frozenset(sf.name for sf in registry.shared_fields())


def effects_by_field(
    extra: Tuple = (),
) -> Dict[str, Tuple[Effect, ...]]:
    """The effect table grouped by field name — the shape the
    conflict checker consumes."""
    out: Dict[str, List[Effect]] = {}
    for e in infer_effects(extra):
        out.setdefault(e.field, []).append(e)
    return {k: tuple(v) for k, v in sorted(out.items())}
