"""Actor lifecycle mechanics: counter widening and retirement
(VERDICT r04 Missing #5; SURVEY.md §7.3 overflow discipline).

The reference sidesteps saturation by being u64 end to end
(src/vclock.rs ``BTreeMap<A, u64>``); the device lattice defaults to
u32 lanes for bandwidth, and strict mode traps an approaching overflow
with ``CounterSaturation`` — whose message prescribes "widen
counter_dtype or retire the actor". This module is those two remedies
as CODE, for the clock/counter family:

- :func:`widen_counters` — u32 → u64 state migration in place
  (bit-identical at the oracle level: every lane value is preserved
  exactly; only the dtype grows). Requires
  ``configure(counter_dtype="uint64")`` first (which enables x64 — see
  config.py).
- :func:`retire_actor` — fold a retired actor's CONVERGED contribution
  into the shared ``RETIRED`` aggregate lane and zero its own lane.
  Sound for GCounter/PNCounter because their read is a lane SUM; the
  migration demands lane convergence across the model's replicas (and,
  operationally, must be applied identically on every host holding the
  replica set — it is an administrative migration, not a CRDT op).
  VClock retirement is deliberately NOT offered: clock comparisons are
  per-actor, so lanes cannot be merged without changing the partial
  order — causal types (VClock, Orswot, MVReg, Map) retire an actor
  via ``Causal::reset_remove`` on their models instead (forget the
  departed actor's causal history; see tests/test_reset_remove.py).
- :func:`compact_actors` — rebuild the interner/lane universe without
  all-zero lanes (retired or never-used actors), shrinking device
  state. Reads are preserved exactly; freed lanes make room for new
  actors in the fixed-width universe.

:func:`compact_actors` is the counter family's host-side reclamation
path and reports through the same ``reclaim.*`` counters as the
causal-stability subsystem (crdt_tpu/reclaim/ — frontier-driven
compaction + ``elastic.shrink`` for the set/map family): freed lanes
count as ``reclaim.reclaimed_slots``, and a run that actually freed
lanes counts one ``reclaim.shrink_events`` (the live universe shrank
into the fixed width — the freed tail is reclaimed headroom, exactly
what a capacity shrink reclaims for the causal kinds).
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np

from .utils import Interner

RETIRED = "__retired__"


def _vclock_models(model) -> Tuple:
    """The BatchedVClock leaves of a counter-family model."""
    from .models.counters import BatchedGCounter, BatchedPNCounter
    from .models.vclock import BatchedVClock

    if isinstance(model, BatchedVClock):
        return (model,)
    if isinstance(model, BatchedGCounter):
        return (model.inner,)
    if isinstance(model, BatchedPNCounter):
        return (model.p, model.n)
    raise TypeError(
        f"lifecycle operations cover the clock/counter family, got "
        f"{type(model).__name__}"
    )


def widen_counters(model) -> None:
    """Widen a counter-family model's device lanes u32 → u64 in place.

    Bit-identical migration: every lane VALUE is unchanged; only the
    dtype doubles, lifting the saturation ceiling from 2^32-1 to
    2^64-1 (reference width, src/vclock.rs). Enable x64 first via
    ``configure(counter_dtype="uint64")`` — without it jax silently
    truncates uint64 arrays back to uint32, which this refuses to do."""
    if not jnp.zeros((), jnp.uint64).dtype == jnp.dtype("uint64"):
        raise RuntimeError(
            "uint64 lanes require x64 mode: call "
            "configure(counter_dtype='uint64') before widening"
        )
    for vc in _vclock_models(model):
        vc.clocks = vc.clocks.astype(jnp.uint64)


def retire_actor(model, actor) -> None:
    """Retire ``actor`` from a GCounter/PNCounter model: fold its
    converged count into the shared ``RETIRED`` aggregate lane and zero
    its own lane. The actor must never mint again (its lane is now
    dead weight until :func:`compact_actors`).

    Demands convergence: every replica row must hold the SAME value in
    the actor's lane (otherwise moving the count would lose or double
    increments depending on later merges) — converge first
    (``fold``/anti-entropy), then retire, then resume. Raises
    ValueError when rows diverge."""
    from .models.counters import BatchedGCounter, BatchedPNCounter

    if not isinstance(model, (BatchedGCounter, BatchedPNCounter)):
        raise TypeError(
            "retire_actor is a counter migration (reads are lane sums); "
            "VClock lanes cannot be merged without changing the partial "
            "order — causal types retire via model.reset_remove(...) "
            f"instead; got {type(model).__name__}"
        )
    clocks = _vclock_models(model)
    actors = clocks[0].actors
    aid = actors.id_of(actor)
    rid = actors.intern(RETIRED)
    if rid == aid:
        raise ValueError("cannot retire the RETIRED aggregate lane")
    # The aggregate may need a lane the fixed universe doesn't have —
    # growing width by one is part of the migration (administrative,
    # applied identically everywhere like the rest of this function).
    for vc in clocks:
        grow = rid + 1 - vc.clocks.shape[-1]
        if grow > 0:
            vc.clocks = jnp.pad(vc.clocks, ((0, 0), (0, grow)))
    for vc in clocks:
        col = np.asarray(vc.clocks[:, aid])
        if col.size and not (col == col[0]).all():
            raise ValueError(
                f"actor {actor!r} lane diverges across replicas "
                f"({sorted(set(col.tolist()))}); converge before retiring"
            )
        moved = vc.clocks.at[:, rid].add(vc.clocks[:, aid])
        vc.clocks = moved.at[:, aid].set(0)


def compact_actors(model) -> None:
    """Drop all-zero lanes (retired or never-used actors) from a
    counter-family model and rebuild its interner with the survivors —
    device state shrinks, reads are untouched, and the freed width is
    available for new actors after a rebuild.

    PNCounter compacts on the UNION of p/n liveness (both share one
    interner, so both must keep the same lanes). The LANE WIDTH is
    preserved — live lanes move to the front and the freed tail becomes
    zero headroom for new actors (shrinking to the live count would
    leave a full universe and defeat the point of retiring).

    Reclamation accounting rides the shared ``reclaim.*`` namespace
    (crdt_tpu/reclaim/compaction.py ``record_reclaim``): freed lanes →
    ``reclaimed_slots``; a run that freed any lane → one
    ``shrink_events`` (see the module docstring)."""
    from .reclaim.compaction import record_reclaim
    from .utils.metrics import metrics

    clocks = _vclock_models(model)
    live = None
    for vc in clocks:
        lanes = np.asarray(vc.clocks).any(axis=0)
        live = lanes if live is None else (live | lanes)
    actors = clocks[0].actors
    keep = [a for a in range(min(len(live), len(actors))) if live[a]]
    freed = len(actors) - len(keep)
    if freed > 0:
        record_reclaim("actors", freed, 0)
        metrics.count("reclaim.shrink_events")
        metrics.count("reclaim.shrink_events.actors")
    new_actors = Interner(actors[a] for a in keep)
    idx = jnp.asarray(np.asarray(keep, np.int64))
    for vc in clocks:
        width = vc.clocks.shape[-1]
        packed = (
            vc.clocks[:, idx]
            if keep
            else jnp.zeros((vc.clocks.shape[0], 0), vc.clocks.dtype)
        )
        vc.clocks = jnp.pad(packed, ((0, 0), (0, width - packed.shape[-1])))
        vc.actors = new_actors
    # Counter wrappers expose .actors via their inner clock(s); the
    # shared-interner invariant (PNCounter) is restored by assigning the
    # same object everywhere above.


__all__ = ["RETIRED", "widen_counters", "retire_actor", "compact_actors"]
