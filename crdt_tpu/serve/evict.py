"""Cold-tenant eviction to the durable tier + restore-on-next-touch
(ISSUE 15; the PR 10 snapshot machinery at tenant granularity).

At 1M+ live sessions most tenants are COLD most of the time; their
device lanes are working-set the hot tenants want. The
:class:`Evictor` moves cold tenants to the PR 10 generational snapshot
tier and re-warms them on their next touch:

- **evict** — a dirty tenant's row is PERSISTED FIRST
  (``durability.snapshot.save_state`` per tenant directory: atomic
  payload→fsync→rename, manifest commit LAST, retain-K), then its lane
  resets to the join identity. The order is the whole durability
  argument: the lane clears only after the durable record commits, so
  a kill anywhere in between recovers the tenant bit-identical to its
  last durable record — the ``serve.evict.*`` crashpoints bracket
  exactly these boundaries and ride the PR 10 fuzz loop
  (tests/test_serve.py + the ``durability`` static-check section).
- **restore** — the next touch loads the newest valid generation
  (corrupt generations fall back — the PR 10 loader) back into the
  lane. The ingest queue calls this automatically
  (crdt_tpu/serve/ingest.py), making eviction invisible to
  correctness.
- **cold selection** — a recency clock over ``note_touch`` picks the
  longest-untouched resident tenants (:meth:`select_cold`).
- **recovery** — :func:`recover_tenants` is the serving tier's
  recovery driver: every tenant directory under the root loads its
  last durable record (tenants never persisted recover as ⊥).

The detector :func:`evictor_preserves_dirt` is the serve section's
broken-twin gate: an evictor that skips persisting dirty rows (the
``analysis.fixtures.evictor_drops_dirt`` twin flips the
``_persist_dirty`` seam) restores stale state and MUST fail it.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..analysis.interleave import boundary
from ..durability import crashpoints, snapshot
from ..obs import trace as obs_trace
from ..utils.metrics import metrics
from .superblock import Superblock

CP_PRE_PERSIST = crashpoints.register(
    "serve.evict.pre_persist",
    "about to persist an evicting tenant's row (nothing durable yet — "
    "a kill here recovers the tenant's PREVIOUS durable record)",
)
CP_POST_PERSIST = crashpoints.register(
    "serve.evict.post_persist_pre_clear",
    "tenant row committed to the durable tier, device lane not yet "
    "cleared (the mid-evict boundary: a kill here must recover the "
    "just-committed record)",
)
CP_RESTORE = crashpoints.register(
    "serve.restore.post_load",
    "evicted tenant's durable record loaded, lane not yet re-warmed "
    "(a kill here re-restores from the same record — restore is "
    "idempotent)",
)


def tenant_dir(root: str, tenant: int) -> str:
    """One tenant's snapshot directory (two-level fanout so a million
    tenant dirs never share one directory listing)."""
    return os.path.join(root, f"{tenant >> 10:05x}", f"t{tenant:08d}")


def persist_tenant(root: str, kind: str, tenant: int, row, *,
                   retain: int = 2) -> int:
    """Commit one tenant's row to its durable directory (the
    crashpoint-bracketed write path — shared by the evictor and the
    durability static-check probe workload)."""
    crashpoints.hit(CP_PRE_PERSIST)
    gen = snapshot.save_state(
        tenant_dir(root, tenant), kind, row, retain=retain,
    )
    crashpoints.hit(CP_POST_PERSIST)
    return gen


def restore_tenant(root: str, kind: str, tenant: int, template):
    """One tenant's last durable record (⊥ template when the tenant
    was never persisted). Crossed by restore-on-touch AND recovery."""
    tdir = tenant_dir(root, tenant)
    if not os.path.isdir(tdir):
        row = template
    else:
        row, _gen = snapshot.load_newest(tdir, template)
    crashpoints.hit(CP_RESTORE)
    return row


class Evictor:
    """Cold-tenant eviction/restore over one superblock's lanes."""

    def __init__(self, superblock: Superblock, root: str, *,
                 retain: int = 2, pressure_batch: int = 64):
        self.sb = superblock
        self.root = root
        self.retain = retain
        # Lanes to free per LanePressure event: evicting one at a time
        # would pay one persist+clear round-trip per admitted tenant
        # under a rotating working set.
        self.pressure_batch = pressure_batch
        self.clock = 0
        self.last_touch = np.zeros(superblock.n_tenants, np.int64)
        # Cumulative per-tenant touches — the hot-tenant skew
        # attribution signal (crdt_tpu/obs/trace.py skew_report ranks
        # by it; last_touch alone cannot distinguish "touched once
        # recently" from "hammered all session").
        self.touch_count = np.zeros(superblock.n_tenants, np.int64)
        os.makedirs(root, exist_ok=True)

    # ---- recency --------------------------------------------------------
    def note_touch(self, tenant: int) -> None:
        self.clock += 1
        self.last_touch[tenant] = self.clock
        self.touch_count[tenant] += 1

    def select_cold(self, k: int, exclude=()) -> List[int]:
        """The k longest-untouched RESIDENT tenants. ``exclude`` pins
        tenants that must not be selected — the ingest queue pins the
        tenants already placed in the slab it is building, so a
        mid-flush pressure eviction can never free (and re-issue) a
        device lane the in-flight slab is about to scatter into."""
        resident = np.sort(self.sb.resident_tenants())
        if len(resident) == 0:
            return []
        if exclude:
            ex = set(exclude)
            resident = np.asarray(
                [t for t in resident if int(t) not in ex], np.int64
            )
            if len(resident) == 0:
                return []
        order = resident[np.argsort(self.last_touch[resident],
                                    kind="stable")]
        return [int(t) for t in order[:k]]

    # ---- evict ----------------------------------------------------------
    def persist(self, tenants: Sequence[int]) -> int:
        """Flush dirty tenants' rows to the durable tier (no lane
        change). Returns rows written."""
        boundary("evict.persist")
        n = 0
        for t in tenants:
            if not self.sb.dirty[t]:
                continue
            persist_tenant(
                self.root, self.sb.kind, t, self.sb.row(t),
                retain=self.retain,
            )
            self.sb.dirty[t] = False
            obs_trace.stamp("durable", tenant=int(t))
            n += 1
        metrics.count("serve.evict.persisted", n)
        return n

    def evict(self, tenants: Sequence[int], *,
              _persist_dirty: bool = True) -> int:
        """Move tenants to the durable tier, reset their lanes to ⊥
        (one batched scatter), and FREE the lanes for other tenants.
        ``_persist_dirty`` is the broken-twin seam
        (``analysis.fixtures.evictor_drops_dirt`` flips it): the honest
        evictor ALWAYS persists dirt before clearing — the order that
        makes a mid-evict kill recoverable."""
        from ..obs import recorder as _rec

        lanes = []
        for t in tenants:
            if not self.sb.is_resident(t):
                continue
            if _persist_dirty and self.sb.dirty[t]:
                self.persist([t])
            self.sb.dirty[t] = False
            self.sb.was_evicted[t] = True
            lanes.append(self.sb.release_lane(t))
            obs_trace.stamp("evict", tenant=int(t))
            _rec.emit("tenant_evicted", tenant=int(t))
        boundary("evict.clear")
        self.sb.clear_lanes(lanes)
        metrics.count("serve.evict.evictions", len(lanes))
        return len(lanes)

    # ---- restore --------------------------------------------------------
    def restore(self, tenant: int, _exclude=()) -> bool:
        """Make a tenant resident: a first ADMISSION takes a ⊥ lane
        (no durable record exists — free), an EVICTED tenant re-warms
        from its last durable record. Under lane pressure, evicts the
        ``pressure_batch`` coldest residents first (serving-tier
        paging; ``_exclude`` pins slab-in-flight tenants — see
        :meth:`select_cold`). Returns True only for a durable-tier
        restore (the quantity the ingest FlushReport counts)."""
        from ..obs import recorder as _rec

        if self.sb.is_resident(tenant):
            return False
        boundary("evict.pick")
        if self.sb.free_lanes == 0:
            self.evict(
                self.select_cold(self.pressure_batch, exclude=_exclude)
            )
        if not self.sb.was_evicted[tenant]:
            # First admission, not a restore: a never-evicted tenant
            # has no durable record and its freed lane is already ⊥ —
            # allocate and stop. No device write, no flight event (a
            # million admissions would flood the recorder ring).
            self.sb.ensure_resident(tenant)
            metrics.count("serve.evict.admissions")
            return False
        row = restore_tenant(
            self.root, self.sb.kind, tenant, self.sb.empty_row()
        )
        row = self._fit_capacity(row)
        self.sb.write_row(tenant, row)
        self.sb.was_evicted[tenant] = False
        self.sb.dirty[tenant] = False
        metrics.count("serve.evict.restores")
        obs_trace.stamp("restore", tenant=int(tenant))
        _rec.emit("tenant_restored", tenant=int(tenant))
        return True

    def _fit_capacity(self, row):
        """Fit a restored row to the superblock's current layout. The
        superblock may have WIDENED while the tenant slept (widen the
        row up — per-kind widen is bit-exact) or NARROWED (the row's
        content is sacred: RE-WIDEN the whole superblock to cover it —
        a row with live lanes cannot narrow, and per-kind ``widen``
        refuses shrink directions outright)."""
        rcaps = self.sb.tk.caps_of(row)
        grow_sb = {
            k: v for k, v in rcaps.items() if v > self.sb.caps.get(k, 0)
        }
        if grow_sb:
            self.sb.widen_capacity(**grow_sb)
        if any(self.sb.caps[k] > rcaps[k] for k in rcaps):
            return self.sb.tk.widen(row, **self.sb.caps)
        return row


def _durable_tenants(root: str):
    """Tenant ids with a durable directory, by WALKING the two-level
    fanout (one scandir per existing bucket) — probing every id of a
    million-tenant population with isdir stats would put minutes of
    syscalls on the recovery path."""
    try:
        buckets = sorted(
            (e for e in os.scandir(root) if e.is_dir()),
            key=lambda e: e.name,
        )
    except OSError:
        return
    for bucket in buckets:
        for e in sorted(os.scandir(bucket.path), key=lambda e: e.name):
            if e.is_dir() and e.name.startswith("t"):
                try:
                    yield int(e.name[1:])
                except ValueError:
                    continue


def recover_tenants(
    root: str, superblock: Superblock,
    tenants: Optional[Sequence[int]] = None,
) -> Dict[int, object]:
    """The serving tier's recovery driver: load every tenant's last
    durable record from ``root`` (after a crash, the device state is
    gone — the durable tier IS the serving state of record). Returns
    ``{tenant: row}`` for every tenant with a durable record; callers
    scatter them back via ``Superblock.write_row``. Tenants without a
    record recover as ⊥ (they were never persisted — their acks never
    promised durability)."""
    out: Dict[int, object] = {}
    it = _durable_tenants(root) if tenants is None else tenants
    for t in it:
        tdir = tenant_dir(root, int(t))
        if not os.path.isdir(tdir):
            continue
        if not snapshot.generations(tdir):
            continue
        row, _gen = snapshot.load_newest(tdir, superblock.empty_row())
        out[int(t)] = row
    metrics.count("serve.evict.recovered_tenants", len(out))
    return out


def evictor_preserves_dirt(evict_fn) -> bool:
    """THE serve broken-twin detector: evict a DIRTY tenant through
    ``evict_fn(evictor, tenants)``, restore it, and require the
    restored row bit-identical to the pre-evict row. The honest
    :meth:`Evictor.evict` persists dirt before clearing and passes;
    the ``analysis.fixtures.evictor_drops_dirt`` twin clears the lane
    on a stale durable record and MUST fail (the ``serve``
    static-check section pins both directions)."""
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp

    from ..parallel import make_mesh

    mesh = make_mesh(1, 1)
    sb = Superblock(
        2, mesh, kind="orswot",
        caps=dict(n_elems=4, n_actors=2, deferred_cap=2),
    )
    root = tempfile.mkdtemp(prefix="serve-evict-gate-")
    try:
        ev = Evictor(sb, root)
        # Round 1: persist a clean-ish state so the durable tier holds
        # a STALE record the broken twin will happily restore.
        mask = np.zeros(4, bool)
        mask[0] = True
        row, _ = sb.tk.apply_add(
            sb.empty_row(), jnp.int32(0), jnp.uint32(1), jnp.asarray(mask)
        )
        sb.write_row(0, row)
        sb.dirty[0] = True
        ev.persist([0])
        # Round 2: new dirt on top — the state the evictor must not lose.
        mask2 = np.zeros(4, bool)
        mask2[2] = True
        row2, _ = sb.tk.apply_add(row, jnp.int32(0), jnp.uint32(2),
                                  jnp.asarray(mask2))
        sb.write_row(0, row2)
        sb.dirty[0] = True
        want = sb.row(0)
        evict_fn(ev, [0])
        if sb.is_resident(0):
            return False  # did not even evict
        ev.restore(0)
        got = sb.row(0)
        return all(
            bool(jnp.array_equal(x, y))
            for x, y in zip(jax.tree.leaves(got), jax.tree.leaves(want))
        )
    finally:
        shutil.rmtree(root, ignore_errors=True)


from ..analysis.registry import register_obs_event as _reg_ev  # noqa: E402

_reg_ev(
    "tenant_evicted", subsystem="serve.evict", fields=("tenant",),
    module=__name__,
)
_reg_ev(
    "tenant_restored", subsystem="serve.evict", fields=("tenant",),
    module=__name__,
)

from ..analysis.registry import register_shared_field as _reg_sf  # noqa: E402

_reg_sf("clock", owner="Evictor", module=__name__,
        kind="logical touch clock")
_reg_sf("last_touch", owner="Evictor", module=__name__,
        kind="per-tenant last-touch stamps (coldness order)")
_reg_sf("touch_count", owner="Evictor", module=__name__,
        kind="per-tenant touch totals (skew stats)")

__all__ = [
    "Evictor", "evictor_preserves_dirt", "persist_tenant",
    "recover_tenants", "restore_tenant", "tenant_dir",
]
