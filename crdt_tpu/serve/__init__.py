"""crdt_tpu.serve — the tenant-packed serving front door (ISSUE 15).

Everything below this package batches replicas of ONE object; this
package serves MILLIONS of independent small objects (per-user carts,
presence sets, doc cursors) from one mesh — ROADMAP item 1. Four
cooperating pieces (see each module's docstring):

- :mod:`.superblock` — :class:`Superblock`: T independent tenant CRDTs
  of a registered kind in ONE device-resident pytree (tenant axis
  prepended, sharded over the replica mesh axis), with per-tenant
  elastic capacity (overflow→widen→retry rolls back ONLY overflowed
  tenants; ``Hysteresis.vote`` governs proactive widen/shrink) over
  the ``mesh_serve_apply`` dispatch (parallel/serve_apply.py +
  ops/superblock.py — one coalesced batch per dispatch).
- :mod:`.ingest` — :class:`IngestQueue`: the host-side front door
  coalescing per-tenant op streams into batched applies (the
  ``models/list.py`` streamed-ingestion prototype generalized), with
  loss-free bounded backpressure and per-tenant order preserved —
  which is why the coalesced path is bit-identical to the per-tenant
  sequential oracle.
- :mod:`.evict` — :class:`Evictor`: cold tenants move to the PR 10
  generational snapshot tier (persist-THEN-clear, crashpoint-
  bracketed) and re-warm on next touch; :func:`recover_tenants` is the
  tier's crash-recovery driver.
- :mod:`.shard` — :class:`TenantShardMap` + :func:`sync_tenant_shards`:
  per-host tenant shards by rendezvous hash (failover on membership
  eviction remaps ONLY the dead host's tenants), DCN anti-entropy
  under ``retry=`` joining handoff rows lattice-safely; ISSUE 18 adds
  :func:`rebalance_plan`/:func:`apply_rebalance` — skew-aware
  minimal-move overrides driven by evictor touch stats.
- :mod:`.wal` — :class:`ServeWal` (ISSUE 18): the dirty-tenant WAL —
  every coalesced slab is logged and group-commit fsynced BEFORE its
  dispatch, so replay (= re-ingest through the same bit-identical
  apply path) recovers every acked op after a kill anywhere.
- :mod:`.loop` — :class:`ServeLoop` (ISSUE 18): the pipelined round —
  slab N+1 assembles + WAL-commits while slab N's scatter is in
  flight; :class:`BackgroundPersister` drains cold-tenant persists
  off the dispatch latency path.

Plus :func:`static_checks` — the ``serve`` section of
tools/run_static_checks.py: surface-registry coverage, the
coalesced==sequential micro A/B, the pack/unpack round-trip, the
rendezvous minimal-remap property, and the broken-twin detector gate
(the dirt-dropping evictor in ``analysis.fixtures`` must be caught).
"""

from __future__ import annotations

from typing import List

from .evict import (
    Evictor,
    evictor_preserves_dirt,
    persist_tenant,
    recover_tenants,
    restore_tenant,
    tenant_dir,
)
from .ingest import (
    AddOp,
    FlushReport,
    IngestBackpressure,
    IngestQueue,
    RmOp,
)
from .loop import BackgroundPersister, ServeLoop
from .shard import (
    RebalanceMove,
    ShardSyncReport,
    TenantShardMap,
    apply_rebalance,
    export_rows,
    host_loads,
    ingest_rows,
    rebalance,
    rebalance_plan,
    sync_tenant_shards,
)
from .superblock import CapacityOverflow, Superblock
from .wal import (
    ReplayReport,
    ServeWal,
    recover_serve,
    replay_into,
    wal_order_violations,
    wal_precedes_dispatch,
)


def static_checks() -> List:
    """The ``serve`` static-check section (Finding list, empty =
    clean):

    1. **surface coverage** — every public operational symbol of this
       package must have called
       ``analysis.registry.register_serve_surface`` (the
       registration-is-the-coverage-contract rule).
    2. **coalesced == sequential** — a micro ingest (two tenants, mixed
       add/rm streams) through the coalesced slab apply must land
       bit-identical to the per-tenant sequential oracle, and
       pack/unpack must round-trip.
    3. **rendezvous minimal remap** — failing over one host must remap
       ONLY that host's tenants.
    4. **broken twin fires** — the dirt-dropping evictor twin
       (``analysis.fixtures.evictor_drops_dirt``) must FAIL
       :func:`evictor_preserves_dirt`; the honest evictor must pass.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..analysis import fixtures
    from ..analysis.registry import unregistered_serve_surfaces
    from ..analysis.report import Finding
    from ..ops import superblock as sb_ops
    from ..parallel import make_mesh

    findings: List[Finding] = []

    for name in unregistered_serve_surfaces():
        findings.append(Finding(
            "serve-surface-coverage", name,
            "public serve symbol never called register_serve_surface — "
            "the serve gate cannot see it",
        ))

    # 2. coalesced == sequential micro A/B + pack/unpack round-trip.
    try:
        mesh = make_mesh(1, 1)
        caps = dict(n_elems=4, n_actors=2, deferred_cap=2)
        sb = Superblock(2, mesh, kind="orswot", caps=caps)
        q = IngestQueue(sb, lanes=2, depth=4)
        m = lambda *on: np.isin(np.arange(4), on)  # noqa: E731
        streams = {
            0: [(sb_ops.ADD, 0, 1, None, m(0, 1)),
                (sb_ops.RM, 0, 0, np.asarray([1, 0], np.uint32), m(0)),
                (sb_ops.ADD, 1, 1, None, m(2))],
            1: [(sb_ops.ADD, 1, 1, None, m(3)),
                (sb_ops.RM, 0, 0, np.asarray([0, 2], np.uint32), m(3))],
        }
        for t, ops_l in streams.items():
            for k, actor, ctr, clock, member in ops_l:
                if k == sb_ops.ADD:
                    q.add(t, actor, ctr, member)
                else:
                    q.rm(t, clock, member)
        q.drain()
        tk = sb.tk
        for t, ops_l in streams.items():
            want = sb_ops.sequential_oracle(tk, tk.empty(**caps), ops_l)
            got = sb_ops.unpack(sb.state, t)
            if not all(
                bool(jnp.array_equal(x, y))
                for x, y in zip(jax.tree.leaves(got), jax.tree.leaves(want))
            ):
                findings.append(Finding(
                    "serve-coalesce-oracle", f"tenant {t}",
                    "coalesced ingest diverged from the per-tenant "
                    "sequential oracle",
                ))
        rows = [sb_ops.unpack(sb.state, t) for t in (0, 1)]
        rt = sb_ops.pack(rows)
        if not all(
            bool(jnp.array_equal(x, y))
            for x, y in zip(
                jax.tree.leaves(rt),
                jax.tree.leaves(sb_ops.pack(
                    [sb_ops.unpack(rt, 0), sb_ops.unpack(rt, 1)]
                )),
            )
        ):
            findings.append(Finding(
                "serve-pack-roundtrip", "pack/unpack",
                "pack(unpack) is not the identity",
            ))
    except Exception as exc:
        findings.append(Finding(
            "serve-coalesce-oracle", "micro-workload",
            f"coalesced micro A/B crashed: {type(exc).__name__}: {exc}",
        ))

    # 3. rendezvous minimal remap.
    sm = TenantShardMap(4)
    before = {t: sm.owner(t) for t in range(64)}
    sm.fail_over(2)
    for t, h in before.items():
        now = sm.owner(t)
        if h != 2 and now != h:
            findings.append(Finding(
                "serve-shard-remap", f"tenant {t}",
                f"failover of host 2 remapped tenant owned by host {h} "
                f"to {now} — rendezvous minimality broken",
            ))
        if h == 2 and now == 2:
            findings.append(Finding(
                "serve-shard-remap", f"tenant {t}",
                "failed-over host still owns a tenant",
            ))

    # 4. broken twin.
    if not evictor_preserves_dirt(lambda ev, ts: ev.evict(ts)):
        findings.append(Finding(
            "evict-durability", "Evictor.evict",
            "the honest evictor lost dirty tenant state across an "
            "evict/restore cycle",
        ))
    if evictor_preserves_dirt(fixtures.evictor_drops_dirt):
        findings.append(Finding(
            "broken-fixture-missed", "evictor_drops_dirt",
            "the dirt-dropping evictor twin PASSED the preservation "
            "detector — the serve durability gate is not actually "
            "firing",
        ))
    return findings


from ..analysis.registry import register_serve_surface as _reg  # noqa: E402

for _name in (
    "Superblock", "IngestQueue", "Evictor", "TenantShardMap",
    "evictor_preserves_dirt", "persist_tenant", "recover_tenants",
    "restore_tenant", "tenant_dir", "export_rows", "ingest_rows",
    "sync_tenant_shards", "static_checks",
    "PendingApply", "ServeWal", "replay_into", "recover_serve",
    "wal_precedes_dispatch", "wal_order_violations",
    "ServeLoop", "BackgroundPersister",
    "host_loads", "rebalance_plan", "apply_rebalance", "rebalance",
):
    _reg(_name, module=__name__)

__all__ = [
    "AddOp", "BackgroundPersister", "CapacityOverflow", "Evictor",
    "FlushReport", "IngestBackpressure", "IngestQueue",
    "RebalanceMove", "ReplayReport", "RmOp", "ServeLoop", "ServeWal",
    "ShardSyncReport", "Superblock", "TenantShardMap",
    "apply_rebalance", "evictor_preserves_dirt", "export_rows",
    "host_loads", "ingest_rows", "persist_tenant", "rebalance",
    "rebalance_plan", "recover_serve", "recover_tenants",
    "replay_into", "restore_tenant", "static_checks",
    "sync_tenant_shards", "tenant_dir", "wal_order_violations",
    "wal_precedes_dispatch",
]
