"""The host-side tenant superblock — a large live-tenant population
served from a bounded pool of device-resident lanes (ISSUE 15
tentpole).

:class:`Superblock` owns the device pytree (``ops/superblock.py``
layout: a LANE axis prepended on a registered kind's planes, sharded
over the replica mesh axis) plus the tenant bookkeeping the kernels
cannot see. The population (``n_tenants``) may EXCEED the device pool
(``n_lanes``): a tenant occupies a lane only while resident, via the
host-side ``tenant → lane`` indirection —

- a never-touched tenant costs NOTHING (no lane, no disk record: its
  state is ⊥ by definition);
- first touch allocates a free lane (⊥ — still no disk);
- cold tenants move to the durable tier and FREE their lane
  (crdt_tpu/serve/evict.py), re-warming on next touch into whatever
  lane is free — which is why the device footprint is
  ``n_lanes × row_bytes`` while the SERVED population is
  ``n_tenants`` (the peak-resident vs all-resident ratio
  ``bench.py --serve`` reports);
- an exhausted pool raises :class:`LanePressure`; the evictor turns
  that into evict-coldest-then-restore (serving-tier paging).

The elastic overflow→widen→retry loop lifts the PR 1 ``elastic_call``
discipline over the lane axis: overflowed tenants (bounded deferred /
dot capacity) roll back from their pre-gathered rows, the WHOLE
superblock widens by ``policy.factor`` (one repack migrates every
lane), and only the overflowed lanes retry — never re-applying a
non-overflowed tenant, so the elastic path stays bit-identical to a
wide-born superblock. :meth:`autoscale_capacity` debounces the
telemetry ``widen_pressure`` gauge through ``elastic.Hysteresis.vote``
(the PR 11 symmetric governor) for proactive widen/shrink; a shrink
that would drop live lanes is REFUSED by the per-kind ``narrow``
kernel (a no-op, never a data loss).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import telemetry as tele
from ..elastic import DEFAULT_POLICY, ElasticPolicy, Hysteresis
from ..ops import superblock as sb_ops
from ..parallel.mesh import REPLICA_AXIS
from ..parallel.serve_apply import mesh_serve_apply


class CapacityOverflow(RuntimeError):
    """An op slab overflowed a tenant's bounded buffers and the widen
    budget (``policy.max_migrations``) is exhausted. ``tenants`` names
    the overflowed tenants — their rows were ROLLED BACK to the
    pre-slab state (nothing lossy survives) and their ops are the ones
    a loss-free caller must re-queue (the ingest queue does)."""

    def __init__(self, msg: str, tenants=()):
        super().__init__(msg)
        self.tenants = tuple(int(t) for t in tenants)


class LanePressure(RuntimeError):
    """No free device lane for a tenant that needs one — evict a cold
    tenant first (the evictor does this automatically:
    crdt_tpu/serve/evict.py restore-under-pressure)."""


class PendingApply:
    """One in-flight coalesced dispatch: the issued (not yet
    overflow-checked) ``mesh_serve_apply`` plus everything
    :meth:`Superblock.finish` needs to run the overflow→widen→retry
    loop — the rollback base (``pre``), the slab/idx for retries, and
    the issue timestamp the host dispatch timing folds from. Minted by
    :meth:`Superblock.apply_async`; the pipelined serving loop
    (crdt_tpu/serve/loop.py) assembles + WAL-logs the NEXT slab while
    one of these is in flight."""

    __slots__ = ("slab", "idx_local", "tenants", "valid", "glanes",
                 "pre", "of", "tel", "telemetry", "donate", "t0")

    def __init__(self, slab, idx_local, tenants, valid, glanes, pre,
                 of, tel, telemetry, donate, t0):
        self.slab = slab
        self.idx_local = idx_local
        self.tenants = tenants
        self.valid = valid
        self.glanes = glanes
        self.pre = pre
        self.of = of
        self.tel = tel
        self.telemetry = telemetry
        self.donate = donate
        self.t0 = t0

    def ready(self) -> bool:
        """Best-effort 'has the scatter landed' probe (the
        ``parallel/stream.py`` ``_ready`` discipline — feeds the
        ``serve_overlap_hit`` counter only, never correctness)."""
        import jax

        leaf = jax.tree.leaves(self.of)[0]
        fn = getattr(leaf, "is_ready", None)
        if not callable(fn):
            return True
        try:
            return bool(fn())
        except Exception:
            return True


class Superblock:
    """``n_tenants`` live sessions of one registered kind served from
    ``n_lanes`` device-resident rows (default: fully resident,
    ``n_lanes == n_tenants``). ``caps`` is the kind's capacity dict
    (the ops ``empty`` kwargs minus ``batch``); ``n_lanes`` must
    divide the mesh's replica axis."""

    def __init__(
        self,
        n_tenants: int,
        mesh,
        *,
        kind: str = "orswot",
        caps: Optional[Dict[str, int]] = None,
        policy: ElasticPolicy = DEFAULT_POLICY,
        n_lanes: Optional[int] = None,
    ):
        self.kind = kind
        self.tk = sb_ops.tenant_kind(kind)
        self.mesh = mesh
        self.p = mesh.shape[REPLICA_AXIS]
        n_lanes = n_tenants if n_lanes is None else n_lanes
        if n_lanes % self.p:
            raise ValueError(
                f"{n_lanes} lanes do not divide the {self.p}-way "
                f"replica mesh axis"
            )
        if n_lanes > n_tenants:
            raise ValueError(
                f"{n_lanes} lanes exceed the {n_tenants}-tenant "
                f"population"
            )
        self.n_tenants = n_tenants
        self.n_lanes = n_lanes
        self.caps = dict(caps) if caps else self._default_caps(kind)
        self.policy = policy
        self.hysteresis = Hysteresis(policy)
        self.state = self._placed(
            self.tk.empty(**self.caps, batch=(n_lanes,))
        )
        # The indirection: lane_of[tenant] (-1 = not resident),
        # tenant_of[lane] (-1 = free), plus the free-lane pool. Dirt is
        # per TENANT (touched since last durable persist — what the
        # evictor must flush before freeing the lane); was_evicted
        # marks tenants currently parked in the durable tier.
        self.lane_of = np.full(n_tenants, -1, np.int64)
        self.tenant_of = np.full(n_lanes, -1, np.int64)
        # Free pool RANK-INTERLEAVED (lane r*lpr+i is rank r's): a
        # sequential pool would hand the first lanes_per_rank
        # admissions to rank 0 alone, serializing every early slab on
        # one rank's lane block.
        lpr = n_lanes // self.p
        order = np.arange(n_lanes).reshape(self.p, lpr).T.reshape(-1)
        self._free: deque = deque(int(x) for x in order)
        self.dirty = np.zeros(n_tenants, bool)
        self.was_evicted = np.zeros(n_tenants, bool)
        self.widen_events = 0
        self.last_pressure = 0.0

    def _placed(self, state):
        """Commit the lane axis to its mesh sharding up front (replica
        axis partitions lanes). The apply dispatch would resolve the
        same placement on its first output anyway — but starting
        UNplaced costs one full recompile when the second dispatch
        sees the now-sharded layout (found live: a 600 ms p99 outlier
        in the serve bench's measured window)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        spec = NamedSharding(self.mesh, P(REPLICA_AXIS))
        return jax.tree.map(lambda x: jax.device_put(x, spec), state)

    @staticmethod
    def _default_caps(kind: str) -> Dict[str, int]:
        if kind == "sparse_orswot":
            return dict(dot_cap=16, n_actors=4, deferred_cap=4, rm_width=8)
        return dict(n_elems=16, n_actors=4, deferred_cap=4)

    # ---- layout / residency --------------------------------------------
    @property
    def lanes_per_rank(self) -> int:
        return self.n_lanes // self.p

    def is_resident(self, tenant: int) -> bool:
        return self.lane_of[tenant] >= 0

    @property
    def n_resident(self) -> int:
        return self.n_lanes - len(self._free)

    @property
    def free_lanes(self) -> int:
        return len(self._free)

    def resident_tenants(self) -> np.ndarray:
        return self.tenant_of[self.tenant_of >= 0]

    def ensure_resident(self, tenant: int) -> int:
        """The tenant's lane, allocating a free (⊥) one on first touch.
        Raises :class:`LanePressure` when the pool is exhausted — the
        evictor's restore path converts that into evict-coldest-first.
        NOTE: this is the ⊥ fast path; a tenant with a DURABLE record
        must come back through ``Evictor.restore`` so the record loads.
        """
        lane = self.lane_of[tenant]
        if lane >= 0:
            return int(lane)
        if not self._free:
            raise LanePressure(
                f"all {self.n_lanes} lanes resident; evict a cold "
                f"tenant before admitting tenant {tenant}"
            )
        lane = self._free.popleft()
        self.lane_of[tenant] = lane
        self.tenant_of[lane] = tenant
        return int(lane)

    def release_lane(self, tenant: int) -> int:
        """Return a tenant's lane to the free pool (the evictor calls
        this AFTER persisting + clearing — the freed lane holds ⊥)."""
        lane = int(self.lane_of[tenant])
        if lane < 0:
            raise ValueError(f"tenant {tenant} is not resident")
        self.lane_of[tenant] = -1
        self.tenant_of[lane] = -1
        self._free.append(lane)
        return lane

    def nbytes(self) -> int:
        return sum(
            x.size * x.dtype.itemsize for x in jax.tree.leaves(self.state)
        )

    def row_nbytes(self) -> int:
        return self.nbytes() // max(self.n_lanes, 1)

    # ---- the coalesced apply (with the elastic retry) -------------------
    def apply(
        self,
        slab: sb_ops.OpSlab,
        idx_local,
        tenants,
        *,
        telemetry: bool = False,
        donate: bool = True,
    ):
        """Apply one coalesced slab (``idx_local`` per
        ``mesh_serve_apply``'s lane convention; ``tenants[B]`` the
        tenant id per slab lane, -1 = empty — every listed tenant must
        be resident). Returns the Telemetry sidecar (or None).
        Overflow rolls back ONLY the overflowed tenants, widens the
        superblock, and retries their lanes — bounded by
        ``policy.max_migrations``. ``apply`` == ``finish(apply_async())``
        — the split is the pipelined serving loop's seam
        (crdt_tpu/serve/loop.py overlaps next-slab assembly + WAL
        append with the in-flight scatter)."""
        return self.finish(self.apply_async(
            slab, idx_local, tenants, telemetry=telemetry, donate=donate,
        ))

    def apply_async(
        self,
        slab: sb_ops.OpSlab,
        idx_local,
        tenants,
        *,
        telemetry: bool = False,
        donate: bool = True,
    ) -> PendingApply:
        """Issue one coalesced dispatch WITHOUT waiting for it: gather
        the rollback base, launch ``mesh_serve_apply``, and return the
        :class:`PendingApply` handle :meth:`finish` completes. The
        superblock's device state advances to the in-flight output
        immediately (JAX async dispatch) — but no NEW dispatch may be
        issued and no overflow decision exists until :meth:`finish`
        runs (a widen retry changes every lane's shape)."""
        tenants = np.asarray(tenants)
        valid = tenants >= 0
        # Pre-rows of touched tenants: the rollback base that keeps the
        # overflow→widen→retry loop exact (a lossy overflowed apply —
        # e.g. a dropped parked remove — must never survive).
        glanes = np.where(valid, self.lane_of[np.where(valid, tenants, 0)], 0)
        gidx = jnp.asarray(glanes, jnp.int32)
        pre = sb_ops.gather_rows(self.state, gidx)
        t0 = time.perf_counter()
        out = mesh_serve_apply(
            self.state, slab, idx_local, self.mesh, kind=self.kind,
            donate=donate, telemetry=telemetry, sync=False,
        )
        if telemetry:
            self.state, of, t_raw = out
        else:
            self.state, of = out
            t_raw = None
        return PendingApply(
            slab, idx_local, tenants, valid, glanes, pre, of, t_raw,
            telemetry, donate, t0,
        )

    def finish(self, p: PendingApply):
        """Complete an in-flight dispatch: wait for its overflow flags,
        run the overflow→widen→retry loop (identical to the serial
        :meth:`apply` — the retries themselves are issued and waited
        inline), mark applied tenants dirty, and return the combined
        Telemetry (or None). The host dispatch timing
        (``hist_dispatch_us``) measures issue→completion, so an
        overlapped dispatch's histogram entry covers exactly the
        wall-clock a serial caller would have blocked for."""
        slab, idx_local = p.slab, p.idx_local
        tenants, valid, glanes, pre = p.tenants, p.valid, p.glanes, p.pre
        tel = None
        of, t_raw, t0 = p.of, p.tel, p.t0
        for attempt in range(self.policy.max_migrations + 1):
            if attempt:
                t0 = time.perf_counter()
                out = mesh_serve_apply(
                    self.state, slab, idx_local, self.mesh,
                    kind=self.kind, donate=p.donate,
                    telemetry=p.telemetry, sync=False,
                )
                if p.telemetry:
                    self.state, of, t_raw = out
                else:
                    self.state, of = out
            if p.telemetry:
                jax.block_until_ready((self.state, of, t_raw))
                t = tele.time_dispatch(t_raw, time.perf_counter() - t0)
                tel = t if tel is None else tele.combine(tel, t)
                self.last_pressure = float(t.widen_pressure)
            of_host = np.asarray(of) & valid
            if not of_host.any():
                break
            if attempt == self.policy.max_migrations:
                # Budget exhausted: roll the overflowed tenants back to
                # their pre-slab rows (a lossy overflowed apply — e.g. a
                # dropped parked remove — must never survive), mark the
                # SUCCESSFULLY applied tenants dirty, and name the
                # overflowed ones so the caller can re-queue their ops.
                ovr = np.where(of_host)[0]
                self.state = sb_ops.write_rows(
                    self.state,
                    jnp.asarray(glanes[ovr], jnp.int32),
                    jax.tree.map(lambda x: x[jnp.asarray(ovr)], pre),
                )
                self.dirty[tenants[valid & ~of_host]] = True
                raise CapacityOverflow(
                    f"{int(of_host.sum())} tenants still overflow after "
                    f"{attempt} widen migrations (caps {self.caps})",
                    tenants=tenants[ovr],
                )
            # Roll back overflowed tenants, widen EVERY lane's capacity
            # in one repack, retry only the overflowed slab lanes.
            ovr = np.where(of_host)[0]
            self.state = sb_ops.write_rows(
                self.state,
                jnp.asarray(glanes[ovr], jnp.int32),
                jax.tree.map(lambda x: x[jnp.asarray(ovr)], pre),
            )
            grow = self._widen_step()
            # The rollback base must track the widened layout, or a
            # SECOND overflow's scatter would mix pre-widen rows into
            # the widened state (shape mismatch at max_migrations > 1).
            pre = self.tk.widen(pre, **grow)
            keep = jnp.asarray(of_host)
            slab = slab._replace(
                kind=jnp.where(keep[:, None], slab.kind, sb_ops.NOOP)
            )
            idx_local = jnp.where(keep, jnp.asarray(idx_local), -1)
        self.dirty[tenants[valid]] = True
        return tel

    def _widen_step(self) -> Dict[str, int]:
        grow = {
            "deferred_cap": max(
                int(np.ceil(self.caps["deferred_cap"] * self.policy.factor)),
                self.caps["deferred_cap"] + 1,
            )
        }
        if "dot_cap" in self.caps:
            grow["dot_cap"] = max(
                int(np.ceil(self.caps["dot_cap"] * self.policy.factor)),
                self.caps["dot_cap"] + 1,
            )
        self.widen_capacity(**grow)
        return grow

    def widen_capacity(self, **growth: int) -> None:
        """Widen named capacity axes for EVERY lane (one repack — the
        PR 1 widen kernels with the lane axis as batch)."""
        self.state = self.tk.widen(self.state, **growth)
        self.caps.update(growth)
        self.widen_events += 1

    def narrow_capacity(self, **shrink: int) -> bool:
        """Try to narrow named capacity axes; a refusal (live lanes —
        the PR 5 ``narrow`` precondition) is a False no-op."""
        try:
            self.state = self.tk.narrow(self.state, **shrink)
        except ValueError:
            return False
        self.caps.update(shrink)
        return True

    def autoscale_capacity(self, pressure: Optional[float] = None):
        """One debounced capacity vote on the serving pressure signal
        (default: the last telemetry ``widen_pressure``) through
        ``elastic.Hysteresis.vote``. Returns the fired decision
        (``"widen"`` / ``"shrink"`` / None); shrink steps the deferred
        cap down by ``policy.factor`` to ``policy.shrink_floor`` and
        silently no-ops when lanes are live."""
        p = self.last_pressure if pressure is None else pressure
        vote = self.hysteresis.vote("serve.capacity", p)
        if vote == "widen":
            self._widen_step()
        elif vote == "shrink":
            floor = max(self.policy.shrink_floor, 1)
            target = max(int(self.caps["deferred_cap"] // self.policy.factor),
                         floor)
            if target >= self.caps["deferred_cap"]:
                return None
            if not self.narrow_capacity(deferred_cap=target):
                return None
        return vote

    # ---- per-tenant rows (the eviction tier's device boundary) ----------
    def _lane(self, tenant: int) -> int:
        lane = self.lane_of[tenant]
        if lane < 0:
            raise ValueError(
                f"tenant {tenant} is not resident — restore it first"
            )
        return int(lane)

    def row(self, tenant: int):
        """One resident tenant's state as a HOST pytree (numpy leaves)
        — the durable form the evictor persists."""
        return jax.tree.map(
            lambda x: np.asarray(x),
            sb_ops.unpack(self.state, self._lane(tenant)),
        )

    def write_row(self, tenant: int, row) -> None:
        """Land a full row for a tenant (allocating a lane on first
        touch — writing IS touching)."""
        lane = self.ensure_resident(tenant)
        self.state = sb_ops.write_rows(
            self.state,
            jnp.asarray([lane], jnp.int32),
            jax.tree.map(lambda x: jnp.asarray(x)[None], row),
        )

    def clear_lanes(self, lanes) -> None:
        """Reset device lanes to the join identity in ONE batched
        scatter (the evictor's post-persist clear)."""
        lanes = np.asarray(lanes, np.int32)
        if len(lanes) == 0:
            return
        empty = self.tk.empty(**self.caps, batch=(len(lanes),))
        self.state = sb_ops.write_rows(
            self.state, jnp.asarray(lanes), empty
        )

    def empty_row(self):
        return self.tk.empty(**self.caps)

    def read(self, tenant: int):
        """The resident tenant's observable read (host), via the
        kind's registered observe projection."""
        return jax.tree.map(
            np.asarray,
            self.tk.observe(sb_ops.unpack(self.state, self._lane(tenant))),
        )

    # ---- telemetry ------------------------------------------------------
    def annotate(self, tel: tele.Telemetry) -> tele.Telemetry:
        """Fill the host-owned serving gauges on a concrete Telemetry
        (the ``stream_*``/``wal_*`` fill discipline): ``live_tenants``
        = the served population (every session the front door answers
        for), ``evicted_tenants`` = tenants currently parked in the
        durable tier."""
        if not tele.is_concrete(tel):
            return tel
        n_evicted = int(
            (self.was_evicted & (self.lane_of < 0)).sum()
        )
        return tel._replace(
            live_tenants=jnp.uint32(self.n_tenants),
            evicted_tenants=jnp.uint32(n_evicted),
        )


from ..analysis.registry import register_shared_field as _reg_sf  # noqa: E402

# Shared-state coverage contract for the ``concurrency`` static-check
# section (analysis/effects.py): every field mutated outside __init__
# registers here, or discovery fails.
_reg_sf("state", owner="Superblock", module=__name__,
        kind="packed per-lane device state")
_reg_sf("caps", owner="Superblock", module=__name__,
        kind="per-kind capacity caps (widen/narrow)")
_reg_sf("lane_of", owner="Superblock", module=__name__,
        kind="tenant→lane indirection table")
_reg_sf("tenant_of", owner="Superblock", module=__name__,
        kind="lane→tenant back-pointer table")
_reg_sf("_free", owner="Superblock", module=__name__,
        kind="free-lane pool (deque)")
_reg_sf("dirty", owner="Superblock", module=__name__,
        kind="per-tenant dirty-since-persist flags")
_reg_sf("was_evicted", owner="Superblock", module=__name__,
        kind="per-tenant evicted-at-least-once flags")
_reg_sf("widen_events", owner="Superblock", module=__name__,
        kind="capacity-widen event counter")
_reg_sf("last_pressure", owner="Superblock", module=__name__,
        kind="smoothed lane-pressure telemetry")

__all__ = [
    "CapacityOverflow", "LanePressure", "PendingApply", "Superblock",
]
