"""The pipelined always-on serving loop (ISSUE 18 tentpole part 2):
slab N+1 assembles and WAL-commits WHILE slab N's scatter is in
flight, and cold-tenant persists drain on a bounded background ledger
off the dispatch latency path.

PR 15's front door ran assemble → log → dispatch → wait strictly
serially on the host: the device idled during every host-side
coalesce, and every pressure eviction paid a full persist inside the
flush. This module overlaps them — the PR 6 ``stream.overlap_hit``
discipline applied to serving:

- **the pipeline** — :meth:`ServeLoop.step` runs ONE round:
  assemble slab N+1 (pinning the in-flight slab's tenants against
  pressure eviction), group-commit it to the dirty-tenant WAL
  (crdt_tpu/serve/wal.py — the append overlaps slab N's scatter),
  probe whether N is still in flight (``serve_overlap_hit`` counts the
  rounds where host work genuinely hid device time), FINISH N
  (overflow→widen→retry), drain the background persister, then issue
  N+1. Depth is strictly 1: finish may widen (every lane changes
  shape), so issue N+1 can never precede finish N.
- **failure ordering** — if finish(N) fails with N+1 already
  assembled, N+1's ops requeue FIRST, then N's rolled ones
  (``appendleft`` puts the last push in front — per-tenant FIFO needs
  round N's ops ahead of round N+1's). N+1's WAL record is already
  durable; replay re-applies it idempotently, so the early log is
  harmless.
- **background persists** — :class:`BackgroundPersister` persists the
  coldest DIRTY residents ahead of need (bounded batch per step,
  between finish and the next issue — never while a dispatch is in
  flight, so it can neither read an unsettled row nor race an overflow
  rollback). A later pressure eviction finds the tenant clean and
  skips the persist entirely — the persist-THEN-clear crashpoint
  contract (crdt_tpu/serve/evict.py) holds trivially because the
  drain only persists; lanes are only ever freed by the evictor's own
  ordered path. Each row persist is timed into ``hist_persist_us``
  and crossed by the ``serve.persist.background_drain`` crashpoint.

The prose invariants above are DECLARED, not just narrated:
``analysis.concur.HB_CONTRACTS`` carries them as checkable
happens-before edges — ``wal_commit_precedes_dispatch`` (the
group-commit ≺ scatter order), ``persist_in_settled_window``
(finish(N) ≺ drain ≺ issue(N+1)), and ``requeue_preserves_durable_seq``
(the failure-ordering rollback keeps the first WAL seq). The
``concurrency`` static-check section proves each edge on every chain
invocation, and ``analysis.interleave.serve_world`` replays this loop
against the background persister and a pressure admission under every
≤2-preemption schedule, bit-identical to the serial oracle
(tests/test_concur.py).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Optional

import jax.numpy as jnp

from .. import telemetry as tele
from ..analysis.interleave import boundary
from ..durability import crashpoints
from ..obs import hist as obs_hist
from ..utils.metrics import metrics
from .ingest import FlushReport, IngestQueue
from .wal import CP_BG_PERSIST


class BackgroundPersister:
    """Bounded persist-ahead drain over one evictor: queue cold dirty
    tenants, persist at most ``batch`` rows per :meth:`drain` call.
    Only persists — never frees a lane, never clears a row — so the
    evictor's persist-THEN-clear ordering is untouched; the drain just
    makes the persist half already-done by eviction time."""

    def __init__(self, evictor, *, batch: int = 8):
        self.evictor = evictor
        self.batch = batch
        self._queue: deque = deque()
        self._queued = set()
        self.persisted = 0
        self.hist = obs_hist.zeros()

    def enqueue(self, tenants) -> int:
        n = 0
        for t in tenants:
            t = int(t)
            if t not in self._queued:
                self._queued.add(t)
                self._queue.append(t)
                n += 1
        return n

    def enqueue_cold(self, k: int, exclude=()) -> int:
        """Queue the k coldest dirty residents (the evictor's own
        coldness order — the tenants a pressure eviction would pick
        next, so persisting them now is exactly the work it saves)."""
        sb = self.evictor.sb
        cold = self.evictor.select_cold(k, exclude=exclude)
        return self.enqueue(t for t in cold if sb.dirty[t])

    def drain(self, *, budget: Optional[int] = None) -> int:
        """Persist up to ``budget`` (default ``batch``) queued tenants.
        Stale entries (evicted / already clean) drop for free. The
        ``serve.persist.background_drain`` crashpoint fires BETWEEN
        rows: a kill mid-drain leaves some tenants persisted and some
        not — all recoverable (last durable record + WAL suffix)."""
        sb = self.evictor.sb
        lim = self.batch if budget is None else budget
        n = 0
        while self._queue and n < lim:
            t = self._queue.popleft()
            self._queued.discard(t)
            if not sb.is_resident(t) or not sb.dirty[t]:
                continue
            crashpoints.hit(CP_BG_PERSIST)
            t0 = time.perf_counter()
            self.evictor.persist([t])
            self.hist = obs_hist.observe(
                self.hist, (time.perf_counter() - t0) * 1e6
            )
            self.persisted += 1
            n += 1
        if n:
            metrics.count("serve.persist.background", n)
        return n

    @property
    def backlog(self) -> int:
        return len(self._queue)

    def take_hist(self):
        """The accumulated persist-latency histogram since the last
        take (the annotate fill's per-record delta discipline)."""
        h, self.hist = self.hist, obs_hist.zeros()
        return h


class ServeLoop:
    """The overlapped serving loop over one :class:`IngestQueue`
    (which must carry the WAL if durability is wanted — the loop
    neither requires nor forbids one)."""

    def __init__(
        self,
        queue: IngestQueue,
        *,
        persister: Optional[BackgroundPersister] = None,
        persist_ahead: int = 0,
        persist_batch: int = 8,
    ):
        self.q = queue
        if persister is None and queue.evictor is not None:
            persister = BackgroundPersister(
                queue.evictor, batch=persist_batch
            )
        self.persister = persister
        # How many coldest-dirty tenants each step FEEDS the persister
        # (0 = drain only what callers enqueue explicitly).
        self.persist_ahead = persist_ahead
        self.inflight = None  # (built, PendingApply, wal_seq) or None
        self.steps = 0
        self.overlap_hits = 0
        self.rebalance_moves = 0
        self._annotated_overlap = 0
        self._annotated_moves = 0

    # ---- the pipelined round --------------------------------------------
    def step(self, *, telemetry: bool = False):
        """One pipelined round. Returns ``(FlushReport-or-None,
        Telemetry-or-None)`` for the dispatch this round FINISHED
        (round N — one step of latency behind the submit stream, the
        price of the overlap; :meth:`flush_inflight` settles the tail).
        """
        self.steps += 1
        pin = ()
        if self.inflight is not None:
            b0 = self.inflight[0]
            pin = [t for t, _ in b0.taken]
        built = self.q._assemble(pin=pin)
        seq = None
        if built.applied:
            try:
                seq = self.q._log(built)
            except BaseException as exc:
                self.q._unwind(built, exc)
                raise
        report = tel = None
        if self.inflight is not None:
            n_built, n_pending, n_seq = self.inflight
            if not n_pending.ready():
                # Host-side assembly + WAL commit genuinely hid device
                # time this round — the quantity the bench headlines.
                self.overlap_hits += 1
                metrics.count("serve.loop.overlap_hit")
            self.inflight = None

            def _requeue_next(exc, _b=built, _s=seq):
                if _b.applied:
                    self.q._unwind(
                        _b, RuntimeError("pipeline unwind"),
                        requeue_seq=_s,
                    )

            report, tel = self.q._finish(
                n_built, n_pending, n_seq, telemetry=telemetry,
                on_fail=_requeue_next,
            )
        # Background persists run in the settled window between
        # finish(N) and issue(N+1): no dispatch is in flight, so a
        # row read here can neither block on an unfinished scatter
        # nor capture an overflowed value a rollback would retract.
        boundary("persist.window")
        if self.persister is not None:
            if self.persist_ahead:
                self.persister.enqueue_cold(
                    self.persist_ahead,
                    exclude=[t for t, _ in built.taken],
                )
            self.persister.drain()
        if built.applied:
            try:
                pend = self.q._issue(built, telemetry=telemetry)
            except BaseException as exc:
                self.q._unwind(built, exc, requeue_seq=seq)
                raise
            self.inflight = (built, pend, seq)
        if tel is not None:
            tel = self.annotate(tel)
        return report, tel

    def flush_inflight(self, *, telemetry: bool = False):
        """Finish the in-flight dispatch without assembling a new slab
        (the loop's drain/shutdown barrier)."""
        if self.inflight is None:
            return None, None
        n_built, n_pending, n_seq = self.inflight
        self.inflight = None
        report, tel = self.q._finish(
            n_built, n_pending, n_seq, telemetry=telemetry,
        )
        if tel is not None:
            tel = self.annotate(tel)
        return report, tel

    def drain(self, *, telemetry: bool = False):
        """Step until the queue AND the pipeline are empty; returns the
        combined ``(FlushReport, Telemetry-or-None)`` totals."""
        tot = FlushReport(0, 0, 0, 0, 0, 0)
        tel = None

        def fold(rep, t):
            nonlocal tot, tel
            if rep is not None:
                tot = FlushReport(
                    tot.ops_applied + rep.ops_applied,
                    max(tot.lanes_used, rep.lanes_used),
                    tot.coalesced + rep.coalesced,
                    rep.pending_after,
                    tot.restored + rep.restored,
                    tot.dispatches + rep.dispatches,
                )
            if t is not None:
                tel = t if tel is None else tele.combine(tel, t)

        while self.q.n_pending or self.inflight is not None:
            before = self.q.n_pending
            rep, t = self.step(telemetry=telemetry)
            fold(rep, t)
            if (self.q.n_pending >= before and self.inflight is None
                    and before):
                break  # nothing placeable (should not happen)
        fold(*self.flush_inflight(telemetry=telemetry))
        return tot, tel

    # ---- skew / telemetry hooks -----------------------------------------
    def note_rebalance(self, moves: int) -> None:
        """Record shard-map moves an ``apply_rebalance`` made (the
        shard layer owns the policy; the loop owns the counter so it
        folds into the same Telemetry stream as the dispatches)."""
        self.rebalance_moves += int(moves)

    def annotate(self, tel: tele.Telemetry) -> tele.Telemetry:
        """Fill the loop-owned serving fields on a concrete Telemetry
        (per-record deltas, so ``telemetry.combine`` folds steps
        exactly): overlap hits and rebalance moves since the last
        annotate, plus the background persister's latency histogram."""
        if not tele.is_concrete(tel):
            return tel
        d_overlap = self.overlap_hits - self._annotated_overlap
        d_moves = self.rebalance_moves - self._annotated_moves
        self._annotated_overlap = self.overlap_hits
        self._annotated_moves = self.rebalance_moves
        tel = tel._replace(
            serve_overlap_hit=jnp.uint32(d_overlap),
            rebalance_moves=jnp.uint32(d_moves),
        )
        if self.persister is not None:
            tel = tel._replace(
                hist_persist_us=obs_hist.merge(
                    tel.hist_persist_us, self.persister.take_hist()
                )
            )
        return tel


from ..analysis.registry import register_shared_field as _reg_sf  # noqa: E402

_reg_sf("_queue", owner="BackgroundPersister", module=__name__,
        kind="cold-tenant persist queue (deque)")
_reg_sf("_queued", owner="BackgroundPersister", module=__name__,
        kind="membership set mirroring the persist queue")
_reg_sf("persisted", owner="BackgroundPersister", module=__name__,
        kind="lifetime background-persist counter")
_reg_sf("hist", owner="BackgroundPersister", module=__name__,
        kind="persist-latency log2 histogram")
_reg_sf("inflight", owner="ServeLoop", module=__name__,
        kind="in-flight slab ring (depth 1)")
_reg_sf("steps", owner="ServeLoop", module=__name__,
        kind="pipeline step counter")
_reg_sf("overlap_hits", owner="ServeLoop", module=__name__,
        kind="assemble-overlapped-with-flight counter")
_reg_sf("rebalance_moves", owner="ServeLoop", module=__name__,
        kind="lifetime shard-rebalance move counter")
_reg_sf("_annotated_overlap", owner="ServeLoop", module=__name__,
        kind="telemetry watermark for overlap_hits")
_reg_sf("_annotated_moves", owner="ServeLoop", module=__name__,
        kind="telemetry watermark for rebalance_moves")

__all__ = ["BackgroundPersister", "ServeLoop"]
