"""The serving tier's dirty-tenant write-ahead log (ISSUE 18
tentpole): every coalesced OpSlab is logged BEFORE its device dispatch,
so kill-anywhere recovery loses zero acked ops.

PR 15 made the serving tier durable only at persist/evict boundaries —
a host crash lost every op applied since a tenant last went cold. This
module extends the :class:`~crdt_tpu.durability.wal.Wal` framing over
the ingest path (δ-mutation logging, Almeida et al. 1410.2803: log the
join-irreducible op lanes, never rows):

- **log-before-dispatch** — :meth:`ServeWal.log_slab` appends ONE
  record per coalesced slab (only the occupied lanes — a 4096-lane
  slab with 40 hot tenants logs 40 lanes) and group-commits it with
  ONE fsync per dispatch (``fsync='on_round'`` + ``mark_round``). The
  fsync returning is the serving tier's ack point: an op is promised
  durable exactly when its slab's group commit lands, BEFORE the
  scatter — which is why a kill anywhere after the ack (mid-dispatch,
  pre-ack, mid-background-persist) recovers it.
- **replay = re-ingest** — :func:`recover_serve` loads every tenant's
  last durable snapshot (crdt_tpu/serve/evict.py ``recover_tenants``)
  and re-submits the WAL suffix through a fresh
  :class:`~crdt_tpu.serve.ingest.IngestQueue` — the SAME bit-identical
  ``mesh_serve_apply`` path that applied the ops the first time.
  Per-tenant submission order is preserved by construction (records
  replay in seq order, lanes preserve slot order), and op re-application
  onto a snapshot that already contains a prefix is idempotent (CRDT
  join semantics: a dot already present adds nothing, a covered remove
  removes nothing new) — so replay lands bit-identical to the
  pre-crash rows whatever the snapshot/WAL overlap.
- **crashpoints** — the new log/dispatch/ack boundaries register
  below (including the MID-DISPATCH point between the group commit and
  the scatter) and ride the PR 10 fuzz engine: the durability
  static-check section's probe workload crosses every one of them, and
  tests/test_serve.py kills at each and asserts recovery bit-identical
  with zero acked-op loss.

:func:`wal_precedes_dispatch` is the first migrated happens-before
contract of the ``concurrency`` static-check section
(``analysis.concur.HB_CONTRACTS``): an AST scan proving no dispatch
site precedes its WAL append/mark_round (the
``analysis.fixtures.serve_dispatch_before_wal`` broken twin must FAIL
it).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import numpy as np

from ..durability import crashpoints
from ..durability.wal import Wal
from ..utils.metrics import metrics

CP_PRE_LOG = crashpoints.register(
    "serve.wal.pre_log",
    "about to append a coalesced slab to the serve WAL (nothing acked "
    "yet — a kill here loses only unacked ops; recovery is the "
    "previous durable record)",
)
CP_POST_LOG_PRE_DISPATCH = crashpoints.register(
    "serve.wal.post_log_pre_dispatch",
    "slab group-committed to the serve WAL, device scatter NOT yet "
    "issued (THE mid-dispatch boundary: the ops are acked-durable, so "
    "recovery MUST replay this slab — zero acked-op loss)",
)
CP_POST_DISPATCH_PRE_ACK = crashpoints.register(
    "serve.dispatch.post_scatter_pre_ack",
    "scatter issued against the WAL'd slab, dispatch/durable trace "
    "stamps not yet placed (device state dies with the process — "
    "recovery replays the same slab from the WAL suffix)",
)
CP_BG_PERSIST = crashpoints.register(
    "serve.persist.background_drain",
    "inside the background persist drain, between tenant rows (a kill "
    "mid-drain leaves a partial persist generation set — every tenant "
    "recovers its last durable record + WAL suffix, acked-op loss "
    "stays zero)",
)

# WAL record leaf order for one compacted slab (meta rtype "slab"):
# tenants[K], kind[K,S], actor[K,S], ctr[K,S], clock[K,S,A],
# member[K,S,...] — K = occupied lanes only.
_SLAB_RTYPE = "slab"


class ReplayReport(NamedTuple):
    records: int    # slab records re-ingested
    ops: int        # individual ops re-submitted
    tenants: int    # distinct tenants touched by the replay


class ServeWal:
    """Group-committed slab log over one :class:`Wal` directory
    (``fsync='on_round'`` — :meth:`log_slab` appends AND commits, one
    fsync barrier per coalesced dispatch however many lanes the slab
    carries)."""

    def __init__(self, path, *, segment_bytes: int = 64 * 1024 * 1024):
        self.wal = Wal(
            path, fsync="on_round", segment_bytes=segment_bytes,
        )

    @property
    def last_seq(self) -> int:
        return self.wal.last_seq

    @property
    def bytes_appended(self) -> int:
        return self.wal.bytes_appended

    @property
    def fsyncs(self) -> int:
        return self.wal.fsyncs

    def log_slab(self, kind_arr, actor, ctr, clock, member, tenants) -> int:
        """Append one coalesced slab (occupied lanes only) and
        group-commit it — the serving tier's ack barrier. Returns the
        record's seq (the durable id requeued traces must reuse —
        crdt_tpu/obs/trace.py)."""
        from .. import obs

        crashpoints.hit(CP_PRE_LOG)
        used = np.nonzero(np.asarray(tenants) >= 0)[0]
        leaves = [
            np.ascontiguousarray(np.asarray(tenants)[used]),
            np.ascontiguousarray(np.asarray(kind_arr)[used]),
            np.ascontiguousarray(np.asarray(actor)[used]),
            np.ascontiguousarray(np.asarray(ctr)[used]),
            np.ascontiguousarray(np.asarray(clock)[used]),
            np.ascontiguousarray(np.asarray(member)[used]),
        ]
        n_ops = int((leaves[1] != 0).sum())
        seq = self.wal.append(
            {"rtype": _SLAB_RTYPE, "lanes": int(len(used)),
             "ops": n_ops},
            leaves,
        )
        self.wal.mark_round()  # THE group commit: one fsync per dispatch
        metrics.count("serve.wal.slabs")
        metrics.count("serve.wal.ops", n_ops)
        obs.emit(
            "serve_wal_round", seq=seq, lanes=int(len(used)), ops=n_ops,
            bytes=self.wal.bytes_appended,
        )
        return seq

    def records(self, since_seq: int = 0):
        """Slab records ``(seq, lanes-leaves)`` after ``since_seq`` —
        non-slab records in a shared directory are skipped."""
        for seq, meta, leaves in self.wal.records(since_seq):
            if meta.get("rtype") == _SLAB_RTYPE:
                yield seq, leaves

    def sync(self) -> None:
        self.wal.sync()

    def close(self) -> None:
        self.wal.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def replay_into(queue, serve_wal: ServeWal, *,
                since_seq: int = 0) -> ReplayReport:
    """Re-ingest the WAL suffix through ``queue`` — the same
    bit-identical coalesce→``mesh_serve_apply`` path that applied the
    ops pre-crash. One drain per record keeps per-tenant submission
    order exact across slab boundaries."""
    from ..ops import superblock as sb_ops
    from .ingest import AddOp, RmOp

    records = ops = 0
    touched = set()
    for _seq, leaves in serve_wal.records(since_seq):
        tenants, kind_arr, actor, ctr, clock, member = leaves
        for k in range(len(tenants)):
            t = int(tenants[k])
            for s in range(kind_arr.shape[1]):
                op_kind = int(kind_arr[k, s])
                if op_kind == sb_ops.NOOP:
                    continue
                if op_kind == sb_ops.ADD:
                    queue.submit(
                        t, AddOp(int(actor[k, s]), int(ctr[k, s]),
                                 np.asarray(member[k, s])),
                    )
                else:
                    queue.submit(
                        t, RmOp(np.asarray(clock[k, s], np.uint32),
                                np.asarray(member[k, s])),
                    )
                ops += 1
                touched.add(t)
        queue.drain()
        records += 1
    metrics.count("serve.wal.replayed_records", records)
    metrics.count("serve.wal.replayed_ops", ops)
    return ReplayReport(records, ops, len(touched))


def recover_serve(snap_root: str, queue,
                  serve_wal: Optional[ServeWal] = None,
                  *, since_seq: int = 0) -> ReplayReport:
    """The serving tier's kill-anywhere recovery driver: load every
    tenant's last durable snapshot into ``queue``'s superblock
    (crdt_tpu/serve/evict.py), then replay the WAL suffix through the
    queue. The snapshot tier and the WAL may overlap (a background
    persist may have landed ops the WAL also holds) — op re-application
    is idempotent, so the overlap is harmless and the result is
    bit-identical to the last acked state."""
    import os

    from .evict import _durable_tenants, recover_tenants

    sb = queue.sb
    ev = getattr(queue, "evictor", None)
    if ev is not None and (
        os.path.abspath(getattr(ev, "root", "")) ==
        os.path.abspath(snap_root)
    ):
        # The queue pages against the SAME durable tier we are
        # recovering from: mark every persisted tenant evicted-with-
        # record and let restore-on-touch load it — the resident set
        # stays bounded by the lane pool however many tenants the tier
        # holds (an eager write_row of all of them would deadlock on
        # LanePressure the moment records outnumber lanes).
        n = 0
        for t in _durable_tenants(snap_root):
            if not sb.is_resident(int(t)):
                sb.was_evicted[int(t)] = True
                n += 1
    else:
        rows = recover_tenants(snap_root, sb)
        for t, row in rows.items():
            sb.write_row(t, row)
            sb.dirty[t] = False
            sb.was_evicted[t] = False
        n = len(rows)
    if serve_wal is None:
        return ReplayReport(0, 0, n)
    rep = replay_into(queue, serve_wal, since_seq=since_seq)
    return rep


# ---- the WAL-before-dispatch ordering detector ---------------------------

_WAL_CALLS = frozenset({"log_slab", "mark_round", "append_slab", "_log"})
_DISPATCH_CALLS = frozenset({
    "apply_async", "mesh_serve_apply", "dispatch_slab", "_issue",
})


def wal_order_violations(obj) -> list:
    """AST-scan ``obj`` (a function, class, or module) for functions
    that both WAL-log a slab and dispatch it, and return a violation
    string per function whose FIRST dispatch site precedes its FIRST
    WAL call — the ordering that would ack ops the log never saw.
    Empty list = every logging dispatcher logs first. The walk itself
    lives in ``analysis.concur.call_order_violations`` (this detector
    is the first migrated ``HB_CONTRACTS`` entry,
    ``wal_precedes_dispatch`` — checked by the ``concurrency``
    static-check section, not the ``pipeline`` one)."""
    from ..analysis.concur import call_order_violations

    return [
        f"{v} — an op could be acked that the log never saw"
        for v in call_order_violations(obj, _WAL_CALLS, _DISPATCH_CALLS)
    ]


def wal_precedes_dispatch(obj) -> bool:
    """True iff ``obj`` contains no WAL-ordering violation (the honest
    ingest flush must pass;
    ``analysis.fixtures.serve_dispatch_before_wal`` must fail) —
    pinned by the ``concurrency`` static-check section's
    ``wal_precedes_dispatch`` HB contract."""
    return not wal_order_violations(obj)


from ..analysis.registry import register_obs_event as _reg_ev  # noqa: E402

_reg_ev(
    "serve_wal_round", subsystem="serve.wal",
    fields=("seq", "lanes", "ops", "bytes"),
    module=__name__,
)

from ..analysis.registry import register_shared_field as _reg_sf  # noqa: E402

_reg_sf("wal", owner="ServeWal", module=__name__,
        kind="underlying segment writer (durable seq + group commit)")

__all__ = [
    "ReplayReport", "ServeWal", "recover_serve", "replay_into",
    "wal_order_violations", "wal_precedes_dispatch",
]
