"""Per-host tenant shards + DCN anti-entropy (ISSUE 15's multi-host
leg; ``parallel/multihost.py`` + examples/04 extended to the serving
tier).

One mesh serves one host's tenant shard; a fleet of hosts serves the
full tenant population. Two pieces:

- :class:`TenantShardMap` — RENDEZVOUS-hashed ownership
  (highest-random-weight: every (tenant, host) pair gets a
  deterministic weight; the live host with the max weight owns the
  tenant). Rendezvous is what makes **failover minimal**: when
  membership evicts a host (the PR 8 suspicion/eviction machinery at
  host granularity — ``fail_over``), ONLY the dead host's tenants
  remap, every other assignment is untouched. The new owner re-warms
  each inherited tenant from the SHARED durable tier on its next touch
  (crdt_tpu/serve/evict.py restore-on-touch) — failover is eviction
  plus restore, no new machinery.
- :func:`sync_tenant_shards` — the DCN anti-entropy round: each host
  exports its resident rows for tenants it NO LONGER owns (or a
  chosen handoff set), every host gathers every export
  (``multihost.sync_tenant_rows`` under ``retry=`` — the PR 8
  exponential-backoff DCN hardening with the multi-collective
  lockstep guard), and JOINS the rows it owns into its superblock.
  Joining (not overwriting) is the CRDT guarantee that makes handoff
  racy-traffic-safe: a row restored from the durable tier and a
  fresher row shipped by the old owner converge to their lattice join
  regardless of arrival order.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.metrics import metrics
from .superblock import Superblock


def _weight(tenant: int, host: int) -> int:
    """Deterministic (tenant, host) rendezvous weight — a splitmix64
    round over the packed pair (stable across processes and runs; no
    Python hash randomization)."""
    z = (
        (tenant & 0xFFFFFFFF) << 32 | (host & 0xFFFFFFFF)
    ) + 0x9E3779B97F4A7C15
    z &= 0xFFFFFFFFFFFFFFFF
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
    return z ^ (z >> 31)


class TenantShardMap:
    """Rendezvous-hashed tenant→host ownership over a live host set."""

    def __init__(self, n_hosts: int, live: Optional[Iterable[int]] = None):
        if n_hosts < 1:
            raise ValueError("need at least one host")
        self.n_hosts = n_hosts
        self.live = set(range(n_hosts) if live is None else live)
        if not self.live <= set(range(n_hosts)):
            raise ValueError(f"live hosts {self.live} exceed {n_hosts}")
        if not self.live:
            raise ValueError("no live hosts")

    def owner(self, tenant: int) -> int:
        return max(self.live, key=lambda h: _weight(tenant, h))

    def owned(self, host: int, tenants: Sequence[int]) -> List[int]:
        return [t for t in tenants if self.owner(t) == host]

    def fail_over(self, host: int) -> None:
        """Membership evicted a host (PR 8's decision, host-granular):
        its tenants remap to survivors by rendezvous; everyone else's
        assignment is untouched. The new owners re-warm inherited
        tenants from the shared durable tier on next touch."""
        if host not in self.live:
            return
        if len(self.live) == 1:
            raise ValueError("cannot fail over the last live host")
        self.live.discard(host)
        metrics.count("serve.shard.failovers")

    def admit(self, host: int) -> None:
        if not 0 <= host < self.n_hosts:
            raise ValueError(f"host {host} out of range")
        self.live.add(host)


class ShardSyncReport(NamedTuple):
    tenants_shipped: int   # rows this host exported
    tenants_joined: int    # received rows joined into owned lanes
    bytes_shipped: int     # wire bytes this host exported


def export_rows(sb: Superblock, tenants: Sequence[int]) -> Dict[str, np.ndarray]:
    """Pack tenant rows into the flat numpy wire dict
    ``multihost.sync_tenant_rows`` gathers: ``tenants[K]`` plus one
    stacked plane per state leaf (``leaf_00``...)."""
    tenants = [int(t) for t in tenants]
    wire: Dict[str, np.ndarray] = {
        "tenants": np.asarray(tenants, np.int64)
    }
    rows = [sb.row(t) for t in tenants]
    template = sb.empty_row()
    leaves_t = jax.tree.leaves(template)
    for i in range(len(leaves_t)):
        if rows:
            wire[f"leaf_{i:02d}"] = np.stack(
                [jax.tree.leaves(r)[i] for r in rows]
            )
        else:
            lt = np.asarray(leaves_t[i])
            wire[f"leaf_{i:02d}"] = np.zeros((0, *lt.shape), lt.dtype)
    return wire


def ingest_rows(
    sb: Superblock, shard_map: TenantShardMap, host: int,
    wire: Dict[str, np.ndarray], *, evictor=None,
) -> int:
    """Join received rows for tenants THIS host owns into the
    superblock (lattice join per row — handoff-safe under races).
    Returns rows joined.

    An EVICTED tenant must re-warm through ``evictor`` first so the
    handoff row joins its durable record — joining against ⊥ and
    marking the lane dirty would let the next persist overwrite the
    durable state with the handoff row alone (silent loss). Without an
    evictor the case is REFUSED loudly rather than lossily absorbed."""
    tenants = wire["tenants"]
    if len(tenants) == 0:
        return 0
    template = sb.empty_row()
    treedef = jax.tree.structure(template)
    n = 0
    for k, t in enumerate(tenants):
        t = int(t)
        if shard_map.owner(t) != host:
            continue
        if not sb.is_resident(t):
            if evictor is not None:
                evictor.restore(t)
            elif sb.was_evicted[t]:
                raise ValueError(
                    f"tenant {t} is evicted — pass evictor= so its "
                    f"durable record joins the handoff row (joining "
                    f"against ⊥ would lose it at the next persist)"
                )
        row = jax.tree.unflatten(
            treedef,
            [jnp.asarray(wire[f"leaf_{i:02d}"][k])
             for i in range(treedef.num_leaves)],
        )
        mine = (
            jax.tree.map(jnp.asarray, sb.row(t))
            if sb.is_resident(t) else sb.empty_row()
        )
        joined = sb.tk.join(mine, row)
        joined = joined[0] if isinstance(joined, tuple) else joined
        sb.write_row(t, joined)
        sb.dirty[t] = True
        n += 1
    return n


def sync_tenant_shards(
    sb: Superblock,
    shard_map: TenantShardMap,
    host: int,
    handoff: Sequence[int],
    retry=None,
    evictor=None,
) -> ShardSyncReport:
    """One DCN anti-entropy round for the serving tier: export
    ``handoff`` rows (typically tenants this host holds but no longer
    owns — post-failover, post-rebalance), gather every host's export
    over DCN under ``retry=``, and join what this host owns. Single-
    process runs degenerate to a self-gather (the same code path the
    two-process example drives — examples/04_multihost_dcn.py)."""
    from ..parallel import multihost

    wire = export_rows(sb, handoff)
    bytes_shipped = sum(a.nbytes for a in wire.values())
    gathered = multihost.sync_tenant_rows(wire, retry=retry)
    joined = 0
    import jax as _jax

    me = _jax.process_index()
    for p, remote in enumerate(gathered):
        if p == me and len(gathered) > 1:
            continue  # own export: nothing new to join
        joined += ingest_rows(
            sb, shard_map, host, remote, evictor=evictor
        )
    metrics.count("serve.shard.rows_shipped", len(handoff))
    metrics.count("serve.shard.rows_joined", joined)
    return ShardSyncReport(
        tenants_shipped=len(handoff), tenants_joined=joined,
        bytes_shipped=bytes_shipped,
    )


__all__ = [
    "ShardSyncReport", "TenantShardMap", "export_rows", "ingest_rows",
    "sync_tenant_shards",
]
