"""Per-host tenant shards + DCN anti-entropy (ISSUE 15's multi-host
leg; ``parallel/multihost.py`` + examples/04 extended to the serving
tier).

One mesh serves one host's tenant shard; a fleet of hosts serves the
full tenant population. Two pieces:

- :class:`TenantShardMap` — RENDEZVOUS-hashed ownership
  (highest-random-weight: every (tenant, host) pair gets a
  deterministic weight; the live host with the max weight owns the
  tenant). Rendezvous is what makes **failover minimal**: when
  membership evicts a host (the PR 8 suspicion/eviction machinery at
  host granularity — ``fail_over``), ONLY the dead host's tenants
  remap, every other assignment is untouched. The new owner re-warms
  each inherited tenant from the SHARED durable tier on its next touch
  (crdt_tpu/serve/evict.py restore-on-touch) — failover is eviction
  plus restore, no new machinery.
- :func:`sync_tenant_shards` — the DCN anti-entropy round: each host
  exports its resident rows for tenants it NO LONGER owns (or a
  chosen handoff set), every host gathers every export
  (``multihost.sync_tenant_rows`` under ``retry=`` — the PR 8
  exponential-backoff DCN hardening with the multi-collective
  lockstep guard), and JOINS the rows it owns into its superblock.
  Joining (not overwriting) is the CRDT guarantee that makes handoff
  racy-traffic-safe: a row restored from the durable tier and a
  fresher row shipped by the old owner converge to their lattice join
  regardless of arrival order.
- :func:`rebalance_plan` / :func:`apply_rebalance` (ISSUE 18) —
  skew-aware placement on top of rendezvous. Real traffic is zipf:
  rendezvous balances tenant COUNTS, but a handful of hot tenants can
  pin one host at 10× the mean LOAD. The planner takes the per-tenant
  touch stats the evictor already keeps (``Evictor.touch_count`` —
  the same signal ``obs/trace.skew_report`` renders), computes
  per-host load, and greedily moves the hottest tenants OFF hosts
  above ``threshold × mean`` until every host fits — the MINIMAL-move
  property the ``pipeline`` static-check section gates: only
  overloaded hosts ever shed, an already-balanced fleet plans zero
  moves. Moves land as explicit ``overrides`` consulted before the
  rendezvous hash (so everything un-overridden keeps its stable
  assignment), and the row handoff rides the existing lattice-safe
  :func:`sync_tenant_shards` join. ``fail_over`` drops any override
  pointing at the dead host — those tenants fall back to rendezvous
  among the survivors, keeping failover minimal too.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.metrics import metrics
from .superblock import Superblock


def _weight(tenant: int, host: int) -> int:
    """Deterministic (tenant, host) rendezvous weight — a splitmix64
    round over the packed pair (stable across processes and runs; no
    Python hash randomization)."""
    z = (
        (tenant & 0xFFFFFFFF) << 32 | (host & 0xFFFFFFFF)
    ) + 0x9E3779B97F4A7C15
    z &= 0xFFFFFFFFFFFFFFFF
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
    return z ^ (z >> 31)


class TenantShardMap:
    """Rendezvous-hashed tenant→host ownership over a live host set."""

    def __init__(self, n_hosts: int, live: Optional[Iterable[int]] = None):
        if n_hosts < 1:
            raise ValueError("need at least one host")
        self.n_hosts = n_hosts
        self.live = set(range(n_hosts) if live is None else live)
        if not self.live <= set(range(n_hosts)):
            raise ValueError(f"live hosts {self.live} exceed {n_hosts}")
        if not self.live:
            raise ValueError("no live hosts")
        # Skew-driven placement overrides (tenant → host), consulted
        # BEFORE the rendezvous hash: everything un-overridden keeps
        # its stable assignment (apply_rebalance writes these).
        self.overrides: Dict[int, int] = {}

    def owner(self, tenant: int) -> int:
        o = self.overrides.get(int(tenant))
        if o is not None and o in self.live:
            return o
        return max(self.live, key=lambda h: _weight(tenant, h))

    def owned(self, host: int, tenants: Sequence[int]) -> List[int]:
        return [t for t in tenants if self.owner(t) == host]

    def fail_over(self, host: int) -> None:
        """Membership evicted a host (PR 8's decision, host-granular):
        its tenants remap to survivors by rendezvous; everyone else's
        assignment is untouched. Overrides POINTING at the dead host
        are dropped — those tenants fall back to rendezvous among the
        survivors (same minimal-remap property as the hash itself).
        The new owners re-warm inherited tenants from the shared
        durable tier on next touch."""
        if host not in self.live:
            return
        if len(self.live) == 1:
            raise ValueError("cannot fail over the last live host")
        self.live.discard(host)
        for t in [t for t, h in self.overrides.items() if h == host]:
            del self.overrides[t]
        metrics.count("serve.shard.failovers")

    def admit(self, host: int) -> None:
        if not 0 <= host < self.n_hosts:
            raise ValueError(f"host {host} out of range")
        self.live.add(host)


class ShardSyncReport(NamedTuple):
    tenants_shipped: int   # rows this host exported
    tenants_joined: int    # received rows joined into owned lanes
    bytes_shipped: int     # wire bytes this host exported


class RebalanceMove(NamedTuple):
    tenant: int
    src: int     # overloaded host shedding the tenant
    dst: int     # least-loaded live host at plan time
    load: float  # the tenant's touch weight that moves with it


def host_loads(
    shard_map: TenantShardMap, tenants: Sequence[int], weights,
) -> Dict[int, float]:
    """Per-live-host LOAD (sum of touch weights of owned tenants) —
    the quantity rendezvous cannot see and zipf traffic skews."""
    loads = {h: 0.0 for h in shard_map.live}
    for t in tenants:
        loads[shard_map.owner(t)] += float(weights[int(t)])
    return loads


def rebalance_plan(
    shard_map: TenantShardMap,
    tenants: Sequence[int],
    weights,
    *,
    threshold: float = 1.5,
    max_moves: Optional[int] = None,
) -> List[RebalanceMove]:
    """Greedy minimal-move plan: while some host carries more than
    ``threshold × mean`` load, move its hottest tenant to the
    least-loaded live host — but only while the move actually shrinks
    the gap (a tenant hotter than the imbalance would just relocate the
    hotspot). ``weights`` is indexable by tenant id (the evictor's
    ``touch_count`` array, or any per-tenant heat signal). MINIMAL
    means: an already-balanced fleet plans ZERO moves, and every
    planned move sheds from a host that was above threshold at the
    moment of the move — the property the ``pipeline`` static-check
    section verifies on synthetic zipf load."""
    if len(shard_map.live) < 2:
        return []
    loads = host_loads(shard_map, tenants, weights)
    by_host: Dict[int, List[int]] = {h: [] for h in shard_map.live}
    for t in tenants:
        by_host[shard_map.owner(t)].append(int(t))
    for h in by_host:
        by_host[h].sort(key=lambda t: float(weights[t]), reverse=True)
    mean = sum(loads.values()) / max(len(loads), 1)
    if mean <= 0:
        return []
    plan: List[RebalanceMove] = []
    limit = max_moves if max_moves is not None else len(tenants)
    while len(plan) < limit:
        src = max(loads, key=loads.get)
        dst = min(loads, key=loads.get)
        if loads[src] <= threshold * mean or src == dst:
            break
        moved = False
        for i, t in enumerate(by_host[src]):
            w = float(weights[t])
            # The move must shrink the src/dst gap, or the hotspot
            # just changes address.
            if loads[dst] + w < loads[src]:
                plan.append(RebalanceMove(t, src, dst, w))
                loads[src] -= w
                loads[dst] += w
                by_host[src].pop(i)
                by_host[dst].append(t)
                moved = True
                break
        if not moved:
            break  # nothing movable improves the imbalance
    return plan


def apply_rebalance(
    shard_map: TenantShardMap, plan: Sequence[RebalanceMove],
) -> int:
    """Land a plan as placement overrides (the handoff of the actual
    rows rides :func:`sync_tenant_shards` — export the moved tenants
    on their OLD owners, everyone joins what they now own). Returns
    moves applied; each is one ``rebalance_moves`` telemetry count and
    one ``shard_rebalance`` flight event."""
    from .. import obs

    n = 0
    for mv in plan:
        if mv.dst not in shard_map.live:
            continue
        shard_map.overrides[int(mv.tenant)] = int(mv.dst)
        n += 1
    if n:
        metrics.count("serve.shard.rebalance_moves", n)
        obs.emit(
            "shard_rebalance", moves=n,
            tenants=[int(m.tenant) for m in plan][:32],
            srcs=[int(m.src) for m in plan][:32],
            dsts=[int(m.dst) for m in plan][:32],
        )
    return n


def rebalance(
    shard_map: TenantShardMap,
    tenants: Sequence[int],
    weights,
    *,
    threshold: float = 1.5,
    max_moves: Optional[int] = None,
) -> List[RebalanceMove]:
    """Plan + apply in one call (the serving loop's periodic hook:
    ``weights`` is usually ``evictor.touch_count``). Returns the
    applied plan so the caller can hand the moved rows off and count
    the moves into its Telemetry (``ServeLoop.note_rebalance``)."""
    plan = rebalance_plan(
        shard_map, tenants, weights,
        threshold=threshold, max_moves=max_moves,
    )
    apply_rebalance(shard_map, plan)
    return plan


def export_rows(sb: Superblock, tenants: Sequence[int]) -> Dict[str, np.ndarray]:
    """Pack tenant rows into the flat numpy wire dict
    ``multihost.sync_tenant_rows`` gathers: ``tenants[K]`` plus one
    stacked plane per state leaf (``leaf_00``...)."""
    tenants = [int(t) for t in tenants]
    wire: Dict[str, np.ndarray] = {
        "tenants": np.asarray(tenants, np.int64)
    }
    rows = [sb.row(t) for t in tenants]
    template = sb.empty_row()
    leaves_t = jax.tree.leaves(template)
    for i in range(len(leaves_t)):
        if rows:
            wire[f"leaf_{i:02d}"] = np.stack(
                [jax.tree.leaves(r)[i] for r in rows]
            )
        else:
            lt = np.asarray(leaves_t[i])
            wire[f"leaf_{i:02d}"] = np.zeros((0, *lt.shape), lt.dtype)
    return wire


def ingest_rows(
    sb: Superblock, shard_map: TenantShardMap, host: int,
    wire: Dict[str, np.ndarray], *, evictor=None,
) -> int:
    """Join received rows for tenants THIS host owns into the
    superblock (lattice join per row — handoff-safe under races).
    Returns rows joined.

    An EVICTED tenant must re-warm through ``evictor`` first so the
    handoff row joins its durable record — joining against ⊥ and
    marking the lane dirty would let the next persist overwrite the
    durable state with the handoff row alone (silent loss). Without an
    evictor the case is REFUSED loudly rather than lossily absorbed."""
    tenants = wire["tenants"]
    if len(tenants) == 0:
        return 0
    template = sb.empty_row()
    treedef = jax.tree.structure(template)
    n = 0
    for k, t in enumerate(tenants):
        t = int(t)
        if shard_map.owner(t) != host:
            continue
        if not sb.is_resident(t):
            if evictor is not None:
                evictor.restore(t)
            elif sb.was_evicted[t]:
                raise ValueError(
                    f"tenant {t} is evicted — pass evictor= so its "
                    f"durable record joins the handoff row (joining "
                    f"against ⊥ would lose it at the next persist)"
                )
        row = jax.tree.unflatten(
            treedef,
            [jnp.asarray(wire[f"leaf_{i:02d}"][k])
             for i in range(treedef.num_leaves)],
        )
        mine = (
            jax.tree.map(jnp.asarray, sb.row(t))
            if sb.is_resident(t) else sb.empty_row()
        )
        joined = sb.tk.join(mine, row)
        joined = joined[0] if isinstance(joined, tuple) else joined
        sb.write_row(t, joined)
        sb.dirty[t] = True
        n += 1
    return n


def sync_tenant_shards(
    sb: Superblock,
    shard_map: TenantShardMap,
    host: int,
    handoff: Sequence[int],
    retry=None,
    evictor=None,
) -> ShardSyncReport:
    """One DCN anti-entropy round for the serving tier: export
    ``handoff`` rows (typically tenants this host holds but no longer
    owns — post-failover, post-rebalance), gather every host's export
    over DCN under ``retry=``, and join what this host owns. Single-
    process runs degenerate to a self-gather (the same code path the
    two-process example drives — examples/04_multihost_dcn.py)."""
    from ..parallel import multihost

    wire = export_rows(sb, handoff)
    bytes_shipped = sum(a.nbytes for a in wire.values())
    gathered = multihost.sync_tenant_rows(wire, retry=retry)
    joined = 0
    import jax as _jax

    me = _jax.process_index()
    for p, remote in enumerate(gathered):
        if p == me and len(gathered) > 1:
            continue  # own export: nothing new to join
        joined += ingest_rows(
            sb, shard_map, host, remote, evictor=evictor
        )
    metrics.count("serve.shard.rows_shipped", len(handoff))
    metrics.count("serve.shard.rows_joined", joined)
    return ShardSyncReport(
        tenants_shipped=len(handoff), tenants_joined=joined,
        bytes_shipped=bytes_shipped,
    )


from ..analysis.registry import register_obs_event as _reg_ev  # noqa: E402

_reg_ev(
    "shard_rebalance", subsystem="serve.shard",
    fields=("moves", "tenants", "srcs", "dsts"),
    module=__name__,
)

__all__ = [
    "RebalanceMove", "ShardSyncReport", "TenantShardMap",
    "apply_rebalance", "export_rows", "host_loads", "ingest_rows",
    "rebalance", "rebalance_plan", "sync_tenant_shards",
]
