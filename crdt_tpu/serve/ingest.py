"""Host-side ingest queue — per-tenant op streams coalesced into
batched device applies (ISSUE 15; the streamed-list ingestion of
``models/list.py`` generalized to the tenant-packed superblock).

Serving traffic arrives as millions of tiny per-tenant ops; dispatching
each alone would drown the device in launch overhead. The queue
buffers ops per tenant and, per :meth:`IngestQueue.flush`, packs them
into one :class:`~crdt_tpu.ops.superblock.OpSlab`:

- **lane layout** — each mesh rank owns a contiguous lane block and
  only tenants SHARDED to that rank fill it (local row indices — the
  ``mesh_serve_apply`` contract), so the device-side gather/scatter
  never crosses ranks;
- **coalescing** — a tenant with several queued ops occupies ONE lane,
  its ops in submission order along the slot axis. The
  ``ingest_coalesced_ops`` telemetry counter counts exactly the ops
  that shared a lane with a predecessor — every one of them is a
  device dispatch the queue amortized away. ``hist_ingest_batch``
  records the per-flush applied-op batch size (the amortization
  distribution the bench reports);
- **order** — per-tenant submission order is preserved across lanes,
  slots, and flush boundaries, which is why the coalesced path is
  bit-identical to the per-tenant sequential oracle (the slab scan
  applies slots in order; overflow-deferred ops stay queued IN FRONT);
- **backpressure** — the queue is bounded (``max_pending``):
  :meth:`submit` raises :class:`IngestBackpressure` when the bound is
  hit (callers flush and retry — the overflow behavior
  tests/test_serve.py pins). A flush that cannot place every hot
  tenant (more hot tenants on one rank than its lane block) leaves the
  remainder queued for the next flush — visible in the returned
  :class:`FlushReport`;
- **restore-on-touch** — submitting to an EVICTED tenant asks the
  attached evictor (crdt_tpu/serve/evict.py) to restore the lane from
  the durable tier BEFORE the op applies, making eviction invisible to
  correctness (only to latency).
"""

from __future__ import annotations

from collections import OrderedDict, deque
from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

from .. import telemetry as tele
from ..analysis.interleave import boundary
from ..durability import crashpoints
from ..obs import hist as obs_hist
from ..obs import trace as obs_trace
from ..ops import superblock as sb_ops
from ..utils.metrics import metrics
from .superblock import Superblock
from .wal import CP_POST_DISPATCH_PRE_ACK, CP_POST_LOG_PRE_DISPATCH


class IngestBackpressure(RuntimeError):
    """The bounded ingest queue is full — flush before submitting more
    (the serving tier's loss-free overflow behavior: ops are refused
    LOUDLY at the front door, never dropped after acceptance)."""


class AddOp(NamedTuple):
    actor: int
    counter: int
    member: np.ndarray  # the kind's member descriptor (mask / id list)


class RmOp(NamedTuple):
    clock: np.ndarray   # [A] uint32
    member: np.ndarray


class FlushReport(NamedTuple):
    ops_applied: int        # ops that landed on device this flush
    lanes_used: int         # slab lanes occupied
    coalesced: int          # ops that shared a lane with a predecessor
    pending_after: int      # ops still queued (rank-block overspill)
    restored: int           # evicted tenants re-warmed before applying
    dispatches: int         # device dispatches issued (1 + widen retries)


class _Built(NamedTuple):
    """One assembled-but-not-yet-dispatched coalesced slab — the unit
    the pipelined loop WAL-logs and issues while the previous dispatch
    is still in flight (host numpy planes; the jnp conversion happens
    at :meth:`IngestQueue._issue`)."""

    kind: np.ndarray
    actor: np.ndarray
    ctr: np.ndarray
    clock: np.ndarray
    member: np.ndarray
    idx: np.ndarray
    tenants: np.ndarray
    applied: int
    coalesced: int
    restored: int
    picked: list    # tenants whose deque fully drained into the slab
    taken: list     # (tenant, popped ops) — the requeue ledger


class IngestQueue:
    """Bounded per-tenant op buffer + slab builder over one
    :class:`~crdt_tpu.serve.superblock.Superblock`."""

    def __init__(
        self,
        superblock: Superblock,
        *,
        lanes: int = 256,
        depth: int = 4,
        max_pending: int = 1 << 16,
        evictor=None,
        wal=None,
    ):
        if lanes % superblock.p:
            raise ValueError(
                f"{lanes} lanes do not divide the {superblock.p}-way "
                f"replica axis"
            )
        self.sb = superblock
        self.lanes = lanes
        self.depth = depth
        self.max_pending = max_pending
        self.evictor = evictor
        # The dirty-tenant WAL (crdt_tpu/serve/wal.py, ISSUE 18): when
        # attached, every assembled slab is group-committed BEFORE its
        # dispatch issues — the flush's ack point moves from "scatter
        # returned" to "fsync returned", and kill-anywhere recovery
        # replays the suffix through an identical queue.
        self.wal = wal
        self.last_wal_seq = None
        self._last_wal_bytes = 0
        # tenant -> deque of ops, insertion-ordered so flushes drain
        # the longest-waiting tenants first (FIFO fairness).
        self.pending: "OrderedDict[int, deque]" = OrderedDict()
        self.n_pending = 0
        self.total_ops = 0
        self.total_coalesced = 0
        self.hist_batch = obs_hist.zeros()

    # ---- submission -----------------------------------------------------
    def submit(self, tenant: int, op) -> None:
        """Queue one op (:class:`AddOp` / :class:`RmOp`) for a tenant.
        Raises :class:`IngestBackpressure` at the bound."""
        if not 0 <= tenant < self.sb.n_tenants:
            raise ValueError(f"tenant {tenant} out of range")
        if self.n_pending >= self.max_pending:
            metrics.count("serve.ingest.backpressure")
            raise IngestBackpressure(
                f"{self.n_pending} ops pending >= max_pending="
                f"{self.max_pending}; flush() first"
            )
        self.pending.setdefault(tenant, deque()).append(op)
        self.n_pending += 1
        obs_trace.stamp("submit", tenant=tenant)
        if self.evictor is not None:
            self.evictor.note_touch(tenant)

    def add(self, tenant: int, actor: int, counter: int, member) -> None:
        self.submit(tenant, AddOp(actor, counter, np.asarray(member)))

    def rm(self, tenant: int, clock, member) -> None:
        self.submit(
            tenant, RmOp(np.asarray(clock, np.uint32), np.asarray(member))
        )

    # ---- the flush (assemble → log → issue → finish) --------------------
    def flush(self, *, telemetry: bool = False):
        """Coalesce queued ops into one slab and apply it. Returns
        ``(FlushReport, Telemetry-or-None)``. Loops are the caller's
        job: one flush issues ONE coalesced dispatch (plus widen
        retries), leaving rank-block overspill queued.

        The body is the serial composition of the four pipeline
        stages — WAL append strictly BEFORE dispatch issue (the
        ``pipeline`` static-check section AST-gates this ordering);
        the pipelined serving loop (crdt_tpu/serve/loop.py) calls the
        same four stages but finishes dispatch N only after assembling
        and logging slab N+1."""
        built = self._assemble()
        if built.applied == 0:
            report = FlushReport(
                0, 0, 0, self.n_pending, built.restored, 0
            )
            return report, (tele.zeros() if telemetry else None)
        try:
            seq = self._log(built)
            pending = self._issue(built, telemetry=telemetry)
        except BaseException as exc:
            self._unwind(built, exc)
            raise
        return self._finish(built, pending, seq, telemetry=telemetry)

    def _assemble(self, pin=()):
        """Stage 1: pack queued ops into host slab planes (residency
        restores included). Pops ops into the ``taken`` ledger; any
        failure mid-assembly (e.g. :class:`LanePressure` while paging)
        requeues every popped op in original order — nothing was
        logged or dispatched yet, so nothing is lost or acked.
        ``pin`` names tenants a pressure eviction must NOT free while
        this slab assembles — the pipelined loop pins the IN-FLIGHT
        slab's tenants, or an overflow rollback after the eviction
        could scatter a stale pre-row into a reallocated lane."""
        p, bl = self.sb.p, self.lanes // self.sb.p
        lpr = self.sb.lanes_per_rank
        caps = self.sb.caps
        a = caps["n_actors"]
        mshape, mdtype, mfill = self.sb.tk.member_plane(caps)

        kind = np.zeros((self.lanes, self.depth), np.uint8)
        actor = np.zeros((self.lanes, self.depth), np.int32)
        ctr = np.zeros((self.lanes, self.depth), np.uint32)
        clock = np.zeros((self.lanes, self.depth, a), np.uint32)
        member = np.full((self.lanes, self.depth, *mshape), mfill, mdtype)
        idx = np.full(self.lanes, -1, np.int32)
        tenants = np.full(self.lanes, -1, np.int64)

        lanes_free = [bl] * p
        lane_next = [r * bl for r in range(p)]
        restored = 0
        applied = 0
        coalesced = 0
        picked = []
        placed = set(int(t) for t in pin)
        taken = []  # (tenant, popped ops) — the requeue ledger
        try:
            for t in list(self.pending):
                # A drained-but-not-yet-settled tenant (picked by the
                # IN-FLIGHT slab; its entry is deleted at finish time)
                # has nothing to take — skipping it keeps the lane for
                # a tenant with real ops.
                if not self.pending[t]:
                    continue
                # Residency first (a tenant's mesh rank is a property
                # of its LANE): evicted/new tenants re-warm through
                # the evictor (durable record + lane-pressure paging —
                # placed tenants are PINNED so paging cannot free a
                # lane this slab already targets), or take a ⊥ lane
                # when no evictor is attached.
                if not self.sb.is_resident(t):
                    if self.evictor is not None:
                        if self.evictor.restore(t, _exclude=placed):
                            restored += 1
                    else:
                        self.sb.ensure_resident(t)
                dev_lane = self.sb.lane_of[t]
                r = int(dev_lane) // lpr
                if lanes_free[r] == 0:
                    continue
                lane = lane_next[r]
                lane_next[r] += 1
                lanes_free[r] -= 1
                q = self.pending[t]
                take = min(len(q), self.depth)
                ops_l = [q.popleft() for _ in range(take)]
                taken.append((t, ops_l))
                obs_trace.stamp("coalesce", tenant=t, count=take)
                for s, op in enumerate(ops_l):
                    if isinstance(op, AddOp):
                        kind[lane, s] = sb_ops.ADD
                        actor[lane, s] = op.actor
                        ctr[lane, s] = op.counter
                        member[lane, s] = self._member(
                            op.member, mshape, mfill
                        )
                    else:
                        kind[lane, s] = sb_ops.RM
                        clock[lane, s] = op.clock
                        member[lane, s] = self._member(
                            op.member, mshape, mfill
                        )
                applied += take
                coalesced += take - 1
                idx[lane] = int(dev_lane) % lpr
                tenants[lane] = t
                placed.add(t)
                if not q:
                    picked.append(t)
                if all(f == 0 for f in lanes_free):
                    break
        except BaseException:
            # Assembly failed: nothing logged, nothing dispatched —
            # every popped op returns to the FRONT of its queue in
            # original order and the traces roll back to submit-only.
            for t, ops_l in taken:
                dq = self.pending.setdefault(t, deque())
                for op in reversed(ops_l):
                    dq.appendleft(op)
            if taken:
                obs_trace.requeue([t for t, _ in taken])
            raise
        return _Built(
            kind, actor, ctr, clock, member, idx, tenants,
            applied, coalesced, restored, picked, taken,
        )

    def _log(self, built: "_Built"):
        """Stage 2: group-commit the assembled slab to the dirty-tenant
        WAL (one fsync per dispatch). The fsync returning IS the ack —
        from here a kill anywhere (the mid-dispatch crashpoint fires
        between this and the scatter) must recover every op this slab
        carries. No-op (returns None) when no WAL is attached."""
        if self.wal is None:
            return None
        before = self.wal.bytes_appended
        seq = self.wal.log_slab(
            built.kind, built.actor, built.ctr, built.clock,
            built.member, built.tenants,
        )
        self._last_wal_bytes = self.wal.bytes_appended - before
        self.last_wal_seq = seq
        crashpoints.hit(CP_POST_LOG_PRE_DISPATCH)
        boundary("wal.group_commit")
        return seq

    def _issue(self, built: "_Built", *, telemetry: bool = False):
        """Stage 3: launch the coalesced dispatch without waiting for
        it (``Superblock.apply_async``)."""
        boundary("dispatch.issue")
        slab = sb_ops.OpSlab(
            kind=jnp.asarray(built.kind), actor=jnp.asarray(built.actor),
            ctr=jnp.asarray(built.ctr), clock=jnp.asarray(built.clock),
            member=jnp.asarray(built.member),
        )
        self._widens_before = self.sb.widen_events
        return self.sb.apply_async(
            slab, jnp.asarray(built.idx), built.tenants,
            telemetry=telemetry,
        )

    def _unwind(self, built: "_Built", exc, requeue_seq=None) -> None:
        """The loss-free contract survives failure: every accepted op
        that did NOT land goes back to the FRONT of its tenant's queue
        in original order. A CapacityOverflow names exactly the tenants
        whose rows were rolled back (everyone else's ops DID apply —
        re-queueing those would double-apply); any earlier failure
        applied nothing, so everything returns. ``requeue_seq`` is the
        slab's durable WAL seq (when it was logged before the failure):
        rolled-back traces KEEP it, so the op's re-dispatch reuses the
        id its durable record already carries and replay/trace ids
        agree after recovery."""
        lost = getattr(exc, "tenants", None)
        requeued = 0
        rolled = []
        landed = []
        for t, ops_l in built.taken:
            if lost is not None and t not in lost:
                landed.append(t)
                continue
            dq = self.pending.setdefault(t, deque())
            for op in reversed(ops_l):
                dq.appendleft(op)
            requeued += len(ops_l)
            rolled.append(t)
        # Trace the split the requeue ledger just made concrete: landed
        # tenants' ops DID reach the device (their traces advance to
        # `dispatch`, and to `durable` when the slab was WAL'd);
        # rolled-back tenants' traces fall back to submit-only — but
        # keep their durable seq — so the next flush re-coalesces them.
        if landed:
            obs_trace.stamp("dispatch", tenants=landed)
            if requeue_seq is not None:
                obs_trace.stamp(
                    "durable", tenants=landed, seq=requeue_seq
                )
        if rolled:
            obs_trace.requeue(rolled, seq=requeue_seq)
        # Ops that DID land leave the pending count; drained tenants
        # that kept nothing leave the map (an empty deque would waste
        # a slab lane next flush).
        self.n_pending -= built.applied - requeued
        for t in built.picked:
            if t in self.pending and not self.pending[t]:
                del self.pending[t]

    def _finish(
        self, built: "_Built", pending, seq, *,
        telemetry: bool = False, on_fail=None,
    ):
        """Stage 4: complete the in-flight dispatch (overflow→widen→
        retry inside ``Superblock.finish``), settle the queue ledger,
        and place the dispatch/durable trace stamps. Failure unwinds
        through :meth:`_unwind` with the slab's WAL seq so re-queued
        ops keep their durable id; ``on_fail`` runs FIRST — the
        pipelined loop uses it to requeue the already-assembled NEXT
        slab's ops ahead of this slab's rolled ones (appendleft order:
        last pushed lands first, so per-tenant FIFO needs round N+1
        requeued before round N)."""
        boundary("dispatch.finish")
        try:
            tel = self.sb.finish(pending)
        except BaseException as exc:
            if on_fail is not None:
                on_fail(exc)
            self._unwind(built, exc, requeue_seq=seq)
            raise
        crashpoints.hit(CP_POST_DISPATCH_PRE_ACK)
        # `picked` means fully-drained AT ASSEMBLY time; under the
        # pipelined loop the deque may have refilled since (new
        # submissions, or the NEXT slab's assembly already popped from
        # it) — only a still-empty entry leaves the map.
        for t in built.picked:
            dq = self.pending.get(t)
            if dq is not None and not dq:
                del self.pending[t]
        applied, coalesced = built.applied, built.coalesced
        self.n_pending -= applied
        done = [t for t, _ in built.taken]
        obs_trace.stamp("dispatch", tenants=done)
        if seq is not None:
            obs_trace.stamp("durable", tenants=done, seq=seq)
        dispatches = 1 + (self.sb.widen_events - self._widens_before)
        self.total_ops += applied
        self.total_coalesced += coalesced
        self.hist_batch = obs_hist.observe(self.hist_batch, applied)
        metrics.count("serve.ingest.flushes")
        metrics.count("serve.ingest.ops", applied)
        metrics.count("serve.ingest.coalesced_ops", coalesced)
        if tel is not None:
            tel = self.annotate(tel, coalesced=coalesced, batch=applied)
        lanes_used = int((built.idx >= 0).sum())
        from ..obs import recorder as _rec

        _rec.emit(
            "ingest_flush", lanes=lanes_used, ops=applied,
            coalesced=coalesced, restored=built.restored,
            pending_after=self.n_pending,
        )
        report = FlushReport(
            applied, lanes_used, coalesced, self.n_pending,
            built.restored, dispatches,
        )
        return report, tel

    def _member(self, m: np.ndarray, mshape, mfill):
        out = np.full(mshape, mfill, np.asarray(m).dtype)
        m = np.asarray(m)
        if m.shape == tuple(mshape):
            return m
        out[: m.shape[0]] = m
        return out

    def drain(self, *, telemetry: bool = False):
        """Flush until the queue is empty; returns the combined
        ``(FlushReport, Telemetry-or-None)`` totals."""
        tot = FlushReport(0, 0, 0, 0, 0, 0)
        tel = None
        while self.n_pending:
            rep, t = self.flush(telemetry=telemetry)
            if rep.ops_applied == 0 and rep.restored == 0:
                break  # nothing placeable (should not happen)
            tot = FlushReport(
                tot.ops_applied + rep.ops_applied,
                max(tot.lanes_used, rep.lanes_used),
                tot.coalesced + rep.coalesced,
                rep.pending_after,
                tot.restored + rep.restored,
                tot.dispatches + rep.dispatches,
            )
            if t is not None:
                tel = t if tel is None else tele.combine(tel, t)
        return tot, tel

    def annotate(
        self, tel: tele.Telemetry, *, coalesced: int, batch: int
    ) -> tele.Telemetry:
        """Fill the host-owned ingest telemetry for ONE flush (the
        ``stream_*`` fill discipline — per-record increments so
        ``telemetry.combine`` folds flushes exactly): the flush's
        coalesced-op count and one batch-size observation, the WAL
        bytes its group commit appended (0 without a WAL), plus the
        superblock's residency gauges."""
        if not tele.is_concrete(tel):
            return tel
        tel = tel._replace(
            ingest_coalesced_ops=jnp.uint32(coalesced),
            serve_wal_bytes=jnp.float32(self._last_wal_bytes),
            hist_ingest_batch=obs_hist.observe(
                obs_hist.zeros(), batch
            ),
        )
        self._last_wal_bytes = 0
        return self.sb.annotate(tel)


from ..analysis.registry import register_obs_event as _reg_ev  # noqa: E402

_reg_ev(
    "ingest_flush", subsystem="serve.ingest",
    fields=("lanes", "ops", "coalesced", "restored", "pending_after"),
    module=__name__,
)

from ..analysis.registry import register_shared_field as _reg_sf  # noqa: E402

_reg_sf("pending", owner="IngestQueue", module=__name__,
        kind="per-tenant queued-op deques")
_reg_sf("n_pending", owner="IngestQueue", module=__name__,
        kind="total queued-op count (backpressure gauge)")
_reg_sf("last_wal_seq", owner="IngestQueue", module=__name__,
        kind="seq of the newest group-committed slab")
_reg_sf("_last_wal_bytes", owner="IngestQueue", module=__name__,
        kind="bytes of the newest WAL record (telemetry)")
_reg_sf("_widens_before", owner="IngestQueue", module=__name__,
        kind="widen-event watermark captured at issue time")
_reg_sf("total_ops", owner="IngestQueue", module=__name__,
        kind="lifetime applied-op counter")
_reg_sf("total_coalesced", owner="IngestQueue", module=__name__,
        kind="lifetime coalesced-op counter")
_reg_sf("hist_batch", owner="IngestQueue", module=__name__,
        kind="ops-per-slab log2 histogram")

__all__ = [
    "AddOp", "FlushReport", "IngestBackpressure", "IngestQueue", "RmOp",
]
