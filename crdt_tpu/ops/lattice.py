"""Shared lattice-reduction machinery.

``tree_fold`` collapses a replica batch (leading axis) with any pairwise
lattice join in a log2 reduction tree — sound because every join in this
package is associative, commutative, and idempotent (the property suite
asserts this on device shapes, SURVEY.md §7.3 "deterministic reduction").
The batch is padded to a power of two with join identities, which the
join absorbs.
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp


def tree_fold(
    states: Any,
    identity: Any,
    join: Callable[[Any, Any], Tuple[Any, jax.Array]],
) -> Tuple[Any, jax.Array]:
    """Fold ``states`` (a pytree batched on the leading axis) with
    ``join(a, b) -> (joined, flag)``; ``identity`` is one unbatched join
    identity. Returns ``(folded, any_flag)`` — flags (overflow/conflict)
    are OR-accumulated across every pairwise join, reducing only the
    batch axis so multi-lane flags (e.g. the map join's [sibling,
    deferred] pair) keep their shape."""
    r = jax.tree.leaves(states)[0].shape[0]
    if r == 1:
        # Join with the identity so the flag comes out in the join's
        # shape (e.g. the map join's [sibling, deferred] pair) — a bare
        # scalar initializer would break multi-lane flag consumers.
        return join(jax.tree.map(lambda x: x[0], states), identity)
    flagged = jnp.zeros((), bool)
    pow2 = 1
    while pow2 < r:
        pow2 *= 2
    if pow2 != r:
        pad = jax.tree.map(
            lambda e, s: jnp.broadcast_to(e, (pow2 - r, *e.shape)).astype(s.dtype),
            identity,
            states,
        )
        states = jax.tree.map(lambda s, p: jnp.concatenate([s, p], axis=0), states, pad)
        r = pow2
    while r > 1:
        half = r // 2
        left = jax.tree.map(lambda x: x[:half], states)
        right = jax.tree.map(lambda x: x[half:], states)
        states, flag = jax.vmap(join)(left, right)
        flagged = flagged | jnp.any(flag, axis=0)
        r = half
    return jax.tree.map(lambda x: x[0], states), flagged
