"""The shared outer-key-level settle sequence for nested-map slabs.

Every nesting level a map wraps around an already-flattened causal slab
(``map_orswot`` around orswot, ``map_map`` around the MVReg map,
``map3`` around map_orswot — SURVEY.md §7.1 slab composition) carries
the same outer deferred buffer and runs the same join-time sequence:

    union both sides' parked keyset-removes
    → dedupe equal-clock slots (dict-union semantics)
    → replay against the content slab, dropping caught-up slots
    → compact back to capacity (overflow if a live slot won't fit)
    → scrub parked state inside bottomed children

The per-type pieces — how a level-keyset mask expands onto the leaf
slab, and which inner buffers a dead child takes down with it — stay in
the type modules as the ``replay``/``scrub`` closures. The ORDER of the
sequence lives here, once: it is correctness-critical (e.g. the scrub
must follow the replay, because a replayed remove can newly bottom a
child — tests/test_models_map3.py pins the failure mode).
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp

from .orswot import _compact_deferred, _dedupe_deferred

Bufs = Tuple[jax.Array, jax.Array, jax.Array]  # (dcl, dkeys, dvalid)


def concat_outer(a: Bufs, b: Bufs) -> Bufs:
    """Union two outer buffers (slot-list concatenation; dedupe happens
    in ``settle_outer_level``)."""
    return (
        jnp.concatenate([a[0], b[0]], axis=-2),
        jnp.concatenate([a[1], b[1]], axis=-2),
        jnp.concatenate([a[2], b[2]], axis=-1),
    )


def settle_outer_level(
    state,
    cap: int,
    get_bufs: Callable,    # state -> (dcl, dkeys, dvalid)
    with_bufs: Callable,   # (state, dcl, dkeys, dvalid) -> state
    replay: Callable,      # state -> state   (kill covered + drop caught-up)
    scrub: Callable,       # (state, element_axis) -> state
    element_axis=None,
):
    """Dedupe → replay → compact → scrub one outer buffer level.
    ``state`` arrives with the buffers already unioned (``concat_outer``)
    and the inner levels already joined. Returns ``(state, overflow)``."""
    dcl, dkeys, dvalid = _dedupe_deferred(*get_bufs(state))
    state = replay(with_bufs(state, dcl, dkeys, dvalid))
    dcl, dkeys, dvalid, overflow = _compact_deferred(*get_bufs(state), cap)
    state = scrub(with_bufs(state, dcl, dkeys, dvalid), element_axis)
    return state, jnp.any(overflow)
