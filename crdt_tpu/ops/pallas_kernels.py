"""Pallas TPU kernels for the anti-entropy hot loop.

The jnp ``tree_fold`` (ops/lattice.py) makes log2(R) passes over HBM —
each reduction level materialises a half-size replica batch. The fused
fold here streams every replica's dot matrix through VMEM once and
accumulates the lattice join on-chip: HBM traffic drops from
O(R·E·A·log R) to O(R·E·A), which is the whole game for the
bandwidth-bound ORSWOT merge (SURVEY.md §4.2; BASELINE config 3).

Layout: the dense state keeps ``ctr[R, E, A]`` with a small actor axis
(A ≈ 8–32). Lanes are 128-wide on TPU, so computing in ``[E, A]`` layout
wastes 15/16 of the VPU — the kernel therefore runs transposed
``[A, E]`` blocks (E on the lane axis), with the wrapper paying two XLA
transposes (one pass each) around the single fused pass.

Mosaic constraints shape two choices here:
- tops ride as ``[R, A, 1]`` so every access is a static slice on the
  untiled leading axis. (A ``[A, R]`` layout would need
  ``tops_ref[:, pl.ds(r, 1)]`` per replica, which does not compile:
  dynamic lane-axis slices must be 128-aligned.)
- the replica axis is walked by an inner sequential grid dimension in
  chunks of ``r_chunk``, with the running join living in the output
  block (same revisited block across the chunk steps — the standard
  TPU reduction pattern). VMEM holds one ``[r_chunk, A, tile_e]``
  input block, so R is unbounded.
- within a resident chunk the fold is a statically-unrolled
  pairwise-halving tree (``r_chunk`` is forced to a power of two):
  log2(rc) *batched* joins over ``[h, A, tile_e]`` values instead of
  rc sequential ``[A, tile_e]`` joins. Same bits by associativity/
  commutativity of the join; the long scalar-loop dependency chain —
  which left the VPU idle and capped the first version at ~136 GB/s —
  disappears, so the stream runs near DMA speed.

Only the entry matrices fold in-kernel. The deferred-removal buffers are
tiny ([R, D, A] clocks + [R, D, E] masks with D ≈ 4–8) and their replay
is a pointwise mask over the folded result, so the wrapper handles them
with stock jnp (XLA fuses it into the epilogue): union all parked
removes, replay once against the folded entries, drop caught-up slots.
Replaying once at the end is equivalent to the pairwise join's
replay-at-every-node because replay is idempotent and monotone (it
zeroes exactly the dots the rm clocks cover, which no join can
resurrect past the final replay), and a slot is always replayed before
the catch-up drop — the property suite pins fused == tree fold.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .orswot import (
    OrswotState,
    _apply_parked,
    _compact_deferred,
    _dedupe_deferred,
)


def _umax(a, b):
    # Mosaic cannot legalize vector arith.maxui/minui on this toolchain;
    # compare+select (cmpi ult + arith.select) lowers fine and keeps
    # unsigned semantics for u32 counters.
    return jnp.where(a >= b, a, b)


def _umin(a, b):
    return jnp.where(a <= b, a, b)


def _join_step(acc_top, acc_ctr, b_top, b_ctr):
    """Pairwise entry-matrix join in transposed [..., A, E] layout —
    2D ``[A, E]`` operands or a batch ``[H, A, 1]``/``[H, A, E]`` of
    independent pairs (the tree levels below). Reference merge rule
    (ops/orswot.py ``join``): unseen dots survive, common members keep
    common dots ∪ each side's unseen dots."""
    wa = jnp.where(acc_ctr > b_top, acc_ctr, 0)
    wb = jnp.where(b_ctr > acc_top, b_ctr, 0)
    pa = jnp.any(acc_ctr > 0, axis=-2, keepdims=True)  # [..., 1, TILE_E]
    pb = jnp.any(b_ctr > 0, axis=-2, keepdims=True)
    common = _umax(_umin(acc_ctr, b_ctr), _umax(wa, wb))
    new_ctr = jnp.where(pa & pb, common, jnp.where(pa, wa, wb))
    return _umax(acc_top, b_top), new_ctr


def _fold_kernel(tops_ref, ctrs_ref, top_out_ref, ctr_out_ref):
    """Lattice fold over one replica chunk, one E-tile per program.
    tops_ref: [RC, A, 1]; ctrs_ref: [RC, A, TILE_E], RC a power of two.

    The in-chunk reduction is a statically-unrolled pairwise-halving
    tree: each level joins the chunk's top half against its bottom half
    as ONE batched [h, A, TILE_E] op, so the VPU always works on large
    vectors and the dependency chain is log2(RC) deep, not RC. The
    output block is the running accumulator across the (inner,
    sequential) replica-chunk grid axis; tree order equals sequential
    order because the join is associative/commutative/idempotent."""
    rc = ctrs_ref.shape[0]
    tops = tops_ref[:]
    ctrs = ctrs_ref[:]
    n = rc
    while n > 1:
        h = n // 2
        tops, ctrs = _join_step(tops[h:n], ctrs[h:n], tops[:h], ctrs[:h])
        n = h
    chunk_top, chunk_ctr = tops[0], ctrs[0]

    first = pl.program_id(1) == 0

    @pl.when(first)
    def _init():
        top_out_ref[:] = chunk_top
        ctr_out_ref[:] = chunk_ctr

    @pl.when(jnp.logical_not(first))
    def _acc():
        acc_top, acc_ctr = _join_step(
            top_out_ref[:], ctr_out_ref[:], chunk_top, chunk_ctr
        )
        top_out_ref[:] = acc_top
        ctr_out_ref[:] = acc_ctr


def _fold_entries_fused(
    top: jax.Array,
    ctr: jax.Array,
    tile_e: int,
    r_chunk: int,
    interpret: bool,
    n_passes: int = 1,
) -> Tuple[jax.Array, jax.Array]:
    """Fused fold of the entry matrices only: ``top[R, A]``,
    ``ctr[R, E, A]`` → ``(top[A], ctr[E, A])``.

    ``n_passes > 1`` makes the grid re-walk the resident replica chunk
    that many times, accumulating into the same output block. Because
    the join is idempotent the result is unchanged, but the DMA and
    compute stream is exactly that of folding ``n_passes * R`` distinct
    replicas — the honest way to time a config-3-scale stream whose full
    dot-state exceeds HBM (bench.py), with one dispatch."""
    r, e, a = ctr.shape
    tile_e = min(tile_e, max(e, 1))
    rc = _pick_r_chunk(r, a, tile_e, r_chunk)  # clamped power of two
    pad_e = (-e) % tile_e
    pad_r = (-r) % rc

    ctrs_t = jnp.swapaxes(ctr, -1, -2)  # [R, A, E]
    tops3 = top[:, :, None]             # [R, A, 1]
    if pad_e:
        ctrs_t = jnp.pad(ctrs_t, ((0, 0), (0, 0), (0, pad_e)))
    if pad_r:
        # Empty replicas are the join identity (ops/orswot.py ``empty``).
        ctrs_t = jnp.pad(ctrs_t, ((0, pad_r), (0, 0), (0, 0)))
        tops3 = jnp.pad(tops3, ((0, pad_r), (0, 0), (0, 0)))
    e_padded = e + pad_e
    r_steps = (r + pad_r) // rc

    top_t, ctr_t = pl.pallas_call(
        _fold_kernel,
        # Replica chunks on the inner (fastest) axis so the output block
        # accumulates across them before the E-tile advances.
        grid=(e_padded // tile_e, n_passes * r_steps),
        in_specs=[
            pl.BlockSpec(
                (rc, a, 1), lambda i, j: (j % r_steps, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (rc, a, tile_e),
                lambda i, j: (j % r_steps, 0, i),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=[
            pl.BlockSpec((a, 1), lambda i, j: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((a, tile_e), lambda i, j: (0, i), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((a, 1), top.dtype),
            jax.ShapeDtypeStruct((a, e_padded), ctr.dtype),
        ],
        interpret=interpret,
    )(tops3, ctrs_t)

    return top_t[:, 0], ctr_t.T[:e]


# VMEM budget for the streamed input block (double-buffered by the
# pipeline). 1 MiB measured fastest on v5e: the in-kernel halving tree
# holds a block copy plus ~block-sized intermediates, so a 2 MiB block
# leaves too little VMEM to overlap DMA with compute (484 GB/s at 1 MiB
# vs 77-436 GB/s at 2 MiB in the r3 sweep), and 4 MiB fails to compile.
_VMEM_BLOCK_BUDGET = 1024 * 1024


def _pick_r_chunk(r: int, a: int, tile_e: int, r_chunk: Optional[int]) -> int:
    if r_chunk is None:
        r_chunk = max(8, _VMEM_BLOCK_BUDGET // (max(a, 1) * tile_e * 4))
    r_chunk = min(r_chunk, max(r, 1))
    # The in-kernel halving tree needs a power of two; round down (the
    # replica axis is padded with join-identity empties to a multiple).
    return 1 << (r_chunk.bit_length() - 1)


def fold_fused(
    states: OrswotState,
    tile_e: int = 512,
    r_chunk: Optional[int] = None,
    interpret: Optional[bool] = None,
    n_passes: int = 1,
) -> Tuple[OrswotState, jax.Array]:
    """Drop-in replacement for ``ops.orswot.fold`` (same result, same
    overflow flag) with the replica reduction fused into one HBM pass.

    ``r_chunk`` defaults to a VMEM-safe size for the given actor count;
    ``interpret`` defaults to auto: compiled on TPU, interpreter
    elsewhere (CPU tests exercise the same kernel semantics).
    ``n_passes`` re-walks the replica batch that many times (identical
    result by idempotence; used by bench.py to time a stream of
    ``n_passes * R`` replicas in one dispatch).
    """
    if interpret is None:
        # "axon" is a TPU chip behind a relay (same Mosaic compile path).
        interpret = jax.default_backend() not in ("tpu", "axon")
    r, e, a = states.ctr.shape
    tile_e = min(tile_e, max(e, 1))
    r_chunk = _pick_r_chunk(r, a, tile_e, r_chunk)
    return _fold_fused_jit(states, tile_e, r_chunk, interpret, n_passes)


def fold_auto(states: OrswotState, prefer: str = "auto"):
    """Local replica-batch fold with backend-appropriate dispatch: the
    fused Pallas kernel where it compiles to Mosaic (TPU backends), the
    jnp log-tree fold elsewhere (where "fused" would mean the Pallas
    *interpreter* — orders of magnitude slower than XLA:CPU).

    ``prefer``: "auto" (backend pick), "fused", or "tree" — the forced
    modes exist so CPU tests can pin fused-in-situ semantics and so
    callers can opt out. Same ``(state, overflow)`` contract as
    ``ops.orswot.fold``; bit-identical results either way (the property
    suite pins it)."""
    from .orswot import fold as tree_fold

    if prefer not in ("auto", "fused", "tree"):
        raise ValueError(f"prefer must be auto|fused|tree, got {prefer!r}")
    if prefer == "fused" or (
        prefer == "auto" and jax.default_backend() in ("tpu", "axon")
    ):
        return fold_fused(states)
    return tree_fold(states)


@partial(jax.jit, static_argnames=("tile_e", "r_chunk", "interpret", "n_passes"))
def _fold_fused_jit(
    states: OrswotState,
    tile_e: int,
    r_chunk: int,
    interpret: bool,
    n_passes: int = 1,
) -> Tuple[OrswotState, jax.Array]:
    r, e, a = states.ctr.shape
    top, ctr = _fold_entries_fused(
        states.top,
        states.ctr,
        tile_e=tile_e,
        r_chunk=r_chunk,
        interpret=interpret,
        n_passes=n_passes,
    )

    # Deferred epilogue (stock jnp; see module docstring): union every
    # replica's parked removes, replay once, drop caught-up, compact.
    d = states.dcl.shape[-2]
    dcl = states.dcl.reshape(r * d, a)
    dmask = states.dmask.reshape(r * d, e)
    dvalid = states.dvalid.reshape(r * d)
    dcl, dmask, dvalid = _dedupe_deferred(dcl, dmask, dvalid)
    ctr = _apply_parked(ctr, dcl, dmask, dvalid)
    still_ahead = ~jnp.all(dcl <= top[None, :], axis=-1)
    dvalid = dvalid & still_ahead
    dcl, dmask, dvalid, overflow = _compact_deferred(dcl, dmask, dvalid, d)
    return (
        OrswotState(top=top, ctr=ctr, dcl=dcl, dmask=dmask, dvalid=dvalid),
        jnp.any(overflow),
    )
