"""Pallas TPU kernels for the anti-entropy hot loop.

The jnp ``tree_fold`` (ops/lattice.py) makes log2(R) passes over HBM —
each reduction level materialises a half-size replica batch. The fused
fold here streams every replica's dot matrix through VMEM once and
accumulates the lattice join on-chip: HBM traffic drops from
O(R·E·A·log R) to O(R·E·A), which is the whole game for the
bandwidth-bound ORSWOT merge (SURVEY.md §4.2; BASELINE config 3).

Layout: the dense state keeps ``ctr[R, E, A]`` with a small actor axis
(A ≈ 8–32). Lanes are 128-wide on TPU, so computing in ``[E, A]`` layout
wastes 15/16 of the VPU — the kernel therefore runs transposed
``[A, E]`` blocks (E on the lane axis), with the wrapper paying two XLA
transposes (one pass each) around the single fused pass.

Mosaic constraints shape two choices here:
- tops ride as ``[R, A, 1]`` so every access is a static slice on the
  untiled leading axis. (A ``[A, R]`` layout would need
  ``tops_ref[:, pl.ds(r, 1)]`` per replica, which does not compile:
  dynamic lane-axis slices must be 128-aligned.)
- the replica axis is walked by an inner sequential grid dimension in
  chunks of ``r_chunk``, with the running join living in the output
  block (same revisited block across the chunk steps — the standard
  TPU reduction pattern). VMEM holds one ``[r_chunk, A, tile_e]``
  input block, so R is unbounded.
- within a resident chunk the fold is a statically-unrolled
  pairwise-halving tree (``r_chunk`` is forced to a power of two):
  log2(rc) *batched* joins over ``[h, A, tile_e]`` values instead of
  rc sequential ``[A, tile_e]`` joins. Same bits by associativity/
  commutativity of the join; the long scalar-loop dependency chain —
  which left the VPU idle and capped the first version at ~136 GB/s —
  disappears, so the stream runs near DMA speed.

Only the entry matrices fold in-kernel. The deferred-removal buffers are
tiny ([R, D, A] clocks + [R, D, E] masks with D ≈ 4–8) and their replay
is a pointwise mask over the folded result, so the wrapper handles them
with stock jnp (XLA fuses it into the epilogue): union all parked
removes, replay once against the folded entries, drop caught-up slots.
Replaying once at the end is equivalent to the pairwise join's
replay-at-every-node because replay is idempotent and monotone (it
zeroes exactly the dots the rm clocks cover, which no join can
resurrect past the final replay), and a slot is always replayed before
the catch-up drop — the property suite pins fused == tree fold.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .orswot import (
    OrswotState,
    _apply_parked,
    _compact_deferred,
    _dedupe_deferred,
)


def _umax(a, b):
    # Mosaic cannot legalize vector arith.maxui/minui on this toolchain;
    # compare+select (cmpi ult + arith.select) lowers fine and keeps
    # unsigned semantics for u32 counters.
    return jnp.where(a >= b, a, b)


def _umin(a, b):
    return jnp.where(a <= b, a, b)


def _join_step(acc_top, acc_ctr, b_top, b_ctr):
    """Pairwise entry-matrix join in transposed [..., A, E] layout —
    2D ``[A, E]`` operands or a batch ``[H, A, 1]``/``[H, A, E]`` of
    independent pairs (the tree levels below). Reference merge rule
    (ops/orswot.py ``join``): unseen dots survive, common members keep
    common dots ∪ each side's unseen dots."""
    wa = jnp.where(acc_ctr > b_top, acc_ctr, 0)
    wb = jnp.where(b_ctr > acc_top, b_ctr, 0)
    pa = jnp.any(acc_ctr > 0, axis=-2, keepdims=True)  # [..., 1, TILE_E]
    pb = jnp.any(b_ctr > 0, axis=-2, keepdims=True)
    common = _umax(_umin(acc_ctr, b_ctr), _umax(wa, wb))
    new_ctr = jnp.where(pa & pb, common, jnp.where(pa, wa, wb))
    return _umax(acc_top, b_top), new_ctr


def _join_step_cells(acc_top, acc_ctr, b_top, b_ctr):
    """Cell-granular dot join for the dense Map<K, MVReg> encoding: cell
    (k, y) holds actor y's sole live witness counter at key k (the
    per-(key, actor) uniqueness invariant — ``_decode_wide``), so the
    survival rule collapses per cell: same counter ⇒ same dot (keep);
    else each side's counter survives only if the other side's top never
    saw it — at most one side can win (y's counters are totally ordered
    and each side's top covers its own dots). No cross-lane presence
    term: absent is 0 and 0==0 keeps 0."""
    wa = jnp.where(acc_ctr > b_top, acc_ctr, 0)
    wb = jnp.where(b_ctr > acc_top, b_ctr, 0)
    new_ctr = jnp.where(acc_ctr == b_ctr, acc_ctr, _umax(wa, wb))
    return _umax(acc_top, b_top), new_ctr


def _fold_kernel(tops_ref, ctrs_ref, top_out_ref, ctr_out_ref, *, join_step):
    """Lattice fold over one replica chunk, one E-tile per program.
    tops_ref: [RC, A, 1]; ctrs_ref: [RC, A, TILE_E], RC a power of two.

    The in-chunk reduction is a statically-unrolled pairwise-halving
    tree: each level joins the chunk's top half against its bottom half
    as ONE batched [h, A, TILE_E] op, so the VPU always works on large
    vectors and the dependency chain is log2(RC) deep, not RC. The
    output block is the running accumulator across the (inner,
    sequential) replica-chunk grid axis; tree order equals sequential
    order because the join is associative/commutative/idempotent.

    ``join_step`` picks the merge rule: the orswot element rule
    (``_join_step``) or the cell-granular MVReg rule
    (``_join_step_cells``)."""
    rc = ctrs_ref.shape[0]
    tops = tops_ref[:]
    ctrs = ctrs_ref[:]
    n = rc
    while n > 1:
        h = n // 2
        tops, ctrs = join_step(tops[h:n], ctrs[h:n], tops[:h], ctrs[:h])
        n = h
    chunk_top, chunk_ctr = tops[0], ctrs[0]

    first = pl.program_id(1) == 0

    @pl.when(first)
    def _init():
        top_out_ref[:] = chunk_top
        ctr_out_ref[:] = chunk_ctr

    @pl.when(jnp.logical_not(first))
    def _acc():
        acc_top, acc_ctr = join_step(
            top_out_ref[:], ctr_out_ref[:], chunk_top, chunk_ctr
        )
        top_out_ref[:] = acc_top
        ctr_out_ref[:] = acc_ctr


def _fold_entries_fused(
    top: jax.Array,
    ctr: jax.Array,
    tile_e: int,
    r_chunk: int,
    interpret: bool,
    n_passes: int = 1,
    cellwise: bool = False,
    pre_t: bool = False,
    out_t: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Fused fold of the entry matrices only: ``top[R, A]``,
    ``ctr[R, E, A]`` → ``(top[A], ctr[E, A])``.

    ``n_passes > 1`` makes the grid re-walk the resident replica chunk
    that many times, accumulating into the same output block. Because
    the join is idempotent the result is unchanged, but the DMA and
    compute stream is exactly that of folding ``n_passes * R`` distinct
    replicas — the honest way to time a config-3-scale stream whose full
    dot-state exceeds HBM (bench.py), with one dispatch.

    ``cellwise`` selects the cell-granular MVReg dot rule
    (``_join_step_cells``) instead of the orswot element rule.
    ``pre_t`` accepts ``ctr`` already in the kernel's transposed
    ``[R, A, E]`` layout; ``out_t`` returns ``ctr[A, E]`` untransposed
    (E-minor) — large-E callers keep everything E-minor so no
    lane-padded [.., E, small] temp ever materialises (TPU tiling pads
    a narrow minor dim to 128 lanes; at E ≈ 1M that 32× blow-up is an
    OOM, the r5 config-4 failure)."""
    if pre_t:
        r, a, e = ctr.shape
        ctrs_t = ctr
    else:
        r, e, a = ctr.shape
        ctrs_t = jnp.swapaxes(ctr, -1, -2)  # [R, A, E]
    tile_e = min(tile_e, max(e, 1))
    rc = _pick_r_chunk(r, a, tile_e, r_chunk)  # clamped power of two
    pad_e = (-e) % tile_e
    pad_r = (-r) % rc

    tops3 = top[:, :, None]             # [R, A, 1]
    if pad_e:
        ctrs_t = jnp.pad(ctrs_t, ((0, 0), (0, 0), (0, pad_e)))
    if pad_r:
        # Empty replicas are the join identity (ops/orswot.py ``empty``).
        ctrs_t = jnp.pad(ctrs_t, ((0, pad_r), (0, 0), (0, 0)))
        tops3 = jnp.pad(tops3, ((0, pad_r), (0, 0), (0, 0)))
    e_padded = e + pad_e
    r_steps = (r + pad_r) // rc

    top_t, ctr_t = pl.pallas_call(
        partial(
            _fold_kernel,
            join_step=_join_step_cells if cellwise else _join_step,
        ),
        # Replica chunks on the inner (fastest) axis so the output block
        # accumulates across them before the E-tile advances.
        grid=(e_padded // tile_e, n_passes * r_steps),
        in_specs=[
            pl.BlockSpec(
                (rc, a, 1), lambda i, j: (j % r_steps, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (rc, a, tile_e),
                lambda i, j: (j % r_steps, 0, i),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=[
            pl.BlockSpec((a, 1), lambda i, j: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((a, tile_e), lambda i, j: (0, i), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((a, 1), top.dtype),
            jax.ShapeDtypeStruct((a, e_padded), ctr.dtype),
        ],
        interpret=interpret,
    )(tops3, ctrs_t)

    return top_t[:, 0], (ctr_t[:, :e] if out_t else ctr_t.T[:e])


# VMEM budget for the streamed input block (double-buffered by the
# pipeline). 1 MiB measured fastest on v5e: the in-kernel halving tree
# holds a block copy plus ~block-sized intermediates, so a 2 MiB block
# leaves too little VMEM to overlap DMA with compute (484 GB/s at 1 MiB
# vs 77-436 GB/s at 2 MiB in the r3 sweep), and 4 MiB fails to compile.
_VMEM_BLOCK_BUDGET = 1024 * 1024

# Measured (tile_e, r_chunk) overrides from tools/tile_sweep.py
# --write-table, committed at tools/tile_table.json. None = not loaded
# yet; {} = no table / no entries (pure heuristic). The sweep writes
# entries keyed by actor count so the defaults are evidence, not
# folklore — see _tile_table().
_TILE_TABLE: Optional[dict] = None


def _tile_table() -> dict:
    """The committed autotune table (tools/tile_table.json), loaded
    once per process: ``{"entries": [{"a": .., "tile_e": ..,
    "r_chunk": ..}, ...]}`` as written by ``tools/tile_sweep.py
    --write-table``. Missing or malformed files degrade to the
    heuristic (an empty table) — the committed table is an override,
    never a requirement."""
    global _TILE_TABLE
    if _TILE_TABLE is None:
        import json
        import os

        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))),
            "tools", "tile_table.json",
        )
        try:
            with open(path) as f:
                table = json.load(f)
            table.get("entries", [])  # shape check
            _TILE_TABLE = table if isinstance(
                table.get("entries", None), list) else {}
        except OSError:
            _TILE_TABLE = {}  # no committed table: the heuristic is fine
        except (ValueError, AttributeError):
            # A table that EXISTS but does not parse is operator error,
            # not absence — degrade to the heuristic, but visibly.
            from ..utils.metrics import metrics

            metrics.count("pallas.tile_table.load_failed")
            _TILE_TABLE = {}
    return _TILE_TABLE


def _pick_r_chunk(
    r: int, a: int, tile_e: int, r_chunk: Optional[int],
    family: str = "fold",
) -> int:
    if r_chunk is None:
        # A committed sweep result for this (kernel family, actor
        # count, tile_e) wins over the VMEM-budget heuristic; both
        # still get clamped to the batch and rounded to the halving
        # tree's power of two below. Entries are keyed by ``family``
        # ("fold" when absent — the pre-wire table form) so a sweep of
        # the fused WIRE kernel (ops/wire_kernels.py) can never be
        # silently reused by the fold kernels, or vice versa: the two
        # families stream different shapes through VMEM and a tile
        # optimal for one is folklore for the other. A malformed entry
        # (missing/non-numeric r_chunk) degrades to the heuristic —
        # the table is an override, never a requirement — but counts
        # in the registry so a fat-fingered sweep table is an operator
        # signal, not silence (tests/test_analysis.py pins it).
        for entry in _tile_table().get("entries", ()):
            try:
                if (entry.get("family", "fold") == family
                        and entry.get("a") == a
                        and entry.get("tile_e") == tile_e):
                    r_chunk = int(entry["r_chunk"])
                    break
            except (AttributeError, KeyError, TypeError, ValueError):
                from ..utils.metrics import metrics

                metrics.count("pallas.tile_table.malformed_entry")
                continue
    if r_chunk is None:
        r_chunk = max(8, _VMEM_BLOCK_BUDGET // (max(a, 1) * tile_e * 4))
    r_chunk = min(r_chunk, max(r, 1))
    # The in-kernel halving tree needs a power of two; round down (the
    # replica axis is padded with join-identity empties to a multiple).
    return 1 << (r_chunk.bit_length() - 1)


def _fused_backend() -> bool:
    """THE backend-dispatch decision, in one place: the fused Pallas
    kernels run where they compile to Mosaic ("axon" is a TPU chip
    behind a relay — same compile path); everywhere else "fused" would
    mean the Pallas *interpreter*, orders of magnitude slower than
    XLA:CPU. bench.py labels its reported path with this same predicate
    so cross-round numbers stay comparable."""
    return jax.default_backend() in ("tpu", "axon")


def fold_fused(
    states: OrswotState,
    tile_e: int = 512,
    r_chunk: Optional[int] = None,
    interpret: Optional[bool] = None,
    n_passes: int = 1,
) -> Tuple[OrswotState, jax.Array]:
    """Drop-in replacement for ``ops.orswot.fold`` (same result, same
    overflow flag) with the replica reduction fused into one HBM pass.

    ``r_chunk`` defaults to a VMEM-safe size for the given actor count;
    ``interpret`` defaults to auto: compiled on TPU, interpreter
    elsewhere (CPU tests exercise the same kernel semantics).
    ``n_passes`` re-walks the replica batch that many times (identical
    result by idempotence; used by bench.py to time a stream of
    ``n_passes * R`` replicas in one dispatch).
    """
    if interpret is None:
        interpret = not _fused_backend()
    r, e, a = states.ctr.shape
    tile_e = min(tile_e, max(e, 1))
    r_chunk = _pick_r_chunk(r, a, tile_e, r_chunk)
    return _fold_fused_jit(states, tile_e, r_chunk, interpret, n_passes)


def fold_auto(states: OrswotState, prefer: str = "auto"):
    """Local replica-batch fold with backend-appropriate dispatch: the
    fused Pallas kernel where it compiles to Mosaic (TPU backends), the
    jnp log-tree fold elsewhere (where "fused" would mean the Pallas
    *interpreter* — orders of magnitude slower than XLA:CPU).

    ``prefer``: "auto" (backend pick), "fused", or "tree" — the forced
    modes exist so CPU tests can pin fused-in-situ semantics and so
    callers can opt out. Same ``(state, overflow)`` contract as
    ``ops.orswot.fold``; bit-identical results either way (the property
    suite pins it)."""
    from .orswot import fold as tree_fold

    if prefer not in ("auto", "fused", "tree"):
        raise ValueError(f"prefer must be auto|fused|tree, got {prefer!r}")
    if prefer == "fused" or (prefer == "auto" and _fused_backend()):
        return fold_fused(states)
    return tree_fold(states)


@partial(jax.jit, static_argnames=("tile_e", "r_chunk", "interpret", "n_passes"))
def _fold_fused_jit(
    states: OrswotState,
    tile_e: int,
    r_chunk: int,
    interpret: bool,
    n_passes: int = 1,
) -> Tuple[OrswotState, jax.Array]:
    r, e, a = states.ctr.shape
    top, ctr = _fold_entries_fused(
        states.top,
        states.ctr,
        tile_e=tile_e,
        r_chunk=r_chunk,
        interpret=interpret,
        n_passes=n_passes,
    )

    # Deferred epilogue (stock jnp; see module docstring): union every
    # replica's parked removes, replay once, drop caught-up, compact.
    d = states.dcl.shape[-2]
    dcl = states.dcl.reshape(r * d, a)
    dmask = states.dmask.reshape(r * d, e)
    dvalid = states.dvalid.reshape(r * d)
    dcl, dmask, dvalid = _dedupe_deferred(dcl, dmask, dvalid)
    ctr = _apply_parked(ctr, dcl, dmask, dvalid)
    still_ahead = ~jnp.all(dcl <= top[None, :], axis=-1)
    dvalid = dvalid & still_ahead
    dcl, dmask, dvalid, overflow = _compact_deferred(dcl, dmask, dvalid, d)
    return (
        OrswotState(top=top, ctr=ctr, dcl=dcl, dmask=dmask, dvalid=dvalid),
        jnp.any(overflow),
    )


# ---- fused folds for the composition layer -------------------------------

def _level_chain(level, states):
    """(outermost-first wrapper list, leaf OrswotState) of a nested
    orswot-leaf state."""
    from .nest import NestLevel

    chain, st = [], states
    lv = level
    while isinstance(lv, NestLevel):
        chain.append((lv, st))
        lv, st = lv.core, st[0]
    return chain, st


def fold_fused_level(
    level,
    states,
    tile_e: int = 512,
    r_chunk: Optional[int] = None,
    interpret: Optional[bool] = None,
    element_axis=None,
) -> Tuple[object, jax.Array]:
    """Drop-in fused replacement for ``NestLevel.fold`` on any
    orswot-leaf nested level (map_orswot, map3, deeper compositions):
    the leaf entry slab folds in ONE Pallas HBM pass exactly as
    ``fold_fused`` does, and every deferred-buffer level settles once in
    a jnp epilogue — leaf member-removes first (flatten R·D slots →
    dedupe → replay → drop caught-up → compact), then each keyset level
    innermost-out through the level's own ``settle_outer`` (dedupe →
    replay → compact → scrub). Same once-at-the-end soundness argument
    as the plain fold (module docstring): replay is monotone, idempotent
    zeroing and always precedes the catch-up drop; the per-level
    property gates in tests/test_pallas_fold.py pin fused == tree.

    Returns ``(state, flags[L])`` with ``NestLevel.fold``'s lane order
    (innermost level first)."""
    if interpret is None:
        interpret = not _fused_backend()
    _, leaf = _level_chain(level, states)
    if isinstance(leaf, OrswotState):
        r, e, a = leaf.ctr.shape
    else:  # the Map<K, MVReg> leaf: dense cells are [R, K, A]
        r, e, _ = leaf.child.wact.shape
        a = leaf.top.shape[-1]
    tile_e = min(tile_e, max(e, 1))
    r_chunk = _pick_r_chunk(r, a, tile_e, r_chunk)
    return _fold_fused_level_jit(
        level, states, tile_e, r_chunk, interpret, element_axis
    )


@partial(
    jax.jit,
    static_argnames=("level", "tile_e", "r_chunk", "interpret", "element_axis"),
)
def _fold_fused_level_jit(
    level, states, tile_e, r_chunk, interpret, element_axis=None
):
    chain, leaf = _level_chain(level, states)
    if isinstance(leaf, OrswotState):
        folded_leaf, leaf_of = _fold_fused_jit(leaf, tile_e, r_chunk, interpret)
    else:  # the Map<K, MVReg> leaf (map_map family)
        folded_leaf, leaf_of = _fold_fused_map_jit(
            leaf, tile_e, r_chunk, interpret
        )

    folded = folded_leaf
    flags = [jnp.atleast_1d(leaf_of)]
    for lv, bst in reversed(chain):  # innermost wrapper first
        d = bst[1].shape[-2]
        flat = lambda x: x.reshape((-1,) + x.shape[2:])
        wrapped = lv._make(folded, flat(bst[1]), flat(bst[2]), flat(bst[3]))
        wrapped, of = lv.settle_outer(wrapped, d, element_axis)
        folded = wrapped
        flags.append(of[None])
    return folded, jnp.concatenate(flags)


def _decode_wide(child, a: int):
    """Slot table ``MVRegState [R, K, S…]`` → K-minor dense per-(actor,
    key) arrays (wctr [R, A, K], val1 [R, A, K], clk [R, A, A, K]).

    Sound because a key holds at most one live sibling per actor: a
    later write by the same actor carries a clock ≥ its earlier write's
    (actor knowledge is monotone), so apply-time domination evicts the
    older one, and the merge survival rule kills the smaller counter
    against the witnessing side's top (``_join_step_cells``). The A/B
    suite pins the round-trip on every reachable state.

    K-minor layout throughout: TPU tiling pads the two minor dims to
    (8, 128), so any [.., K, small] temp pays a 16-64× lane-padding
    blow-up — at K = 1M that is an instant OOM (the r5 config-4
    failure). With K on the lane axis padding is ≤2× (the tiny
    slot/actor axis rides the sublane dim), and the decode itself is a
    static unroll over the S ≤ 8 slots instead of a device scatter."""
    r, k, s = child.wact.shape
    act_t = jnp.swapaxes(child.wact, -1, -2)    # [R, S, K]
    wctr_t = jnp.swapaxes(child.wctr, -1, -2)
    val_t = jnp.swapaxes(child.val, -1, -2)
    live_t = jnp.swapaxes(child.valid, -1, -2)
    clk_t = jnp.transpose(child.clk, (0, 2, 3, 1))  # [R, S, A, K]
    ids = jnp.arange(a, dtype=child.wact.dtype)
    wctr = jnp.zeros((r, a, k), child.wctr.dtype)
    val1 = jnp.zeros((r, a, k), jnp.uint32)
    clk = jnp.zeros((r, a, a, k), child.clk.dtype)
    for si in range(s):
        own = (act_t[:, si, None, :] == ids[None, :, None]) & live_t[:, si, None, :]
        wctr = _umax(wctr, jnp.where(own, wctr_t[:, si, None, :], 0))
        # val ids are ≥ 0; shift by one so "absent" is distinguishable.
        val1 = _umax(
            val1,
            jnp.where(own, val_t[:, si, None, :].astype(jnp.uint32) + 1, 0),
        )
        clk = _umax(clk, jnp.where(own[:, :, None, :], clk_t[:, si, None, :, :], 0))
    return wctr, val1, clk


def _wide_to_slots(wctr, val1, clk, s: int):
    """K-minor dense cells (unbatched: wctr [A, K], val1 [A, K],
    clk [A, A, K]) → canonical slot table fitted to S slots, API shapes
    ``[K, S(, A)]``. Every large intermediate stays K-minor; only the
    final (output) transposes leave the lane-friendly layout."""
    from .mvreg import MVRegState

    a, k = wctr.shape
    present = wctr > 0
    # Canonical slot order (ops/map._canon_child): valid first, then by
    # actor (unique per key, so no further tiebreak needed).
    order = jnp.argsort(~present, axis=0, stable=True)  # [A, K] actor ids
    take = lambda x: jnp.take_along_axis(x, order, axis=0)
    valid = take(present)
    acts = jnp.broadcast_to(jnp.arange(a, dtype=jnp.int32)[:, None], (a, k))
    wact_s = jnp.where(valid, take(acts), 0)
    wctr_s = jnp.where(valid, take(wctr), 0)
    val_s = jnp.where(valid, take(val1).astype(jnp.int32) - 1, 0)
    clk_s = jnp.where(
        valid[:, None, :],
        jnp.take_along_axis(clk, order[:, None, :], axis=0),
        0,
    )

    # Back to the slot capacity: truncate (A > S) or zero-pad (A < S) —
    # canonical form keeps dead slots zeroed either way.
    def fit(x):
        if a >= s:
            return x[:s]
        return jnp.pad(x, [(0, s - a)] + [(0, 0)] * (x.ndim - 1))

    return MVRegState(
        wact=fit(wact_s).T,
        wctr=fit(wctr_s).T,
        clk=jnp.transpose(fit(clk_s), (2, 0, 1)),
        val=fit(val_s).T,
        valid=fit(valid).T,
    )


def fold_fused_map(
    states,
    tile_e: int = 512,
    r_chunk: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> Tuple[object, jax.Array]:
    """Fused fold for ``Map<K, MVReg>`` (``ops.map.MapState``) — the
    config-4 hot loop in one streamed HBM pass.

    The slot tables convert to a dense per-(key, actor) witness-counter
    slab (``_decode_wide``), whose replica fold is the cell-granular
    dot rule — the Pallas kernel with ``_join_step_cells``. Payload
    (val, clk) follows the surviving counter by a winner-select
    reduction in the jnp epilogue, then the parked keyset-removes replay
    once on the A-wide decoded table BEFORE the sibling-capacity check
    (the tree join's transient-overflow semantics). Returns
    ``(state, overflow[2])`` like ``ops.map.fold``."""
    if interpret is None:
        interpret = not _fused_backend()
    r, k, s = states.child.wact.shape
    a = states.top.shape[-1]
    tile_e = min(tile_e, max(k, 1))
    r_chunk = _pick_r_chunk(r, a, tile_e, r_chunk)
    return _fold_fused_map_jit(states, tile_e, r_chunk, interpret)


@partial(jax.jit, static_argnames=("tile_e", "r_chunk", "interpret"))
def _fold_fused_map_jit(states, tile_e, r_chunk, interpret):
    from . import map as map_ops

    r, k, s = states.child.wact.shape
    a = states.top.shape[-1]
    wctr, val1, clk = _decode_wide(states.child, a)  # [R, A, K] K-minor

    top, folded_w = _fold_entries_fused(
        states.top, wctr, tile_e, r_chunk, interpret, cellwise=True,
        pre_t=True, out_t=True,
    )  # top [A], folded_w [A, K]

    # Winner-select payload: the surviving counter's replica supplies
    # val and clk (ties ⇒ same dot ⇒ same payload, max is safe).
    match = (wctr == folded_w[None]) & (folded_w[None] > 0)
    val1 = jnp.max(jnp.where(match, val1, 0), axis=0)              # [A, K]
    clk = jnp.max(jnp.where(match[:, :, None, :], clk, 0), axis=0)  # [A, A, K]

    # Parked keyset-removes: union → dedupe → replay directly on the
    # K-minor cells (cell (y, k) dies iff some parked slot masks key k
    # with a clock covering its dot) → drop caught-up → compact, then
    # the sibling-capacity check — the tree join's transient-overflow
    # semantics (replay precedes the capacity check).
    d = states.dcl.shape[-2]
    dcl = states.dcl.reshape(r * d, a)
    dkeys = states.dkeys.reshape(r * d, k)
    dvalid = states.dvalid.reshape(r * d)
    dcl, dkeys, dvalid = _dedupe_deferred(dcl, dkeys, dvalid)

    def cover(maxcov, slot):
        cl, keys, dv = slot
        return _umax(maxcov, jnp.where(dv & keys[None, :], cl[:, None], 0)), None

    maxcov, _ = lax.scan(cover, jnp.zeros_like(folded_w), (dcl, dkeys, dvalid))
    kill = (folded_w > 0) & (folded_w <= maxcov)
    folded_w = jnp.where(kill, 0, folded_w)
    val1 = jnp.where(kill, 0, val1)
    clk = jnp.where(kill[:, None, :], 0, clk)

    still_ahead = ~jnp.all(dcl <= top[None, :], axis=-1)
    dvalid = dvalid & still_ahead
    dcl, dkeys, dvalid, d_of = _compact_deferred(dcl, dkeys, dvalid, d)

    c_of = jnp.any(jnp.sum(folded_w > 0, axis=0) > s)
    child = _wide_to_slots(folded_w, val1, clk, s)
    return (
        map_ops.MapState(
            top=top, child=child, dcl=dcl, dkeys=dkeys, dvalid=dvalid
        ),
        jnp.stack([c_of, jnp.any(d_of)]),
    )


def fold_auto_level(level, states, prefer: str = "auto", element_axis=None):
    """Backend-appropriate fold dispatch for the nested family — the
    ``fold_auto`` of composed slabs: the fused Pallas path where it
    compiles to Mosaic (TPU backends), the jnp log-tree fold elsewhere.
    Same ``(state, flags)`` contract as ``NestLevel.fold``."""
    if prefer not in ("auto", "fused", "tree"):
        raise ValueError(f"prefer must be auto|fused|tree, got {prefer!r}")
    if prefer == "fused" or (prefer == "auto" and _fused_backend()):
        return fold_fused_level(level, states, element_axis=element_axis)
    return level.fold(states, element_axis)


def fold_auto_map(states, prefer: str = "auto"):
    """Backend-appropriate fold dispatch for ``Map<K, MVReg>`` replica
    batches; same contract as ``ops.map.fold``."""
    from .map import _tree_fold

    if prefer not in ("auto", "fused", "tree"):
        raise ValueError(f"prefer must be auto|fused|tree, got {prefer!r}")
    if prefer == "fused" or (prefer == "auto" and _fused_backend()):
        return fold_fused_map(states)
    return _tree_fold(states)
