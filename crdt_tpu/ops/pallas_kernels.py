"""Pallas TPU kernels for the anti-entropy hot loop.

The jnp ``tree_fold`` (ops/lattice.py) makes log2(R) passes over HBM —
each reduction level materialises a half-size replica batch. The fused
fold here streams every replica's dot matrix through VMEM once and
accumulates the lattice join on-chip: HBM traffic drops from
O(R·E·A·log R) to O(R·E·A), which is the whole game for the
bandwidth-bound ORSWOT merge (SURVEY.md §4.2; BASELINE config 3).

Layout: the dense state keeps ``ctr[R, E, A]`` with a small actor axis
(A ≈ 8–32). Lanes are 128-wide on TPU, so computing in ``[E, A]`` layout
wastes 15/16 of the VPU — the kernel therefore runs transposed
``[A, E]`` blocks (E on the lane axis), with the wrapper paying two XLA
transposes (one pass each) around the single fused pass.

Only the entry matrices fold in-kernel. The deferred-removal buffers are
tiny ([R, D, A] clocks + [R, D, E] masks with D ≈ 4–8) and their replay
is a pointwise mask over the folded result, so the wrapper handles them
with stock jnp (XLA fuses it into the epilogue): union all parked
removes, replay once against the folded entries, drop caught-up slots.
Replaying once at the end is equivalent to the pairwise join's
replay-at-every-node because replay is idempotent and monotone (it
zeroes exactly the dots the rm clocks cover, which no join can
resurrect past the final replay), and a slot is always replayed before
the catch-up drop — the property suite pins fused == tree fold.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .orswot import (
    OrswotState,
    _apply_parked,
    _compact_deferred,
    _dedupe_deferred,
)


def _fold_kernel(tops_ref, ctrs_ref, top_out_ref, ctr_out_ref):
    """Sequential lattice fold over the replica axis, one E-tile per
    program. tops_ref: [A, R]; ctrs_ref: [R, A, TILE_E] (transposed
    layout, E on lanes). Sequential accumulation equals any reduction
    tree — the join is associative/commutative/idempotent."""
    r_total = ctrs_ref.shape[0]

    acc_top = tops_ref[:, pl.ds(0, 1)]  # [A, 1]
    acc_ctr = ctrs_ref[0]               # [A, TILE_E]

    def body(r, carry):
        acc_top, acc_ctr = carry
        b_top = tops_ref[:, pl.ds(r, 1)]
        b_ctr = ctrs_ref[r]
        # Reference merge rule (ops/orswot.py join): unseen dots survive,
        # common members keep common dots ∪ each side's unseen dots.
        wa = jnp.where(acc_ctr > b_top, acc_ctr, 0)
        wb = jnp.where(b_ctr > acc_top, b_ctr, 0)
        pa = jnp.any(acc_ctr > 0, axis=0, keepdims=True)  # [1, TILE_E]
        pb = jnp.any(b_ctr > 0, axis=0, keepdims=True)
        common = jnp.maximum(jnp.minimum(acc_ctr, b_ctr), jnp.maximum(wa, wb))
        new_ctr = jnp.where(pa & pb, common, jnp.where(pa, wa, wb))
        return jnp.maximum(acc_top, b_top), new_ctr

    acc_top, acc_ctr = jax.lax.fori_loop(1, r_total, body, (acc_top, acc_ctr))
    top_out_ref[:] = acc_top
    ctr_out_ref[:] = acc_ctr


@partial(jax.jit, static_argnames=("tile_e", "interpret"))
def fold_fused(
    states: OrswotState, tile_e: int = 512, interpret: Optional[bool] = None
) -> Tuple[OrswotState, jax.Array]:
    """Drop-in replacement for ``ops.orswot.fold`` (same result, same
    overflow flag) with the replica reduction fused into one HBM pass.

    ``interpret`` defaults to auto: compiled on TPU, interpreter
    elsewhere (CPU tests exercise the same kernel semantics).
    """
    if interpret is None:
        # "axon" is a TPU chip behind a relay (same Mosaic compile path).
        interpret = jax.default_backend() not in ("tpu", "axon")

    r, e, a = states.ctr.shape
    tile_e = min(tile_e, max(e, 1))
    pad_e = (-e) % tile_e

    ctrs_t = jnp.swapaxes(states.ctr, -1, -2)  # [R, A, E]
    if pad_e:
        ctrs_t = jnp.pad(ctrs_t, ((0, 0), (0, 0), (0, pad_e)))
    e_padded = e + pad_e
    tops_t = states.top.T  # [A, R]

    top_t, ctr_t = pl.pallas_call(
        _fold_kernel,
        grid=(e_padded // tile_e,),
        in_specs=[
            pl.BlockSpec((a, r), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(
                (r, a, tile_e), lambda i: (0, 0, i), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=[
            pl.BlockSpec((a, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((a, tile_e), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((a, 1), states.top.dtype),
            jax.ShapeDtypeStruct((a, e_padded), states.ctr.dtype),
        ],
        interpret=interpret,
    )(tops_t, ctrs_t)

    top = top_t[:, 0]
    ctr = ctr_t.T[:e]

    # Deferred epilogue (stock jnp; see module docstring): union every
    # replica's parked removes, replay once, drop caught-up, compact.
    d = states.dcl.shape[-2]
    dcl = states.dcl.reshape(r * d, a)
    dmask = states.dmask.reshape(r * d, e)
    dvalid = states.dvalid.reshape(r * d)
    dcl, dmask, dvalid = _dedupe_deferred(dcl, dmask, dvalid)
    ctr = _apply_parked(ctr, dcl, dmask, dvalid)
    still_ahead = ~jnp.all(dcl <= top[None, :], axis=-1)
    dvalid = dvalid & still_ahead
    dcl, dmask, dvalid, overflow = _compact_deferred(dcl, dmask, dvalid, d)
    return (
        OrswotState(top=top, ctr=ctr, dcl=dcl, dmask=dmask, dvalid=dvalid),
        jnp.any(overflow),
    )
