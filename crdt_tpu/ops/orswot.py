"""Dense batched ORSWOT kernels — THE hot loop (SURVEY.md §4.2).

State layout (dense mode, SURVEY.md §7.1): for an element universe of E
interned members and A interned actors,

- ``top［..., A]``      — the replica's top clock,
- ``ctr［..., E, A]``   — per-element birth clocks (0 = no dot; membership
  mask is ``any(ctr > 0, -1)``),
- ``dcl［..., D, A]`` / ``dmask［..., D, E]`` / ``dvalid［..., D]`` — the
  deferred-removal buffer as masked epochs (SURVEY.md §7.3): D parked rm
  clocks + member masks, re-evaluated after every state change.

``join`` implements exactly the reference merge rule (src/orswot.rs
``CvRDT::merge``): an entry survives iff its birth clock has dots unseen
by the other side's top clock, or it is present on both sides (then the
birth clocks join as common-dots ∪ each side's unseen dots). Everything is
element-wise max/min + boolean masks → pure MXU/VPU work, no gather
dependence on data, so XLA tiles it and vmap/pjit batch it freely.

The join is a true lattice join (bit-identical to the oracle under
tests/test_models_orswot.py), so N-replica full-mesh anti-entropy folds
into a log2(N) reduction tree (``fold``) — the device analog of
``lax.all_reduce`` with the lattice-join monoid.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

DTYPE = jnp.uint32


class OrswotState(NamedTuple):
    """A (possibly batched) dense ORSWOT replica state (pytree)."""

    top: jax.Array    # [..., A]
    ctr: jax.Array    # [..., E, A]
    dcl: jax.Array    # [..., D, A]
    dmask: jax.Array  # [..., D, E]
    dvalid: jax.Array # [..., D]


def empty(n_elems: int, n_actors: int, deferred_cap: int = 8, batch: tuple = ()) -> OrswotState:
    """The join identity: no dots, no members, no parked removes."""
    return OrswotState(
        top=jnp.zeros((*batch, n_actors), DTYPE),
        ctr=jnp.zeros((*batch, n_elems, n_actors), DTYPE),
        dcl=jnp.zeros((*batch, deferred_cap, n_actors), DTYPE),
        dmask=jnp.zeros((*batch, deferred_cap, n_elems), bool),
        dvalid=jnp.zeros((*batch, deferred_cap), bool),
    )


def _pad_tail(x, *tail, lead: int, fill=0):
    """Tail-pad trailing axes of ``x`` with ``fill`` — the one pad
    helper every kind's ``widen`` kernel shares (sparse repacks pass
    dead sentinels like -1; dense absence is the 0/False default)."""
    spec = ((0, 0),) * lead + tail
    return jnp.pad(x, spec, constant_values=fill)


def widen(
    state: OrswotState,
    n_elems: int = 0,
    n_actors: int = 0,
    deferred_cap: int = 0,
) -> OrswotState:
    """Re-encode a (possibly batched) dense state into a wider layout —
    the elastic capacity migration (elastic.py). Dense absence is
    all-zero, so growing an axis is pure zero/False padding at the tail:
    interned ids keep their lanes, and the result is bit-identical to a
    from-scratch state of the wider shape holding the same dots. A
    capacity of 0 keeps the current width; shrinking is refused (lanes
    may hold live dots)."""
    e, a = state.ctr.shape[-2:]
    d = state.dvalid.shape[-1]
    ne, na, nd = n_elems or e, n_actors or a, deferred_cap or d
    if ne < e or na < a or nd < d:
        raise ValueError(
            f"widen cannot shrink: ({e}, {a}, {d}) -> ({ne}, {na}, {nd})"
        )
    lead = state.top.ndim - 1
    pad = partial(_pad_tail, lead=lead)
    return OrswotState(
        top=pad(state.top, (0, na - a)),
        ctr=pad(state.ctr, (0, ne - e), (0, na - a)),
        dcl=pad(state.dcl, (0, nd - d), (0, na - a)),
        dmask=pad(state.dmask, (0, nd - d), (0, ne - e)),
        dvalid=pad(state.dvalid, (0, nd - d)),
    )


def narrow(
    state: OrswotState,
    n_elems: int = 0,
    n_actors: int = 0,
    deferred_cap: int = 0,
) -> OrswotState:
    """The inverse of :func:`widen` — re-encode into a NARROWER layout
    by slicing tail lanes off (elastic.shrink drives this after the
    hysteresis policy clears it). Precondition, checked here: every
    dropped lane must be dead (zero dots / False masks / invalid
    slots) — a live lane REFUSES with ValueError rather than silently
    forgetting state. Run ``compact`` first so retired parked slots and
    stale payload do not pin lanes. 0 keeps a width; growing is
    ``widen``'s job."""
    e, a = state.ctr.shape[-2:]
    d = state.dvalid.shape[-1]
    ne, na, nd = n_elems or e, n_actors or a, deferred_cap or d
    if ne > e or na > a or nd > d:
        raise ValueError(
            f"narrow cannot grow: ({e}, {a}, {d}) -> ({ne}, {na}, {nd})"
        )
    live = []
    if ne < e and bool(
        jnp.any(state.ctr[..., ne:, :]) | jnp.any(state.dmask[..., :, ne:])
    ):
        live.append(f"n_elems {e}->{ne}")
    if na < a and bool(
        jnp.any(state.top[..., na:]) | jnp.any(state.ctr[..., :, na:])
        | jnp.any(state.dcl[..., :, na:])
    ):
        live.append(f"n_actors {a}->{na}")
    if nd < d and bool(jnp.any(state.dvalid[..., nd:])):
        live.append(f"deferred_cap {d}->{nd}")
    if live:
        raise ValueError(
            f"narrow refused — dropped lanes hold live state: {live} "
            f"(compact first, or shrink less)"
        )
    return OrswotState(
        top=state.top[..., :na],
        ctr=state.ctr[..., :ne, :na],
        dcl=state.dcl[..., :nd, :na],
        dmask=state.dmask[..., :nd, :ne],
        dvalid=state.dvalid[..., :nd],
    )


def _without(ctr: jax.Array, top: jax.Array) -> jax.Array:
    """Per-element clocks shorn of dots the top clock has seen."""
    return jnp.where(ctr > top[..., None, :], ctr, jnp.zeros_like(ctr))


def _present(ctr: jax.Array) -> jax.Array:
    return jnp.any(ctr > 0, axis=-1)


def _apply_parked(
    ctr: jax.Array,
    dcl: jax.Array,
    dmask: jax.Array,
    dvalid: jax.Array,
    slot_chunk: int = 32,
) -> jax.Array:
    """Replay every parked remove against the entry matrix (the oracle's
    ``_apply_rm`` partial application: zero dots the rm clock dominates,
    for masked members only).

    Removal is monotone zeroing, so the per-slot condition against the
    ORIGINAL ctr decides the final value exactly (a dot another slot
    already zeroed would re-zero to the same 0) — slots can therefore
    replay as an any-reduction over vectorized chunks instead of one
    sequential pass per slot. That matters for ``fold_fused``, whose
    epilogue flattens R·D slots: the scan is O(S) passes over the entry
    matrix, the chunked form O(S / slot_chunk)."""
    d_axis = dcl.ndim - 2
    s = dcl.shape[d_axis]
    chunk = min(slot_chunk, max(s, 1))
    pad = (-s) % chunk
    dcl = jnp.moveaxis(dcl, d_axis, 0)
    dmask = jnp.moveaxis(dmask, d_axis, 0)
    dvalid = jnp.moveaxis(dvalid, -1, 0)
    if pad:
        # Invalid padding slots dominate nothing.
        zpad = lambda x: jnp.pad(x, ((0, pad),) + ((0, 0),) * (x.ndim - 1))
        dcl, dmask, dvalid = zpad(dcl), zpad(dmask), zpad(dvalid)

    def step(ctr, slots):
        cl, mask, valid = slots  # [C, ..., A], [C, ..., E], [C, ...]
        dominated = (
            mask[..., :, None]
            & (ctr[None] <= cl[..., None, :])
            & valid[..., None, None]
        )
        return jnp.where(jnp.any(dominated, axis=0), 0, ctr), None

    reshape = lambda x: x.reshape((-1, chunk) + x.shape[1:])
    ctr, _ = lax.scan(step, ctr, (reshape(dcl), reshape(dmask), reshape(dvalid)))
    return ctr


def _dedupe_deferred(dcl, dmask, dvalid):
    """Union member masks of slots holding equal rm clocks (the oracle's
    ``defer_remove`` dict-union), keeping the first slot of each group.

    The group-OR of member masks (``merged[j, e] = ∃i in group j:
    dmask[i, e]``) is a 0/1 matmul, so it rides the MXU: bf16 operands
    and an f32 accumulator are both exact for 0/1 values at any
    realistic slot count, and the result only needs a >0 test. The
    naive ``any(sel & dmask)`` broadcast is O(N²·E) VPU boolean work —
    at the fused fold's flattened R·D slot axis it dominated the whole
    fold (1.1e12 ops ≈ 1.2 s at R = 2048, E = 16k; the r5 npasses_ab
    check caught it)."""
    d = dcl.shape[-2]
    idx = jnp.arange(d)
    eq = (
        dvalid[..., :, None]
        & dvalid[..., None, :]
        & jnp.all(dcl[..., :, None, :] == dcl[..., None, :, :], axis=-1)
    )  # [..., D, D]
    rep = jnp.argmax(eq, axis=-2)  # first valid slot with an equal clock
    keep = dvalid & (rep == idx)
    sel = (rep[..., :, None] == idx[..., None, :]) & dvalid[..., :, None]
    merged = (
        jnp.einsum(
            "...ij,...ie->...je",
            sel.astype(jnp.bfloat16),
            dmask.astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        )
        > 0.5
    )
    return dcl, merged & keep[..., None], keep


def _compact_deferred(dcl, dmask, dvalid, cap: int):
    """Stable-sort valid slots to the front and truncate to capacity.
    Returns the compacted buffer plus an overflow flag."""
    order = jnp.argsort(~dvalid, axis=-1, stable=True)
    dcl = jnp.take_along_axis(dcl, order[..., None], axis=-2)
    dmask = jnp.take_along_axis(dmask, order[..., None], axis=-2)
    dvalid = jnp.take_along_axis(dvalid, order, axis=-1)
    overflow = jnp.sum(dvalid, axis=-1) > cap
    dcl, dmask, dvalid = dcl[..., :cap, :], dmask[..., :cap, :], dvalid[..., :cap]
    # Canonical form: invalid slots carry no stale payload, so raw arrays
    # of converged replicas compare equal and later unions cannot leak.
    dcl = jnp.where(dvalid[..., None], dcl, jnp.zeros_like(dcl))
    dmask = dmask & dvalid[..., None]
    return dcl, dmask, dvalid, overflow


@jax.jit
def reset_remove(state: OrswotState, clock: jax.Array) -> OrswotState:
    """ResetRemove — the ``Causal`` trait's ``forget``: erase all causal
    history ``clock`` dominates, lane-wise. Reference: src/orswot.rs
    ResetRemove impl (SURVEY §3.2).

    Dense translation of the oracle (pure/orswot.py ``reset_remove``):
    entry clocks zero every lane the given clock covers (a member whose
    lanes all zero is gone — dense encodes absent as all-zero); each
    parked rm clock resets the same way, a slot dies when its clock
    empties, and surviving equal clocks re-union (the oracle re-defers
    into a dict); the top clock forgets covered lanes
    (ops/vclock.reset_remove). Capacity cannot overflow — slots only
    die."""
    from . import vclock

    clock = jnp.asarray(clock, state.ctr.dtype)
    ctr = vclock.reset_remove(state.ctr, clock[..., None, :])
    dcl = vclock.reset_remove(state.dcl, clock[..., None, :])
    dvalid = state.dvalid & jnp.any(dcl > 0, axis=-1)
    dcl = jnp.where(dvalid[..., None], dcl, 0)
    dmask = state.dmask & dvalid[..., None]
    dcl, dmask, dvalid = _dedupe_deferred(dcl, dmask, dvalid)
    dcl, dmask, dvalid, _ = _compact_deferred(
        dcl, dmask, dvalid, state.dvalid.shape[-1]
    )
    top = vclock.reset_remove(state.top, clock)
    return OrswotState(top=top, ctr=ctr, dcl=dcl, dmask=dmask, dvalid=dvalid)


@jax.jit
def join(a: OrswotState, b: OrswotState):
    """Pairwise lattice join — the reference's ``Orswot::merge`` as pure
    element-wise arithmetic. Reference: src/orswot.rs CvRDT::merge.

    Returns ``(state, overflow)``: ``overflow`` is True where the combined
    deferred buffers exceeded capacity (parked removes would be lost) —
    callers must surface it (models raise ``DeferredOverflow``)."""
    wa = _without(a.ctr, b.top)  # our dots they never saw
    wb = _without(b.ctr, a.top)  # their dots we never saw
    pa, pb = _present(a.ctr), _present(b.ctr)
    common = jnp.maximum(jnp.minimum(a.ctr, b.ctr), jnp.maximum(wa, wb))
    ctr = jnp.where(
        (pa & pb)[..., None],
        common,
        jnp.where((pa & ~pb)[..., None], wa, jnp.where((pb & ~pa)[..., None], wb, 0)),
    ).astype(a.ctr.dtype)
    top = jnp.maximum(a.top, b.top)

    # Deferred buffers: union (dict-union on equal clocks), replay every
    # parked remove against the joined entries, keep only still-ahead ones.
    dcl = jnp.concatenate([a.dcl, b.dcl], axis=-2)
    dmask = jnp.concatenate([a.dmask, b.dmask], axis=-2)
    dvalid = jnp.concatenate([a.dvalid, b.dvalid], axis=-1)
    dcl, dmask, dvalid = _dedupe_deferred(dcl, dmask, dvalid)
    ctr = _apply_parked(ctr, dcl, dmask, dvalid)
    still_ahead = ~jnp.all(dcl <= top[..., None, :], axis=-1)
    dvalid = dvalid & still_ahead
    cap = a.dcl.shape[-2]
    dcl, dmask, dvalid, overflow = _compact_deferred(dcl, dmask, dvalid, cap)
    return (
        OrswotState(top=top, ctr=ctr, dcl=dcl, dmask=dmask, dvalid=dvalid),
        overflow,
    )


def changed_members(a: OrswotState, b: OrswotState) -> jax.Array:
    """Telemetry counter emitted next to the merge masks: members whose
    birth clocks differ between two states (uint32, summed over every
    leading batch lane). The dense kind's ``slots_changed`` — birth
    clocks are the membership-deciding plane, and ``ctr`` is the
    element-sharded plane, so element-shard psums of this count never
    double count replicated buffers (telemetry.py)."""
    return jnp.sum(jnp.any(a.ctr != b.ctr, axis=-1), dtype=jnp.uint32)


def fold(states: OrswotState):
    """Join a whole replica batch (leading axis) in a log2 reduction tree.
    Sound because ``join`` is associative/commutative/idempotent — the
    N-replica full mesh collapses to one reduction (the north star).

    Returns ``(state, overflow)`` like ``join``."""
    from .lattice import tree_fold

    identity = empty(states.ctr.shape[-2], states.ctr.shape[-1], states.dcl.shape[-2])
    return tree_fold(states, identity, join)


@jax.jit
def apply_add(state: OrswotState, actor: jax.Array, counter: jax.Array, member_mask: jax.Array) -> OrswotState:
    """CmRDT add-op application (reference: src/orswot.rs apply, Op::Add):
    drop already-seen dots, else record the birth dot on every member in
    ``member_mask`` and advance the top; then replay parked removes (the
    oracle's ``apply_deferred``)."""
    counter = counter.astype(state.top.dtype)
    seen = state.top[..., actor] >= counter
    stamp = jnp.where(member_mask, counter, 0).astype(state.ctr.dtype)
    new_ctr = state.ctr.at[..., actor].max(stamp)
    ctr = jnp.where(seen[..., None, None], state.ctr, new_ctr)
    top = jnp.where(seen[..., None], state.top, state.top.at[..., actor].max(counter))
    ctr = _apply_parked(ctr, state.dcl, state.dmask, state.dvalid)
    still_ahead = ~jnp.all(state.dcl <= top[..., None, :], axis=-1)
    return state._replace(top=top, ctr=ctr, dvalid=state.dvalid & still_ahead)


def _park_remove(dcl, dmask, dvalid, rm_clock, payload_mask, ahead):
    """Park an ahead remove: union its payload onto an equal-clock slot,
    else claim the first free slot (the oracle's ``_defer_remove``
    dict-union). Shared by every deferred buffer (orswot members, map
    keysets, nested outer keysets). Returns ``(dcl, dmask, dvalid,
    overflow)``; overflow is True where an ahead remove found neither an
    equal-clock slot nor a free one."""
    same = dvalid & jnp.all(dcl == rm_clock[..., None, :], axis=-1)
    has_same = jnp.any(same, axis=-1)
    free = ~dvalid
    has_free = jnp.any(free, axis=-1)
    slot = jnp.where(
        has_same, jnp.argmax(same, axis=-1), jnp.argmax(free, axis=-1)
    )
    park = ahead & (has_same | has_free)
    overflow = ahead & ~has_same & ~has_free

    d = dvalid.shape[-1]
    onehot = jax.nn.one_hot(slot, d, dtype=bool) & park[..., None]
    new_dcl = jnp.where(onehot[..., None], rm_clock[..., None, :], dcl)
    # Union only live payload (a free slot may hold a stale mask).
    live = dmask & dvalid[..., None]
    new_dmask = jnp.where(
        onehot[..., None], payload_mask[..., None, :] | live, dmask
    )
    return new_dcl, new_dmask, dvalid | onehot, overflow


@jax.jit
def apply_rm(state: OrswotState, rm_clock: jax.Array, member_mask: jax.Array):
    """CmRDT rm-op application (reference: src/orswot.rs apply_rm): always
    apply the covered part now; if the rm clock is ahead of the top, park
    it in the deferred buffer (union on an equal-clock slot, else claim the
    first free slot). Returns ``(state, overflow)``; overflow is True where
    an ahead remove could not be parked (buffer full) — callers must
    surface it."""
    dominated = member_mask[..., :, None] & (state.ctr <= rm_clock[..., None, :])
    ctr = jnp.where(dominated, jnp.zeros_like(state.ctr), state.ctr)

    ahead = ~jnp.all(rm_clock <= state.top, axis=-1)
    dcl, dmask, dvalid, overflow = _park_remove(
        state.dcl, state.dmask, state.dvalid, rm_clock, member_mask, ahead
    )
    return (
        OrswotState(top=state.top, ctr=ctr, dcl=dcl, dmask=dmask, dvalid=dvalid),
        overflow,
    )


# ---- static-analysis registration (crdt_tpu.analysis) --------------------

def _law_apply(s: OrswotState, op):
    if op[0] == "add":
        _, actor, ctr, mask = op
        return apply_add(s, actor, jnp.uint32(ctr), mask)
    _, clock, mask = op
    return apply_rm(s, clock, mask)[0]


def _law_states():
    """Adds, covered removes, and parked (ahead) removes over a 2×2
    universe with deferred headroom (D = 4)."""
    m0 = jnp.array([True, False])
    m1 = jnp.array([False, True])
    mb = jnp.array([True, True])
    cl = lambda x, y: jnp.array([x, y], DTYPE)
    e = empty(2, 2, 4)
    a1 = apply_add(e, 0, jnp.uint32(1), m0)
    a2 = apply_add(a1, 0, jnp.uint32(2), m1)
    b1 = apply_add(e, 1, jnp.uint32(1), mb)
    ab, _ = join(a2, b1)
    r1, _ = apply_rm(ab, cl(2, 1), m0)   # covered: kills elem 0 now
    r2, _ = apply_rm(a1, cl(0, 2), m1)   # ahead: parks in the buffer
    r3, _ = apply_rm(e, cl(1, 1), mb)    # ahead on empty: parks
    return [e, a1, a2, b1, r1, r2, r3]


def _law_states_big():
    """Property-sampled larger domain: replicas applying ordered
    subsets of one shared 10-op history (per-actor counter order is
    causal delivery; rm clocks observed at the mint site, occasionally
    nudged ahead so parking happens)."""
    import numpy as np

    rng = np.random.default_rng(20260803)
    e_n, a_n, d_n = 4, 3, 6
    site = empty(e_n, a_n, d_n)
    history = []
    next_ctr = [0] * a_n
    for _ in range(10):
        actor = int(rng.integers(a_n))
        if rng.random() < 0.7 or not history:
            next_ctr[actor] += 1
            mask = jnp.asarray(rng.random(e_n) < 0.5)
            op = ("add", actor, next_ctr[actor], mask)
        else:
            top = np.asarray(site.top).astype(np.uint64)
            if rng.random() < 0.3:
                top[actor] += 1  # ahead -> parks
            mask = jnp.asarray(rng.random(e_n) < 0.5)
            op = ("rm", jnp.asarray(top, DTYPE), mask)
        site = _law_apply(site, op)
        history.append(op)
    states = [empty(e_n, a_n, d_n)]
    for _ in range(6):
        take = rng.random(len(history)) < 0.6
        s = empty(e_n, a_n, d_n)
        for keep, op in zip(take, history):
            if keep:
                s = _law_apply(s, op)
        states.append(s)
    return states


def _law_deltas():
    """Schedule-generator hook (analysis/schedules.py): four δ-states
    minted by three origins — two causally ordered ops at origin 0 (an
    add, then a remove observed from it), a concurrent both-element add
    at origin 1, and an ahead remove parked at origin 2. Exercises
    every delivery hazard the bounded checker enumerates: the parked
    remove must survive duplication and arbitrary reorder against the
    adds it races."""
    states = _law_states()
    e, a1, _, b1, _, r2, r3 = states
    return [(0, a1), (0, r2), (1, b1), (2, r3)]


def _law_canon(s: OrswotState) -> OrswotState:
    """Deferred slot order depends on join operand order — compare
    content-ordered (clocks are unique among valid slots post-dedupe)."""
    from ..analysis.canon import canon_epochs

    dcl, dmask, dvalid = canon_epochs(s.dcl, s.dmask, s.dvalid)
    return s._replace(dcl=dcl, dmask=dmask, dvalid=dvalid)


@jax.jit
def compact(state: OrswotState, frontier: jax.Array):
    """Causal-stability compaction (reclaim/): retire parked removes
    the stable frontier has caught up to (every replica's top covers
    them — they can never kill another dot anywhere) and scrub the
    stale dead-slot payload ``apply_add`` leaves behind, repacking
    valid slots to the front. Dense entry lanes are fixed-shape, so the
    byte win here is the parked buffer; observable reads (the present
    mask) are untouched — the compaction-invariance law pins it.
    Returns ``(state, freed_slots, freed_bytes)``."""
    from ..reclaim.compaction import retire_epochs

    dcl, dmask, dvalid, freed, freed_b = retire_epochs(
        state.dcl, state.dmask, state.dvalid, state.top, frontier
    )
    return (
        state._replace(dcl=dcl, dmask=dmask, dvalid=dvalid), freed, freed_b
    )


def _observe(s: OrswotState) -> jax.Array:
    """The observable read: the membership mask (pure/orswot.py
    ``read().val`` as the dense present mask)."""
    return _present(s.ctr)


def _decomp_split(s: OrswotState):
    """Join-irreducible decomposition granularity (delta_opt/): one δ
    lane per element birth-clock row; the top clock and the bounded
    parked-remove buffer are the residual (a clock-compressed context
    cannot be split finer — see delta_opt.decompose)."""
    return (s.ctr,), (s.top, s.dcl, s.dmask, s.dvalid)


def _decomp_unsplit(rows, res) -> OrswotState:
    (ctr,) = rows
    top, dcl, dmask, dvalid = res
    return OrswotState(top=top, ctr=ctr, dcl=dcl, dmask=dmask, dvalid=dvalid)


from ..analysis.registry import (  # noqa: E402
    register_compactor,
    register_decomposition,
    register_merge,
)

register_merge(
    "orswot", module=__name__, join=join, states=_law_states,
    canon=_law_canon, big_states=_law_states_big, deltas=_law_deltas,
)
register_compactor(
    "orswot", module=__name__, compact=compact, observe=_observe,
    top_of=lambda s: s.top,
)
register_decomposition(
    "orswot", module=__name__, split=_decomp_split, unsplit=_decomp_unsplit,
)
