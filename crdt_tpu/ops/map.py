"""Dense batched Map kernels — the composition layer on device.

Oracle: ``crdt_tpu.pure.map.Map`` (reference: src/map.rs ``Map<K, V, A>``,
SURVEY.md §3 row 11, §4.3) specialised to MVReg children — the
``Map<String, MVReg<_>>`` shape of BASELINE config 4. State layout for K
interned key slots, A actors, S sibling slots per child register, D
deferred slots (leading axes batch replicas):

- ``top [..., A]``  — the map's top clock (the one shared causal context),
- ``child`` (``MVRegState [..., K, S…]``) — the per-key content slab; a
  content's witness dot is its birth dot and the key's existence witness
  (pure/map.py composition rule: a key is present iff its child holds
  any live dot — no separate witness table),
- ``dcl [..., D, A]`` / ``dkeys [..., D, K]`` / ``dvalid [..., D]`` —
  parked key removes whose clock ran ahead of the top (masked epochs,
  SURVEY.md §7.3), replayed after every state change.

``join`` is the oracle's merge: per content dot, the orswot dot rule
under the two top clocks (kept iff the other side also holds it or never
saw it). Sibling write-clock domination happens ONLY at op-apply time
(``apply_up``), never at merge — the merge-time variant is
order-dependent (see pure/map.py); the context rule propagates apply-time
evictions, making the join a true lattice (safe under any reduction-tree
order). Everything is element-wise compares + masks; no data-dependent
gathers, so vmap/pjit batch it freely and XLA tiles it.

Slot tables are kept in canonical form (valid-first, sorted by (actor,
counter), dead payload zeroed) so converged replicas compare equal as
raw arrays.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from . import mvreg
from .mvreg import MVRegState
from .orswot import _compact_deferred, _dedupe_deferred, _pad_tail, _park_remove

DTYPE = jnp.uint32


class MapState(NamedTuple):
    """A (possibly batched) dense Map<K, MVReg> replica state (pytree)."""

    top: jax.Array     # [..., A]
    child: MVRegState  # arrays [..., K, S(, A)]
    dcl: jax.Array     # [..., D, A]
    dkeys: jax.Array   # [..., D, K] bool
    dvalid: jax.Array  # [..., D]


def empty(
    n_keys: int,
    n_actors: int,
    sibling_cap: int = 4,
    deferred_cap: int = 4,
    batch: tuple = (),
) -> MapState:
    """The join identity: no dots, no keys, no parked removes."""
    return MapState(
        top=jnp.zeros((*batch, n_actors), DTYPE),
        child=mvreg.empty(sibling_cap, n_actors, batch=(*batch, n_keys)),
        dcl=jnp.zeros((*batch, deferred_cap, n_actors), DTYPE),
        dkeys=jnp.zeros((*batch, deferred_cap, n_keys), bool),
        dvalid=jnp.zeros((*batch, deferred_cap), bool),
    )


def widen(
    state: MapState,
    n_keys: int = 0,
    n_actors: int = 0,
    sibling_cap: int = 0,
    deferred_cap: int = 0,
) -> MapState:
    """Re-encode into a wider key/actor/sibling/deferred layout
    (elastic.py). The child slab rides ``mvreg.widen`` with the key axis
    as one more batch axis, then pads fresh (all-dead) key rows at the
    tail; deferred key masks pad False on both axes. Bit-identical to a
    from-scratch wider state holding the same dots. 0 keeps a width;
    shrinking is refused."""
    k, a = state.dkeys.shape[-1], state.top.shape[-1]
    s, d = state.child.wact.shape[-1], state.dvalid.shape[-1]
    nk, na = n_keys or k, n_actors or a
    ns, nd = sibling_cap or s, deferred_cap or d
    if nk < k or na < a or ns < s or nd < d:
        raise ValueError(
            f"widen cannot shrink: ({k}, {a}, {s}, {d}) -> "
            f"({nk}, {na}, {ns}, {nd})"
        )
    lead = state.top.ndim - 1
    pad = partial(_pad_tail, lead=lead)
    child = mvreg.widen(state.child, ns, na)
    child = jax.tree.map(
        lambda x: jnp.pad(
            x, ((0, 0),) * lead + ((0, nk - k),) + ((0, 0),) * (x.ndim - lead - 1)
        ),
        child,
    )
    return MapState(
        top=pad(state.top, (0, na - a)),
        child=child,
        dcl=pad(state.dcl, (0, nd - d), (0, na - a)),
        dkeys=pad(state.dkeys, (0, nd - d), (0, nk - k)),
        dvalid=pad(state.dvalid, (0, nd - d)),
    )


def narrow(
    state: MapState,
    n_keys: int = 0,
    n_actors: int = 0,
    sibling_cap: int = 0,
    deferred_cap: int = 0,
) -> MapState:
    """The inverse of :func:`widen` — slice tail key/actor/sibling/
    deferred lanes off (elastic.shrink drives this). Any live data in a
    dropped lane REFUSES with ValueError; run ``compact`` first so
    retired parked slots and stale payload do not pin lanes."""
    k, a = state.dkeys.shape[-1], state.top.shape[-1]
    s, d = state.child.wact.shape[-1], state.dvalid.shape[-1]
    nk, na = n_keys or k, n_actors or a
    ns, nd = sibling_cap or s, deferred_cap or d
    if nk > k or na > a or ns > s or nd > d:
        raise ValueError(
            f"narrow cannot grow: ({k}, {a}, {s}, {d}) -> "
            f"({nk}, {na}, {ns}, {nd})"
        )
    from . import mvreg as mv_ops

    live = []
    if nk < k and bool(
        jnp.any(state.child.valid[..., nk:, :])
        | jnp.any(state.dkeys[..., :, nk:])
    ):
        live.append(f"n_keys {k}->{nk}")
    if na < a and bool(
        jnp.any(state.top[..., na:]) | jnp.any(state.dcl[..., :, na:])
    ):
        live.append(f"n_actors {a}->{na}")
    if nd < d and bool(jnp.any(state.dvalid[..., nd:])):
        live.append(f"deferred_cap {d}->{nd}")
    if live:
        raise ValueError(
            f"narrow refused — dropped lanes hold live state: {live} "
            f"(compact first, or shrink less)"
        )
    child = jax.tree.map(
        lambda x: x[..., :nk, :, :] if x.ndim == state.child.clk.ndim
        else x[..., :nk, :],
        state.child,
    )
    child = mv_ops.narrow(child, ns, na)  # refuses live sibling/actor lanes
    return MapState(
        top=state.top[..., :na],
        child=child,
        dcl=state.dcl[..., :nd, :na],
        dkeys=state.dkeys[..., :nd, :nk],
        dvalid=state.dvalid[..., :nd],
    )


def _top_at(top: jax.Array, act: jax.Array) -> jax.Array:
    """``top[act]`` for an actor-id table ``act [..., K, S]`` against a
    clock ``top [..., A]`` (broadcast gather over the key axis)."""
    return jnp.take_along_axis(
        jnp.broadcast_to(top[..., None, :], (*act.shape[:-1], top.shape[-1])),
        act,
        axis=-1,
    )


def _canon_child(child: MVRegState) -> MVRegState:
    """Canonical slot order: valid first, then by (actor, counter); dead
    payload zeroed — converged replicas compare equal as raw arrays."""
    order = jnp.lexsort((child.wctr, child.wact, ~child.valid), axis=-1)
    valid = jnp.take_along_axis(child.valid, order, axis=-1)
    return MVRegState(
        wact=jnp.where(valid, jnp.take_along_axis(child.wact, order, axis=-1), 0),
        wctr=jnp.where(valid, jnp.take_along_axis(child.wctr, order, axis=-1), 0),
        clk=jnp.where(
            valid[..., None],
            jnp.take_along_axis(child.clk, order[..., None], axis=-2),
            0,
        ),
        val=jnp.where(valid, jnp.take_along_axis(child.val, order, axis=-1), 0),
        valid=valid,
    )


# ---- removes -------------------------------------------------------------

def _rm_covered(child: MVRegState, rm_clock: jax.Array, key_mask: jax.Array) -> jax.Array:
    """Content survival under one keyset-remove (the oracle's
    ``remove_dots_under``): masked keys drop contents whose witness dot
    the rm clock covers. Returns the new child valid mask."""
    covered = child.wctr <= _top_at(rm_clock, child.wact)
    return child.valid & ~(key_mask[..., :, None] & covered)


def _apply_parked(state: MapState) -> MapState:
    """Replay every parked keyset-remove against the content slab (the
    removes commute, so scan order is free)."""

    def step(valid, slot):
        cl, keys, dv = slot
        new = state.child._replace(valid=valid)
        new_valid = _rm_covered(new, cl, keys)
        return jnp.where(dv[..., None, None], new_valid, valid), None

    d_axis = state.dcl.ndim - 2
    valid, _ = lax.scan(
        step,
        state.child.valid,
        (
            jnp.moveaxis(state.dcl, d_axis, 0),
            jnp.moveaxis(state.dkeys, d_axis, 0),
            jnp.moveaxis(state.dvalid, d_axis, 0),
        ),
    )
    return state._replace(child=state.child._replace(valid=valid))


def _drop_stale_deferred(state: MapState) -> MapState:
    """Forget parked removes the top clock has caught up to (the oracle
    re-defers only clocks still ahead of ``self.clock``)."""
    still_ahead = ~jnp.all(state.dcl <= state.top[..., None, :], axis=-1)
    dvalid = state.dvalid & still_ahead
    return state._replace(
        dcl=jnp.where(dvalid[..., None], state.dcl, 0),
        dkeys=state.dkeys & dvalid[..., None],
        dvalid=dvalid,
    )


# ---- CvRDT join (the config-4 hot loop) ----------------------------------

def _dot_in(a: MVRegState, b: MVRegState) -> jax.Array:
    """For each content slot of ``a``: does ``b`` hold the same witness
    dot (any slot, same key)? [..., K, S]"""
    eq = (
        (a.wact[..., :, None] == b.wact[..., None, :])
        & (a.wctr[..., :, None] == b.wctr[..., None, :])
        & b.valid[..., None, :]
    )
    return a.valid & jnp.any(eq, axis=-1)


@jax.jit
def reset_remove(state: MapState, clock: jax.Array) -> MapState:
    """ResetRemove — nested causal removal (pure/map.py ``reset_remove``,
    SURVEY §4.3; reference: src/map.rs ResetRemove impl). Children drop
    contents whose WITNESS DOT the clock covers (``remove_dots_under``
    dot-level semantics — not full-clock domination), a bottomed child's
    key dies implicitly (all slots invalid), parked keyset-removes reset
    like the orswot deferred buffer (slot dies when its clock empties,
    equal survivors re-union), and the outer clock forgets covered
    lanes. Nothing grows, so no overflow is possible."""
    from . import vclock

    clock = jnp.asarray(clock, state.top.dtype)
    valid = state.child.valid & (
        state.child.wctr > _top_at(clock, state.child.wact)
    )
    child = _canon_child(state.child._replace(valid=valid))
    dcl = vclock.reset_remove(state.dcl, clock[..., None, :])
    dvalid = state.dvalid & jnp.any(dcl > 0, axis=-1)
    dcl = jnp.where(dvalid[..., None], dcl, 0)
    dkeys = state.dkeys & dvalid[..., None]
    dcl, dkeys, dvalid = _dedupe_deferred(dcl, dkeys, dvalid)
    dcl, dkeys, dvalid, _ = _compact_deferred(
        dcl, dkeys, dvalid, state.dvalid.shape[-1]
    )
    top = vclock.reset_remove(state.top, clock)
    return MapState(top=top, child=child, dcl=dcl, dkeys=dkeys, dvalid=dvalid)


@jax.jit
def join(a: MapState, b: MapState):
    """Pairwise lattice join — the oracle's ``Map::merge`` as element-wise
    arithmetic. Reference: src/map.rs ``CvRDT::merge`` (causal-composition
    semantics per pure/map.py). Returns ``(state, overflow)``."""
    # Content survival: the orswot dot rule under the top clocks. No
    # write-clock domination here (see module docstring).
    keep_a = a.child.valid & (
        _dot_in(a.child, b.child) | (a.child.wctr > _top_at(b.top, a.child.wact))
    )
    keep_b = b.child.valid & (
        _dot_in(b.child, a.child) | (b.child.wctr > _top_at(a.top, b.child.wact))
    )

    # Union the survivors (double-width slab for now — parked removes
    # replay BEFORE the capacity check, so a union that only transiently
    # exceeds capacity does not flag overflow); dedupe dots held by both
    # (same dot ⇒ same content).
    child = MVRegState(
        wact=jnp.concatenate([a.child.wact, b.child.wact], axis=-1),
        wctr=jnp.concatenate([a.child.wctr, b.child.wctr], axis=-1),
        clk=jnp.concatenate([a.child.clk, b.child.clk], axis=-2),
        val=jnp.concatenate([a.child.val, b.child.val], axis=-1),
        valid=jnp.concatenate([keep_a, keep_b], axis=-1),
    )
    s = child.wact.shape[-1]
    dup = (
        (child.wact[..., :, None] == child.wact[..., None, :])
        & (child.wctr[..., :, None] == child.wctr[..., None, :])
        & child.valid[..., :, None]
        & child.valid[..., None, :]
    )
    first = jnp.argmax(dup, axis=-1)  # first valid slot holding this dot
    child = child._replace(valid=child.valid & (first == jnp.arange(s)))

    top = jnp.maximum(a.top, b.top)

    # Deferred: dict-union on equal clocks, replay, drop caught-up slots.
    dcl = jnp.concatenate([a.dcl, b.dcl], axis=-2)
    dkeys = jnp.concatenate([a.dkeys, b.dkeys], axis=-2)
    dvalid = jnp.concatenate([a.dvalid, b.dvalid], axis=-1)
    dcl, dkeys, dvalid = _dedupe_deferred(dcl, dkeys, dvalid)
    state = MapState(top=top, child=child, dcl=dcl, dkeys=dkeys, dvalid=dvalid)
    state = _apply_parked(state)
    state = _drop_stale_deferred(state)
    dcl, dkeys, dvalid, d_overflow = _compact_deferred(
        state.dcl, state.dkeys, state.dvalid, a.dcl.shape[-2]
    )

    # Now compact the (replayed) slab back to capacity.
    child = _canon_child(state.child)
    scap = a.child.wact.shape[-1]
    c_overflow = jnp.any(jnp.sum(child.valid, axis=-1) > scap)
    child = jax.tree.map(
        lambda x: x[..., :scap, :] if x.ndim == child.clk.ndim else x[..., :scap],
        child,
    )
    state = state._replace(child=child, dcl=dcl, dkeys=dkeys, dvalid=dvalid)
    # Two flag lanes: [sibling-slab overflow, deferred-buffer overflow] —
    # models surface them as SlotOverflow vs DeferredOverflow.
    return state, jnp.stack([c_overflow, jnp.any(d_overflow)])


def changed_keys(a: MapState, b: MapState) -> jax.Array:
    """Telemetry counter emitted next to the merge masks: keys whose
    MVReg cell slab (writer, counter, clock, value, liveness) differs
    between two states (uint32, summed over every leading batch lane).
    Counts only the key-sharded child planes, so element-shard psums
    never double count the replicated top/deferred buffers
    (telemetry.py)."""
    diff = (
        jnp.any(a.child.wact != b.child.wact, axis=-1)
        | jnp.any(a.child.wctr != b.child.wctr, axis=-1)
        | jnp.any(a.child.clk != b.child.clk, axis=(-2, -1))
        | jnp.any(a.child.val != b.child.val, axis=-1)
        | jnp.any(a.child.valid != b.child.valid, axis=-1)
    )
    return jnp.sum(diff, dtype=jnp.uint32)


def fold(states: MapState, prefer: str = "auto"):
    """Join a whole replica batch (leading axis) — the fused dense-slab
    Pallas fold on TPU backends (pallas_kernels.fold_fused_map), the jnp
    log2 reduction tree elsewhere; both sound because ``join`` is a true
    lattice join (tests assert this on device shapes, and fused == tree
    is pinned by tests/test_pallas_fold.py). Returns
    ``(state, overflow)``."""
    from .pallas_kernels import fold_auto_map

    return fold_auto_map(states, prefer)


def _tree_fold(states: MapState):
    """The jnp log-tree fold (the fused path's oracle)."""
    from .lattice import tree_fold

    identity = empty(
        states.dkeys.shape[-1],
        states.top.shape[-1],
        states.child.wact.shape[-1],
        states.dcl.shape[-2],
    )
    return tree_fold(states, identity, join)


# ---- CmRDT op application ------------------------------------------------

@jax.jit
def apply_up(
    state: MapState,
    actor: jax.Array,
    counter: jax.Array,
    key: jax.Array,
    put_clock: jax.Array,
    val: jax.Array,
):
    """Apply ``Op::Up { dot, key, op: Put { clock, val } }`` (reference:
    src/map.rs CmRDT::apply): drop already-seen dots; else route the put
    into the key's register (evicting siblings its clock dominates — the
    apply-time domination the merge relies on), advance the top, and
    replay parked removes. Returns ``(state, overflow)``."""
    counter = counter.astype(state.top.dtype)
    seen = state.top[..., actor] >= counter
    k = state.dkeys.shape[-1]
    key_onehot = jax.nn.one_hot(key, k, dtype=bool)

    # Route the put into the key's child register (computed for every key
    # row, selected at the target — dense-mode style, no dynamic gather).
    put_clock = jnp.asarray(put_clock, state.child.clk.dtype)
    bc = lambda x: jnp.broadcast_to(x[..., None], (*x.shape, k))
    new_child, c_of = mvreg.apply_put(
        state.child,
        bc(jnp.asarray(actor, jnp.int32)),
        bc(counter),
        jnp.broadcast_to(
            put_clock[..., None, :], (*put_clock.shape[:-1], k, put_clock.shape[-1])
        ),
        bc(jnp.asarray(val, jnp.int32)),
    )
    sel = (key_onehot & ~seen[..., None])[..., None]  # [..., K, 1]
    child = jax.tree.map(
        lambda new, old: jnp.where(
            sel[..., None] if old.ndim > sel.ndim else sel, new, old
        ),
        new_child,
        state.child,
    )
    c_overflow = jnp.any(c_of & key_onehot & ~seen[..., None], axis=-1)

    top = jnp.where(
        seen[..., None], state.top, state.top.at[..., actor].max(counter)
    )
    state = state._replace(top=top, child=child)
    state = _drop_stale_deferred(_apply_parked(state))
    return state._replace(child=_canon_child(state.child)), c_overflow


def _law_states():
    """Concurrent puts, a covered key-remove, and parked (ahead)
    removes over 2 keys × 2 actors with sibling/deferred headroom."""
    cl = lambda x, y: jnp.array([x, y], DTYPE)
    k0 = jnp.array([True, False])
    k1 = jnp.array([False, True])
    kb = jnp.array([True, True])
    e = empty(2, 2, sibling_cap=4, deferred_cap=4)
    u1, _ = apply_up(e, 0, jnp.uint32(1), 0, cl(1, 0), 5)
    u2, _ = apply_up(u1, 0, jnp.uint32(2), 1, cl(2, 0), 6)
    v1, _ = apply_up(e, 1, jnp.uint32(1), 0, cl(0, 1), 7)
    uv, _ = join(u2, v1)
    r1, _ = apply_rm(uv, cl(2, 1), k0)   # covered: kills key 0 now
    r2, _ = apply_rm(u1, cl(0, 2), k1)   # ahead: parks
    r3, _ = apply_rm(e, cl(1, 1), kb)    # ahead on empty: parks
    return [e, u1, u2, v1, r1, r2, r3]


def _law_canon(s: MapState) -> MapState:
    from ..analysis.canon import canon_epochs, canon_mvreg

    dcl, dkeys, dvalid = canon_epochs(s.dcl, s.dkeys, s.dvalid)
    return MapState(
        top=s.top, child=canon_mvreg(s.child),
        dcl=dcl, dkeys=dkeys, dvalid=dvalid,
    )


@jax.jit
def apply_rm(state: MapState, rm_clock: jax.Array, key_mask: jax.Array):
    """Apply ``Op::Rm { clock, keyset }`` (reference: src/map.rs
    ``apply_keyset_rm``): always kill the covered content now; if the rm
    clock is ahead of the top, park it (union on an equal-clock slot,
    else claim a free one). Returns ``(state, overflow)``."""
    rm_clock = jnp.asarray(rm_clock, state.top.dtype)
    valid = _rm_covered(state.child, rm_clock, key_mask)
    child = _canon_child(state.child._replace(valid=valid))

    ahead = ~jnp.all(rm_clock <= state.top, axis=-1)
    dcl, dkeys, dvalid, overflow = _park_remove(
        state.dcl, state.dkeys, state.dvalid, rm_clock, key_mask, ahead
    )
    return (
        MapState(top=state.top, child=child, dcl=dcl, dkeys=dkeys, dvalid=dvalid),
        overflow,
    )


# ---- static-analysis registration (crdt_tpu.analysis) --------------------

@jax.jit
def compact(state: MapState, frontier: jax.Array):
    """Causal-stability compaction (reclaim/): retire parked
    keyset-removes the stable frontier has caught up to, scrub stale
    parked payload, and re-canonicalize the child slab (dead sibling
    slots of removed keys carry no payload — the dead-key scrub).
    Observable reads (live values per key) untouched. Returns
    ``(state, freed_slots, freed_bytes)``."""
    from ..reclaim.compaction import retire_epochs

    dcl, dkeys, dvalid, freed, freed_b = retire_epochs(
        state.dcl, state.dkeys, state.dvalid, state.top, frontier
    )
    return (
        state._replace(
            child=_canon_child(state.child), dcl=dcl, dkeys=dkeys,
            dvalid=dvalid,
        ),
        freed,
        freed_b,
    )


def _observe(s: MapState):
    """The observable read: per-key live value sets, content-ordered
    (the map read of pure/map.py — key present iff its child holds a
    live dot, value = the MVReg sibling set)."""
    cc = _canon_child(s.child)
    return (cc.val, cc.valid)


def _decomp_split(s: MapState):
    """Decomposition granularity (delta_opt/): one δ lane per key's
    content-slot row group; top + parked keyset buffer residual."""
    return s.child, (s.top, s.dcl, s.dkeys, s.dvalid)


def _decomp_unsplit(rows, res) -> MapState:
    top, dcl, dkeys, dvalid = res
    return MapState(top=top, child=rows, dcl=dcl, dkeys=dkeys, dvalid=dvalid)


from ..analysis.registry import (  # noqa: E402
    register_compactor,
    register_decomposition,
    register_merge,
)

register_merge(
    "map", module=__name__, join=join, states=_law_states,
    canon=_law_canon,
)
register_compactor(
    "map", module=__name__, compact=compact, observe=_observe,
    top_of=lambda s: s.top,
)
register_decomposition(
    "map", module=__name__, split=_decomp_split, unsplit=_decomp_unsplit,
)
