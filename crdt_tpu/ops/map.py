"""Dense batched Map kernels — the composition layer on device.

Oracle: ``crdt_tpu.pure.map.Map`` (reference: src/map.rs ``Map<K, V, A>``,
SURVEY.md §3 row 11, §4.3) specialised to MVReg children — the
``Map<String, MVReg<_>>`` shape of BASELINE config 4. State layout for K
interned key slots, A actors, W witness slots per key, S sibling slots
per child register, D deferred slots (leading axes batch replicas):

- ``top [..., A]``                     — the map's top clock,
- ``wact/wctr/wvalid [..., K, W]``     — per-key witness dot sets (the
  oracle's ``_Entry.dots``: true dot sets, not per-actor-max clocks, so
  removing the state witnessed by (A,1) while (A,2) lives is exact),
- ``child`` (``MVRegState [..., K, S…]``) — the per-key MVReg slab; a
  content is alive iff its witness dot is in the key's witness set,
- ``dcl [..., D, A]`` / ``dkeys [..., D, K]`` / ``dvalid [..., D]`` —
  parked key removes whose clock ran ahead of the top (masked epochs,
  SURVEY.md §7.3), replayed after every state change.

A key is present iff any witness slot is valid. ``join`` is the oracle's
merge: witness dots survive by the orswot dot rule (kept iff the other
side also witnesses them or never saw them), children merge by the MVReg
domination rule and are then pruned to the surviving witnesses — a pure
pointwise function of the joined witness set, which is what makes the
join a true lattice (safe under any reduction-tree order). Everything is
element-wise compares + masks; no data-dependent gathers, so vmap/pjit
batch it freely and XLA tiles it.

All slot tables are kept in canonical form (valid-first, sorted by
(actor, counter), dead payload zeroed) so converged replicas compare
equal as raw arrays.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from . import mvreg
from .mvreg import MVRegState
from .orswot import _compact_deferred, _dedupe_deferred

DTYPE = jnp.uint32


class MapState(NamedTuple):
    """A (possibly batched) dense Map<K, MVReg> replica state (pytree)."""

    top: jax.Array     # [..., A]
    wact: jax.Array    # [..., K, W] int32
    wctr: jax.Array    # [..., K, W] uint32
    wvalid: jax.Array  # [..., K, W] bool
    child: MVRegState  # arrays [..., K, S(, A)]
    dcl: jax.Array     # [..., D, A]
    dkeys: jax.Array   # [..., D, K] bool
    dvalid: jax.Array  # [..., D]


def empty(
    n_keys: int,
    n_actors: int,
    witness_cap: int = 4,
    sibling_cap: int = 4,
    deferred_cap: int = 4,
    batch: tuple = (),
) -> MapState:
    """The join identity: no dots, no keys, no parked removes."""
    return MapState(
        top=jnp.zeros((*batch, n_actors), DTYPE),
        wact=jnp.zeros((*batch, n_keys, witness_cap), jnp.int32),
        wctr=jnp.zeros((*batch, n_keys, witness_cap), DTYPE),
        wvalid=jnp.zeros((*batch, n_keys, witness_cap), bool),
        child=mvreg.empty(sibling_cap, n_actors, batch=(*batch, n_keys)),
        dcl=jnp.zeros((*batch, deferred_cap, n_actors), DTYPE),
        dkeys=jnp.zeros((*batch, deferred_cap, n_keys), bool),
        dvalid=jnp.zeros((*batch, deferred_cap), bool),
    )


# ---- witness-set helpers -------------------------------------------------

def _top_at(top: jax.Array, act: jax.Array) -> jax.Array:
    """``top[act]`` for an actor-id table ``act [..., K, W]`` against a
    clock ``top [..., A]`` (broadcast gather over the key axis)."""
    return jnp.take_along_axis(
        jnp.broadcast_to(top[..., None, :], (*act.shape[:-1], top.shape[-1])),
        act,
        axis=-1,
    )


def _witness_in(wact, wctr, wvalid, oact, octr, ovalid) -> jax.Array:
    """For each witness slot on our side: is the same dot witnessed (in
    any slot) on the other side? [..., K, W]"""
    eq = (
        (wact[..., :, None] == oact[..., None, :])
        & (wctr[..., :, None] == octr[..., None, :])
        & ovalid[..., None, :]
    )
    return wvalid & jnp.any(eq, axis=-1)


def _retain_witnesses(child: MVRegState, wact, wctr, wvalid) -> MVRegState:
    """The oracle's ``retain_witnesses``: a child content survives iff its
    witness dot is in the key's (surviving) witness set."""
    alive = (
        (child.wact[..., :, None] == wact[..., None, :])
        & (child.wctr[..., :, None] == wctr[..., None, :])
        & wvalid[..., None, :]
    )
    return child._replace(valid=child.valid & jnp.any(alive, axis=-1))


def _canon_witnesses(wact, wctr, wvalid):
    """Canonical slot order: valid first, then by (actor, counter); dead
    payload zeroed — converged replicas compare equal as raw arrays."""
    order = jnp.lexsort((wctr, wact, ~wvalid), axis=-1)
    wact = jnp.take_along_axis(wact, order, axis=-1)
    wctr = jnp.take_along_axis(wctr, order, axis=-1)
    wvalid = jnp.take_along_axis(wvalid, order, axis=-1)
    return (
        jnp.where(wvalid, wact, 0),
        jnp.where(wvalid, wctr, 0),
        wvalid,
    )


def _canon_child(child: MVRegState) -> MVRegState:
    """Same canonicalisation for the sibling slab (keyed by witness dot)."""
    order = jnp.lexsort((child.wctr, child.wact, ~child.valid), axis=-1)
    valid = jnp.take_along_axis(child.valid, order, axis=-1)
    return MVRegState(
        wact=jnp.where(valid, jnp.take_along_axis(child.wact, order, axis=-1), 0),
        wctr=jnp.where(valid, jnp.take_along_axis(child.wctr, order, axis=-1), 0),
        clk=jnp.where(
            valid[..., None],
            jnp.take_along_axis(child.clk, order[..., None], axis=-2),
            0,
        ),
        val=jnp.where(valid, jnp.take_along_axis(child.val, order, axis=-1), 0),
        valid=valid,
    )


# ---- removes -------------------------------------------------------------

def _rm_covered(wact, wctr, wvalid, rm_clock, key_mask) -> jax.Array:
    """Witness survival under one keyset-remove (the oracle's
    ``_apply_keyset_rm`` filter): masked keys drop dots the rm clock
    covers. Returns the new wvalid."""
    covered = wctr <= _top_at(rm_clock, wact)
    return wvalid & ~(key_mask[..., :, None] & covered)


def _apply_parked(state: MapState) -> MapState:
    """Replay every parked keyset-remove against the witness table (the
    removes commute, so scan order is free), then prune children once."""

    def step(wvalid, slot):
        cl, keys, valid = slot
        new = _rm_covered(state.wact, state.wctr, wvalid, cl, keys)
        return jnp.where(valid[..., None, None], new, wvalid), None

    d_axis = state.dcl.ndim - 2
    wvalid, _ = lax.scan(
        step,
        state.wvalid,
        (
            jnp.moveaxis(state.dcl, d_axis, 0),
            jnp.moveaxis(state.dkeys, d_axis, 0),
            jnp.moveaxis(state.dvalid, d_axis, 0),
        ),
    )
    child = _retain_witnesses(state.child, state.wact, state.wctr, wvalid)
    return state._replace(wvalid=wvalid, child=child)


def _drop_stale_deferred(state: MapState) -> MapState:
    """Forget parked removes the top clock has caught up to (the oracle
    re-defers only clocks still ahead of ``self.clock``)."""
    still_ahead = ~jnp.all(state.dcl <= state.top[..., None, :], axis=-1)
    dvalid = state.dvalid & still_ahead
    return state._replace(
        dcl=jnp.where(dvalid[..., None], state.dcl, 0),
        dkeys=state.dkeys & dvalid[..., None],
        dvalid=dvalid,
    )


# ---- CvRDT join (the config-4 hot loop) ----------------------------------

@jax.jit
def join(a: MapState, b: MapState):
    """Pairwise lattice join — the oracle's ``Map::merge`` as element-wise
    arithmetic. Reference: src/map.rs ``CvRDT::merge`` (witness-dot-set
    semantics per pure/map.py). Returns ``(state, overflow)``."""
    # Witness survival: the orswot dot rule, uniform over present/absent
    # keys (an absent key is an empty witness set).
    keep_a = a.wvalid & (
        _witness_in(a.wact, a.wctr, a.wvalid, b.wact, b.wctr, b.wvalid)
        | (a.wctr > _top_at(b.top, a.wact))
    )
    keep_b = b.wvalid & (
        _witness_in(b.wact, b.wctr, b.wvalid, a.wact, a.wctr, a.wvalid)
        | (b.wctr > _top_at(a.top, b.wact))
    )

    # Union the surviving witness slots; dedupe dots witnessed by both.
    wact = jnp.concatenate([a.wact, b.wact], axis=-1)
    wctr = jnp.concatenate([a.wctr, b.wctr], axis=-1)
    wvalid = jnp.concatenate([keep_a, keep_b], axis=-1)
    dup = (
        (wact[..., :, None] == wact[..., None, :])
        & (wctr[..., :, None] == wctr[..., None, :])
        & wvalid[..., :, None]
        & wvalid[..., None, :]
    )
    w = wact.shape[-1]
    first = jnp.argmax(dup, axis=-1)  # first valid slot holding this dot
    wvalid = wvalid & (first == jnp.arange(w))
    wact, wctr, wvalid = _canon_witnesses(wact, wctr, wvalid)
    wcap = a.wact.shape[-1]
    w_overflow = jnp.any(jnp.sum(wvalid, axis=-1) > wcap)
    wact, wctr, wvalid = wact[..., :wcap], wctr[..., :wcap], wvalid[..., :wcap]

    # Children: MVReg domination merge per key, then prune to the joined
    # witness set (pure pointwise function of the join — lattice-safe).
    child, c_overflow = mvreg.join(a.child, b.child)
    child = _retain_witnesses(child, wact, wctr, wvalid)

    top = jnp.maximum(a.top, b.top)

    # Deferred: dict-union on equal clocks, replay, drop caught-up slots.
    dcl = jnp.concatenate([a.dcl, b.dcl], axis=-2)
    dkeys = jnp.concatenate([a.dkeys, b.dkeys], axis=-2)
    dvalid = jnp.concatenate([a.dvalid, b.dvalid], axis=-1)
    dcl, dkeys, dvalid = _dedupe_deferred(dcl, dkeys, dvalid)
    state = MapState(
        top=top, wact=wact, wctr=wctr, wvalid=wvalid, child=child,
        dcl=dcl, dkeys=dkeys, dvalid=dvalid,
    )
    state = _apply_parked(state)
    state = _drop_stale_deferred(state)
    dcl, dkeys, dvalid, d_overflow = _compact_deferred(
        state.dcl, state.dkeys, state.dvalid, a.dcl.shape[-2]
    )
    state = state._replace(
        child=_canon_child(state.child), dcl=dcl, dkeys=dkeys, dvalid=dvalid
    )
    overflow = w_overflow | jnp.any(c_overflow) | jnp.any(d_overflow)
    return state, overflow


def fold(states: MapState):
    """Join a whole replica batch (leading axis) in a log2 reduction tree
    — sound because ``join`` is a true lattice join (tests assert this on
    device shapes). Returns ``(state, overflow)``."""
    from .lattice import tree_fold

    identity = empty(
        states.wact.shape[-2],
        states.top.shape[-1],
        states.wact.shape[-1],
        states.child.wact.shape[-1],
        states.dcl.shape[-2],
    )
    return tree_fold(states, identity, join)


# ---- CmRDT op application ------------------------------------------------

@jax.jit
def apply_up(
    state: MapState,
    actor: jax.Array,
    counter: jax.Array,
    key: jax.Array,
    put_clock: jax.Array,
    val: jax.Array,
):
    """Apply ``Op::Up { dot, key, op: Put { clock, val } }`` (reference:
    src/map.rs CmRDT::apply): drop already-seen dots; else witness the key
    with the dot, route the put into the key's MVReg, advance the top, and
    replay parked removes. Returns ``(state, overflow)``."""
    counter = counter.astype(state.top.dtype)
    seen = state.top[..., actor] >= counter
    k = state.wact.shape[-2]
    key_onehot = jax.nn.one_hot(key, k, dtype=bool)

    # Witness the key: claim the first free slot on the key's row. The dot
    # is fresh (unseen ⇒ in no witness set), so no dedupe is needed.
    free = ~state.wvalid & key_onehot[..., :, None]
    has_free = jnp.any(free, axis=(-2, -1))
    flat = free.reshape(*free.shape[:-2], -1)
    slot = jnp.argmax(flat, axis=-1)
    claim = (
        jax.nn.one_hot(slot, flat.shape[-1], dtype=bool).reshape(free.shape)
        & (has_free & ~seen)[..., None, None]
    )
    wact = jnp.where(claim, jnp.asarray(actor, jnp.int32)[..., None, None], state.wact)
    wctr = jnp.where(claim, counter[..., None, None], state.wctr)
    wvalid = state.wvalid | claim
    w_overflow = ~seen & ~has_free

    # Route the put into the key's child register (computed for every key
    # row, selected at the target — dense-mode style, no dynamic gather).
    put_clock = jnp.asarray(put_clock, state.child.clk.dtype)
    bc = lambda x: jnp.broadcast_to(x[..., None], (*x.shape, k))
    new_child, c_of = mvreg.apply_put(
        state.child,
        bc(jnp.asarray(actor, jnp.int32)),
        bc(counter),
        jnp.broadcast_to(put_clock[..., None, :], (*put_clock.shape[:-1], k, put_clock.shape[-1])),
        bc(jnp.asarray(val, jnp.int32)),
    )
    sel = (key_onehot & ~seen[..., None])[..., None]  # [..., K, 1]
    child = jax.tree.map(
        lambda new, old: jnp.where(
            sel[..., None] if old.ndim > sel.ndim else sel, new, old
        ),
        new_child,
        state.child,
    )
    c_overflow = jnp.any(c_of & key_onehot & ~seen[..., None], axis=-1)

    top = jnp.where(
        seen[..., None], state.top, state.top.at[..., actor].max(counter)
    )
    state = state._replace(
        top=top, wact=wact, wctr=wctr, wvalid=wvalid, child=child
    )
    state = _drop_stale_deferred(_apply_parked(state))
    state = state._replace(child=_canon_child(state.child))
    return state, w_overflow | c_overflow


@jax.jit
def apply_rm(state: MapState, rm_clock: jax.Array, key_mask: jax.Array):
    """Apply ``Op::Rm { clock, keyset }`` (reference: src/map.rs
    ``apply_keyset_rm``): always strip the covered witnesses now; if the
    rm clock is ahead of the top, park it (union on an equal-clock slot,
    else claim a free one). Returns ``(state, overflow)``."""
    rm_clock = jnp.asarray(rm_clock, state.top.dtype)
    wvalid = _rm_covered(state.wact, state.wctr, state.wvalid, rm_clock, key_mask)
    wact, wctr, wvalid = _canon_witnesses(state.wact, state.wctr, wvalid)
    child = _retain_witnesses(state.child, wact, wctr, wvalid)
    child = _canon_child(child)

    ahead = ~jnp.all(rm_clock <= state.top, axis=-1)
    same = state.dvalid & jnp.all(state.dcl == rm_clock[..., None, :], axis=-1)
    has_same = jnp.any(same, axis=-1)
    free = ~state.dvalid
    has_free = jnp.any(free, axis=-1)
    slot = jnp.where(has_same, jnp.argmax(same, axis=-1), jnp.argmax(free, axis=-1))
    park = ahead & (has_same | has_free)
    overflow = ahead & ~has_same & ~has_free

    d = state.dvalid.shape[-1]
    onehot = jax.nn.one_hot(slot, d, dtype=bool) & park[..., None]
    dcl = jnp.where(onehot[..., None], rm_clock[..., None, :], state.dcl)
    live = state.dkeys & state.dvalid[..., None]
    dkeys = jnp.where(onehot[..., None], key_mask[..., None, :] | live, state.dkeys)
    return (
        MapState(
            top=state.top, wact=wact, wctr=wctr, wvalid=wvalid, child=child,
            dcl=dcl, dkeys=dkeys, dvalid=state.dvalid | onehot,
        ),
        overflow,
    )
