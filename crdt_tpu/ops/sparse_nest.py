"""Sparse (segment-encoded) map nesting — the NestLevel induction for
the compressed representation.

Round 4 left the sparse backend flat (VERDICT r04 Missing #2): the
segment-encoded ORSWOT scaled to huge member universes, but the map
family was dense-only, so ``Map<K, Orswot>`` at 1M keys could not fit
the E×A slab. This module is the sparse counterpart of ops/nest.py's
``NestLevel``: one induction step that wraps any sparse slab with one
more outer parked-keyset buffer — with LISTS where the dense level uses
masks, so state stays proportional to live content at every level.

Reference semantics: src/map.rs ``Map<K, V: Val<A>, A>`` (SURVEY.md §3
r11) under the causal-composition rule of pure/map.py — every child's
top clock equals the outer map clock, so the whole nest flattens onto
ONE leaf dot-segment table over the product key space, and each map
level adds only its parked keyset-removes. Flattening convention:

    leaf element id  e = key_id * span + member_id

where ``span`` (a static per-level constant) is the number of LEAF ids
per key of that level. A dot's level-ℓ key is ``e // span_ℓ`` — so a
parked (clock, key-list) replays against the leaf segments by integer
division, and per-key liveness is a range query [k·span, (k+1)·span) on
the canonically sorted segment table. No dense K-wide mask is ever
materialized; the universe bound is the packed int32 key of
ops/sparse_orswot._match_other (K · span · A < 2^31).

Key liveness facts the scrub relies on (oracle: pure/map.py — a key is
present iff its child holds any live dot, and a bottomed child dies
with ALL parked state inside it):

- deadness is monotone up the nest: an outer key's leaf range contains
  its inner keys' ranges, so outer-dead ⟹ inner-dead — each level's
  parked entries only need checking against their IMMEDIATELY enclosing
  level's key;
- a newly-dead key can appear whenever a replay kills dots, so (as in
  ops/nest.py ``settle_outer``) the scrub must run AFTER the replay and
  must recurse into inner levels (a replayed outer remove can newly
  bottom an inner child — tests/test_models_map3.py pins the dense
  failure mode; tests/test_sparse_nest.py pins it sparse).
"""

from __future__ import annotations

from functools import partial
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from . import sparse_orswot as sp
from .sparse_orswot import (
    SparseOrswotState,
    _canon,
    _canon_rmlist,
    _compact_parked,
    _dedupe_parked,
    _pad_tail,
    _replay_parked,
)

DTYPE = jnp.uint32
_INT32_MAX = jnp.iinfo(jnp.int32).max


class SparseNestState(NamedTuple):
    """One more level around any sparse slab: the core plus this level's
    parked keyset-removes (key LISTS, -1 = empty lane)."""

    core: Any          # SparseOrswotState or an inner SparseNestState
    kcl: jax.Array     # [..., D, A]  parked rm clocks
    kidx: jax.Array    # [..., D, Q]  key ids (-1 pad)
    kdvalid: jax.Array  # [..., D]


def _bsearch_count(key: jax.Array, lo: jax.Array, hi: jax.Array) -> jax.Array:
    """How many entries of the ascending ``key`` fall in [lo, hi) — the
    range-liveness primitive. Batched over leading axes (key [..., C],
    lo/hi [..., N])."""
    if key.ndim > 1:
        return jax.vmap(_bsearch_count)(key, lo, hi)
    return jnp.searchsorted(key, hi) - jnp.searchsorted(key, lo)


def _sorted_key(leaf) -> jax.Array:
    """The leaf table's ascending search key (invalid lanes sort last —
    canonical order guarantees the valid prefix is id-ascending). Works
    for any leaf slab whose first id plane is canonically sorted: the
    ORSWOT segment table (``eid``) and the sparse register-map cell
    table (``kid``, ops/sparse_mvmap.py)."""
    ids = leaf.eid if hasattr(leaf, "eid") else leaf.kid
    return jnp.where(leaf.valid, ids, _INT32_MAX)


def _ids_alive(
    leaf: SparseOrswotState, ids: jax.Array, span: int, element_axis=None
) -> jax.Array:
    """For each id list entry (level-local key ids, -1 = pad): does the
    key have any live leaf dot? Dead pads report False.

    Under element sharding (``element_axis`` set, inside shard_map) a
    key's dots spread across ALL shards (eid % S partitioning), and the
    query lists themselves may be shard-local (the leaf's parked didx
    entries are restricted per shard) — so a plain psum of per-position
    counts would add up answers for DIFFERENT keys. Instead every shard
    all-gathers the query keys, answers every shard's queries against
    its local table, and the answer matrix is psum-reduced; each shard
    then reads its own row. Sound for both shard-local lists (leaf) and
    replicated lists (outer levels, where all rows coincide)."""
    shape = ids.shape
    flat = ids.reshape(*shape[:-2], -1) if ids.ndim > 1 else ids
    key = _sorted_key(leaf)
    if element_axis is None:
        lo = jnp.where(flat >= 0, flat * span, _INT32_MAX)
        hi = jnp.where(flat >= 0, (flat + 1) * span, _INT32_MAX)
        return (_bsearch_count(key, lo, hi) > 0).reshape(shape)

    from jax import lax

    qk = lax.all_gather(flat, element_axis)        # [S, ...same as flat]
    lo = jnp.where(qk >= 0, qk * span, _INT32_MAX)
    hi = jnp.where(qk >= 0, (qk + 1) * span, _INT32_MAX)
    if flat.ndim > 1:
        # Batched states: fold the shard axis into the query width so the
        # batched bsearch maps over the leading batch only.
        s = qk.shape[0]
        lo2 = jnp.moveaxis(lo, 0, -2).reshape(*flat.shape[:-1], -1)
        hi2 = jnp.moveaxis(hi, 0, -2).reshape(*flat.shape[:-1], -1)
        counts = _bsearch_count(key, lo2, hi2)
        counts = jnp.moveaxis(
            counts.reshape(*flat.shape[:-1], s, flat.shape[-1]), -2, 0
        )
    else:
        counts = jax.vmap(lambda l, h: _bsearch_count(key, l, h))(lo, hi)
    counts = lax.psum(counts, element_axis)        # [S, ...]
    me = lax.axis_index(element_axis)
    return (counts[me] > 0).reshape(shape)


def widen_level(
    state: SparseNestState,
    core_widen,
    key_deferred_cap: int = 0,
    key_rm_width: int = 0,
    n_actors: int = 0,
) -> SparseNestState:
    """Widen one nest level's parked-keylist buffer (and, via
    ``core_widen``, everything inside it) — the elastic capacity
    migration for nested sparse states (elastic.py). ``core_widen`` maps
    the core slab to its widened form (compose ``sparse_orswot.widen``/
    ``sparse_mvmap.widen``/a nested ``widen_level``); 0 keeps a width;
    shrinking is refused."""
    d, a = state.kcl.shape[-2:]
    q = state.kidx.shape[-1]
    nd, nq = key_deferred_cap or d, key_rm_width or q
    na = n_actors or a
    if nd < d or nq < q or na < a:
        raise ValueError(
            f"widen cannot shrink: ({d}, {q}, {a}) -> ({nd}, {nq}, {na})"
        )
    lead = state.kdvalid.ndim - 1
    pad = partial(_pad_tail, lead=lead)
    return type(state)(
        core_widen(state.core),
        pad(state.kcl, (0, nd - d), (0, na - a)),
        pad(state.kidx, (0, nd - d), (0, nq - q), fill=-1),
        pad(state.kdvalid, (0, nd - d), fill=False),
    )


def narrow_level(
    state: SparseNestState,
    core_narrow,
    key_deferred_cap: int = 0,
    key_rm_width: int = 0,
    n_actors: int = 0,
) -> SparseNestState:
    """The inverse of :func:`widen_level` — slice one nest level's
    parked-keylist buffer (and, via ``core_narrow``, everything inside
    it) down to a narrower layout (elastic.shrink drives this). Live
    data in a dropped lane REFUSES with ValueError; run the kind's
    ``compact`` first so retired slots do not pin lanes. 0 keeps a
    width."""
    d, a = state.kcl.shape[-2:]
    q = state.kidx.shape[-1]
    nd, nq = key_deferred_cap or d, key_rm_width or q
    na = n_actors or a
    if nd > d or nq > q or na > a:
        raise ValueError(
            f"narrow cannot grow: ({d}, {q}, {a}) -> ({nd}, {nq}, {na})"
        )
    live = []
    if nd < d and bool(jnp.any(state.kdvalid[..., nd:])):
        live.append(f"key_deferred_cap {d}->{nd}")
    if nq < q and bool(jnp.any(state.kidx[..., nq:] >= 0)):
        live.append(f"key_rm_width {q}->{nq}")
    if na < a and bool(jnp.any(state.kcl[..., na:])):
        live.append(f"n_actors {a}->{na}")
    if live:
        raise ValueError(
            f"narrow refused — dropped lanes hold live state: {live} "
            f"(compact first, or shrink less)"
        )
    return type(state)(
        core_narrow(state.core),
        state.kcl[..., :nd, :na],
        state.kidx[..., :nd, :nq],
        state.kdvalid[..., :nd],
    )


def narrow_span(state: SparseNestState, old_span: int, new_span: int) -> SparseNestState:
    """The inverse of :func:`widen_span` — re-encode a depth-2 nest
    under a NARROWER per-key span. Preconditions: the old span must be
    a multiple of the new (aligned offsets preserve key ids) and every
    live flat id's offset must fit the new span — a live offset beyond
    it REFUSES with ValueError (the occupancy-fits contract of every
    narrow kernel)."""
    if new_span > old_span:
        raise ValueError(f"narrow_span cannot grow: {old_span} -> {new_span}")
    if old_span % new_span:
        raise ValueError(
            f"old span {old_span} must be a multiple of the new {new_span} "
            f"(key-id preservation needs aligned offsets)"
        )
    leaf = state.core
    if isinstance(leaf, SparseNestState):
        raise TypeError(
            "narrow_span covers depth-2 nests; rekey deeper nests level "
            "by level with rekey_flat"
        )
    id_planes = ("eid", "didx") if hasattr(leaf, "eid") else ("kid", "kidx")
    for plane in id_planes:
        ids = getattr(leaf, plane)
        if bool(jnp.any((ids >= 0) & (ids % old_span >= new_span))):
            raise ValueError(
                f"narrow_span refused — {plane} holds offsets >= "
                f"{new_span} (occupancy does not fit the narrower span)"
            )
    new_leaf = leaf._replace(**{
        plane: rekey_flat(getattr(leaf, plane), old_span, new_span)
        for plane in id_planes
    })
    return type(state)(new_leaf, state.kcl, state.kidx, state.kdvalid)


def rekey_flat(ids: jax.Array, old_span: int, new_span: int) -> jax.Array:
    """Remap flat leaf ids ``key·old_span + off`` → ``key·new_span +
    off`` (the segment-table repack of a span widening). Monotone for
    ``new_span >= old_span`` with offsets < old_span, so canonical
    segment order survives without a re-sort; negative pads pass
    through."""
    return jnp.where(ids >= 0, (ids // old_span) * new_span + ids % old_span, ids)


def widen_span(state: SparseNestState, old_span: int, new_span: int) -> SparseNestState:
    """Re-encode a depth-2 nest under a wider per-key span (more leaf
    ids per key of THIS level): flat ids in the leaf slab's id plane AND
    the leaf's own parked lists remap via :func:`rekey_flat`; this
    level's parked lists hold level-local key ids and are untouched.
    Keys keep their ids, so the result is bit-identical to a
    from-scratch nest built at the wider span over the same content.
    Deeper nests must compose the remap level by level (every
    intermediate level's lists would need its own rekey) — refused
    here."""
    if new_span < old_span:
        raise ValueError(f"widen_span cannot shrink: {old_span} -> {new_span}")
    if new_span % old_span:
        raise ValueError(
            f"new span {new_span} must be a multiple of the old {old_span} "
            f"(key-id preservation needs aligned offsets)"
        )
    leaf = state.core
    if isinstance(leaf, SparseNestState):
        raise TypeError(
            "widen_span covers depth-2 nests; rekey deeper nests level "
            "by level with rekey_flat"
        )
    if hasattr(leaf, "eid"):
        new_leaf = leaf._replace(
            eid=rekey_flat(leaf.eid, old_span, new_span),
            didx=rekey_flat(leaf.didx, old_span, new_span),
        )
    else:  # the sparse register-map cell table (ops/sparse_mvmap.py)
        new_leaf = leaf._replace(
            kid=rekey_flat(leaf.kid, old_span, new_span),
            kidx=rekey_flat(leaf.kidx, old_span, new_span),
        )
    return type(state)(new_leaf, state.kcl, state.kidx, state.kdvalid)


class SparseLeaf:
    """Protocol adapter: the flat segment slab (ops/sparse_orswot.py) as
    the innermost level. Its ids are leaf element ids (span 1); its own
    buffer parks member-removes as element lists."""

    span = 1

    def leaf(self, s: SparseOrswotState) -> SparseOrswotState:
        return s

    def top(self, s):
        return s.top

    def witness(self, s, actor, counter):
        return s._replace(top=s.top.at[..., actor].max(counter.astype(s.top.dtype)))

    def join(self, a, b, element_axis=None):
        return sp.join(a, b)  # flags [dot-cap, deferred]

    def replay_keylist(self, s, kcl, kidx, kdvalid, span: int):
        # (shard-oblivious: kills only dots present in THIS table)
        """Kill dots whose level-key (eid // span) a valid parked slot
        lists with a covering clock — the sparse analog of the dense
        expanded-mask replay. Re-canonicalizes (kills open holes)."""
        key_of = jnp.where(s.valid, s.eid // span, -2)
        listed = jnp.any(
            key_of[..., None, :, None] == kidx[..., :, None, :], axis=-1
        )  # [..., D, C]
        cl_at = jnp.take_along_axis(
            kcl, jnp.broadcast_to(s.act[..., None, :], listed.shape), axis=-1
        )
        covered = listed & (s.ctr[..., None, :] <= cl_at) & kdvalid[..., None]
        valid = s.valid & ~jnp.any(covered, axis=-2)
        eid, act, ctr, valid, _ = _canon(
            s.eid, s.act, jnp.where(valid, s.ctr, 0), valid, s.eid.shape[-1]
        )
        return s._replace(eid=eid, act=act, ctr=ctr, valid=valid)

    def scrub_enclosing(self, s, span: int, element_axis=None):
        """Drop parked member-remove entries whose enclosing span-key is
        dead (the oracle deletes a bottomed child WITH its deferred
        buffer); emptied slots die."""
        entry_key = jnp.where(s.didx >= 0, s.didx // span, -1)
        alive = _ids_alive(self.leaf(s), entry_key, span, element_axis)
        didx = _canon_rmlist(jnp.where(alive, s.didx, -1))
        dvalid = s.dvalid & jnp.any(didx >= 0, axis=-1)
        return s._replace(
            didx=jnp.where(dvalid[..., None], didx, -1),
            dcl=jnp.where(dvalid[..., None], s.dcl, 0),
            dvalid=dvalid,
        )

    def scrub_self(self, s, element_axis=None):
        return s  # leaf elements hold nothing inside them

    def settle_self(self, s, element_axis=None):
        """Replay the leaf's own parked member-removes under the (maybe
        advanced) top, drop caught-up slots."""
        valid = _replay_parked(
            s.eid, s.act, s.ctr, s.valid, s.dcl, s.didx, s.dvalid
        )
        still = ~jnp.all(s.dcl <= s.top[..., None, :], axis=-1)
        eid, act, ctr, valid, _ = _canon(
            s.eid, s.act, jnp.where(valid, s.ctr, 0), valid, s.eid.shape[-1]
        )
        return s._replace(
            eid=eid, act=act, ctr=ctr, valid=valid, dvalid=s.dvalid & still
        )

    def rm_route(self, s, levels_down: int, rm_clock, ids):
        assert levels_down == 0, "leaf cannot route deeper"
        return sp.apply_rm(s, rm_clock, ids)


SPARSE_LEAF = SparseLeaf()


class SparseNestLevel:
    """One application of the sparse nesting induction: wraps a
    protocol-satisfying sparse slab with one outer parked-keylist
    buffer. The result satisfies the same protocol, so levels compose to
    any depth (mirrors ops/nest.py ``NestLevel``, list-flavored).

    ``span`` — leaf ids per key of THIS level (static). For
    ``Map<K, Orswot>`` with member capacity M: span = M. For
    ``Map<K1, Map<K2, Orswot>>``: outer level span = K2·M over an inner
    level with span M."""

    def __init__(self, core, span: int, state_cls=SparseNestState):
        self.core = core
        self.span = span
        self.state_cls = state_cls
        core_span = getattr(core, "span", 1)
        if span % core_span or span <= core_span:
            raise ValueError(
                f"level span {span} must be a proper multiple of the "
                f"core's span {core_span}"
            )

    def _make(self, core_state, kcl, kidx, kdvalid):
        return self.state_cls(core_state, kcl, kidx, kdvalid)

    def _bufs(self, s):
        return s[1], s[2], s[3]

    def empty(self, core_state, n_actors: int, deferred_cap: int = 4,
              rm_width: int = 8, batch: tuple = ()):
        return self._make(
            core_state,
            jnp.zeros((*batch, deferred_cap, n_actors), DTYPE),
            jnp.full((*batch, deferred_cap, rm_width), -1, jnp.int32),
            jnp.zeros((*batch, deferred_cap), bool),
        )

    # ---- protocol -----------------------------------------------------

    def leaf(self, s) -> SparseOrswotState:
        return self.core.leaf(s[0])

    def top(self, s):
        return self.core.top(s[0])

    def witness(self, s, actor, counter):
        return self._make(
            self.core.witness(s[0], actor, counter), *self._bufs(s)
        )

    def replay_keylist(self, s, kcl, kidx, kdvalid, span: int):
        # (shard-oblivious: kills only dots present in THIS table)
        """An OUTER level's parked removes replay straight through to
        the leaf segments (content only; buffers untouched — matching
        NestLevel.replay_keyset)."""
        return self._make(
            self.core.replay_keylist(s[0], kcl, kidx, kdvalid, span),
            *self._bufs(s),
        )

    def replay_outer(self, s):
        """Replay THIS level's parked keyset-removes, then drop slots
        the top has caught up to (oracle ``_apply_deferred``)."""
        replayed = self.core.replay_keylist(s[0], s[1], s[2], s[3], self.span)
        still = ~jnp.all(s[1] <= self.top(s)[..., None, :], axis=-1)
        kdvalid = s[3] & still
        return self._make(
            replayed,
            jnp.where(kdvalid[..., None], s[1], 0),
            jnp.where(kdvalid[..., None], s[2], -1),
            kdvalid,
        )

    def scrub_enclosing(self, s, span: int, element_axis=None):
        """Called by an ENCLOSING level: drop this level's parked
        entries (and recursively the core's) whose enclosing span-key is
        dead. A key id j at this level starts at leaf id j·self.span, so
        its enclosing key is (j·self.span) // span."""
        leaf = self.leaf(s)
        entry_key = jnp.where(
            s[2] >= 0, (s[2] * self.span) // span, -1
        )
        alive = _ids_alive(leaf, entry_key, span, element_axis)
        kidx = _canon_rmlist(jnp.where(alive, s[2], -1))
        kdvalid = s[3] & jnp.any(kidx >= 0, axis=-1)
        return self._make(
            self.core.scrub_enclosing(s[0], span, element_axis),
            jnp.where(kdvalid[..., None], s[1], 0),
            jnp.where(kdvalid[..., None], kidx, -1),
            kdvalid,
        )

    def scrub_self(self, s, element_axis=None):
        """Drop parked state inside THIS level's bottomed children —
        recursing inner-first (a replayed remove here can newly bottom
        an inner child). This level's OWN buffer is never self-scrubbed
        (it belongs to the level, not to any child)."""
        core = self.core.scrub_self(s[0], element_axis)
        core = self.core.scrub_enclosing(core, self.span, element_axis)
        return self._make(core, *self._bufs(s))

    def settle_self(self, s, element_axis=None):
        core = self.core.settle_self(s[0], element_axis)
        out = self.replay_outer(self._make(core, *self._bufs(s)))
        return self.scrub_self(out, element_axis)

    def settle_outer(self, s, cap: int, element_axis=None):
        """Post-union buffer settlement: dedupe equal-clock slots →
        replay → compact → scrub; the order is correctness-critical
        (ops/nest.py ``settle_outer`` documents why)."""
        kcl, kidx, kdvalid = _dedupe_parked(s[1], s[2], s[3])
        s = self.replay_outer(self._make(s[0], kcl, kidx, kdvalid))
        kcl, kidx, kdvalid, overflow = _compact_parked(s[1], s[2], s[3], cap)
        s = self.scrub_self(self._make(s[0], kcl, kidx, kdvalid), element_axis)
        return s, jnp.any(overflow)

    def join(self, a, b, element_axis=None):
        """Pairwise lattice join. Returns ``(state, flags[L+1])`` —
        core lanes first, this level's parked-capacity lane last.
        ``element_axis`` (inside shard_map, leaf sharded by eid % S)
        routes the scrub's key-liveness psum across element shards."""
        core, core_flags = self.core.join(a[0], b[0], element_axis)
        kcl = jnp.concatenate([a[1], b[1]], axis=-2)
        kidx = jnp.concatenate([a[2], b[2]], axis=-2)
        kdvalid = jnp.concatenate([a[3], b[3]], axis=-1)
        state, of = self.settle_outer(
            self._make(core, kcl, kidx, kdvalid), a[1].shape[-2], element_axis
        )
        return state, jnp.concatenate([core_flags, of[None]])

    def fold(self, states, element_axis=None):
        """Log-tree fold of a replica batch (leading axis)."""
        from functools import partial

        from .lattice import tree_fold

        identity = jax.tree.map(
            lambda x: jnp.zeros(x.shape[1:], x.dtype), states
        )
        identity = _sparse_identity_like(identity)
        return tree_fold(
            states, identity, partial(self.join, element_axis=element_axis)
        )

    # ---- op application (CmRDT) --------------------------------------

    def rm_parked(self, s, rm_clock, ids):
        """``Op::Rm { clock, keyset }`` at THIS level: kill covered leaf
        dots of the listed keys now, park if the clock runs ahead, scrub
        newly-bottomed children. Returns ``(s, overflow)``."""
        rm_clock = jnp.asarray(rm_clock, self.top(s).dtype)
        killed = self.core.replay_keylist(
            s[0],
            rm_clock[..., None, :],
            ids[..., None, :],
            jnp.ones(rm_clock.shape[:-1] + (1,), bool),
            self.span,
        )
        ahead = ~jnp.all(rm_clock <= self.top(s), axis=-1)
        kcl, kidx, kdvalid, overflow = _park_list(
            s[1], s[2], s[3], rm_clock, ids, ahead
        )
        out = self.scrub_self(self._make(killed, kcl, kidx, kdvalid))
        return out, overflow

    def rm_route(self, s, levels_down: int, rm_clock, ids):
        """Route a keyset-remove ``levels_down`` levels into the core
        (0 = this level). ``ids`` are key ids AT THE TARGET LEVEL."""
        if levels_down == 0:
            return self.rm_parked(s, rm_clock, ids)
        core, overflow = self.core.rm_route(s[0], levels_down - 1, rm_clock, ids)
        return self._make(core, *self._bufs(s)), overflow

    def apply_up_add(self, s, actor, counter, eids):
        """``Op::Up { dot, key, Add { members } }`` — member adds inside
        one (or several) children, all witnessed by one minted dot.
        ``eids`` are FLATTENED leaf ids (key·span + member). Dup-drop on
        a seen dot (oracle apply returns early). Returns (s, overflow)."""
        counter = jnp.asarray(counter).astype(self.top(s).dtype)
        seen = self.top(s)[..., actor] >= counter
        leaf0 = self.leaf(s)
        new_leaf, overflow = sp.apply_add(leaf0, actor, counter, eids)
        out = _graft_leaf(self, s, new_leaf)
        out = self.settle_self(out)
        keep = lambda old, new: jnp.where(
            seen.reshape(seen.shape + (1,) * (new.ndim - seen.ndim)), old, new
        )
        out = jax.tree.map(keep, s, out)
        return out, overflow & ~seen

    def apply_up_rm(self, s, actor, counter, rm_clock, ids,
                    levels_down: int):
        """``Op::Up^j { dot, …, Rm { clock, keyset } }`` — a
        keyset-remove routed ``levels_down`` levels in (0 = this level's
        buffer; for a member-remove inside a child pass levels_down =
        depth so it lands on the LEAF buffer with flattened ids),
        witnessed by one minted dot. Returns (s, overflow)."""
        counter = jnp.asarray(counter).astype(self.top(s).dtype)
        seen = self.top(s)[..., actor] >= counter
        rmed, overflow = self.rm_route(s, levels_down, rm_clock, ids)
        out = self.settle_self(self.witness(rmed, actor, counter))
        keep = lambda old, new: jnp.where(
            seen.reshape(seen.shape + (1,) * (new.ndim - seen.ndim)), old, new
        )
        out = jax.tree.map(keep, s, out)
        return out, overflow & ~seen


def _graft_leaf(level, s, new_leaf):
    """Rebuild the nest state with a replaced leaf slab."""
    if not isinstance(level.core, SparseNestLevel):  # any leaf adapter
        return level._make(new_leaf, *level._bufs(s))
    inner = _graft_leaf(level.core, s[0], new_leaf)
    return level._make(inner, *level._bufs(s))


def _sparse_identity_like(identity):
    """Fix -1 pad conventions on a zeros-built identity pytree."""
    def fix(node):
        if isinstance(node, SparseOrswotState):
            return node._replace(
                eid=jnp.full_like(node.eid, -1),
                didx=jnp.full_like(node.didx, -1),
            )
        if isinstance(node, tuple) and hasattr(node, "_fields"):
            if "kid" in node._fields:  # sparse register-map cell table
                return node._replace(
                    kid=jnp.full_like(node.kid, -1),
                    kidx=jnp.full_like(node.kidx, -1),
                )
            fixed = fix(node[0])
            return type(node)(
                fixed, node[1], jnp.full_like(node[2], -1), node[3]
            )
        return node

    return fix(identity)


def _park_list(kcl, kidx, kdvalid, rm_clock, ids, ahead):
    """Park (clock, id-list) into the bounded slot table: union onto an
    equal-clock slot when the canonical union fits, else claim a free
    slot (the list flavor of ops/orswot._park_remove — same logic as
    sparse_orswot.apply_rm's parking tail). Returns
    ``(kcl, kidx, kdvalid, overflow)``."""
    q = kidx.shape[-1]
    w = ids.shape[-1]
    assert w <= q, "rm op id-list width must fit the buffer lane"
    same = kdvalid & jnp.all(kcl == rm_clock[None, :], axis=-1)
    merged = _canon_rmlist(
        jnp.concatenate(
            [kidx, jnp.broadcast_to(ids, (kidx.shape[0], w))], axis=-1
        )
    )
    fits = jnp.sum(merged >= 0, axis=-1) <= q
    use_same = same & fits
    has_same = jnp.any(use_same)
    free = ~kdvalid
    has_free = jnp.any(free)
    slot = jnp.where(has_same, jnp.argmax(use_same), jnp.argmax(free))
    park = ahead & (has_same | has_free)
    overflow = ahead & ~has_same & ~has_free
    onehot = jax.nn.one_hot(slot, kdvalid.shape[-1], dtype=bool) & park
    fresh = _canon_rmlist(jnp.pad(ids, (0, q - w), constant_values=-1))
    new_list = jnp.where(has_same, merged[slot][:q], fresh)
    kcl = jnp.where(onehot[:, None], rm_clock[None, :], kcl)
    kidx = jnp.where(onehot[:, None], new_list[None, :], kidx)
    kdvalid = kdvalid | onehot
    return kcl, kidx, kdvalid, overflow


# ---- the concrete depth-2 flavor: sparse Map<K, Orswot> ------------------

def level_map_orswot(span: int) -> SparseNestLevel:
    """``Map<K, Orswot>`` over a member capacity of ``span`` leaf ids
    per key (the universe bound is K·span·A < 2^31)."""
    return SparseNestLevel(SPARSE_LEAF, span)


def empty_map_orswot(
    span: int,
    dot_cap: int,
    n_actors: int,
    deferred_cap: int = 4,
    rm_width: int = 8,
    key_deferred_cap: int = 4,
    key_rm_width: int = 8,
    batch: tuple = (),
) -> SparseNestState:
    """The join identity for sparse ``Map<K, Orswot>``."""
    lvl = level_map_orswot(span)
    return lvl.empty(
        sp.empty(dot_cap, n_actors, deferred_cap, rm_width, batch=batch),
        n_actors, key_deferred_cap, key_rm_width, batch=batch,
    )


# ---- static-analysis registration (crdt_tpu.analysis) --------------------

def _law_states():
    """Sparse ``Map<K, Orswot>`` (span 2): member adds, leaf-routed
    member-removes, and covered/ahead key-removes with headroom."""
    lvl = level_map_orswot(2)
    cl = lambda x, y: jnp.array([x, y], DTYPE)
    ids = lambda *xs: jnp.array(list(xs) + [-1] * (4 - len(xs)), jnp.int32)
    mk = lambda: empty_map_orswot(
        2, 8, 2, deferred_cap=3, rm_width=4,
        key_deferred_cap=3, key_rm_width=4,
    )
    e = mk()
    a1, _ = lvl.apply_up_add(e, 0, jnp.uint32(1), ids(0))        # key 0, member 0
    a2, _ = lvl.apply_up_add(a1, 0, jnp.uint32(2), ids(2, 3))    # key 1, both members
    b1, _ = lvl.apply_up_add(e, 1, jnp.uint32(1), ids(1, 2))
    mr, _ = lvl.apply_up_rm(a2, 0, jnp.uint32(3), cl(1, 0), ids(0), levels_down=1)
    kr1, _ = lvl.rm_parked(b1, cl(0, 1), ids(0))   # covered key rm
    kr2, _ = lvl.rm_parked(a1, cl(0, 2), ids(1))   # ahead: parks
    return [e, a1, a2, b1, mr, kr1, kr2]


def _law_canon(s: SparseNestState) -> SparseNestState:
    from ..analysis.canon import canon_epochs
    from .sparse_orswot import _law_canon as _canon_leaf

    kcl, kidx, kdvalid = canon_epochs(s.kcl, s.kidx, s.kdvalid, payload_fill=-1)
    return SparseNestState(
        core=_canon_leaf(s.core), kcl=kcl, kidx=kidx, kdvalid=kdvalid,
    )


def _law_join(a, b):
    return level_map_orswot(2).join(a, b)


@jax.jit
def compact(state: SparseNestState, frontier: jax.Array):
    """Causal-stability compaction (reclaim/) for any sparse nest:
    compact the core slab (recursing through inner levels down to the
    ORSWOT segment table or the register-map cell table), then retire
    this level's stable parked keylist slots and scrub their stale
    payload. Returns ``(state, freed_slots, freed_bytes)``."""
    from ..reclaim.compaction import retire_epochs
    from ..reclaim.frontier import top_of

    core = state.core
    if isinstance(core, SparseNestState):
        core, n0, b0 = compact(core, frontier)
    elif hasattr(core, "eid"):
        core, n0, b0 = sp.compact(core, frontier)
    else:  # the sparse register-map cell table (ops/sparse_mvmap.py)
        from .sparse_mvmap import compact as _smv_compact

        core, n0, b0 = _smv_compact(core, frontier)
    kcl, kidx, kdvalid, n1, b1 = retire_epochs(
        state.kcl, state.kidx, state.kdvalid, top_of(state), frontier,
        payload_fill=-1,
    )
    return (
        type(state)(core, kcl, kidx, kdvalid),
        n0 + n1,
        b0 + b1,
    )


def _observe(s: SparseNestState):
    """The observable read: the LEAF slab's read (membership ids for an
    ORSWOT leaf, (key, value) cells for a register-map leaf) — the
    causal-composition rule makes every outer level's read a projection
    of it."""
    leaf = s
    while isinstance(leaf, SparseNestState):
        leaf = leaf.core
    if hasattr(leaf, "eid"):
        from .sparse_orswot import _observe as _leaf_observe

        return _leaf_observe(leaf)
    from .sparse_mvmap import _observe as _leaf_observe

    return _leaf_observe(leaf)


def _decomp_split(s: SparseNestState):
    """Decomposition granularity (delta_opt/): one δ lane per LEAF-slab
    table lane (recursing through inner levels down to the ORSWOT
    segment table or the register-map cell table); every level's parked
    keylist buffer plus the leaf residual ride whole. The level stack is
    encoded positionally in the residual, so ``_decomp_unsplit`` can
    rebuild the nest without a type tag (leaf arity disambiguates the
    two leaf slabs)."""
    levels = []
    core = s
    while isinstance(core, SparseNestState):
        levels.append((core.kcl, core.kidx, core.kdvalid))
        core = core.core
    if hasattr(core, "eid"):
        from .sparse_orswot import _decomp_split as _leaf_split
    else:
        from .sparse_mvmap import _decomp_split as _leaf_split
    rows, leaf_res = _leaf_split(core)
    return rows, (tuple(levels), leaf_res)


def _decomp_unsplit(rows, res) -> SparseNestState:
    levels, leaf_res = res
    if len(rows) == 4:  # (eid, act, ctr, valid) — the ORSWOT leaf
        from .sparse_orswot import _decomp_unsplit as _leaf_unsplit
    else:  # 6 planes — the register-map cell leaf (ops/sparse_mvmap.py)
        from .sparse_mvmap import _decomp_unsplit as _leaf_unsplit
    core = _leaf_unsplit(rows, leaf_res)
    for kcl, kidx, kdvalid in reversed(levels):
        core = SparseNestState(core=core, kcl=kcl, kidx=kidx, kdvalid=kdvalid)
    return core


from ..analysis.registry import (  # noqa: E402
    register_compactor,
    register_decomposition,
    register_merge,
)

register_merge(
    "sparse_nested_map", module=__name__, join=_law_join,
    states=_law_states, canon=_law_canon,
)
def _top_of(s):
    from ..reclaim.frontier import top_of

    return top_of(s)


register_compactor(
    "sparse_nested_map", module=__name__, compact=compact,
    observe=_observe, top_of=_top_of,
)
register_decomposition(
    "sparse_nested_map", module=__name__, split=_decomp_split,
    unsplit=_decomp_unsplit,
)
