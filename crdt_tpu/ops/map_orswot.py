"""Dense batched kernels for ``Map<K, Orswot<M>>`` — slab composition.

Oracle: ``crdt_tpu.pure.map.Map`` with ``Orswot`` children (reference:
src/map.rs ``Map<K, V: Val<A>, A>`` with an orswot value type —
SURVEY.md §3 row 11's ``V: Val<A>`` genericity). Under the causal-
composition rule (pure/map.py module docstring) every child orswot's top
clock equals the map's top clock, so the child tops need no storage and
the composed state is *structurally an ORSWOT over the product space
K × M*: one birth-clock slab ``ctr[..., K*M, A]`` under one top. This is
SURVEY.md §7.1's "nesting by composition of slabs, not recursion at
trace time": the nested join IS the flat orswot join over a bigger
element axis — no new kernel math, no trace-time recursion.

What *is* new is the second deferred buffer: outer key-removes
(``Op::Rm { clock, keyset }``) park masks over K while inner orswot
removes (routed via ``Op::Up``) park masks over K×M. Both replay with
the same covered-dot rule, but they must stay distinct so device state
round-trips losslessly to the oracle's ``map.deferred`` (keysets) vs
``child.deferred`` (membersets) — the A/B gate in
tests/test_models_map_nested.py checks exactly that.

State: ``core`` is a plain ``OrswotState`` with E = K*M (top, ctr, and
the inner deferred buffer); ``kdcl/kdkeys/kdvalid`` are the outer parked
keyset-removes.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from . import orswot as core_ops
from .orswot import OrswotState, _apply_parked, _park_remove
from .outer_level import concat_outer, settle_outer_level

DTYPE = jnp.uint32


class MapOrswotState(NamedTuple):
    """A (possibly batched) dense Map<K, Orswot<M>> replica (pytree)."""

    core: OrswotState  # top [..., A]; ctr [..., K*M, A]; inner deferred
    kdcl: jax.Array    # [..., D, A]  outer parked rm clocks
    kdkeys: jax.Array  # [..., D, K]  outer parked keysets
    kdvalid: jax.Array # [..., D]


def empty(
    n_keys: int,
    n_members: int,
    n_actors: int,
    deferred_cap: int = 4,
    batch: tuple = (),
) -> MapOrswotState:
    """The join identity."""
    return MapOrswotState(
        core=core_ops.empty(
            n_keys * n_members, n_actors, deferred_cap, batch=batch
        ),
        kdcl=jnp.zeros((*batch, deferred_cap, n_actors), DTYPE),
        kdkeys=jnp.zeros((*batch, deferred_cap, n_keys), bool),
        kdvalid=jnp.zeros((*batch, deferred_cap), bool),
    )


def _n_keys(state: MapOrswotState) -> int:
    return state.kdkeys.shape[-1]


def _expand_keys(state: MapOrswotState, key_mask: jax.Array) -> jax.Array:
    """[..., K] key mask → [..., K*M] element mask (all members)."""
    m = state.core.ctr.shape[-2] // _n_keys(state)
    return jnp.repeat(key_mask, m, axis=-1)


def _replay_outer(state: MapOrswotState) -> MapOrswotState:
    """Replay parked keyset-removes against the slab, then drop slots the
    top has caught up to (the oracle's ``_apply_deferred``)."""
    emask = _expand_keys(state, state.kdkeys)
    ctr = _apply_parked(state.core.ctr, state.kdcl, emask, state.kdvalid)
    still_ahead = ~jnp.all(
        state.kdcl <= state.core.top[..., None, :], axis=-1
    )
    kdvalid = state.kdvalid & still_ahead
    return MapOrswotState(
        core=state.core._replace(ctr=ctr),
        kdcl=jnp.where(kdvalid[..., None], state.kdcl, 0),
        kdkeys=state.kdkeys & kdvalid[..., None],
        kdvalid=kdvalid,
    )


def _any_slots(mask: jax.Array, element_axis) -> jax.Array:
    """Per-slot liveness ``any(mask, -1)``, reduced across element
    shards when the mask's last axis is sharded (``element_axis`` set,
    inside shard_map): a slot's keys may live in other shards, and slot
    validity must stay replicated across them."""
    live = jnp.any(mask, axis=-1)
    if element_axis is not None:
        from jax import lax

        live = lax.psum(live.astype(jnp.int32), element_axis) > 0
    return live


def _scrub_dead_keys(state: MapOrswotState, element_axis=None) -> MapOrswotState:
    """A memberless child is deleted by the oracle — together with its
    parked inner removes (``Orswot.is_bottom`` counts live members only,
    and ``Map`` drops bottom children after every apply/merge). Mirror:
    clear inner parked masks on keys holding no live dot, drop slots
    whose masks empty out. Outer parked keyset-removes belong to the map
    itself and are never scrubbed.

    Key liveness itself is shard-local (element shards align to whole
    key blocks — K*M is sharded in multiples of M), only the slot
    liveness reduces across shards (``_any_slots``)."""
    k = _n_keys(state)
    m = state.core.ctr.shape[-2] // k
    alive = jnp.any(
        state.core.ctr.reshape(*state.core.ctr.shape[:-2], k, m, -1) > 0,
        axis=(-2, -1),
    )  # [..., K]
    acols = jnp.repeat(alive, m, axis=-1)  # [..., K*M]
    dmask = state.core.dmask & acols[..., None, :]
    dvalid = state.core.dvalid & _any_slots(dmask, element_axis)
    return state._replace(
        core=state.core._replace(
            dcl=jnp.where(dvalid[..., None], state.core.dcl, 0),
            dmask=dmask & dvalid[..., None],
            dvalid=dvalid,
        )
    )


@partial(jax.jit, static_argnames=("element_axis",))
def join(a: MapOrswotState, b: MapOrswotState, element_axis=None):
    """Pairwise lattice join: the flat orswot join over K*M elements plus
    the union/replay/compaction of the outer keyset buffer. Returns
    ``(state, overflow[2])`` — lanes [inner-deferred, outer-deferred].
    ``element_axis`` names the mesh axis the key/element dimension is
    sharded over when joining inside shard_map (see ``_any_slots``).

    (The core join's inner-overflow flag is conservative here: it counts
    parked slots before dead-key scrubbing, so a buffer transiently full
    of dead-key slots can flag where the oracle would not.)"""
    core, inner_of = core_ops.join(a.core, b.core)

    state = MapOrswotState(
        core,
        *concat_outer(
            (a.kdcl, a.kdkeys, a.kdvalid), (b.kdcl, b.kdkeys, b.kdvalid)
        ),
    )
    state, outer_of = settle_outer_level(
        state,
        a.kdcl.shape[-2],
        get_bufs=lambda s: (s.kdcl, s.kdkeys, s.kdvalid),
        with_bufs=lambda s, cl, ks, v: s._replace(kdcl=cl, kdkeys=ks, kdvalid=v),
        replay=_replay_outer,
        scrub=_scrub_dead_keys,
        element_axis=element_axis,
    )
    return state, jnp.stack([jnp.any(inner_of), outer_of])


def fold(states: MapOrswotState, element_axis=None):
    """Log-tree fold of a replica batch (leading axis)."""
    from .lattice import tree_fold

    k = states.kdkeys.shape[-1]
    m = states.core.ctr.shape[-2] // k
    identity = empty(
        k, m, states.core.top.shape[-1], states.kdcl.shape[-2]
    )
    return tree_fold(states, identity, partial(join, element_axis=element_axis))


@jax.jit
def apply_member_add(
    state: MapOrswotState,
    actor: jax.Array,
    counter: jax.Array,
    key: jax.Array,
    member_mask: jax.Array,
) -> MapOrswotState:
    """``Op::Up { dot, key, op: Add { dot, members } }`` — the inner add
    shares the Up's dot (both minted from one AddCtx). Dup dots drop the
    whole op (pure/map.py ``apply``); parked removes replay after."""
    k = _n_keys(state)
    m = state.core.ctr.shape[-2] // k
    emask = (jax.nn.one_hot(key, k, dtype=bool)[..., :, None] & member_mask[..., None, :]).reshape(
        *member_mask.shape[:-1], k * m
    )
    core = core_ops.apply_add(state.core, actor, counter, emask)
    return _scrub_dead_keys(_replay_outer(state._replace(core=core)))


@jax.jit
def apply_member_rm(
    state: MapOrswotState,
    actor: jax.Array,
    counter: jax.Array,
    key: jax.Array,
    rm_clock: jax.Array,
    member_mask: jax.Array,
):
    """``Op::Up { dot, key, op: Rm { clock, members } }`` — an inner
    orswot remove routed through the map: kill covered dots of the key's
    masked members (parking in the INNER buffer if ahead), then witness
    the Up's dot on the top clock. Returns ``(state, overflow)``."""
    counter = counter.astype(state.core.top.dtype)
    seen = state.core.top[..., actor] >= counter
    k = _n_keys(state)
    m = state.core.ctr.shape[-2] // k
    emask = (
        jax.nn.one_hot(key, k, dtype=bool)[..., :, None]
        & member_mask[..., None, :]
    ).reshape(*member_mask.shape[:-1], k * m)
    rmed, overflow = core_ops.apply_rm(state.core, rm_clock, emask)
    top = rmed.top.at[..., actor].max(counter)
    # Advancing the top may un-park inner and outer removes: replay both.
    ctr = _apply_parked(rmed.ctr, rmed.dcl, rmed.dmask, rmed.dvalid)
    still = ~jnp.all(rmed.dcl <= top[..., None, :], axis=-1)
    core = rmed._replace(top=top, ctr=ctr, dvalid=rmed.dvalid & still)
    out = _scrub_dead_keys(_replay_outer(state._replace(core=core)))
    # A dup dot drops the whole Up (pure/map.py ``apply`` returns early —
    # nothing applied, nothing parked).
    bshape = lambda new: seen.reshape(seen.shape + (1,) * (new.ndim - seen.ndim))
    out = jax.tree.map(
        lambda old, new: jnp.where(bshape(new), old, new), state, out
    )
    return out, overflow & ~seen


@jax.jit
def apply_key_rm(state: MapOrswotState, rm_clock: jax.Array, key_mask: jax.Array):
    """``Op::Rm { clock, keyset }`` (reference: src/map.rs
    ``apply_keyset_rm``): kill covered dots across the masked keys' whole
    member rows now; park in the OUTER buffer if the clock is ahead.
    Returns ``(state, overflow)``."""
    rm_clock = jnp.asarray(rm_clock, state.core.top.dtype)
    emask = _expand_keys(state, key_mask)
    dominated = emask[..., :, None] & (state.core.ctr <= rm_clock[..., None, :])
    ctr = jnp.where(dominated, jnp.zeros_like(state.core.ctr), state.core.ctr)

    ahead = ~jnp.all(rm_clock <= state.core.top, axis=-1)
    kdcl, kdkeys, kdvalid, overflow = _park_remove(
        state.kdcl, state.kdkeys, state.kdvalid, rm_clock, key_mask, ahead
    )
    out = _scrub_dead_keys(
        MapOrswotState(
            core=state.core._replace(ctr=ctr),
            kdcl=kdcl,
            kdkeys=kdkeys,
            kdvalid=kdvalid,
        )
    )
    return out, overflow
