"""Dense batched kernels for ``Map<K, Orswot<M>>`` — slab composition.

Oracle: ``crdt_tpu.pure.map.Map`` with ``Orswot`` children (reference:
src/map.rs ``Map<K, V: Val<A>, A>`` with an orswot value type —
SURVEY.md §3 row 11's ``V: Val<A>`` genericity). Under the causal-
composition rule (pure/map.py module docstring) every child orswot's top
clock equals the map's top clock, so the child tops need no storage and
the composed state is *structurally an ORSWOT over the product space
K × M*: one birth-clock slab ``ctr[..., K*M, A]`` under one top. This is
SURVEY.md §7.1's "nesting by composition of slabs, not recursion at
trace time": the nested join IS the flat orswot join over a bigger
element axis — no new kernel math, no trace-time recursion.

What *is* new is the second deferred buffer: outer key-removes
(``Op::Rm { clock, keyset }``) park masks over K while inner orswot
removes (routed via ``Op::Up``) park masks over K×M. Both replay with
the same covered-dot rule, but they must stay distinct so device state
round-trips losslessly to the oracle's ``map.deferred`` (keysets) vs
``child.deferred`` (membersets) — the A/B gate in
tests/test_models_map_nested.py checks exactly that.

All of that is ONE application of the nesting induction step, so this
module is now an instantiation of ``ops.nest.NestLevel`` around the
orswot leaf slab; only the CmRDT op-routing signatures (which flatten
(key, member) coordinates) are flavor-specific.

State: ``core`` is a plain ``OrswotState`` with E = K*M (top, ctr, and
the inner deferred buffer); ``kdcl/kdkeys/kdvalid`` are the outer parked
keyset-removes.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from . import orswot as core_ops
from .nest import ORSWOT, DTYPE, NestLevel, _any_slots  # noqa: F401 (re-export)


class MapOrswotState(NamedTuple):
    """A (possibly batched) dense Map<K, Orswot<M>> replica (pytree)."""

    core: core_ops.OrswotState  # top [..., A]; ctr [..., K*M, A]; inner deferred
    kdcl: jax.Array    # [..., D, A]  outer parked rm clocks
    kdkeys: jax.Array  # [..., D, K]  outer parked keysets
    kdvalid: jax.Array # [..., D]


LEVEL = NestLevel(ORSWOT, MapOrswotState)


def empty(
    n_keys: int,
    n_members: int,
    n_actors: int,
    deferred_cap: int = 4,
    batch: tuple = (),
) -> MapOrswotState:
    """The join identity."""
    return LEVEL.empty(
        core_ops.empty(n_keys * n_members, n_actors, deferred_cap, batch=batch),
        n_keys, n_actors, deferred_cap, batch,
    )


def _n_keys(state: MapOrswotState) -> int:
    return state.kdkeys.shape[-1]


def _expand_keys(state: MapOrswotState, key_mask: jax.Array) -> jax.Array:
    """[..., K] key mask → [..., K*M] element mask (all members)."""
    return LEVEL.expand(state, key_mask)


# Shared-level entry points (delta flavors and tests use these names).
_replay_outer = LEVEL.replay_outer
_scrub_dead_keys = LEVEL.scrub_self


@partial(jax.jit, static_argnames=("element_axis",))
def join(a: MapOrswotState, b: MapOrswotState, element_axis=None):
    """Pairwise lattice join: the flat orswot join over K*M elements plus
    the union/replay/compaction of the outer keyset buffer. Returns
    ``(state, overflow[2])`` — lanes [inner-deferred, outer-deferred].
    ``element_axis`` names the mesh axis the key/element dimension is
    sharded over when joining inside shard_map (see ``_any_slots``).

    (The core join's inner-overflow flag is conservative here: it counts
    parked slots before dead-key scrubbing, so a buffer transiently full
    of dead-key slots can flag where the oracle would not.)"""
    return LEVEL.join(a, b, element_axis)


def fold(states: MapOrswotState, element_axis=None, prefer: str = "auto"):
    """Replica-batch fold with backend-appropriate dispatch: the fused
    one-HBM-pass Pallas kernel on TPU backends, the jnp log-tree fold
    elsewhere (``prefer`` = "auto"|"fused"|"tree" as in
    pallas_kernels.fold_auto)."""
    from .pallas_kernels import fold_auto_level

    return fold_auto_level(LEVEL, states, prefer, element_axis)


@jax.jit
def apply_member_add(
    state: MapOrswotState,
    actor: jax.Array,
    counter: jax.Array,
    key: jax.Array,
    member_mask: jax.Array,
) -> MapOrswotState:
    """``Op::Up { dot, key, op: Add { dot, members } }`` — the inner add
    shares the Up's dot (both minted from one AddCtx). Dup dots drop the
    whole op (pure/map.py ``apply``); parked removes replay after."""
    k = _n_keys(state)
    m = state.core.ctr.shape[-2] // k
    emask = (
        jax.nn.one_hot(key, k, dtype=bool)[..., :, None]
        & member_mask[..., None, :]
    ).reshape(*member_mask.shape[:-1], k * m)
    core = core_ops.apply_add(state.core, actor, counter, emask)
    return LEVEL.cascade(state, core)


@jax.jit
def apply_member_rm(
    state: MapOrswotState,
    actor: jax.Array,
    counter: jax.Array,
    key: jax.Array,
    rm_clock: jax.Array,
    member_mask: jax.Array,
):
    """``Op::Up { dot, key, op: Rm { clock, members } }`` — an inner
    orswot remove routed through the map: kill covered dots of the key's
    masked members (parking in the INNER buffer if ahead), then witness
    the Up's dot on the top clock. Returns ``(state, overflow)``."""
    k = _n_keys(state)
    m = state.core.ctr.shape[-2] // k
    emask = (
        jax.nn.one_hot(key, k, dtype=bool)[..., :, None]
        & member_mask[..., None, :]
    ).reshape(*member_mask.shape[:-1], k * m)
    return LEVEL.apply_up_rm(
        state, actor, counter, rm_clock, emask, levels_down=1
    )


@jax.jit
def apply_key_rm(state: MapOrswotState, rm_clock: jax.Array, key_mask: jax.Array):
    """``Op::Rm { clock, keyset }`` (reference: src/map.rs
    ``apply_keyset_rm``): kill covered dots across the masked keys' whole
    member rows now; park in the OUTER buffer if the clock is ahead.
    Returns ``(state, overflow)``."""
    return LEVEL.rm_parked(state, rm_clock, key_mask)


# ---- static-analysis registration (crdt_tpu.analysis) --------------------

def _law_states():
    """Member adds, routed member-removes, and covered/ahead key-removes
    over 2 keys × 2 members × 2 actors with deferred headroom."""
    cl = lambda x, y: jnp.array([x, y], DTYPE)
    m0 = jnp.array([True, False])
    mb = jnp.array([True, True])
    k0 = jnp.array([True, False])
    kb = jnp.array([True, True])
    e = empty(2, 2, 2, deferred_cap=4)
    a1 = apply_member_add(e, 0, jnp.uint32(1), 0, m0)
    a2 = apply_member_add(a1, 0, jnp.uint32(2), 1, mb)
    b1 = apply_member_add(e, 1, jnp.uint32(1), 0, mb)
    mr, _ = apply_member_rm(a2, 0, jnp.uint32(3), 0, cl(1, 0), m0)
    kr1, _ = apply_key_rm(b1, cl(0, 1), k0)   # covered key rm
    kr2, _ = apply_key_rm(a1, cl(0, 2), kb)   # ahead: parks in outer buffer
    return [e, a1, a2, b1, mr, kr1, kr2]


def _law_canon(s: MapOrswotState) -> MapOrswotState:
    from ..analysis.canon import canon_epochs
    from .orswot import _law_canon as _canon_core

    kdcl, kdkeys, kdvalid = canon_epochs(s.kdcl, s.kdkeys, s.kdvalid)
    return MapOrswotState(
        core=_canon_core(s.core), kdcl=kdcl, kdkeys=kdkeys, kdvalid=kdvalid,
    )


@jax.jit
def compact(state: MapOrswotState, frontier: jax.Array):
    """Causal-stability compaction (reclaim/): retire stable parked
    keyset-removes at the OUTER level, then compact the flat orswot
    core (its own parked buffer + dead-slot scrub) — the dead-key scrub
    rides the core's canonical zeroing, since a dead key is exactly an
    all-dead member row of the product slab. Returns
    ``(state, freed_slots, freed_bytes)``."""
    from ..reclaim.compaction import retire_epochs

    core, n0, b0 = core_ops.compact(state.core, frontier)
    kdcl, kdkeys, kdvalid, n1, b1 = retire_epochs(
        state.kdcl, state.kdkeys, state.kdvalid, state.core.top, frontier
    )
    return (
        MapOrswotState(core=core, kdcl=kdcl, kdkeys=kdkeys, kdvalid=kdvalid),
        n0 + n1,
        b0 + b1,
    )


def _observe(s: MapOrswotState):
    """The observable read: the K×M membership mask (key present iff
    any member row lives — the causal-composition read)."""
    return core_ops._present(s.core.ctr)


def _decomp_split(s: MapOrswotState):
    """Decomposition granularity (delta_opt/): one δ lane per flat
    (key, member) birth-clock row of the core slab; top + both parked
    buffers residual."""
    c = s.core
    return (c.ctr,), (
        c.top, c.dcl, c.dmask, c.dvalid, s.kdcl, s.kdkeys, s.kdvalid,
    )


def _decomp_unsplit(rows, res) -> MapOrswotState:
    (ctr,) = rows
    top, dcl, dmask, dvalid, kdcl, kdkeys, kdvalid = res
    core = core_ops.OrswotState(
        top=top, ctr=ctr, dcl=dcl, dmask=dmask, dvalid=dvalid
    )
    return MapOrswotState(core=core, kdcl=kdcl, kdkeys=kdkeys, kdvalid=kdvalid)


from ..analysis.registry import (  # noqa: E402
    register_compactor,
    register_decomposition,
    register_merge,
)

register_merge(
    "map_orswot", module=__name__, join=join, states=_law_states,
    canon=_law_canon,
)
register_compactor(
    "map_orswot", module=__name__, compact=compact, observe=_observe,
    top_of=lambda s: s.core.top,
)
register_decomposition(
    "map_orswot", module=__name__, split=_decomp_split,
    unsplit=_decomp_unsplit,
)
