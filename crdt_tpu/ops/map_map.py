"""Dense batched kernels for ``Map<K1, Map<K2, MVReg>>`` — nested maps
by slab flattening.

Oracle: ``crdt_tpu.pure.map.Map`` with nested ``Map`` children
(reference: src/map.rs arbitrary ``V: Val<A>`` nesting depth). Under the
causal-composition rule every child map's top equals the outer top, so
the two key levels flatten into ONE ``ops.map.MapState`` over the
K1 × K2 product key space (the MVReg content slab and its semantics are
reused wholesale) — SURVEY.md §7.1's slab composition instead of
trace-time recursion.

The flat state's own deferred buffer carries the INNER maps' parked
keyset-removes (masks over K1×K2, routed via ``Op::Up``); a second
buffer carries the OUTER map's parked removes (masks over K1). They
replay with the same covered-dot rule but must stay distinct for
lossless round-trips (outer ``map.deferred`` vs per-child
``child.deferred``), and inner parked removes die with a bottomed child
(``Map.is_bottom`` counts live entries only) — the dead-key scrub.

This is ONE application of the nesting induction step around the
``Map<K, MVReg>`` leaf slab, instantiated via ``ops.nest.NestLevel``;
only the CmRDT op-routing signatures are flavor-specific.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from . import map as core_ops
from .map import MapState
from .nest import MAP_MVREG, NestLevel


class NestedMapState(NamedTuple):
    """A (possibly batched) dense Map<K1, Map<K2, MVReg>> replica."""

    m: MapState        # flat over K1*K2; its deferred = inner parked rms
    odcl: jax.Array    # [..., D, A]   outer parked rm clocks
    odkeys: jax.Array  # [..., D, K1]  outer parked keysets
    odvalid: jax.Array # [..., D]


LEVEL = NestLevel(MAP_MVREG, NestedMapState)


def empty(
    n_keys1: int,
    n_keys2: int,
    n_actors: int,
    sibling_cap: int = 4,
    deferred_cap: int = 4,
    batch: tuple = (),
) -> NestedMapState:
    """The join identity."""
    return LEVEL.empty(
        core_ops.empty(
            n_keys1 * n_keys2, n_actors, sibling_cap, deferred_cap, batch=batch
        ),
        n_keys1, n_actors, deferred_cap, batch,
    )


def _n_keys1(state: NestedMapState) -> int:
    return state.odkeys.shape[-1]


_replay_outer = LEVEL.replay_outer
_scrub_dead_keys = LEVEL.scrub_self


@partial(jax.jit, static_argnames=("element_axis",))
def join(a: NestedMapState, b: NestedMapState, element_axis=None):
    """Pairwise lattice join: the flat map join over K1*K2 keys plus the
    outer buffer union/replay/compaction and the dead-key scrub. Returns
    ``(state, overflow[3])`` — [sibling-slab, inner-deferred,
    outer-deferred] (slab/inner lanes conservative as in ops.map).
    ``element_axis`` names the mesh axis the key dimension is sharded
    over when joining inside shard_map."""
    return LEVEL.join(a, b, element_axis)


def fold(states: NestedMapState, element_axis=None, prefer: str = "auto"):
    """Replica-batch fold with backend-appropriate dispatch: the fused
    dense-slab Pallas kernel on TPU backends, the jnp log-tree fold
    elsewhere (``prefer`` = "auto"|"fused"|"tree" as in
    pallas_kernels.fold_auto)."""
    from .pallas_kernels import fold_auto_level

    return fold_auto_level(LEVEL, states, prefer, element_axis)


@jax.jit
def apply_put(
    state: NestedMapState,
    actor: jax.Array,
    counter: jax.Array,
    key1: jax.Array,
    key2: jax.Array,
    put_clock: jax.Array,
    val: jax.Array,
):
    """``Op::Up { dot, k1, op: Up { dot, k2, op: Put } }`` — both Up
    levels share the one minted dot. Returns ``(state, overflow)``."""
    k2n = state.m.dkeys.shape[-1] // _n_keys1(state)
    flat_key = key1 * k2n + key2
    m, overflow = core_ops.apply_up(
        state.m, actor, counter, flat_key, put_clock, val
    )
    return LEVEL.cascade(state, m), overflow


@jax.jit
def apply_inner_rm(
    state: NestedMapState,
    actor: jax.Array,
    counter: jax.Array,
    key1: jax.Array,
    rm_clock: jax.Array,
    key2_mask: jax.Array,
):
    """``Op::Up { dot, k1, op: Rm { clock, keyset2 } }`` — an inner map
    keyset-remove routed through the outer map: kill covered content at
    (k1, keyset2) (parking in the INNER buffer if ahead), then witness
    the Up's dot. Returns ``(state, overflow)``."""
    k1n = _n_keys1(state)
    k2n = state.m.dkeys.shape[-1] // k1n
    fmask = (
        jax.nn.one_hot(key1, k1n, dtype=bool)[..., :, None]
        & key2_mask[..., None, :]
    ).reshape(*key2_mask.shape[:-1], k1n * k2n)
    return LEVEL.apply_up_rm(
        state, actor, counter, rm_clock, fmask, levels_down=1
    )


@jax.jit
def apply_key1_rm(state: NestedMapState, rm_clock: jax.Array, key1_mask: jax.Array):
    """``Op::Rm { clock, keyset }`` on the outer map: kill covered
    content across the masked K1 rows now; park in the OUTER buffer if
    the clock is ahead. Returns ``(state, overflow)``."""
    return LEVEL.rm_parked(state, rm_clock, key1_mask)


# ---- static-analysis registration (crdt_tpu.analysis) --------------------

def _law_states():
    """Nested puts, routed inner keyset-removes, and covered/ahead outer
    removes over 2×2 keys × 2 actors with headroom."""
    cl = lambda x, y: jnp.array([x, y], jnp.uint32)
    k0 = jnp.array([True, False])
    kb = jnp.array([True, True])
    e = empty(2, 2, 2, sibling_cap=3, deferred_cap=4)
    u1, _ = apply_put(e, 0, jnp.uint32(1), 0, 0, cl(1, 0), 5)
    u2, _ = apply_put(u1, 0, jnp.uint32(2), 1, 1, cl(2, 0), 6)
    v1, _ = apply_put(e, 1, jnp.uint32(1), 0, 1, cl(0, 1), 7)
    ir, _ = apply_inner_rm(u2, 0, jnp.uint32(3), 0, cl(1, 0), kb)
    or1, _ = apply_key1_rm(v1, cl(0, 1), k0)  # covered outer rm
    or2, _ = apply_key1_rm(u1, cl(0, 2), kb)  # ahead: parks in outer buffer
    return [e, u1, u2, v1, ir, or1, or2]


def _law_canon(s: NestedMapState) -> NestedMapState:
    from ..analysis.canon import canon_epochs
    from .map import _law_canon as _canon_core

    odcl, odkeys, odvalid = canon_epochs(s.odcl, s.odkeys, s.odvalid)
    return NestedMapState(
        m=_canon_core(s.m), odcl=odcl, odkeys=odkeys, odvalid=odvalid,
    )


@jax.jit
def compact(state: NestedMapState, frontier: jax.Array):
    """Causal-stability compaction (reclaim/): retire stable parked
    K1 removes at the outer level, then compact the flat map core
    (inner parked buffer + child-slab scrub). Returns
    ``(state, freed_slots, freed_bytes)``."""
    from ..reclaim.compaction import retire_epochs

    m, n0, b0 = core_ops.compact(state.m, frontier)
    odcl, odkeys, odvalid, n1, b1 = retire_epochs(
        state.odcl, state.odkeys, state.odvalid, state.m.top, frontier
    )
    return (
        NestedMapState(m=m, odcl=odcl, odkeys=odkeys, odvalid=odvalid),
        n0 + n1,
        b0 + b1,
    )


def _observe(s: NestedMapState):
    """The observable read: the flat map's per-key live value sets."""
    return core_ops._observe(s.m)


def _decomp_split(s: NestedMapState):
    """Decomposition granularity (delta_opt/): one δ lane per flat
    (k1, k2) content-slot row group; top + both parked levels residual."""
    return s.m.child, (
        s.m.top, s.m.dcl, s.m.dkeys, s.m.dvalid,
        s.odcl, s.odkeys, s.odvalid,
    )


def _decomp_unsplit(rows, res) -> NestedMapState:
    top, dcl, dkeys, dvalid, odcl, odkeys, odvalid = res
    m = MapState(top=top, child=rows, dcl=dcl, dkeys=dkeys, dvalid=dvalid)
    return NestedMapState(m=m, odcl=odcl, odkeys=odkeys, odvalid=odvalid)


from ..analysis.registry import (  # noqa: E402
    register_compactor,
    register_decomposition,
    register_merge,
)

register_merge(
    "map_map", module=__name__, join=join, states=_law_states,
    canon=_law_canon,
)
register_compactor(
    "map_map", module=__name__, compact=compact, observe=_observe,
    top_of=lambda s: s.m.top,
)
register_decomposition(
    "map_map", module=__name__, split=_decomp_split, unsplit=_decomp_unsplit,
)
