"""Dense batched kernels for ``Map<K1, Map<K2, MVReg>>`` — nested maps
by slab flattening.

Oracle: ``crdt_tpu.pure.map.Map`` with nested ``Map`` children
(reference: src/map.rs arbitrary ``V: Val<A>`` nesting depth). Under the
causal-composition rule every child map's top equals the outer top, so
the two key levels flatten into ONE ``ops.map.MapState`` over the
K1 × K2 product key space (the MVReg content slab and its semantics are
reused wholesale) — SURVEY.md §7.1's slab composition instead of
trace-time recursion.

The flat state's own deferred buffer carries the INNER maps' parked
keyset-removes (masks over K1×K2, routed via ``Op::Up``); a second
buffer carries the OUTER map's parked removes (masks over K1). They
replay with the same covered-dot rule but must stay distinct for
lossless round-trips (outer ``map.deferred`` vs per-child
``child.deferred``), and inner parked removes die with a bottomed child
(``Map.is_bottom`` counts live entries only) — the dead-key scrub.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from . import map as core_ops
from .map import MapState, _canon_child, _rm_covered
from .orswot import _park_remove
from .outer_level import concat_outer, settle_outer_level

DTYPE = jnp.uint32


class NestedMapState(NamedTuple):
    """A (possibly batched) dense Map<K1, Map<K2, MVReg>> replica."""

    m: MapState        # flat over K1*K2; its deferred = inner parked rms
    odcl: jax.Array    # [..., D, A]   outer parked rm clocks
    odkeys: jax.Array  # [..., D, K1]  outer parked keysets
    odvalid: jax.Array # [..., D]


def empty(
    n_keys1: int,
    n_keys2: int,
    n_actors: int,
    sibling_cap: int = 4,
    deferred_cap: int = 4,
    batch: tuple = (),
) -> NestedMapState:
    """The join identity."""
    return NestedMapState(
        m=core_ops.empty(
            n_keys1 * n_keys2, n_actors, sibling_cap, deferred_cap, batch=batch
        ),
        odcl=jnp.zeros((*batch, deferred_cap, n_actors), DTYPE),
        odkeys=jnp.zeros((*batch, deferred_cap, n_keys1), bool),
        odvalid=jnp.zeros((*batch, deferred_cap), bool),
    )


def _n_keys1(state: NestedMapState) -> int:
    return state.odkeys.shape[-1]


def _expand1(state: NestedMapState, key1_mask: jax.Array) -> jax.Array:
    """[..., K1] outer key mask → [..., K1*K2] flat key mask."""
    k2 = state.m.dkeys.shape[-1] // _n_keys1(state)
    return jnp.repeat(key1_mask, k2, axis=-1)


def _replay_outer(state: NestedMapState) -> NestedMapState:
    """Replay parked outer keyset-removes against the content slab, then
    drop slots the top has caught up to."""
    tmp = state.m._replace(
        dcl=state.odcl,
        dkeys=_expand1(state, state.odkeys),
        dvalid=state.odvalid,
    )
    replayed = core_ops._apply_parked(tmp)
    still = ~jnp.all(state.odcl <= state.m.top[..., None, :], axis=-1)
    odvalid = state.odvalid & still
    return NestedMapState(
        m=state.m._replace(child=_canon_child(replayed.child)),
        odcl=jnp.where(odvalid[..., None], state.odcl, 0),
        odkeys=state.odkeys & odvalid[..., None],
        odvalid=odvalid,
    )


def _scrub_dead_keys(state: NestedMapState, element_axis=None) -> NestedMapState:
    """A bottomed child map is deleted by the oracle together with its
    parked inner removes (``Map.is_bottom``); clear inner parked masks on
    K1 rows holding no live content, drop emptied slots. The outer
    buffer belongs to the outer map and is never scrubbed.

    K1 liveness is shard-local (element shards align to whole K1
    blocks); slot liveness reduces across shards (``_any_slots``)."""
    from .map_orswot import _any_slots

    k1 = _n_keys1(state)
    k2 = state.m.dkeys.shape[-1] // k1
    alive = jnp.any(
        state.m.child.valid.reshape(*state.m.child.valid.shape[:-2], k1, k2, -1),
        axis=(-2, -1),
    )  # [..., K1]
    acols = jnp.repeat(alive, k2, axis=-1)
    dkeys = state.m.dkeys & acols[..., None, :]
    dvalid = state.m.dvalid & _any_slots(dkeys, element_axis)
    return state._replace(
        m=state.m._replace(
            dcl=jnp.where(dvalid[..., None], state.m.dcl, 0),
            dkeys=dkeys & dvalid[..., None],
            dvalid=dvalid,
        )
    )


@partial(jax.jit, static_argnames=("element_axis",))
def join(a: NestedMapState, b: NestedMapState, element_axis=None):
    """Pairwise lattice join: the flat map join over K1*K2 keys plus the
    outer buffer union/replay/compaction and the dead-key scrub. Returns
    ``(state, overflow[3])`` — [sibling-slab, inner-deferred,
    outer-deferred] (slab/inner lanes conservative as in ops.map).
    ``element_axis`` names the mesh axis the key dimension is sharded
    over when joining inside shard_map."""
    m, mf = core_ops.join(a.m, b.m)  # mf = [sibling, inner-deferred]

    state = NestedMapState(
        m,
        *concat_outer(
            (a.odcl, a.odkeys, a.odvalid), (b.odcl, b.odkeys, b.odvalid)
        ),
    )
    state, outer_of = settle_outer_level(
        state,
        a.odcl.shape[-2],
        get_bufs=lambda s: (s.odcl, s.odkeys, s.odvalid),
        with_bufs=lambda s, cl, ks, v: s._replace(odcl=cl, odkeys=ks, odvalid=v),
        replay=_replay_outer,
        scrub=_scrub_dead_keys,
        element_axis=element_axis,
    )
    return state, jnp.stack([mf[0], mf[1], outer_of])


def fold(states: NestedMapState, element_axis=None):
    """Log-tree fold of a replica batch (leading axis)."""
    from .lattice import tree_fold

    k1 = states.odkeys.shape[-1]
    k2 = states.m.dkeys.shape[-1] // k1
    identity = empty(
        k1, k2,
        states.m.top.shape[-1],
        states.m.child.wact.shape[-1],
        states.odcl.shape[-2],
    )
    return tree_fold(states, identity, partial(join, element_axis=element_axis))


@jax.jit
def apply_put(
    state: NestedMapState,
    actor: jax.Array,
    counter: jax.Array,
    key1: jax.Array,
    key2: jax.Array,
    put_clock: jax.Array,
    val: jax.Array,
):
    """``Op::Up { dot, k1, op: Up { dot, k2, op: Put } }`` — both Up
    levels share the one minted dot. Returns ``(state, overflow)``."""
    k2n = state.m.dkeys.shape[-1] // _n_keys1(state)
    flat_key = key1 * k2n + key2
    m, overflow = core_ops.apply_up(
        state.m, actor, counter, flat_key, put_clock, val
    )
    out = _scrub_dead_keys(_replay_outer(state._replace(m=m)))
    return out, overflow


@jax.jit
def apply_inner_rm(
    state: NestedMapState,
    actor: jax.Array,
    counter: jax.Array,
    key1: jax.Array,
    rm_clock: jax.Array,
    key2_mask: jax.Array,
):
    """``Op::Up { dot, k1, op: Rm { clock, keyset2 } }`` — an inner map
    keyset-remove routed through the outer map: kill covered content at
    (k1, keyset2) (parking in the INNER buffer if ahead), then witness
    the Up's dot. Returns ``(state, overflow)``."""
    counter = counter.astype(state.m.top.dtype)
    seen = state.m.top[..., actor] >= counter
    k1n = _n_keys1(state)
    k2n = state.m.dkeys.shape[-1] // k1n
    fmask = (
        jax.nn.one_hot(key1, k1n, dtype=bool)[..., :, None]
        & key2_mask[..., None, :]
    ).reshape(*key2_mask.shape[:-1], k1n * k2n)
    rmed, overflow = core_ops.apply_rm(state.m, rm_clock, fmask)
    top = rmed.top.at[..., actor].max(counter)
    m = core_ops._drop_stale_deferred(
        core_ops._apply_parked(rmed._replace(top=top))
    )
    m = m._replace(child=_canon_child(m.child))
    out = _scrub_dead_keys(_replay_outer(state._replace(m=m)))
    # A dup dot drops the whole Up (pure/map.py ``apply`` returns early).
    bshape = lambda new: seen.reshape(seen.shape + (1,) * (new.ndim - seen.ndim))
    out = jax.tree.map(
        lambda old, new: jnp.where(bshape(new), old, new), state, out
    )
    return out, overflow & ~seen


@jax.jit
def apply_key1_rm(state: NestedMapState, rm_clock: jax.Array, key1_mask: jax.Array):
    """``Op::Rm { clock, keyset }`` on the outer map: kill covered
    content across the masked K1 rows now; park in the OUTER buffer if
    the clock is ahead. Returns ``(state, overflow)``."""
    rm_clock = jnp.asarray(rm_clock, state.m.top.dtype)
    fmask = _expand1(state, key1_mask)
    valid = _rm_covered(state.m.child, rm_clock, fmask)
    child = _canon_child(state.m.child._replace(valid=valid))

    ahead = ~jnp.all(rm_clock <= state.m.top, axis=-1)
    odcl, odkeys, odvalid, overflow = _park_remove(
        state.odcl, state.odkeys, state.odvalid, rm_clock, key1_mask, ahead
    )
    out = _scrub_dead_keys(
        NestedMapState(
            m=state.m._replace(child=child),
            odcl=odcl,
            odkeys=odkeys,
            odvalid=odvalid,
        )
    )
    return out, overflow
