"""Cohort δ fan-out kernels — the PR 14 fused wire format generalized
from P ring links to millions of client links (ISSUE 16 tentpole).

The subscription plane (crdt_tpu/fanout/) buckets subscribers by acked
watermark so ONE join-irreducible decomposition serves a whole cohort:
a dispatch gathers B superblock rows next to their B cohort base rows
(each base is the bit-exact state the cohort's clients positively
acked) and

- :func:`cohort_deltas` vmaps the registered decomposition
  (delta_opt/decompose.py) over the batch — one traced program, B
  independent ``decompose(live, acked_base)`` lanes;
- :func:`cohort_wire_encode` runs the WHOLE batch's δ clock lanes
  through a SINGLE :func:`~crdt_tpu.ops.wire_kernels.wire_pack` call —
  the ``[B, E, A]`` element birth-clock planes flatten to ``B·E`` wire
  rows of ``A`` columns, so the fused Pallas pass (biased-u16 delta vs
  the acked base, two lanes per u32 word, checksum + packed-word count
  in the same read) prices ONE kernel launch per dispatch instead of
  one per link, which is the whole reason a 1M-subscriber fan-out can
  run at device speed;
- rows outside the u16 window DEFER to a raw-lane fallback (``raw``
  carries them verbatim — a fan-out client has no ring to re-mark
  dirty, so unencodable rows ship wide instead of starving);
- the residual planes (top clock + bounded parked buffers) ride whole
  per cohort, bool planes bit-packed 8× by
  :func:`~crdt_tpu.ops.wire_kernels.pack_bits`.

:func:`cohort_wire_decode` inverts the wire bit-exactly against the
client's OWN state (which equals the acked base by the plane's
promote-on-ack invariant — delta_opt/ackwin.py semantics host-side),
and ``reconstruct(kind, client_state, d)`` then lands the client
replica bit-identical to the served tenant row (the fanout property
tests/test_fanout.py pins, including across churn and resync).

:func:`cohort_push_bytes` is the honest per-cohort wire price (the
``delta_push_bytes`` / ``hist_push_bytes`` telemetry unit): kept rows
at the packed width, deferred rows at the raw width, plus the two
row bitmaps and the packed residual.
"""

from __future__ import annotations

import math
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..delta_opt.decompose import Decomposition, decompose
from .wire_kernels import (
    WireLaneSpec,
    pack_bits,
    unpack_bits,
    wire_pack,
)


class CohortWire(NamedTuple):
    """One dispatch's packed fan-out payload: B cohorts, E δ lanes per
    cohort, A clock columns per lane (``W = (A + 1) // 2`` packed
    words). ``keep`` / ``defer`` partition ``valid`` (a changed lane
    either fits the biased-u16 window or ships raw); ``residual`` is
    the per-cohort residual pytree with bool planes bit-packed.
    ``nnz`` / ``chk`` are the fused kernel's packed-word count and
    integrity-checksum partial, mesh-folded per dispatch."""

    words: jax.Array    # [B, E, W] u32 — packed biased-u16 δ lanes
    keep: jax.Array     # [B, E] bool — lanes on the packed wire
    defer: jax.Array    # [B, E] bool — changed lanes shipping raw
    valid: jax.Array    # [B, E] bool — the decomposition's lane mask
    raw: jax.Array      # [B, E, A] — deferred lanes verbatim, else 0
    residual: Any       # per-cohort residual, bool planes bit-packed
    nnz: jax.Array      # u32 — nonzero packed words
    chk: jax.Array      # u32 — checksum partial over the packed words


def cohort_deltas(kind: str, rows, bases) -> Decomposition:
    """B independent ``decompose(live_row, acked_base)`` lanes in one
    vmapped pass (leading batch axis on every leaf of ``rows`` /
    ``bases``). Pure where/select on static shapes — safe inside the
    ``mesh_fanout_push`` shard_map body."""
    return jax.vmap(lambda r, b: decompose(kind, r, b))(rows, bases)


def _ctr_plane(d: Decomposition) -> jax.Array:
    lanes = jax.tree.leaves(d.lanes)
    if len(lanes) != 1 or lanes[0].ndim != 3:
        raise ValueError(
            "cohort wire encode needs a single [B, E, A] clock row "
            f"plane (dense orswot-family decomposition), got "
            f"{[tuple(x.shape) for x in lanes]}"
        )
    return lanes[0]


def _pack_residual(res):
    """Bool residual planes as per-cohort little-endian bitmaps (the
    ``pack_bits`` wire form, 8× over byte-per-bool); other planes ride
    unchanged."""
    return jax.tree.map(
        lambda x: jax.vmap(pack_bits)(x.reshape(x.shape[0], -1))
        if x.dtype == jnp.bool_ else x,
        res,
    )


def _unpack_residual(packed, like):
    """Invert :func:`_pack_residual` given any pytree with the
    original residual's shapes/dtypes (the client's own split residual
    works — shapes are capacity-static)."""
    def un(p, l):
        if l.dtype != jnp.bool_:
            return p
        n = math.prod(l.shape[1:]) if len(l.shape) > 1 else 1
        flat = jax.vmap(lambda w: unpack_bits(w, n))(p)
        return flat.reshape(l.shape)

    return jax.tree.map(un, packed, like)


def cohort_wire_encode(
    d: Decomposition,
    base_ctr: jax.Array,
    interpret: Optional[bool] = None,
) -> CohortWire:
    """Encode one dispatch's stacked decomposition against the cohort
    bases' clock plane ``base_ctr [B, E, A]`` — ONE fused
    :func:`wire_pack` pass over all ``B·E`` δ lanes (module
    docstring). Backend dispatch follows the wire kernel: compiled on
    TPU, the Pallas interpreter elsewhere (bit-identical)."""
    ctr = _ctr_plane(d)
    b, e, a = ctr.shape
    spec = WireLaneSpec(lc=a)
    out = wire_pack(
        spec,
        ctr.reshape(b * e, a),
        base_ctr.reshape(b * e, a),
        d.valid.reshape(b * e),
        interpret=interpret,
    )
    keep = out.keep.reshape(b, e)
    defer = out.defer.reshape(b, e)
    return CohortWire(
        words=out.words.reshape(b, e, spec.w),
        keep=keep,
        defer=defer,
        valid=d.valid,
        raw=jnp.where(defer[..., None], ctr, jnp.zeros_like(ctr)),
        residual=_pack_residual(d.residual),
        nnz=out.nnz,
        chk=out.chk,
    )


def cohort_wire_decode(
    wire: CohortWire, base_ctr: jax.Array, res_like
) -> Decomposition:
    """Invert :func:`cohort_wire_encode` bit-exactly: kept lanes
    decode ``base + (enc16 - BIAS)`` against the client's own clock
    plane (== the acked base, the plane's promote-on-ack invariant),
    deferred lanes adopt the raw fallback, bool residual planes
    unpack against ``res_like`` (any pytree with the residual's
    shapes/dtypes). Plain lax — the receive side fuses with the
    client's reconstruct, the kernel earns its keep on send
    (wire_kernels.wire_unpack's convention)."""
    from .wire_kernels import wire_unpack

    b, e, a = base_ctr.shape
    spec = WireLaneSpec(lc=a)
    dec = wire_unpack(
        spec,
        wire.words.reshape(b * e, spec.w),
        base_ctr.reshape(b * e, a),
        wire.keep.reshape(b * e),
        base_ctr.dtype,
    ).reshape(b, e, a)
    ctr = jnp.where(wire.defer[..., None], wire.raw, dec)
    return Decomposition(
        lanes=(ctr,),
        valid=wire.valid,
        residual=_unpack_residual(wire.residual, res_like),
    )


def cohort_push_bytes(wire: CohortWire) -> jax.Array:
    """The per-cohort wire price ``[B] f32`` (``delta_push_bytes`` /
    ``hist_push_bytes`` unit): packed words for kept lanes, raw lanes
    for deferred ones, plus the static framing — the keep/defer
    bitmaps and the (bit-packed) residual riding whole."""
    b, e, w = wire.words.shape
    a = wire.raw.shape[-1]
    framing = 2 * ((e + 31) // 32) * 4 + sum(
        (leaf.size // b) * leaf.dtype.itemsize
        for leaf in jax.tree.leaves(wire.residual)
    )
    return (
        jnp.sum(wire.keep, axis=-1, dtype=jnp.float32) * (4 * w)
        + jnp.sum(wire.defer, axis=-1, dtype=jnp.float32)
        * (a * wire.raw.dtype.itemsize)
        + jnp.float32(framing)
    )


def wire_lane(wire: CohortWire, b: int) -> CohortWire:
    """One cohort's slice of a dispatch wire (leading batch axis kept
    at 1 — the shape :func:`cohort_wire_decode` expects): what the
    plane hands every subscriber of cohort ``b``."""
    sl = lambda x: x[b:b + 1]  # noqa: E731
    return CohortWire(
        words=sl(wire.words),
        keep=sl(wire.keep),
        defer=sl(wire.defer),
        valid=sl(wire.valid),
        raw=sl(wire.raw),
        residual=jax.tree.map(sl, wire.residual),
        nnz=wire.nnz,
        chk=wire.chk,
    )


__all__ = [
    "CohortWire", "cohort_deltas", "cohort_push_bytes",
    "cohort_wire_decode", "cohort_wire_encode", "wire_lane",
]
