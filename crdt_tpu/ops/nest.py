"""THE nesting combinator — one induction step, any depth.

Reference: src/map.rs ``Map<K, V: Val<A>, A>`` composes causal CRDTs to
arbitrary depth. Under the causal-composition rule (pure/map.py) every
child's top clock equals the outer top, so each map level flattens onto
its child's slab over a product key space, and nesting a map around ANY
already-flattened causal slab costs exactly one more outer deferred
buffer (parked keyset-removes at the new level) plus the
replay/compaction/dead-key-scrub cascade. Through round 3 that induction
step was *prose* — ops/map_orswot.py, ops/map_map.py, and ops/map3.py
were three hand-written instantiations (map3.py's own docstring: "depth
N is N-1 applications of this wrapper"). This module is the wrapper AS
CODE: ``NestLevel`` takes any slab satisfying the small protocol below
and IS the one-more-outer-buffer slab — itself nestable, so depth 4+
needs no new module (tests/test_nest_depth4.py builds
``Map<K1, Map<K2, Map<K3, Orswot>>>`` by composing three levels).

Protocol (every nestable slab level implements; ``s`` is its state
pytree):

- ``keys_width(s)``          — size of the level's keyset-mask axis.
- ``top(s)`` / ``witness(s, actor, counter)`` — the shared top clock
  (lives on the leaf slab; one dot witnesses at every level at once).
- ``join(a, b, element_axis=None) -> (s, flags[L])`` — full lattice
  join; flags are scalar overflow lanes, innermost level first.
- ``replay_keyset(s, dcl, dmask, dvalid) -> s`` — kill content covered
  by parked (clock, keyset-mask-over-my-keys) slots. Monotone zeroing;
  touches no buffers, so replay order across levels is free.
- ``rm_parked(s, rm_clock, mask) -> (s, overflow)`` — apply the covered
  part of a keyset-remove now, parking the clock in THIS level's buffer
  when it runs ahead of the top.
- ``alive(s) -> bool[..., keys_width]`` — per-key liveness.
- ``scrub_cols(s, alive_cols, element_axis) -> s`` — mask ALL of the
  level's buffers (own + inner) to live columns, dropping emptied
  slots. Used by the ENCLOSING level when my keys die with its keys.
- ``scrub_self(s, element_axis) -> s`` — the level's own dead-key
  scrub: bottomed children die with their parked state (the oracle's
  ``is_bottom`` drop); the level's OWN buffer belongs to it and is
  never self-scrubbed.
- ``settle_self(s, element_axis) -> s`` — after a top advance: replay
  parked slots at every level (innermost first), drop caught-up slots,
  then scrub.
- ``leaf_ctr(s)`` — the leaf dot slab (delta flavors diff it).

Leaf adapters: ``ORSWOT`` (the dot-matrix slab of ops/orswot.py — leaf
of the orswot-valued family) and ``MAP_MVREG`` (the slot-table slab of
ops/map.py — the ``Map<K, MVReg>`` leaf, whose own dkeys buffer makes it
directly nestable). The concrete flavor modules instantiate:
``map_orswot.LEVEL = NestLevel(ORSWOT)``, ``map_map.LEVEL =
NestLevel(MAP_MVREG)``, ``map3.LEVEL = NestLevel(map_orswot.LEVEL)``.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from . import map as map_ops
from . import orswot as orswot_ops
from .orswot import (
    _apply_parked,
    _compact_deferred,
    _dedupe_deferred,
    _park_remove,
)

DTYPE = jnp.uint32


def _any_slots(mask: jax.Array, element_axis) -> jax.Array:
    """Per-slot liveness ``any(mask, -1)``, reduced across element
    shards when the mask's last axis is sharded (``element_axis`` set,
    inside shard_map): a slot's keys may live in other shards, and slot
    validity must stay replicated across them."""
    live = jnp.any(mask, axis=-1)
    if element_axis is not None:
        from jax import lax

        live = lax.psum(live.astype(jnp.int32), element_axis) > 0
    return live


class NestedState(NamedTuple):
    """Generic one-more-level state: any nestable slab + one outer
    parked-keyset-remove buffer. The concrete flavors keep their own
    NamedTuple classes (same POSITIONAL layout — field names differ for
    compatibility); ``NestLevel`` accesses fields positionally so any
    4-field (core, dcl, dkeys, dvalid) class works."""

    core: Any
    dcl: jax.Array     # [..., D, A]  parked rm clocks
    dkeys: jax.Array   # [..., D, K]  parked keysets
    dvalid: jax.Array  # [..., D]


class OrswotSlab:
    """Leaf adapter: the flat orswot dot slab (ops/orswot.py). Its
    "keys" are its elements; its buffer parks member-removes."""

    def keys_width(self, s):
        return s.ctr.shape[-2]

    def top(self, s):
        return s.top

    def witness(self, s, actor, counter):
        return s._replace(top=s.top.at[..., actor].max(counter))

    def join(self, a, b, element_axis=None):
        st, of = orswot_ops.join(a, b)
        return st, jnp.atleast_1d(jnp.any(of))

    def replay_keyset(self, s, dcl, dmask, dvalid):
        return s._replace(ctr=_apply_parked(s.ctr, dcl, dmask, dvalid))

    def rm_parked(self, s, rm_clock, mask):
        return orswot_ops.apply_rm(s, rm_clock, mask)

    def alive(self, s):
        return jnp.any(s.ctr > 0, axis=-1)

    def scrub_cols(self, s, cols, element_axis=None):
        dmask = s.dmask & cols[..., None, :]
        dvalid = s.dvalid & _any_slots(dmask, element_axis)
        return s._replace(
            dcl=jnp.where(dvalid[..., None], s.dcl, 0),
            dmask=dmask & dvalid[..., None],
            dvalid=dvalid,
        )

    def scrub_self(self, s, element_axis=None):
        return s  # elements have nothing inside them to scrub

    def settle_self(self, s, element_axis=None):
        ctr = _apply_parked(s.ctr, s.dcl, s.dmask, s.dvalid)
        still = ~jnp.all(s.dcl <= s.top[..., None, :], axis=-1)
        return s._replace(ctr=ctr, dvalid=s.dvalid & still)

    def rm_route(self, s, levels_down, rm_clock, mask):
        assert levels_down == 0, "leaf slab cannot route deeper"
        return self.rm_parked(s, rm_clock, mask)

    def leaf_ctr(self, s):
        return s.ctr


class MapMVRegSlab:
    """Leaf adapter: the Map<K, MVReg> slot slab (ops/map.py). Its
    buffer parks keyset-removes; content lives in per-key slot tables."""

    def keys_width(self, s):
        return s.dkeys.shape[-1]

    def top(self, s):
        return s.top

    def witness(self, s, actor, counter):
        return s._replace(top=s.top.at[..., actor].max(counter))

    def join(self, a, b, element_axis=None):
        return map_ops.join(a, b)  # flags already [sibling, deferred]

    def replay_keyset(self, s, dcl, dkeys, dvalid):
        tmp = s._replace(dcl=dcl, dkeys=dkeys, dvalid=dvalid)
        replayed = map_ops._apply_parked(tmp)
        return s._replace(child=map_ops._canon_child(replayed.child))

    def rm_parked(self, s, rm_clock, mask):
        return map_ops.apply_rm(s, rm_clock, mask)

    def alive(self, s):
        return jnp.any(s.child.valid, axis=-1)

    def scrub_cols(self, s, cols, element_axis=None):
        dkeys = s.dkeys & cols[..., None, :]
        dvalid = s.dvalid & _any_slots(dkeys, element_axis)
        return s._replace(
            dcl=jnp.where(dvalid[..., None], s.dcl, 0),
            dkeys=dkeys & dvalid[..., None],
            dvalid=dvalid,
        )

    def scrub_self(self, s, element_axis=None):
        return s  # MVReg children hold no parked state of their own

    def settle_self(self, s, element_axis=None):
        out = map_ops._drop_stale_deferred(map_ops._apply_parked(s))
        return out._replace(child=map_ops._canon_child(out.child))

    def rm_route(self, s, levels_down, rm_clock, mask):
        assert levels_down == 0, "leaf slab cannot route deeper"
        return self.rm_parked(s, rm_clock, mask)

    def leaf_ctr(self, s):
        # The witness-counter table stands in for a dot slab: delta
        # flavors only diff it for change detection.
        return s.child.wctr


ORSWOT = OrswotSlab()
MAP_MVREG = MapMVRegSlab()


class NestLevel:
    """One application of the map-nesting induction step: wraps any
    protocol-satisfying slab with one outer parked-keyset buffer. The
    result satisfies the same protocol, so levels compose to any depth.

    ``state_cls`` is any 4-field NamedTuple with positional layout
    (core, dcl, dkeys, dvalid) — the concrete flavors pass their own
    classes so their public state types stay stable."""

    def __init__(self, core, state_cls=NestedState):
        self.core = core
        self.state_cls = state_cls

    def _make(self, core_state, dcl, dkeys, dvalid):
        return self.state_cls(core_state, dcl, dkeys, dvalid)

    def _bufs(self, s):
        return (s[1], s[2], s[3])

    def empty(self, core_state, n_keys: int, n_actors: int,
              deferred_cap: int, batch: tuple = ()):
        """Wrap an (empty) core state with an empty outer buffer."""
        return self._make(
            core_state,
            jnp.zeros((*batch, deferred_cap, n_actors), DTYPE),
            jnp.zeros((*batch, deferred_cap, n_keys), bool),
            jnp.zeros((*batch, deferred_cap), bool),
        )

    # ---- protocol -----------------------------------------------------

    def keys_width(self, s):
        return s[2].shape[-1]

    def mult(self, s) -> int:
        """Core keys per key of this level (the product-space factor)."""
        return self.core.keys_width(s[0]) // self.keys_width(s)

    def expand(self, s, mask):
        """[..., K] mask at this level → core keyset-mask."""
        return jnp.repeat(mask, self.mult(s), axis=-1)

    def top(self, s):
        return self.core.top(s[0])

    def witness(self, s, actor, counter):
        return self._make(self.core.witness(s[0], actor, counter), *self._bufs(s))

    def alive(self, s):
        ca = self.core.alive(s[0])
        k = self.keys_width(s)
        return jnp.any(ca.reshape(*ca.shape[:-1], k, -1), axis=-1)

    def replay_keyset(self, s, dcl, dmask, dvalid):
        return self._make(
            self.core.replay_keyset(s[0], dcl, self.expand(s, dmask), dvalid),
            *self._bufs(s),
        )

    def scrub_cols(self, s, cols, element_axis=None):
        dkeys = s[2] & cols[..., None, :]
        dvalid = s[3] & _any_slots(dkeys, element_axis)
        core = self.core.scrub_cols(s[0], self.expand(s, cols), element_axis)
        return self._make(
            core,
            jnp.where(dvalid[..., None], s[1], 0),
            dkeys & dvalid[..., None],
            dvalid,
        )

    def replay_outer(self, s):
        """Replay this level's parked keyset-removes against the content
        slab, then drop slots the top has caught up to (the oracle's
        ``_apply_deferred``)."""
        replayed = self.replay_keyset(s, s[1], s[2], s[3])
        still = ~jnp.all(s[1] <= self.top(s)[..., None, :], axis=-1)
        dvalid = s[3] & still
        return self._make(
            replayed[0],
            jnp.where(dvalid[..., None], s[1], 0),
            s[2] & dvalid[..., None],
            dvalid,
        )

    def scrub_self(self, s, element_axis=None):
        """A bottomed child (no live leaf dot in its block) is deleted
        by the oracle together with ALL parked state inside it — at
        every inner level. Core's own scrub runs FIRST: a replayed
        remove at this level can newly bottom an inner child while this
        level's block stays alive (tests/test_models_map3.py pins the
        ordering). This level's own buffer is never self-scrubbed."""
        core = self.core.scrub_self(s[0], element_axis)
        s2 = self._make(core, *self._bufs(s))
        cols = self.alive(s2)
        core = self.core.scrub_cols(core, self.expand(s2, cols), element_axis)
        return self._make(core, *self._bufs(s))

    def settle_self(self, s, element_axis=None):
        core = self.core.settle_self(s[0], element_axis)
        out = self.replay_outer(self._make(core, *self._bufs(s)))
        return self.scrub_self(out, element_axis)

    def leaf_ctr(self, s):
        return self.core.leaf_ctr(s[0])

    def concat_bufs(self, a, b):
        """Union two replicas' outer buffers (slot-list concatenation;
        dedupe happens in ``settle_outer``)."""
        return (
            jnp.concatenate([a[1], b[1]], axis=-2),
            jnp.concatenate([a[2], b[2]], axis=-2),
            jnp.concatenate([a[3], b[3]], axis=-1),
        )

    def settle_outer(self, s, cap: int, element_axis=None):
        """Settle this level's buffer after a union: dedupe equal-clock
        slots (dict-union semantics) → replay against the content slab,
        dropping caught-up slots → compact back to capacity (overflow if
        a live slot won't fit) → scrub parked state inside bottomed
        children. The ORDER is correctness-critical: the scrub must
        follow the replay, because a replayed remove can newly bottom a
        child (tests/test_models_map3.py pins the failure mode). Returns
        ``(state, overflow)``."""
        dcl, dkeys, dvalid = _dedupe_deferred(s[1], s[2], s[3])
        s = self.replay_outer(self._make(s[0], dcl, dkeys, dvalid))
        dcl, dkeys, dvalid, overflow = _compact_deferred(s[1], s[2], s[3], cap)
        s = self.scrub_self(self._make(s[0], dcl, dkeys, dvalid), element_axis)
        return s, jnp.any(overflow)

    def join(self, a, b, element_axis=None):
        """Pairwise lattice join: the core join plus this level's buffer
        union → dedupe → replay → compact → scrub sequence
        (``settle_outer`` holds the order). Returns ``(state,
        flags[L+1])`` — core lanes first, this level last."""
        core, core_flags = self.core.join(a[0], b[0], element_axis)
        state = self._make(core, *self.concat_bufs(a, b))
        state, of = self.settle_outer(state, a[1].shape[-2], element_axis)
        return state, jnp.concatenate([core_flags, of[None]])

    def fold(self, states, element_axis=None):
        """Log-tree fold of a replica batch (leading axis)."""
        from functools import partial

        from .lattice import tree_fold

        identity = jax.tree.map(
            lambda x: jnp.zeros(x.shape[1:], x.dtype), states
        )
        return tree_fold(
            states, identity, partial(self.join, element_axis=element_axis)
        )

    # ---- op application (CmRDT) --------------------------------------

    def rm_parked(self, s, rm_clock, mask):
        """``Op::Rm { clock, keyset }`` addressed to THIS level: kill
        covered content now, park in this level's buffer if the clock is
        ahead, scrub newly-bottomed children. Returns ``(s, overflow)``."""
        rm_clock = jnp.asarray(rm_clock, self.top(s).dtype)
        killed = self.replay_keyset(
            s,
            rm_clock[..., None, :],
            mask[..., None, :],
            jnp.ones(rm_clock.shape[:-1] + (1,), bool),
        )
        ahead = ~jnp.all(rm_clock <= self.top(s), axis=-1)
        dcl, dkeys, dvalid, overflow = _park_remove(
            s[1], s[2], s[3], rm_clock, mask, ahead
        )
        out = self.scrub_self(self._make(killed[0], dcl, dkeys, dvalid))
        return out, overflow

    def rm_route(self, s, levels_down: int, rm_clock, mask):
        """Route a keyset-remove ``levels_down`` levels into the core
        (0 = this level's own buffer). ``mask`` is already flattened to
        the target level's key space."""
        if levels_down == 0:
            return self.rm_parked(s, rm_clock, mask)
        core, overflow = self.core.rm_route(s[0], levels_down - 1, rm_clock, mask)
        return self._make(core, *self._bufs(s)), overflow

    def apply_up_rm(self, s, actor, counter, rm_clock, mask,
                    levels_down: int, element_axis=None):
        """``Op::Up^j { dot, …, op: Rm { clock, keyset } }`` — a
        keyset-remove routed through ``j`` Up levels sharing one minted
        dot: kill+park at the target level, witness the dot on the
        shared top, settle every level, dup-drop the whole Up
        (pure/map.py ``apply`` returns early on a seen dot). Returns
        ``(s, overflow)``."""
        counter = jnp.asarray(counter).astype(self.top(s).dtype)
        seen = self.top(s)[..., actor] >= counter
        rmed, overflow = self.rm_route(s, levels_down, rm_clock, mask)
        out = self.settle_self(
            self.witness(rmed, actor, counter), element_axis
        )
        bshape = lambda new: seen.reshape(
            seen.shape + (1,) * (new.ndim - seen.ndim)
        )
        out = jax.tree.map(
            lambda old, new: jnp.where(bshape(new), old, new), s, out
        )
        return out, overflow & ~seen

    def cascade(self, s, new_core, element_axis=None):
        """After a core-level op application (which witnessed its own
        dot): replay this level's parked removes under the advanced top
        and scrub newly-bottomed children."""
        out = self.replay_outer(self._make(new_core, *self._bufs(s)))
        return self.scrub_self(out, element_axis)
