"""Dense batched kernels for ``Map<K1, Map<K2, Orswot<M>>>`` — depth-3
nesting by the same slab induction that built the depth-2 types.

Oracle: ``crdt_tpu.pure.map.Map`` with nested ``Map(Orswot)`` children
(reference: src/map.rs arbitrary ``V: Val<A>`` nesting depth). The
causal-composition rule (pure/map.py) pins every child top to the outer
top, so the inner two levels collapse into ONE ``map_orswot`` slab over
the K1 × K2 product key space — and this module is *structurally
identical to ops/map_map.py with a different core module*. That is the
induction step SURVEY.md §7.1's slab-composition plan promises: nesting
a map around ANY already-flattened causal slab costs exactly one more
outer deferred buffer (parked keyset-removes at the new level) plus the
replay/compaction/dead-key-scrub cascade below; depth N is N-1
applications of this wrapper around a leaf slab. No trace-time
recursion, no new kernel math.

Buffer levels in this state, outermost first:
- ``odcl/odkeys/odvalid`` — K1-level parked keyset-removes (NEW here),
- ``mo.kdcl/kdkeys/kdvalid`` — K2-level parked keyset-removes over the
  K1×K2 product (the middle maps' deferred, shared-slot encoded),
- ``mo.core.dcl/dmask/dvalid`` — leaf orswot member-removes over
  K1×K2×M.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from . import map_orswot as mo_ops
from .map_orswot import MapOrswotState, _any_slots
from .orswot import _apply_parked, _park_remove
from .outer_level import concat_outer, settle_outer_level

DTYPE = jnp.uint32


class Map3State(NamedTuple):
    """A (possibly batched) dense Map<K1, Map<K2, Orswot<M>>> replica."""

    mo: MapOrswotState  # flat over K1*K2 keys of M members
    odcl: jax.Array     # [..., D, A]   K1-level parked rm clocks
    odkeys: jax.Array   # [..., D, K1]  K1-level parked keysets
    odvalid: jax.Array  # [..., D]


def empty(
    n_keys1: int,
    n_keys2: int,
    n_members: int,
    n_actors: int,
    deferred_cap: int = 4,
    batch: tuple = (),
) -> Map3State:
    """The join identity."""
    return Map3State(
        mo=mo_ops.empty(
            n_keys1 * n_keys2, n_members, n_actors, deferred_cap, batch=batch
        ),
        odcl=jnp.zeros((*batch, deferred_cap, n_actors), DTYPE),
        odkeys=jnp.zeros((*batch, deferred_cap, n_keys1), bool),
        odvalid=jnp.zeros((*batch, deferred_cap), bool),
    )


def _n1(state: Map3State) -> int:
    return state.odkeys.shape[-1]


def _n2(state: Map3State) -> int:
    return state.mo.kdkeys.shape[-1] // _n1(state)


def _nm(state: Map3State) -> int:
    return state.mo.core.ctr.shape[-2] // state.mo.kdkeys.shape[-1]


def _expand1(state: Map3State, key1_mask: jax.Array, to: str) -> jax.Array:
    """[..., K1] outer mask → K1*K2 key mask (``to="keys"``) or
    K1*K2*M element mask (``to="elems"``)."""
    n = _n2(state) * (_nm(state) if to == "elems" else 1)
    return jnp.repeat(key1_mask, n, axis=-1)


def _replay_outer(state: Map3State) -> Map3State:
    """Replay parked K1 keyset-removes against the leaf dot slab, then
    drop slots the top has caught up to (the oracle's
    ``_apply_deferred``)."""
    emask = _expand1(state, state.odkeys, "elems")
    ctr = _apply_parked(state.mo.core.ctr, state.odcl, emask, state.odvalid)
    still = ~jnp.all(state.odcl <= state.mo.core.top[..., None, :], axis=-1)
    odvalid = state.odvalid & still
    return Map3State(
        mo=state.mo._replace(core=state.mo.core._replace(ctr=ctr)),
        odcl=jnp.where(odvalid[..., None], state.odcl, 0),
        odkeys=state.odkeys & odvalid[..., None],
        odvalid=odvalid,
    )


def _scrub_dead1(state: Map3State, element_axis=None) -> Map3State:
    """A bottomed K1 child (no live leaf dot anywhere in its block) is
    deleted by the oracle together with ALL parked state inside it — its
    middle-map keyset-removes and its orswots' member-removes. The K1
    buffer belongs to the outer map itself and is never scrubbed.

    Runs the (K1,K2)-granular leaf scrub FIRST: a replayed K1-level
    remove can bottom one (k1, k2) orswot while its K1 block stays
    alive, and the oracle drops that orswot with its parked member-
    removes even though the k1 child survives (mo_ops._scrub_dead_keys
    last ran inside mo_ops.join, before our K1 replay killed content).

    K1 liveness is shard-local (element shards align to whole K1
    blocks); slot liveness reduces across shards (``_any_slots``)."""
    state = state._replace(
        mo=mo_ops._scrub_dead_keys(state.mo, element_axis=element_axis)
    )
    k1, k2, m = _n1(state), _n2(state), _nm(state)
    ctr = state.mo.core.ctr
    alive1 = jnp.any(
        ctr.reshape(*ctr.shape[:-2], k1, k2 * m, ctr.shape[-1]) > 0,
        axis=(-2, -1),
    )  # [..., K1]
    kcols = jnp.repeat(alive1, k2, axis=-1)       # [..., K1*K2]
    ecols = jnp.repeat(alive1, k2 * m, axis=-1)   # [..., K1*K2*M]
    kdkeys = state.mo.kdkeys & kcols[..., None, :]
    kdvalid = state.mo.kdvalid & _any_slots(kdkeys, element_axis)
    dmask = state.mo.core.dmask & ecols[..., None, :]
    dvalid = state.mo.core.dvalid & _any_slots(dmask, element_axis)
    return state._replace(
        mo=state.mo._replace(
            core=state.mo.core._replace(
                dcl=jnp.where(dvalid[..., None], state.mo.core.dcl, 0),
                dmask=dmask & dvalid[..., None],
                dvalid=dvalid,
            ),
            kdcl=jnp.where(kdvalid[..., None], state.mo.kdcl, 0),
            kdkeys=kdkeys & kdvalid[..., None],
            kdvalid=kdvalid,
        )
    )


@partial(jax.jit, static_argnames=("element_axis",))
def join(a: Map3State, b: Map3State, element_axis=None):
    """Pairwise lattice join: the flat Map<K1*K2, Orswot> join plus the
    K1 buffer union/replay/compaction and the dead-K1 scrub. Returns
    ``(state, overflow[3])`` — [leaf-deferred, K2-deferred, K1-deferred].
    ``element_axis`` names the mesh axis the key/element dimension is
    sharded over when joining inside shard_map."""
    mo, mo_flags = mo_ops.join(a.mo, b.mo, element_axis=element_axis)

    state = Map3State(
        mo,
        *concat_outer(
            (a.odcl, a.odkeys, a.odvalid), (b.odcl, b.odkeys, b.odvalid)
        ),
    )
    state, outer_of = settle_outer_level(
        state,
        a.odcl.shape[-2],
        get_bufs=lambda s: (s.odcl, s.odkeys, s.odvalid),
        with_bufs=lambda s, cl, ks, v: s._replace(odcl=cl, odkeys=ks, odvalid=v),
        replay=_replay_outer,
        scrub=_scrub_dead1,
        element_axis=element_axis,
    )
    return state, jnp.stack([mo_flags[0], mo_flags[1], outer_of])


def fold(states: Map3State, element_axis=None):
    """Log-tree fold of a replica batch (leading axis)."""
    from .lattice import tree_fold

    k1, k2, m = _n1(states), _n2(states), _nm(states)
    identity = empty(
        k1, k2, m, states.mo.core.top.shape[-1], states.odcl.shape[-2]
    )
    return tree_fold(states, identity, partial(join, element_axis=element_axis))


# ---- op application (CmRDT) ----------------------------------------------

@jax.jit
def apply_member_add(
    state: Map3State,
    actor: jax.Array,
    counter: jax.Array,
    key1: jax.Array,
    key2: jax.Array,
    member_mask: jax.Array,
) -> Map3State:
    """``Op::Up { dot, k1, op: Up { dot, k2, op: Add { dot, members } } }``
    — all three levels share the one minted dot, so the leaf add on the
    flat K1*K2 key IS the whole op."""
    flat_key = key1 * _n2(state) + key2
    mo = mo_ops.apply_member_add(
        state.mo, actor, counter, flat_key, member_mask
    )
    return _scrub_dead1(_replay_outer(state._replace(mo=mo)))


@jax.jit
def apply_member_rm(
    state: Map3State,
    actor: jax.Array,
    counter: jax.Array,
    key1: jax.Array,
    key2: jax.Array,
    rm_clock: jax.Array,
    member_mask: jax.Array,
):
    """``Op::Up { dot, k1, op: Up { dot, k2, op: Rm { clock, members } } }``
    — a leaf member remove routed through both map levels. Returns
    ``(state, overflow)``."""
    flat_key = key1 * _n2(state) + key2
    mo, overflow = mo_ops.apply_member_rm(
        state.mo, actor, counter, flat_key, rm_clock, member_mask
    )
    out = _scrub_dead1(_replay_outer(state._replace(mo=mo)))
    return out, overflow


@jax.jit
def apply_key2_rm(
    state: Map3State,
    actor: jax.Array,
    counter: jax.Array,
    key1: jax.Array,
    rm_clock: jax.Array,
    key2_mask: jax.Array,
):
    """``Op::Up { dot, k1, op: Rm { clock, keyset2 } }`` — a middle-map
    keyset-remove routed through the outer map: kill covered content at
    (k1, keyset2) (parking in the K2 buffer if ahead), then witness the
    Up's dot. Returns ``(state, overflow)``."""
    counter = counter.astype(state.mo.core.top.dtype)
    seen = state.mo.core.top[..., actor] >= counter
    k1n, k2n = _n1(state), _n2(state)
    fmask = (
        jax.nn.one_hot(key1, k1n, dtype=bool)[..., :, None]
        & key2_mask[..., None, :]
    ).reshape(*key2_mask.shape[:-1], k1n * k2n)
    rmed, overflow = mo_ops.apply_key_rm(state.mo, rm_clock, fmask)
    top = rmed.core.top.at[..., actor].max(counter)
    # Advancing the top may un-park removes at every level: replay leaf,
    # then middle, then outer, each dropping caught-up slots.
    ctr = _apply_parked(rmed.core.ctr, rmed.core.dcl, rmed.core.dmask, rmed.core.dvalid)
    still = ~jnp.all(rmed.core.dcl <= top[..., None, :], axis=-1)
    core = rmed.core._replace(top=top, ctr=ctr, dvalid=rmed.core.dvalid & still)
    mo = mo_ops._scrub_dead_keys(mo_ops._replay_outer(rmed._replace(core=core)))
    out = _scrub_dead1(_replay_outer(state._replace(mo=mo)))
    # A dup dot drops the whole Up (pure/map.py ``apply`` returns early).
    bshape = lambda new: seen.reshape(seen.shape + (1,) * (new.ndim - seen.ndim))
    out = jax.tree.map(
        lambda old, new: jnp.where(bshape(new), old, new), state, out
    )
    return out, overflow & ~seen


@jax.jit
def apply_key1_rm(state: Map3State, rm_clock: jax.Array, key1_mask: jax.Array):
    """``Op::Rm { clock, keyset }`` on the outer map (reference:
    src/map.rs ``apply_keyset_rm``): kill covered leaf dots across the
    masked K1 blocks now; park in the K1 buffer if the clock is ahead.
    Returns ``(state, overflow)``."""
    rm_clock = jnp.asarray(rm_clock, state.mo.core.top.dtype)
    emask = _expand1(state, key1_mask, "elems")
    ctr = state.mo.core.ctr
    dominated = emask[..., :, None] & (ctr <= rm_clock[..., None, :])
    ctr = jnp.where(dominated, jnp.zeros_like(ctr), ctr)

    ahead = ~jnp.all(rm_clock <= state.mo.core.top, axis=-1)
    odcl, odkeys, odvalid, overflow = _park_remove(
        state.odcl, state.odkeys, state.odvalid, rm_clock, key1_mask, ahead
    )
    out = _scrub_dead1(
        Map3State(
            mo=state.mo._replace(core=state.mo.core._replace(ctr=ctr)),
            odcl=odcl,
            odkeys=odkeys,
            odvalid=odvalid,
        )
    )
    return out, overflow
