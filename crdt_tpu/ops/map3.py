"""Dense batched kernels for ``Map<K1, Map<K2, Orswot<M>>>`` — depth-3
nesting as a second application of the ``ops.nest`` induction step.

Oracle: ``crdt_tpu.pure.map.Map`` with nested ``Map(Orswot)`` children
(reference: src/map.rs arbitrary ``V: Val<A>`` nesting depth). The
causal-composition rule (pure/map.py) pins every child top to the outer
top, so the inner two levels collapse into ONE ``map_orswot`` slab over
the K1 × K2 product key space, and this module is literally
``NestLevel(map_orswot.LEVEL)`` — the combinator applied to the already-
wrapped slab. Depth N is N-1 ``NestLevel`` applications around a leaf
slab (tests/test_nest_depth4.py composes depth 4 with no new module).

Buffer levels in this state, outermost first:
- ``odcl/odkeys/odvalid`` — K1-level parked keyset-removes (NEW here),
- ``mo.kdcl/kdkeys/kdvalid`` — K2-level parked keyset-removes over the
  K1×K2 product (the middle maps' deferred, shared-slot encoded),
- ``mo.core.dcl/dmask/dvalid`` — leaf orswot member-removes over
  K1×K2×M.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from . import map_orswot as mo_ops
from .map_orswot import MapOrswotState
from .nest import NestLevel


class Map3State(NamedTuple):
    """A (possibly batched) dense Map<K1, Map<K2, Orswot<M>>> replica."""

    mo: MapOrswotState  # flat over K1*K2 keys of M members
    odcl: jax.Array     # [..., D, A]   K1-level parked rm clocks
    odkeys: jax.Array   # [..., D, K1]  K1-level parked keysets
    odvalid: jax.Array  # [..., D]


LEVEL = NestLevel(mo_ops.LEVEL, Map3State)


def empty(
    n_keys1: int,
    n_keys2: int,
    n_members: int,
    n_actors: int,
    deferred_cap: int = 4,
    batch: tuple = (),
) -> Map3State:
    """The join identity."""
    return LEVEL.empty(
        mo_ops.empty(
            n_keys1 * n_keys2, n_members, n_actors, deferred_cap, batch=batch
        ),
        n_keys1, n_actors, deferred_cap, batch,
    )


def _n1(state: Map3State) -> int:
    return state.odkeys.shape[-1]


def _n2(state: Map3State) -> int:
    return state.mo.kdkeys.shape[-1] // _n1(state)


def _nm(state: Map3State) -> int:
    return state.mo.core.ctr.shape[-2] // state.mo.kdkeys.shape[-1]


_replay_outer = LEVEL.replay_outer
_scrub_dead1 = LEVEL.scrub_self


@partial(jax.jit, static_argnames=("element_axis",))
def join(a: Map3State, b: Map3State, element_axis=None):
    """Pairwise lattice join: the flat Map<K1*K2, Orswot> join plus the
    K1 buffer union/replay/compaction and the dead-K1 scrub. Returns
    ``(state, overflow[3])`` — [leaf-deferred, K2-deferred, K1-deferred].
    ``element_axis`` names the mesh axis the key/element dimension is
    sharded over when joining inside shard_map."""
    return LEVEL.join(a, b, element_axis)


def fold(states: Map3State, element_axis=None, prefer: str = "auto"):
    """Replica-batch fold with backend-appropriate dispatch: the fused
    one-HBM-pass Pallas kernel on TPU backends, the jnp log-tree fold
    elsewhere (``prefer`` = "auto"|"fused"|"tree" as in
    pallas_kernels.fold_auto)."""
    from .pallas_kernels import fold_auto_level

    return fold_auto_level(LEVEL, states, prefer, element_axis)


# ---- op application (CmRDT) ----------------------------------------------

@jax.jit
def apply_member_add(
    state: Map3State,
    actor: jax.Array,
    counter: jax.Array,
    key1: jax.Array,
    key2: jax.Array,
    member_mask: jax.Array,
) -> Map3State:
    """``Op::Up { dot, k1, op: Up { dot, k2, op: Add { dot, members } } }``
    — all three levels share the one minted dot, so the leaf add on the
    flat K1*K2 key IS the whole op."""
    flat_key = key1 * _n2(state) + key2
    mo = mo_ops.apply_member_add(
        state.mo, actor, counter, flat_key, member_mask
    )
    return LEVEL.cascade(state, mo)


@jax.jit
def apply_member_rm(
    state: Map3State,
    actor: jax.Array,
    counter: jax.Array,
    key1: jax.Array,
    key2: jax.Array,
    rm_clock: jax.Array,
    member_mask: jax.Array,
):
    """``Op::Up { dot, k1, op: Up { dot, k2, op: Rm { clock, members } } }``
    — a leaf member remove routed through both map levels. Returns
    ``(state, overflow)``."""
    k = _n1(state) * _n2(state)
    m = _nm(state)
    flat_key = key1 * _n2(state) + key2
    emask = (
        jax.nn.one_hot(flat_key, k, dtype=bool)[..., :, None]
        & member_mask[..., None, :]
    ).reshape(*member_mask.shape[:-1], k * m)
    return LEVEL.apply_up_rm(
        state, actor, counter, rm_clock, emask, levels_down=2
    )


@jax.jit
def apply_key2_rm(
    state: Map3State,
    actor: jax.Array,
    counter: jax.Array,
    key1: jax.Array,
    rm_clock: jax.Array,
    key2_mask: jax.Array,
):
    """``Op::Up { dot, k1, op: Rm { clock, keyset2 } }`` — a middle-map
    keyset-remove routed through the outer map: kill covered content at
    (k1, keyset2) (parking in the K2 buffer if ahead), then witness the
    Up's dot. Returns ``(state, overflow)``."""
    k1n, k2n = _n1(state), _n2(state)
    fmask = (
        jax.nn.one_hot(key1, k1n, dtype=bool)[..., :, None]
        & key2_mask[..., None, :]
    ).reshape(*key2_mask.shape[:-1], k1n * k2n)
    return LEVEL.apply_up_rm(
        state, actor, counter, rm_clock, fmask, levels_down=1
    )


@jax.jit
def apply_key1_rm(state: Map3State, rm_clock: jax.Array, key1_mask: jax.Array):
    """``Op::Rm { clock, keyset }`` on the outer map (reference:
    src/map.rs ``apply_keyset_rm``): kill covered leaf dots across the
    masked K1 blocks now; park in the K1 buffer if the clock is ahead.
    Returns ``(state, overflow)``."""
    return LEVEL.rm_parked(state, rm_clock, key1_mask)


# ---- static-analysis registration (crdt_tpu.analysis) --------------------

def _law_states():
    """Depth-3 adds, routed K2 keyset-removes, and covered/ahead K1
    removes over a 2×2×2 universe with headroom."""
    cl = lambda x, y: jnp.array([x, y], jnp.uint32)
    m0 = jnp.array([True, False])
    mb = jnp.array([True, True])
    k0 = jnp.array([True, False])
    kb = jnp.array([True, True])
    e = empty(2, 2, 2, 2, deferred_cap=4)
    a1 = apply_member_add(e, 0, jnp.uint32(1), 0, 0, m0)
    a2 = apply_member_add(a1, 0, jnp.uint32(2), 1, 1, mb)
    b1 = apply_member_add(e, 1, jnp.uint32(1), 0, 1, mb)
    k2r, _ = apply_key2_rm(a2, 0, jnp.uint32(3), 0, cl(1, 0), kb)
    k1r1, _ = apply_key1_rm(b1, cl(0, 1), k0)  # covered K1 rm
    k1r2, _ = apply_key1_rm(a1, cl(0, 2), kb)  # ahead: parks in K1 buffer
    return [e, a1, a2, b1, k2r, k1r1, k1r2]


def _law_canon(s: Map3State) -> Map3State:
    from ..analysis.canon import canon_epochs
    from .map_orswot import _law_canon as _canon_core

    odcl, odkeys, odvalid = canon_epochs(s.odcl, s.odkeys, s.odvalid)
    return Map3State(
        mo=_canon_core(s.mo), odcl=odcl, odkeys=odkeys, odvalid=odvalid,
    )


@jax.jit
def compact(state: Map3State, frontier: jax.Array):
    """Causal-stability compaction (reclaim/): retire stable parked K1
    removes, then compact the flat ``map_orswot`` core (K2 buffer +
    leaf orswot buffer + dead-slot scrub) — three buffer levels, one
    frontier. Returns ``(state, freed_slots, freed_bytes)``."""
    from ..reclaim.compaction import retire_epochs

    mo, n0, b0 = mo_ops.compact(state.mo, frontier)
    odcl, odkeys, odvalid, n1, b1 = retire_epochs(
        state.odcl, state.odkeys, state.odvalid, state.mo.core.top, frontier
    )
    return (
        Map3State(mo=mo, odcl=odcl, odkeys=odkeys, odvalid=odvalid),
        n0 + n1,
        b0 + b1,
    )


def _observe(s: Map3State):
    """The observable read: the K1×K2×M membership mask."""
    return mo_ops._observe(s.mo)


def _decomp_split(s: Map3State):
    """Decomposition granularity (delta_opt/): one δ lane per flat
    (k1, k2, member) birth-clock row; top + both parked levels residual."""
    c = s.mo.core
    return (c.ctr,), (
        c.top, c.dcl, c.dmask, c.dvalid,
        s.mo.kdcl, s.mo.kdkeys, s.mo.kdvalid,
        s.odcl, s.odkeys, s.odvalid,
    )


def _decomp_unsplit(rows, res) -> Map3State:
    (ctr,) = rows
    top, dcl, dmask, dvalid, kdcl, kdkeys, kdvalid, odcl, odkeys, odvalid = res
    core = mo_ops.core_ops.OrswotState(
        top=top, ctr=ctr, dcl=dcl, dmask=dmask, dvalid=dvalid
    )
    mo = MapOrswotState(core=core, kdcl=kdcl, kdkeys=kdkeys, kdvalid=kdvalid)
    return Map3State(mo=mo, odcl=odcl, odkeys=odkeys, odvalid=odvalid)


from ..analysis.registry import (  # noqa: E402
    register_compactor,
    register_decomposition,
    register_merge,
)

register_merge(
    "map3", module=__name__, join=join, states=_law_states,
    canon=_law_canon,
)
register_compactor(
    "map3", module=__name__, compact=compact, observe=_observe,
    top_of=lambda s: s.mo.core.top,
)
register_decomposition(
    "map3", module=__name__, split=_decomp_split, unsplit=_decomp_unsplit,
)
