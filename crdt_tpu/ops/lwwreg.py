"""Dense LWW-register kernels — max-marker select.

State (``LWWState``, leading axes batch replicas):

- ``hi``/``lo [...]`` — the marker as two uint32 lanes compared
  lexicographically (so 64-bit timestamps survive JAX's x64-disabled
  default),
- ``val [...]``      — interned value id (int32),
- ``has [...]``      — written-at-least-once mask (a fresh register's
  marker is the reference's implicit bottom).

``join`` keeps the strictly-newer write; an equal marker guarding a
different value raises the reference's conflicting-marker validation error
at the model layer via the returned ``conflict`` mask. Oracle:
``crdt_tpu.pure.lwwreg.LWWReg`` (reference: src/lwwreg.rs — update keeps
max marker; validate_merge rejects equal-marker/different-val).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

MARKER_DTYPE = jnp.uint32
VAL_DTYPE = jnp.int32


class LWWState(NamedTuple):
    hi: jax.Array   # [...]
    lo: jax.Array   # [...]
    val: jax.Array  # [...]
    has: jax.Array  # [...]


def empty(batch: tuple = ()) -> LWWState:
    return LWWState(
        hi=jnp.zeros(batch, MARKER_DTYPE),
        lo=jnp.zeros(batch, MARKER_DTYPE),
        val=jnp.zeros(batch, VAL_DTYPE),
        has=jnp.zeros(batch, bool),
    )


def _newer(a: LWWState, b: LWWState) -> jax.Array:
    """b's marker strictly above a's (lexicographic on (hi, lo)),
    or a never written."""
    gt = (b.hi > a.hi) | ((b.hi == a.hi) & (b.lo > a.lo))
    return b.has & (~a.has | gt)


@jax.jit
def join(a: LWWState, b: LWWState):
    """Keep the max-marker write. Returns ``(state, conflict)`` where
    ``conflict`` marks lanes with equal markers guarding different values
    (reference: src/lwwreg.rs validate_merge) — callers must surface it."""
    take_b = _newer(a, b)
    out = LWWState(
        hi=jnp.where(take_b, b.hi, a.hi),
        lo=jnp.where(take_b, b.lo, a.lo),
        val=jnp.where(take_b, b.val, a.val),
        has=a.has | b.has,
    )
    conflict = (
        a.has
        & b.has
        & (a.hi == b.hi)
        & (a.lo == b.lo)
        & (a.val != b.val)
    )
    return out, conflict


def fold(states: LWWState):
    """Join over the leading replica axis via a log2 reduction tree.
    Returns ``(state, conflict)``; conflict is any-reduced."""
    from .lattice import tree_fold

    return tree_fold(states, empty(), join)


@jax.jit
def apply_update(state: LWWState, hi, lo, val):
    """CmRDT apply: take (val, marker) iff strictly newer (equal markers
    keep the incumbent — idempotent replay). Returns ``(state, conflict)``.
    Reference: src/lwwreg.rs LWWReg::update."""
    put = LWWState(
        hi=jnp.asarray(hi, MARKER_DTYPE),
        lo=jnp.asarray(lo, MARKER_DTYPE),
        val=jnp.asarray(val, VAL_DTYPE),
        has=jnp.ones(jnp.shape(hi), bool),
    )
    return join(state, put)


# ---- static-analysis registration (crdt_tpu.analysis) --------------------

def _law_states():
    """Exhaustive over 2-bit markers, CONFLICT-FREE by construction: the
    value is a function of the marker (equal markers guarding different
    values are the documented validation error — join returns the
    ``conflict`` mask and the lattice laws only hold on the conflict-free
    domain, exactly like the reference's validate_merge)."""
    states = [empty()]
    for hi in range(2):
        for lo in range(2):
            s, _ = apply_update(empty(), hi, lo, hi * 2 + lo + 1)
            states.append(s)
    return states


def _decomp_split(s: LWWState):
    """Decomposition granularity (delta_opt/): ONE lane — a register's
    single surviving write is itself join-irreducible (max-marker select
    cannot be split finer); no residual."""
    return jax.tree.map(lambda x: x[None], s), ()


def _decomp_unsplit(rows, res) -> LWWState:
    return jax.tree.map(lambda x: x[0], rows)


from ..analysis.registry import (  # noqa: E402
    register_compactor,
    register_decomposition,
    register_merge,
)
from ..reclaim.compaction import _noop_compact  # noqa: E402

register_merge("lwwreg", module=__name__, join=join, states=_law_states)
# One marker + one value IS the state — nothing reclaimable; identity
# compactor keeps the reclaim/ coverage contract total.
register_compactor(
    "lwwreg", module=__name__, compact=_noop_compact, observe=lambda s: s,
    top_of=None,
)
register_decomposition(
    "lwwreg", module=__name__, split=_decomp_split, unsplit=_decomp_unsplit,
)
