"""Dense MV-register kernels — sibling slots under domination filtering.

State (``MVRegState``): S sibling slots over an A-actor universe, leading
axes batch replicas:

- ``wact``/``wctr [..., S]`` — each sibling's witness dot (the AddCtx dot
  that minted the write; the DotFun key, see pure/mvreg.py),
- ``clk [..., S, A]``       — each sibling's full write clock,
- ``val [..., S]``          — interned value id,
- ``valid [..., S]``        — live-slot mask.

``join`` is the reference's merge (src/mvreg.rs): a sibling survives iff no
sibling on the other side strictly dominates its write clock; surviving
slots are unioned, deduped by witness dot (same dot ⇒ same content), and
compacted to capacity with an overflow flag (like the ORSWOT deferred
buffer — models raise rather than drop siblings). Oracle:
``crdt_tpu.pure.mvreg.MVReg``.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from .orswot import _pad_tail

DTYPE = jnp.uint32


class MVRegState(NamedTuple):
    wact: jax.Array   # [..., S] int32
    wctr: jax.Array   # [..., S] uint32
    clk: jax.Array    # [..., S, A] uint32
    val: jax.Array    # [..., S] int32
    valid: jax.Array  # [..., S] bool


def empty(n_slots: int, n_actors: int, batch: tuple = ()) -> MVRegState:
    return MVRegState(
        wact=jnp.zeros((*batch, n_slots), jnp.int32),
        wctr=jnp.zeros((*batch, n_slots), DTYPE),
        clk=jnp.zeros((*batch, n_slots, n_actors), DTYPE),
        val=jnp.zeros((*batch, n_slots), jnp.int32),
        valid=jnp.zeros((*batch, n_slots), bool),
    )


def widen(state: MVRegState, n_slots: int = 0, n_actors: int = 0) -> MVRegState:
    """Re-encode into a wider sibling-slot/actor layout (elastic.py).
    Slot tables are canonical valid-first, so tail padding with dead
    slots preserves canonical form; new actor lanes are zero (= unseen).
    0 keeps the current width; shrinking is refused."""
    s, a = state.clk.shape[-2:]
    ns, na = n_slots or s, n_actors or a
    if ns < s or na < a:
        raise ValueError(f"widen cannot shrink: ({s}, {a}) -> ({ns}, {na})")
    lead = state.wact.ndim - 1
    pad = partial(_pad_tail, lead=lead)
    return MVRegState(
        wact=pad(state.wact, (0, ns - s)),
        wctr=pad(state.wctr, (0, ns - s)),
        clk=pad(state.clk, (0, ns - s), (0, na - a)),
        val=pad(state.val, (0, ns - s)),
        valid=pad(state.valid, (0, ns - s)),
    )


def narrow(state: MVRegState, n_slots: int = 0, n_actors: int = 0) -> MVRegState:
    """The inverse of :func:`widen` — slice tail sibling/actor lanes
    off (elastic.shrink drives this through the map kinds). Slot tables
    are canonical valid-first, so narrowing is tail slicing once the
    occupancy check passes; live data in a dropped lane REFUSES."""
    s, a = state.clk.shape[-2:]
    ns, na = n_slots or s, n_actors or a
    if ns > s or na > a:
        raise ValueError(f"narrow cannot grow: ({s}, {a}) -> ({ns}, {na})")
    live = []
    if ns < s and bool(jnp.any(state.valid[..., ns:])):
        live.append(f"n_slots {s}->{ns}")
    if na < a and bool(
        jnp.any(state.clk[..., na:]) | jnp.any(state.valid & (state.wact >= na))
    ):
        live.append(f"n_actors {a}->{na}")
    if live:
        raise ValueError(
            f"narrow refused — dropped lanes hold live state: {live}"
        )
    return MVRegState(
        wact=state.wact[..., :ns],
        wctr=state.wctr[..., :ns],
        clk=state.clk[..., :ns, :na],
        val=state.val[..., :ns],
        valid=state.valid[..., :ns],
    )


def _strictly_dominated(clk_a, valid_a, clk_b, valid_b) -> jax.Array:
    """For each slot i of a: ∃ valid j in b with clk_a[i] < clk_b[j]
    (partial-order strict less: all lanes ≤ and some lane <)."""
    le = jnp.all(clk_a[..., :, None, :] <= clk_b[..., None, :, :], axis=-1)
    lt = jnp.any(clk_a[..., :, None, :] < clk_b[..., None, :, :], axis=-1)
    strict = le & lt & valid_a[..., :, None] & valid_b[..., None, :]
    return jnp.any(strict, axis=-1)


def _dedupe_by_witness(state: MVRegState) -> MVRegState:
    """Drop later slots whose witness dot equals an earlier valid slot's
    (same dot ⇒ same content, the oracle's dict-key union)."""
    s = state.wact.shape[-1]
    idx = jnp.arange(s)
    eq = (
        state.valid[..., :, None]
        & state.valid[..., None, :]
        & (state.wact[..., :, None] == state.wact[..., None, :])
        & (state.wctr[..., :, None] == state.wctr[..., None, :])
    )
    rep = jnp.argmax(eq, axis=-2)  # first valid slot with the same dot
    keep = state.valid & (rep == idx)
    return state._replace(valid=keep)


def _compact(state: MVRegState, cap: int):
    """Stable-sort valid slots to the front, truncate to capacity, zero
    dead payload. Slot order still depends on join operand order — raw
    arrays of converged replicas are equal as sets, not bit-for-bit;
    compare via to_pure (ops/map.py adds its own (actor, counter)
    canonical sort on top where raw-array comparability is wanted)."""
    order = jnp.argsort(~state.valid, axis=-1, stable=True)
    wact = jnp.take_along_axis(state.wact, order, axis=-1)
    wctr = jnp.take_along_axis(state.wctr, order, axis=-1)
    clk = jnp.take_along_axis(state.clk, order[..., None], axis=-2)
    val = jnp.take_along_axis(state.val, order, axis=-1)
    valid = jnp.take_along_axis(state.valid, order, axis=-1)
    overflow = jnp.sum(valid, axis=-1) > cap
    wact, wctr, clk = wact[..., :cap], wctr[..., :cap], clk[..., :cap, :]
    val, valid = val[..., :cap], valid[..., :cap]
    return (
        MVRegState(
            wact=jnp.where(valid, wact, 0),
            wctr=jnp.where(valid, wctr, 0),
            clk=jnp.where(valid[..., None], clk, 0),
            val=jnp.where(valid, val, 0),
            valid=valid,
        ),
        overflow,
    )


@jax.jit
def reset_remove(state: MVRegState, clock: jax.Array) -> MVRegState:
    """ResetRemove — forget siblings whose FULL write clock the given
    clock dominates (pure/mvreg.py ``reset_remove``; dot-level removal
    is the separate ``remove_dots_under`` used by Map composition).
    Reference: src/mvreg.rs ResetRemove impl (SURVEY §3.2). Slots only
    die, so compaction cannot overflow."""
    clock = jnp.asarray(clock, state.clk.dtype)
    dead = state.valid & jnp.all(state.clk <= clock[..., None, :], axis=-1)
    out, _ = _compact(
        state._replace(valid=state.valid & ~dead), state.wact.shape[-1]
    )
    return out


@jax.jit
def join(a: MVRegState, b: MVRegState):
    """Pairwise merge: drop strictly-dominated siblings, union the rest.
    Returns ``(state, overflow)``. Reference: src/mvreg.rs CvRDT::merge."""
    keep_a = a.valid & ~_strictly_dominated(a.clk, a.valid, b.clk, b.valid)
    keep_b = b.valid & ~_strictly_dominated(b.clk, b.valid, a.clk, a.valid)
    both = MVRegState(
        wact=jnp.concatenate([a.wact, b.wact], axis=-1),
        wctr=jnp.concatenate([a.wctr, b.wctr], axis=-1),
        clk=jnp.concatenate([a.clk, b.clk], axis=-2),
        val=jnp.concatenate([a.val, b.val], axis=-1),
        valid=jnp.concatenate([keep_a, keep_b], axis=-1),
    )
    return _compact(_dedupe_by_witness(both), a.wact.shape[-1])


def fold(states: MVRegState):
    """Join over the leading replica axis in a log2 reduction tree.
    Returns ``(state, overflow)``."""
    from .lattice import tree_fold

    return tree_fold(states, empty(states.wact.shape[-1], states.clk.shape[-1]), join)


@jax.jit
def apply_put(state: MVRegState, wact, wctr, clock, val):
    """CmRDT apply of ``Op::Put { dot, clock, val }``: a dominated or
    duplicate put is a no-op; otherwise dominated siblings are evicted and
    the put claims a free slot. Returns ``(state, overflow)``.
    Reference: src/mvreg.rs CmRDT::apply."""
    clock = jnp.asarray(clock, state.clk.dtype)
    noop = jnp.all(clock == 0, axis=-1) | jnp.any(
        state.valid & jnp.all(state.clk >= clock[..., None, :], axis=-1), axis=-1
    )
    evict = state.valid & jnp.all(state.clk <= clock[..., None, :], axis=-1) & jnp.any(
        state.clk < clock[..., None, :], axis=-1
    )
    valid = state.valid & ~(evict & ~noop[..., None])

    free = ~valid
    has_free = jnp.any(free, axis=-1)
    slot = jnp.argmax(free, axis=-1)
    write = ~noop & has_free
    overflow = ~noop & ~has_free
    onehot = jax.nn.one_hot(slot, state.valid.shape[-1], dtype=bool) & write[..., None]
    return (
        MVRegState(
            wact=jnp.where(onehot, jnp.asarray(wact, jnp.int32)[..., None], state.wact),
            wctr=jnp.where(onehot, jnp.asarray(wctr, DTYPE)[..., None], state.wctr),
            clk=jnp.where(onehot[..., None], clock[..., None, :], state.clk),
            val=jnp.where(onehot, jnp.asarray(val, jnp.int32)[..., None], state.val),
            valid=valid | onehot,
        ),
        overflow,
    )


# ---- static-analysis registration (crdt_tpu.analysis) --------------------

def _law_states():
    """Concurrent / dominating / duplicate puts over 2 actors with slot
    headroom (S = 6 ≫ the 2-3 live siblings any state holds)."""
    cl = lambda x, y: jnp.array([x, y], DTYPE)
    e = empty(6, 2)
    p0, _ = apply_put(e, 0, 1, cl(1, 0), 5)         # actor-0 write
    p1, _ = apply_put(e, 1, 1, cl(0, 1), 6)         # concurrent actor-1 write
    both, _ = apply_put(p0, 1, 1, cl(0, 1), 6)      # two live siblings
    dom, _ = apply_put(both, 0, 2, cl(2, 1), 7)     # dominates both
    seen, _ = apply_put(dom, 0, 2, cl(2, 1), 7)     # duplicate dot no-op
    p2, _ = apply_put(p1, 1, 2, cl(0, 2), 8)        # actor-1 advances alone
    return [e, p0, p1, both, dom, seen, p2]


def _law_canon(s: MVRegState) -> MVRegState:
    """Sibling slot order depends on join operand order (``_compact``
    docstring) — compare content-ordered."""
    from ..analysis.canon import canon_mvreg

    return canon_mvreg(s)


@jax.jit
def compact(state: MVRegState, frontier: jax.Array):
    """Causal-stability compaction (reclaim/): a register has no parked
    buffer, so the only reclaimable state is the stale payload evicted
    slots leave behind (``apply_put`` flips ``valid`` without
    scrubbing) — zero it and repack valid-first. The frontier is unused
    (nothing here is clock-retired); reads are untouched. Returns
    ``(state, freed_slots, freed_bytes)``."""
    stale = ~state.valid & (
        (state.wact != 0) | (state.wctr != 0) | (state.val != 0)
        | jnp.any(state.clk != 0, axis=-1)
    )
    out, _ = _compact(state, state.wact.shape[-1])
    return (
        out,
        jnp.sum(stale, dtype=jnp.uint32),
        jnp.zeros((), jnp.float32),
    )


def _observe(s: MVRegState):
    """The observable read: the live sibling value set, content-ordered
    (canon_mvreg) so converged replicas compare equal leaf-wise."""
    from ..analysis.canon import canon_mvreg

    c = canon_mvreg(s)
    return (c.val, c.valid)


def _decomp_split(s: MVRegState):
    """Decomposition granularity (delta_opt/): one δ lane per sibling
    slot — a slot's (witness dot, clock, value) is one concurrent write,
    the register's join-irreducible unit; no residual."""
    return s, ()


def _decomp_unsplit(rows, res) -> MVRegState:
    return rows


from ..analysis.registry import (  # noqa: E402
    register_compactor,
    register_decomposition,
    register_merge,
)

register_merge(
    "mvreg", module=__name__, join=join, states=_law_states,
    canon=_law_canon,
)
register_compactor(
    "mvreg", module=__name__, compact=compact, observe=_observe,
    top_of=None,
)
register_decomposition(
    "mvreg", module=__name__, split=_decomp_split, unsplit=_decomp_unsplit,
)
