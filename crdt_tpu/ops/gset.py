"""Dense G-Set kernels — the simplest lattice (union as logical OR).

State is a membership bitmask ``present[..., E]`` over an interned member
universe of E elements; leading axes batch replicas. Oracle:
``crdt_tpu.pure.gset.GSet`` (reference: src/gset.rs — merge = set union,
Op = M). Union over a replica batch is one ``any`` reduction, so full-mesh
anti-entropy of R replicas is a single VPU pass.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def zeros(n_members: int, batch: tuple = ()) -> jax.Array:
    return jnp.zeros((*batch, n_members), dtype=bool)


@jax.jit
def join(a: jax.Array, b: jax.Array) -> jax.Array:
    """Lattice join = set union. Reference: src/gset.rs CvRDT::merge."""
    return a | b


@jax.jit
def fold(present: jax.Array) -> jax.Array:
    """N-way union over the leading replica axis."""
    return jnp.any(present, axis=0)


@jax.jit
def insert(present: jax.Array, member: jax.Array) -> jax.Array:
    """CmRDT apply (Op = the member id). Reference: src/gset.rs insert."""
    return present.at[..., member].set(True)


@jax.jit
def contains(present: jax.Array, member: jax.Array) -> jax.Array:
    return present[..., member]


# ---- static-analysis registration (crdt_tpu.analysis) --------------------

def _law_states():
    """Exhaustive: every subset of a 3-member universe (identity first)."""
    return [
        jnp.array([bool(bits >> i & 1) for i in range(3)])
        for bits in range(8)
    ]


def _decomp_split(s: jax.Array):
    """Decomposition granularity (delta_opt/): one δ lane per member's
    presence bit — the G-Set's join-irreducibles ARE its singletons; no
    residual."""
    return (s,), ()


def _decomp_unsplit(rows, res) -> jax.Array:
    (present,) = rows
    return present


from ..analysis.registry import (  # noqa: E402
    register_compactor,
    register_decomposition,
    register_merge,
)
from ..reclaim.compaction import _noop_compact  # noqa: E402

register_merge("gset", module=__name__, join=join, states=_law_states)
# A G-Set is its own observable read and holds no causal metadata — the
# identity compactor keeps the reclaim/ coverage contract total.
register_compactor(
    "gset", module=__name__, compact=_noop_compact, observe=lambda s: s,
    top_of=None,
)
register_decomposition(
    "gset", module=__name__, split=_decomp_split, unsplit=_decomp_unsplit,
)
