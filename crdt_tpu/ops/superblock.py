"""Tenant-packed superblock kernels — many independent CRDTs, one
dispatch (ROADMAP item 1, ISSUE 15).

Everything before this module batches *replicas of one object*; the
production workload the north star names is millions of SMALL
INDEPENDENT objects (per-user carts, presence sets, doc cursors), each
a few dots wide. Dispatching one kernel per tenant would drown the
device in launch overhead, so the superblock prepends a TENANT axis to
an existing per-kind state layout — ``T`` independent ORSWOTs live in
one device-resident pytree of ``[T, ...]`` planes — and applies a whole
coalesced batch of per-tenant CmRDT ops as ONE program:

    gather touched rows -> scan S sequential op slots, each a vmapped
    per-tenant apply -> scatter rows back (conflict-free by the ingest
    contract below).

The op container is :class:`OpSlab`: ``B`` tenant lanes × ``S``
sequential slots. Within one slab a tenant occupies AT MOST ONE lane
(the host-side ingest queue — crdt_tpu/serve/ingest.py — enforces it),
so the row scatter has unique targets; a lane's ``S`` slots apply in
submission order, which is exactly why the coalesced apply is
bit-identical to the per-tenant sequential oracle (tests/test_serve.py
pins it for the dense AND sparse kinds). Tenants are INDEPENDENT —
no cross-tenant lattice traffic exists, so the tenant axis shards
embarrassingly over the replica mesh axis
(crdt_tpu/parallel/serve_apply.py).

Per-kind support rides a small adapter table (:data:`TENANT_KINDS`)
over the already-registered op kernels — the superblock is a PRODUCT
of registered lattices, not a new lattice, so it registers no new
merge kind (the per-tenant joins are the registered ``orswot`` /
``sparse_orswot`` kinds the law engine and SEC checker already cover);
its own coverage contract is the ``serve`` static-check section plus
the ``mesh_serve_apply`` entry-point registration.

Capacity is elastic PER SUPERBLOCK: ``widen``/``narrow`` lift the
per-kind elastic kernels (PR 1/5) over the tenant axis — one repack
migrates every tenant at once. Causal-stability compaction lifts the
same way (:func:`compact_tenants`).
"""

from __future__ import annotations

from typing import Callable, Dict, NamedTuple, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from . import orswot as dense_ops
from . import sparse_orswot as sparse_ops

# Op slot kinds. NOOP lanes/slots apply the identity — padding never
# touches state, so a partially-filled slab is sound by construction.
NOOP, ADD, RM = 0, 1, 2


class OpSlab(NamedTuple):
    """One coalesced batch of per-tenant ops: ``B`` tenant lanes × ``S``
    sequential slots (slot axis applies in order; the per-tenant
    submission order). ``member`` is the kind's op member descriptor:
    a ``bool[B, S, E]`` element mask for the dense kind, an
    ``int32[B, S, W]`` element-id list (-1 = pad) for the sparse kind.
    """

    kind: jax.Array    # [B, S] uint8 — NOOP / ADD / RM
    actor: jax.Array   # [B, S] int32 — add mint site
    ctr: jax.Array     # [B, S] uint32 — add counter
    clock: jax.Array   # [B, S, A] uint32 — rm clock
    member: jax.Array  # [B, S, *] — per-kind member descriptor


class TenantKind(NamedTuple):
    """One superblock-capable kind: the per-kind kernels the slab apply
    composes, normalized so ADD and RM both return
    ``(state, overflow)``. ``member_plane(caps)`` gives the op member
    descriptor's trailing shape / dtype / pad fill; ``caps`` is the
    kind's capacity dict (the ``empty`` kwargs minus ``batch``)."""

    name: str
    empty: Callable          # (**caps, batch=...) -> state
    apply_add: Callable      # (state, actor, ctr, member) -> (state, of)
    apply_rm: Callable       # (state, clock, member) -> (state, of)
    member_plane: Callable   # caps -> (shape tuple, dtype, fill)
    changed: Callable        # (a, b) -> uint32 changed-lane count
    join: Callable           # (a, b) -> (state, overflow)
    compact: Callable        # (state, frontier) -> (state, n, bytes)
    widen: Callable
    narrow: Callable
    observe: Callable        # state -> observable read pytree
    n_actors_of: Callable    # state -> A (clock lane width)
    caps_of: Callable        # state -> its capacity dict (empty kwargs)


def _dense_add(state, actor, ctr, member):
    return dense_ops.apply_add(state, actor, ctr, member), jnp.zeros((), bool)


TENANT_KINDS: Dict[str, TenantKind] = {
    "orswot": TenantKind(
        name="orswot",
        empty=dense_ops.empty,
        apply_add=_dense_add,
        apply_rm=dense_ops.apply_rm,
        member_plane=lambda caps: ((caps["n_elems"],), jnp.bool_, False),
        changed=dense_ops.changed_members,
        join=dense_ops.join,
        compact=dense_ops.compact,
        widen=dense_ops.widen,
        narrow=dense_ops.narrow,
        observe=lambda s: jnp.any(s.ctr > 0, axis=-1),
        n_actors_of=lambda s: s.top.shape[-1],
        caps_of=lambda s: dict(
            n_elems=s.ctr.shape[-2], n_actors=s.top.shape[-1],
            deferred_cap=s.dvalid.shape[-1],
        ),
    ),
    "sparse_orswot": TenantKind(
        name="sparse_orswot",
        empty=sparse_ops.empty,
        apply_add=sparse_ops.apply_add,
        apply_rm=sparse_ops.apply_rm,
        # One list width for ADD and RM: the rm width bounds both, so a
        # parked remove's element list always fits its didx lanes.
        member_plane=lambda caps: ((caps["rm_width"],), jnp.int32, -1),
        changed=sparse_ops.changed_dots,
        join=sparse_ops.join,
        compact=sparse_ops.compact,
        widen=sparse_ops.widen,
        narrow=sparse_ops.narrow,
        observe=lambda s: (s.eid, s.act, s.ctr, s.valid),
        n_actors_of=lambda s: s.top.shape[-1],
        caps_of=lambda s: dict(
            dot_cap=s.eid.shape[-1], n_actors=s.top.shape[-1],
            deferred_cap=s.dvalid.shape[-1], rm_width=s.didx.shape[-1],
        ),
    ),
}


def tenant_kind(name: str) -> TenantKind:
    if name not in TENANT_KINDS:
        raise KeyError(
            f"no superblock adapter for kind {name!r} "
            f"(know {sorted(TENANT_KINDS)})"
        )
    return TENANT_KINDS[name]


# ---- pack / unpack --------------------------------------------------------

def pack(states: Sequence):
    """Stack per-tenant states (uniform shapes) into one superblock —
    tenant axis prepended on every plane. Exact inverse of
    :func:`unpack` row-wise (the round-trip property in
    tests/test_serve.py)."""
    states = list(states)
    if not states:
        raise ValueError("pack() of zero tenants")
    shapes = {
        tuple(x.shape for x in jax.tree.leaves(s)) for s in states
    }
    if len(shapes) != 1:
        raise ValueError(
            f"pack() needs uniform per-tenant shapes, got {len(shapes)} "
            "distinct layouts — widen the narrow tenants first"
        )
    return jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *states)


def unpack(superblock, tenant: int):
    """One tenant's state, sliced off the tenant axis."""
    return jax.tree.map(lambda x: x[tenant], superblock)


@jax.jit
def gather_rows(superblock, idx: jax.Array):
    """Rows ``idx`` of the superblock (out-of-range indices clamp —
    callers mask separately; the slab path routes invalid lanes to
    NOOP ops, so a clamped gather is never observable)."""
    return jax.tree.map(lambda x: x[idx], superblock)


@jax.jit
def write_rows(superblock, idx: jax.Array, rows):
    """Scatter per-tenant rows back (unique ``idx`` by the ingest
    contract; negative indices drop via the out-of-range lane)."""
    t = jax.tree.leaves(superblock)[0].shape[0]
    safe = jnp.where(idx >= 0, idx, t)
    return jax.tree.map(
        lambda x, r: x.at[safe].set(r, mode="drop"), superblock, rows
    )


# ---- the coalesced slab apply --------------------------------------------

def empty_slab(tk: TenantKind, caps: dict, lanes: int, depth: int) -> OpSlab:
    """An all-NOOP slab of ``lanes`` × ``depth`` for capacity dict
    ``caps`` — the fill target the ingest queue writes into."""
    a = caps["n_actors"]
    mshape, mdtype, mfill = tk.member_plane(caps)
    return OpSlab(
        kind=jnp.zeros((lanes, depth), jnp.uint8),
        actor=jnp.zeros((lanes, depth), jnp.int32),
        ctr=jnp.zeros((lanes, depth), jnp.uint32),
        clock=jnp.zeros((lanes, depth, a), jnp.uint32),
        member=jnp.full((lanes, depth, *mshape), mfill, mdtype),
    )


def apply_slab_rows(tk: TenantKind, rows, slab: OpSlab):
    """Apply one slab to its gathered tenant rows: ``S`` sequential
    steps (lax.scan), each step one VMAPPED per-tenant op across all
    ``B`` lanes. NOOP slots keep the row bit-identical. Returns
    ``(rows, overflow[B])`` — overflow is the per-tenant deferred /
    dot-capacity pressure signal the serve layer widens on."""

    def one(state, k, actor, ctr, clock, member):
        added, of_a = tk.apply_add(state, actor, ctr, member)
        removed, of_r = tk.apply_rm(state, clock, member)
        is_add, is_rm = k == ADD, k == RM

        def pick(a, r, s):
            return jnp.where(is_add, a, jnp.where(is_rm, r, s))

        new = jax.tree.map(pick, added, removed, state)
        return new, (is_add & of_a) | (is_rm & of_r)

    def step(rows, sl):
        return jax.vmap(one)(
            rows, sl.kind, sl.actor, sl.ctr, sl.clock, sl.member
        )

    slab_s = jax.tree.map(lambda x: jnp.moveaxis(x, 1, 0), slab)
    rows, of = lax.scan(step, rows, slab_s)
    return rows, jnp.any(of, axis=0)


def compact_tenants(tk: TenantKind, superblock, frontier):
    """Causal-stability compaction lifted over the tenant axis: every
    tenant's registered compact kernel in one vmapped pass.
    ``frontier[T, A]`` is per-tenant (each tenant is its own causal
    domain — a single-replica tenant's own top IS its stable frontier).
    Returns ``(superblock, freed_slots, freed_bytes)`` summed over
    tenants."""
    out, freed, freed_b = jax.vmap(tk.compact)(superblock, frontier)
    return (
        out,
        jnp.sum(freed).astype(jnp.uint32),
        jnp.sum(freed_b).astype(jnp.float32),
    )


def sequential_oracle(tk: TenantKind, state, ops_list):
    """The per-tenant SEQUENTIAL oracle: apply one tenant's op stream
    one dispatch at a time on its unbatched state — the bit-identity
    reference for the coalesced slab apply (``bench.py --serve`` and
    tests/test_serve.py both gate on it). ``ops_list`` entries are
    ``(kind, actor, ctr, clock, member)`` host tuples."""
    for k, actor, ctr, clock, member in ops_list:
        if k == ADD:
            state, _ = tk.apply_add(
                state, jnp.int32(actor), jnp.uint32(ctr), jnp.asarray(member)
            )
        elif k == RM:
            state, _ = tk.apply_rm(
                state, jnp.asarray(clock, jnp.uint32), jnp.asarray(member)
            )
    return state


__all__ = [
    "ADD", "NOOP", "OpSlab", "RM", "TENANT_KINDS", "TenantKind",
    "apply_slab_rows", "compact_tenants", "empty_slab", "gather_rows",
    "pack", "sequential_oracle", "tenant_kind", "unpack", "write_rows",
]
