"""Dense vector-clock kernels.

A clock is ``counters[..., A]`` (uint32, one lane per interned actor,
0 = never seen). Leading axes batch replicas — every kernel broadcasts, so
``vmap``/sharding fall out for free. Oracle: ``crdt_tpu.vclock.VClock``
(reference: src/vclock.rs); bit-identity is asserted in
tests/test_ops_vclock.py.

The two hot kernels of the whole framework (SURVEY.md §3 row 2): ``merge``
(element-wise max — the lattice join the north star collapses anti-entropy
into) and ``compare`` (sign analysis of the pairwise difference).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# compare() result codes. None (concurrent) has no scalar analog, so the
# device encoding is: -1 less, 0 equal, 1 greater, 2 concurrent.
LESS, EQUAL, GREATER, CONCURRENT = -1, 0, 1, 2


def counter_dtype():
    """The configured clock/counter lane dtype (config.counter_dtype —
    u64 restores reference src/vclock.rs width for the counter family;
    every kernel below is dtype-generic)."""
    from ..config import config

    return jnp.uint64 if config.counter_dtype == "uint64" else jnp.uint32


def zeros(n_actors: int, batch: tuple = ()) -> jax.Array:
    return jnp.zeros((*batch, n_actors), dtype=counter_dtype())


@jax.jit
def merge(a: jax.Array, b: jax.Array) -> jax.Array:
    """Join: element-wise max. Reference: src/vclock.rs CvRDT::merge."""
    return jnp.maximum(a, b)


@jax.jit
def fold(clocks: jax.Array) -> jax.Array:
    """N-way join over the leading replica axis: one reduction, valid
    because the join is associative/commutative/idempotent."""
    return jnp.max(clocks, axis=0)


@jax.jit
def dominates(a: jax.Array, b: jax.Array) -> jax.Array:
    """``b <= a`` in the partial order (all counters)."""
    return jnp.all(a >= b, axis=-1)


@jax.jit
def compare(a: jax.Array, b: jax.Array) -> jax.Array:
    """Partial-order compare: -1/0/1/2(concurrent).

    Reference: src/vclock.rs ``PartialOrd::partial_cmp`` (None =
    concurrent).
    """
    le = jnp.all(a <= b, axis=-1)
    ge = jnp.all(a >= b, axis=-1)
    return jnp.where(
        le & ge,
        EQUAL,
        jnp.where(le, LESS, jnp.where(ge, GREATER, CONCURRENT)),
    ).astype(jnp.int8)


@jax.jit
def apply_dot(clock: jax.Array, actor: jax.Array, counter: jax.Array) -> jax.Array:
    """Observe a dot (monotone max at the actor lane).

    Reference: src/vclock.rs ``CmRDT::apply`` (Op = Dot).
    """
    return clock.at[..., actor].max(counter.astype(clock.dtype))


@jax.jit
def inc(clock: jax.Array, actor: jax.Array) -> jax.Array:
    """Advance the actor's lane by one (mint-and-apply fused — the device
    form of ``inc`` + ``apply``)."""
    return clock.at[..., actor].add(jnp.asarray(1, clock.dtype))


@jax.jit
def reset_remove(clock: jax.Array, other: jax.Array) -> jax.Array:
    """Forget dots dominated by ``other``: zero lanes where
    clock[a] <= other[a]. Reference: src/vclock.rs ResetRemove/forget."""
    return jnp.where(clock <= other, jnp.zeros_like(clock), clock)


@jax.jit
def glb(a: jax.Array, b: jax.Array) -> jax.Array:
    """Greatest lower bound: element-wise min. Reference: src/vclock.rs
    ``glb``/``intersection``."""
    return jnp.minimum(a, b)


@jax.jit
def clone_without(c: jax.Array, base: jax.Array) -> jax.Array:
    """Keep only dots not dominated by ``base`` (c[a] > base[a]).

    Reference: src/vclock.rs ``clone_without``.
    """
    return jnp.where(c > base, c, jnp.zeros_like(c))


@jax.jit
def is_empty(clock: jax.Array) -> jax.Array:
    return jnp.all(clock == 0, axis=-1)


@jax.jit
def pairwise_merge_matrix(clocks: jax.Array) -> jax.Array:
    """All-pairs join of ``clocks[R, A]`` → ``[R, R, A]`` (BASELINE
    config 2's kernel): vmap over both replica axes."""
    return jax.vmap(lambda a: jax.vmap(lambda b: jnp.maximum(a, b))(clocks))(
        clocks
    )


# ---- static-analysis registration (crdt_tpu.analysis) --------------------

def _law_states():
    """Exhaustive: 2-actor clocks with counters in {0, 1, 2} (identity
    first)."""
    return [
        jnp.array([i, j], counter_dtype())
        for i in range(3) for j in range(3)
    ]


def _decomp_split(s: jax.Array):
    """Decomposition granularity (delta_opt/): one δ lane per actor
    counter — a clock's join-irreducibles are its per-actor dots, and
    the lane diff ships exactly the advanced actors; no residual."""
    return (s,), ()


def _decomp_unsplit(rows, res) -> jax.Array:
    (counters,) = rows
    return counters


from ..analysis.registry import (  # noqa: E402
    register_compactor,
    register_decomposition,
    register_merge,
)
from ..reclaim.compaction import _noop_compact  # noqa: E402

register_merge("vclock", module=__name__, join=merge, states=_law_states)
# A clock's read IS the clock; frontier-dominated lanes are exactly the
# read, so nothing can be discarded read-invariantly — identity
# compactor (actor-LANE reclamation is lifecycle.compact_actors, an
# administrative host-side migration, not a kernel).
register_compactor(
    "vclock", module=__name__, compact=_noop_compact, observe=lambda s: s,
    top_of=None,
)
register_decomposition(
    "vclock", module=__name__, split=_decomp_split, unsplit=_decomp_unsplit,
)
