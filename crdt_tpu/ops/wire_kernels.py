"""The fused δ wire kernel: gate ∧ mask ∧ encode ∧ checksum ∧ count in
ONE pass over the packet's clock lanes.

By PR 12 every δ ring round made five separate elementwise passes over
the outbound packet planes — the digest gate (PR 3), the ack-window
mask (PR 9), the integrity checksum lane (PR 8), the fault draws'
payload walk (PR 8), and the telemetry byte counters (PR 2/12) — each
a full read of the same lanes, exactly the layered-HBM-traffic shape
the fused fold in :mod:`.pallas_kernels` was built to kill for merges.
This module is the wire-side twin: one Pallas kernel reads each slot's
clock lanes ONCE and emits

- the **gate verdicts** — digest-covered (``ctxs == know`` ∧
  ``know <= digest``, the ``gate_delta`` rule) and ack-covered
  (content equal to the peer's positively confirmed rows under a
  covered context, the ``ackwin.gate_window`` rule) — so the two
  redundancy layers cost no extra reads;
- the **bit-packed encoding** — every clock lane delta-encoded against
  the link watermark as a biased u16 (`(value - base) + 32768`, exact
  for values within ±32 Ki of the base) with TWO lanes packed per u32
  wire word (the half-split pairing: output word ``j`` carries input
  columns ``j`` and ``W + j``), masked slots zeroed so the wire stays
  canonical;
- the **fit mask** — slots whose encoding would not round-trip are
  DEFERRED (shipped invalid; the ring re-marks them dirty and the
  residue certificate counts the starvation — parallel/wire.py
  documents the soundness contract);
- the **checksum partial** — the position-weighted modular sum of the
  output words, bit-equal to what ``faults.integrity.checksum`` would
  compute for this leaf, so the receiver verifies the wire with the
  stock integrity lane;
- the **packed-word count** — nonzero output words, the
  ``wire_packed_bytes`` telemetry unit.

The kernel is ONE program; each δ flavor (dense, map, map3/map_orswot
nested) instantiates it with its own static lane map (column ranges of
the ctx plane, gate/ack flags — :class:`WireLaneSpec`), so autotuning
and the static-analysis surface registry see one kernel FAMILY with
per-flavor instances (``tools/tile_table.json`` entries carry
``family: "wire"`` — :func:`.pallas_kernels._pick_r_chunk` refuses to
reuse fold-family tiles here).

Backend dispatch follows :func:`.pallas_kernels._fused_backend`:
compiled on TPU, the Pallas **interpreter** elsewhere — the interpret
path traces to plain lax ops, so CPU tier-1 exercises bit-identical
kernel semantics (tests/test_wire.py pins fused == layered per
flavor).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_kernels import _fused_backend, _pick_r_chunk

# The biased-u16 window: a clock lane encodes exactly when its value is
# within [-BIAS, BIAS - 1] of the link watermark (wrapping arithmetic —
# exact round-trip for any unsigned clock dtype).
BIAS = 32768
_U16 = 65536


class WireLaneSpec(NamedTuple):
    """The static lane map of one flavor's slot clock matrix.

    ``lc`` clock columns per slot (concatenated plane columns, in the
    flavor codec's declared order), with the per-slot causal-context
    plane occupying columns ``[ctx_lo, ctx_hi)``. ``gated`` /
    ``acked`` select which verdict lanes the kernel computes;
    ``parked`` marks the parked-buffer instantiation (no gates — a
    fit failure there is a LOST slot, not a deferral). Hashable: one
    jit/pallas specialization per flavor instance."""

    lc: int
    ctx_lo: int = 0
    ctx_hi: int = 0
    gated: bool = False
    acked: bool = False
    parked: bool = False

    @property
    def w(self) -> int:
        """Output wire words per slot (two u16 lanes per u32 word)."""
        return (self.lc + 1) // 2


class WirePackOut(NamedTuple):
    """One fused pack pass's outputs (all per the kernel's single read
    of the slot lanes)."""

    words: jax.Array   # [N, W] u32 — the bit-packed wire lanes
    keep: jax.Array    # [N] bool — slots that ship (post gate+fit)
    defer: jax.Array   # [N] bool — valid, ungated, but unencodable
    covered: jax.Array # [N] bool — ack-window verdicts (skip-byte unit)
    nnz: jax.Array     # u32 — nonzero wire words (packed-bytes unit)
    chk: jax.Array     # u32 — integrity-checksum partial for `words`


def _wire_kernel(spec: WireLaneSpec, n: int, rc: int, *refs):
    """The fused pass, one row chunk per program. Positional refs
    (presence by spec flags): clocks [RC, LC2], base [RC, LC2],
    valid [RC, 1], [know [RC, A], dig [RC, A]] when gated,
    [winc [RC, LC2], ack_ok [RC, 1]] when acked, then outputs:
    out [RC, W], keep/defer/cov [RC, 1], stats [1, 8] (the same
    revisited block across the sequential row-chunk grid — the
    standard TPU reduction pattern the fold kernel uses). ``n`` is the
    UNPADDED row count — checksum weights must match the shipped
    (unpadded) leaf's flat lane order, so rows are indexed globally
    via the program id."""
    i = 0
    clocks = refs[i][:]; i += 1
    base = refs[i][:]; i += 1
    valid = refs[i][:] != 0; i += 1
    if spec.gated:
        know = refs[i][:]; i += 1
        dig = refs[i][:]; i += 1
    if spec.acked:
        winc = refs[i][:]; i += 1
        ack_ok = refs[i][:] != 0; i += 1
    out_ref, keep_ref, defer_ref, cov_ref, stats_ref = refs[i:]

    ct = clocks.dtype
    lc2 = clocks.shape[-1]
    w = lc2 // 2

    # ---- encode: biased-u16 delta vs the watermark, one read --------
    encb = clocks - base + jnp.asarray(BIAS, ct)   # wraps in ct
    fits = encb < jnp.asarray(_U16, ct)
    # Padded columns hold clocks == base == 0 -> encb == BIAS: fits.
    fit_slot = jnp.min(fits.astype(jnp.int32), axis=-1, keepdims=True) != 0

    # ---- gate verdicts (the delta.gate_delta / ackwin.gate_window
    # rules, evaluated on the same resident lanes) --------------------
    if spec.gated:
        ctxs = clocks[:, spec.ctx_lo:spec.ctx_hi]
        addonly = jnp.min(
            (ctxs == know).astype(jnp.int32), axis=-1, keepdims=True
        ) != 0
        under = jnp.min(
            (know <= dig).astype(jnp.int32), axis=-1, keepdims=True
        ) != 0
        cov_d = valid & addonly & under
    else:
        cov_d = jnp.zeros_like(valid)
    if spec.acked:
        # Content columns are every clock column OUTSIDE the ctx range
        # (padding columns compare equal by construction); the ctx
        # columns check coverage instead of equality.
        is_ctx = (
            (jax.lax.broadcasted_iota(jnp.int32, (1, lc2), 1)
             >= spec.ctx_lo)
            & (jax.lax.broadcasted_iota(jnp.int32, (1, lc2), 1)
               < spec.ctx_hi)
        )
        same = jnp.min(
            jnp.where(is_ctx, 1, (clocks == winc).astype(jnp.int32)),
            axis=-1, keepdims=True,
        ) != 0
        covc = jnp.min(
            jnp.where(is_ctx, (clocks <= winc).astype(jnp.int32), 1),
            axis=-1, keepdims=True,
        ) != 0
        cov_a = valid & ~cov_d & ack_ok & same & covc
    else:
        cov_a = jnp.zeros_like(valid)

    keep = valid & ~cov_d & ~cov_a & fit_slot
    defer = valid & ~cov_d & ~cov_a & ~fit_slot

    # ---- masked pack: two u16 lanes per u32 word (half-split) -------
    enc = jnp.where(keep & fits, encb, jnp.zeros_like(encb)).astype(
        jnp.uint32
    ) & jnp.uint32(0xFFFF)
    words = enc[:, :w] | (enc[:, w:2 * w] << 16)

    # ---- checksum partial + packed-word count, same read ------------
    # Weights replicate integrity._lanes_u32's flat order over the
    # UNPADDED [n, w] leaf; padded rows contribute zero values, so
    # their (out-of-range) weights multiply zeros.
    row0 = pl.program_id(0) * rc
    r_ix = row0 + jax.lax.broadcasted_iota(jnp.int32, words.shape, 0)
    c_ix = jax.lax.broadcasted_iota(jnp.int32, words.shape, 1)
    wts = (jnp.uint32(2) * (r_ix * w + c_ix).astype(jnp.uint32)
           + jnp.uint32(1))
    chk = jnp.sum(words * wts, dtype=jnp.uint32)
    nnz = jnp.sum((words != 0).astype(jnp.uint32), dtype=jnp.uint32)

    out_ref[:] = words
    keep_ref[:] = keep.astype(jnp.int32)
    defer_ref[:] = defer.astype(jnp.int32)
    cov_ref[:] = cov_a.astype(jnp.int32)
    stats = jnp.zeros((1, 8), jnp.uint32)
    stats = stats.at[0, 0].set(nnz).at[0, 1].set(chk)

    first = pl.program_id(0) == 0

    @pl.when(first)
    def _init():
        stats_ref[:] = stats

    @pl.when(jnp.logical_not(first))
    def _acc():
        stats_ref[:] = stats_ref[:] + stats


def wire_pack(
    spec: WireLaneSpec,
    clocks: jax.Array,
    base: jax.Array,
    valid: jax.Array,
    know: Optional[jax.Array] = None,
    dig: Optional[jax.Array] = None,
    winc: Optional[jax.Array] = None,
    ack_ok: Optional[jax.Array] = None,
    interpret: Optional[bool] = None,
) -> WirePackOut:
    """One fused pack pass over a flavor's slot clock matrix
    ``clocks [N, LC]`` with per-lane watermark ``base`` and per-slot
    ``valid``. ``know``/``dig`` feed the digest verdict (``gated``),
    ``winc``/``ack_ok`` the ack verdict (``acked``) — shapes per
    :func:`_wire_kernel`. Returns :class:`WirePackOut`; the ``words``
    leaf is what ships.

    Dispatch follows the fold kernels: compiled on TPU backends, the
    Pallas interpreter elsewhere (bit-identical semantics — the CPU
    tier-1 path)."""
    if interpret is None:
        interpret = not _fused_backend()
    n, lc = clocks.shape
    assert lc == spec.lc, (lc, spec.lc)
    lc2 = 2 * spec.w
    a = max(spec.ctx_hi - spec.ctx_lo, 1)
    # Row-chunk the grid via the shared autotune table, keyed on the
    # WIRE family so fold-family sweeps are never silently reused
    # (tools/tile_table.json; tests/test_wire.py pins the key split).
    rc = _pick_r_chunk(n, a, lc2, None, family="wire")
    steps = (n + rc - 1) // rc
    pad_r = steps * rc - n

    def padded(x, cols=None):
        p = ((0, pad_r), (0, 0 if cols is None else cols - x.shape[-1]))
        return jnp.pad(x, p) if (p[0][1] or p[1][1]) else x

    clocks = padded(clocks, lc2)
    base = padded(base, lc2)
    ins = [clocks, base, padded(valid.astype(jnp.int32)[:, None])]
    row2 = lambda i: (i, 0)
    in_specs = [
        pl.BlockSpec((rc, lc2), row2, memory_space=pltpu.VMEM),
        pl.BlockSpec((rc, lc2), row2, memory_space=pltpu.VMEM),
        pl.BlockSpec((rc, 1), row2, memory_space=pltpu.VMEM),
    ]
    if spec.gated:
        ins += [padded(know), padded(dig)]
        in_specs += [
            pl.BlockSpec((rc, know.shape[-1]), row2,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rc, dig.shape[-1]), row2,
                         memory_space=pltpu.VMEM),
        ]
    if spec.acked:
        ins += [padded(winc, lc2),
                padded(ack_ok.astype(jnp.int32)[:, None])]
        in_specs += [
            pl.BlockSpec((rc, lc2), row2, memory_space=pltpu.VMEM),
            pl.BlockSpec((rc, 1), row2, memory_space=pltpu.VMEM),
        ]
    outs = pl.pallas_call(
        partial(_wire_kernel, spec, n, rc),
        grid=(steps,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((rc, spec.w), row2, memory_space=pltpu.VMEM),
            pl.BlockSpec((rc, 1), row2, memory_space=pltpu.VMEM),
            pl.BlockSpec((rc, 1), row2, memory_space=pltpu.VMEM),
            pl.BlockSpec((rc, 1), row2, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 8), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((steps * rc, spec.w), jnp.uint32),
            jax.ShapeDtypeStruct((steps * rc, 1), jnp.int32),
            jax.ShapeDtypeStruct((steps * rc, 1), jnp.int32),
            jax.ShapeDtypeStruct((steps * rc, 1), jnp.int32),
            jax.ShapeDtypeStruct((1, 8), jnp.uint32),
        ],
        interpret=interpret,
    )(*ins)
    words, keep, defer, cov, stats = outs
    return WirePackOut(
        words=words[:n],
        keep=keep[:n, 0] != 0,
        defer=defer[:n, 0] != 0,
        covered=cov[:n, 0] != 0,
        nnz=stats[0, 0],
        chk=stats[0, 1],
    )


def wire_unpack(
    spec: WireLaneSpec, words: jax.Array, base: jax.Array,
    keep: jax.Array, ct,
) -> jax.Array:
    """Invert :func:`wire_pack`'s encoding for the kept slots:
    ``value = base + (enc16 - BIAS)`` (wrapping in the clock dtype
    ``ct``), zeros elsewhere — bit-exact against the sender's masked
    packet by construction (the round-trip property
    tests/test_wire.py pins). Receive is deliberately plain lax — one
    pass XLA fuses with the apply's gathers; the Pallas kernel earns
    its keep on the SEND side where five layers used to stack."""
    w = spec.w
    lo = (words & jnp.uint32(0xFFFF)).astype(ct)
    hi = (words >> 16).astype(ct)
    enc = jnp.concatenate([lo, hi], axis=-1)[:, :spec.lc]
    dec = base[:, :spec.lc] + enc - jnp.asarray(BIAS, ct)
    sel = keep.reshape((-1, 1))
    return jnp.where(sel, dec, jnp.zeros_like(dec))


# ---- bitmaps: bool planes as u32 words ------------------------------------

def pack_bits(bits: jax.Array) -> jax.Array:
    """A flat bool vector as little-endian u32 bitmap words
    (``ceil(n / 32)`` of them) — the presence/ack masks' wire form.
    Pure lax on static shapes."""
    n = bits.shape[0]
    wn = max((n + 31) // 32, 1)
    padded = jnp.pad(bits.astype(jnp.uint32), (0, wn * 32 - n))
    lanes = padded.reshape(wn, 32)
    shifts = jnp.arange(32, dtype=jnp.uint32)
    return jnp.sum(lanes << shifts[None, :], axis=-1, dtype=jnp.uint32)


def unpack_bits(words: jax.Array, n: int) -> jax.Array:
    """Invert :func:`pack_bits` to the first ``n`` bools."""
    shifts = jnp.arange(32, dtype=jnp.uint32)
    bits = (words[:, None] >> shifts[None, :]) & jnp.uint32(1)
    return bits.reshape(-1)[:n] != 0


def pack_u16_pairs(vals: jax.Array) -> jax.Array:
    """A flat vector of values < 2^16 as half-split u16 pairs in u32
    words (word ``j`` carries lanes ``j`` and ``H + j`` — the same
    pairing convention as the clock kernel). Used for the id planes
    (slot indices, actor ids) whose static bound proves the narrowing
    lossless."""
    n = vals.shape[0]
    h = (n + 1) // 2
    v = jnp.pad(vals.astype(jnp.uint32), (0, 2 * h - n)) & jnp.uint32(0xFFFF)
    return v[:h] | (v[h:] << 16)


def unpack_u16_pairs(words: jax.Array, n: int, dtype) -> jax.Array:
    """Invert :func:`pack_u16_pairs` to the first ``n`` lanes."""
    lo = words & jnp.uint32(0xFFFF)
    hi = words >> 16
    return jnp.concatenate([lo, hi])[:n].astype(dtype)


def leaf_checksum(leaf: jax.Array) -> jax.Array:
    """``integrity.checksum``'s per-leaf partial (position-weighted
    modular sum) for a small host-assembled wire leaf — the chaining
    twin of the kernel's in-pass ``chk`` output
    (parallel/wire.py ``wire_checksum`` composes the two)."""
    from ..faults.integrity import _lanes_u32

    lanes = _lanes_u32(leaf)
    w = jnp.arange(lanes.shape[0], dtype=jnp.uint32) * 2 + 1
    return jnp.sum(lanes * w, dtype=jnp.uint32)


__all__ = [
    "BIAS", "WireLaneSpec", "WirePackOut", "leaf_checksum", "pack_bits",
    "pack_u16_pairs", "unpack_bits", "unpack_u16_pairs", "wire_pack",
    "wire_unpack",
]
