"""Sparse (segment-encoded) ``Map<K, MVReg>`` — the config-4 flavor for
huge key universes.

Reference semantics: src/map.rs ``Map<K, MVReg<_>, A>`` (SURVEY §3 r11
specialised to BASELINE config 4) under the causal-composition rule of
pure/map.py. The dense slab (ops/map.py ``MapState``) spends O(K·S·A)
state on the full key universe; at 100M+ keys — or tiny live sets over
1M-key spaces — that loses to live-content-proportional storage the
same way the flat ORSWOT does (ops/sparse_orswot.py, SURVEY §7.3).

Representation: one segment table of live CELLS. Under the
per-(key, actor) uniqueness invariant (a later write by the same actor
carries a clock ≥ its earlier write's, so apply-time domination evicts
the older one — the same invariant the fused dense kernel rests on,
ops/pallas_kernels._decode_wide), a register map is exactly a set of
cells ``(key, actor) → (witness counter, value, write clock)``:

- ``kid/act/ctr/valid [..., C]``  — the cell dot, canonically sorted by
  (kid, act), dead lanes last (raw arrays of converged replicas are
  bit-comparable),
- ``val [..., C]`` + ``clk [..., C, A]`` — the payload riding the dot,
- ``dcl [..., D, A]`` + ``kidx [..., D, Q]`` — parked keyset-removes as
  (clock, key-LIST) slots (lists where the dense level uses K-wide
  masks — state proportional to the op, not the universe; shared
  machinery with ops/sparse_nest.py's list-flavored buffers).

The join is the cell-granular dot rule of the fused dense path
(ops/pallas_kernels._join_step_cells), matched across sides by binary
search on the packed ``kid·A + act`` key (O(C log C), the same trick as
sparse_orswot._match_other): equal counters keep the cell (same dot ⇒
same payload); otherwise a side's cell survives iff the other side's
top never saw it — at most one side can win, because an actor's
counters are totally ordered and each side's top covers its own dots.
The payload follows the surviving counter. Sibling capacity is a
PER-KEY live-cell bound checked after replay (the dense join's
transient-overflow semantics).

A/B gates: tests/test_sparse_mvmap.py pins this module against the
pure oracle AND bit-for-bit against the dense ``BatchedMap`` through
``to_pure`` on every reachable state.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from .sparse_nest import _park_list
from .sparse_orswot import (
    DTYPE,
    _compact_parked,
    _dedupe_parked,
    _pad_tail,
    _replay_parked,
)

_INT32_MAX = jnp.iinfo(jnp.int32).max


class SparseMVMapState(NamedTuple):
    """A (possibly batched) segment-encoded Map<K, MVReg> replica."""

    top: jax.Array    # [..., A]
    kid: jax.Array    # [..., C] int32 key id (-1 = dead lane)
    act: jax.Array    # [..., C] int32
    ctr: jax.Array    # [..., C] u32 witness counter
    val: jax.Array    # [..., C] int32 interned value
    clk: jax.Array    # [..., C, A] u32 full write clock
    valid: jax.Array  # [..., C]
    dcl: jax.Array    # [..., D, A] parked rm clocks
    kidx: jax.Array   # [..., D, Q] int32 parked key lists (-1 = empty)
    dvalid: jax.Array # [..., D]


def empty(
    cell_cap: int,
    n_actors: int,
    deferred_cap: int = 4,
    rm_width: int = 8,
    batch: tuple = (),
) -> SparseMVMapState:
    """The join identity: no cells, no parked removes."""
    return SparseMVMapState(
        top=jnp.zeros((*batch, n_actors), DTYPE),
        kid=jnp.full((*batch, cell_cap), -1, jnp.int32),
        act=jnp.zeros((*batch, cell_cap), jnp.int32),
        ctr=jnp.zeros((*batch, cell_cap), DTYPE),
        val=jnp.zeros((*batch, cell_cap), jnp.int32),
        clk=jnp.zeros((*batch, cell_cap, n_actors), DTYPE),
        valid=jnp.zeros((*batch, cell_cap), bool),
        dcl=jnp.zeros((*batch, deferred_cap, n_actors), DTYPE),
        kidx=jnp.full((*batch, deferred_cap, rm_width), -1, jnp.int32),
        dvalid=jnp.zeros((*batch, deferred_cap), bool),
    )


def widen(
    state: SparseMVMapState,
    cell_cap: int = 0,
    n_actors: int = 0,
    deferred_cap: int = 0,
    rm_width: int = 0,
) -> SparseMVMapState:
    """Cell-table repack into a wider layout — the elastic capacity
    migration (elastic.py; segment sibling: sparse_orswot.widen). Dead
    lanes sort last under canonical order, so every axis grows by tail
    padding with its dead sentinel; the result is bit-identical to a
    from-scratch wider table holding the same cells. 0 keeps a width;
    shrinking is refused."""
    c, a = state.kid.shape[-1], state.top.shape[-1]
    d, q = state.kidx.shape[-2:]
    nc, na = cell_cap or c, n_actors or a
    nd, nq = deferred_cap or d, rm_width or q
    if nc < c or na < a or nd < d or nq < q:
        raise ValueError(
            f"widen cannot shrink: ({c}, {a}, {d}, {q}) -> "
            f"({nc}, {na}, {nd}, {nq})"
        )
    lead = state.top.ndim - 1
    pad = partial(_pad_tail, lead=lead)
    return SparseMVMapState(
        top=pad(state.top, (0, na - a)),
        kid=pad(state.kid, (0, nc - c), fill=-1),
        act=pad(state.act, (0, nc - c)),
        ctr=pad(state.ctr, (0, nc - c)),
        val=pad(state.val, (0, nc - c)),
        clk=pad(state.clk, (0, nc - c), (0, na - a)),
        valid=pad(state.valid, (0, nc - c), fill=False),
        dcl=pad(state.dcl, (0, nd - d), (0, na - a)),
        kidx=pad(state.kidx, (0, nd - d), (0, nq - q), fill=-1),
        dvalid=pad(state.dvalid, (0, nd - d), fill=False),
    )


def narrow(
    state: SparseMVMapState,
    cell_cap: int = 0,
    n_actors: int = 0,
    deferred_cap: int = 0,
    rm_width: int = 0,
) -> SparseMVMapState:
    """The inverse of :func:`widen` — slice tail lanes off the cell
    table (elastic.shrink drives this). Canonical order keeps dead
    lanes last, so narrowing is tail slicing once the occupancy check
    passes; live data in a dropped lane REFUSES. Run ``compact`` first
    so retired parked slots do not pin lanes. 0 keeps a width."""
    c, a = state.kid.shape[-1], state.top.shape[-1]
    d, q = state.kidx.shape[-2:]
    nc, na = cell_cap or c, n_actors or a
    nd, nq = deferred_cap or d, rm_width or q
    if nc > c or na > a or nd > d or nq > q:
        raise ValueError(
            f"narrow cannot grow: ({c}, {a}, {d}, {q}) -> "
            f"({nc}, {na}, {nd}, {nq})"
        )
    live = []
    if nc < c and bool(jnp.any(state.valid[..., nc:])):
        live.append(f"cell_cap {c}->{nc}")
    if na < a and bool(
        jnp.any(state.top[..., na:]) | jnp.any(state.dcl[..., :, na:])
        | jnp.any(state.clk[..., na:])
        | jnp.any(state.valid & (state.act >= na))
    ):
        live.append(f"n_actors {a}->{na}")
    if nd < d and bool(jnp.any(state.dvalid[..., nd:])):
        live.append(f"deferred_cap {d}->{nd}")
    if nq < q and bool(jnp.any(state.kidx[..., nq:] >= 0)):
        live.append(f"rm_width {q}->{nq}")
    if live:
        raise ValueError(
            f"narrow refused — dropped lanes hold live state: {live} "
            f"(compact first, or shrink less)"
        )
    return SparseMVMapState(
        top=state.top[..., :na],
        kid=state.kid[..., :nc],
        act=state.act[..., :nc],
        ctr=state.ctr[..., :nc],
        val=state.val[..., :nc],
        clk=state.clk[..., :nc, :na],
        valid=state.valid[..., :nc],
        dcl=state.dcl[..., :nd, :na],
        kidx=state.kidx[..., :nd, :nq],
        dvalid=state.dvalid[..., :nd],
    )


def _canon(kid, act, ctr, val, clk, valid, cap: int):
    """Sort live cells by (kid, act), dead lanes last with zeroed
    payload; truncate to ``cap``. Returns the table + overflow flag."""
    # Two keys, not three: the masked kid (MAX sentinel) already sends
    # dead lanes last — live kids are bounded by K·A < 2^31, strictly
    # below the sentinel (see sparse_orswot._canon).
    order = jnp.lexsort(
        (act, jnp.where(valid, kid, _INT32_MAX)), axis=-1
    )
    take = lambda x: jnp.take_along_axis(x, order, axis=-1)
    kid, act, ctr, val, valid = (
        take(kid), take(act), take(ctr), take(val), take(valid)
    )
    clk = jnp.take_along_axis(clk, order[..., None], axis=-2)
    overflow = jnp.sum(valid, axis=-1) > cap
    kid, act, ctr, val, valid = (
        kid[..., :cap], act[..., :cap], ctr[..., :cap],
        val[..., :cap], valid[..., :cap],
    )
    clk = clk[..., :cap, :]
    return (
        jnp.where(valid, kid, -1),
        jnp.where(valid, act, 0),
        jnp.where(valid, ctr, 0),
        jnp.where(valid, val, 0),
        jnp.where(valid[..., None], clk, 0),
        valid,
        overflow,
    )


def _match_pos(kid, act, valid, okid, oact, ovalid, n_act: int):
    """For each cell lane: the OTHER side's lane holding the same
    (key, actor) cell — ``(pos, hit)``. Both tables canonical, so the
    packed key ``kid·A + act`` is ascending over the valid prefix and a
    binary search replaces the all-pairs matrix (int32 bound:
    ``K·A < 2^31``)."""
    if kid.ndim > 1:
        inner = partial(_match_pos, n_act=n_act)
        return jax.vmap(inner)(kid, act, valid, okid, oact, ovalid)
    key = jnp.where(valid, kid * n_act + act, _INT32_MAX)
    okey = jnp.where(ovalid, okid * n_act + oact, _INT32_MAX)
    pos = jnp.clip(jnp.searchsorted(okey, key), 0, okey.shape[-1] - 1)
    hit = valid & jnp.take(ovalid, pos) & (jnp.take(okey, pos) == key)
    return pos, hit


def _sibling_overflow(kid, valid, sibling_cap: int):
    """Per-key live-cell count must stay ≤ sibling_cap. Cells are
    canonically sorted by kid, so a cell's sibling rank is its lane
    index minus its key-run's start (binary search)."""
    if kid.ndim > 1:
        return jax.vmap(partial(_sibling_overflow, sibling_cap=sibling_cap))(
            kid, valid
        )
    kids = jnp.where(valid, kid, _INT32_MAX)
    start = jnp.searchsorted(kids, kids, side="left")
    rank = jnp.arange(kid.shape[-1]) - start
    return jnp.any(valid & (rank >= sibling_cap))


@partial(jax.jit, static_argnames=("sibling_cap",))
def join(a: SparseMVMapState, b: SparseMVMapState, sibling_cap: int = 4):
    """Pairwise lattice join on cell segments — the cell-granular dot
    rule with payload winner-select (reference: src/map.rs
    ``CvRDT::merge`` specialised to MVReg children; dense sibling:
    ops/map.join and the fused ``_join_step_cells``). Returns
    ``(state, overflow[3])``: [cell-capacity, deferred-capacity,
    sibling-capacity] lanes."""
    n_act = a.top.shape[-1]
    pos_a, hit_a = _match_pos(
        a.kid, a.act, a.valid, b.kid, b.act, b.valid, n_act
    )
    _, hit_b = _match_pos(
        b.kid, b.act, b.valid, a.kid, a.act, a.valid, n_act
    )
    octr = jnp.take_along_axis(b.ctr, pos_a, axis=-1)
    oval = jnp.take_along_axis(b.val, pos_a, axis=-1)
    oclk = jnp.take_along_axis(b.clk, pos_a[..., None], axis=-2)

    btop_at_a = jnp.take_along_axis(b.top, a.act, axis=-1)
    atop_at_a = jnp.take_along_axis(a.top, a.act, axis=-1)
    atop_at_b = jnp.take_along_axis(a.top, b.act, axis=-1)

    # Per a-lane cell: equal dots keep; else the unilateral winner (at
    # most one side's counter escapes the other's top — totally-ordered
    # actor counters, tops cover own dots).
    equal = hit_a & (octr == a.ctr)
    a_wins = a.ctr > btop_at_a
    b_wins = hit_a & (octr > atop_at_a)
    out_ctr = jnp.where(
        equal | a_wins, a.ctr, jnp.where(b_wins, octr, 0)
    )
    out_ctr = jnp.where(a.valid, out_ctr, 0)
    take_b = b_wins & ~(equal | a_wins)
    out_val = jnp.where(take_b, oval, a.val)
    out_clk = jnp.where(take_b[..., None], oclk, a.clk)

    # b's matched cells are accounted for on a's lane; keep only b's
    # unmatched winners.
    keep_b = b.valid & ~hit_b & (b.ctr > atop_at_b)

    kid = jnp.concatenate([a.kid, b.kid], axis=-1)
    act = jnp.concatenate([a.act, b.act], axis=-1)
    ctr = jnp.concatenate([out_ctr, jnp.where(keep_b, b.ctr, 0)], axis=-1)
    val = jnp.concatenate([out_val, b.val], axis=-1)
    clk = jnp.concatenate([out_clk, b.clk], axis=-2)
    valid = jnp.concatenate([out_ctr > 0, keep_b], axis=-1)
    top = jnp.maximum(a.top, b.top)

    # Parked keyset-removes: dict-union on equal clocks, replay against
    # the joined cells, drop caught-up slots, compact.
    dcl = jnp.concatenate([a.dcl, b.dcl], axis=-2)
    kidx = jnp.concatenate([a.kidx, b.kidx], axis=-2)
    dvalid = jnp.concatenate([a.dvalid, b.dvalid], axis=-1)
    dcl, kidx, dvalid = _dedupe_parked(dcl, kidx, dvalid)
    valid = _replay_parked(kid, act, ctr, valid, dcl, kidx, dvalid)
    still = ~jnp.all(dcl <= top[..., None, :], axis=-1)
    dvalid = dvalid & still
    dcl, kidx, dvalid, d_of = _compact_parked(
        dcl, kidx, dvalid, a.dcl.shape[-2]
    )

    kid, act, ctr, val, clk, valid, c_of = _canon(
        kid, act, ctr, val, clk, valid, a.kid.shape[-1]
    )
    s_of = _sibling_overflow(kid, valid, sibling_cap)
    return (
        SparseMVMapState(
            top=top, kid=kid, act=act, ctr=ctr, val=val, clk=clk,
            valid=valid, dcl=dcl, kidx=kidx, dvalid=dvalid,
        ),
        jnp.stack([jnp.any(c_of), jnp.any(d_of), jnp.any(s_of)]),
    )


@jax.jit
def apply_up(
    state: SparseMVMapState,
    wact: jax.Array,
    wctr: jax.Array,
    key: jax.Array,
    clock: jax.Array,
    val: jax.Array,
):
    """CmRDT apply of ``Op::Up { dot, key, MVReg Put }`` (reference:
    src/map.rs CmRDT::apply routing src/mvreg.rs Put; dense sibling:
    ops/map.apply_up). A seen dot is a no-op; otherwise siblings of the
    key that the Put's write clock strictly dominates are evicted
    (same-actor older writes always are — actor clocks are monotone),
    the cell lands in its existing (key, actor) lane or a free one, the
    top advances, and parked removes replay. Unbatched. Returns
    ``(state, overflow)`` — overflow = no free lane for a new cell."""
    c = state.kid.shape[-1]
    n_act = state.top.shape[-1]
    wctr = wctr.astype(state.top.dtype)
    clock = jnp.asarray(clock, state.clk.dtype)
    seen = state.top[wact] >= wctr
    same_key = state.valid & (state.kid == key)

    # A put some existing sibling's clock already dominates is a
    # CONTENT no-op — but its dot still advances the top (the mvreg
    # apply_put rule the dense path routes through).
    content_noop = jnp.any(
        same_key & jnp.all(state.clk >= clock[None, :], axis=-1)
    )
    act_on = ~seen & ~content_noop

    # Evict strictly-dominated siblings of this key.
    dominated = (
        same_key
        & jnp.all(state.clk <= clock[None, :], axis=-1)
        & jnp.any(state.clk < clock[None, :], axis=-1)
    )
    valid = state.valid & ~(dominated & act_on)

    # Upsert: the (key, wact) lane if it exists (searched on the
    # canonical PRE-eviction table — eviction holes would break the
    # ascending packed-key order searchsorted needs; a same-actor
    # evicted cell is exactly the lane being overwritten), else a free
    # lane.
    okey = jnp.where(state.valid, state.kid * n_act + state.act, _INT32_MAX)
    tkey = key * n_act + wact
    pos = jnp.clip(jnp.searchsorted(okey, tkey), 0, c - 1)
    hit = jnp.take(state.valid, pos) & (jnp.take(okey, pos) == tkey)
    free_order = jnp.argsort(valid, stable=True)
    has_free = jnp.any(~valid)
    lane = jnp.where(hit, pos, jnp.where(has_free, free_order[0], c))
    write = act_on & (hit | has_free)
    overflow = act_on & ~hit & ~has_free
    lane = jnp.where(write, lane, c)

    kid = state.kid.at[lane].set(key, mode="drop")
    act = state.act.at[lane].set(wact, mode="drop")
    ctr = state.ctr.at[lane].set(wctr, mode="drop")
    valr = state.val.at[lane].set(val, mode="drop")
    clk = state.clk.at[lane].set(clock, mode="drop")
    valid = valid.at[lane].set(True, mode="drop")

    top = jnp.where(seen, state.top, state.top.at[wact].max(wctr))
    valid = _replay_parked(
        kid, act, ctr, valid, state.dcl, state.kidx, state.dvalid
    )
    still = ~jnp.all(state.dcl <= top[None, :], axis=-1)
    kid, act, ctr, valr, clk, valid, _ = _canon(
        kid, act, ctr, valr, clk, valid, c
    )
    return (
        state._replace(
            top=top, kid=kid, act=act, ctr=ctr, val=valr, clk=clk,
            valid=valid, dvalid=state.dvalid & still,
        ),
        overflow,
    )


@jax.jit
def apply_rm(state: SparseMVMapState, rm_clock: jax.Array, kids: jax.Array):
    """CmRDT apply of ``Op::Rm { clock, keyset }`` (reference:
    src/map.rs CmRDT::apply; dense sibling: ops/map.apply_rm): kill the
    covered cells of listed keys now; park the (clock, key-list) when
    the clock runs ahead of the top. Unbatched. Returns
    ``(state, overflow)``."""
    rm_clock = jnp.asarray(rm_clock, state.top.dtype)
    listed = jnp.any(
        (state.kid[:, None] == kids[None, :]) & (kids[None, :] >= 0), axis=-1
    )
    covered = (
        state.valid & listed & (state.ctr <= jnp.take(rm_clock, state.act))
    )
    valid = state.valid & ~covered

    ahead = ~jnp.all(rm_clock <= state.top)
    dcl, kidx, dvalid, overflow = _park_list(
        state.dcl, state.kidx, state.dvalid, rm_clock, kids, ahead
    )

    kid, act, ctr, val, clk, valid, _ = _canon(
        state.kid, state.act, state.ctr, state.val, state.clk, valid,
        state.kid.shape[-1],
    )
    return (
        state._replace(
            kid=kid, act=act, ctr=ctr, val=val, clk=clk, valid=valid,
            dcl=dcl, kidx=kidx, dvalid=dvalid,
        ),
        overflow,
    )


@jax.jit
def reset_remove(state: SparseMVMapState, clock: jax.Array) -> SparseMVMapState:
    """ResetRemove — nested causal forget on the segment table
    (reference: src/map.rs ResetRemove impl; dense sibling:
    ops/map.reset_remove): cells whose witness dot the clock covers
    die, parked rm clocks zero covered lanes (slot dies when empty,
    equal survivors re-union), the top forgets covered lanes."""
    from . import vclock

    clock = jnp.asarray(clock, state.ctr.dtype)
    cl_at = jnp.take_along_axis(
        jnp.broadcast_to(clock, (*state.act.shape[:-1], clock.shape[-1])),
        state.act,
        axis=-1,
    )
    valid = state.valid & (state.ctr > cl_at)
    kid, act, ctr, val, clk, valid, _ = _canon(
        state.kid, state.act, state.ctr, state.val, state.clk, valid,
        state.kid.shape[-1],
    )
    dcl = vclock.reset_remove(state.dcl, clock[..., None, :])
    dvalid = state.dvalid & jnp.any(dcl > 0, axis=-1)
    dcl = jnp.where(dvalid[..., None], dcl, 0)
    kidx = jnp.where(dvalid[..., None], state.kidx, -1)
    dcl, kidx, dvalid = _dedupe_parked(dcl, kidx, dvalid)
    dcl, kidx, dvalid, _ = _compact_parked(
        dcl, kidx, dvalid, state.dvalid.shape[-1]
    )
    top = vclock.reset_remove(state.top, clock)
    return SparseMVMapState(
        top=top, kid=kid, act=act, ctr=ctr, val=val, clk=clk,
        valid=valid, dcl=dcl, kidx=kidx, dvalid=dvalid,
    )


def changed_cells(a: SparseMVMapState, b: SparseMVMapState) -> jax.Array:
    """Telemetry counter emitted next to the merge tables: cell lanes
    whose (kid, act, ctr, val, clk, valid) payload differs between two
    canonical states (uint32, summed over every leading batch lane) —
    the sparse map kind's ``slots_changed`` (telemetry.py)."""
    diff = (
        (a.kid != b.kid) | (a.act != b.act) | (a.ctr != b.ctr)
        | (a.val != b.val) | (a.valid != b.valid)
        | jnp.any(a.clk != b.clk, axis=-1)
    )
    return jnp.sum(diff, dtype=jnp.uint32)


def fold(states: SparseMVMapState, sibling_cap: int = 4):
    """Log-tree fold of a replica batch (leading axis)."""
    from .lattice import tree_fold

    identity = jax.tree.map(lambda x: jnp.zeros(x.shape[1:], x.dtype), states)
    identity = identity._replace(
        kid=jnp.full_like(identity.kid, -1),
        kidx=jnp.full_like(identity.kidx, -1),
    )
    return tree_fold(
        states, identity, partial(join, sibling_cap=sibling_cap)
    )


def nbytes(state: SparseMVMapState) -> int:
    return sum(x.nbytes for x in state)


# ---- the nesting protocol adapter (sparse Map<K1, Map<K2, MVReg>>) -------

class SparseMVMapLeaf:
    """Protocol adapter: the register-map cell table as the innermost
    level of the sparse nesting induction (ops/sparse_nest.py
    ``SparseNestLevel`` — the list-flavored ``NestLevel``). Its ids are
    FLAT key ids (outer key · span + inner key under the causal
    composition rule), and its own ``kidx`` buffer holds the inner map
    level's parked keyset-removes — exactly how the dense ``MAP_MVREG``
    leaf nests (ops/nest.py). ``SparseNestLevel(SparseMVMapLeaf(s), K2)``
    is therefore the sparse ``Map<K1, Map<K2, MVReg>>``; reference:
    src/map.rs nested Val composition (SURVEY §3 r11)."""

    span = 1

    def __init__(self, sibling_cap: int = 4):
        self.sibling_cap = sibling_cap

    def leaf(self, s: SparseMVMapState) -> SparseMVMapState:
        return s

    def top(self, s):
        return s.top

    def witness(self, s, actor, counter):
        return s._replace(
            top=s.top.at[..., actor].max(counter.astype(s.top.dtype))
        )

    def join(self, a, b, element_axis=None):
        return join(a, b, sibling_cap=self.sibling_cap)

    def replay_keylist(self, s, kcl, kidx, kdvalid, span: int):
        """Kill cells whose level-key (kid // span) a valid parked slot
        lists with a clock covering the cell's dot; payload dies with
        the cell (canonical zeroing)."""
        key_of = jnp.where(s.valid, s.kid // span, -2)
        listed = jnp.any(
            key_of[..., None, :, None] == kidx[..., :, None, :], axis=-1
        )  # [..., D, C]
        cl_at = jnp.take_along_axis(
            kcl, jnp.broadcast_to(s.act[..., None, :], listed.shape), axis=-1
        )
        covered = listed & (s.ctr[..., None, :] <= cl_at) & kdvalid[..., None]
        valid = s.valid & ~jnp.any(covered, axis=-2)
        kid, act, ctr, val, clk, valid, _ = _canon(
            s.kid, s.act, s.ctr, s.val, s.clk, valid, s.kid.shape[-1]
        )
        return s._replace(
            kid=kid, act=act, ctr=ctr, val=val, clk=clk, valid=valid
        )

    def scrub_enclosing(self, s, span: int, element_axis=None):
        """Drop parked inner-keyset entries whose enclosing span-key is
        dead (a bottomed child dies WITH its parked state); emptied
        slots die."""
        from .sparse_nest import _canon_rmlist, _ids_alive

        entry_key = jnp.where(s.kidx >= 0, s.kidx // span, -1)
        alive = _ids_alive(s, entry_key, span, element_axis)
        kidx = _canon_rmlist(jnp.where(alive, s.kidx, -1))
        dvalid = s.dvalid & jnp.any(kidx >= 0, axis=-1)
        return s._replace(
            kidx=jnp.where(dvalid[..., None], kidx, -1),
            dcl=jnp.where(dvalid[..., None], s.dcl, 0),
            dvalid=dvalid,
        )

    def scrub_self(self, s, element_axis=None):
        return s  # a register cell holds nothing inside it

    def settle_self(self, s, element_axis=None):
        """Replay the table's own parked keyset-removes under the (maybe
        advanced) top, drop caught-up slots."""
        valid = _replay_parked(
            s.kid, s.act, s.ctr, s.valid, s.dcl, s.kidx, s.dvalid
        )
        still = ~jnp.all(s.dcl <= s.top[..., None, :], axis=-1)
        kid, act, ctr, val, clk, valid, _ = _canon(
            s.kid, s.act, s.ctr, s.val, s.clk, valid, s.kid.shape[-1]
        )
        return s._replace(
            kid=kid, act=act, ctr=ctr, val=val, clk=clk, valid=valid,
            dvalid=s.dvalid & still,
        )

    def rm_route(self, s, levels_down: int, rm_clock, ids):
        assert levels_down == 0, "leaf cannot route deeper"
        return apply_rm(s, rm_clock, ids)


def level_map_mvreg(span: int, sibling_cap: int = 4):
    """The sparse ``Map<K1, Map<K2, MVReg>>`` level: one nesting step
    around the register-map cell table. ``span`` = the inner key
    universe width K2 (flat kid = k1·span + k2)."""
    from .sparse_nest import SparseNestLevel

    return SparseNestLevel(SparseMVMapLeaf(sibling_cap), span)


def empty_map_mvreg(
    span: int,
    cell_cap: int,
    n_actors: int,
    deferred_cap: int = 4,
    rm_width: int = 8,
    key_deferred_cap: int = 4,
    key_rm_width: int = 8,
    sibling_cap: int = 4,
    batch: tuple = (),
):
    """(level, state) for an empty sparse ``Map<K1, Map<K2, MVReg>>``."""
    level = level_map_mvreg(span, sibling_cap)
    state = level.empty(
        empty(cell_cap, n_actors, deferred_cap, rm_width, batch=batch),
        n_actors, key_deferred_cap, key_rm_width, batch=batch,
    )
    return level, state


def nest_apply_up_put(level, s, wact, wctr, kid_flat, clock, val):
    """``Op::Up { dot, k1, Up { k2, Put } }`` for the nested flavor —
    the put lands in the leaf cell table at the FLAT key id (the leaf
    applier witnesses the shared top and replays its own buffer), then
    every outer level settles. Seen dots are full no-ops."""
    from .sparse_nest import _graft_leaf

    wctr = jnp.asarray(wctr).astype(level.top(s).dtype)
    seen = level.top(s)[..., wact] >= wctr
    new_leaf, overflow = apply_up(level.leaf(s), wact, wctr, kid_flat, clock, val)
    out = level.settle_self(_graft_leaf(level, s, new_leaf))
    keep = lambda old, new: jnp.where(
        seen.reshape(seen.shape + (1,) * (new.ndim - seen.ndim)), old, new
    )
    out = jax.tree.map(keep, s, out)
    return out, overflow & ~seen


# ---- static-analysis registration (crdt_tpu.analysis) --------------------

def _law_states():
    """Cell puts (concurrent / dominating / duplicate) and covered/ahead
    key-removes over 3 keys × 2 actors with cell headroom."""
    cl = lambda x, y: jnp.array([x, y], DTYPE)
    ids = lambda *xs: jnp.array(list(xs) + [-1] * (4 - len(xs)), jnp.int32)
    e = empty(8, 2, deferred_cap=3, rm_width=4)
    u1, _ = apply_up(e, 0, jnp.uint32(1), 0, cl(1, 0), 5)
    u2, _ = apply_up(u1, 0, jnp.uint32(2), 1, cl(2, 0), 6)
    v1, _ = apply_up(e, 1, jnp.uint32(1), 0, cl(0, 1), 7)
    # Actor 1's second write after observing both branches: its clock
    # dominates u1's and v1's key-0 siblings (a FRESH dot — reusing a
    # witness dot for different content is a non-causal history and no
    # CRDT's laws survive that).
    uv, _ = join(u2, v1)
    dom, _ = apply_up(uv, 1, jnp.uint32(2), 0, cl(2, 2), 8)
    r1, _ = apply_rm(dom, cl(2, 1), ids(0))   # covered key rm
    r2, _ = apply_rm(u1, cl(0, 2), ids(1))    # ahead: parks
    r3, _ = apply_rm(e, cl(1, 1), ids(0, 2))  # ahead on empty
    return [e, u1, u2, v1, dom, r1, r2, r3]


def _law_canon(s: SparseMVMapState) -> SparseMVMapState:
    from ..analysis.canon import canon_epochs

    dcl, kidx, dvalid = canon_epochs(s.dcl, s.kidx, s.dvalid, payload_fill=-1)
    return s._replace(dcl=dcl, kidx=kidx, dvalid=dvalid)


@jax.jit
def compact(state: SparseMVMapState, frontier: jax.Array):
    """Causal-stability compaction (reclaim/): replay parked
    keyset-removes against the cell table (kills cells their caught-up
    clocks still cover), retire slots the stable frontier dominates,
    scrub stale parked payload, and re-canonicalize so freed lanes pack
    to the tail — the headroom ``elastic.shrink`` turns into bytes.
    Observable reads (live values per key) untouched. Returns
    ``(state, freed_slots, freed_bytes)``."""
    from ..reclaim.compaction import retire_epochs

    valid = _replay_parked(
        state.kid, state.act, state.ctr, state.valid,
        state.dcl, state.kidx, state.dvalid,
    )
    kid, act, ctr, val, clk, valid, _ = _canon(
        state.kid, state.act, state.ctr, state.val, state.clk, valid,
        state.kid.shape[-1],
    )
    dcl, kidx, dvalid, freed, freed_b = retire_epochs(
        state.dcl, state.kidx, state.dvalid, state.top, frontier,
        payload_fill=-1,
    )
    return (
        SparseMVMapState(
            top=state.top, kid=kid, act=act, ctr=ctr, val=val, clk=clk,
            valid=valid, dcl=dcl, kidx=kidx, dvalid=dvalid,
        ),
        freed,
        freed_b,
    )


def _observe(s: SparseMVMapState):
    """The observable read: the live ``(key, value)`` cell set in
    canonical (kid, act) order — the register map's sibling-set read."""
    return (
        jnp.where(s.valid, s.kid, -1),
        jnp.where(s.valid, s.val, 0),
        s.valid,
    )


def _decomp_split(s: SparseMVMapState):
    """Decomposition granularity (delta_opt/): one δ lane per cell-table
    lane (positional, like sparse_orswot); top + parked buffer residual."""
    return (
        (s.kid, s.act, s.ctr, s.val, s.clk, s.valid),
        (s.top, s.dcl, s.kidx, s.dvalid),
    )


def _decomp_unsplit(rows, res) -> SparseMVMapState:
    kid, act, ctr, val, clk, valid = rows
    top, dcl, kidx, dvalid = res
    return SparseMVMapState(
        top=top, kid=kid, act=act, ctr=ctr, val=val, clk=clk, valid=valid,
        dcl=dcl, kidx=kidx, dvalid=dvalid,
    )


from ..analysis.registry import (  # noqa: E402
    register_compactor,
    register_decomposition,
    register_merge,
)

register_merge(
    "sparse_mvmap", module=__name__, join=join, states=_law_states,
    canon=_law_canon,
)
register_compactor(
    "sparse_mvmap", module=__name__, compact=compact, observe=_observe,
    top_of=lambda s: s.top,
)
register_decomposition(
    "sparse_mvmap", module=__name__, split=_decomp_split,
    unsplit=_decomp_unsplit,
)
