"""Compressed (segment-encoded) ORSWOT — the sparse mode for element
universes where the dense ``ctr[R, E, A]`` cube stops scaling.

SURVEY.md §7.3 names the tension: 10k replicas × 1M elements × A actors
cannot be a dense u32 cube (4·E·A bytes per replica regardless of how
many dots are LIVE). This module is the compressed dot representation
the survey prescribes — exactly why ORSWOT is tombstone-free: the top
clock subsumes removal history, so a replica's whole state is

    top[A]  +  the set of live dots {(element, actor, counter)}.

TPU form (static shapes, no ragged data): a bounded dot-segment table
sorted by (element, actor) —

- ``eid [..., C] int32``  — element id per live dot (-pad = invalid),
- ``act [..., C] int32``  — actor lane,
- ``ctr [..., C] u32``    — the dot counter (> 0 where valid),
- ``valid [..., C] bool``,

plus the same masked-epoch deferred-removal buffer as the dense form,
with element LISTS instead of E-wide masks (``dcl [D, A]``,
``didx [D, Q]`` element ids, ``dvalid [D]``).

``join`` is the reference merge rule (src/orswot.rs ``CvRDT::merge``)
on segments: concatenate both tables, keep a dot iff the other side
also holds it (same triple) or its counter exceeds the other top's
actor lane, dedupe identical triples, sort-compact to capacity. The
sort is the price of sparsity (XLA lowers it to a bitonic network —
O(C log² C) VPU work vs the dense join's O(E·A) HBM traffic), which is
the crossover the bench measures: sparse wins when live dots ≪ E·A.

Capacity discipline matches the deferred buffers: ``C`` bounds live
dots per replica; a join whose survivor set exceeds C reports overflow
(callers size C for their workload — the A/B suite pins behavior below
capacity bit-identically to the dense form via ``to_dense``).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from .orswot import OrswotState, _pad_tail, empty as dense_empty

DTYPE = jnp.uint32


class SparseOrswotState(NamedTuple):
    """A (possibly batched) segment-encoded ORSWOT replica (pytree)."""

    top: jax.Array    # [..., A]
    eid: jax.Array    # [..., C] int32
    act: jax.Array    # [..., C] int32
    ctr: jax.Array    # [..., C] u32
    valid: jax.Array  # [..., C]
    dcl: jax.Array    # [..., D, A]
    didx: jax.Array   # [..., D, Q] int32 element ids (-1 = empty lane)
    dvalid: jax.Array # [..., D]


def empty(
    dot_cap: int,
    n_actors: int,
    deferred_cap: int = 4,
    rm_width: int = 8,
    batch: tuple = (),
) -> SparseOrswotState:
    """The join identity: no dots, no parked removes."""
    return SparseOrswotState(
        top=jnp.zeros((*batch, n_actors), DTYPE),
        eid=jnp.full((*batch, dot_cap), -1, jnp.int32),
        act=jnp.zeros((*batch, dot_cap), jnp.int32),
        ctr=jnp.zeros((*batch, dot_cap), DTYPE),
        valid=jnp.zeros((*batch, dot_cap), bool),
        dcl=jnp.zeros((*batch, deferred_cap, n_actors), DTYPE),
        didx=jnp.full((*batch, deferred_cap, rm_width), -1, jnp.int32),
        dvalid=jnp.zeros((*batch, deferred_cap), bool),
    )


def widen(
    state: SparseOrswotState,
    dot_cap: int = 0,
    n_actors: int = 0,
    deferred_cap: int = 0,
    rm_width: int = 0,
) -> SparseOrswotState:
    """Segment-table repack into a wider layout — the elastic capacity
    migration (elastic.py). Canonical order puts dead lanes last, so
    growing any axis is tail padding with the axis's dead sentinel
    (-1 eids / -1 parked ids / zero lanes / False masks): the valid
    prefix is untouched and the result is bit-identical to a
    from-scratch wider table holding the same dots. 0 keeps a width;
    shrinking is refused (lanes may be live)."""
    c, a = state.eid.shape[-1], state.top.shape[-1]
    d, q = state.didx.shape[-2:]
    nc, na = dot_cap or c, n_actors or a
    nd, nq = deferred_cap or d, rm_width or q
    if nc < c or na < a or nd < d or nq < q:
        raise ValueError(
            f"widen cannot shrink: ({c}, {a}, {d}, {q}) -> "
            f"({nc}, {na}, {nd}, {nq})"
        )
    lead = state.top.ndim - 1
    pad = partial(_pad_tail, lead=lead)
    return SparseOrswotState(
        top=pad(state.top, (0, na - a)),
        eid=pad(state.eid, (0, nc - c), fill=-1),
        act=pad(state.act, (0, nc - c)),
        ctr=pad(state.ctr, (0, nc - c)),
        valid=pad(state.valid, (0, nc - c), fill=False),
        dcl=pad(state.dcl, (0, nd - d), (0, na - a)),
        didx=pad(state.didx, (0, nd - d), (0, nq - q), fill=-1),
        dvalid=pad(state.dvalid, (0, nd - d), fill=False),
    )


def narrow(
    state: SparseOrswotState,
    dot_cap: int = 0,
    n_actors: int = 0,
    deferred_cap: int = 0,
    rm_width: int = 0,
) -> SparseOrswotState:
    """The inverse of :func:`widen` — slice tail lanes off the segment
    table (elastic.shrink drives this). Canonical order keeps dead
    lanes last, so narrowing an axis is pure tail slicing once the
    occupancy check passes; any live data in a dropped lane REFUSES
    with ValueError. Run ``compact`` first so retired parked slots do
    not pin lanes. 0 keeps a width."""
    c, a = state.eid.shape[-1], state.top.shape[-1]
    d, q = state.didx.shape[-2:]
    nc, na = dot_cap or c, n_actors or a
    nd, nq = deferred_cap or d, rm_width or q
    if nc > c or na > a or nd > d or nq > q:
        raise ValueError(
            f"narrow cannot grow: ({c}, {a}, {d}, {q}) -> "
            f"({nc}, {na}, {nd}, {nq})"
        )
    live = []
    if nc < c and bool(jnp.any(state.valid[..., nc:])):
        live.append(f"dot_cap {c}->{nc}")
    if na < a and bool(
        jnp.any(state.top[..., na:]) | jnp.any(state.dcl[..., :, na:])
        | jnp.any(state.valid & (state.act >= na))
    ):
        live.append(f"n_actors {a}->{na}")
    if nd < d and bool(jnp.any(state.dvalid[..., nd:])):
        live.append(f"deferred_cap {d}->{nd}")
    if nq < q and bool(jnp.any(state.didx[..., nq:] >= 0)):
        live.append(f"rm_width {q}->{nq}")
    if live:
        raise ValueError(
            f"narrow refused — dropped lanes hold live state: {live} "
            f"(compact first, or shrink less)"
        )
    return SparseOrswotState(
        top=state.top[..., :na],
        eid=state.eid[..., :nc],
        act=state.act[..., :nc],
        ctr=state.ctr[..., :nc],
        valid=state.valid[..., :nc],
        dcl=state.dcl[..., :nd, :na],
        didx=state.didx[..., :nd, :nq],
        dvalid=state.dvalid[..., :nd],
    )


def _canon(eid, act, ctr, valid, cap: int):
    """Sort live dots by (eid, act), dead lanes last with zeroed
    payload; truncate to ``cap``. Returns the table + overflow flag.

    Two sort keys, not four: every key is a full stable-sort pass on
    TPU. The masked eid (MAX sentinel) already sends dead lanes last
    (a separate ~valid key is redundant — live eids are bounded by
    E·A < 2^31, strictly below the sentinel), and (eid, act) is unique
    among live lanes (one counter per cell), so a ctr tiebreak can
    never fire. Order is bit-identical to the old 4-key sort."""
    order = jnp.lexsort(
        (act, jnp.where(valid, eid, jnp.iinfo(jnp.int32).max)), axis=-1
    )
    take = lambda x: jnp.take_along_axis(x, order, axis=-1)
    eid, act, ctr, valid = take(eid), take(act), take(ctr), take(valid)
    overflow = jnp.sum(valid, axis=-1) > cap
    eid, act, ctr, valid = (
        eid[..., :cap], act[..., :cap], ctr[..., :cap], valid[..., :cap]
    )
    return (
        jnp.where(valid, eid, -1),
        jnp.where(valid, act, 0),
        jnp.where(valid, ctr, 0),
        valid,
        overflow,
    )


def _replay_parked(eid, act, ctr, valid, dcl, didx, dvalid):
    """Kill dots of listed elements that the parked rm clocks cover
    (the oracle's deferred-remove replay): dot (e, a, c) dies iff some
    valid slot lists e and has clock[a] >= c."""
    listed = jnp.any(
        eid[..., None, :, None] == didx[..., :, None, :], axis=-1
    )  # [..., D, C]
    cl_at = jnp.take_along_axis(
        dcl, jnp.broadcast_to(act[..., None, :], listed.shape), axis=-1
    )  # [..., D, C] clock value at each dot's actor lane
    covered = listed & (ctr[..., None, :] <= cl_at) & dvalid[..., None]
    return valid & ~jnp.any(covered, axis=-2)


def _match_other(eid, act, valid, oeid, oact, octr, ovalid, n_act: int):
    """For each segment lane: the OTHER side's counter at the same
    (element, actor) cell (0 = absent), plus the match mask.

    Both tables are in canonical segment order (valid-first, sorted by
    (eid, act); (eid, act) is unique per replica — the dense form keeps
    one counter per cell), so the packed key ``eid·A + act`` is strictly
    ascending over the valid prefix and a binary search replaces the
    all-pairs matrix: O(C log C), which is what keeps the documented
    O(C log² C) join cost honest. The int32 key bounds the universe at
    ``E·A < 2^31`` (E ≤ 268M at A=8 — far past any dense-comparable
    scale)."""
    if eid.ndim > 1:
        inner = partial(_match_other, n_act=n_act)
        return jax.vmap(inner)(eid, act, valid, oeid, oact, octr, ovalid)
    big = jnp.iinfo(jnp.int32).max
    key = jnp.where(valid, eid * n_act + act, big)
    okey = jnp.where(ovalid, oeid * n_act + oact, big)
    pos = jnp.clip(jnp.searchsorted(okey, key), 0, okey.shape[-1] - 1)
    hit = valid & jnp.take(ovalid, pos) & (jnp.take(okey, pos) == key)
    return jnp.where(hit, jnp.take(octr, pos), 0), hit


@jax.jit
def join(a: SparseOrswotState, b: SparseOrswotState):
    """Pairwise lattice join on dot segments — the reference merge rule
    with top-clock subsumption. A cell counter is a PREFIX clock (the
    per-element VClock lane: counter c attests dots 1..c by that actor
    — exactly the dense ``ctr[e, a]`` semantics), so the per-cell rule
    mirrors ops.orswot.join's: common part ``min(ca, cb)`` ∪ each
    side's unseen tail (``c > other.top[actor]``); a cell held by one
    side only keeps its unseen tail. Inputs must be in canonical
    segment order (every constructor and ``join`` itself produce it).
    Returns ``(state, overflow)``; overflow's two lanes are
    [dot-capacity, deferred-capacity]."""
    n_act = a.top.shape[-1]
    cb_at_a, a_matched = _match_other(
        a.eid, a.act, a.valid, b.eid, b.act, b.ctr, b.valid, n_act
    )
    _, b_matched = _match_other(
        b.eid, b.act, b.valid, a.eid, a.act, a.ctr, a.valid, n_act
    )
    btop_at_a = jnp.take_along_axis(b.top, a.act, axis=-1)
    atop_at_b = jnp.take_along_axis(a.top, b.act, axis=-1)
    wa = jnp.where(a.ctr > btop_at_a, a.ctr, 0)
    wb_at_a = jnp.where(cb_at_a > jnp.take_along_axis(a.top, a.act, axis=-1), cb_at_a, 0)
    out_a = jnp.maximum(jnp.minimum(a.ctr, cb_at_a), jnp.maximum(wa, wb_at_a))
    out_a = jnp.where(a.valid, out_a, 0)
    # b's matched cells are fully accounted for by a's lane; keep only
    # b's unmatched unseen tails.
    wb = jnp.where(b.ctr > atop_at_b, b.ctr, 0)
    out_b = jnp.where(b.valid & ~b_matched, wb, 0)

    eid = jnp.concatenate([a.eid, b.eid], axis=-1)
    act = jnp.concatenate([a.act, b.act], axis=-1)
    ctr = jnp.concatenate([out_a, out_b], axis=-1)
    valid = jnp.concatenate([out_a > 0, out_b > 0], axis=-1)
    top = jnp.maximum(a.top, b.top)

    # Deferred union (dict-union on equal clocks as element-list union),
    # replay against the joined dots, drop caught-up slots, compact.
    dcl = jnp.concatenate([a.dcl, b.dcl], axis=-2)
    didx = jnp.concatenate([a.didx, b.didx], axis=-2)
    dvalid = jnp.concatenate([a.dvalid, b.dvalid], axis=-1)
    dcl, didx, dvalid = _dedupe_parked(dcl, didx, dvalid)
    valid = _replay_parked(eid, act, ctr, valid, dcl, didx, dvalid)
    still = ~jnp.all(dcl <= top[..., None, :], axis=-1)
    dvalid = dvalid & still
    dcl, didx, dvalid, d_of = _compact_parked(
        dcl, didx, dvalid, a.dcl.shape[-2]
    )

    eid, act, ctr, valid, overflow = _canon(
        eid, act, ctr, valid, a.eid.shape[-1]
    )
    return (
        SparseOrswotState(
            top=top, eid=eid, act=act, ctr=ctr, valid=valid,
            dcl=dcl, didx=didx, dvalid=dvalid,
        ),
        jnp.stack([jnp.any(overflow), jnp.any(d_of)]),
    )


_INT32_MAX = jnp.iinfo(jnp.int32).max


def _canon_rmlist(didx):
    """Canonical parked-element list: ids sorted ascending, duplicates
    removed, -1 padding last — equal sets compare equal as raw lanes
    (join commutativity holds bitwise)."""
    big = jnp.where(didx < 0, _INT32_MAX, didx)
    s = jnp.sort(big, axis=-1)
    dup = jnp.concatenate(
        [jnp.zeros_like(s[..., :1], bool), s[..., 1:] == s[..., :-1]], axis=-1
    )
    s = jnp.sort(jnp.where(dup, _INT32_MAX, s), axis=-1)
    return jnp.where(s == _INT32_MAX, -1, s)


def _dedupe_parked(dcl, didx, dvalid):
    """Union element lists of slots holding equal rm clocks (the
    oracle's ``defer_remove`` dict-union), keeping the first slot of
    each group — when the UNIQUE union fits the fixed Q lanes (identical
    slots therefore always merge, keeping the join idempotent). A group
    whose deduplicated union exceeds Q keeps its member slots separate
    instead (replay is per-slot, so correctness is unaffected; only the
    capacity accounting is conservative — the buffer may flag overflow
    where the oracle's dict would not)."""
    d = dcl.shape[-2]
    q = didx.shape[-1]
    idx = jnp.arange(d)
    eq = (
        dvalid[..., :, None]
        & dvalid[..., None, :]
        & jnp.all(dcl[..., :, None, :] == dcl[..., None, :, :], axis=-1)
    )  # [..., D, D]
    rep = jnp.argmax(eq, axis=-2)          # first valid slot w/ equal clock
    is_rep = dvalid & (rep == idx)
    # group[i, j]: slot j belongs to representative i
    group = eq & (rep[..., None, :] == idx[..., :, None])
    gathered = jnp.where(
        group[..., None], didx[..., None, :, :], -1
    ).reshape(*didx.shape[:-2], d, d * q)
    union = _canon_rmlist(gathered)        # sorted unique, -1 last
    need = jnp.sum(union >= 0, axis=-1)
    fits = need <= q                       # [..., D] per representative
    didx = jnp.where((is_rep & fits)[..., None], union[..., :q], didx)
    absorbed = jnp.any(
        group & fits[..., :, None] & ~jnp.eye(d, dtype=bool), axis=-2
    )  # member slots folded into a fitting representative
    return dcl, didx, dvalid & ~absorbed


def _compact_parked(dcl, didx, dvalid, cap: int):
    order = jnp.argsort(~dvalid, axis=-1, stable=True)
    dcl = jnp.take_along_axis(dcl, order[..., None], axis=-2)
    didx = jnp.take_along_axis(didx, order[..., None], axis=-2)
    dvalid = jnp.take_along_axis(dvalid, order, axis=-1)
    overflow = jnp.sum(dvalid, axis=-1) > cap
    dcl, didx, dvalid = dcl[..., :cap, :], didx[..., :cap, :], dvalid[..., :cap]
    dcl = jnp.where(dvalid[..., None], dcl, 0)
    didx = _canon_rmlist(jnp.where(dvalid[..., None], didx, -1))
    return dcl, didx, dvalid, overflow


@jax.jit
def reset_remove(state: SparseOrswotState, clock: jax.Array) -> SparseOrswotState:
    """ResetRemove — the ``Causal`` trait's ``forget`` on the
    segment-encoded backend (reference: src/orswot.rs ResetRemove impl;
    oracle: pure/orswot.py ``reset_remove``; dense sibling:
    ops/orswot.reset_remove). A dot (e, a, c) dies iff ``c <=
    clock[a]``; parked rm clocks zero covered lanes, a slot dies when
    its clock empties and surviving equal clocks re-union; the top
    clock forgets covered lanes. Nothing grows, so no overflow."""
    from . import vclock

    clock = jnp.asarray(clock, state.ctr.dtype)
    cl_at = jnp.take_along_axis(
        jnp.broadcast_to(clock, (*state.act.shape[:-1], clock.shape[-1])),
        state.act,
        axis=-1,
    )
    valid = state.valid & (state.ctr > cl_at)
    eid, act, ctr, valid, _ = _canon(
        state.eid, state.act, state.ctr, valid, state.eid.shape[-1]
    )
    dcl = vclock.reset_remove(state.dcl, clock[..., None, :])
    dvalid = state.dvalid & jnp.any(dcl > 0, axis=-1)
    dcl = jnp.where(dvalid[..., None], dcl, 0)
    didx = jnp.where(dvalid[..., None], state.didx, -1)
    dcl, didx, dvalid = _dedupe_parked(dcl, didx, dvalid)
    dcl, didx, dvalid, _ = _compact_parked(
        dcl, didx, dvalid, state.dvalid.shape[-1]
    )
    top = vclock.reset_remove(state.top, clock)
    return SparseOrswotState(
        top=top, eid=eid, act=act, ctr=ctr, valid=valid,
        dcl=dcl, didx=didx, dvalid=dvalid,
    )


# ---- op application (CmRDT) ----------------------------------------------

@jax.jit
def apply_add(
    state: SparseOrswotState,
    actor: jax.Array,
    counter: jax.Array,
    eids: jax.Array,
):
    """CmRDT add-op application on segments (reference: src/orswot.rs
    apply, Op::Add): drop already-seen dots, else stamp the birth dot on
    every listed element — updating existing (element, actor) cells in
    place and inserting new cells into free lanes — advance the top,
    and replay parked removes. ``eids [W] int32`` lists the op's member
    ids (-1 = pad). Unbatched state. Returns ``(state, overflow)``;
    overflow = not enough free lanes for the new cells."""
    c = state.eid.shape[-1]
    n_act = state.top.shape[-1]
    counter = counter.astype(state.top.dtype)
    seen = state.top[actor] >= counter
    want = eids >= 0

    # Existing (eid, actor) cells among the targets.
    big = jnp.iinfo(jnp.int32).max
    okey = jnp.where(state.valid, state.eid * n_act + state.act, big)
    tkey = jnp.where(want, eids * n_act + actor, big)
    pos = jnp.clip(jnp.searchsorted(okey, tkey), 0, c - 1)
    hit = want & (jnp.take(okey, pos) == tkey)
    ctr = state.ctr.at[jnp.where(hit & ~seen, pos, c)].max(
        counter, mode="drop"
    )

    # New cells into free lanes, one per missing target, scattered via
    # out-of-range drop for every non-inserting position (no lane
    # collisions: put ranks are unique, everything else targets lane C).
    miss = want & ~hit & ~seen
    free_order = jnp.argsort(state.valid, stable=True)  # invalid lanes first
    n_free = jnp.sum(~state.valid)
    slot_rank = jnp.cumsum(miss) - 1
    put = miss & (slot_rank < n_free)
    overflow = jnp.any(miss & (slot_rank >= n_free))
    lane = jnp.where(
        put, jnp.take(free_order, jnp.clip(slot_rank, 0, c - 1)), c
    )
    eid = state.eid.at[lane].set(eids, mode="drop")
    act = state.act.at[lane].set(
        jnp.broadcast_to(actor, eids.shape), mode="drop"
    )
    ctr = ctr.at[lane].set(counter, mode="drop")
    valid = state.valid.at[lane].set(True, mode="drop")

    top = jnp.where(seen, state.top, state.top.at[actor].max(counter))
    valid = _replay_parked(eid, act, ctr, valid, state.dcl, state.didx, state.dvalid)
    still = ~jnp.all(state.dcl <= top[None, :], axis=-1)
    eid, act, ctr, valid, _ = _canon(eid, act, ctr, valid, c)
    return (
        state._replace(
            top=top, eid=eid, act=act, ctr=ctr, valid=valid,
            dvalid=state.dvalid & still,
        ),
        overflow & ~seen,
    )


@jax.jit
def apply_rm(state: SparseOrswotState, rm_clock: jax.Array, eids: jax.Array):
    """CmRDT rm-op application on segments (reference: src/orswot.rs
    apply_rm): kill the covered part now (cells of listed elements whose
    counter the rm clock covers); park the (clock, element-list) if the
    clock runs ahead of the top — union onto an equal-clock slot when
    the combined list fits, else claim a free slot. Unbatched state.
    Returns ``(state, overflow)``."""
    q = state.didx.shape[-1]
    w = eids.shape[-1]
    assert w <= q, "rm op element-list width must fit rm_width"
    rm_clock = jnp.asarray(rm_clock, state.top.dtype)
    listed = jnp.any(
        (state.eid[:, None] == eids[None, :]) & (eids[None, :] >= 0), axis=-1
    )
    covered = (
        state.valid & listed & (state.ctr <= jnp.take(rm_clock, state.act))
    )
    valid = state.valid & ~covered

    ahead = ~jnp.all(rm_clock <= state.top)
    # Park: union onto an equal-clock slot if the canonical union fits,
    # else claim a free slot.
    same = state.dvalid & jnp.all(state.dcl == rm_clock[None, :], axis=-1)
    merged = _canon_rmlist(
        jnp.concatenate(
            [state.didx, jnp.broadcast_to(eids, (state.didx.shape[0], w))],
            axis=-1,
        )
    )
    fits = jnp.sum(merged >= 0, axis=-1) <= q
    use_same = same & fits
    has_same = jnp.any(use_same)
    free = ~state.dvalid
    has_free = jnp.any(free)
    slot = jnp.where(has_same, jnp.argmax(use_same), jnp.argmax(free))
    park = ahead & (has_same | has_free)
    overflow = ahead & ~has_same & ~has_free
    onehot = jax.nn.one_hot(slot, state.dvalid.shape[-1], dtype=bool) & park
    fresh = _canon_rmlist(
        jnp.pad(eids, (0, q - w), constant_values=-1)
    )
    new_list = jnp.where(has_same, merged[slot][:q], fresh)
    dcl = jnp.where(onehot[:, None], rm_clock[None, :], state.dcl)
    didx = jnp.where(onehot[:, None], new_list[None, :], state.didx)
    dvalid = state.dvalid | onehot

    eid, act, ctr, valid, _ = _canon(
        state.eid, state.act, state.ctr, valid, state.eid.shape[-1]
    )
    return (
        state._replace(
            eid=eid, act=act, ctr=ctr, valid=valid,
            dcl=dcl, didx=didx, dvalid=dvalid,
        ),
        overflow,
    )


def changed_dots(a: SparseOrswotState, b: SparseOrswotState) -> jax.Array:
    """Telemetry counter emitted next to the merge tables: dot-segment
    lanes whose (eid, act, ctr, valid) payload differs between two
    canonical states (uint32, summed over every leading batch lane) —
    the sparse kind's ``slots_changed`` (telemetry.py)."""
    diff = (
        (a.eid != b.eid) | (a.act != b.act)
        | (a.ctr != b.ctr) | (a.valid != b.valid)
    )
    return jnp.sum(diff, dtype=jnp.uint32)


def fold(states: SparseOrswotState):
    """Log-tree fold of a replica batch (leading axis)."""
    from .lattice import tree_fold

    identity = jax.tree.map(lambda x: jnp.zeros(x.shape[1:], x.dtype), states)
    identity = identity._replace(
        eid=jnp.full_like(identity.eid, -1),
        didx=jnp.full_like(identity.didx, -1),
    )
    return tree_fold(states, identity, join)


# ---- dense interop (the A/B boundary) ------------------------------------

def from_dense(state: OrswotState, dot_cap: int, rm_width: int = 8):
    """Dense → sparse. Raises if live dots exceed ``dot_cap`` or any
    parked mask lists more than ``rm_width`` elements (host-side check;
    conversion is a tooling/test path, not a hot loop)."""
    import numpy as np

    top = np.asarray(state.top)
    ctr = np.asarray(state.ctr)
    dmask = np.asarray(state.dmask)
    batch = ctr.shape[:-2]
    flat = int(np.prod(batch)) if batch else 1
    e, a = ctr.shape[-2:]
    d = state.dcl.shape[-2]
    out = empty(
        dot_cap, a, deferred_cap=d, rm_width=rm_width, batch=batch
    )
    eid = np.full((flat, dot_cap), -1, np.int32)
    act = np.zeros((flat, dot_cap), np.int32)
    cv = np.zeros((flat, dot_cap), np.uint32)
    valid = np.zeros((flat, dot_cap), bool)
    didx = np.full((flat, d, rm_width), -1, np.int32)
    for i in range(flat):
        es, as_ = np.nonzero(ctr.reshape(flat, e, a)[i])
        if len(es) > dot_cap:
            raise ValueError(f"replica {i}: {len(es)} live dots > cap {dot_cap}")
        eid[i, : len(es)] = es
        act[i, : len(es)] = as_
        cv[i, : len(es)] = ctr.reshape(flat, e, a)[i, es, as_]
        valid[i, : len(es)] = True
        for s in range(d):
            els = np.nonzero(dmask.reshape(flat, d, e)[i, s])[0]
            if len(els) > rm_width:
                raise ValueError(
                    f"replica {i} slot {s}: {len(els)} parked elements > "
                    f"rm_width {rm_width}"
                )
            didx[i, s, : len(els)] = els
    rs = lambda x: jnp.asarray(x.reshape(*batch, *x.shape[1:]) if batch else x[0])
    out = out._replace(
        top=jnp.asarray(top),
        eid=rs(eid), act=rs(act), ctr=rs(cv), valid=rs(valid),
        dcl=state.dcl, didx=rs(didx), dvalid=state.dvalid,
    )
    # Canonical order so sparse states are comparable as raw arrays.
    ceid, cact, cctr, cvalid, _ = _canon(
        out.eid, out.act, out.ctr, out.valid, dot_cap
    )
    return out._replace(eid=ceid, act=cact, ctr=cctr, valid=cvalid)


def to_dense(state: SparseOrswotState, n_elems: int) -> OrswotState:
    """Sparse → dense (the bit-identity bridge to ops.orswot)."""
    lead = state.eid.shape[:-1]
    a = state.top.shape[-1]
    d = state.dcl.shape[-2]

    def one(s: SparseOrswotState) -> OrswotState:
        out = dense_empty(n_elems, a, deferred_cap=d)
        safe_e = jnp.where(s.valid, s.eid, n_elems)  # OOB lanes drop
        ctr = out.ctr.at[safe_e, s.act].max(
            jnp.where(s.valid, s.ctr, 0), mode="drop"
        )
        safe_q = jnp.where(s.didx >= 0, s.didx, n_elems)
        dmask = out.dmask.at[jnp.arange(d)[:, None], safe_q].set(
            True, mode="drop"
        )
        dmask = dmask & s.dvalid[..., None]
        return out._replace(
            top=s.top, ctr=ctr, dcl=s.dcl, dmask=dmask, dvalid=s.dvalid
        )

    if not lead:
        return one(state)
    import numpy as np

    n = int(np.prod(lead))
    flat = jax.tree.map(lambda x: x.reshape(n, *x.shape[len(lead):]), state)
    out = jax.vmap(one)(flat)
    return jax.tree.map(lambda x: x.reshape(*lead, *x.shape[1:]), out)


def nbytes(state: SparseOrswotState) -> int:
    """Device bytes of one replica's sparse state (the crossover
    metric vs the dense 4·E·A + masks)."""
    import numpy as np

    total = sum(x.nbytes for x in jax.tree_util.tree_leaves(state))
    lead = state.eid.shape[:-1]
    return total // (int(np.prod(lead)) if lead else 1)


# ---- static-analysis registration (crdt_tpu.analysis) --------------------

def _law_ids(*xs, w: int = 4):
    return jnp.array(list(xs) + [-1] * (w - len(xs)), jnp.int32)


def _law_states():
    """Segment adds, covered removes, and parked (ahead) removes over a
    small element universe with dot/deferred headroom."""
    cl = lambda x, y: jnp.array([x, y], DTYPE)
    e = empty(8, 2, deferred_cap=3, rm_width=4)
    a1, _ = apply_add(e, 0, jnp.uint32(1), _law_ids(0))
    a2, _ = apply_add(a1, 0, jnp.uint32(2), _law_ids(1, 2))
    b1, _ = apply_add(e, 1, jnp.uint32(1), _law_ids(0, 3))
    ab, _ = join(a2, b1)
    r1, _ = apply_rm(ab, cl(2, 1), _law_ids(0))     # covered
    r2, _ = apply_rm(a1, cl(0, 2), _law_ids(1))     # ahead: parks
    r3, _ = apply_rm(e, cl(1, 1), _law_ids(0, 2))   # ahead on empty
    return [e, a1, a2, b1, r1, r2, r3]


def _law_states_big():
    """Property-sampled: replicas applying ordered subsets of one shared
    op history over a 6-element universe, 3 actors."""
    import numpy as np

    rng = np.random.default_rng(20260803)
    e_n, a_n = 6, 3
    mk = lambda: empty(16, a_n, deferred_cap=4, rm_width=6)
    site = mk()
    history = []
    next_ctr = [0] * a_n

    def apply_op(s, op):
        if op[0] == "add":
            return apply_add(s, op[1], jnp.uint32(op[2]), op[3])[0]
        return apply_rm(s, op[1], op[2])[0]

    for _ in range(10):
        actor = int(rng.integers(a_n))
        eids = np.flatnonzero(rng.random(e_n) < 0.4)[:6]
        lst = jnp.asarray(
            np.pad(eids, (0, 6 - len(eids)), constant_values=-1), jnp.int32
        )
        if rng.random() < 0.7 or not history:
            next_ctr[actor] += 1
            op = ("add", actor, next_ctr[actor], lst)
        else:
            top = np.asarray(site.top).astype(np.uint64)
            if rng.random() < 0.3:
                top[actor] += 1  # ahead -> parks
            op = ("rm", jnp.asarray(top, DTYPE), lst)
        site = apply_op(site, op)
        history.append(op)
    states = [mk()]
    for _ in range(6):
        take = rng.random(len(history)) < 0.6
        s = mk()
        for keep, op in zip(take, history):
            if keep:
                s = apply_op(s, op)
        states.append(s)
    return states


def _law_canon(s: SparseOrswotState) -> SparseOrswotState:
    from ..analysis.canon import canon_epochs

    dcl, didx, dvalid = canon_epochs(s.dcl, s.didx, s.dvalid, payload_fill=-1)
    return s._replace(dcl=dcl, didx=didx, dvalid=dvalid)


@jax.jit
def compact(state: SparseOrswotState, frontier: jax.Array):
    """Causal-stability compaction (reclaim/): replay parked removes
    against the segment table (kills any dots their caught-up clocks
    still cover — the "caught-up" part; idempotent for states that
    settled at the last join), retire the slots the stable frontier
    dominates, scrub stale parked payload, and re-canonicalize so dead
    lanes pack to the tail — the freed tail is the headroom
    ``elastic.shrink`` turns into bytes. Observable reads (membership)
    are untouched: a retired slot's removal effect was already applied
    at park time (``apply_rm`` kills the covered part immediately) and
    at every replica whose top covers it. Returns
    ``(state, freed_slots, freed_bytes)``."""
    from ..reclaim.compaction import retire_epochs

    valid = _replay_parked(
        state.eid, state.act, state.ctr, state.valid,
        state.dcl, state.didx, state.dvalid,
    )
    eid, act, ctr, valid, _ = _canon(
        state.eid, state.act, jnp.where(valid, state.ctr, 0), valid,
        state.eid.shape[-1],
    )
    dcl, didx, dvalid, freed, freed_b = retire_epochs(
        state.dcl, state.didx, state.dvalid, state.top, frontier,
        payload_fill=-1,
    )
    return (
        SparseOrswotState(
            top=state.top, eid=eid, act=act, ctr=ctr, valid=valid,
            dcl=dcl, didx=didx, dvalid=dvalid,
        ),
        freed,
        freed_b,
    )


def _observe(s: SparseOrswotState):
    """The observable read: the live member-id set, deduped across
    witness actors and canonically sorted (dead lanes as -1) so
    converged replicas compare equal leaf-wise."""
    first = jnp.concatenate(
        [jnp.ones_like(s.valid[..., :1]), s.eid[..., 1:] != s.eid[..., :-1]],
        axis=-1,
    )
    member = jnp.where(s.valid & first, s.eid, _INT32_MAX)
    member = jnp.sort(member, axis=-1)
    return jnp.where(member == _INT32_MAX, -1, member)


def _decomp_split(s: SparseOrswotState):
    """Decomposition granularity (delta_opt/): one δ lane per segment-
    table dot lane (positional — canonical order keeps the diff tight
    under append-style growth); top + parked buffer residual."""
    return (s.eid, s.act, s.ctr, s.valid), (s.top, s.dcl, s.didx, s.dvalid)


def _decomp_unsplit(rows, res) -> SparseOrswotState:
    eid, act, ctr, valid = rows
    top, dcl, didx, dvalid = res
    return SparseOrswotState(
        top=top, eid=eid, act=act, ctr=ctr, valid=valid,
        dcl=dcl, didx=didx, dvalid=dvalid,
    )


from ..analysis.registry import (  # noqa: E402
    register_compactor,
    register_decomposition,
    register_merge,
)

register_merge(
    "sparse_orswot", module=__name__, join=join, states=_law_states,
    canon=_law_canon, big_states=_law_states_big,
)
register_compactor(
    "sparse_orswot", module=__name__, compact=compact, observe=_observe,
    top_of=lambda s: s.top,
)
register_decomposition(
    "sparse_orswot", module=__name__, split=_decomp_split,
    unsplit=_decomp_unsplit,
)
