"""Batched lattice kernels — the XLA data plane.

Every kernel here is a pure function on dense arrays, jit/vmap-friendly
(static shapes, no data-dependent Python control flow), and bit-identical
to the corresponding ``crdt_tpu.pure`` oracle operation under the A/B
property suite in tests/. These are the "native" components of the
framework in the sense of SURVEY.md §3: the compiled code XLA generates
from them is the TPU equivalent of the reference's compiled Rust.
"""

from . import vclock  # noqa: F401
from . import orswot  # noqa: F401
from . import gset  # noqa: F401
from . import lwwreg  # noqa: F401
from . import mvreg  # noqa: F401
