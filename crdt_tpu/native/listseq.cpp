// listseq — native sequence-CRDT engine: dense identifier allocation +
// ordered-sequence maintenance for the List/GList types.
//
// This is the host-side hot loop of BASELINE config 5 (automerge-perf
// style edit traces, SURVEY.md §4.5): identifier allocation is inherently
// sequential per edit trace (each op's identifier depends on the current
// neighbor identifiers), so it cannot ride the TPU — the reference runs
// it as native Rust; here it is native C++ behind a ctypes boundary
// (crdt_tpu/native/__init__.py), with the batched multi-replica op
// application done on device (crdt_tpu/models/list.py).
//
// Semantics mirror crdt_tpu/pure/identifier.py `between` exactly
// (LSEQ/Logoot-style (index, marker) tree paths, BASE = 2^31, markers =
// OrdDot(actor, counter) compared lexicographically) — the parity suite
// (tests/test_native_list.py) asserts bit-identical identifiers against
// the pure oracle. Reference: src/identifier.rs, src/list.rs.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <unordered_map>
#include <vector>

namespace {

constexpr int64_t BASE = int64_t(1) << 31;

struct Comp {
  int64_t idx;
  int32_t actor;   // marker: OrdDot.actor (dense interned id)
  uint64_t ctr;    // marker: OrdDot.counter
};

inline int cmp_comp(const Comp& a, const Comp& b) {
  if (a.idx != b.idx) return a.idx < b.idx ? -1 : 1;
  if (a.actor != b.actor) return a.actor < b.actor ? -1 : 1;
  if (a.ctr != b.ctr) return a.ctr < b.ctr ? -1 : 1;
  return 0;
}

using Path = std::vector<Comp>;

// Lexicographic path comparison; a strict prefix sorts before its
// extensions (Python tuple semantics).
inline int cmp_path(const Path& a, const Path& b) {
  size_t n = a.size() < b.size() ? a.size() : b.size();
  for (size_t i = 0; i < n; ++i) {
    int c = cmp_comp(a[i], b[i]);
    if (c) return c;
  }
  if (a.size() != b.size()) return a.size() < b.size() ? -1 : 1;
  return 0;
}

// Mirror of pure/identifier.py `between` — see that file for the
// invariant notes. lo/hi may be null (-inf/+inf bounds).
Path between(const Path* lo, const Path* hi, int32_t actor, uint64_t ctr) {
  Path prefix;
  bool lo_active = lo && !lo->empty();
  bool hi_active = hi && !hi->empty();
  size_t d = 0;
  for (;;) {
    const Comp* l =
        (lo_active && d < lo->size()) ? &(*lo)[d] : nullptr;
    const Comp* h =
        (hi_active && d < hi->size()) ? &(*hi)[d] : nullptr;
    int64_t h_idx = h ? h->idx : BASE;

    if (l) {
      if (h_idx - l->idx > 1) {
        prefix.push_back({(l->idx + h_idx) / 2, actor, ctr});
        return prefix;
      }
      prefix.push_back(*l);
      if (!h || cmp_comp(*l, *h) < 0) hi_active = false;
    } else {
      if (h_idx >= 2) {
        prefix.push_back({h_idx / 2, actor, ctr});
        return prefix;
      }
      if (h_idx == 1) {
        prefix.push_back({0, actor, ctr});
        hi_active = false;
      } else {
        prefix.push_back(*h);
      }
    }
    ++d;
  }
}

struct Engine {
  std::vector<Path> ids;        // identifier arena; handle = index
  std::vector<int32_t> vals;    // value id per handle
  std::vector<uint8_t> alive;   // liveness per handle
  std::vector<int64_t> seq;     // handles of live identifiers, in order
  std::unordered_map<int32_t, uint64_t> clock;  // actor -> max counter
};

}  // namespace

extern "C" {

void* ls_new() { return new Engine(); }

void ls_free(void* e) { delete static_cast<Engine*>(e); }

// Apply a local edit trace. kinds[i]: 0 = insert, 1 = delete. For
// inserts, idx[i] is the insert position in [0, len] and vals[i] the
// value id; for deletes, idx[i] is the victim position in [0, len).
// actors[i] mints the op's dot. out_handle[i] receives the op's
// identifier handle (the stable device slot). Returns the number of ops
// applied, or -(i+1) if op i had an out-of-range index.
int64_t ls_apply_trace(void* ep, const uint8_t* kinds, const int64_t* idx,
                       const int32_t* vals, const int32_t* actors,
                       int64_t n, int64_t* out_handle) {
  Engine& e = *static_cast<Engine*>(ep);
  for (int64_t i = 0; i < n; ++i) {
    int64_t p = idx[i];
    uint64_t ctr = ++e.clock[actors[i]];
    if (kinds[i] == 0) {
      if (p < 0 || p > int64_t(e.seq.size())) return -(i + 1);
      const Path* lo = p > 0 ? &e.ids[e.seq[p - 1]] : nullptr;
      const Path* hi =
          p < int64_t(e.seq.size()) ? &e.ids[e.seq[p]] : nullptr;
      Path ident = between(lo, hi, actors[i], ctr);
      int64_t handle = int64_t(e.ids.size());
      e.ids.push_back(std::move(ident));
      e.vals.push_back(vals[i]);
      e.alive.push_back(1);
      e.seq.insert(e.seq.begin() + p, handle);
      out_handle[i] = handle;
    } else {
      if (p < 0 || p >= int64_t(e.seq.size())) return -(i + 1);
      int64_t handle = e.seq[p];
      e.alive[handle] = 0;
      e.seq.erase(e.seq.begin() + p);
      out_handle[i] = handle;
    }
  }
  return n;
}

// Apply a remote op stream by identifier (CmRDT apply — reference:
// src/list.rs CmRDT::apply). kinds[i]: 0 = insert (identifier given by
// handle into a FOREIGN engine's arena is meaningless here, so remote
// ops are described by their full identifier paths): paths are passed
// flattened — comp_counts[i] components for op i, drawn sequentially
// from (cidx, cactor, cctr). Inserts carry vals[i]; duplicate inserts
// and deletes of absent identifiers are no-ops (idempotent delivery).
// out_handle[i] = local handle of the identifier. Returns n or -(i+1).
int64_t ls_apply_remote(void* ep, const uint8_t* kinds,
                        const int64_t* comp_counts, const int64_t* cidx,
                        const int32_t* cactor, const uint64_t* cctr,
                        const int32_t* vals, int64_t n,
                        int64_t* out_handle) {
  Engine& e = *static_cast<Engine*>(ep);
  int64_t off = 0;
  for (int64_t i = 0; i < n; ++i) {
    if (comp_counts[i] <= 0) return -(i + 1);  // malformed wire input
    Path ident;
    ident.reserve(comp_counts[i]);
    for (int64_t c = 0; c < comp_counts[i]; ++c)
      ident.push_back({cidx[off + c], cactor[off + c], cctr[off + c]});
    off += comp_counts[i];
    // Binary search for the identifier's rank in the live sequence.
    int64_t lo = 0, hi = int64_t(e.seq.size());
    while (lo < hi) {
      int64_t mid = (lo + hi) / 2;
      if (cmp_path(e.ids[e.seq[mid]], ident) < 0)
        lo = mid + 1;
      else
        hi = mid;
    }
    bool present = lo < int64_t(e.seq.size()) &&
                   cmp_path(e.ids[e.seq[lo]], ident) == 0;
    // Track causality: the op's dot is the final component's marker.
    const Comp& last = ident.back();
    uint64_t& top = e.clock[last.actor];
    if (last.ctr > top) top = last.ctr;
    if (kinds[i] == 0) {
      if (!present) {
        int64_t handle = int64_t(e.ids.size());
        e.ids.push_back(std::move(ident));
        e.vals.push_back(vals[i]);
        e.alive.push_back(1);
        e.seq.insert(e.seq.begin() + lo, handle);
        out_handle[i] = handle;
      } else {
        out_handle[i] = e.seq[lo];
      }
    } else {
      if (present) {
        int64_t handle = e.seq[lo];
        e.alive[handle] = 0;
        e.seq.erase(e.seq.begin() + lo);
        out_handle[i] = handle;
      } else {
        out_handle[i] = -1;
      }
    }
  }
  return n;
}

int64_t ls_len(void* ep) {
  return int64_t(static_cast<Engine*>(ep)->seq.size());
}

int64_t ls_total_ids(void* ep) {
  return int64_t(static_cast<Engine*>(ep)->ids.size());
}

// Live sequence: handles (device slots) and value ids, in order.
void ls_read(void* ep, int64_t* out_handles, int32_t* out_vals) {
  Engine& e = *static_cast<Engine*>(ep);
  for (size_t i = 0; i < e.seq.size(); ++i) {
    out_handles[i] = e.seq[i];
    if (out_vals) out_vals[i] = e.vals[e.seq[i]];
  }
}

// Rank of every allocated identifier in the TOTAL identifier order
// (live or dead) — the device order-maintenance permutation: a read is
// a gather of alive values through this order.
void ls_total_order(void* ep, int64_t* out_rank) {
  Engine& e = *static_cast<Engine*>(ep);
  std::vector<int64_t> order(e.ids.size());
  for (size_t i = 0; i < order.size(); ++i) order[i] = int64_t(i);
  std::stable_sort(order.begin(), order.end(), [&](int64_t a, int64_t b) {
    return cmp_path(e.ids[a], e.ids[b]) < 0;
  });
  for (size_t r = 0; r < order.size(); ++r) out_rank[order[r]] = int64_t(r);
}

// Identifier introspection (for the parity suite): path length, then
// the components of handle's path.
int64_t ls_id_len(void* ep, int64_t handle) {
  Engine& e = *static_cast<Engine*>(ep);
  if (handle < 0 || handle >= int64_t(e.ids.size())) return -1;
  return int64_t(e.ids[handle].size());
}

void ls_id_path(void* ep, int64_t handle, int64_t* out_idx,
                int32_t* out_actor, uint64_t* out_ctr) {
  Engine& e = *static_cast<Engine*>(ep);
  const Path& p = e.ids[handle];
  for (size_t i = 0; i < p.size(); ++i) {
    out_idx[i] = p[i].idx;
    out_actor[i] = p[i].actor;
    out_ctr[i] = p[i].ctr;
  }
}

int64_t ls_clock_get(void* ep, int32_t actor) {
  Engine& e = *static_cast<Engine*>(ep);
  auto it = e.clock.find(actor);
  return it == e.clock.end() ? 0 : int64_t(it->second);
}

// Actor-clock persistence (checkpoint/resume): deletes consume mint
// counters that no surviving identifier path records, so restoring an
// engine from identifier paths alone would re-mint spent dots. The
// checkpoint dumps the clock map and re-seeds it after re-ingestion.
int64_t ls_clock_count(void* ep) {
  Engine& e = *static_cast<Engine*>(ep);
  return int64_t(e.clock.size());
}

void ls_clock_dump(void* ep, int32_t* out_actors, uint64_t* out_ctrs) {
  Engine& e = *static_cast<Engine*>(ep);
  size_t i = 0;
  for (const auto& kv : e.clock) {
    out_actors[i] = kv.first;
    out_ctrs[i] = kv.second;
    ++i;
  }
}

void ls_clock_seed(void* ep, int32_t actor, uint64_t ctr) {
  Engine& e = *static_cast<Engine*>(ep);
  uint64_t& top = e.clock[actor];
  if (ctr > top) top = ctr;
}

}  // extern "C"
