"""crdt_tpu.native — compiled host-side runtime components.

The reference is a native (Rust) crate; per the build rule its host-side
hot loops get native equivalents, not Python stand-ins. Today that is
``listseq`` (listseq.cpp): dense identifier allocation + ordered-sequence
maintenance for List/GList — the inherently sequential part of BASELINE
config 5 that cannot ride the TPU (SURVEY.md §4.5, §7.1 "identifier
allocation on host").

The shared library is built on demand with g++ (no pip, no pybind11 —
plain ctypes over an ``extern "C"`` surface) and cached next to the
source. ``ListEngine`` is the Python face; if the toolchain is missing
the pure-Python fallback (``_PyEngine``, driving ``crdt_tpu.pure.list``)
keeps the API alive at oracle speed.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sys
import tempfile
from typing import Optional, Sequence, Tuple

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "listseq.cpp")
_LIB = os.path.join(_DIR, "_listseq.so")


_BUILD_FAILED = False


def _build() -> Optional[str]:
    """Compile listseq.cpp → _listseq.so if stale/missing. Returns the
    library path, or None if no toolchain is available. A failure is
    cached so repeated engine constructions don't re-spawn g++."""
    global _BUILD_FAILED
    if _BUILD_FAILED:
        return None
    try:
        if os.path.exists(_LIB) and os.path.getmtime(_LIB) >= os.path.getmtime(_SRC):
            return _LIB
        # Build to a temp name then rename: atomic for concurrent pytest
        # workers sharing the checkout.
        fd, tmp = tempfile.mkstemp(suffix=".so", dir=_DIR)
        os.close(fd)
        cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", tmp]
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            os.unlink(tmp)
            print(f"crdt_tpu.native: g++ failed:\n{proc.stderr}", file=sys.stderr)
            _BUILD_FAILED = True
            return None
        os.replace(tmp, _LIB)
        return _LIB
    except (OSError, FileNotFoundError) as exc:
        print(f"crdt_tpu.native: build unavailable ({exc})", file=sys.stderr)
        _BUILD_FAILED = True
        return None


_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    path = _build()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        # Stale or wrong-arch binary (e.g. a cached .so from another
        # platform): rebuild from source once, else fall back.
        try:
            os.unlink(path)
        except OSError:
            pass
        path = _build()
        if path is None:
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError as exc:
            print(f"crdt_tpu.native: load failed ({exc})", file=sys.stderr)
            return None
    lib.ls_new.restype = ctypes.c_void_p
    lib.ls_free.argtypes = [ctypes.c_void_p]
    lib.ls_apply_trace.restype = ctypes.c_int64
    lib.ls_apply_trace.argtypes = [
        ctypes.c_void_p,
        np.ctypeslib.ndpointer(np.uint8, flags="C"),
        np.ctypeslib.ndpointer(np.int64, flags="C"),
        np.ctypeslib.ndpointer(np.int32, flags="C"),
        np.ctypeslib.ndpointer(np.int32, flags="C"),
        ctypes.c_int64,
        np.ctypeslib.ndpointer(np.int64, flags="C"),
    ]
    lib.ls_apply_remote.restype = ctypes.c_int64
    lib.ls_apply_remote.argtypes = [
        ctypes.c_void_p,
        np.ctypeslib.ndpointer(np.uint8, flags="C"),
        np.ctypeslib.ndpointer(np.int64, flags="C"),
        np.ctypeslib.ndpointer(np.int64, flags="C"),
        np.ctypeslib.ndpointer(np.int32, flags="C"),
        np.ctypeslib.ndpointer(np.uint64, flags="C"),
        np.ctypeslib.ndpointer(np.int32, flags="C"),
        ctypes.c_int64,
        np.ctypeslib.ndpointer(np.int64, flags="C"),
    ]
    lib.ls_len.restype = ctypes.c_int64
    lib.ls_len.argtypes = [ctypes.c_void_p]
    lib.ls_total_ids.restype = ctypes.c_int64
    lib.ls_total_ids.argtypes = [ctypes.c_void_p]
    lib.ls_read.argtypes = [
        ctypes.c_void_p,
        np.ctypeslib.ndpointer(np.int64, flags="C"),
        np.ctypeslib.ndpointer(np.int32, flags="C"),
    ]
    lib.ls_total_order.argtypes = [
        ctypes.c_void_p,
        np.ctypeslib.ndpointer(np.int64, flags="C"),
    ]
    lib.ls_id_len.restype = ctypes.c_int64
    lib.ls_id_len.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.ls_id_path.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int64,
        np.ctypeslib.ndpointer(np.int64, flags="C"),
        np.ctypeslib.ndpointer(np.int32, flags="C"),
        np.ctypeslib.ndpointer(np.uint64, flags="C"),
    ]
    lib.ls_clock_get.restype = ctypes.c_int64
    lib.ls_clock_get.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.ls_clock_count.restype = ctypes.c_int64
    lib.ls_clock_count.argtypes = [ctypes.c_void_p]
    lib.ls_clock_dump.argtypes = [
        ctypes.c_void_p,
        np.ctypeslib.ndpointer(np.int32, flags="C"),
        np.ctypeslib.ndpointer(np.uint64, flags="C"),
    ]
    lib.ls_clock_seed.argtypes = [ctypes.c_void_p, ctypes.c_int32, ctypes.c_uint64]
    _lib = lib
    return lib


def native_available() -> bool:
    return _load() is not None


INSERT, DELETE = 0, 1


class ListEngine:
    """Native sequence engine: the host half of the device List.

    Actors are dense int ids (callers intern, as everywhere else); for
    bit-identical identifier parity with the pure oracle the interned id
    order must agree with the actors' natural ordering (OrdDot markers
    compare by actor first).
    """

    def __init__(self):
        lib = _load()
        if lib is None:
            self._impl = _PyEngine()
            self._e = None
        else:
            self._impl = None
            self._e = ctypes.c_void_p(lib.ls_new())

    def __del__(self):
        if getattr(self, "_e", None) is not None and _lib is not None:
            _lib.ls_free(self._e)
            self._e = None

    @property
    def is_native(self) -> bool:
        return self._e is not None

    # ---- local edit trace (mint + apply) ------------------------------
    def apply_trace(
        self,
        kinds: Sequence[int],
        indices: Sequence[int],
        values: Sequence[int],
        actors: Sequence[int],
    ) -> np.ndarray:
        """Apply a local edit trace (INSERT at index with value / DELETE
        at index), minting identifiers; returns each op's identifier
        handle (the stable device slot)."""
        kinds = np.ascontiguousarray(kinds, np.uint8)
        indices = np.ascontiguousarray(indices, np.int64)
        values = np.ascontiguousarray(values, np.int32)
        actors = np.ascontiguousarray(actors, np.int32)
        n = len(kinds)
        out = np.empty(n, np.int64)
        if self._impl is not None:
            self._impl.apply_trace(kinds, indices, values, actors, out)
            return out
        rc = _lib.ls_apply_trace(self._e, kinds, indices, values, actors, n, out)
        if rc < 0:
            raise IndexError(f"trace op {-rc - 1}: index out of range")
        return out

    # ---- remote op delivery (CmRDT apply by identifier) ----------------
    def apply_remote(self, kinds, paths, values) -> np.ndarray:
        """Apply remote ops: each op is (kind, identifier path, value).
        Paths are sequences of (index, actor, counter) components.
        Duplicate inserts / absent deletes are idempotent no-ops."""
        n = len(kinds)
        counts = np.asarray([len(p) for p in paths], np.int64)
        flat = [c for p in paths for c in p]
        cidx = np.asarray([c[0] for c in flat], np.int64)
        cactor = np.asarray([c[1] for c in flat], np.int32)
        cctr = np.asarray([c[2] for c in flat], np.uint64)
        if (counts <= 0).any():
            bad = int(np.argmax(counts <= 0))
            raise ValueError(f"remote op {bad}: empty identifier path")
        kinds = np.ascontiguousarray(kinds, np.uint8)
        values = np.ascontiguousarray(values, np.int32)
        out = np.empty(n, np.int64)
        if self._impl is not None:
            self._impl.apply_remote(kinds, counts, cidx, cactor, cctr, values, out)
            return out
        rc = _lib.ls_apply_remote(
            self._e, kinds, counts, cidx, cactor, cctr, values, n, out
        )
        if rc < 0:
            raise ValueError(f"remote op {-rc - 1}: malformed identifier path")
        return out

    # ---- reads ---------------------------------------------------------
    def __len__(self) -> int:
        if self._impl is not None:
            return len(self._impl)
        return int(_lib.ls_len(self._e))

    def total_ids(self) -> int:
        if self._impl is not None:
            return self._impl.total_ids()
        return int(_lib.ls_total_ids(self._e))

    def read(self) -> Tuple[np.ndarray, np.ndarray]:
        """(handles, value ids) of the live sequence, in order."""
        n = len(self)
        handles = np.empty(n, np.int64)
        vals = np.empty(n, np.int32)
        if self._impl is not None:
            self._impl.read(handles, vals)
        else:
            _lib.ls_read(self._e, handles, vals)
        return handles, vals

    def total_order(self) -> np.ndarray:
        """rank[handle] over ALL allocated identifiers (live or dead) —
        the device order-maintenance permutation."""
        out = np.empty(self.total_ids(), np.int64)
        if self._impl is not None:
            self._impl.total_order(out)
        else:
            _lib.ls_total_order(self._e, out)
        return out

    def identifier_path(self, handle: int):
        """The (index, actor, counter) components of a handle's path."""
        if self._impl is not None:
            return self._impl.identifier_path(handle)
        n = int(_lib.ls_id_len(self._e, handle))
        if n < 0:
            raise IndexError(f"no identifier with handle {handle}")
        idx = np.empty(n, np.int64)
        act = np.empty(n, np.int32)
        ctr = np.empty(n, np.uint64)
        _lib.ls_id_path(self._e, handle, idx, act, ctr)
        return [(int(i), int(a), int(c)) for i, a, c in zip(idx, act, ctr)]

    def clock_get(self, actor: int) -> int:
        if self._impl is not None:
            return self._impl.clock_get(actor)
        return int(_lib.ls_clock_get(self._e, int(actor)))

    def clock_dump(self) -> Tuple[np.ndarray, np.ndarray]:
        """(actors, counters) of the mint clock — checkpoint payload
        (deletes consume counters no identifier path records)."""
        if self._impl is not None:
            return self._impl.clock_dump()
        n = int(_lib.ls_clock_count(self._e))
        actors = np.empty(n, np.int32)
        ctrs = np.empty(n, np.uint64)
        _lib.ls_clock_dump(self._e, actors, ctrs)
        return actors, ctrs

    def clock_seed(self, actor: int, ctr: int) -> None:
        """Raise an actor's mint clock to at least ``ctr`` (resume)."""
        if self._impl is not None:
            self._impl.clock_seed(actor, ctr)
        else:
            _lib.ls_clock_seed(self._e, int(actor), int(ctr))


class _PyEngine:
    """Pure-Python fallback with the same surface, driving the oracle
    types — correctness-equal, oracle-speed."""

    def __init__(self):
        from ..pure.identifier import Identifier, between

        self._between = between
        self._Identifier = Identifier
        self.ids = []       # handle -> Identifier
        self.vals = []
        self.alive = []
        self.seq = []       # handles in order
        self.clock = {}

    def apply_trace(self, kinds, indices, values, actors, out):
        from ..dot import OrdDot

        for i in range(len(kinds)):
            p = int(indices[i])
            actor = int(actors[i])
            self.clock[actor] = self.clock.get(actor, 0) + 1
            if kinds[i] == INSERT:
                if p < 0 or p > len(self.seq):
                    raise IndexError(f"trace op {i}: index out of range")
                lo = self.ids[self.seq[p - 1]] if p > 0 else None
                hi = self.ids[self.seq[p]] if p < len(self.seq) else None
                ident = self._between(lo, hi, OrdDot(actor, self.clock[actor]))
                handle = len(self.ids)
                self.ids.append(ident)
                self.vals.append(int(values[i]))
                self.alive.append(True)
                self.seq.insert(p, handle)
                out[i] = handle
            else:
                if p < 0 or p >= len(self.seq):
                    raise IndexError(f"trace op {i}: index out of range")
                handle = self.seq.pop(p)
                self.alive[handle] = False
                out[i] = handle

    def apply_remote(self, kinds, counts, cidx, cactor, cctr, values, out):
        import bisect
        from ..dot import OrdDot

        off = 0
        for i in range(len(kinds)):
            comps = tuple(
                (int(cidx[off + c]), OrdDot(int(cactor[off + c]), int(cctr[off + c])))
                for c in range(int(counts[i]))
            )
            off += int(counts[i])
            ident = self._Identifier(comps)
            marker = comps[-1][1]
            self.clock[marker.actor] = max(
                self.clock.get(marker.actor, 0), marker.counter
            )
            keys = [self.ids[h] for h in self.seq]
            pos = bisect.bisect_left(keys, ident)
            present = pos < len(self.seq) and keys[pos] == ident
            if kinds[i] == INSERT:
                if not present:
                    handle = len(self.ids)
                    self.ids.append(ident)
                    self.vals.append(int(values[i]))
                    self.alive.append(True)
                    self.seq.insert(pos, handle)
                    out[i] = handle
                else:
                    out[i] = self.seq[pos]
            else:
                if present:
                    handle = self.seq.pop(pos)
                    self.alive[handle] = False
                    out[i] = handle
                else:
                    out[i] = -1

    def __len__(self):
        return len(self.seq)

    def total_ids(self):
        return len(self.ids)

    def read(self, handles, vals):
        for i, h in enumerate(self.seq):
            handles[i] = h
            vals[i] = self.vals[h]

    def total_order(self, out):
        order = sorted(range(len(self.ids)), key=lambda h: self.ids[h])
        for r, h in enumerate(order):
            out[h] = r

    def identifier_path(self, handle):
        return [(i, m.actor, m.counter) for i, m in self.ids[handle].path]

    def clock_get(self, actor):
        return self.clock.get(int(actor), 0)

    def clock_dump(self):
        actors = np.asarray(list(self.clock.keys()), np.int32)
        ctrs = np.asarray(list(self.clock.values()), np.uint64)
        return actors, ctrs

    def clock_seed(self, actor, ctr):
        actor, ctr = int(actor), int(ctr)
        if ctr > self.clock.get(actor, 0):
            self.clock[actor] = ctr


__all__ = ["ListEngine", "native_available", "INSERT", "DELETE"]
