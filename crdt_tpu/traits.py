"""Trait contracts — the two replication disciplines + causal removal.

Reference: src/traits.rs — ``CvRDT``, ``CmRDT``, ``Causal``/``ResetRemove``,
and the v7-era ``Validation`` associated types with ``validate_merge`` /
``validate_op`` (SURVEY.md §2 L0; mount empty, symbols per SURVEY.md §0).

Both trait-name vintages are provided (``Causal`` is an alias of
``ResetRemove``; ``forget`` is an alias of ``reset_remove``) because the
fork's exact era is unknown — SURVEY.md §0 says to implement the union.
"""

from __future__ import annotations

import abc
from typing import Any, Generic, TypeVar

Op = TypeVar("Op")


class ValidationError(Exception):
    """Base class for pre-merge / pre-apply validation failures.

    Reference: src/traits.rs associated ``type Validation`` error carriers.
    """


class DotRange(ValidationError):
    """A dot is non-contiguous with the clock it is applied against.

    Reference: src/dot.rs ``DotRange`` — raised by ``validate_op`` when an
    op's dot duplicates (counter <= seen) or gaps (counter > seen + 1) the
    local per-actor counter.
    """

    def __init__(self, actor: Any, counter: int, next_counter: int):
        self.actor = actor
        self.counter = counter
        self.next_counter = next_counter
        super().__init__(
            f"dot ({actor!r}, {counter}) is out of range: next expected "
            f"counter for this actor is {next_counter}"
        )


class CounterSaturation(ValidationError):
    """A device counter lane is at (or would exceed) its dtype's maximum.

    No reference analog — src/vclock.rs is u64 end to end; the device
    lattice defaults to u32 lanes (the fused fold's bandwidth advantage
    rides on 4-byte lanes), so a lane reaching 2^32-1 would silently
    break clock monotonicity on the next event. Strict mode turns that
    into this error; ``configure(counter_dtype="uint64")`` restores
    reference width for the clock/counter family."""

    def __init__(self, actor: Any, counter: int, limit: int):
        self.actor = actor
        self.counter = counter
        self.limit = limit
        super().__init__(
            f"counter lane for {actor!r} at {counter} is saturated "
            f"(dtype max {limit}); widen counter_dtype or retire the actor"
        )


class ConflictingMarker(ValidationError):
    """LWW merge saw equal markers guarding different values.

    Reference: src/lwwreg.rs ``validate_merge`` conflicting-marker error
    [LOW-CONF name per SURVEY.md §3 row 8].
    """


class CvRDT(abc.ABC):
    """State-based (convergent) CRDT: ``merge`` is a join-semilattice op.

    Reference: src/traits.rs ``trait CvRDT { fn merge(&mut self, Self) }``.
    ``merge`` must be commutative, associative, and idempotent — property
    tests in tests/ assert all three for every type.
    """

    @abc.abstractmethod
    def merge(self, other: "CvRDT") -> None:
        """Join ``other``'s state into ``self`` (in place)."""

    def validate_merge(self, other: "CvRDT") -> None:
        """Raise ``ValidationError`` if merging ``other`` would be unsound.

        Default: always valid. Reference: src/traits.rs ``validate_merge``
        (v7).
        """


class CmRDT(abc.ABC, Generic[Op]):
    """Op-based (commutative) CRDT: ``apply`` commutes for concurrent ops.

    Reference: src/traits.rs ``trait CmRDT { type Op; fn apply(&mut self,
    Self::Op) }``. Causal delivery is assumed for dependent ops; ``apply``
    must be idempotent for duplicated ops wherever the reference's is
    (e.g. Orswot drops already-seen dots).
    """

    @abc.abstractmethod
    def apply(self, op: Op) -> None:
        """Apply a (possibly remote) op to local state (in place)."""

    def validate_op(self, op: Op) -> None:
        """Raise ``ValidationError`` if ``op`` cannot be applied soundly.

        Default: always valid. Reference: src/traits.rs ``validate_op`` (v7).
        """


class ResetRemove(abc.ABC):
    """Causal removal: forget all dots dominated by ``clock``.

    Reference: src/traits.rs — v7 ``trait ResetRemove<A> { fn
    reset_remove(&mut self, &VClock<A>) }``; v4–v6 spelled ``Causal`` /
    ``forget``. Used by Map removal to reset children under the removed
    clock (SURVEY.md §4.3).
    """

    @abc.abstractmethod
    def reset_remove(self, clock) -> None:
        """Remove any state dominated by ``clock`` (in place)."""

    def forget(self, clock) -> None:
        """v4–v6 era alias of ``reset_remove``."""
        self.reset_remove(clock)


# v4–v6 era name for the same contract.
Causal = ResetRemove
