"""VClock — the causality engine (actor → counter map, partial order).

Reference: src/vclock.rs ``VClock<A: Ord> { dots: BTreeMap<A, u64> }`` with
``inc`` / ``get`` / ``apply(Dot)`` / ``merge`` / ``partial_cmp`` (None =
concurrent) / ``glb``/``intersection`` / ``forget``/``reset_remove`` /
``clone_without`` (SURVEY.md §3 row 2; mount empty, symbols per §0).

This is the sequential oracle form (a dict). The batched device form of the
same lattice (``crdt_tpu.ops.vclock``) makes merge an element-wise max and
compare a sign analysis of the difference, bit-identical to this
implementation under the property suite in tests/.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, Optional

from .dot import Dot
from .traits import CmRDT, CvRDT, ResetRemove


class VClock(CvRDT, CmRDT, ResetRemove):
    """Vector clock: a map of actor → max counter observed for that actor.

    An absent actor is equivalent to counter 0 (never stored — the invariant
    matches the reference, which never stores zero counters, so equality is
    plain dict equality).
    """

    __slots__ = ("dots",)

    def __init__(self, dots: Optional[Dict[Any, int]] = None):
        self.dots: Dict[Any, int] = {}
        if dots:
            for actor, counter in dots.items():
                if counter < 0:
                    raise ValueError(f"negative counter for {actor!r}")
                if counter > 0:
                    self.dots[actor] = counter

    # ---- reads ---------------------------------------------------------
    def get(self, actor: Any) -> int:
        """Max counter observed for ``actor`` (0 if never seen).

        Reference: src/vclock.rs ``VClock::get``.
        """
        return self.dots.get(actor, 0)

    def dot(self, actor: Any) -> Dot:
        """The latest dot observed for ``actor``.

        Reference: src/vclock.rs ``VClock::dot``.
        """
        return Dot(actor, self.get(actor))

    def is_empty(self) -> bool:
        return not self.dots

    def __iter__(self) -> Iterator[Dot]:
        """Iterate observed dots. Reference: src/vclock.rs ``VClock::iter``."""
        return (Dot(a, c) for a, c in self.dots.items())

    def __len__(self) -> int:
        return len(self.dots)

    # ---- mutation ------------------------------------------------------
    def inc(self, actor: Any) -> Dot:
        """Return (without applying) the next dot for ``actor``.

        Reference: src/vclock.rs ``VClock::inc`` — pure; the caller applies
        the returned dot (the op) via ``apply``.
        """
        return self.dot(actor).inc()

    def validate_op(self, op: Dot) -> None:
        """DotRange unless the dot is the next contiguous event for its
        actor. Reference: src/vclock.rs ``validate_op`` (v7)."""
        from .traits import DotRange

        expected = self.get(op.actor) + 1
        if op.counter != expected:
            raise DotRange(op.actor, op.counter, expected)

    def apply(self, op: Dot) -> None:
        """Observe a dot; monotone (ignores stale counters).

        Reference: src/vclock.rs ``impl CmRDT for VClock`` (Op = Dot).
        """
        if op.counter > self.get(op.actor):
            self.dots[op.actor] = op.counter

    def merge(self, other: "VClock") -> None:
        """Join: element-wise max. Reference: src/vclock.rs CvRDT::merge."""
        for actor, counter in other.dots.items():
            if counter > self.get(actor):
                self.dots[actor] = counter

    def reset_remove(self, clock: "VClock") -> None:
        """Forget dots dominated by ``clock``: drop actor a iff
        self[a] <= clock[a].

        Reference: src/vclock.rs ``ResetRemove``/``forget``.
        """
        for actor in list(self.dots):
            if clock.get(actor) >= self.dots[actor]:
                del self.dots[actor]

    # ---- lattice / order ----------------------------------------------
    def partial_cmp(self, other: "VClock") -> Optional[int]:
        """-1 if self < other, 0 if equal, 1 if self > other, None if
        concurrent. Reference: src/vclock.rs ``PartialOrd::partial_cmp``.
        """
        if self.dots == other.dots:
            return 0
        le = all(c <= other.get(a) for a, c in self.dots.items())
        ge = all(c <= self.get(a) for a, c in other.dots.items())
        if le and not ge:
            return -1
        if ge and not le:
            return 1
        if le and ge:
            return 0
        return None

    def __le__(self, other: "VClock") -> bool:
        return all(c <= other.get(a) for a, c in self.dots.items())

    def __lt__(self, other: "VClock") -> bool:
        return self <= other and self.dots != other.dots

    def __ge__(self, other: "VClock") -> bool:
        return other <= self

    def __gt__(self, other: "VClock") -> bool:
        return other < self

    def concurrent(self, other: "VClock") -> bool:
        return self.partial_cmp(other) is None

    def __eq__(self, other) -> bool:
        return isinstance(other, VClock) and self.dots == other.dots

    def __hash__(self) -> int:
        # VClocks key the deferred-removal maps (Orswot/Map), mirroring the
        # reference's HashMap<VClock, _>; dots never mutate while used as a
        # key there because we hash a frozen snapshot.
        return hash(frozenset(self.dots.items()))

    def glb(self, other: "VClock") -> "VClock":
        """Greatest lower bound: element-wise min (absent = 0 drops out).

        Reference: src/vclock.rs ``VClock::glb``/``intersection``.
        """
        out = {}
        for actor, counter in self.dots.items():
            m = min(counter, other.get(actor))
            if m > 0:
                out[actor] = m
        return VClock(out)

    intersection = glb

    def clone_without(self, base: "VClock") -> "VClock":
        """Clone keeping only dots NOT dominated by ``base``
        (self[a] > base[a]). Reference: src/vclock.rs ``clone_without``
        [LOW-CONF name per SURVEY §3 row 2].
        """
        return VClock(
            {a: c for a, c in self.dots.items() if c > base.get(a)}
        )

    def clone(self) -> "VClock":
        return VClock(dict(self.dots))

    def __repr__(self) -> str:
        inner = ", ".join(f"{a!r}:{c}" for a, c in sorted(self.dots.items(), key=lambda kv: repr(kv[0])))
        return f"VClock<{inner}>"
