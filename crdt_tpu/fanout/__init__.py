"""crdt_tpu.fanout — the δ-subscription fan-out plane (ISSUE 16).

The serving tier (crdt_tpu/serve/) gets writes IN at device speed;
this package pushes converged updates back OUT to a million thin
clients. Three cooperating pieces (see each module's docstring):

- :mod:`.plane` — :class:`FanoutPlane`: the subscription registry
  (clients register ``(tenant, acked watermark)`` interests) and push
  driver. Subscribers sharing an acked watermark form a COHORT — one
  join-irreducible decomposition and one wire payload serve them all —
  and cohorts pack into ``mesh_fanout_push`` dispatches riding the
  superblock's tenant→lane indirection (so the registry survives
  eviction/re-warm). Watermarks promote ONLY on positive ack
  (delta_opt/ackwin.py semantics host-side); out-of-window subscribers
  degrade to the PR 10/11 snapshot+suffix bootstrap, never unbounded
  buffering.
- :mod:`crdt_tpu.ops.fanout_kernels` / ``parallel/fanout_push.py`` —
  the device half: the PR 14 fused wire kernel generalized from P ring
  links to B·E client lanes (one ``wire_pack`` launch per dispatch,
  biased-u16 delta vs the acked base, bit-packed residual bitmaps).
- :mod:`.client` — :class:`ClientReplica`: the thin-client receive
  half; its acked ``base`` equals the encoder's base bit-exactly by
  promote-on-ack, which is what makes the wire decode sound and the
  replay property (client ≡ served tenant at every acked watermark)
  hold.

Plus :func:`static_checks` — the ``fanout`` section of
tools/run_static_checks.py: surface-registry coverage, the
encode/decode round-trip + push/replay micro A/B, and the broken-twin
gate (the watermark-bucket-skipping pusher in ``analysis.fixtures``
must be caught by :func:`plane.fanout_covers_cohorts`).
"""

from __future__ import annotations

from typing import List

from .client import ClientReplica
from .plane import (
    CohortPush,
    CohortResync,
    FanoutPlane,
    PushReport,
    fanout_covers_cohorts,
)


def static_checks() -> List:
    """The ``fanout`` static-check section (Finding list, empty =
    clean):

    1. **surface coverage** — every public operational symbol of this
       package must have called
       ``analysis.registry.register_fanout_surface`` (the
       registration-is-the-coverage-contract rule).
    2. **push/replay micro A/B** — a two-subscriber workload with split
       acked watermarks must land BOTH client replicas bit-identical
       to the served tenant (one cohort per watermark bucket), and the
       cohort wire encode/decode must round-trip the decomposition
       bit-exactly.
    3. **broken twin fires** — the bucket-skipping pusher twin
       (``analysis.fixtures.fanout_skips_watermark_bucket``) must FAIL
       :func:`plane.fanout_covers_cohorts`; the honest
       :meth:`FanoutPlane.push` must pass.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..analysis import fixtures
    from ..analysis.registry import (
        get_decomposer,
        unregistered_fanout_surfaces,
    )
    from ..analysis.report import Finding
    from ..ops import superblock as sb_ops
    from ..ops.fanout_kernels import (
        cohort_deltas,
        cohort_wire_decode,
        cohort_wire_encode,
    )

    findings: List[Finding] = []

    for name in unregistered_fanout_surfaces():
        findings.append(Finding(
            "fanout-surface-coverage", name,
            "public fanout symbol never called register_fanout_surface "
            "— the fanout gate cannot see it",
        ))

    # 2. encode/decode round-trip on a micro cohort batch.
    try:
        caps = dict(n_elems=4, n_actors=2, deferred_cap=2)
        tk = sb_ops.tenant_kind("orswot")
        m = lambda *on: jnp.asarray(np.isin(np.arange(4), on))  # noqa: E731
        live = tk.empty(**caps)
        live, _ = tk.apply_add(live, jnp.int32(0), jnp.uint32(1), m(0, 1))
        live, _ = tk.apply_add(live, jnp.int32(1), jnp.uint32(1), m(2))
        base = tk.empty(**caps)
        base, _ = tk.apply_add(base, jnp.int32(0), jnp.uint32(1), m(0, 1))
        rows = jax.tree.map(lambda a, b: jnp.stack([a, b]), live, base)
        bases = jax.tree.map(lambda b: jnp.stack([b, b]), base)
        d = cohort_deltas("orswot", rows, bases)
        base_lanes, base_res = get_decomposer("orswot").split(bases)
        wire = cohort_wire_encode(d, jax.tree.leaves(base_lanes)[0])
        rt = cohort_wire_decode(
            wire, jax.tree.leaves(base_lanes)[0], base_res
        )
        ok = (
            bool(jnp.array_equal(d.valid, rt.valid))
            and all(
                bool(jnp.array_equal(
                    jnp.where(
                        d.valid.reshape(
                            d.valid.shape + (1,) * (x.ndim - 2)
                        ),
                        x, jnp.zeros_like(x),
                    ),
                    jnp.where(
                        d.valid.reshape(
                            d.valid.shape + (1,) * (y.ndim - 2)
                        ),
                        y, jnp.zeros_like(y),
                    ),
                ))
                for x, y in zip(
                    jax.tree.leaves(d.lanes), jax.tree.leaves(rt.lanes)
                )
            )
            and all(
                bool(jnp.array_equal(x, y))
                for x, y in zip(
                    jax.tree.leaves(d.residual),
                    jax.tree.leaves(rt.residual),
                )
            )
        )
        if not ok:
            findings.append(Finding(
                "fanout-wire-roundtrip", "cohort_wire_encode",
                "cohort wire decode is not the bit-exact inverse of "
                "encode on the micro batch",
            ))
        # Changed lanes must partition into keep ∪ defer exactly.
        if not bool(jnp.array_equal(wire.keep | wire.defer, d.valid)):
            findings.append(Finding(
                "fanout-wire-roundtrip", "keep/defer",
                "keep ∪ defer does not cover the changed-lane mask — "
                "some δ lanes would never ship",
            ))
    except Exception as exc:
        findings.append(Finding(
            "fanout-wire-roundtrip", "micro-batch",
            f"cohort wire micro A/B crashed: {type(exc).__name__}: "
            f"{exc}",
        ))

    # 3. push/replay property + broken twin, both directions.
    try:
        if not fanout_covers_cohorts(lambda plane: plane.push()):
            findings.append(Finding(
                "fanout-cohort-coverage", "FanoutPlane.push",
                "the honest pusher left a client replica diverged from "
                "the served tenant across split watermark buckets",
            ))
        if fanout_covers_cohorts(fixtures.fanout_skips_watermark_bucket):
            findings.append(Finding(
                "broken-fixture-missed", "fanout_skips_watermark_bucket",
                "the bucket-skipping pusher twin PASSED the cohort "
                "coverage detector — the fanout gate is not actually "
                "firing",
            ))
    except Exception as exc:
        findings.append(Finding(
            "fanout-cohort-coverage", "detector",
            f"cohort coverage detector crashed: {type(exc).__name__}: "
            f"{exc}",
        ))
    return findings


from ..analysis.registry import register_fanout_surface as _reg  # noqa: E402

for _name in (
    "FanoutPlane", "ClientReplica", "fanout_covers_cohorts",
    "static_checks",
):
    _reg(_name, module=__name__)

__all__ = [
    "ClientReplica", "CohortPush", "CohortResync", "FanoutPlane",
    "PushReport", "fanout_covers_cohorts", "static_checks",
]
