"""The δ-subscription fan-out plane (ISSUE 16 tentpole).

:class:`FanoutPlane` is the serving tier's egress twin of the ingest
queue: clients register ``(tenant, acked watermark)`` interests and
every push cycle ships each subscriber the join-irreducible δ between
its acked watermark and the served tenant row — Almeida et al.'s
thin-client δ sync (PAPERS.md, arXiv 1410.2803 / 1603.01529) run
against the PR 15 superblock.

**Watermarks are versions of the sender's own shipped copy** — the
``delta_opt/ackwin.py`` discipline host-side. Per tenant the plane
keeps an integer version counter (0 = ⊥) and, per pushed version, the
bit-exact host snapshot of the row it shipped against. A subscriber's
acked watermark names one of those snapshots; promotion happens ONLY
on a positive ack (:meth:`FanoutPlane.ack` — acks are knowledge of
delivered content, never inference), so the encoder's base and the
client's decode base are bit-identical by construction, which is what
makes the biased-u16 wire delta-encoding exact end to end.

**Cohorts**: subscribers sharing ``(tenant, acked version)`` form one
cohort — ONE decomposition and ONE wire payload serve them all. A push
cycle buckets every lagging-or-dirty subscriber, packs cohorts into
``mesh_fanout_push`` dispatches (lane blocks per mesh rank, riding the
superblock's tenant→lane indirection — evicted tenants re-warm through
the evictor first, so the subscription registry survives
eviction/restore by keying on TENANT ids, never lanes), and marks the
shipped version pending per subscriber. Un-acked subscribers simply
re-enter the next cycle's cohorts (the retry loop is the bucketing).

**Slow/dead subscribers degrade, never buffer**: versions older than
``window_cap`` pushes are pruned; a subscriber acked below the window
(or at a pruned snapshot) falls back to the PR 10/11 snapshot+suffix
path — :func:`crdt_tpu.scaleout.bootstrap.bootstrap` against whatever
acked base survives — counted by the ``resync_fallbacks`` telemetry
counter and the ``subscriber_resync`` flight-recorder event. The
``fanout.ack.*`` crashpoints bracket the promote and resync
boundaries; ack promotion is idempotent, so a crash at any point
re-acks to the same watermark (tests/test_fanout.py fuzzes this amid
tenant eviction/restore cycles).

:func:`fanout_covers_cohorts` is the ``fanout`` static-check section's
broken-twin gate: a pusher that skips a watermark bucket (the
``analysis.fixtures.fanout_skips_watermark_bucket`` twin flips the
``_skip_versions`` seam) starves that cohort forever and MUST fail it.

Two of the prose invariants above are declared happens-before
contracts in ``analysis.concur.HB_CONTRACTS``:
``pin_precedes_gather_dispatch`` (a push chunk pins its whole tenant
set via ``_ensure_resident(_exclude=pinned)`` before warming, and
``_snapshot``/``_dispatch`` refuse a lane that lost residency
mid-cycle — the PR 16 lane-eviction race, rebuilt as the explorable
``analysis.fixtures.racy_fanout_world`` twin) and
``ack_clamped_to_window`` (promotion clamps to [watermark, shipped];
``analysis.fixtures.regressing_ack_promoter_cls`` must fail the
probe). ``analysis.interleave.fanout_world`` replays one push cycle
against client acks and a concurrent eviction under every
≤2-preemption schedule (the ``concurrency`` static-check section).
"""

from __future__ import annotations

from typing import Any, Dict, List, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import telemetry as tele
from ..analysis.interleave import boundary
from ..durability import crashpoints
from ..obs import recorder as _rec
from ..obs import trace as obs_trace
from ..ops import superblock as sb_ops
from ..ops.fanout_kernels import CohortWire, wire_lane
from ..parallel.fanout_push import mesh_fanout_push

CP_ACK_PRE = crashpoints.register(
    "fanout.ack.pre_promote",
    "about to promote acked watermarks (nothing promoted yet — a kill "
    "here leaves every subscriber at its previous acked version)",
)
CP_ACK_POST = crashpoints.register(
    "fanout.ack.post_promote",
    "acked watermarks promoted, pending marks not yet cleared (the "
    "mid-ack boundary: re-acking promotes to the SAME version — "
    "promotion is idempotent)",
)
CP_RESYNC_PRE = crashpoints.register(
    "fanout.ack.pre_resync",
    "subscriber fell out of the ack window, snapshot+suffix resync not "
    "yet shipped (a kill here re-resyncs from the same live row)",
)


class CohortPush(NamedTuple):
    """One cohort's shipped δ payload: ``wire`` is the lane-sliced
    :class:`~crdt_tpu.ops.fanout_kernels.CohortWire` (batch axis 1)
    every member decodes against its acked base."""

    tenant: int
    base_ver: int     # the cohort's acked watermark version
    to_ver: int       # the version this payload lands the client at
    wire: CohortWire  # host-sliced, leading batch axis 1
    members: np.ndarray  # subscriber ids


class CohortResync(NamedTuple):
    """One cohort's snapshot+suffix fallback (the bootstrap path)."""

    tenant: int
    to_ver: int
    state: Any        # bit-identical to the served row (bootstrap law)
    report: Any       # scaleout.bootstrap.BootstrapReport
    members: np.ndarray


class PushReport(NamedTuple):
    """One push cycle's accounting."""

    pushes: List[CohortPush]
    resyncs: List[CohortResync]
    cohorts: int          # δ cohorts dispatched
    subscribers: int      # subscriber deliveries (δ + resync)
    telemetry: Optional[tele.Telemetry]


class FanoutPlane:
    """The subscription registry + push driver over one superblock
    (module docstring). ``dispatch_lanes`` must divide the mesh's
    replica axis; ``window_cap`` bounds how many un-acked versions a
    subscriber may lag before degrading to resync."""

    def __init__(
        self,
        superblock,
        *,
        evictor=None,
        window_cap: int = 4,
        dispatch_lanes: Optional[int] = None,
        capacity: int = 1024,
    ):
        self.sb = superblock
        self.ev = evictor
        self.kind = superblock.kind
        self.mesh = superblock.mesh
        self.p = superblock.p
        self.window_cap = int(window_cap)
        dl = int(dispatch_lanes) if dispatch_lanes else self.p * 256
        if dl % self.p:
            raise ValueError(
                f"{dl} dispatch lanes do not divide the {self.p}-way "
                f"replica mesh axis"
            )
        self.dispatch_lanes = dl
        # Per-tenant shipped-version counter (0 = ⊥) and the shipped
        # base snapshots: tenant -> {version: (host row, caps)}. Keyed
        # by TENANT id, never lane — eviction/re-warm is invisible.
        self.ver = np.zeros(superblock.n_tenants, np.int64)
        self._bases: Dict[int, Dict[int, tuple]] = {}
        # Plane-owned dirt (the ingest driver calls note_dirty after
        # applies): the superblock's dirty flag means
        # touched-since-persist, which the EVICTOR owns.
        self.dirt = np.zeros(superblock.n_tenants, bool)
        cap = max(int(capacity), 1)
        self.sub_tenant = np.full(cap, -1, np.int64)
        self.sub_ver = np.zeros(cap, np.int64)   # acked watermark
        self.sub_pend = np.full(cap, -1, np.int64)  # shipped, un-acked
        self._top = 0
        self._free_ids: List[int] = []
        self.resyncs_total = 0
        self._empty: Optional[tuple] = None  # (caps, host empty row)

    # ---- subscription registry -----------------------------------------
    @property
    def n_live(self) -> int:
        return int(np.count_nonzero(self.sub_tenant[: self._top] >= 0))

    def _grow(self, need: int) -> None:
        cap = len(self.sub_tenant)
        if need <= cap:
            return
        while cap < need:
            cap *= 2
        for name in ("sub_tenant", "sub_ver", "sub_pend"):
            old = getattr(self, name)
            fill = 0 if name == "sub_ver" else -1
            new = np.full(cap, fill, np.int64)
            new[: len(old)] = old
            setattr(self, name, new)

    def subscribe(self, tenants) -> np.ndarray:
        """Register subscribers (one per entry of ``tenants``) at the
        ⊥ watermark — their first push ships the full content as δ, or
        bootstraps when the tenant's window has moved past ⊥. Returns
        the subscriber ids."""
        tenants = np.atleast_1d(np.asarray(tenants, np.int64))
        n = len(tenants)
        ids = np.empty(n, np.int64)
        take = min(len(self._free_ids), n)
        for i in range(take):
            ids[i] = self._free_ids.pop()
        fresh = n - take
        if fresh:
            self._grow(self._top + fresh)
            ids[take:] = np.arange(self._top, self._top + fresh)
            self._top += fresh
        self.sub_tenant[ids] = tenants
        self.sub_ver[ids] = 0
        self.sub_pend[ids] = -1
        return ids

    def unsubscribe(self, ids) -> None:
        ids = np.atleast_1d(np.asarray(ids, np.int64))
        self.sub_tenant[ids] = -1
        self.sub_pend[ids] = -1
        self._free_ids.extend(int(i) for i in ids)

    def ack(self, ids, versions=None) -> None:
        """Positive confirmation: promote each subscriber's acked
        watermark (promote-on-ack, the ackwin discipline). ``versions``
        is the version the CLIENT says it applied
        (``ClientReplica.ver`` after its own ``ack()``) — pass it
        whenever deliveries can be lost, so a client that missed the
        latest ship promotes the server only to what it actually
        holds; ``None`` trusts the last shipped version (in-order
        synchronous transport). Promotion is monotonic: a reordered
        stale ack is clamped to the current watermark and a claim
        above the last shipped version is clamped down to it.
        Idempotent across the ``fanout.ack.*``
        crashpoints: a kill between promote and clear re-acks to the
        SAME version, and an un-promoted kill leaves the pending mark
        for the re-ack."""
        boundary("ack.promote")
        ids = np.atleast_1d(np.asarray(ids, np.int64))
        crashpoints.hit(CP_ACK_PRE)
        pend = self.sub_pend[ids]
        if versions is None:
            v = pend
        else:
            v = np.broadcast_to(
                np.asarray(versions, np.int64), ids.shape
            )
        ok = (pend >= 0) & (self.sub_tenant[ids] >= 0) & (v >= 0)
        sel = ids[ok]
        # Lossy transports reorder and duplicate acks: clamp each
        # promotion to [current watermark, last shipped version]. A
        # stale ack arriving after a newer one must never regress
        # sub_ver below the base the client actually decodes with (the
        # next push would encode against an older snapshot and the
        # client would silently reconstruct wrong state), and a claim
        # above pend names a version this plane never shipped. max
        # keeps the promote idempotent across the fanout.ack.*
        # crashpoints.
        self.sub_ver[sel] = np.maximum(
            self.sub_ver[sel], np.minimum(v[ok], pend[ok])
        )
        crashpoints.hit(CP_ACK_POST)
        # An ack BELOW the pending ship confirms an older payload only:
        # the latest ship is still outstanding, so keep its pend mark —
        # clearing it would gate out the real ack when a duplicate of
        # an old one sneaks in first (lag-driven re-bucketing still
        # covers the subscriber either way).
        self.sub_pend[sel[v[ok] >= pend[ok]]] = -1
        # Close the freshness loop: each promoted tenant's highest
        # acked watermark completes every open trace pushed at or
        # below it (the submit→client-ack headline metric).
        if obs_trace.get_tracer() is not None and len(sel):
            t_sel = self.sub_tenant[sel]
            v_sel = self.sub_ver[sel]
            for t in np.unique(t_sel):
                obs_trace.stamp(
                    "ack", tenant=int(t),
                    version=int(v_sel[t_sel == t].max()),
                )

    def note_dirty(self, tenants) -> None:
        """Mark tenants changed since their last push (the ingest
        driver's hook — mirrors ``Evictor.note_touch``)."""
        self.dirt[np.atleast_1d(np.asarray(tenants, np.int64))] = True

    # ---- base snapshots --------------------------------------------------
    def _empty_host(self):
        """The host ⊥ row at the superblock's CURRENT caps, cached —
        every ⊥-watermark cohort uses it as its base, so building it
        per cohort would cost a device transfer each."""
        caps = dict(self.sb.caps)
        if self._empty is None or self._empty[0] != caps:
            self._empty = (
                caps, jax.tree.map(np.asarray, self.sb.empty_row())
            )
        return self._empty[1]

    def _base_row(self, tenant: int, version: int):
        """The bit-exact host row shipped as ``version`` of ``tenant``
        (⊥ synthesized for version 0), widened to the superblock's
        CURRENT capacity when an elastic widen landed since the
        snapshot — decompose needs shape-identical operands. None when
        the snapshot was pruned (the caller resyncs)."""
        if version == 0:
            return self._empty_host()
        entry = self._bases.get(int(tenant), {}).get(int(version))
        if entry is None:
            return None
        row, caps = entry
        if caps != self.sb.caps:
            grow = {
                k: v for k, v in self.sb.caps.items()
                if v > caps.get(k, 0)
            }
            row = jax.tree.map(np.asarray, self.sb.tk.widen(row, **grow))
            self._bases[int(tenant)][int(version)] = (row, dict(self.sb.caps))
        return row

    def _snapshot(self, tenants: np.ndarray) -> None:
        """Bump each tenant's version and store the live row as the
        new shipped base — ONE batched device gather for the whole
        cycle, then host slices."""
        if len(tenants) == 0:
            return
        lanes_host = np.asarray(self.sb.lane_of[tenants])
        if np.any(lanes_host < 0):
            lost = tenants[lanes_host < 0]
            raise RuntimeError(
                f"tenants {lost.tolist()} lost residency mid-cycle — a "
                f"-1 lane would gather a wrapped index (another "
                f"tenant's row) as the shipped base snapshot"
            )
        lanes = jnp.asarray(lanes_host, jnp.int32)
        host = jax.tree.map(
            np.asarray, sb_ops.gather_rows(self.sb.state, lanes)
        )
        caps = dict(self.sb.caps)
        for i, t in enumerate(tenants):
            t = int(t)
            self.ver[t] += 1
            row = jax.tree.map(lambda x, i=i: x[i], host)
            vers = self._bases.setdefault(t, {})
            vers[int(self.ver[t])] = (row, caps)
            floor = int(self.ver[t]) - self.window_cap
            for v in [v for v in vers if v < floor]:
                del vers[v]

    def _ensure_resident(self, tenant: int, _exclude=()) -> None:
        """Warm one tenant's lane before the cycle reads it. ``_exclude``
        pins the cycle's whole pushed-tenant set (the ingest slab's
        ``restore(t, _exclude=placed)`` discipline): a lane-pressure
        eviction inside ``restore`` must never free a lane some OTHER
        cohort of this same cycle is about to snapshot or dispatch
        from. A push is also a touch — refreshing recency keeps
        fan-out-restored tenants off the next pressure batch's cold
        list (they would otherwise keep a stale ``last_touch`` and
        thrash restore→evict→restore)."""
        if self.sb.lane_of[tenant] < 0:
            if self.ev is not None:
                self.ev.restore(int(tenant), _exclude=_exclude)
            else:
                self.sb.ensure_resident(int(tenant))
        if self.ev is not None:
            self.ev.note_touch(int(tenant))

    # ---- the push cycle --------------------------------------------------
    def push(
        self,
        tenants=None,
        *,
        telemetry: bool = False,
        _skip_versions=(),
    ) -> PushReport:
        """One fan-out cycle: bucket every lagging-or-dirty subscriber
        into ``(tenant, acked version)`` cohorts, dispatch the δ
        cohorts through ``mesh_fanout_push``, degrade out-of-window
        cohorts to snapshot+suffix resync. ``tenants`` overrides the
        dirty set for this cycle (default: every tenant noted dirty
        since the last push). ``_skip_versions`` is the broken-twin
        seam (``analysis.fixtures.fanout_skips_watermark_bucket``):
        production callers never pass it."""
        top = self._top
        st = self.sub_tenant[:top]
        alive = st >= 0
        safe_t = np.where(alive, st, 0)
        if tenants is None:
            dirty = self.dirt
        else:
            dirty = np.zeros(self.sb.n_tenants, bool)
            dirty[np.atleast_1d(np.asarray(tenants, np.int64))] = True
        lag = alive & (self.sub_ver[:top] < self.ver[safe_t])
        sel = alive & (dirty[safe_t] | lag)
        ids = np.where(sel)[0]
        report = PushReport([], [], 0, 0, None)
        if len(ids) == 0:
            tel = self.annotate(tele.zeros()) if telemetry else None
            return report._replace(telemetry=tel)

        # Residency + version bump for the dirty tenants being pushed
        # (lag-only tenants keep their version: their stored newest
        # base IS the live row — note_dirty is the change contract).
        t_s = st[ids]
        v_s = self.sub_ver[:top][ids]
        pushed_tenants = np.unique(t_s)

        # Residency is the cycle's working-set bound: a chunk's tenants
        # must hold their lanes from the batched snapshot gather through
        # the lane-indexed dispatch, so a push over MORE tenants than
        # the lane pool proceeds in pool-sized chunks. Each chunk pins
        # ONLY its own tenants against the restores' lane-pressure
        # evictions (the ingest slab's ``restore(t, _exclude=placed)``
        # discipline — without the pin a mid-cycle eviction hands an
        # already-warmed cohort's lane to another tenant and its row
        # ships as the wrong δ base); a later chunk is free to page an
        # earlier chunk's lanes out, because that chunk already shipped.
        pushes: List[CohortPush] = []
        resyncs: List[CohortResync] = []
        tel = None
        n_cohorts = 0
        n_subs = 0
        n_resync_subs = 0
        resync_bytes = 0.0
        chunk_cap = max(self.sb.n_lanes, 1)
        for lo in range(0, len(pushed_tenants), chunk_cap):
            chunk = pushed_tenants[lo:lo + chunk_cap]
            pinned = set(map(int, chunk))
            for t in chunk:
                self._ensure_resident(int(t), _exclude=pinned)
            boundary("push.warm")
            bumped = chunk[dirty[chunk]]
            self._snapshot(bumped)
            boundary("push.snapshot")
            self.dirt[bumped] = False

            # Cohorts: subscribers sharing (tenant, acked version).
            in_chunk = np.isin(t_s, chunk)
            c_ids, c_t, c_v = ids[in_chunk], t_s[in_chunk], v_s[in_chunk]
            code = c_t * (int(self.ver.max()) + 2) + c_v
            order = np.argsort(code, kind="stable")
            c_ids, c_t, c_v = c_ids[order], c_t[order], c_v[order]
            _, starts, counts = np.unique(
                code[order], return_index=True, return_counts=True
            )

            wire_cohorts: List[tuple] = []
            for s, c in zip(starts, counts):
                t, v = int(c_t[s]), int(c_v[s])
                members = c_ids[s:s + c]
                target = int(self.ver[t])
                if v == target:
                    continue  # already current (dirty push raced an ack)
                if v in _skip_versions:
                    continue  # the broken-twin seam — never taken honestly
                base = self._base_row(t, v)
                if (target - v > self.window_cap) or base is None:
                    crashpoints.hit(CP_RESYNC_PRE)
                    from ..scaleout.bootstrap import bootstrap

                    state, rep = bootstrap(
                        self.kind, self.sb.row(t), base=base
                    )
                    resyncs.append(CohortResync(
                        tenant=t, to_ver=target,
                        state=jax.tree.map(np.asarray, state), report=rep,
                        members=members,
                    ))
                    self.sub_pend[members] = target
                    n_resync_subs += len(members)
                    resync_bytes += rep.bytes_shipped * len(members)
                    obs_trace.stamp("push", tenant=t, version=target)
                    _rec.emit(
                        "subscriber_resync", tenant=t,
                        subscribers=len(members),
                    )
                else:
                    wire_cohorts.append((t, v, target, members, base))

            chunk_pushes, chunk_tel = self._dispatch(wire_cohorts, telemetry)
            pushes.extend(chunk_pushes)
            n_cohorts += len(wire_cohorts)
            n_subs += int(sum(len(m) for *_x, m, _b in wire_cohorts))
            if chunk_tel is not None:
                tel = (
                    chunk_tel if tel is None
                    else tele.combine(tel, chunk_tel)
                )

        self.resyncs_total += n_resync_subs
        if telemetry:
            tel = tele.zeros() if tel is None else tel
            tel = self.annotate(tel._replace(
                resync_fallbacks=(
                    tel.resync_fallbacks + jnp.uint32(n_resync_subs)
                ),
                bootstrap_bytes=(
                    tel.bootstrap_bytes + jnp.float32(resync_bytes)
                ),
            ))
        return PushReport(
            pushes=pushes, resyncs=resyncs, cohorts=n_cohorts,
            subscribers=n_subs + n_resync_subs, telemetry=tel,
        )

    def _dispatch(self, cohorts, telemetry: bool):
        """Pack wire cohorts into ``dispatch_lanes``-wide
        ``mesh_fanout_push`` calls: each cohort lands in the lane block
        of the mesh rank owning its tenant's superblock lane (the
        serve_apply index convention)."""
        boundary("push.dispatch")
        pushes: List[CohortPush] = []
        tel = None
        if not cohorts:
            return pushes, tel
        lpr_disp = self.dispatch_lanes // self.p
        per_rank: List[List[tuple]] = [[] for _ in range(self.p)]
        for co in cohorts:
            lane = int(self.sb.lane_of[co[0]])
            if lane < 0:
                raise RuntimeError(
                    f"tenant {co[0]} lost residency mid-cycle — a -1 "
                    f"lane would dispatch another rank's row as this "
                    f"cohort's delta base"
                )
            per_rank[lane // self.sb.lanes_per_rank].append((lane, co))
        n_disp = max(
            (len(r) + lpr_disp - 1) // lpr_disp for r in per_rank
        )
        empty = self._empty_host()
        for dnum in range(n_disp):
            idx = np.full(self.dispatch_lanes, -1, np.int32)
            wts = np.zeros(self.dispatch_lanes, np.float32)
            rows = [empty] * self.dispatch_lanes
            slots: List[tuple] = []
            for r in range(self.p):
                chunk = per_rank[r][dnum * lpr_disp:(dnum + 1) * lpr_disp]
                for j, (lane, (t, v, target, members, base)) in enumerate(
                    chunk
                ):
                    dl = r * lpr_disp + j
                    idx[dl] = lane % self.sb.lanes_per_rank
                    wts[dl] = len(members)
                    rows[dl] = base
                    slots.append((dl, t, v, target, members))
            bases_dev = jax.tree.map(
                lambda *xs: jnp.asarray(np.stack(xs)), *rows
            )
            out = mesh_fanout_push(
                self.sb.state, bases_dev, jnp.asarray(idx), self.mesh,
                kind=self.kind, weights=jnp.asarray(wts),
                telemetry=telemetry,
            )
            wire = jax.tree.map(np.asarray, out[0])
            if telemetry:
                t3 = out[2]
                tel = t3 if tel is None else tele.combine(tel, t3)
            for dl, t, v, target, members in slots:
                pushes.append(CohortPush(
                    tenant=t, base_ver=v, to_ver=target,
                    wire=wire_lane(wire, dl), members=members,
                ))
                self.sub_pend[members] = target
                obs_trace.stamp("push", tenant=t, version=target)
            _rec.emit(
                "fanout_push", cohorts=len(slots),
                subscribers=int(wts.sum()),
            )
        return pushes, tel

    # ---- telemetry -------------------------------------------------------
    def annotate(self, tel: tele.Telemetry) -> tele.Telemetry:
        """Fill the host-owned fan-out gauge (the serve ``annotate``
        discipline): ``subscribers_live`` = the registered population
        the plane answers for."""
        if not tele.is_concrete(tel):
            return tel
        return tel._replace(subscribers_live=jnp.uint32(self.n_live))


def fanout_covers_cohorts(push_fn) -> bool:
    """Detector behind the ``fanout`` static-check section: drive
    ``push_fn(plane)`` over a two-subscriber workload whose acks split
    the subscribers into DIFFERENT watermark buckets, deliver every
    payload, and return True iff both client replicas land
    bit-identical to the served tenant. The honest
    ``FanoutPlane.push`` passes; the committed bucket-skipping twin
    (``analysis.fixtures.fanout_skips_watermark_bucket``) starves the
    stale-watermark cohort and must FAIL here, proving the gate
    catches cohort-selection bugs."""
    from ..parallel import make_mesh
    from ..serve.superblock import Superblock
    from .client import ClientReplica

    mesh = make_mesh(1, 1)
    caps = dict(n_elems=4, n_actors=2, deferred_cap=2)
    sb = Superblock(2, mesh, kind="orswot", caps=caps)
    plane = FanoutPlane(sb, window_cap=8, dispatch_lanes=2)
    ids = plane.subscribe([0, 0])
    clients = {int(i): ClientReplica("orswot", sb.empty_row()) for i in ids}
    m = lambda *on: np.isin(np.arange(4), on)  # noqa: E731

    def touch(adds):
        lane = sb.ensure_resident(0)
        row = sb_ops.unpack(sb.state, lane)
        for actor, c, mask in adds:
            row, _ = sb.tk.apply_add(
                row, jnp.int32(actor), jnp.uint32(c), jnp.asarray(mask)
            )
        sb.state = sb_ops.write_rows(
            sb.state, jnp.asarray([lane], jnp.int32),
            jax.tree.map(lambda x: x[None], row),
        )
        plane.note_dirty([0])

    def deliver(rep):
        for cp in rep.pushes:
            for s in cp.members:
                clients[int(s)].apply_wire(cp.wire, cp.to_ver)
        for rs in rep.resyncs:
            for s in rs.members:
                clients[int(s)].adopt(rs.state, rs.to_ver)

    touch([(0, 1, m(0, 1))])
    deliver(push_fn(plane))
    clients[int(ids[0])].ack()
    plane.ack([ids[0]])  # only subscriber 0 promotes — watermarks split
    touch([(1, 1, m(2)), (0, 2, m(3))])
    deliver(push_fn(plane))  # cohorts (t0, v1) AND (t0, v0)
    for i in ids:
        clients[int(i)].ack()
    plane.ack(ids)
    want = sb.row(0)
    return all(clients[int(i)].equals(want) for i in ids)


# ---- observability registration (crdt_tpu.analysis) -----------------------

from ..analysis.registry import register_obs_event as _reg_ev  # noqa: E402

_reg_ev(
    "fanout_push", subsystem="fanout",
    fields=("cohorts", "subscribers"), module=__name__,
)
_reg_ev(
    "subscriber_resync", subsystem="fanout",
    fields=("tenant", "subscribers"), module=__name__,
)

from ..analysis.registry import register_shared_field as _reg_sf  # noqa: E402

_reg_sf("ver", owner="FanoutPlane", module=__name__,
        kind="per-tenant shipped-version counters")
_reg_sf("dirt", owner="FanoutPlane", module=__name__,
        kind="per-tenant dirty-since-push flags")
_reg_sf("_bases", owner="FanoutPlane", module=__name__,
        kind="retained δ bases keyed (tenant, version)")
_reg_sf("sub_tenant", owner="FanoutPlane", module=__name__,
        kind="subscriber→tenant interest table")
_reg_sf("sub_ver", owner="FanoutPlane", module=__name__,
        kind="per-subscriber acked watermark")
_reg_sf("sub_pend", owner="FanoutPlane", module=__name__,
        kind="per-subscriber shipped-pending version")
_reg_sf("_top", owner="FanoutPlane", module=__name__,
        kind="high-water subscriber id")
_reg_sf("_free_ids", owner="FanoutPlane", module=__name__,
        kind="recycled subscriber-id pool")
_reg_sf("resyncs_total", owner="FanoutPlane", module=__name__,
        kind="lifetime forced-resync counter")
_reg_sf("_empty", owner="FanoutPlane", module=__name__,
        kind="cached host empty-row template")

__all__ = [
    "CohortPush", "CohortResync", "FanoutPlane", "PushReport",
    "fanout_covers_cohorts",
]
