"""The thin-client replica — the receive half of the fan-out plane.

A :class:`ClientReplica` is what one of the million subscribers runs:
its CRDT row plus the two-version discipline that makes the wire
decode exact. ``base`` is the state at the client's ACKED watermark —
bit-identical to the snapshot the plane stored when it shipped that
version, which is the promote-on-ack invariant
(crdt_tpu/fanout/plane.py) — and ``state`` is the latest APPLIED
payload, possibly ahead of ``base`` while the ack is in flight. Every
δ payload decodes against ``base`` (the encoder's base for this
cohort, by construction), so applying is idempotent and re-shipped
payloads after a lost ack land on the same decode base instead of a
drifted one. :meth:`ack` promotes ``base`` to the applied state — call
it exactly when the ack is handed to the plane.

Plain lax on the receive path (``wire_unpack`` convention: decode
fuses with reconstruct; the fused kernel earns its keep on the send
side). Host-friendly: leaves may be numpy throughout.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..delta_opt.decompose import Decomposition, reconstruct
from ..ops.fanout_kernels import CohortWire, cohort_wire_decode


class ClientReplica:
    """One subscriber's replica: ``state`` (latest applied), ``base``
    (acked watermark state), ``ver`` (acked version) and ``pend`` (the
    last applied-but-unacked version). Start it from the tenant kind's
    empty row — version 0 is ⊥ everywhere in the plane."""

    def __init__(self, kind: str, empty_row):
        self.kind = kind
        self.base = empty_row
        self.state = empty_row
        self.ver = 0
        self.pend = 0

    def _split_base(self):
        from ..analysis.registry import get_decomposer

        rows = jax.tree.map(lambda x: jnp.asarray(x)[None], self.base)
        return get_decomposer(self.kind).split(rows)

    def apply_wire(self, wire: CohortWire, to_ver: int) -> None:
        """Apply one cohort payload (leading batch axis 1 — the
        ``wire_lane`` slice the plane hands out). Decodes against the
        acked ``base``, never the possibly-ahead ``state``, so a
        re-shipped payload after a lost ack is harmless."""
        lanes, res = self._split_base()
        base_ctr = jax.tree.leaves(lanes)[0]
        d = cohort_wire_decode(wire, base_ctr, res)
        d1 = Decomposition(
            lanes=jax.tree.map(lambda x: x[0], d.lanes),
            valid=d.valid[0],
            residual=jax.tree.map(lambda x: x[0], d.residual),
        )
        self.state = reconstruct(self.kind, self.base, d1)
        self.pend = int(to_ver)

    def adopt(self, state, to_ver: int) -> None:
        """The snapshot+suffix resync landing (bootstrap path): adopt
        the shipped state wholesale — it is bit-identical to the served
        row by the bootstrap contract."""
        self.state = state
        self.pend = int(to_ver)

    def ack(self) -> None:
        """Promote the acked watermark to the applied state — call
        exactly when the ack is handed to ``FanoutPlane.ack`` (the two
        promotions are the one protocol step, split across the wire)."""
        self.base = self.state
        self.ver = self.pend

    def equals(self, row) -> bool:
        """Bit-exact leaf-wise comparison against a served row (the
        fan-out property: a subscriber replaying its δ stream from the
        acked watermark IS the served tenant)."""
        mine = jax.tree.leaves(self.state)
        theirs = jax.tree.leaves(row)
        return len(mine) == len(theirs) and all(
            np.array_equal(np.asarray(a), np.asarray(b))
            for a, b in zip(mine, theirs)
        )


__all__ = ["ClientReplica"]
