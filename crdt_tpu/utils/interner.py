"""Interner — dense integer ids for actors and members.

The reference is generic over ``A: Ord`` (SURVEY.md §3.2 "actor
genericity"); the device sees only dense int lanes, so the host keeps the
bidirectional actor/member ↔ id table. Ids are allocated in first-intern
order and never reused.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List


class Interner:
    __slots__ = ("_ids", "_items")

    def __init__(self, items: Iterable[Any] = ()):
        self._ids: Dict[Any, int] = {}
        self._items: List[Any] = []
        for item in items:
            self.intern(item)

    def intern(self, item: Any) -> int:
        """Id for ``item``, allocating one on first sight."""
        ix = self._ids.get(item)
        if ix is None:
            ix = len(self._items)
            self._ids[item] = ix
            self._items.append(item)
        return ix

    def id_of(self, item: Any) -> int:
        """Id for ``item``; KeyError if never interned."""
        return self._ids[item]

    def __getitem__(self, ix: int) -> Any:
        return self._items[ix]

    def __contains__(self, item: Any) -> bool:
        return item in self._ids

    def __len__(self) -> int:
        return len(self._items)

    def items(self) -> List[Any]:
        return list(self._items)

    def clone(self) -> "Interner":
        return Interner(self._items)
