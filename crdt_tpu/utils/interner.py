"""Interner — dense integer ids for actors and members.

The reference is generic over ``A: Ord`` (SURVEY.md §3.2 "actor
genericity"); the device sees only dense int lanes, so the host keeps the
bidirectional actor/member ↔ id table. Ids are allocated in first-intern
order and never reused.
"""

from __future__ import annotations

import contextlib

import numpy as np
from typing import Any, Dict, Iterable, Iterator, List


class UniverseFull(IndexError):
    """A bounded intern would land outside the device array's lanes.
    Subclasses IndexError (the historical signal) so existing handlers
    keep working; elastic.py catches THIS type, so unrelated
    IndexErrors are never mistaken for capacity pressure."""


class Interner:
    __slots__ = ("_ids", "_items")

    def __init__(self, items: Iterable[Any] = ()):
        self._ids: Dict[Any, int] = {}
        self._items: List[Any] = []
        for item in items:
            self.intern(item)

    def intern(self, item: Any) -> int:
        """Id for ``item``, allocating one on first sight."""
        ix = self._ids.get(item)
        if ix is None:
            ix = len(self._items)
            self._ids[item] = ix
            self._items.append(item)
        return ix

    def id_of(self, item: Any) -> int:
        """Id for ``item``; KeyError if never interned."""
        return self._ids[item]

    def bounded_intern(self, item: Any, cap: int, what: str = "item") -> int:
        """Id for ``item``, allocating into a ``cap``-lane universe.
        UniverseFull (an IndexError, not a silent out-of-bounds scatter)
        when the id would land outside the device array's lanes."""
        ix = self._ids.get(item)
        if ix is None:
            if len(self._items) >= cap:
                raise UniverseFull(
                    f"{what} {item!r}: the {cap}-lane universe is full; "
                    f"rebuild with more lanes"
                )
            return self.intern(item)
        if ix >= cap:
            raise UniverseFull(
                f"{what} {item!r} (id {ix}) outside the {cap}-lane "
                f"universe; rebuild with more lanes"
            )
        return ix

    def truncate(self, n: int) -> None:
        """Roll back to the first ``n`` ids. ONLY for transactional op
        application: a rejected op must be side-effect free (the
        validation.py contract), so names it interned before the
        rejection are un-allocated again. Never valid once any state
        references the dropped lanes."""
        for item in self._items[n:]:
            del self._ids[item]
        del self._items[n:]

    def __getitem__(self, ix: int) -> Any:
        return self._items[ix]

    def __contains__(self, item: Any) -> bool:
        return item in self._ids

    def __len__(self) -> int:
        return len(self._items)

    def items(self) -> List[Any]:
        return list(self._items)

    def clone(self) -> "Interner":
        return Interner(self._items)


@contextlib.contextmanager
def transactional(*interners: Interner) -> Iterator[None]:
    """Roll back any names the body interned if it raises — the
    rejected-op contract (models/validation.py: 'a rejected op must be
    side-effect free'). Wrap every model ``apply`` body that interns
    names before a kernel/validation step can still reject the op."""
    marks = [len(i) for i in interners]
    try:
        yield
    except Exception:
        for i, n in zip(interners, marks):
            i.truncate(n)
        raise


def transactional_apply(*interner_attrs: str):
    """Decorator form of ``transactional`` for model op methods: names
    the instance's interner attributes to roll back when the op is
    rejected (``@transactional_apply("keys", "actors", "values")``)."""
    import functools

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(self, *args, **kwargs):
            with transactional(*(getattr(self, a) for a in interner_attrs)):
                return fn(self, *args, **kwargs)
        return wrapper
    return deco


def clock_lanes(clock, actors: Interner, n_actors: int, what: str = "actor",
                dtype=np.uint32):
    """``VClock`` → the dense per-actor lane array the device encodes
    clocks as ([n_actors], default uint32 — pass the model's counter
    dtype where config widens it to uint64), interning unseen actors
    within the ``n_actors`` bound. The one place the dict→lane
    conversion lives — every model op/reset path that ships a clock to
    the device uses it."""
    lanes = np.zeros((n_actors,), dtype)
    for actor, c in clock.dots.items():
        lanes[actors.bounded_intern(actor, n_actors, what)] = c
    return lanes


def pad_id_list(items, width=None):
    """Sorted id list padded with -1 to a fixed lane width (the parked
    keylist encoding of the sparse backends). ``width=None`` picks a
    power-of-two bucket >= 8 to bound jit retraces; an explicit width is
    the buffer lane size and overflow raises."""
    ids = sorted(items)
    if width is None:
        width = 8
        while width < len(ids):
            width *= 2
    if len(ids) > width:
        raise ValueError(
            f"op lists {len(ids)} targets; the buffer lane is {width} "
            f"— rebuild with a larger rm_width or split the op"
        )
    out = np.full(width, -1, np.int32)
    out[: len(ids)] = ids
    return out
