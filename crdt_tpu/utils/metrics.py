"""Metrics / tracing / observability (SURVEY.md §6.1, §6.5).

The reference has none of this; the BASELINE metrics (merges/sec,
deferred-buffer depth, bytes exchanged per anti-entropy round) need a
home, so the framework keeps one process-global registry:

- ``metrics.count(name, n)``          — monotonic counters,
- ``metrics.observe(name, value)``    — last/min/max/sum/n gauges,
- ``metrics.time(name)``              — wall-clock context manager,
- ``metrics.snapshot()`` / ``reset()``.

Elastic capacity pressure (fed by crdt_tpu/elastic.py; visible in the
bench metrics snapshot): ``elastic.widen_events`` (+ per-kind
``elastic.widen_events.<kind>``) and ``elastic.migrated_bytes``
counters for every overflow→widen→resume migration, and
``elastic.<kind>.headroom.<axis>`` free-fraction gauges (0.0 = at
capacity — the operator signal to widen BEFORE overflow) refreshed by
``elastic.record_headroom``.

``profile_trace(logdir)`` wraps ``jax.profiler.trace`` for device-level
timelines (viewable in TensorBoard/XProf; SURVEY.md §6.1) and degrades
to a no-op where the profiler is unavailable.

Device code never touches this module (host-side only, zero jit
impact); the models and the mesh anti-entropy entry points feed it.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, Dict


class Metrics:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        self._gauges: Dict[str, Dict[str, float]] = {}

    def count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            g = self._gauges.setdefault(
                name, {"last": 0.0, "min": float("inf"), "max": float("-inf"),
                       "sum": 0.0, "n": 0},
            )
            g["last"] = value
            g["min"] = min(g["min"], value)
            g["max"] = max(g["max"], value)
            g["sum"] += value
            g["n"] += 1

    @contextlib.contextmanager
    def time(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(f"{name}_seconds", time.perf_counter() - t0)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": {k: dict(v) for k, v in self._gauges.items()},
            }

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()


metrics = Metrics()


_profile_trace_logged = False


@contextlib.contextmanager
def profile_trace(logdir: str):
    """Device-level profiling around a block (perfetto/XProf trace in
    ``logdir``); no-op if the profiler cannot start (e.g. no device) —
    but a DIAGNOSABLE no-op: each failed start records
    ``profile_trace.start_failed`` and the first one logs the reason,
    so a missing XProf trace points at its cause instead of silence."""
    import jax

    started = False
    try:
        jax.profiler.start_trace(logdir)
        started = True
    except Exception as exc:
        global _profile_trace_logged
        metrics.count("profile_trace.start_failed")
        if not _profile_trace_logged:
            _profile_trace_logged = True
            import logging

            logging.getLogger(__name__).warning(
                "jax.profiler.start_trace(%r) failed (%r); proceeding "
                "without a device trace (logged once; subsequent "
                "failures only count profile_trace.start_failed)",
                logdir, exc,
            )
    try:
        yield
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass


def deferred_depth(state) -> float:
    """Live deferred-buffer depth of a (possibly batched) state pytree:
    the MAX over replicas of valid parked slots summed across every
    buffer level (fields named ``*dvalid`` — the shared masked-epoch
    convention across the dense, sparse, and nested families). The
    BASELINE §6.5 'deferred-buffer depth' gauge; callers observe it at
    join/fold time. Returns -1.0 (and records nothing via
    ``observe_depth``) when the state is a traced value — the mesh entry
    points may legitimately run under an outer jit (e.g. a fully jitted
    train step), where host-side metrics cannot see concrete values.
    Each such skip counts ``anti_entropy.depth_skipped_traced`` so
    operators SEE the blindness (and know to ask the entry point for
    the in-jit ``telemetry=`` sidecar — crdt_tpu/telemetry.py) instead
    of inferring it from absent gauges."""
    import jax
    import numpy as np

    total = None
    def opaque(x):
        # Traced values have no concrete data; multi-host global arrays
        # span non-addressable devices — either way, nothing to record.
        return isinstance(x, jax.core.Tracer) or (
            isinstance(x, jax.Array) and not x.is_fully_addressable
        )

    if any(opaque(x) for x in jax.tree.leaves(state)):
        metrics.count("anti_entropy.depth_skipped_traced")
        return -1.0

    def walk(node):
        nonlocal total
        if hasattr(node, "_fields"):
            for name in node._fields:
                child = getattr(node, name)
                if name.endswith("dvalid"):
                    # Sum slot axis (last); accumulate per leading batch.
                    d = np.asarray(child).astype(np.int64)
                    d = d.sum(axis=-1)
                    total = d if total is None else total + d
                elif hasattr(child, "_fields"):
                    walk(child)
    walk(state)
    if total is None:
        return 0.0
    return float(np.max(total))


def observe_depth(name: str, state) -> None:
    """Record ``deferred_depth(state)`` under ``<name>.deferred_depth``
    (a no-op under tracing — see ``deferred_depth``)."""
    depth = deferred_depth(state)
    if depth >= 0:
        metrics.observe(f"{name}.deferred_depth", depth)


def state_nbytes(state) -> int:
    """Total device bytes of a pytree state — the per-round 'bytes
    exchanged' metric for anti-entropy collectives."""
    import jax

    return sum(
        x.size * x.dtype.itemsize for x in jax.tree.leaves(state)
    )


__all__ = [
    "Metrics", "metrics", "profile_trace", "state_nbytes",
    "deferred_depth", "observe_depth",
]
