"""Host-side utilities: interning, serialization, checkpoint, metrics."""

from .interner import Interner, transactional, transactional_apply

__all__ = ["Interner", "transactional", "transactional_apply"]
