"""Host-side utilities: interning, serialization, checkpoint, metrics."""

from .interner import Interner

__all__ = ["Interner"]
