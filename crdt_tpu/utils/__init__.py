"""Host-side utilities: interning, serialization, checkpoint, metrics."""

from .interner import Interner, clock_lanes, transactional, transactional_apply

__all__ = ["Interner", "clock_lanes", "transactional", "transactional_apply"]
