"""Host-side utilities: interning, serialization, checkpoint, metrics."""

from .interner import (
    Interner,
    UniverseFull,
    clock_lanes,
    pad_id_list,
    transactional,
    transactional_apply,
)

__all__ = [
    "Interner", "UniverseFull", "clock_lanes", "pad_id_list",
    "transactional", "transactional_apply",
]
