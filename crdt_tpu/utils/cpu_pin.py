"""Pin JAX onto the host CPU backend, robustly, for tests and tools.

This development image's sitecustomize registers an experimental TPU
tunnel backend ("axon") whose mere enumeration can hang when the tunnel
is down, and it imports jax at interpreter startup — so plain env-var
overrides are sometimes too late. The one reliable recipe (used by the
test suite, the multihost worker processes, and the runnable examples)
lives here: set the platform through ``jax.config`` AND drop the axon
backend factory before first backend initialization.

Must be called before anything queries devices (``jax.devices()``,
first jit execution); importing jax or crdt_tpu beforehand is fine —
backend initialization is lazy.
"""

from __future__ import annotations

import os
import re


def pin_cpu(virtual_devices: int | None = None) -> None:
    """Force the CPU backend, optionally with N virtual devices.

    ``virtual_devices`` sets ``--xla_force_host_platform_device_count``
    in XLA_FLAGS, REPLACING any count inherited from the environment or a
    parent process (multihost worker processes want their own per-process
    count, and the test suite pins exactly 8 — run with
    ``virtual_devices=None`` to keep a caller-supplied XLA_FLAGS count).
    """
    if virtual_devices:
        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+",
            "",
            os.environ.get("XLA_FLAGS", ""),
        ).strip()
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={virtual_devices}"
        ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"  # for any subprocesses

    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        from jax._src import xla_bridge

        xla_bridge._backend_factories.pop("axon", None)
    except Exception:
        # Private API — if it moves, the jax.config pin alone still
        # selects CPU; only the hung-tunnel enumeration hazard returns.
        pass
