"""Config / flag system (SURVEY.md §6.6).

The reference's only configuration is Cargo feature flags (an
``arbitrary``/``quickcheck`` test feature; the north star imagines a
``backend = "xla"`` feature). Here that becomes a plain dataclass with a
process-global instance: ``backend`` selects the execution path the
``replicaset`` factory hands out (the feature-flag analog, and what the
bit-identical A/B gate toggles), ``strict`` turns on v7-style
``validate_op`` checks before every apply, and the capacity knobs feed
the device models' static slab shapes.

Usage::

    from crdt_tpu.config import config, configure, replicaset

    configure(backend="xla", strict=True)
    replicas = replicaset("orswot", n_replicas=8, n_members=64, n_actors=8)
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Iterator, Optional


@dataclasses.dataclass
class Config:
    # "pure" — sequential oracle semantics (reference behavior);
    # "xla"  — batched device-resident models (jit/vmap/pjit kernels).
    backend: str = "xla"
    # Raise ValidationError from validate_op before every apply (v7
    # validation) — on BOTH backends: the pure types validate per type,
    # the batched models check dot contiguity against the replica's top
    # clock (models/validation.py) at one device->host scalar per apply.
    strict: bool = False
    # Static capacities for the device models' slab shapes.
    deferred_cap: int = 8
    sibling_cap: int = 8
    # Debug mode: jax NaN/inf checks around kernels (SURVEY §6.2).
    debug_numerics: bool = False
    # Device counter width for the clock/counter family (reference:
    # src/vclock.rs is BTreeMap<A, u64>). "uint32" (default) matches the
    # dot-slab lattice and allows 2^32-1 events per actor — the envelope
    # the strict-mode saturation trap guards (models/validation.py);
    # "uint64" restores full reference width for VClock / GCounter /
    # PNCounter (enables jax x64 mode — see ``configure``). The
    # orswot/map dot slabs stay u32 (VMEM/bandwidth: the fused fold's
    # whole advantage rides on 4-byte lanes); long-lived actors there
    # are covered by the trap, not by widening.
    counter_dtype: str = "uint32"

    def validate(self) -> None:
        if self.backend not in ("pure", "xla"):
            raise ValueError(f"backend must be 'pure' or 'xla', got {self.backend!r}")
        if self.deferred_cap < 1 or self.sibling_cap < 1:
            raise ValueError("capacities must be >= 1")
        if self.counter_dtype not in ("uint32", "uint64"):
            raise ValueError(
                f"counter_dtype must be 'uint32' or 'uint64', got {self.counter_dtype!r}"
            )


config = Config()

# jax_debug_nans / jax_enable_x64 values from before *we* enabled them
# (None = we didn't), so turning the feature back off restores the
# user's own setting rather than forcing False.
_debug_nans_prev = None
_x64_prev = None


def configure(**kwargs) -> Config:
    """Update the global config in place (unknown keys rejected)."""
    global _debug_nans_prev, _x64_prev
    for key, value in kwargs.items():
        if not hasattr(config, key):
            raise TypeError(f"unknown config field {key!r}")
        setattr(config, key, value)
    config.validate()
    if config.counter_dtype == "uint64":
        # uint64 arrays silently truncate to uint32 without x64 mode.
        # Enabled globally (jax has no narrower switch); affects default
        # widths of NEW arrays only — the dot slabs pin uint32 explicitly.
        import jax

        if _x64_prev is None:
            _x64_prev = bool(jax.config.jax_enable_x64)
        jax.config.update("jax_enable_x64", True)
    elif _x64_prev is not None:
        import jax

        jax.config.update("jax_enable_x64", _x64_prev)
        _x64_prev = None
    if config.debug_numerics:
        import jax

        if _debug_nans_prev is None:
            _debug_nans_prev = bool(jax.config.jax_debug_nans)
        jax.config.update("jax_debug_nans", True)
    elif _debug_nans_prev is not None:
        import jax

        jax.config.update("jax_debug_nans", _debug_nans_prev)
        _debug_nans_prev = None
    return config


@contextlib.contextmanager
def configured(**kwargs) -> Iterator[Config]:
    """Scoped config override (restores previous values on exit)."""
    saved = dataclasses.replace(config)
    try:
        yield configure(**kwargs)
    finally:
        configure(**dataclasses.asdict(saved))


def replicaset(
    kind: str,
    n_replicas: int,
    *,
    n_members: Optional[int] = None,
    n_actors: Optional[int] = None,
    n_keys: Optional[int] = None,
    n_keys2: Optional[int] = None,
):
    """The backend-selecting factory: N replicas of ``kind`` under the
    configured backend — a list of oracle objects for ``pure``, one
    batched device model for ``xla``. Kinds: orswot, map, map_orswot
    (Map<K, Orswot>), map_map (Map<K1, Map<K2, MVReg>>), map3
    (Map<K1, Map<K2, Orswot>>), gcounter, pncounter, gset, lwwreg,
    mvreg, sparse_orswot, sparse_map_orswot (segment-encoded
    Map<K, Orswot> for huge key universes), sparse_map (segment-encoded
    Map<K, MVReg> — the config-4 flavor at huge key universes),
    sparse_map_map (segment-encoded Map<K1, Map<K2, MVReg>>).

    Lane sizing for the xla backend: ``n_keys`` sizes the (outer) key
    axis, ``n_members`` sizes the inner axis of the nested kinds — the
    member universe for map_orswot, the INNER key universe (K2) for
    map_map — ``n_keys2`` the K2 axis of map3, and ``n_actors`` the
    actor lanes. ``sparse_orswot`` (xla) is the segment-encoded mode
    for huge member universes: ``n_members`` there sizes the LIVE-dot
    capacity, not the universe (which is unbounded). The other sparse
    kinds repurpose lanes the same way: ``sparse_map_orswot`` takes
    ``n_members`` as the per-key span and ``n_keys2`` as live-dot
    capacity; ``sparse_map`` takes ``n_keys`` as the (virtual) key
    universe bound and ``n_keys2`` as live-cell capacity;
    ``sparse_map_map`` takes ``n_members`` as the (virtual) inner-key
    span and ``n_keys2`` as live-cell capacity."""
    config.validate()
    if config.backend == "pure":
        from .pure.gcounter import GCounter
        from .pure.gset import GSet
        from .pure.lwwreg import LWWReg
        from .pure.map import Map
        from .pure.mvreg import MVReg
        from .pure.orswot import Orswot
        from .pure.pncounter import PNCounter

        factories = {
            "orswot": Orswot,
            "map": lambda: Map(val_default=MVReg),
            "map_orswot": lambda: Map(val_default=Orswot),
            "map_map": lambda: Map(val_default=lambda: Map(val_default=MVReg)),
            "map3": lambda: Map(val_default=lambda: Map(val_default=Orswot)),
            "gcounter": GCounter,
            "pncounter": PNCounter,
            "gset": GSet,
            "lwwreg": LWWReg,
            "mvreg": MVReg,
            "sparse_orswot": Orswot,  # same oracle; sparsity is a backend trait
            "sparse_map_orswot": lambda: Map(val_default=Orswot),
            "sparse_map": lambda: Map(val_default=MVReg),
            "sparse_map_map": lambda: Map(val_default=lambda: Map(val_default=MVReg)),
        }
        if kind not in factories:
            raise ValueError(f"unknown replicaset kind {kind!r}")
        return [factories[kind]() for _ in range(n_replicas)]

    from .models import (
        BatchedGCounter,
        BatchedGSet,
        BatchedLWWReg,
        BatchedMap,
        BatchedMap3,
        BatchedMapOrswot,
        BatchedMVReg,
        BatchedNestedMap,
        BatchedOrswot,
        BatchedPNCounter,
        BatchedSparseOrswot,
    )

    if kind == "orswot":
        return BatchedOrswot(
            n_replicas, n_members or 64, n_actors or 16, config.deferred_cap
        )
    if kind == "sparse_orswot":
        return BatchedSparseOrswot(
            n_replicas, n_members or 256, n_actors or 16, config.deferred_cap
        )
    if kind == "sparse_map_orswot":
        from .models import BatchedSparseMapOrswot

        # n_members sizes the per-key span (the member-universe capacity
        # per key — cheap, it is virtual); n_keys2 repurposed as the
        # live-dot capacity per replica.
        return BatchedSparseMapOrswot(
            n_replicas,
            n_members or 64,
            n_keys2 or 256,
            n_actors or 16,
            config.deferred_cap,
            key_deferred_cap=config.deferred_cap,
        )
    if kind == "sparse_map_map":
        from .models import BatchedSparseNestedMap

        # n_members = the (virtual) inner-key span; n_keys2 repurposed
        # as the live-cell capacity per replica.
        return BatchedSparseNestedMap(
            n_replicas,
            span=n_members or 1 << 16,
            cell_cap=n_keys2 or 256,
            n_actors=n_actors or 16,
            sibling_cap=config.sibling_cap,
            deferred_cap=config.deferred_cap,
            key_deferred_cap=config.deferred_cap,
        )
    if kind == "sparse_map":
        from .models import BatchedSparseMap

        # n_keys bounds the (virtual) key-id universe; n_keys2
        # repurposed as the live-cell capacity per replica.
        na = n_actors or 16
        return BatchedSparseMap(
            n_replicas,
            n_keys or (2**31 - 1) // na,  # widest int32-packable universe
            na,
            n_keys2 or 256,
            config.sibling_cap,
            config.deferred_cap,
        )
    if kind == "map":
        return BatchedMap(
            n_replicas,
            n_keys or 64,
            n_actors or 16,
            config.sibling_cap,
            config.deferred_cap,
        )
    if kind == "map_orswot":
        return BatchedMapOrswot(
            n_replicas,
            n_keys or 16,
            n_members or 16,
            n_actors or 16,
            config.deferred_cap,
        )
    if kind == "map_map":
        return BatchedNestedMap(
            n_replicas,
            n_keys or 16,
            n_members or 16,
            n_actors or 16,
            config.sibling_cap,
            config.deferred_cap,
        )
    if kind == "map3":
        return BatchedMap3(
            n_replicas,
            n_keys or 8,
            n_keys2 or 8,
            n_members or 8,
            n_actors or 16,
            config.deferred_cap,
        )
    if kind == "gcounter":
        return BatchedGCounter(n_replicas, n_actors=n_actors or 16)
    if kind == "pncounter":
        return BatchedPNCounter(n_replicas, n_actors=n_actors or 16)
    if kind == "gset":
        return BatchedGSet(n_replicas, n_members or 64)
    if kind == "lwwreg":
        return BatchedLWWReg(n_replicas)
    if kind == "mvreg":
        return BatchedMVReg(n_replicas, n_actors or 16, config.sibling_cap)
    raise ValueError(f"unknown replicaset kind {kind!r}")


__all__ = ["Config", "config", "configure", "configured", "replicaset"]
