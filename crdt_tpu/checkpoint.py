"""Checkpoint / resume for device-resident replica state.

Reference story (SURVEY.md §6.4): serde bytes on disk ARE the checkpoint;
a resumed replica merges back in. Device form: the struct-of-arrays
state goes into one ``.npz`` (host-synced numpy), the host-side tables
(interners, capacities) ride along as a canonical-JSON sidecar inside
the same file. ``load`` reconstructs the model; the resume path is then
ordinary anti-entropy — ``merge``/``fold`` with the live replicas (the
resume-then-merge test in tests/test_checkpoint.py).

Interned actors/members/keys/values are serialized with
``crdt_tpu.serde`` so arbitrary payload types survive the round trip.
"""

from __future__ import annotations

import io
import json
import os
import zlib
from typing import Union

import jax
import numpy as np

from . import serde
from .models.glist import BatchedGList
from .models.list import BatchedList
from .models.map import BatchedMap
from .models.map3 import BatchedMap3
from .models.map_nested import BatchedMapOrswot, BatchedNestedMap
from .models.orswot import BatchedOrswot
from .models.sparse_orswot import BatchedSparseOrswot
from .native import DELETE, INSERT
from .ops import map as map_ops
from .ops import mvreg as mv_ops
from .ops import orswot as orswot_ops
from .utils import Interner


class CheckpointCorrupt(RuntimeError):
    """A checkpoint's stored bytes fail their recorded content checksum.
    ``array`` names the offending array so the operator knows WHAT
    rotted, not just that something did. Raised by :func:`load` instead
    of silently reconstructing a model from rotten bytes; recovery is a
    matter for the generational snapshot tier
    (``crdt_tpu.durability.snapshot`` falls back one generation)."""

    def __init__(self, path, array: str, expect: int, got=None):
        detail = (
            "is MISSING from the file" if got is None
            else f"fails its content checksum (recorded {expect:#010x}, "
                 f"stored bytes hash to {got:#010x})"
        )
        super().__init__(
            f"checkpoint {os.fspath(path)!r}: array {array!r} {detail} — "
            f"the file is corrupt; restore from an older generation "
            f"instead of loading rotten state"
        )
        self.path = os.fspath(path)
        self.array = array


def array_checksum(v: np.ndarray) -> int:
    """CRC-32 of one array's dtype, shape, and content bytes — the
    per-array integrity unit ``save`` records and ``load`` verifies
    (also the manifest unit of ``durability.snapshot``)."""
    v = np.ascontiguousarray(v)
    crc = zlib.crc32(f"{v.dtype.str}:{v.shape}".encode("ascii"))
    # crc32 takes any buffer: hash the array's memory in place instead
    # of a tobytes() copy (flagship-scale content planes are GBs).
    return zlib.crc32(v.reshape(-1).view(np.uint8).data, crc) & 0xFFFFFFFF


def fsync_dir(path) -> None:
    """fsync a DIRECTORY so a just-renamed/created entry inside it is
    durable across power loss (write-then-rename alone only orders the
    data, not the directory entry). Best-effort on platforms whose
    directories refuse O_RDONLY opens."""
    flags = os.O_RDONLY | getattr(os, "O_DIRECTORY", 0)
    try:
        fd = os.open(os.fspath(path), flags)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _interner_items(interner: Interner):
    return [serde.encode(item) for item in interner.items()]


def _interner_from(items) -> Interner:
    return Interner(serde.decode(item) for item in items)


def _state_arrays(state) -> dict:
    """Flatten any NamedTuple state pytree to numbered host arrays (the
    leaf order of ``jax.tree`` is deterministic for a fixed pytree
    type, so load can unflatten through a template of the same type)."""
    return {f"a_{i}": np.asarray(x) for i, x in enumerate(jax.tree.leaves(state))}


def _state_from_arrays(template, arrays):
    n = sum(1 for k in arrays if k.startswith("a_"))
    leaves = [jax.device_put(arrays[f"a_{i}"]) for i in range(n)]
    return jax.tree.unflatten(jax.tree.structure(template), leaves)


def _engine_dump(engine) -> dict:
    """Host-side identifier-tree state: every minted identifier's path
    (ragged, as counts + flat components) plus the live handle set —
    enough to re-ingest into a fresh engine via ``apply_remote``."""
    total = engine.total_ids()
    counts, cidx, cactor, cctr = [], [], [], []
    for h in range(total):
        path = engine.identifier_path(h)
        counts.append(len(path))
        for ix, a, c in path:
            cidx.append(ix)
            cactor.append(a)
            cctr.append(c)
    live, _ = engine.read()
    clk_actors, clk_ctrs = engine.clock_dump()
    return {
        "e_counts": np.asarray(counts, np.int64),
        "e_cidx": np.asarray(cidx, np.int64),
        "e_cactor": np.asarray(cactor, np.int32),
        "e_cctr": np.asarray(cctr, np.uint64),
        "e_live": np.asarray(live, np.int64),
        # The mint clock rides separately: deletes consume counters no
        # surviving identifier path records, and a resumed engine must
        # not re-mint spent dots.
        "e_clk_actors": clk_actors,
        "e_clk_ctrs": clk_ctrs,
    }


def _engine_restore(engine, arrays, values: np.ndarray) -> None:
    """Re-ingest a dumped identifier tree: INSERT every identifier by
    path (in mint order, reproducing handle numbering), then DELETE the
    ones that were dead. ``values[h]`` is identifier ``h``'s payload."""
    for actor, ctr in zip(arrays["e_clk_actors"], arrays["e_clk_ctrs"]):
        engine.clock_seed(int(actor), int(ctr))
    counts = arrays["e_counts"]
    paths, pos = [], 0
    for c in counts:
        c = int(c)
        paths.append(
            [
                (int(arrays["e_cidx"][i]), int(arrays["e_cactor"][i]), int(arrays["e_cctr"][i]))
                for i in range(pos, pos + c)
            ]
        )
        pos += c
    if not paths:
        return
    kinds = np.full(len(paths), INSERT, np.uint8)
    handles = engine.apply_remote(kinds, paths, np.asarray(values, np.int32))
    assert (handles == np.arange(len(paths))).all(), "handle order drifted"
    live = set(int(h) for h in arrays["e_live"])
    dead = [h for h in range(len(paths)) if h not in live]
    if dead:
        engine.apply_remote(
            np.full(len(dead), DELETE, np.uint8),
            [paths[h] for h in dead],
            np.zeros(len(dead), np.int32),
        )


def _is_sparse_map(model) -> bool:
    from .models.sparse_map import BatchedSparseMapOrswot

    return isinstance(model, BatchedSparseMapOrswot)


def _is_sparse_mvmap(model) -> bool:
    from .models.sparse_mvmap import BatchedSparseMap

    return isinstance(model, BatchedSparseMap)


def _is_sparse_nested_map(model) -> bool:
    from .models.sparse_nested_map import BatchedSparseNestedMap

    return isinstance(model, BatchedSparseNestedMap)


def _dump(model) -> tuple:
    """``(meta, arrays)`` for any checkpointable model — the type
    dispatch :func:`save` serializes and ``durability.snapshot`` layers
    generations on. ``arrays`` values are host numpy; ``meta`` is
    JSON-serializable."""
    if isinstance(model, BatchedOrswot):
        meta = {
            "kind": "orswot",
            "members": _interner_items(model.members),
            "actors": _interner_items(model.actors),
        }
        arrays = {f"s_{k}": np.asarray(v) for k, v in model.state._asdict().items()}
    elif isinstance(model, BatchedSparseOrswot):
        meta = {
            "kind": "sparse_orswot",
            "members": _interner_items(model.members),
            "actors": _interner_items(model.actors),
        }
        arrays = {f"s_{k}": np.asarray(v) for k, v in model.state._asdict().items()}
    elif _is_sparse_map(model):
        meta = {
            "kind": "sparse_map_orswot",
            "span": model.span,
            "keys": _interner_items(model.keys),
            "members": _interner_items(model.members),
            "actors": _interner_items(model.actors),
        }
        arrays = {
            **{f"c_{k}": np.asarray(v)
               for k, v in model.state.core._asdict().items()},
            **{f"s_{k}": np.asarray(v)
               for k, v in model.state._asdict().items() if k != "core"},
        }
    elif _is_sparse_nested_map(model):
        meta = {
            "kind": "sparse_map_map",
            "span": model.span,
            "sibling_cap": model.sibling_cap,
            "n_keys1": model.n_keys1,
            "keys1": _interner_items(model.keys1),
            "keys2": _interner_items(model.keys2),
            "actors": _interner_items(model.actors),
            "values": _interner_items(model.values),
        }
        arrays = {
            **{f"c_{k}": np.asarray(v)
               for k, v in model.state.core._asdict().items()},
            **{f"s_{k}": np.asarray(v)
               for k, v in model.state._asdict().items() if k != "core"},
        }
    elif _is_sparse_mvmap(model):
        meta = {
            "kind": "sparse_map",
            "n_keys": model.n_keys,
            "sibling_cap": model.sibling_cap,
            "keys": _interner_items(model.keys),
            "actors": _interner_items(model.actors),
            "values": _interner_items(model.values),
        }
        arrays = {f"s_{k}": np.asarray(v) for k, v in model.state._asdict().items()}
    elif isinstance(model, BatchedMap):
        meta = {
            "kind": "map",
            "keys": _interner_items(model.keys),
            "actors": _interner_items(model.actors),
            "values": _interner_items(model.values),
        }
        arrays = {
            f"s_{k}": np.asarray(v)
            for k, v in model.state._asdict().items()
            if k != "child"
        }
        arrays.update(
            {f"c_{k}": np.asarray(v) for k, v in model.state.child._asdict().items()}
        )
    elif isinstance(model, BatchedMapOrswot):
        meta = {
            "kind": "map_orswot",
            "keys": _interner_items(model.keys),
            "members": _interner_items(model.members),
            "actors": _interner_items(model.actors),
            "dims": [
                model.n_replicas, model.n_keys, model.n_members,
                int(model.state.core.top.shape[-1]),
                int(model.state.kdcl.shape[-2]),
            ],
        }
        arrays = _state_arrays(model.state)
    elif isinstance(model, BatchedNestedMap):
        meta = {
            "kind": "map_map",
            "keys1": _interner_items(model.keys1),
            "keys2": _interner_items(model.keys2),
            "actors": _interner_items(model.actors),
            "values": _interner_items(model.values),
            "dims": [
                model.n_replicas, model.n_keys1, model.n_keys2,
                int(model.state.m.top.shape[-1]),
                int(model.state.m.child.wact.shape[-1]),
                int(model.state.odcl.shape[-2]),
            ],
        }
        arrays = _state_arrays(model.state)
    elif isinstance(model, BatchedMap3):
        meta = {
            "kind": "map3",
            "keys1": _interner_items(model.keys1),
            "keys2": _interner_items(model.keys2),
            "members": _interner_items(model.members),
            "actors": _interner_items(model.actors),
            "dims": [
                model.n_replicas, model.n_keys1, model.n_keys2,
                model.n_members,
                int(model.state.mo.core.top.shape[-1]),
                int(model.state.odcl.shape[-2]),
            ],
        }
        arrays = _state_arrays(model.state)
    elif isinstance(model, BatchedList):
        ins = model.op_kinds == INSERT
        values = np.zeros(model.engine.total_ids(), np.int32)
        values[model.op_handles[ins]] = model.op_vals[ins]
        # Mesh placement is NOT persisted (a mesh names live devices;
        # the restoring host's topology may differ). ``placed`` records
        # that the caller should re-``place`` after load.
        meta = {
            "kind": "list",
            "n_replicas": model.n_replicas,
            "applied": model._applied,
            "placed": model._mesh is not None,
        }
        arrays = {
            "slots": model.slots,
            "vals": np.asarray(model.vals),
            "alive": np.asarray(model.alive),
            "op_handles": model.op_handles,
            "op_kinds": model.op_kinds,
            "op_vals": model.op_vals,
            "id_values": values,
            **_engine_dump(model.engine),
        }
    elif isinstance(model, BatchedGList):
        meta = {"kind": "glist", "n_replicas": model.n_replicas}
        arrays = {
            "slots": model.slots,
            "uvals": model.uvals,
            "alive": np.asarray(model.alive),
            **_engine_dump(model.engine),
        }
    else:
        raise TypeError(f"cannot checkpoint {type(model).__name__}")
    return meta, {k: np.asarray(v) for k, v in arrays.items()}


def to_npz_bytes(meta: dict, arrays: dict) -> bytes:
    """One .npz image of ``(meta, arrays)`` with per-array content
    checksums recorded in the meta — the byte format ``save`` writes
    and ``durability.snapshot`` frames into generations."""
    meta = dict(meta)
    meta["checksums"] = {k: array_checksum(v) for k, v in arrays.items()}
    buf = io.BytesIO()
    np.savez(
        buf,
        meta=np.frombuffer(
            json.dumps(meta, sort_keys=True).encode("utf-8"), dtype=np.uint8
        ),
        **arrays,
    )
    return buf.getvalue()


def from_npz_bytes(path, raw) -> tuple:
    """Parse + integrity-check one .npz image: returns ``(meta,
    arrays)`` or raises :class:`CheckpointCorrupt` naming the first
    array whose stored bytes fail their recorded checksum. Checkpoints
    predating the checksums load with a one-shot warning — their
    integrity is UNKNOWN, not verified."""
    global _WARNED_NO_CHECKSUMS
    with np.load(io.BytesIO(raw) if isinstance(raw, bytes) else raw) as z:
        meta = json.loads(bytes(z["meta"]).decode("utf-8"))
        arrays = {k: z[k] for k in z.files if k != "meta"}
    sums = meta.get("checksums")
    if sums is None:
        if not _WARNED_NO_CHECKSUMS:
            _WARNED_NO_CHECKSUMS = True
            import warnings

            warnings.warn(
                f"checkpoint {os.fspath(path)!r} predates per-array "
                f"content checksums — integrity NOT verified (re-save to "
                f"upgrade). Warned once per process.",
                stacklevel=3,
            )
        from .utils.metrics import metrics

        metrics.count("checkpoint.loaded_unverified")
        return meta, arrays
    # Iterate the RECORDED set, not the stored one: a rotten file that
    # dropped an array entirely must fail here with its name, not leak
    # a bare KeyError out of the restore dispatch.
    missing = sorted(set(sums) - set(arrays))
    if missing:
        raise CheckpointCorrupt(path, missing[0], int(sums[missing[0]]))
    for name, v in arrays.items():
        got = array_checksum(v)
        expect = int(sums.get(name, -1))
        if got != expect:
            raise CheckpointCorrupt(path, name, expect, got)
    return meta, arrays


_WARNED_NO_CHECKSUMS = False


def save(path: Union[str, os.PathLike], model, compact: bool = False) -> None:
    """Checkpoint a device model to ``path`` (one .npz file) with
    per-array content checksums, atomically AND durably: the tmp file
    (and its directory) is fsynced BEFORE the rename — write-then-rename
    without the fsync orders nothing across power loss, so a crash
    could leave the renamed file empty.

    ``compact=True`` runs causal-stability compaction against the
    model's OWN replica rows first (``reclaim.compact_model`` — sound
    because the checkpointed batch is the replica set the frontier is
    computed over): retired parked slots and stale dead payload never
    reach disk, and a model shrunk after restore starts from the
    compacted occupancy. Models outside the compactable family (lists,
    counters) save as-is with ``reclaim.compact_on_save_unsupported``
    counted — compact-on-save must never make a checkpoint impossible."""
    if compact:
        from . import elastic
        from .reclaim import compact_model
        from .utils.metrics import metrics

        # Only the family check may soften to a counter — a TypeError
        # raised INSIDE a registered compaction kernel is a kernel bug
        # and must surface, not be miscounted as "unsupported".
        try:
            elastic.kind_of(model)
        except TypeError:
            metrics.count("reclaim.compact_on_save_unsupported")
        else:
            compact_model(model)
    meta, arrays = _dump(model)
    # Write-then-fsync-then-rename: a crash mid-checkpoint never
    # corrupts the last good checkpoint (the reference's bytes-on-disk
    # story, made atomic and durable).
    tmp = f"{os.fspath(path)}.tmp"
    with open(tmp, "wb") as f:
        f.write(to_npz_bytes(meta, arrays))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    fsync_dir(os.path.dirname(os.path.abspath(os.fspath(path))))


def load(path: Union[str, os.PathLike]):
    """Restore a device model checkpointed by ``save``; raises
    :class:`CheckpointCorrupt` (naming the array) when the stored bytes
    fail their recorded content checksums instead of silently
    reconstructing from rotten state."""
    with open(path, "rb") as f:
        meta, arrays = from_npz_bytes(path, f.read())
    return _restore(meta, arrays)


def _restore(meta: dict, arrays: dict):
    """Rebuild the model from a parsed ``(meta, arrays)`` image (the
    inverse of :func:`_dump`; shared with ``durability.snapshot``)."""
    dev = lambda a: jax.device_put(a)
    if meta["kind"] == "orswot":
        state = orswot_ops.OrswotState(
            **{k[2:]: dev(v) for k, v in arrays.items() if k.startswith("s_")}
        )
        model = BatchedOrswot(
            state.top.shape[0],
            state.ctr.shape[-2],
            state.ctr.shape[-1],
            state.dcl.shape[-2],
            members=_interner_from(meta["members"]),
            actors=_interner_from(meta["actors"]),
        )
        model.state = state
        return model
    if meta["kind"] == "sparse_orswot":
        from .ops import sparse_orswot as sparse_ops

        state = sparse_ops.SparseOrswotState(
            **{k[2:]: dev(v) for k, v in arrays.items() if k.startswith("s_")}
        )
        model = BatchedSparseOrswot(
            state.top.shape[0],
            state.eid.shape[-1],
            state.top.shape[-1],
            state.dcl.shape[-2],
            state.didx.shape[-1],
            members=_interner_from(meta["members"]),
            actors=_interner_from(meta["actors"]),
        )
        model.state = state
        return model
    if meta["kind"] == "sparse_map_orswot":
        from .models.sparse_map import BatchedSparseMapOrswot
        from .ops import sparse_nest as nest_ops
        from .ops import sparse_orswot as sparse_ops

        core = sparse_ops.SparseOrswotState(
            **{k[2:]: dev(v) for k, v in arrays.items() if k.startswith("c_")}
        )
        state = nest_ops.SparseNestState(
            core=core,
            **{k[2:]: dev(v) for k, v in arrays.items() if k.startswith("s_")},
        )
        model = BatchedSparseMapOrswot(
            core.top.shape[0],
            int(meta["span"]),
            core.eid.shape[-1],
            core.top.shape[-1],
            core.dcl.shape[-2],
            core.didx.shape[-1],
            state.kcl.shape[-2],
            state.kidx.shape[-1],
            keys=_interner_from(meta["keys"]),
            members=_interner_from(meta["members"]),
            actors=_interner_from(meta["actors"]),
        )
        model.state = state
        return model
    if meta["kind"] == "sparse_map_map":
        from .models.sparse_nested_map import BatchedSparseNestedMap
        from .ops import sparse_mvmap as smv_ops
        from .ops import sparse_nest as nest_ops

        core = smv_ops.SparseMVMapState(
            **{k[2:]: dev(v) for k, v in arrays.items() if k.startswith("c_")}
        )
        state = nest_ops.SparseNestState(
            core=core,
            **{k[2:]: dev(v) for k, v in arrays.items() if k.startswith("s_")},
        )
        model = BatchedSparseNestedMap(
            core.top.shape[0],
            int(meta["span"]),
            core.kid.shape[-1],
            core.top.shape[-1],
            int(meta["sibling_cap"]),
            core.dcl.shape[-2],
            core.kidx.shape[-1],
            state.kcl.shape[-2],
            state.kidx.shape[-1],
            # Older checkpoints predate the persisted bound; 0 falls back
            # to the packing-max default (their save-time value).
            n_keys1=int(meta.get("n_keys1", 0)),
            keys1=_interner_from(meta["keys1"]),
            keys2=_interner_from(meta["keys2"]),
            actors=_interner_from(meta["actors"]),
            values=_interner_from(meta["values"]),
        )
        model.state = state
        return model
    if meta["kind"] == "sparse_map":
        from .models.sparse_mvmap import BatchedSparseMap
        from .ops import sparse_mvmap as smv_ops

        state = smv_ops.SparseMVMapState(
            **{k[2:]: dev(v) for k, v in arrays.items() if k.startswith("s_")}
        )
        model = BatchedSparseMap(
            state.top.shape[0],
            int(meta["n_keys"]),
            state.top.shape[-1],
            state.kid.shape[-1],
            int(meta["sibling_cap"]),
            state.dcl.shape[-2],
            state.kidx.shape[-1],
            keys=_interner_from(meta["keys"]),
            actors=_interner_from(meta["actors"]),
            values=_interner_from(meta["values"]),
        )
        model.state = state
        return model
    if meta["kind"] == "map":
        child = mv_ops.MVRegState(
            **{k[2:]: dev(v) for k, v in arrays.items() if k.startswith("c_")}
        )
        state = map_ops.MapState(
            child=child,
            **{k[2:]: dev(v) for k, v in arrays.items() if k.startswith("s_")},
        )
        model = BatchedMap(
            state.top.shape[0],
            state.dkeys.shape[-1],
            state.top.shape[-1],
            state.child.wact.shape[-1],
            state.dcl.shape[-2],
            keys=_interner_from(meta["keys"]),
            actors=_interner_from(meta["actors"]),
            values=_interner_from(meta["values"]),
        )
        model.state = state
        return model
    if meta["kind"] == "map_orswot":
        r, nk, nm, na, d = meta["dims"]
        model = BatchedMapOrswot(
            r, nk, nm, na, d,
            keys=_interner_from(meta["keys"]),
            members=_interner_from(meta["members"]),
            actors=_interner_from(meta["actors"]),
        )
        model.state = _state_from_arrays(model.state, arrays)
        return model
    if meta["kind"] == "map_map":
        r, nk1, nk2, na, s, d = meta["dims"]
        model = BatchedNestedMap(
            r, nk1, nk2, na, s, d,
            keys1=_interner_from(meta["keys1"]),
            keys2=_interner_from(meta["keys2"]),
            actors=_interner_from(meta["actors"]),
            values=_interner_from(meta["values"]),
        )
        model.state = _state_from_arrays(model.state, arrays)
        return model
    if meta["kind"] == "map3":
        r, nk1, nk2, nm, na, d = meta["dims"]
        model = BatchedMap3(
            r, nk1, nk2, nm, na, d,
            keys1=_interner_from(meta["keys1"]),
            keys2=_interner_from(meta["keys2"]),
            members=_interner_from(meta["members"]),
            actors=_interner_from(meta["actors"]),
        )
        model.state = _state_from_arrays(model.state, arrays)
        return model
    if meta["kind"] == "list":
        model = BatchedList(meta["n_replicas"])
        if meta.get("placed"):
            import warnings

            warnings.warn(
                "checkpointed BatchedList was mesh-placed; placement is "
                "not persisted — call place(mesh) again on the restored "
                "model before large-scale use",
                stacklevel=2,
            )
        _engine_restore(model.engine, arrays, arrays["id_values"])
        model.slots = arrays["slots"]
        assert (model.engine.total_order() == model.slots).all(), (
            "restored identifier order drifted from the checkpoint"
        )
        model.vals = jax.device_put(arrays["vals"])
        model.alive = jax.device_put(arrays["alive"])
        model.op_handles = arrays["op_handles"]
        model.op_kinds = arrays["op_kinds"]
        model.op_vals = arrays["op_vals"]
        model._applied = int(meta["applied"])
        return model
    if meta["kind"] == "glist":
        model = BatchedGList(meta["n_replicas"])
        _engine_restore(model.engine, arrays, arrays["uvals"])
        model.slots = arrays["slots"]
        assert (model.engine.total_order() == model.slots).all(), (
            "restored identifier order drifted from the checkpoint"
        )
        model.uvals = arrays["uvals"]
        model.alive = jax.device_put(arrays["alive"])
        return model
    raise ValueError(f"unknown checkpoint kind {meta['kind']!r}")


__all__ = [
    "CheckpointCorrupt", "array_checksum", "fsync_dir", "from_npz_bytes",
    "load", "save", "to_npz_bytes",
]
