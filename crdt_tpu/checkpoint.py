"""Checkpoint / resume for device-resident replica state.

Reference story (SURVEY.md §6.4): serde bytes on disk ARE the checkpoint;
a resumed replica merges back in. Device form: the struct-of-arrays
state goes into one ``.npz`` (host-synced numpy), the host-side tables
(interners, capacities) ride along as a canonical-JSON sidecar inside
the same file. ``load`` reconstructs the model; the resume path is then
ordinary anti-entropy — ``merge``/``fold`` with the live replicas (the
resume-then-merge test in tests/test_checkpoint.py).

Interned actors/members/keys/values are serialized with
``crdt_tpu.serde`` so arbitrary payload types survive the round trip.
"""

from __future__ import annotations

import io
import json
import os
from typing import Union

import jax
import numpy as np

from . import serde
from .models.map import BatchedMap
from .models.orswot import BatchedOrswot
from .ops import map as map_ops
from .ops import mvreg as mv_ops
from .ops import orswot as orswot_ops
from .utils import Interner


def _interner_items(interner: Interner):
    return [serde.encode(item) for item in interner.items()]


def _interner_from(items) -> Interner:
    return Interner(serde.decode(item) for item in items)


def save(path: Union[str, os.PathLike], model) -> None:
    """Checkpoint a device model to ``path`` (one .npz file)."""
    if isinstance(model, BatchedOrswot):
        meta = {
            "kind": "orswot",
            "members": _interner_items(model.members),
            "actors": _interner_items(model.actors),
        }
        arrays = {f"s_{k}": np.asarray(v) for k, v in model.state._asdict().items()}
    elif isinstance(model, BatchedMap):
        meta = {
            "kind": "map",
            "keys": _interner_items(model.keys),
            "actors": _interner_items(model.actors),
            "values": _interner_items(model.values),
        }
        arrays = {
            f"s_{k}": np.asarray(v)
            for k, v in model.state._asdict().items()
            if k != "child"
        }
        arrays.update(
            {f"c_{k}": np.asarray(v) for k, v in model.state.child._asdict().items()}
        )
    else:
        raise TypeError(f"cannot checkpoint {type(model).__name__}")

    buf = io.BytesIO()
    np.savez(
        buf,
        meta=np.frombuffer(
            json.dumps(meta, sort_keys=True).encode("utf-8"), dtype=np.uint8
        ),
        **arrays,
    )
    # Write-then-rename: a crash mid-checkpoint never corrupts the last
    # good checkpoint (the reference's bytes-on-disk story, made atomic).
    tmp = f"{os.fspath(path)}.tmp"
    with open(tmp, "wb") as f:
        f.write(buf.getvalue())
    os.replace(tmp, path)


def load(path: Union[str, os.PathLike]):
    """Restore a device model checkpointed by ``save``."""
    with np.load(path) as z:
        meta = json.loads(bytes(z["meta"]).decode("utf-8"))
        arrays = {k: z[k] for k in z.files if k != "meta"}

    dev = lambda a: jax.device_put(a)
    if meta["kind"] == "orswot":
        state = orswot_ops.OrswotState(
            **{k[2:]: dev(v) for k, v in arrays.items() if k.startswith("s_")}
        )
        model = BatchedOrswot(
            state.top.shape[0],
            state.ctr.shape[-2],
            state.ctr.shape[-1],
            state.dcl.shape[-2],
            members=_interner_from(meta["members"]),
            actors=_interner_from(meta["actors"]),
        )
        model.state = state
        return model
    if meta["kind"] == "map":
        child = mv_ops.MVRegState(
            **{k[2:]: dev(v) for k, v in arrays.items() if k.startswith("c_")}
        )
        state = map_ops.MapState(
            child=child,
            **{k[2:]: dev(v) for k, v in arrays.items() if k.startswith("s_")},
        )
        model = BatchedMap(
            state.top.shape[0],
            state.dkeys.shape[-1],
            state.top.shape[-1],
            state.child.wact.shape[-1],
            state.dcl.shape[-2],
            keys=_interner_from(meta["keys"]),
            actors=_interner_from(meta["actors"]),
            values=_interner_from(meta["values"]),
        )
        model.state = state
        return model
    raise ValueError(f"unknown checkpoint kind {meta['kind']!r}")


__all__ = ["save", "load"]
