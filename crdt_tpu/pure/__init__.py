"""Sequential oracle implementations with reference semantics.

These are the correctness ground truth (the reference's L0–L4 behavior,
SURVEY.md §7.2 step 1): plain-Python data structures whose merge/apply paths
the batched backends in ``crdt_tpu.models`` must match bit-for-bit.
"""
