"""MerkleReg — content-addressed merkle-DAG register.

Reference: src/merkle_reg.rs ``MerkleReg<T> { leaves: BTreeSet<Hash>, dag:
BTreeMap<Hash, Node<T>>, orphans }`` with ``Node { value, parents }``,
``write(value, parents) -> Node``, ``read() -> Content<T>`` (the current
concurrent leaves); Hash = 32 bytes of SHA-3 (SURVEY.md §3 row 15). Nodes
whose parents have not arrived yet are buffered in ``orphans`` and spliced
in when the missing parent lands (out-of-order delivery support).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Any, Dict, FrozenSet, List, Set

from ..traits import CmRDT, CvRDT

Hash = bytes  # 32-byte SHA3-256 digest


def _canonical_bytes(value: Any) -> bytes:
    """Stable, injective byte encoding for hashing (tag + length-prefix
    framing so composite values cannot collide). The reference hashes the
    serde encoding."""

    def frame(tag: bytes, payload: bytes) -> bytes:
        return tag + len(payload).to_bytes(8, "big") + payload

    if isinstance(value, bytes):
        return frame(b"b", value)
    if isinstance(value, bool):  # before int: bool is an int subclass
        return frame(b"?", b"1" if value else b"0")
    if isinstance(value, str):
        return frame(b"s", value.encode("utf-8"))
    if isinstance(value, int):
        return frame(b"i", str(value).encode())
    if isinstance(value, float):
        # CPython float repr is the shortest round-tripping IEEE-754
        # decimal — stable across processes and platforms.
        return frame(b"f", repr(value).encode())
    if isinstance(value, (tuple, list)):
        return frame(b"l", b"".join(_canonical_bytes(v) for v in value))
    if isinstance(value, (set, frozenset)):
        return frame(b"e", b"".join(sorted(_canonical_bytes(v) for v in value)))
    if isinstance(value, dict):
        items = sorted(
            (_canonical_bytes(k), _canonical_bytes(v)) for k, v in value.items()
        )
        return frame(b"d", b"".join(k + v for k, v in items))
    if value is None:
        return frame(b"n", b"")
    raise TypeError(
        f"cannot canonically hash {type(value).__name__}: repr() is not "
        "stable across processes; use bytes/str/int/float/bool/None or "
        "list/tuple/set/dict compositions of them"
    )


@dataclass(frozen=True)
class Node:
    """Reference: src/merkle_reg.rs ``Node { value, parents }``."""

    value: Any
    parents: FrozenSet[Hash] = field(default_factory=frozenset)

    def hash(self) -> Hash:
        h = hashlib.sha3_256()
        for parent in sorted(self.parents):
            h.update(parent)
        h.update(_canonical_bytes(self.value))
        return h.digest()


@dataclass
class Content:
    """Reference: src/merkle_reg.rs ``Content<T>`` — the concurrent
    leaves of the DAG."""

    nodes: Dict[Hash, Node]

    def values(self) -> List[Any]:
        return [n.value for _, n in sorted(self.nodes.items())]

    def hashes(self) -> FrozenSet[Hash]:
        return frozenset(self.nodes)

    def is_empty(self) -> bool:
        return not self.nodes


class MerkleReg(CvRDT, CmRDT):
    __slots__ = ("leaves", "dag", "orphans")

    def __init__(self):
        self.leaves: Set[Hash] = set()
        self.dag: Dict[Hash, Node] = {}
        # missing parent hash -> nodes waiting on it
        self.orphans: Dict[Hash, List[Node]] = {}

    # ---- reads ---------------------------------------------------------
    def read(self) -> Content:
        """Reference: src/merkle_reg.rs ``MerkleReg::read``."""
        return Content(nodes={h: self.dag[h] for h in self.leaves})

    def node(self, hash_: Hash) -> Node:
        return self.dag.get(hash_)

    def parents(self, hash_: Hash) -> Content:
        """The parent nodes of ``hash_``. Reference: src/merkle_reg.rs
        ``MerkleReg::parents``."""
        node = self.dag.get(hash_)
        hashes = node.parents if node else frozenset()
        return Content(nodes={h: self.dag[h] for h in hashes if h in self.dag})

    def children(self, hash_: Hash) -> Content:
        """Reference: src/merkle_reg.rs ``MerkleReg::children``."""
        return Content(
            nodes={
                h: n for h, n in self.dag.items() if hash_ in n.parents
            }
        )

    def num_nodes(self) -> int:
        return len(self.dag)

    def num_orphans(self) -> int:
        return sum(len(v) for v in self.orphans.values())

    # ---- writes --------------------------------------------------------
    def write(self, value: Any, parents: FrozenSet[Hash] = frozenset()) -> Node:
        """Mint (not apply) a node on top of ``parents``.

        Reference: src/merkle_reg.rs ``MerkleReg::write``.
        """
        return Node(value=value, parents=frozenset(parents))

    # ---- CmRDT / CvRDT -------------------------------------------------
    def apply(self, node: Node) -> None:
        h = node.hash()
        if h in self.dag:
            return
        missing = [p for p in node.parents if p not in self.dag]
        if missing:
            # Orphan until the first missing parent arrives.
            self.orphans.setdefault(missing[0], []).append(node)
            return
        self.dag[h] = node
        for parent in node.parents:
            self.leaves.discard(parent)
        # A node enters the dag only after all its parents, so nothing in
        # the dag can already reference h: it is necessarily a leaf.
        self.leaves.add(h)
        # Splice in any orphans that were waiting on this node.
        woken = self.orphans.pop(h, [])
        for orphan in woken:
            self.apply(orphan)

    def merge(self, other: "MerkleReg") -> None:
        for node in other.dag.values():
            self.apply(node)
        for waiting in other.orphans.values():
            for node in waiting:
                self.apply(node)

    # ---- plumbing ------------------------------------------------------
    def __eq__(self, other) -> bool:
        return (
            isinstance(other, MerkleReg)
            and self.dag == other.dag
            and self.leaves == other.leaves
        )

    def clone(self) -> "MerkleReg":
        out = MerkleReg()
        out.leaves = set(self.leaves)
        out.dag = dict(self.dag)
        out.orphans = {k: list(v) for k, v in self.orphans.items()}
        return out

    def __repr__(self) -> str:
        return f"MerkleReg({len(self.dag)} nodes, {len(self.leaves)} leaves)"
