"""PN-Counter — increment/decrement counter as a pair of G-Counters.

Reference: src/pncounter.rs ``PNCounter<A> { p: GCounter, n: GCounter }``;
``Op { dot, dir: Dir::Pos|Neg }``; ``read() -> BigInt`` (p − n) — Python
ints are arbitrary-precision, which preserves the BigInt read semantics at
the API edge (SURVEY.md §3 row 6, §7.3).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Any

from ..dot import Dot
from ..traits import CmRDT, CvRDT
from .gcounter import GCounter


class Dir(enum.Enum):
    """Reference: src/pncounter.rs ``Dir::Pos`` / ``Dir::Neg``."""

    POS = "pos"
    NEG = "neg"


@dataclass(frozen=True)
class PNOp:
    """Reference: src/pncounter.rs ``Op { dot, dir }``."""

    dot: Dot
    dir: Dir


class PNCounter(CvRDT, CmRDT):
    __slots__ = ("p", "n")

    def __init__(self, p: GCounter = None, n: GCounter = None):
        self.p = p if p is not None else GCounter()
        self.n = n if n is not None else GCounter()

    def inc(self, actor: Any) -> PNOp:
        """Reference: src/pncounter.rs ``PNCounter::inc`` (pure op mint)."""
        return PNOp(dot=self.p.inc(actor), dir=Dir.POS)

    def dec(self, actor: Any) -> PNOp:
        """Reference: src/pncounter.rs ``PNCounter::dec``."""
        return PNOp(dot=self.n.inc(actor), dir=Dir.NEG)

    def validate_op(self, op: PNOp) -> None:
        """Reference: src/pncounter.rs ``validate_op``."""
        (self.p if op.dir is Dir.POS else self.n).validate_op(op.dot)

    def apply(self, op: PNOp) -> None:
        if op.dir is Dir.POS:
            self.p.apply(op.dot)
        else:
            self.n.apply(op.dot)

    def merge(self, other: "PNCounter") -> None:
        self.p.merge(other.p)
        self.n.merge(other.n)

    def read(self) -> int:
        """p − n as an arbitrary-precision int (reference: BigInt read)."""
        return self.p.read() - self.n.read()

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, PNCounter)
            and self.p == other.p
            and self.n == other.n
        )

    def __hash__(self):
        return hash((self.p, self.n))

    def clone(self) -> "PNCounter":
        return PNCounter(self.p.clone(), self.n.clone())

    def __repr__(self) -> str:
        return f"PNCounter({self.read()})"
