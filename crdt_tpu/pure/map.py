"""Map — CRDT of CRDTs; the composition layer.

Reference: src/map.rs ``Map<K, V: Val<A>, A> { clock, entries: BTreeMap<K,
Entry { clock, val }>, deferred }`` with ``Op::{Nop, Up { dot, key, op },
Rm { clock, keyset }}`` (SURVEY.md §3 row 11, §4.3). Values must satisfy
the ``Val`` contract: cloneable, default-constructible, ``CmRDT`` +
``CvRDT`` + supporting witness-pruning — removal of a key prunes the child
to the surviving update witnesses, and merge prunes child state whose
witnessing update dots one side observed and deleted (the hardest
correctness surface in the reference).

In Python the ``trait Val<A>`` bound becomes a constructor argument: the
Map holds ``val_default`` (a zero-arg factory, e.g. ``MVReg`` / ``Orswot``
/ a nested ``Map`` factory) playing the role of ``V::default()``.

Composition rule (the causal-composition law from the delta-CRDT
literature — Almeida et al., PAPERS.md; chosen per SURVEY.md §0 since the
mount was empty): each entry tracks its *witness dot set* ``W`` (every
update dot routed to the key that has not been removed), and

    child state is alive iff its witness dot is in ``W``.

``W`` is a true dot set, not a per-actor-max clock — so removing the state
witnessed by (A,1) while (A,2) lives is representable exactly, and every
path maintains the single invariant: key removal filters ``W`` under the
rm clock and prunes the child to ``W``; merge joins ``W`` with the orswot
dot rule (a dot survives iff the other side also has it or never saw it),
plain-merges the children, and prunes to the joined ``W``. Because the
child prune is a pure pointwise function of the joined witness set —
never of top clocks or merge order — ``merge`` is a true lattice join
(commutative, associative, idempotent, bit-for-bit), which the property
suite asserts and the TPU reduction-tree anti-entropy path requires
(SURVEY.md §7.3 "deterministic reduction").
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, FrozenSet, Iterable, Set, Tuple

from ..ctx import AddCtx, ReadCtx, RmCtx
from ..dot import Dot
from ..traits import CmRDT, CvRDT, ResetRemove
from ..vclock import VClock


@dataclass(frozen=True)
class Nop:
    """Reference: src/map.rs ``Op::Nop``."""


@dataclass(frozen=True)
class Up:
    """Reference: src/map.rs ``Op::Up { dot, key, op }`` — route a child op
    to the entry at ``key``, witnessed by ``dot``."""

    dot: Dot
    key: Any
    op: Any


@dataclass(frozen=True)
class MapRm:
    """Reference: src/map.rs ``Op::Rm { clock, keyset }``."""

    clock: VClock
    keyset: Tuple[Any, ...]


def _witness_clock(dots: Set[Dot]) -> VClock:
    """Per-actor-max view of a witness set (the RmCtx wire form —
    reference: src/map.rs ``Entry.clock``)."""
    out = VClock()
    for d in dots:
        out.apply(d)
    return out


class _Entry:
    """Reference: src/map.rs ``Entry { clock, val }`` — here the birth
    witnesses are a dot set (see module docstring for why)."""

    __slots__ = ("dots", "val")

    def __init__(self, dots: Set[Dot], val: Any):
        self.dots = dots
        self.val = val

    def clone(self) -> "_Entry":
        return _Entry(set(self.dots), self.val.clone())

    def __eq__(self, other):
        return (
            isinstance(other, _Entry)
            and self.dots == other.dots
            and self.val == other.val
        )

    def __repr__(self):
        return f"Entry(dots={sorted((repr(d.actor), d.counter) for d in self.dots)}, val={self.val!r})"


class Map(CvRDT, CmRDT, ResetRemove):
    __slots__ = ("val_default", "clock", "entries", "deferred")

    def __init__(self, val_default: Callable[[], Any]):
        self.val_default = val_default
        self.clock = VClock()
        self.entries: Dict[Any, _Entry] = {}
        self.deferred: Dict[VClock, set] = {}

    # ---- reads ---------------------------------------------------------
    def len(self) -> ReadCtx:
        """Reference: src/map.rs ``Map::len``."""
        return ReadCtx(
            add_clock=self.clock.clone(),
            rm_clock=self.clock.clone(),
            val=len(self.entries),
        )

    def is_empty(self) -> ReadCtx:
        ctx = self.len()
        ctx.val = ctx.val == 0
        return ctx

    def get(self, key: Any) -> ReadCtx:
        """Reference: src/map.rs ``Map::get`` — rm_clock covers the entry's
        observed witnesses so a derived rm removes exactly the observed
        updates."""
        entry = self.entries.get(key)
        return ReadCtx(
            add_clock=self.clock.clone(),
            rm_clock=_witness_clock(entry.dots) if entry is not None else VClock(),
            val=entry.val.clone() if entry is not None else None,
        )

    def keys(self) -> FrozenSet[Any]:
        return frozenset(self.entries)

    # ---- op minting ----------------------------------------------------
    def update(
        self,
        key: Any,
        ctx: AddCtx,
        f: Callable[[Any, AddCtx], Any],
    ) -> Up:
        """Mint an op applying ``f(current_or_default_child, ctx) ->
        child_op`` at ``key``. Reference: src/map.rs ``Map::update``."""
        entry = self.entries.get(key)
        val = entry.val.clone() if entry is not None else self.val_default()
        child_op = f(val, ctx)
        return Up(dot=ctx.dot, key=key, op=child_op)

    def rm(self, key: Any, ctx: RmCtx) -> MapRm:
        """Reference: src/map.rs ``Map::rm``."""
        return MapRm(clock=ctx.clock.clone(), keyset=(key,))

    def rm_all(self, keys: Iterable[Any], ctx: RmCtx) -> MapRm:
        return MapRm(clock=ctx.clock.clone(), keyset=tuple(keys))

    # ---- CmRDT ---------------------------------------------------------
    def apply(self, op) -> None:
        if isinstance(op, Nop):
            return
        if isinstance(op, Up):
            if self.clock.get(op.dot.actor) >= op.dot.counter:
                return  # already observed this update
            entry = self.entries.get(op.key)
            if entry is None:
                entry = _Entry(set(), self.val_default())
                self.entries[op.key] = entry
            entry.dots.add(op.dot)
            entry.val.apply(op.op)
            self.clock.apply(op.dot)
            self._apply_deferred()
            self._cover_children(dot=op.dot)
        elif isinstance(op, MapRm):
            self._apply_keyset_rm(op.keyset, op.clock)
        else:
            raise TypeError(f"not a Map op: {op!r}")

    def _apply_keyset_rm(self, keyset: Iterable[Any], clock: VClock) -> None:
        """Reference: src/map.rs ``apply_keyset_rm`` — drop the witnesses
        the rm clock covers and prune the child to the survivors; defer if
        the rm clock is ahead of our view."""
        for key in keyset:
            entry = self.entries.get(key)
            if entry is not None:
                entry.dots = {
                    d for d in entry.dots if d.counter > clock.get(d.actor)
                }
                if not entry.dots:
                    del self.entries[key]
                else:
                    entry.val.retain_witnesses(entry.dots)
        if not clock <= self.clock:
            self._defer_remove(clock, keyset)

    def _defer_remove(self, clock: VClock, keys: Iterable[Any]) -> None:
        self.deferred.setdefault(clock.clone(), set()).update(keys)

    def _apply_deferred(self) -> None:
        deferred = self.deferred
        self.deferred = {}
        for clock, keys in deferred.items():
            self._apply_keyset_rm(keys, clock)

    # ---- CvRDT ---------------------------------------------------------
    def merge(self, other: "Map") -> None:
        # Witness survival is the orswot dot rule: a dot survives iff the
        # other side also witnesses it, or has never seen it at all.
        for key in list(self.entries):
            if key not in other.entries:
                entry = self.entries[key]
                entry.dots = {
                    d
                    for d in entry.dots
                    if d.counter > other.clock.get(d.actor)
                }
                if not entry.dots:
                    del self.entries[key]
                else:
                    entry.val.retain_witnesses(entry.dots)

        for key, their_entry in other.entries.items():
            our_entry = self.entries.get(key)
            if our_entry is not None:
                ours, theirs = our_entry.dots, their_entry.dots
                survivors = (
                    {
                        d
                        for d in ours
                        if d in theirs or d.counter > other.clock.get(d.actor)
                    }
                    | {
                        d
                        for d in theirs
                        if d in ours or d.counter > self.clock.get(d.actor)
                    }
                )
                if not survivors:
                    del self.entries[key]
                else:
                    our_entry.val.merge(their_entry.val)
                    our_entry.dots = survivors
                    our_entry.val.retain_witnesses(survivors)
            else:
                survivors = {
                    d
                    for d in their_entry.dots
                    if d.counter > self.clock.get(d.actor)
                }
                if survivors:
                    entry = _Entry(survivors, their_entry.val.clone())
                    entry.val.retain_witnesses(survivors)
                    self.entries[key] = entry

        for clock, keys in other.deferred.items():
            self._defer_remove(clock, keys)

        self.clock.merge(other.clock)
        self._apply_deferred()
        self._cover_children()

    def _cover_children(self, dot: Dot = None) -> None:
        """Maintain the shared-causal-context invariant: every child's top
        clock equals this map's clock after every top-advancing mutation.
        This is what makes child tops a canonical function of the merged
        state (bit-identical across merge orders) — and it is exact: a dot
        the map has seen either reached this child or proves that absent
        child state born at it was removed (map dots belong to exactly one
        key). The op path advances the clock by exactly one dot, so it
        takes the O(1)-per-child ``covered_dot`` fast path."""
        if dot is not None:
            for entry in self.entries.values():
                entry.val.covered_dot(dot)
        else:
            for entry in self.entries.values():
                entry.val.covered(self.clock)

    def covered(self, ctx: VClock) -> None:
        """Causal-composition hook for a containing ``Map`` (nested
        maps): absorb the outer context, replay parked removes, recurse."""
        self.clock.merge(ctx)
        self._apply_deferred()
        self._cover_children()

    def covered_dot(self, dot: Dot) -> None:
        """One-dot fast path of ``covered``."""
        self.clock.apply(dot)
        self._apply_deferred()
        self._cover_children(dot=dot)

    # ---- ResetRemove (nested removal, SURVEY §4.3) ---------------------
    def reset_remove(self, clock: VClock) -> None:
        for key in list(self.entries):
            entry = self.entries[key]
            entry.dots = {
                d for d in entry.dots if d.counter > clock.get(d.actor)
            }
            if not entry.dots:
                del self.entries[key]
            else:
                entry.val.retain_witnesses(entry.dots)
        deferred = self.deferred
        self.deferred = {}
        for rm_clock, keys in deferred.items():
            rm_clock = rm_clock.clone()
            rm_clock.reset_remove(clock)
            if not rm_clock.is_empty():
                self._defer_remove(rm_clock, keys)
        self.clock.reset_remove(clock)

    def retain_witnesses(self, alive: Set[Dot]) -> None:
        """Causal-composition hook for a containing ``Map``: keep only
        entries whose witness dots survive in ``alive``, recursing into
        children."""
        for key in list(self.entries):
            entry = self.entries[key]
            entry.dots &= alive
            if not entry.dots:
                del self.entries[key]
            else:
                entry.val.retain_witnesses(entry.dots)

    # ---- plumbing ------------------------------------------------------
    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Map)
            and self.clock == other.clock
            and self.entries == other.entries
            and {k: frozenset(v) for k, v in self.deferred.items()}
            == {k: frozenset(v) for k, v in other.deferred.items()}
        )

    def clone(self) -> "Map":
        out = Map(self.val_default)
        out.clock = self.clock.clone()
        out.entries = {k: e.clone() for k, e in self.entries.items()}
        out.deferred = {c.clone(): set(ks) for c, ks in self.deferred.items()}
        return out

    def __repr__(self) -> str:
        return f"Map({dict(sorted(self.entries.items(), key=lambda kv: repr(kv[0])))!r})"
