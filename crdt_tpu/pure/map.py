"""Map — CRDT of CRDTs; the composition layer.

Reference: src/map.rs ``Map<K, V: Val<A>, A> { clock, entries: BTreeMap<K,
Entry { clock, val }>, deferred }`` with ``Op::{Nop, Up { dot, key, op },
Rm { clock, keyset }}`` (SURVEY.md §3 row 11, §4.3). Values must satisfy
the ``Val`` contract: cloneable, default-constructible, ``CmRDT`` +
``CvRDT`` + causally removable (removal of a key kills exactly the child
state whose birth dots the remove clock covers — the hardest correctness
surface in the reference).

In Python the ``trait Val<A>`` bound becomes a constructor argument: the
Map holds ``val_default`` (a zero-arg factory, e.g. ``MVReg`` / ``Orswot``
/ a nested ``Map`` factory) playing the role of ``V::default()``.

Composition rule (the causal-composition law from the delta-CRDT
literature — Almeida et al., PAPERS.md; chosen per SURVEY.md §0 since the
mount was empty): the map is a DotMap under one shared causal context
(the map's top clock), and every child is a dot store whose *live birth
dots* are the key's existence witnesses:

    a key is present iff its child holds any live dot.

There is no separate per-entry witness set: for contextless children
(MVReg — a DotFun) the live dots are the content witness dots and merge
is the orswot dot rule under the outer tops (``causal_merge``); for
children with their own top clock (Orswot, nested Map) the ``covered``
invariant keeps child tops equal to the map clock, so their own
``merge`` IS the context-rule join. Either way child survival in a merge
is a pointwise function of birth dots and the two (top, context) pairs —
never of sibling write-clock comparisons at merge time — which makes the
composed merge a true lattice join (commutative, associative, idempotent,
bit-for-bit). The property suite asserts this and the TPU reduction-tree
anti-entropy path requires it (SURVEY.md §7.3 "deterministic reduction").

The earlier design (separate witness dot-sets + MVReg write-clock
domination at merge) was NOT associative: a dominated sibling could be
evicted by a merge, then its dominator removed by a key-remove, leaving
states whose join depended on encounter order. Apply-time domination +
context-rule merge has no such interaction.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, FrozenSet, Iterable, Set, Tuple

from ..ctx import AddCtx, ReadCtx, RmCtx
from ..dot import Dot
from ..traits import CmRDT, CvRDT, ResetRemove
from ..vclock import VClock

from dataclasses import dataclass


@dataclass(frozen=True)
class Nop:
    """Reference: src/map.rs ``Op::Nop``."""


@dataclass(frozen=True)
class Up:
    """Reference: src/map.rs ``Op::Up { dot, key, op }`` — route a child op
    to the entry at ``key``, witnessed by ``dot``."""

    dot: Dot
    key: Any
    op: Any


@dataclass(frozen=True)
class MapRm:
    """Reference: src/map.rs ``Op::Rm { clock, keyset }``."""

    clock: VClock
    keyset: Tuple[Any, ...]


class Map(CvRDT, CmRDT, ResetRemove):
    __slots__ = ("val_default", "clock", "entries", "deferred")

    def __init__(self, val_default: Callable[[], Any]):
        self.val_default = val_default
        self.clock = VClock()
        self.entries: Dict[Any, Any] = {}  # key -> child Val
        self.deferred: Dict[VClock, set] = {}

    # ---- reads ---------------------------------------------------------
    def len(self) -> ReadCtx:
        """Reference: src/map.rs ``Map::len``."""
        return ReadCtx(
            add_clock=self.clock.clone(),
            rm_clock=self.clock.clone(),
            val=len(self.entries),
        )

    def is_empty(self) -> ReadCtx:
        ctx = self.len()
        ctx.val = ctx.val == 0
        return ctx

    def get(self, key: Any) -> ReadCtx:
        """Reference: src/map.rs ``Map::get`` — rm_clock covers the child's
        observed live dots so a derived rm removes exactly the observed
        state."""
        val = self.entries.get(key)
        rm_clock = VClock()
        if val is not None:
            for d in val.live_dots():
                rm_clock.apply(d)
        return ReadCtx(
            add_clock=self.clock.clone(),
            rm_clock=rm_clock,
            val=val.clone() if val is not None else None,
        )

    def keys(self) -> FrozenSet[Any]:
        return frozenset(self.entries)

    # ---- op minting ----------------------------------------------------
    def update(
        self,
        key: Any,
        ctx: AddCtx,
        f: Callable[[Any, AddCtx], Any],
    ) -> Up:
        """Mint an op applying ``f(current_or_default_child, ctx) ->
        child_op`` at ``key``. Reference: src/map.rs ``Map::update``."""
        val = self.entries.get(key)
        val = val.clone() if val is not None else self.val_default()
        child_op = f(val, ctx)
        return Up(dot=ctx.dot, key=key, op=child_op)

    def rm(self, key: Any, ctx: RmCtx) -> MapRm:
        """Reference: src/map.rs ``Map::rm``."""
        return MapRm(clock=ctx.clock.clone(), keyset=(key,))

    def rm_all(self, keys: Iterable[Any], ctx: RmCtx) -> MapRm:
        return MapRm(clock=ctx.clock.clone(), keyset=tuple(keys))

    # ---- CmRDT ---------------------------------------------------------
    def validate_op(self, op) -> None:
        """DotRange unless an Up's dot is the next contiguous event for
        its actor (Rm/Nop always valid — removes carry clocks, not new
        dots). Reference: src/map.rs ``validate_op`` (v7)."""
        if isinstance(op, Up):
            from ..traits import DotRange

            expected = self.clock.get(op.dot.actor) + 1
            if op.dot.counter != expected:
                raise DotRange(op.dot.actor, op.dot.counter, expected)

    def apply(self, op) -> None:
        if isinstance(op, Nop):
            return
        if isinstance(op, Up):
            if self.clock.get(op.dot.actor) >= op.dot.counter:
                return  # already observed this update
            val = self.entries.get(op.key)
            if val is None:
                val = self.val_default()
                val.covered(self.clock)  # adopt the shared context
                self.entries[op.key] = val
            val.apply(op.op)
            self.clock.apply(op.dot)
            self._apply_deferred()
            self._cover_children(dot=op.dot)
            if val.is_bottom() and op.key in self.entries:
                del self.entries[op.key]
        elif isinstance(op, MapRm):
            self._apply_keyset_rm(op.keyset, op.clock)
        else:
            raise TypeError(f"not a Map op: {op!r}")

    def _apply_keyset_rm(self, keyset: Iterable[Any], clock: VClock) -> None:
        """Reference: src/map.rs ``apply_keyset_rm`` — kill the child state
        whose birth dots the rm clock covers; defer if the rm clock is
        ahead of our view."""
        for key in keyset:
            val = self.entries.get(key)
            if val is not None:
                val.remove_dots_under(clock)
                if val.is_bottom():
                    del self.entries[key]
        if not clock <= self.clock:
            self._defer_remove(clock, keyset)

    def _defer_remove(self, clock: VClock, keys: Iterable[Any]) -> None:
        self.deferred.setdefault(clock.clone(), set()).update(keys)

    def _apply_deferred(self) -> None:
        deferred = self.deferred
        self.deferred = {}
        for clock, keys in deferred.items():
            self._apply_keyset_rm(keys, clock)

    # ---- CvRDT ---------------------------------------------------------
    def merge(self, other: "Map") -> None:
        """The DotMap context-rule join (see module docstring). Children
        are joined under the PRE-merge top clocks as contexts; a key
        absent on one side joins as a default child carrying that side's
        context, so state the absent side observed-and-removed dies and
        state it never saw survives."""
        self_ctx = self.clock.clone()
        other_ctx = other.clock.clone()
        for key in set(self.entries) | set(other.entries):
            mine = self.entries.get(key)
            if mine is None:
                mine = self.val_default()
                mine.covered(self_ctx)
            theirs = other.entries.get(key)
            if theirs is None:
                theirs = self.val_default()
                theirs.covered(other_ctx)
            else:
                theirs = theirs.clone()
            mine.causal_merge(theirs, self_ctx, other_ctx)
            if mine.is_bottom():
                self.entries.pop(key, None)
            else:
                self.entries[key] = mine

        for clock, keys in other.deferred.items():
            self._defer_remove(clock, keys)

        self.clock.merge(other.clock)
        self._apply_deferred()
        self._cover_children()

    def _cover_children(self, dot: Dot = None) -> None:
        """Maintain the shared-causal-context invariant: every child's top
        clock equals this map's clock after every top-advancing mutation.
        This is what makes child tops a canonical function of the merged
        state (bit-identical across merge orders) — and it is exact: a dot
        the map has seen either reached this child or proves that absent
        child state born at it was removed (map dots belong to exactly one
        key). The op path advances the clock by exactly one dot, so it
        takes the O(1)-per-child ``covered_dot`` fast path."""
        if dot is not None:
            for val in self.entries.values():
                val.covered_dot(dot)
        else:
            for val in self.entries.values():
                val.covered(self.clock)

    def covered(self, ctx: VClock) -> None:
        """Causal-composition hook for a containing ``Map`` (nested
        maps): absorb the outer context, replay parked removes, recurse."""
        self.clock.merge(ctx)
        self._apply_deferred()
        self._cover_children()

    def covered_dot(self, dot: Dot) -> None:
        """One-dot fast path of ``covered``."""
        self.clock.apply(dot)
        self._apply_deferred()
        self._cover_children(dot=dot)

    # ---- causal composition (the Val contract, for nesting) ------------
    def causal_merge(self, other: "Map", self_ctx: VClock, other_ctx: VClock) -> None:
        """As a child of an outer Map: the ``covered`` invariant keeps
        this map's top equal to the outer context, so the context-rule
        join is plain ``merge``."""
        self.merge(other)

    def live_dots(self) -> Set[Dot]:
        """All birth dots witnessing live state in this map (recursive) —
        what a derived key-remove of this child must cover."""
        out: Set[Dot] = set()
        for val in self.entries.values():
            out |= val.live_dots()
        return out

    def remove_dots_under(self, clock: VClock) -> None:
        """Causal removal for the Val contract: recursively kill child
        state born at dots the clock covers. Leaves this map's own top
        clock and parked removes alone (unlike the standalone
        ``reset_remove``) — inside an outer Map the top tracks the shared
        context (``covered`` invariant)."""
        for key in list(self.entries):
            val = self.entries[key]
            val.remove_dots_under(clock)
            if val.is_bottom():
                del self.entries[key]

    def is_bottom(self) -> bool:
        """True iff no live entries — a containing Map entry holding this
        is dead (its causal history lives on in the outer top clock)."""
        return not self.entries

    # ---- ResetRemove (nested removal, SURVEY §4.3) ---------------------
    def reset_remove(self, clock: VClock) -> None:
        for key in list(self.entries):
            val = self.entries[key]
            val.remove_dots_under(clock)
            if val.is_bottom():
                del self.entries[key]
        deferred = self.deferred
        self.deferred = {}
        for rm_clock, keys in deferred.items():
            rm_clock = rm_clock.clone()
            rm_clock.reset_remove(clock)
            if not rm_clock.is_empty():
                self._defer_remove(rm_clock, keys)
        self.clock.reset_remove(clock)

    # ---- plumbing ------------------------------------------------------
    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Map)
            and self.clock == other.clock
            and self.entries == other.entries
            and {k: frozenset(v) for k, v in self.deferred.items()}
            == {k: frozenset(v) for k, v in other.deferred.items()}
        )

    def clone(self) -> "Map":
        out = Map(self.val_default)
        out.clock = self.clock.clone()
        out.entries = {k: v.clone() for k, v in self.entries.items()}
        out.deferred = {c.clone(): set(ks) for c, ks in self.deferred.items()}
        return out

    def __repr__(self) -> str:
        return f"Map({dict(sorted(self.entries.items(), key=lambda kv: repr(kv[0])))!r})"
