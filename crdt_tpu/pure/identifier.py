"""Identifier — dense, totally-ordered tree-path identifiers.

Reference: src/identifier.rs ``Identifier<T>`` with ``between(lo, hi)``
allocation (SURVEY.md §3 row 12 [LOW-CONF on exact representation]; the
reconstruction here is the LSEQ/Logoot-style design the survey names).

An identifier is a path of ``(index, marker)`` components ordered
lexicographically; ``between`` always finds an identifier strictly between
its bounds by splitting the per-level integer arena and descending a level
when the arena is locally exhausted, so sequence inserts never shift
neighbors. ``marker`` (an ``OrdDot`` for List, the element itself for
GList) makes concurrent allocations at the same spot distinct and
deterministically ordered.

Invariants the property suite asserts:
- ``lo < between(lo, hi, m) < hi`` for every valid ``lo < hi``;
- allocation is deterministic in ``(lo, hi, marker)``;
- final components always carry index >= 1 (index 0 is descend-only),
  which is what guarantees ``between`` can always go below an existing
  identifier without needing marker order.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import total_ordering
from typing import Any, Optional, Tuple

# Per-level index arena. 2^31 slots leaves log2 arena depth ~31 splits
# before a level saturates under pathological (always-same-gap) workloads.
BASE = 1 << 31


@total_ordering
@dataclass(frozen=True)
class Identifier:
    """A dense tree-path identifier: tuple of (index, marker) components."""

    path: Tuple[Tuple[int, Any], ...]

    def __lt__(self, other: "Identifier") -> bool:
        # Lexicographic, comparing markers only on index ties; a strict
        # prefix sorts before its extensions (tuple semantics).
        return self.path < other.path

    def value(self) -> Any:
        """The final component's marker — GList stores the element here.

        Reference: src/identifier.rs ``Identifier::value`` [LOW-CONF].
        """
        return self.path[-1][1]

    def __repr__(self) -> str:
        inner = ".".join(f"{i}:{m!r}" for i, m in self.path)
        return f"Id<{inner}>"


def between(
    lo: Optional[Identifier], hi: Optional[Identifier], marker: Any
) -> Identifier:
    """Allocate an identifier strictly between ``lo`` and ``hi``.

    ``None`` bounds are -inf / +inf. Reference: src/identifier.rs
    ``Identifier::between``.
    """
    lo_p = lo.path if lo is not None else ()
    hi_p = hi.path if hi is not None else ()
    if lo_p and hi_p and not lo_p < hi_p:
        raise ValueError(f"between requires lo < hi, got {lo!r} !< {hi!r}")

    prefix = []
    lo_active = bool(lo_p)
    hi_active = bool(hi_p)
    d = 0
    while True:
        l = lo_p[d] if lo_active and d < len(lo_p) else None
        h = hi_p[d] if hi_active and d < len(hi_p) else None
        h_idx = h[0] if h is not None else BASE

        if l is not None:
            l_idx = l[0]
            if h_idx - l_idx > 1:
                # Room for a fresh final component strictly between.
                prefix.append(((l_idx + h_idx) // 2, marker))
                return Identifier(tuple(prefix))
            # Adjacent or tied: adopt lo's component and descend below hi.
            prefix.append(l)
            if h is None or l < h:
                hi_active = False  # settled strictly below hi at this level
            # l == h keeps both bounds active; l > h cannot happen (lo < hi)
        else:
            # lo is exhausted: any extension of the prefix exceeds it.
            if h_idx >= 2:
                prefix.append((h_idx // 2, marker))
                return Identifier(tuple(prefix))
            if h_idx == 1:
                # Descend-only component; (0, ·) < (1, ·) settles hi.
                prefix.append((0, marker))
                hi_active = False
            else:
                # h is a concrete (0, marker) descend component (final
                # components always have index >= 1): tie with it and keep
                # descending — it is guaranteed to have deeper components.
                prefix.append(h)
        d += 1
