"""GList — grow-only ordered list over dense identifiers.

Reference: src/glist.rs ``GList<T: Ord>`` with ``insert_after`` /
``insert_before`` over ``Identifier<T>`` (SURVEY.md §3 row 14). The element
itself is the identifier's final marker, so the list is a plain ordered set
of identifiers; merge is set union.
"""

from __future__ import annotations

import bisect
from dataclasses import dataclass
from typing import Any, List, Optional

from ..traits import CmRDT, CvRDT
from .identifier import Identifier, between


@dataclass(frozen=True)
class Insert:
    """Reference: src/glist.rs ``Op::Insert { id }``."""

    id: Identifier


class GList(CvRDT, CmRDT):
    __slots__ = ("list",)

    def __init__(self):
        self.list: List[Identifier] = []  # sorted, unique

    # ---- op minting ----------------------------------------------------
    def insert_after(self, anchor: Optional[Identifier], elem: Any) -> Insert:
        """Mint an insert placing ``elem`` directly after ``anchor``
        (``None`` = front). Reference: src/glist.rs ``insert_after``."""
        if anchor is None:
            hi = self.list[0] if self.list else None
            return Insert(id=between(None, hi, elem))
        ix = bisect.bisect_right(self.list, anchor)
        hi = self.list[ix] if ix < len(self.list) else None
        return Insert(id=between(anchor, hi, elem))

    def insert_before(self, anchor: Optional[Identifier], elem: Any) -> Insert:
        """Reference: src/glist.rs ``insert_before`` (``None`` = back)."""
        if anchor is None:
            lo = self.list[-1] if self.list else None
            return Insert(id=between(lo, None, elem))
        ix = bisect.bisect_left(self.list, anchor)
        lo = self.list[ix - 1] if ix > 0 else None
        return Insert(id=between(lo, anchor, elem))

    # ---- CmRDT / CvRDT -------------------------------------------------
    def apply(self, op: Insert) -> None:
        ix = bisect.bisect_left(self.list, op.id)
        if ix == len(self.list) or self.list[ix] != op.id:
            self.list.insert(ix, op.id)

    def merge(self, other: "GList") -> None:
        # Both sides are sorted and unique: linear two-pointer union.
        if not other.list:
            return
        out = []
        i = j = 0
        mine, theirs = self.list, other.list
        while i < len(mine) and j < len(theirs):
            if mine[i] < theirs[j]:
                out.append(mine[i]); i += 1
            elif theirs[j] < mine[i]:
                out.append(theirs[j]); j += 1
            else:
                out.append(mine[i]); i += 1; j += 1
        out.extend(mine[i:])
        out.extend(theirs[j:])
        self.list = out

    # ---- reads ---------------------------------------------------------
    def read(self) -> List[Any]:
        """Element values in order. Reference: src/glist.rs iter/read."""
        return [ident.value() for ident in self.list]

    def get(self, ix: int) -> Optional[Identifier]:
        return self.list[ix] if 0 <= ix < len(self.list) else None

    def first(self) -> Optional[Identifier]:
        return self.list[0] if self.list else None

    def last(self) -> Optional[Identifier]:
        return self.list[-1] if self.list else None

    def __len__(self) -> int:
        return len(self.list)

    def __eq__(self, other) -> bool:
        return isinstance(other, GList) and self.list == other.list

    def clone(self) -> "GList":
        out = GList()
        out.list = list(self.list)
        return out

    def __repr__(self) -> str:
        return f"GList({self.read()!r})"
