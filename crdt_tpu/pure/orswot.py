"""ORSWOT — Observed-Remove Set WithOut Tombstones. The flagship type.

Reference: src/orswot.rs ``Orswot<M, A> { clock: VClock<A>, entries:
BTreeMap<M, VClock<A>>, deferred: HashMap<VClock<A>, BTreeSet<M>> }``
(SURVEY.md §3 row 10, §4.1–4.2). Merge rule: an entry survives iff its
birth clock has dots unseen by the other replica's top clock, or it is
present on both sides (then the birth clocks join the orswot way);
tombstone-free because the top clock subsumes removal history. Removal ops
whose clock is ahead of the local view are parked in ``deferred`` and
replayed when the clock catches up.

``crdt_tpu.models.orswot`` / ``crdt_tpu.ops.orswot`` carry the batched
device form of this exact lattice.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, FrozenSet, Iterable, Tuple

from ..ctx import AddCtx, ReadCtx, RmCtx
from ..dot import Dot
from ..traits import CmRDT, CvRDT, DotRange, ResetRemove
from ..vclock import VClock


@dataclass(frozen=True)
class Add:
    """Reference: src/orswot.rs ``Op::Add { dot, members }``."""

    dot: Dot
    members: Tuple[Any, ...]


@dataclass(frozen=True)
class Rm:
    """Reference: src/orswot.rs ``Op::Rm { clock, members }``."""

    clock: VClock
    members: Tuple[Any, ...]


class Orswot(CvRDT, CmRDT, ResetRemove):
    __slots__ = ("clock", "entries", "deferred")

    def __init__(self):
        self.clock = VClock()
        # member -> birth clock (the dots that added it, minus removed ones)
        self.entries: Dict[Any, VClock] = {}
        # rm clock -> members, for removes ahead of our causal view
        self.deferred: Dict[VClock, set] = {}

    # ---- reads ---------------------------------------------------------
    def read(self) -> ReadCtx:
        """Reference: src/orswot.rs ``Orswot::read``."""
        return ReadCtx(
            add_clock=self.clock.clone(),
            rm_clock=self.clock.clone(),
            val=frozenset(self.entries),
        )

    def contains(self, member: Any) -> ReadCtx:
        """Reference: src/orswot.rs ``Orswot::contains`` — rm_clock is the
        member's birth clock so a derived rm covers exactly the observed
        adds."""
        entry = self.entries.get(member)
        return ReadCtx(
            add_clock=self.clock.clone(),
            rm_clock=entry.clone() if entry is not None else VClock(),
            val=member in self.entries,
        )

    # ---- op minting (pure; reference returns the Op, caller applies) ---
    def add(self, member: Any, ctx: AddCtx) -> Add:
        """Reference: src/orswot.rs ``Orswot::add``."""
        return Add(dot=ctx.dot, members=(member,))

    def add_all(self, members: Iterable[Any], ctx: AddCtx) -> Add:
        return Add(dot=ctx.dot, members=tuple(members))

    def rm(self, member: Any, ctx: RmCtx) -> Rm:
        """Reference: src/orswot.rs ``Orswot::rm``."""
        return Rm(clock=ctx.clock.clone(), members=(member,))

    def rm_all(self, members: Iterable[Any], ctx: RmCtx) -> Rm:
        return Rm(clock=ctx.clock.clone(), members=tuple(members))

    # ---- CmRDT ---------------------------------------------------------
    def validate_op(self, op) -> None:
        """Adds must carry the actor's next contiguous dot.

        Reference: src/orswot.rs ``validate_op`` → DotRange (SURVEY §4.1).
        """
        if isinstance(op, Add):
            seen = self.clock.get(op.dot.actor)
            if op.dot.counter != seen + 1:
                raise DotRange(op.dot.actor, op.dot.counter, seen + 1)

    def apply(self, op) -> None:
        if isinstance(op, Add):
            if self.clock.get(op.dot.actor) >= op.dot.counter:
                return  # already observed this dot
            for member in op.members:
                entry = self.entries.setdefault(member, VClock())
                entry.apply(op.dot)
            self.clock.apply(op.dot)
            self._apply_deferred()
        elif isinstance(op, Rm):
            self._apply_rm(op.members, op.clock)
        else:
            raise TypeError(f"not an Orswot op: {op!r}")

    def _apply_rm(self, members: Iterable[Any], clock: VClock) -> None:
        """Reference: src/orswot.rs ``apply_rm`` — defer if the rm clock is
        ahead of our view (covers adds we haven't seen), and remove the
        dominated part of what we do have now."""
        if not clock <= self.clock:
            self._defer_remove(clock, members)
        for member in members:
            entry = self.entries.get(member)
            if entry is not None:
                entry.reset_remove(clock)
                if entry.is_empty():
                    del self.entries[member]

    def _defer_remove(self, clock: VClock, members: Iterable[Any]) -> None:
        key = clock.clone()
        self.deferred.setdefault(key, set()).update(members)

    def _apply_deferred(self) -> None:
        """Reference: src/orswot.rs ``apply_deferred`` — replay parked
        removes; still-ahead ones re-defer themselves."""
        deferred = self.deferred
        self.deferred = {}
        for clock, members in deferred.items():
            self._apply_rm(members, clock)

    # ---- CvRDT (THE hot loop — SURVEY §4.2) ----------------------------
    def merge(self, other: "Orswot") -> None:
        # Entries we have and they don't: they either removed them (birth
        # clock dominated by their top) or never saw them (keep the unseen
        # dots only).
        for member in list(self.entries):
            if member not in other.entries:
                clock = self.entries[member]
                if clock <= other.clock:
                    del self.entries[member]
                else:
                    clock.reset_remove(other.clock)

        for member, their_clock in other.entries.items():
            our_clock = self.entries.get(member)
            if our_clock is not None:
                # Present on both sides: keep common dots plus each side's
                # dots the other side has never seen.
                common = their_clock.glb(our_clock)
                common.merge(their_clock.clone_without(self.clock))
                common.merge(our_clock.clone_without(other.clock))
                if common.is_empty():
                    del self.entries[member]
                else:
                    self.entries[member] = common
            else:
                if their_clock <= self.clock:
                    pass  # we observed those adds and removed the member
                else:
                    kept = their_clock.clone_without(self.clock)
                    self.entries[member] = kept

        for clock, members in other.deferred.items():
            self._defer_remove(clock, members)

        self.clock.merge(other.clock)
        self._apply_deferred()

    # ---- ResetRemove ---------------------------------------------------
    def reset_remove(self, clock: VClock) -> None:
        """Reference: src/orswot.rs ``ResetRemove`` impl."""
        for member in list(self.entries):
            entry = self.entries[member]
            entry.reset_remove(clock)
            if entry.is_empty():
                del self.entries[member]
        deferred = self.deferred
        self.deferred = {}
        for rm_clock, members in deferred.items():
            rm_clock = rm_clock.clone()
            rm_clock.reset_remove(clock)
            if not rm_clock.is_empty():
                self._defer_remove(rm_clock, members)
        self.clock.reset_remove(clock)

    def covered(self, ctx: VClock) -> None:
        """Causal-composition hook for a containing ``Map``: absorb the
        map's causal context into the top clock (the composed document has
        ONE context — every dot the map has seen was either routed to this
        child or proves absence-means-removed for it), then replay parked
        removes the wider context may have enabled."""
        self.clock.merge(ctx)
        self._apply_deferred()

    def covered_dot(self, dot: Dot) -> None:
        """One-dot fast path of ``covered``."""
        self.clock.apply(dot)
        self._apply_deferred()

    # ---- causal composition (the Val contract for Map) -----------------
    def causal_merge(self, other: "Orswot", self_ctx: VClock, other_ctx: VClock) -> None:
        """As a Map child: the ``covered`` invariant keeps this set's top
        equal to the outer context, so the context-rule join is plain
        ``merge`` (see pure/map.py module docstring)."""
        self.merge(other)

    def live_dots(self):
        """Per-actor-max birth dots of all live members — the covering set
        a derived key-remove of this child must dominate."""
        out = set()
        for entry in self.entries.values():
            for a, c in entry.dots.items():
                out.add(Dot(a, c))
        return out

    def remove_dots_under(self, clock: VClock) -> None:
        """Causal removal for the Val contract: kill member birth dots the
        clock covers. Unlike the standalone ``reset_remove`` this leaves
        the top clock (and parked removes) alone — inside a Map the top
        tracks the shared context (``covered`` invariant), and its
        coverage of the killed dots is exactly what encodes
        observed-and-removed for later merges."""
        for member in list(self.entries):
            entry = self.entries[member]
            entry.reset_remove(clock)
            if entry.is_empty():
                del self.entries[member]

    def is_bottom(self) -> bool:
        """True iff no live members — a Map entry holding this is dead
        (its causal history lives on in the outer top clock)."""
        return not self.entries

    # ---- plumbing ------------------------------------------------------
    def members(self) -> FrozenSet[Any]:
        return frozenset(self.entries)

    def __len__(self) -> int:
        return len(self.entries)

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Orswot)
            and self.clock == other.clock
            and self.entries == other.entries
            and {k: frozenset(v) for k, v in self.deferred.items()}
            == {k: frozenset(v) for k, v in other.deferred.items()}
        )

    def __hash__(self):
        return hash((self.clock, frozenset(self.entries)))

    def clone(self) -> "Orswot":
        out = Orswot()
        out.clock = self.clock.clone()
        out.entries = {m: c.clone() for m, c in self.entries.items()}
        out.deferred = {c.clone(): set(ms) for c, ms in self.deferred.items()}
        return out

    def __repr__(self) -> str:
        return f"Orswot({sorted(map(repr, self.entries))}, clock={self.clock!r})"
