"""G-Set — grow-only set; the simplest lattice (union).

Reference: src/gset.rs ``GSet<M: Ord> { value: BTreeSet<M> }``; Op = M;
merge = set union (SURVEY.md §3 row 7).
"""

from __future__ import annotations

from typing import Any, FrozenSet, Iterable, Optional, Set

from ..traits import CmRDT, CvRDT


class GSet(CvRDT, CmRDT):
    __slots__ = ("value",)

    def __init__(self, value: Optional[Iterable[Any]] = None):
        self.value: Set[Any] = set(value) if value is not None else set()

    def insert(self, member: Any) -> Any:
        """Insert locally and return the op (the member itself).

        Reference: src/gset.rs ``GSet::insert``; CmRDT Op = M.
        """
        self.value.add(member)
        return member

    def apply(self, op: Any) -> None:
        self.value.add(op)

    def merge(self, other: "GSet") -> None:
        self.value |= other.value

    def contains(self, member: Any) -> bool:
        return member in self.value

    def read(self) -> FrozenSet[Any]:
        return frozenset(self.value)

    def __len__(self) -> int:
        return len(self.value)

    def __eq__(self, other) -> bool:
        return isinstance(other, GSet) and self.value == other.value

    def __hash__(self):
        return hash(frozenset(self.value))

    def clone(self) -> "GSet":
        return GSet(set(self.value))

    def __repr__(self) -> str:
        return f"GSet({sorted(map(repr, self.value))})"
