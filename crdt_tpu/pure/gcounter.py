"""G-Counter — grow-only counter as a thin VClock wrapper.

Reference: src/gcounter.rs ``GCounter<A> { inner: VClock<A> }``; Op = Dot
(SURVEY.md §3 row 5).
"""

from __future__ import annotations

from typing import Any, Optional

from ..dot import Dot
from ..traits import CmRDT, CvRDT
from ..vclock import VClock


class GCounter(CvRDT, CmRDT):
    __slots__ = ("inner",)

    def __init__(self, inner: Optional[VClock] = None):
        self.inner = inner if inner is not None else VClock()

    def inc(self, actor: Any) -> Dot:
        """Mint (not apply) the op incrementing this actor's count by one.

        Reference: src/gcounter.rs ``GCounter::inc``.
        """
        return self.inner.inc(actor)

    def inc_many(self, actor: Any, steps: int) -> Dot:
        """Mint the op advancing ``actor`` by ``steps`` at once.

        Reference: src/gcounter.rs ``GCounter::inc_many`` [LOW-CONF name]:
        dots are per-actor contiguous so a jump of ``steps`` is one dot.
        """
        return Dot(actor, self.inner.get(actor) + steps)

    def validate_op(self, op: Dot) -> None:
        """Reference: src/gcounter.rs ``validate_op`` (delegates to the
        inner clock's dot-contiguity check)."""
        self.inner.validate_op(op)

    def apply(self, op: Dot) -> None:
        self.inner.apply(op)

    def merge(self, other: "GCounter") -> None:
        self.inner.merge(other.inner)

    def read(self) -> int:
        """Sum of all per-actor counters. Reference: src/gcounter.rs read."""
        return sum(self.inner.dots.values())

    def __eq__(self, other) -> bool:
        return isinstance(other, GCounter) and self.inner == other.inner

    def __hash__(self):
        return hash(self.inner)

    def clone(self) -> "GCounter":
        return GCounter(self.inner.clone())

    def __repr__(self) -> str:
        return f"GCounter({self.read()}, {self.inner!r})"
