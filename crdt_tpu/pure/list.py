"""List — RGA-style sequence CRDT for collaborative editing.

Reference: src/list.rs ``List<T, A>`` — an ordered sequence keyed by
``Identifier<OrdDot<A>>`` with ``Op::Insert { id, val }`` / ``Op::Delete
{ id, dot }`` (SURVEY.md §3 row 13, §4.5). Op-based only (no CvRDT): a
delete leaves no tombstone, so convergence relies on causal delivery of
ops — matching the reference's trait surface (§3.2: CmRDT includes List,
CvRDT does not).

The automerge-perf edit-trace benchmark (BASELINE config 5) drives
``insert_index`` / ``delete_index``.
"""

from __future__ import annotations

import bisect
from dataclasses import dataclass
from typing import Any, Iterator, List as PyList, Optional, Tuple

from ..dot import Dot, OrdDot
from ..traits import CmRDT, DotRange, ValidationError
from ..vclock import VClock
from .identifier import Identifier, between


@dataclass(frozen=True)
class Insert:
    """Reference: src/list.rs ``Op::Insert { id, val }``."""

    id: Identifier
    val: Any

    @property
    def dot(self) -> Dot:
        """The dot minted for this insert (the id's final marker)."""
        marker: OrdDot = self.id.value()
        return marker.to_dot()


@dataclass(frozen=True)
class Delete:
    """Reference: src/list.rs ``Op::Delete { id, dot }``."""

    id: Identifier
    dot: Dot


class List(CmRDT):
    __slots__ = ("seq", "vals", "clock")

    def __init__(self):
        self.seq: PyList[Identifier] = []  # sorted identifiers
        self.vals: dict = {}  # identifier -> element
        self.clock = VClock()

    # ---- op minting ----------------------------------------------------
    def insert_index(self, ix: int, val: Any, actor: Any) -> Insert:
        """Mint an insert at position ``ix`` (clamped to [0, len]).

        Reference: src/list.rs ``List::insert_index`` — find the neighbor
        identifiers and allocate densely between them (§4.5); no index
        shifting ever happens.
        """
        if ix < 0 or ix > len(self.seq):
            raise IndexError(f"insert index {ix} out of range 0..{len(self.seq)}")
        lo = self.seq[ix - 1] if ix > 0 else None
        hi = self.seq[ix] if ix < len(self.seq) else None
        dot = self.clock.inc(actor)
        ident = between(lo, hi, OrdDot.from_dot(dot))
        return Insert(id=ident, val=val)

    def append(self, val: Any, actor: Any) -> Insert:
        """Reference: src/list.rs ``List::append``."""
        return self.insert_index(len(self.seq), val, actor)

    def delete_index(self, ix: int, actor: Any) -> Optional[Delete]:
        """Reference: src/list.rs ``List::delete_index``."""
        if ix < 0 or ix >= len(self.seq):
            return None
        dot = self.clock.inc(actor)
        return Delete(id=self.seq[ix], dot=dot)

    # ---- CmRDT ---------------------------------------------------------
    def validate_op(self, op) -> None:
        """v7 validation parity (reference: src/traits.rs ``CmRDT::
        validate_op``; SURVEY.md §3.2 "the same set + List"):

        - ``Insert``: the id's minted dot must be the actor's next
          contiguous event (a duplicate identifier IS a duplicate dot —
          the id embeds it — so dup inserts are caught here too);
        - ``Delete``: the delete's own dot must be contiguous, and the
          TARGET id's dot must already be observed — deleting an insert
          this replica never saw breaks the causal-delivery assumption
          the tombstone-free design relies on (both → DotRange)."""
        if isinstance(op, Insert):
            seen = self.clock.get(op.dot.actor)
            if op.dot.counter != seen + 1:
                raise DotRange(op.dot.actor, op.dot.counter, seen + 1)
        elif isinstance(op, Delete):
            seen = self.clock.get(op.dot.actor)
            if op.dot.counter != seen + 1:
                raise DotRange(op.dot.actor, op.dot.counter, seen + 1)
            target: OrdDot = op.id.value()
            tdot = target.to_dot()
            observed = self.clock.get(tdot.actor)
            if tdot.counter > observed:
                raise DotRange(tdot.actor, tdot.counter, observed)
        else:
            raise ValidationError(f"not a List op: {op!r}")

    def apply(self, op) -> None:
        if isinstance(op, Insert):
            if op.id not in self.vals:
                bisect.insort(self.seq, op.id)
                self.vals[op.id] = op.val
            self.clock.apply(op.dot)
        elif isinstance(op, Delete):
            if op.id in self.vals:
                ix = bisect.bisect_left(self.seq, op.id)
                del self.seq[ix]
                del self.vals[op.id]
            self.clock.apply(op.dot)
        else:
            raise TypeError(f"not a List op: {op!r}")

    # ---- reads ---------------------------------------------------------
    def read(self) -> PyList[Any]:
        return [self.vals[i] for i in self.seq]

    def position(self, ident: Identifier) -> Optional[int]:
        """Index of ``ident`` in the sequence. Reference: src/list.rs
        ``List::position``."""
        ix = bisect.bisect_left(self.seq, ident)
        if ix < len(self.seq) and self.seq[ix] == ident:
            return ix
        return None

    def get(self, ix: int) -> Optional[Any]:
        return self.vals[self.seq[ix]] if 0 <= ix < len(self.seq) else None

    def iter_entries(self) -> Iterator[Tuple[Identifier, Any]]:
        return ((i, self.vals[i]) for i in self.seq)

    def __len__(self) -> int:
        return len(self.seq)

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, List)
            and self.seq == other.seq
            and self.vals == other.vals
        )

    def clone(self) -> "List":
        out = List()
        out.seq = list(self.seq)
        out.vals = dict(self.vals)
        out.clock = self.clock.clone()
        return out

    def __repr__(self) -> str:
        return f"List({self.read()!r})"
