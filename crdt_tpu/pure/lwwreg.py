"""LWW register — last-writer-wins with a caller-supplied total marker.

Reference: src/lwwreg.rs ``LWWReg<V, M: Ord> { val, marker }``; update keeps
the max marker; merging equal markers guarding different values is a
validation error (SURVEY.md §3 row 8).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

from ..traits import CmRDT, ConflictingMarker, CvRDT


@dataclass(frozen=True)
class LWWOp:
    """Op-based form: ship (marker, value). Reference: src/lwwreg.rs — the
    CmRDT Op for LWWReg is the update itself [LOW-CONF exact shape]."""

    val: Any
    marker: Any


class _Unset:
    """Sentinel for a never-written register (the reference constructs
    LWWReg with an initial value; the default constructor is our
    addition, and a stored ``None`` must stay distinguishable)."""

    def __repr__(self):
        return "<unset>"


UNSET = _Unset()


class _Bottom:
    """Marker that sorts strictly below every other marker, preserving the
    reference's ``M: Ord`` genericity (string/tuple/float markers all
    work against a fresh register). Comparisons rely on Python's
    reflected-operator fallback: ``marker > BOTTOM`` resolves via
    ``BOTTOM.__lt__``."""

    def __lt__(self, other):
        return not isinstance(other, _Bottom)

    def __le__(self, other):
        return True

    def __gt__(self, other):
        return False

    def __ge__(self, other):
        return isinstance(other, _Bottom)

    def __eq__(self, other):
        return isinstance(other, _Bottom)

    def __hash__(self):
        return hash("_Bottom")

    def __repr__(self):
        return "<bottom>"


BOTTOM = _Bottom()


class LWWReg(CvRDT, CmRDT):
    __slots__ = ("val", "marker")

    def __init__(self, val: Any = UNSET, marker: Any = BOTTOM):
        self.val = val
        self.marker = marker

    def update(self, val: Any, marker: Any) -> LWWOp:
        """Take (val, marker) iff marker is strictly newer; equal markers
        keep the incumbent (idempotent replay of the same write is a no-op,
        and conflicting same-marker writes are caught by validation).

        Reference: src/lwwreg.rs ``LWWReg::update``.
        """
        if marker > self.marker or (self.val is UNSET and self.marker == marker):
            self.val = val
            self.marker = marker
        return LWWOp(val=val, marker=marker)

    def validate_update(self, val: Any, marker: Any) -> None:
        """Reference: src/lwwreg.rs validation — equal marker guarding a
        different value is a conflict."""
        if marker == self.marker and self.val is not UNSET and val != self.val:
            raise ConflictingMarker(
                f"marker {marker!r} already guards {self.val!r}, got {val!r}"
            )

    def apply(self, op: LWWOp) -> None:
        self.update(op.val, op.marker)

    def validate_op(self, op: LWWOp) -> None:
        self.validate_update(op.val, op.marker)

    def merge(self, other: "LWWReg") -> None:
        self.update(other.val, other.marker)

    def validate_merge(self, other: "LWWReg") -> None:
        self.validate_update(other.val, other.marker)

    def read(self) -> Any:
        return None if self.val is UNSET else self.val

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, LWWReg)
            and self.val == other.val
            and self.marker == other.marker
        )

    def __hash__(self):
        return hash((self.val, self.marker))

    def clone(self) -> "LWWReg":
        return LWWReg(self.val, self.marker)

    def __repr__(self) -> str:
        return f"LWWReg({self.val!r} @ {self.marker!r})"
