"""MV register — concurrent writes survive as siblings until resolved.

Reference: src/mvreg.rs ``MVReg<V, A> { vals: Vec<Content { clock, val }>
}``; ``write(val, AddCtx) -> Op::Put``; ``read() -> ReadCtx<Vec<V>>``;
merge/apply discard dominated values, keep concurrent siblings (SURVEY.md
§3 row 9, §4.4).

Representation deviation (documented per SURVEY.md §0): contents are keyed
by their *witness dot* (the AddCtx dot that minted the write) alongside
the full write clock — the DotFun form from the delta-CRDT literature
(Almeida et al., "Delta State Replicated Data Types", PAPERS.md). The
observable semantics (dominance filtering, sibling survival) are the
reference's; the witness dot is what lets a containing ``Map`` compose
this register causally (``causal_merge`` / ``remove_dots_under`` /
``live_dots`` — the content dots double as the key's existence
witnesses), which keeps the composed merge a true lattice join (see
pure/map.py).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Tuple

from ..ctx import AddCtx, ReadCtx
from ..dot import Dot
from ..traits import CmRDT, CvRDT, DotRange, ResetRemove, ValidationError
from ..vclock import VClock


@dataclass(frozen=True)
class Put:
    """Reference: src/mvreg.rs ``Op::Put { clock, val }`` (+ witness dot)."""

    dot: Dot
    clock: VClock
    val: Any


class MVReg(CvRDT, CmRDT, ResetRemove):
    __slots__ = ("vals",)

    def __init__(self, vals: Dict[Dot, Tuple[VClock, Any]] = None):
        # witness dot -> (write clock, value)
        self.vals: Dict[Dot, Tuple[VClock, Any]] = dict(vals) if vals else {}

    # ---- reads ---------------------------------------------------------
    def read(self) -> ReadCtx:
        """All concurrent values + the joined clock of their writes.

        Reference: src/mvreg.rs ``MVReg::read``.
        """
        clock = self.clock()
        return ReadCtx(
            add_clock=clock,
            rm_clock=clock.clone(),
            val=[v for _, v in self.vals.values()],
        )

    def clock(self) -> VClock:
        """Join of all content clocks. Reference: src/mvreg.rs clock."""
        out = VClock()
        for c, _ in self.vals.values():
            out.merge(c)
        return out

    # ---- writes --------------------------------------------------------
    def write(self, val: Any, ctx: AddCtx) -> Put:
        """Mint the op writing ``val`` under the read context's clock.

        Reference: src/mvreg.rs ``MVReg::write`` — the AddCtx clock already
        contains the fresh dot, so the put dominates everything read.
        """
        return Put(dot=ctx.dot, clock=ctx.clock.clone(), val=val)

    def validate_op(self, op: Put) -> None:
        """v7 validation parity (reference: src/traits.rs ``CmRDT::
        validate_op``; SURVEY.md §3.2 "the same set + List"): a Put must
        be well-formed — its clock contains its own witness dot as the
        minter's latest self-event (every AddCtx mints exactly that) —
        and its dot must be the minter's next contiguous event against
        this register's observed clock (duplicate or gapped → DotRange,
        exactly the orswot Add rule)."""
        if not isinstance(op, Put):
            raise ValidationError(f"not an MVReg op: {op!r}")
        if op.clock.get(op.dot.actor) != op.dot.counter:
            raise ValidationError(
                f"malformed Put: clock {op.clock!r} does not carry its own "
                f"witness dot {op.dot!r}"
            )
        expected = self.clock().get(op.dot.actor) + 1
        if op.dot.counter != expected:
            raise DotRange(op.dot.actor, op.dot.counter, expected)

    def apply(self, op: Put) -> None:
        if op.clock.is_empty():
            return
        if any(c >= op.clock for c, _ in self.vals.values()):
            return  # dominated or duplicate
        self.vals = {
            d: (c, v) for d, (c, v) in self.vals.items() if not c < op.clock
        }
        self.vals[op.dot] = (op.clock, op.val)

    def merge(self, other: "MVReg") -> None:
        keep_self = {
            d: (c, v)
            for d, (c, v) in self.vals.items()
            if not any(c < oc for oc, _ in other.vals.values())
        }
        keep_other = {
            d: (oc, ov)
            for d, (oc, ov) in other.vals.items()
            if not any(oc < c for c, _ in self.vals.values())
        }
        keep_self.update(keep_other)  # same dot => same content
        self.vals = keep_self

    def reset_remove(self, clock: VClock) -> None:
        """Reference: src/mvreg.rs ``ResetRemove`` — forget contents whose
        write is fully dominated by ``clock``."""
        self.vals = {
            d: (c, v) for d, (c, v) in self.vals.items() if not c <= clock
        }

    def covered(self, ctx: VClock) -> None:
        """Causal-composition hook for ``Map``: MVReg holds no top clock,
        so absorbing the shared causal context is a no-op."""

    def covered_dot(self, dot) -> None:
        """One-dot fast path of ``covered`` — also a no-op."""

    # ---- causal composition (the Val contract for Map) -----------------
    def causal_merge(self, other: "MVReg", self_ctx: VClock, other_ctx: VClock) -> None:
        """Join as a DotFun under shared causal contexts (the containing
        Map's top clocks): a content survives iff both sides hold its
        witness dot, or one side holds it and the other's context never
        saw it (the orswot dot rule — a true lattice join). Write-clock
        domination is NOT applied here: a put evicts dominated siblings
        at apply time on every replica that delivers it (causal delivery
        guarantees the dominated put arrived first), and the context rule
        propagates those evictions — applying domination at merge time
        instead is order-dependent and breaks associativity."""
        keep = {}
        for d, cv in self.vals.items():
            if d in other.vals or d.counter > other_ctx.get(d.actor):
                keep[d] = cv
        for d, cv in other.vals.items():
            if d in self.vals or d.counter > self_ctx.get(d.actor):
                keep[d] = cv
        self.vals = keep

    def remove_dots_under(self, clock: VClock) -> None:
        """Causal removal for the Val contract: drop contents whose
        witness dot the remove clock covers (dot-level, unlike the
        standalone ``reset_remove`` which compares full write clocks)."""
        self.vals = {
            d: cv
            for d, cv in self.vals.items()
            if d.counter > clock.get(d.actor)
        }

    def live_dots(self):
        """The live content witness dots — the covering set a derived
        key-remove of this register must dominate."""
        return set(self.vals)

    def is_bottom(self) -> bool:
        """True iff no live content — a Map entry holding this is dead."""
        return not self.vals

    # ---- plumbing ------------------------------------------------------
    def __eq__(self, other) -> bool:
        if not isinstance(other, MVReg):
            return NotImplemented
        return self.vals == other.vals

    def __hash__(self):
        return hash(frozenset((d, c) for d, (c, _) in self.vals.items()))

    def clone(self) -> "MVReg":
        return MVReg({d: (c.clone(), v) for d, (c, v) in self.vals.items()})

    def __repr__(self) -> str:
        inner = {d: v for d, (_, v) in self.vals.items()}
        return f"MVReg({inner!r})"
