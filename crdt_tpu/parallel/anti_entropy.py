"""Mesh-level anti-entropy entry points.

These wrap the in-``shard_map`` collectives (collectives.py) into
device-count-agnostic calls: hand them a batched state [R, ...] and a
mesh, get back the converged lattice join — the TPU replacement for the
reference's "serialize state, caller transports bytes, merge on arrival"
loop (SURVEY.md §4.2 anti-entropy path).

``check_vma=False`` on every shard_map: the outputs *are* replicated
over the reduced axes (the join is idempotent and the overflow flags are
psum-reduced), but the static replication checker cannot see that
through ``ppermute``-based recursive doubling.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..ops import orswot as ops
from ..ops.orswot import OrswotState
from .collectives import all_reduce_clock, all_reduce_join, ring_round
from .mesh import (
    ELEMENT_AXIS,
    REPLICA_AXIS,
    orswot_out_specs,
    orswot_specs,
    pad_elements,
    pad_replicas,
)


def mesh_fold(state: OrswotState, mesh: Mesh) -> Tuple[OrswotState, jax.Array]:
    """Full-mesh anti-entropy over the device mesh: every replica's state
    joined into one converged state, in one collective round.

    Plan: fold the device-local replica block in a log2 tree (pure local
    compute), then one lattice-join all-reduce across the ``replica``
    mesh axis. Element shards never communicate — the join is
    element-parallel (mesh.py). Returns (converged state [no replica
    axis, element-sharded], overflow flag).
    """
    state = pad_replicas(state, mesh.shape[REPLICA_AXIS])
    state = pad_elements(state, mesh.shape[ELEMENT_AXIS])

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(orswot_specs(),),
        out_specs=(orswot_out_specs(), P()),
        check_vma=False,
    )
    def fold_fn(local):
        folded, of_local = ops.fold(local)
        joined, of_cross = all_reduce_join(folded, REPLICA_AXIS)
        of = (lax.psum(of_local.astype(jnp.int32), REPLICA_AXIS) > 0) | of_cross
        return joined, of

    return fold_fn(state)


def mesh_gossip(
    state: OrswotState, mesh: Mesh, rounds: Optional[int] = None
) -> Tuple[OrswotState, jax.Array]:
    """Ring anti-entropy: each device folds its local replica block, then
    runs ``rounds`` unit-shift gossip rounds (default P-1, which fully
    converges the ring). Bandwidth per round is one state per ICI link —
    the bounded-traffic mode for DCN-crossing replica axes.

    Returns (per-device states [P, ...], overflow): with the default
    round count every row equals the full join.
    """
    rsize = mesh.shape[REPLICA_AXIS]
    if rounds is None:
        rounds = rsize - 1
    state = pad_replicas(state, rsize)
    state = pad_elements(state, mesh.shape[ELEMENT_AXIS])

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(orswot_specs(),),
        out_specs=(orswot_specs(), P()),
        check_vma=False,
    )
    def gossip_fn(local):
        folded, of = ops.fold(local)
        for _ in range(rounds):
            folded, of_r = ring_round(folded, REPLICA_AXIS, reduce_overflow=False)
            of = of | of_r
        of = lax.psum(of.astype(jnp.int32), REPLICA_AXIS) > 0
        return jax.tree.map(lambda x: x[None], folded), of

    return gossip_fn(state)


def mesh_fold_clocks(clocks: jax.Array, mesh: Mesh) -> jax.Array:
    """Converge a batch of vector clocks [R, A] (VClock / GCounter /
    PNCounter states) over the mesh: local max + ``pmax`` across the
    replica axis. BASELINE configs 1–2 at mesh scale."""
    rsize = mesh.shape[REPLICA_AXIS]
    r = clocks.shape[0]
    pad = (-r) % rsize
    if pad:
        clocks = jnp.concatenate(
            [clocks, jnp.zeros((pad, clocks.shape[1]), clocks.dtype)], axis=0
        )

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(REPLICA_AXIS, None),),
        out_specs=P(None),
        check_vma=False,
    )
    def fold_fn(local):
        return all_reduce_clock(jnp.max(local, axis=0), REPLICA_AXIS)

    return fold_fn(clocks)
