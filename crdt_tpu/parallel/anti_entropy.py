"""Mesh-level anti-entropy entry points.

These wrap the in-``shard_map`` collectives (collectives.py) into
device-count-agnostic calls: hand them a batched state [R, ...] and a
mesh, get back the converged lattice join — the TPU replacement for the
reference's "serialize state, caller transports bytes, merge on arrival"
loop (SURVEY.md §4.2 anti-entropy path).

``check_vma=False`` on every shard_map: the outputs *are* replicated
over the reduced axes (the join is idempotent and the overflow flags are
psum-reduced), but the static replication checker cannot see that
through ``ppermute``-based recursive doubling.

Entry points memoise their ``shard_map`` closures per (mesh, input
shapes) — without this every call re-traces and re-lowers the whole
collective program, which costs seconds per anti-entropy round.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..ops import map as map_ops
from ..ops import map_map as nested_ops
from ..ops import map_orswot as mo_ops
from ..ops import orswot as ops
from ..ops.map import MapState
from ..ops.map_map import NestedMapState
from ..ops.map_orswot import MapOrswotState
from ..ops.orswot import OrswotState
from .collectives import (
    all_reduce_clock,
    all_reduce_join,
    all_reduce_lattice,
    ring_round,
)
from .mesh import (
    ELEMENT_AXIS,
    REPLICA_AXIS,
    map_orswot_out_specs,
    map_orswot_specs,
    map_out_specs,
    map_specs,
    nested_map_out_specs,
    nested_map_specs,
    orswot_out_specs,
    orswot_specs,
    pad_elements,
    pad_keys,
    pad_map_orswot,
    pad_nested_map,
    pad_replicas,
    pad_replicas_map,
)
from ..obs import hist as _hist
from ..utils.metrics import metrics, observe_depth, state_nbytes
from .. import telemetry as tele


_FN_CACHE: dict = {}


def _exchange_count(p: int) -> int:
    """Static per-device exchange count of the replica-axis all-reduce:
    log2(P) recursive-doubling hops on a power-of-two axis, P-1 shipped
    shards on the all_gather fallback (collectives.all_reduce_lattice).
    Feeds the telemetry merge/byte counters."""
    if p <= 1:
        return 0
    if p & (p - 1) == 0:
        return p.bit_length() - 1
    return p - 1


def _tel_reduced(folded, slots, merges_per_dev, bytes_per_dev,
                 sum_axes, residue=None, useful_per_dev=None):
    """Mesh-reduce per-device telemetry into replicated scalars (inside
    shard_map): throughput counters psum over the replica axis (and the
    element axis only for ``slots`` when the content plane is
    element-sharded — ``sum_axes``; None = caller already reduced);
    bytes psum over ALL devices (element copies physically transmit);
    final-state gauges pmax. ``useful_per_dev`` is the per-device
    post-mask payload byte count (δ-ring packets after digest gating);
    None = no mask exists, wire == useful (whole-state exchanges)."""
    both = (REPLICA_AXIS, ELEMENT_AXIS)
    wire = lax.psum(jnp.float32(bytes_per_dev), both)
    return tele.Telemetry(
        merges=lax.psum(jnp.uint32(merges_per_dev), REPLICA_AXIS),
        slots_changed=slots if sum_axes is None else lax.psum(slots, sum_axes),
        deferred_depth=lax.pmax(tele.device_depth(folded), both),
        bytes_exchanged=wire,
        bytes_useful=(
            wire if useful_per_dev is None
            else lax.psum(jnp.float32(useful_per_dev), both)
        ),
        residue=(
            jnp.zeros((), jnp.int32) if residue is None else residue
        ),
        widen_pressure=lax.pmax(tele.device_pressure(folded), both),
        # The reclaim fields are zero unless the stability path fills
        # them in (gossip_stab_fn's _replace); the stream fields are
        # filled host-side by the block loop (parallel/stream.py).
        reclaimed_slots=jnp.zeros((), jnp.uint32),
        reclaimed_bytes=jnp.zeros((), jnp.float32),
        frontier_lag=jnp.zeros((), jnp.uint32),
        stream_blocks=jnp.zeros((), jnp.uint32),
        stream_staged_bytes=jnp.zeros((), jnp.float32),
        stream_overlap_hit=jnp.zeros((), jnp.uint32),
        # The fault fields are zero unless the faults= path fills them
        # in (the entry's _replace on the counters psum — faults/).
        faults_dropped=jnp.zeros((), jnp.uint32),
        faults_rejected=jnp.zeros((), jnp.uint32),
        faults_delayed=jnp.zeros((), jnp.uint32),
        # The ack-window fields are zero unless the δ ring's
        # ack_window= path fills them in (delta_ring's _replace).
        bytes_acked_skipped=jnp.zeros((), jnp.float32),
        ack_window_depth=jnp.zeros((), jnp.uint32),
        # The durability fields are filled host-side by the wal= append
        # loop (delta_ring / stream) and the recovery driver
        # (crdt_tpu/durability/) — never in-kernel.
        wal_bytes=jnp.zeros((), jnp.float32),
        wal_fsyncs=jnp.zeros((), jnp.uint32),
        snapshots_written=jnp.zeros((), jnp.uint32),
        replayed_records=jnp.zeros((), jnp.uint32),
        torn_tail_truncated=jnp.zeros((), jnp.uint32),
        recovery_rounds=jnp.zeros((), jnp.uint32),
        # The scale-out fields are filled host-side by the membership
        # controller (crdt_tpu/scaleout/ ScaleoutMesh.annotate) — never
        # in-kernel.
        live_ranks=jnp.zeros((), jnp.uint32),
        scaleout_admits=jnp.zeros((), jnp.uint32),
        scaleout_drains=jnp.zeros((), jnp.uint32),
        bootstrap_bytes=jnp.zeros((), jnp.float32),
        # The packed-wire fields are zero unless the δ ring's fused=
        # path fills them in (delta_ring's _replace).
        wire_packed_bytes=jnp.zeros((), jnp.float32),
        # The serving-tier fields are filled host-side by the serve
        # layer (crdt_tpu/serve/ Superblock.annotate /
        # IngestQueue.annotate) — never in-kernel.
        live_tenants=jnp.zeros((), jnp.uint32),
        evicted_tenants=jnp.zeros((), jnp.uint32),
        ingest_coalesced_ops=jnp.zeros((), jnp.uint32),
        serve_wal_bytes=jnp.zeros((), jnp.float32),
        serve_overlap_hit=jnp.zeros((), jnp.uint32),
        rebalance_moves=jnp.zeros((), jnp.uint32),
        # The fan-out fields are filled by the subscription plane
        # (crdt_tpu/fanout/ FanoutPlane.annotate + mesh_fanout_push's
        # telemetry body) — never on the anti-entropy paths.
        subscribers_live=jnp.zeros((), jnp.uint32),
        cohorts_per_dispatch=jnp.zeros((), jnp.uint32),
        delta_push_bytes=jnp.zeros((), jnp.float32),
        resync_fallbacks=jnp.zeros((), jnp.uint32),
        # The geo-federation fields are filled host-side by the
        # federation front door (crdt_tpu/geo/ Federation.annotate) —
        # never in-kernel.
        regions_live=jnp.zeros((), jnp.uint32),
        geo_home_tenants=jnp.zeros((), jnp.uint32),
        geo_exchanges=jnp.zeros((), jnp.uint32),
        geo_exchange_bytes=jnp.zeros((), jnp.float32),
        geo_full_mirror_bytes=jnp.zeros((), jnp.float32),
        geo_failovers=jnp.zeros((), jnp.uint32),
        hist_geo_watermark_lag=_hist.zeros(),
        # The in-kernel histograms are zero unless the δ ring's loop
        # carry fills them in (delta_ring's _replace);
        # hist_dispatch_us is filled host-side (telemetry.time_dispatch
        # at the entry wrappers — never in-kernel).
        hist_residue=_hist.zeros(),
        hist_useful_bytes=_hist.zeros(),
        hist_ack_depth=_hist.zeros(),
        hist_packed_bytes=_hist.zeros(),
        hist_dispatch_us=_hist.zeros(),
        hist_ingest_batch=_hist.zeros(),
        hist_push_bytes=_hist.zeros(),
        # The trace-plane stage/freshness hists are filled host-side by
        # obs.trace.Tracer.annotate — never in-kernel.
        hist_queue_wait_us=_hist.zeros(),
        hist_dispatch_gap_us=_hist.zeros(),
        hist_durable_lag_us=_hist.zeros(),
        hist_push_lag_us=_hist.zeros(),
        hist_ack_lag_us=_hist.zeros(),
        hist_freshness_us=_hist.zeros(),
        # hist_persist_us is filled host-side by the serve layer's
        # BackgroundPersister (crdt_tpu/serve/loop.py) — never in-kernel.
        hist_persist_us=_hist.zeros(),
    )


def _cached(kind: str, state, mesh: Mesh, build, *extra, donate_argnums=()):
    """The memoised shard_map closure for ``kind`` on this (mesh, input
    shape/dtype signature): jit-wrapped once, so repeated anti-entropy
    rounds hit the trace/compile cache instead of re-lowering.
    ``donate_argnums`` rides the cache key — a donating call consumes
    its inputs, so it must never share a compiled program with the
    copying flavor."""
    sig = tuple(
        (tuple(x.shape), str(x.dtype)) for x in jax.tree.leaves(state)
    )
    key = (kind, mesh, sig, tuple(donate_argnums), *extra)
    fn = _FN_CACHE.get(key)
    if fn is None:
        fn = _FN_CACHE[key] = jax.jit(
            build(), donate_argnums=tuple(donate_argnums)
        )
    return fn


def _ring_donate_argnums(state, mesh: Mesh, donate: bool, n: int = 1):
    """The donate_argnums for a ring/gossip entry point whose outputs
    keep the ``[P, ...]`` per-device layout: the first ``n`` args
    (state pytree, and for δ flavors the dirty mask) alias their
    outputs in place — zero-copy — exactly when the padded replica axis
    equals the mesh's (one replica block row per device), which is the
    steady-state mesh shape. A larger batch reduces away leading rows,
    XLA would silently drop the donation (with a warning), so we fall
    back to the copying program and count the miss instead."""
    if not donate:
        return ()
    lead = jax.tree.leaves(state)[0].shape[0]
    if lead != mesh.shape[REPLICA_AXIS]:
        metrics.count("anti_entropy.donate_unaliasable")
        return ()
    return tuple(range(n))


def _consume(donate: bool, *trees) -> None:
    """Donation semantics for the entry points whose outputs cannot
    alias their inputs (the fold family reduces the replica axis away —
    no output shares the batched input's shape): the caller yielded
    ownership, so free the input buffers NOW rather than at whatever
    point the last reference dies. Already-deleted / tracer leaves are
    skipped (a donating ring call upstream may have consumed them)."""
    if not donate:
        return
    for tree in trees:
        for leaf in jax.tree.leaves(tree):
            try:
                leaf.delete()
            except Exception:
                pass  # tracers / already-donated buffers


def mesh_fold(
    state: OrswotState, mesh: Mesh, local_fold: str = "auto",
    telemetry: bool = False, donate: bool = False,
) -> Tuple[OrswotState, jax.Array]:
    """Full-mesh anti-entropy over the device mesh: every replica's state
    joined into one converged state, in one collective round.

    Plan: fold the device-local replica block (the fused one-HBM-pass
    Pallas kernel on TPU backends, the jnp log2 tree elsewhere —
    ``local_fold`` = "auto"|"fused"|"tree", see pallas_kernels
    ``fold_auto``), then one lattice-join all-reduce across the
    ``replica`` mesh axis. Element shards never communicate — the join
    is element-parallel (mesh.py). Returns (converged state [no replica
    axis, element-sharded], overflow flag); with ``telemetry=True`` a
    :class:`crdt_tpu.telemetry.Telemetry` pytree rides along as a third
    element (in-kernel counters — they survive an outer jit; the flag
    off traces exactly the flag-free program).

    ``donate=True`` consumes ``state``: the fold reduces the replica
    axis away, so no output can alias the batched input — the input
    buffers are instead freed as soon as the reduction lands rather
    than when the caller's last reference dies, halving the entry's
    resident HBM tail. The caller must not touch ``state`` afterwards
    (in-place aliasing is the *ring* family's mode — ``mesh_gossip*``
    keep the [P, ...] layout, so there donation really aliases)."""
    from ..ops.pallas_kernels import fold_auto

    orig = state
    state = pad_replicas(state, mesh.shape[REPLICA_AXIS])
    state = pad_elements(state, mesh.shape[ELEMENT_AXIS])

    def build():
        @partial(
            jax.shard_map,
            mesh=mesh,
            in_specs=(orswot_specs(),),
            out_specs=(orswot_out_specs(), P()),
            check_vma=False,
        )
        def fold_fn(local):
            folded, of_local = fold_auto(local, prefer=local_fold)
            joined, of_cross = all_reduce_join(folded, REPLICA_AXIS)
            of = (lax.psum(of_local.astype(jnp.int32), REPLICA_AXIS) > 0) | of_cross
            return joined, of

        return fold_fn

    def build_tel():
        n_ex = _exchange_count(mesh.shape[REPLICA_AXIS])

        @partial(
            jax.shard_map,
            mesh=mesh,
            in_specs=(orswot_specs(),),
            out_specs=(orswot_out_specs(), P(), tele.specs()),
            check_vma=False,
        )
        def fold_tel_fn(local):
            folded, of_local = fold_auto(local, prefer=local_fold)
            joined, of_cross = all_reduce_join(folded, REPLICA_AXIS)
            of = (lax.psum(of_local.astype(jnp.int32), REPLICA_AXIS) > 0) | of_cross
            local_rows = jax.tree.leaves(local)[0].shape[0]
            tel = _tel_reduced(
                joined,
                lax.psum(
                    ops.changed_members(folded, joined),
                    (REPLICA_AXIS, ELEMENT_AXIS),
                ),
                max(local_rows - 1, 0) + n_ex,
                tele.shipped_bytes(folded) * n_ex,
                sum_axes=None,  # already reduced above
            )
            return joined, of, tel

        return fold_tel_fn

    metrics.count("anti_entropy.fold_rounds")
    metrics.count(
        "anti_entropy.merges", max(jax.tree.leaves(state)[0].shape[0] - 1, 0)
    )
    metrics.observe("anti_entropy.state_bytes", state_nbytes(state))
    observe_depth("anti_entropy.orswot_fold", state)
    t0 = time.perf_counter()
    with metrics.time("anti_entropy.fold"):
        out = _cached(
            "orswot_fold", state, mesh,
            build_tel if telemetry else build, local_fold, telemetry,
        )(state)
        jax.block_until_ready(out)  # time device work, not async dispatch
    _consume(donate, state, orig)
    if telemetry and tele.is_concrete(out[2]):
        out = out[:2] + (tele.time_dispatch(
            out[2], time.perf_counter() - t0
        ),)
        tele.record("orswot_fold", out[2])
    return out


def _mesh_gossip_lattice(
    kind: str,
    state,
    mesh: Mesh,
    join_fn,
    fold_fn,
    in_specs,
    rounds: Optional[int] = None,
    cache_extra: tuple = (),
    telemetry: bool = False,
    slots_fn=None,
    element_sharded: bool = True,
    donate: bool = False,
    stability: bool = False,
    compact_fn=None,
    faults=None,
    lag_threshold=None,
):
    """Shared scaffold for ring anti-entropy: each device folds its
    local replica block, then runs ``rounds`` unit-shift gossip rounds.
    Bandwidth per round is one state per link — the bounded-traffic mode
    for DCN-crossing replica axes. Returns (per-device states [P, ...],
    overflow); with the default rounds = P-1 every row equals the full
    join.

    ``telemetry=True`` appends an in-kernel accumulated Telemetry pytree
    (telemetry.py) — per-round joins feed ``slots_fn`` (the kind's
    changed-lane counter; ``element_sharded`` picks the psum axes for it)
    and the shipped-state bytes; the flag off traces exactly the
    flag-free program.

    ``donate=True`` consumes the input state and — when the padded
    replica axis equals the mesh's, the steady-state shape — aliases
    the output rows onto the input buffers (``input_output_alias`` in
    the lowering; tools/check_aliasing.py gates it), so the gossip
    carries no second copy of the state in HBM. Larger batches cannot
    alias (the local fold reduces leading rows away); they fall back to
    freeing the input after the run and count
    ``anti_entropy.donate_unaliasable``.

    ``stability=True`` piggybacks the mesh-wide STABLE FRONTIER on the
    round (reclaim/frontier.py): one lax ``pmin`` over the replica axis
    of the PRE-fold input tops — the knowledge each replica entered
    with, so a straggler row pins the frontier — appended as the last
    output (replicated ``[A]``), and the kind's registered compaction
    kernel (``compact_fn``) runs in-kernel on the converged rows before
    they ship out. The flag off traces exactly the flag-free program
    (same HLO-identity discipline as ``telemetry=``); with both flags
    on, the Telemetry pytree carries ``reclaimed_slots`` /
    ``reclaimed_bytes`` / ``frontier_lag``.

    ``faults=`` (a ``crdt_tpu.faults.FaultPlan``) injects seeded
    drop/corrupt/delay faults on every ring exchange — each shipped
    state carries a checksum lane, corrupted arrivals are REJECTED
    (local state kept), the ring runs over the plan's LIVE ranks, and
    with ``stability=`` on the frontier ``pmin`` EXCLUDES evicted tops
    (the headline unpinning: a dead rank stops stalling reclamation;
    its own row is left uncompacted — a frontier past its knowledge
    must not retire its parked slots). A ``FaultCounters`` pytree is
    appended as the LAST output. Unlike the δ ring, loss here is never
    unsound — full states carry their own tops, a missed round only
    slows convergence (run more rounds, or heal with a fault-free run).

    ``lag_threshold=`` (host-side, needs ``stability=``): when the
    run's ``frontier_lag`` reaches it, ``reclaim.frontier_stalled``
    counts and a once-per-kind stall warning fires
    (reclaim/frontier.py ``watch_lag`` — the operator signal that a
    straggler is pinning the frontier and reclamation has stalled)."""
    if lag_threshold is not None and not stability:
        raise ValueError(
            "lag_threshold= needs stability=True: the stall alert "
            "watches the stable frontier, which only exists on the "
            "stability path — without it the alert would silently "
            "never arm"
        )
    if rounds is None:
        rounds = mesh.shape[REPLICA_AXIS] - 1
    faulted = faults is not None
    delay_mode = faulted and faults.delay > 0
    if faulted:
        from .. import faults as flt

        p = mesh.shape[REPLICA_AXIS]
        perm = flt.ring_perm(p, faults.evicted)
        snd_tbl = flt.sender_of(p, faults.evicted)
    argnums = _ring_donate_argnums(state, mesh, donate)

    def build():
        # ONE parameterized builder for every (telemetry, stability)
        # combination: the flag-off branches trace EXACTLY the pre-flag
        # program (Python conditionals emit nothing when off — the
        # HLO-identity pins in tests/test_telemetry.py and
        # tests/test_reclaim.py hold on this single body).
        from ..reclaim.frontier import frontier_lag as _lag, top_of as _top

        slots_of = slots_fn or tele.generic_slots_changed
        sum_axes = (
            (REPLICA_AXIS, ELEMENT_AXIS) if element_sharded
            else (REPLICA_AXIS,)
        )
        out_specs = [in_specs, P()]
        if telemetry:
            out_specs.append(tele.specs())
        if stability:
            out_specs.append(P())  # the frontier, replicated
        if faulted:
            out_specs.append(flt.counters_specs())

        @partial(
            jax.shard_map,
            mesh=mesh,
            in_specs=(in_specs,),
            out_specs=tuple(out_specs),
            check_vma=False,
        )
        def gossip_fn(local):
            if faulted:
                ev = flt.evicted_mask(faults, REPLICA_AXIS)
            if stability:
                # Frontier over the PRE-fold input tops: the knowledge
                # each replica ENTERED the round with — a straggler row
                # pins it.
                tmin = jnp.min(_top(local), axis=0)
                if faulted and faults.evicted:
                    # Eviction unpins: a dead rank's stale top leaves
                    # the pmin (the membership decision — its rejoin
                    # must be full-state resync, faults/membership.py).
                    tmin = jnp.where(
                        ev, jnp.asarray(jnp.iinfo(tmin.dtype).max,
                                        tmin.dtype), tmin
                    )
                frontier = lax.pmin(tmin, REPLICA_AXIS)
            folded, of = fold_fn(local)
            if telemetry:
                slots = jnp.zeros((), jnp.uint32)
            if faulted:
                fc = (
                    jnp.zeros((), jnp.uint32), jnp.zeros((), jnp.uint32),
                    jnp.zeros((), jnp.uint32), jnp.zeros((), jnp.int32),
                )
                if delay_mode:
                    held = jax.tree.map(jnp.zeros_like, folded)
                    heldv = jnp.zeros((), bool)
            for r in range(rounds):
                if faulted:
                    # The faulted exchange: checksum lane on the wire,
                    # per-round drop/corrupt/delay draws on the inbound
                    # link (faults.receive_wire — evicted self-loops
                    # masked out of the accounting), rejected/dropped
                    # deliveries deselected (full-state loss is never
                    # unsound — see above).
                    other, chk_in = jax.tree.map(
                        lambda x: lax.ppermute(x, REPLICA_AXIS, perm),
                        (folded, flt.checksum(folded)),
                    )
                    # The last round delivers a would-be-delayed state
                    # now — no later round to hold it for.
                    other, keep, fates = flt.receive_wire(
                        faults, r, REPLICA_AXIS, snd_tbl, other, chk_in,
                        delay_ok=delay_mode and r < rounds - 1,
                    )
                    base = folded
                    if delay_mode:
                        newh, of_h = join_fn(folded, held)
                        folded = flt.tree_select(heldv, newh, folded)
                        of = of | (of_h & heldv)
                    joined, of_r = join_fn(folded, other)
                    new = flt.tree_select(keep, joined, folded)
                    of_r = of_r & keep
                    if delay_mode:
                        held = flt.tree_select(fates[2], other, held)
                        heldv = fates[2]
                    fc = flt.tick_counters(fc, fates)
                    if telemetry:
                        slots = slots + slots_of(base, new)
                    folded, of = new, of | of_r
                    continue
                new, of_r = ring_round(
                    folded, REPLICA_AXIS, reduce_overflow=False,
                    join_fn=join_fn,
                )
                if telemetry:
                    slots = slots + slots_of(folded, new)
                folded, of = new, of | of_r
            if stability:
                freed = jnp.zeros((), jnp.uint32)
                freed_b = jnp.zeros((), jnp.float32)
                if compact_fn is not None:
                    compacted, freed, freed_b = compact_fn(folded, frontier)
                    if faulted and faults.evicted:
                        # Never compact an evicted rank's own row: the
                        # frontier may exceed its knowledge, and
                        # retiring parked slots it has not applied
                        # breaks its (resync-pending) local state.
                        compacted = flt.tree_select(~ev, compacted, folded)
                        freed = jnp.where(ev, 0, freed)
                        freed_b = jnp.where(ev, 0.0, freed_b)
                    folded = compacted
            of = lax.psum(of.astype(jnp.int32), (REPLICA_AXIS, ELEMENT_AXIS)) > 0
            outs = [jax.tree.map(lambda x: x[None], folded), of]
            if telemetry:
                local_rows = jax.tree.leaves(local)[0].shape[0]
                tel = _tel_reduced(
                    folded, slots,
                    max(local_rows - 1, 0) + rounds,
                    tele.shipped_bytes(folded) * rounds,
                    sum_axes,
                )
                if stability:
                    tel = tel._replace(
                        reclaimed_slots=lax.psum(freed, REPLICA_AXIS),
                        reclaimed_bytes=lax.psum(freed_b, REPLICA_AXIS),
                        frontier_lag=lax.pmax(
                            _lag(_top(folded), frontier), REPLICA_AXIS
                        ),
                    )
                if faulted:
                    tel = tel._replace(
                        faults_dropped=lax.psum(fc[0], REPLICA_AXIS),
                        faults_rejected=lax.psum(fc[1], REPLICA_AXIS),
                        faults_delayed=lax.psum(fc[2], REPLICA_AXIS),
                    )
                outs.append(tel)
            if stability:
                outs.append(frontier)
            if faulted:
                # Replica-axis psum only: the fault draw is per logical
                # link (element shards share the fate) — a both-axes sum
                # would count device shards, not packets.
                outs.append(flt.FaultCounters(
                    packets_dropped=lax.psum(fc[0], REPLICA_AXIS),
                    packets_rejected=lax.psum(fc[1], REPLICA_AXIS),
                    packets_delayed=lax.psum(fc[2], REPLICA_AXIS),
                    miss_streak=fc[3].reshape(1),
                ))
            return tuple(outs)

        return gossip_fn

    metrics.count(f"anti_entropy.{kind}_rounds", rounds)
    metrics.observe("anti_entropy.state_bytes", state_nbytes(state))
    observe_depth(f"anti_entropy.{kind}", state)
    t0 = time.perf_counter()
    with metrics.time(f"anti_entropy.{kind}"):
        out = _cached(
            kind, state, mesh, build,
            rounds, telemetry, stability, faults, *cache_extra,
            donate_argnums=argnums,
        )(state)
        jax.block_until_ready(out)  # time device work, not async dispatch
    # Aliased buffers are already consumed by the donation; this frees
    # the leftovers — the unaliasable fallback, and originals that were
    # implicitly resharded onto the mesh (the executable then donated
    # the committed copy, not the caller's array).
    _consume(donate, state)
    if telemetry and tele.is_concrete(out[2]):
        out = out[:2] + (tele.time_dispatch(
            out[2], time.perf_counter() - t0
        ),) + out[3:]
        tele.record(kind, out[2])
    if faulted:
        from .. import faults as flt

        flt.record(out[-1])  # no-op under tracing
    if stability and lag_threshold is not None:
        from ..reclaim.frontier import frontier_lag, top_of, watch_lag

        frontier = out[2 + (1 if telemetry else 0)]
        lag = frontier_lag(top_of(out[0]), frontier)
        if not isinstance(lag, jax.core.Tracer):
            watch_lag(kind, int(lag), lag_threshold)
    return out


def mesh_gossip(
    state: OrswotState,
    mesh: Mesh,
    rounds: Optional[int] = None,
    local_fold: str = "auto",
    telemetry: bool = False,
    donate: bool = False,
    stability: bool = False,
    faults=None,
    lag_threshold=None,
) -> Tuple[OrswotState, jax.Array]:
    """Ring anti-entropy for ORSWOT replica batches (see
    ``_mesh_gossip_lattice``); the device-local pre-fold dispatches like
    ``mesh_fold`` (fused Pallas on TPU backends). ``telemetry=True``
    appends the in-kernel Telemetry pytree (telemetry.py);
    ``donate=True`` consumes ``state`` and aliases the converged rows
    onto its buffers in place (zero-copy — ``_mesh_gossip_lattice``);
    ``stability=True`` appends the mesh-wide stable frontier and
    compacts the rows in-kernel (reclaim/)."""
    from ..ops.pallas_kernels import fold_auto

    state = pad_replicas(state, mesh.shape[REPLICA_AXIS])
    state = pad_elements(state, mesh.shape[ELEMENT_AXIS])
    return _mesh_gossip_lattice(
        "orswot_gossip", state, mesh, ops.join,
        partial(fold_auto, prefer=local_fold), orswot_specs(), rounds,
        cache_extra=(local_fold,),
        telemetry=telemetry, slots_fn=ops.changed_members, donate=donate,
        stability=stability, compact_fn=ops.compact,
        faults=faults, lag_threshold=lag_threshold,
    )


def mesh_gossip_map(
    state: MapState, mesh: Mesh, rounds: Optional[int] = None,
    telemetry: bool = False, donate: bool = False,
    stability: bool = False,
    faults=None,
    lag_threshold=None,
) -> Tuple[MapState, jax.Array]:
    """Ring anti-entropy for the composition layer: Map<K, MVReg>
    replica blocks gossiped one neighbor per round over the replica
    axis, key shards independent."""
    state = pad_replicas_map(state, mesh.shape[REPLICA_AXIS])
    state = pad_keys(state, mesh.shape[ELEMENT_AXIS])
    return _mesh_gossip_lattice(
        "map_gossip", state, mesh, map_ops.join, map_ops.fold, map_specs(),
        rounds, telemetry=telemetry, slots_fn=map_ops.changed_keys,
        donate=donate, stability=stability, compact_fn=map_ops.compact,
        faults=faults, lag_threshold=lag_threshold,
    )


def mesh_gossip_map_orswot(
    state: MapOrswotState, mesh: Mesh, rounds: Optional[int] = None,
    telemetry: bool = False, donate: bool = False,
    stability: bool = False,
    faults=None,
    lag_threshold=None,
) -> Tuple[MapOrswotState, jax.Array]:
    """Ring anti-entropy for ``Map<K, Orswot>`` replica blocks (the
    Val-generic slab composition) over the replica axis."""
    state = pad_map_orswot(state, mesh.shape[REPLICA_AXIS], mesh.shape[ELEMENT_AXIS])
    return _mesh_gossip_lattice(
        "map_orswot_gossip", state, mesh,
        partial(mo_ops.join, element_axis=ELEMENT_AXIS),
        partial(mo_ops.fold, element_axis=ELEMENT_AXIS),
        map_orswot_specs(), rounds,
        telemetry=telemetry,
        slots_fn=lambda a, b: ops.changed_members(a.core, b.core),
        donate=donate, stability=stability, compact_fn=mo_ops.compact,
        faults=faults, lag_threshold=lag_threshold,
    )


def mesh_gossip_nested_map(
    state: NestedMapState, mesh: Mesh, rounds: Optional[int] = None,
    telemetry: bool = False, donate: bool = False,
    stability: bool = False,
    faults=None,
    lag_threshold=None,
) -> Tuple[NestedMapState, jax.Array]:
    """Ring anti-entropy for ``Map<K1, Map<K2, MVReg>>`` replica blocks
    over the replica axis."""
    state = pad_nested_map(state, mesh.shape[REPLICA_AXIS], mesh.shape[ELEMENT_AXIS])
    return _mesh_gossip_lattice(
        "nested_map_gossip", state, mesh,
        partial(nested_ops.join, element_axis=ELEMENT_AXIS),
        partial(nested_ops.fold, element_axis=ELEMENT_AXIS),
        nested_map_specs(), rounds,
        telemetry=telemetry,
        slots_fn=lambda a, b: map_ops.changed_keys(a.m, b.m),
        donate=donate, stability=stability, compact_fn=nested_ops.compact,
        faults=faults, lag_threshold=lag_threshold,
    )


def _mesh_fold_lattice(
    kind: str,
    state,
    mesh: Mesh,
    join_fn,
    fold_fn,
    in_specs,
    out_specs,
    telemetry: bool = False,
    slots_fn=None,
    element_sharded: bool = False,
    donate: bool = False,
):
    """Shared scaffold for the map-family mesh folds: local log-tree
    fold per shard, replica-axis lattice-join all-reduce, and overflow
    flags reduced over BOTH axes (slab/deferred overflows can be
    key-shard-local, so every device must report the global flag).
    ``telemetry=True`` appends the in-kernel Telemetry pytree
    (telemetry.py); the flag off traces exactly the flag-free program.
    ``donate=True`` consumes the input batch: the fold reduces the
    replica axis away so no output aliases it — the buffers are freed
    as soon as the reduction lands (see ``mesh_fold``)."""

    def build():
        @partial(
            jax.shard_map,
            mesh=mesh,
            in_specs=(in_specs,),
            out_specs=(out_specs, P()),
            check_vma=False,
        )
        def mesh_fn(local):
            folded, of_local = fold_fn(local)
            joined, of_cross = all_reduce_lattice(
                folded, REPLICA_AXIS, join_fn, fold_fn
            )
            of = (lax.psum(of_local.astype(jnp.int32), REPLICA_AXIS) > 0) | of_cross
            of = lax.psum(of.astype(jnp.int32), ELEMENT_AXIS) > 0
            return joined, of

        return mesh_fn

    def build_tel():
        slots_of = slots_fn or tele.generic_slots_changed
        sum_axes = (
            (REPLICA_AXIS, ELEMENT_AXIS) if element_sharded
            else (REPLICA_AXIS,)
        )
        n_ex = _exchange_count(mesh.shape[REPLICA_AXIS])

        @partial(
            jax.shard_map,
            mesh=mesh,
            in_specs=(in_specs,),
            out_specs=(out_specs, P(), tele.specs()),
            check_vma=False,
        )
        def mesh_tel_fn(local):
            folded, of_local = fold_fn(local)
            joined, of_cross = all_reduce_lattice(
                folded, REPLICA_AXIS, join_fn, fold_fn
            )
            of = (lax.psum(of_local.astype(jnp.int32), REPLICA_AXIS) > 0) | of_cross
            of = lax.psum(of.astype(jnp.int32), ELEMENT_AXIS) > 0
            local_rows = jax.tree.leaves(local)[0].shape[0]
            tel = _tel_reduced(
                joined, slots_of(folded, joined),
                max(local_rows - 1, 0) + n_ex,
                tele.shipped_bytes(folded) * n_ex,
                sum_axes,
            )
            return joined, of, tel

        return mesh_tel_fn

    metrics.count(f"anti_entropy.{kind}_rounds")
    metrics.count(
        "anti_entropy.merges", max(jax.tree.leaves(state)[0].shape[0] - 1, 0)
    )
    metrics.observe("anti_entropy.state_bytes", state_nbytes(state))
    observe_depth(f"anti_entropy.{kind}", state)
    t0 = time.perf_counter()
    with metrics.time(f"anti_entropy.{kind}"):
        out = _cached(
            kind, state, mesh, build_tel if telemetry else build, telemetry
        )(state)
        jax.block_until_ready(out)  # time device work, not async dispatch
    _consume(donate, state)
    if telemetry and tele.is_concrete(out[2]):
        out = out[:2] + (tele.time_dispatch(
            out[2], time.perf_counter() - t0
        ),)
        tele.record(kind, out[2])
    return out


def mesh_fold_map(
    state: MapState, mesh: Mesh, telemetry: bool = False,
    donate: bool = False,
) -> Tuple[MapState, jax.Array]:
    """Full-mesh anti-entropy for the composition layer (BASELINE config
    4): every replica's Map<K, MVReg> state joined into one converged
    state over the (replica × key) mesh. Key shards never communicate —
    the map join is key-wise independent (mesh.map_specs); the only
    collective is the lattice-join all-reduce over the replica axis.

    Returns (converged state [no replica axis, key-sharded], overflow).
    """
    state = pad_replicas_map(state, mesh.shape[REPLICA_AXIS])
    state = pad_keys(state, mesh.shape[ELEMENT_AXIS])
    return _mesh_fold_lattice(
        "map_fold", state, mesh,
        map_ops.join, map_ops.fold,
        map_specs(), map_out_specs(),
        telemetry=telemetry, slots_fn=map_ops.changed_keys,
        element_sharded=True, donate=donate,
    )


def mesh_fold_map_orswot(
    state: MapOrswotState, mesh: Mesh, telemetry: bool = False,
    donate: bool = False,
) -> Tuple[MapOrswotState, jax.Array]:
    """Full-mesh anti-entropy for ``Map<K, Orswot>`` over the
    (replica × key) mesh: element shards hold whole keys (K*M blocks)
    and never exchange content; the collectives are the replica-axis
    lattice-join all-reduce plus the tiny slot-liveness reduction the
    dead-key scrub needs across key shards (ops/map_orswot.py
    ``_any_slots``). Returns (converged state, overflow[2])."""
    state = pad_map_orswot(
        state, mesh.shape[REPLICA_AXIS], mesh.shape[ELEMENT_AXIS]
    )
    return _mesh_fold_lattice(
        "map_orswot_fold", state, mesh,
        partial(mo_ops.join, element_axis=ELEMENT_AXIS),
        partial(mo_ops.fold, element_axis=ELEMENT_AXIS),
        map_orswot_specs(), map_orswot_out_specs(),
        telemetry=telemetry,
        slots_fn=lambda a, b: ops.changed_members(a.core, b.core),
        element_sharded=True, donate=donate,
    )


def mesh_fold_nested_map(
    state: NestedMapState, mesh: Mesh, telemetry: bool = False,
    donate: bool = False,
) -> Tuple[NestedMapState, jax.Array]:
    """Full-mesh anti-entropy for ``Map<K1, Map<K2, MVReg>>`` over the
    (replica × outer-key) mesh (K1*K2 blocks per shard). Returns
    (converged state, overflow[3])."""
    state = pad_nested_map(
        state, mesh.shape[REPLICA_AXIS], mesh.shape[ELEMENT_AXIS]
    )
    return _mesh_fold_lattice(
        "nested_map_fold", state, mesh,
        partial(nested_ops.join, element_axis=ELEMENT_AXIS),
        partial(nested_ops.fold, element_axis=ELEMENT_AXIS),
        nested_map_specs(), nested_map_out_specs(),
        telemetry=telemetry,
        slots_fn=lambda a, b: map_ops.changed_keys(a.m, b.m),
        element_sharded=True, donate=donate,
    )


def mesh_fold_gset(present: jax.Array, mesh: Mesh) -> jax.Array:
    """Converge a GSet replica batch ``present[R, M]`` over the mesh:
    member-sharded set union (logical OR) with the replica axis reduced —
    the simplest lattice (reference: src/gset.rs ``CvRDT::merge``).
    Returns the converged membership ``[M]`` (member-sharded)."""
    rsize = mesh.shape[REPLICA_AXIS]
    pad_r = (-present.shape[0]) % rsize
    if pad_r:
        present = jnp.pad(present, ((0, pad_r), (0, 0)))
    esize = mesh.shape[ELEMENT_AXIS]
    pad_m = (-present.shape[1]) % esize
    if pad_m:
        present = jnp.pad(present, ((0, 0), (0, pad_m)))
    m = present.shape[1] - pad_m

    def build():
        @partial(
            jax.shard_map,
            mesh=mesh,
            in_specs=(P(REPLICA_AXIS, ELEMENT_AXIS),),
            out_specs=P(ELEMENT_AXIS),
            check_vma=False,
        )
        def fold_fn(local):
            return (
                lax.psum(
                    jnp.any(local, axis=0).astype(jnp.int32), REPLICA_AXIS
                )
                > 0
            )

        return fold_fn

    metrics.count("anti_entropy.gset_fold_rounds")
    metrics.observe("anti_entropy.state_bytes", float(present.nbytes))
    with metrics.time("anti_entropy.gset_fold"):
        out = _cached("gset_fold", present, mesh, build)(present)
        jax.block_until_ready(out)
    return out[:m]


def _pad_with_identity(states, rsize: int, ident):
    """Pad the replica axis to a multiple of the mesh's replica-axis
    size with join identities (absorbed by any lattice join)."""
    lead = jax.tree.leaves(states)[0].shape[0]
    if lead % rsize == 0:
        return states
    return jax.tree.map(
        lambda x, p: jnp.concatenate([x, p.astype(x.dtype)], axis=0),
        states,
        ident,
    )


def mesh_fold_lww(states, mesh: Mesh, telemetry: bool = False,
                  donate: bool = False):
    """Converge an LWWReg replica batch (LWWState with leading axis R)
    over the mesh's replica axis. Returns ``(state, conflict)``;
    conflict marks an equal-marker/different-value merge anywhere
    (reference: src/lwwreg.rs validate_merge)."""
    from ..ops import lwwreg as lww_ops

    rsize = mesh.shape[REPLICA_AXIS]
    pad_r = (-states.hi.shape[0]) % rsize
    states = _pad_with_identity(
        states, rsize, lww_ops.empty(batch=(pad_r,)) if pad_r else None
    )

    template = lww_ops.empty()
    return _mesh_fold_lattice(
        "lww_fold", states, mesh,
        lww_ops.join, lww_ops.fold,
        jax.tree.map(lambda _: P(REPLICA_AXIS), template),
        jax.tree.map(lambda _: P(), template),
        telemetry=telemetry, donate=donate,
    )


def mesh_fold_mvreg(states, mesh: Mesh, telemetry: bool = False,
                    donate: bool = False):
    """Converge an MVReg replica batch (MVRegState with leading axis R)
    over the mesh's replica axis: dominated contents die, concurrent
    siblings survive (reference: src/mvreg.rs ``CvRDT::merge``).
    Returns ``(state, overflow)``."""
    from ..ops import mvreg as mv

    rsize = mesh.shape[REPLICA_AXIS]
    pad_r = (-states.wact.shape[0]) % rsize
    s, a = states.wact.shape[-1], states.clk.shape[-1]
    states = _pad_with_identity(
        states, rsize, mv.empty(s, a, batch=(pad_r,)) if pad_r else None
    )

    template = mv.empty(s, a)
    return _mesh_fold_lattice(
        "mvreg_fold", states, mesh,
        mv.join, mv.fold,
        jax.tree.map(lambda _: P(REPLICA_AXIS), template),
        jax.tree.map(lambda _: P(), template),
        telemetry=telemetry, donate=donate,
    )


def _sparse_pad_and_template(states, rsize: int):
    """Identity-pad a sparse replica batch to the mesh's replica-axis
    size and build the (unbatched) spec template — shared shape plumbing
    for the sparse mesh entry points."""
    from ..ops import sparse_orswot as sp

    shape_args = (
        states.eid.shape[-1],
        states.top.shape[-1],
        states.dcl.shape[-2],
        states.didx.shape[-1],
    )
    pad_r = (-states.top.shape[0]) % rsize
    states = _pad_with_identity(
        states, rsize, sp.empty(*shape_args, batch=(pad_r,)) if pad_r else None
    )
    return states, sp.empty(*shape_args)


def mesh_fold_sparse(states, mesh: Mesh, telemetry: bool = False,
                     donate: bool = False):
    """Converge a SPARSE (segment-encoded) ORSWOT replica batch over the
    mesh's replica axis, with the segment table REPLICATED across the
    element axis — the simple layout for moderate dot counts. For true
    element scaling, partition the table by ``eid % S`` and use
    ``sparse_shard.mesh_fold_sparse_sharded`` (per-device state and join
    cost drop by S; restriction commutes with the join, so shard-local
    joins are exact). Returns ``(state, overflow[2])``."""
    from ..ops import sparse_orswot as sp

    states, template = _sparse_pad_and_template(
        states, mesh.shape[REPLICA_AXIS]
    )
    return _mesh_fold_lattice(
        "sparse_orswot_fold", states, mesh,
        sp.join, sp.fold,
        jax.tree.map(lambda _: P(REPLICA_AXIS), template),
        jax.tree.map(lambda _: P(), template),
        telemetry=telemetry, slots_fn=sp.changed_dots, donate=donate,
    )


def _sparse_mvmap_pad_and_template(states, rsize: int):
    """Identity-pad a sparse Map<K, MVReg> replica batch to the mesh's
    replica-axis size and build the (unbatched) spec template — shared
    shape plumbing for the two mesh entry points (the mvmap analog of
    ``_sparse_pad_and_template``)."""
    from ..ops import sparse_mvmap as smv

    shape_args = (
        states.kid.shape[-1],
        states.top.shape[-1],
        states.dcl.shape[-2],
        states.kidx.shape[-1],
    )
    pad_r = (-states.top.shape[0]) % rsize
    states = _pad_with_identity(
        states, rsize, smv.empty(*shape_args, batch=(pad_r,)) if pad_r else None
    )
    return states, smv.empty(*shape_args)


def mesh_fold_sparse_mvmap(
    states, mesh: Mesh, sibling_cap: int = 4, telemetry: bool = False,
    donate: bool = False,
):
    """Converge a SPARSE ``Map<K, MVReg>`` replica batch
    (ops/sparse_mvmap) over the mesh's replica axis, cell table
    replicated across the element axis — the layout that pairs with the
    backend's live-cell-proportional state (the key universe is
    virtual, so there is nothing to shard until cell counts demand it).
    Returns ``(state, overflow[3])``."""
    from ..ops import sparse_mvmap as smv

    states, template = _sparse_mvmap_pad_and_template(
        states, mesh.shape[REPLICA_AXIS]
    )
    return _mesh_fold_lattice(
        f"sparse_mvmap_fold_s{sibling_cap}", states, mesh,
        partial(smv.join, sibling_cap=sibling_cap),
        partial(smv.fold, sibling_cap=sibling_cap),
        jax.tree.map(lambda _: P(REPLICA_AXIS), template),
        jax.tree.map(lambda _: P(), template),
        telemetry=telemetry, slots_fn=smv.changed_cells, donate=donate,
    )


def mesh_gossip_sparse_mvmap(
    states, mesh: Mesh, rounds: Optional[int] = None, sibling_cap: int = 4,
    telemetry: bool = False, donate: bool = False,
    stability: bool = False,
    faults=None,
    lag_threshold=None,
):
    """Ring anti-entropy for SPARSE ``Map<K, MVReg>`` replica batches
    over the replica axis — per-round traffic is one cell table per
    link, proportional to LIVE cells, not the key universe. Same
    replicated-element-axis layout as ``mesh_fold_sparse_mvmap``."""
    from ..ops import sparse_mvmap as smv

    states, template = _sparse_mvmap_pad_and_template(
        states, mesh.shape[REPLICA_AXIS]
    )
    return _mesh_gossip_lattice(
        f"sparse_mvmap_gossip_s{sibling_cap}", states, mesh,
        partial(smv.join, sibling_cap=sibling_cap),
        partial(smv.fold, sibling_cap=sibling_cap),
        jax.tree.map(lambda _: P(REPLICA_AXIS), template), rounds,
        telemetry=telemetry, slots_fn=smv.changed_cells,
        element_sharded=False, donate=donate,
        stability=stability, compact_fn=smv.compact,
        faults=faults, lag_threshold=lag_threshold,
    )


def mesh_fold_sparse_nested(states, mesh: Mesh, level,
                            telemetry: bool = False, donate: bool = False):
    """Converge a SPARSE nested-map replica batch (any
    ``sparse_nest.SparseNestLevel`` composition — e.g. the
    ``Map<K1, Map<K2, MVReg>>`` of ops/sparse_mvmap.level_map_mvreg)
    over the mesh's replica axis, state replicated across the element
    axis. ``level`` carries the join/fold (and their static caps).
    Returns ``(state, flags[L+1])``."""
    states, template, kind = _sparse_nested_pad_and_key(
        states, mesh.shape[REPLICA_AXIS], level, "fold"
    )
    return _mesh_fold_lattice(
        kind, states, mesh,
        level.join, level.fold,
        jax.tree.map(lambda _: P(REPLICA_AXIS), template),
        jax.tree.map(lambda _: P(), template),
        telemetry=telemetry, donate=donate,
    )


def _sparse_nested_pad_and_key(states, rsize: int, level, op: str):
    """Identity-pad a nested sparse replica batch and derive the memo
    key for its mesh entry points. The key MUST come from the level's
    static shape/caps — an id()-based key could be reused after GC and
    resurrect a compiled closure with the wrong caps."""
    from ..ops.sparse_nest import _sparse_identity_like

    pad_r = (-jax.tree.leaves(states)[0].shape[0]) % rsize
    if pad_r:
        identity = _sparse_identity_like(jax.tree.map(
            lambda x: jnp.zeros((pad_r, *x.shape[1:]), x.dtype), states
        ))
        states = jax.tree.map(
            lambda s, p: jnp.concatenate([s, p], axis=0), states, identity
        )
    template = jax.tree.map(lambda x: x[0], states)
    spans, core = [], level
    while hasattr(core, "core"):
        spans.append(str(core.span))
        core = core.core
    kind = (
        f"sparse_nested_{op}_{'x'.join(spans)}"
        f"_s{getattr(core, 'sibling_cap', 0)}"
    )
    return states, template, kind


def mesh_gossip_sparse_nested(
    states, mesh: Mesh, level, rounds: Optional[int] = None,
    telemetry: bool = False, donate: bool = False,
    stability: bool = False,
    faults=None,
    lag_threshold=None,
):
    """Ring anti-entropy for SPARSE nested-map replica batches (any
    ``SparseNestLevel`` composition) over the replica axis — per-round
    traffic is one live-content-proportional state per link. State
    replicated across the element axis (the sharded fold is the
    element-scaling mode)."""
    from ..ops import sparse_nest as nest_ops

    states, template, kind = _sparse_nested_pad_and_key(
        states, mesh.shape[REPLICA_AXIS], level, "gossip"
    )
    return _mesh_gossip_lattice(
        kind, states, mesh, level.join, level.fold,
        jax.tree.map(lambda _: P(REPLICA_AXIS), template), rounds,
        telemetry=telemetry, element_sharded=False, donate=donate,
        stability=stability, compact_fn=nest_ops.compact,
        faults=faults, lag_threshold=lag_threshold,
    )


def mesh_gossip_sparse(
    states, mesh: Mesh, rounds: Optional[int] = None,
    telemetry: bool = False, donate: bool = False,
    stability: bool = False,
    faults=None,
    lag_threshold=None,
):
    """Ring anti-entropy for SPARSE (segment-encoded) ORSWOT replica
    batches over the replica axis (the bounded-bandwidth mode —
    per-round traffic is one segment table per link, which for sparse
    states is proportional to LIVE dots, not the universe). Same
    replicated-element-axis layout as ``mesh_fold_sparse``."""
    from ..ops import sparse_orswot as sp

    states, template = _sparse_pad_and_template(
        states, mesh.shape[REPLICA_AXIS]
    )
    return _mesh_gossip_lattice(
        "sparse_gossip", states, mesh, sp.join, sp.fold,
        jax.tree.map(lambda _: P(REPLICA_AXIS), template), rounds,
        telemetry=telemetry, slots_fn=sp.changed_dots,
        element_sharded=False, donate=donate,
        stability=stability, compact_fn=sp.compact,
        faults=faults, lag_threshold=lag_threshold,
    )


def gossip_elastic(model, mesh: Mesh, rounds: Optional[int] = None,
                   policy=None, telemetry: bool = False,
                   donate: bool = False, stability: bool = False,
                   reclaim=None, faults=None, lag_threshold=None):
    """Ring anti-entropy with elastic capacity recovery — the
    overflow→widen→resume loop at mesh scale (elastic.py).

    Runs the model family's ring gossip on ``model.state``; when a
    capacity lane overflows mid-round, the round's result is DISCARDED
    (the gossip entry points never commit to the model, and the join is
    idempotent, so re-entering from the pre-round state is sound), the
    implicated axis widens 2× (policy-configurable) with the live state
    re-encoded on device, and the ring re-enters. Because the widened
    state is bit-identical to a from-scratch wider model, the re-entered
    gossip converges to exactly the full join of the wider family —
    replicas pause, migrate, and rejoin; nothing replays.

    Returns ``(rows, widened)``: ``rows`` are the per-device converged
    states ([P, ...] — every row equals the full join after the default
    P-1 rounds, as in ``mesh_gossip``), ``widened`` the dict of axes
    grown along the way (empty when capacity sufficed). Widening is
    administrative — apply the same growth on every host holding the
    replica set before the next round (elastic.py module docstring).

    ``telemetry=True`` appends a Telemetry pytree folded across every
    attempt (``telemetry.combine``: counters from discarded overflow
    runs still count — they were real work — while the final-state
    gauges come from the successful run).

    ``donate=True`` donates each attempt's state into the ring (the
    gossip rows then alias it in place — ``_mesh_gossip_lattice``) and
    restores ``model.state`` from a pre-round device copy afterwards:
    the overflow→widen fallback needs the pre-round state alive across
    a failed attempt, so the wrapper trades the ring-internal second
    state copy for one explicit snapshot while keeping the model
    coherent either way.

    ``stability=True`` threads the flag into the ring (the rows come
    back compacted, the mesh-wide frontier rides as the LAST tuple
    element — reclaim/). ``reclaim=`` takes an ``elastic.Hysteresis``
    tracker and is the shrink analog of the widen loop: after the
    successful attempt it observes the model's occupancy and — once the
    low-water streak clears — narrows the implicated axes in place, so
    the model carries the reclaimed capacity into its next round
    (administrative, like widening: apply identically on every host).

    ``faults=`` threads a ``crdt_tpu.faults.FaultPlan`` into every
    attempt; the LAST tuple element is then the ``FaultCounters``
    pytree with packet counters summed across attempts.
    ``lag_threshold=`` is the frontier-stall alert
    (``_mesh_gossip_lattice``)."""
    from .. import elastic
    from ..models.map import BatchedMap
    from ..models.orswot import BatchedOrswot
    from ..models.sparse_mvmap import BatchedSparseMap
    from ..models.sparse_nested_map import BatchedSparseNestedMap
    from ..models.sparse_orswot import BatchedSparseOrswot

    policy = policy or elastic.DEFAULT_POLICY

    def plan(m):
        # (gossip runner, overflow-flag lane -> elastic axis)
        if isinstance(m, BatchedOrswot):
            return (
                lambda: mesh_gossip(m.state, mesh, rounds,
                                    telemetry=telemetry, donate=donate,
                                    stability=stability, faults=faults,
                                    lag_threshold=lag_threshold),
                ("deferred_cap",),
            )
        if isinstance(m, BatchedSparseOrswot):
            return (
                lambda: mesh_gossip_sparse(m.state, mesh, rounds,
                                           telemetry=telemetry,
                                           donate=donate,
                                           stability=stability,
                                           faults=faults,
                                           lag_threshold=lag_threshold),
                ("dot_cap", "deferred_cap"),
            )
        if isinstance(m, BatchedMap):
            return (
                lambda: mesh_gossip_map(m.state, mesh, rounds,
                                        telemetry=telemetry,
                                        donate=donate,
                                        stability=stability,
                                        faults=faults,
                                        lag_threshold=lag_threshold),
                ("sibling_cap", "deferred_cap"),
            )
        if isinstance(m, BatchedSparseMap):
            return (
                lambda: mesh_gossip_sparse_mvmap(
                    m.state, mesh, rounds, sibling_cap=m.sibling_cap,
                    telemetry=telemetry, donate=donate,
                    stability=stability, faults=faults,
                    lag_threshold=lag_threshold,
                ),
                ("cell_cap", "deferred_cap", "sibling_cap"),
            )
        if isinstance(m, BatchedSparseNestedMap):
            return (
                lambda: mesh_gossip_sparse_nested(
                    m.state, mesh, m.level, rounds, telemetry=telemetry,
                    donate=donate, stability=stability, faults=faults,
                    lag_threshold=lag_threshold,
                ),
                ("cell_cap", "deferred_cap", "sibling_cap",
                 "key_deferred_cap"),
            )
        raise TypeError(
            f"gossip_elastic covers the batched set/map family, got "
            f"{type(m).__name__}"
        )

    widened: dict = {}
    migrations = 0
    tel = None
    fcs = None
    while True:
        run, lanes = plan(model)
        if donate:
            snap = jax.tree.map(jnp.copy, model.state)
        out = run()
        if donate:
            model.state = snap
        if faults is not None:
            from .. import faults as flt

            fcs = flt.accumulate_counters(fcs, out[-1])
            out = out[:-1]
        rows, flags = out[0], out[1]
        frontier = out[-1] if stability else None
        if telemetry:
            tel = out[2] if tel is None else tele.combine(tel, out[2])
        flags = jnp.atleast_1d(flags)
        hot = tuple(
            axis for lane, axis in enumerate(lanes) if bool(flags[lane])
        )
        if not hot:
            if reclaim is not None:
                # The shrink half of the elastic loop: COMMIT the
                # converged rows into the model (the shrink must narrow
                # the state the model carries into its next round, not
                # the stale pre-round one), then let the hysteresis
                # decide — see elastic.Hysteresis. After a reclaim
                # round, read the model, not the returned rows (a
                # shrink leaves them at the old capacity).
                _commit_rows(model, rows)
                reclaim.observe(model)
            ret = [rows, widened]
            if telemetry:
                ret.append(tel)
            if stability:
                ret.append(frontier)
            if fcs is not None:
                ret.append(fcs)
            return tuple(ret) if len(ret) > 2 else (rows, widened)
        if migrations >= policy.max_migrations:
            raise RuntimeError(
                f"gossip still overflowing after {migrations} migrations "
                f"(axes grown: {widened}) — raise policy.factor or "
                f"max_migrations"
            )
        metrics.count("elastic.gossip_migrations")
        widened.update(elastic.widen(model, hot, policy))
        migrations += 1


def _commit_rows(model, rows) -> None:
    """Commit gossip rows back into a model for the reclaim path: slice
    the identity-padded tail off and assign — skipped (model untouched)
    when the mesh padded other axes too and shapes cannot line up
    (shrinking the pre-round state is still sound; narrow refuses
    anything unfit)."""
    lead = jax.tree.leaves(model.state)[0].shape[0]
    sliced = jax.tree.map(lambda x: x[:lead], rows)
    if all(
        a.shape == b.shape and a.dtype == b.dtype
        for a, b in zip(jax.tree.leaves(sliced), jax.tree.leaves(model.state))
    ):
        model.state = sliced


def mesh_fold_clocks(clocks: jax.Array, mesh: Mesh) -> jax.Array:
    """Converge a batch of vector clocks [R, A] (VClock / GCounter /
    PNCounter states) over the mesh: local max + ``pmax`` across the
    replica axis. BASELINE configs 1–2 at mesh scale."""
    rsize = mesh.shape[REPLICA_AXIS]
    r = clocks.shape[0]
    pad = (-r) % rsize
    if pad:
        clocks = jnp.concatenate(
            [clocks, jnp.zeros((pad, clocks.shape[1]), clocks.dtype)], axis=0
        )

    def build():
        @partial(
            jax.shard_map,
            mesh=mesh,
            in_specs=(P(REPLICA_AXIS, None),),
            out_specs=P(None),
            check_vma=False,
        )
        def fold_fn(local):
            return all_reduce_clock(jnp.max(local, axis=0), REPLICA_AXIS)

        return fold_fn

    return _cached("clock_fold", clocks, mesh, build)(clocks)


def mesh_fold_map3(state, mesh: Mesh, telemetry: bool = False,
                   donate: bool = False):
    """Full-mesh anti-entropy for ``Map<K1, Map<K2, Orswot>>`` over the
    (replica × outer-key) mesh (K1×K2×M blocks per shard; ops/map3.py
    depth-3 slab composition). Returns (converged state, overflow[3])."""
    from ..ops import map3 as map3_ops
    from .mesh import map3_out_specs, map3_specs, pad_map3

    state = pad_map3(state, mesh.shape[REPLICA_AXIS], mesh.shape[ELEMENT_AXIS])
    return _mesh_fold_lattice(
        "map3_fold", state, mesh,
        partial(map3_ops.join, element_axis=ELEMENT_AXIS),
        partial(map3_ops.fold, element_axis=ELEMENT_AXIS),
        map3_specs(), map3_out_specs(),
        telemetry=telemetry,
        slots_fn=lambda a, b: ops.changed_members(a.mo.core, b.mo.core),
        element_sharded=True, donate=donate,
    )


def mesh_gossip_map3(
    state, mesh: Mesh, rounds: Optional[int] = None, telemetry: bool = False,
    donate: bool = False, stability: bool = False, faults=None,
    lag_threshold=None,
):
    """Ring anti-entropy for ``Map<K1, Map<K2, Orswot>>`` replica blocks
    over the replica axis."""
    from ..ops import map3 as map3_ops
    from .mesh import map3_specs, pad_map3

    state = pad_map3(state, mesh.shape[REPLICA_AXIS], mesh.shape[ELEMENT_AXIS])
    return _mesh_gossip_lattice(
        "map3_gossip", state, mesh,
        partial(map3_ops.join, element_axis=ELEMENT_AXIS),
        partial(map3_ops.fold, element_axis=ELEMENT_AXIS),
        map3_specs(), rounds,
        telemetry=telemetry,
        slots_fn=lambda a, b: ops.changed_members(a.mo.core, b.mo.core),
        donate=donate, stability=stability, compact_fn=map3_ops.compact,
        faults=faults, lag_threshold=lag_threshold,
    )


# ---- static-analysis registration (crdt_tpu.analysis) --------------------
#
# Every mesh entry point defined here registers its jit-cache kind, an
# example-args builder (an R == P batch of join identities, in the
# shared gate geometry of crdt_tpu.analysis.gate_states) and an
# invoker. tools/check_aliasing.py and crdt_tpu.analysis.jit_lint
# iterate this registry, and a public ``mesh_*`` entry that forgets to
# register fails discovery (tests/test_analysis.py).

from ..analysis import gate_states as _gs  # noqa: E402
from ..analysis.registry import register_entry_point as _reg_ep  # noqa: E402


def _reg(name, kind, mk, call, n_donated):
    _reg_ep(
        name, kind=kind,
        make_args=lambda mesh: (mk(_gs.replicas(mesh)),),
        invoke=lambda mesh, args: call(args[0], mesh),
        n_donated=n_donated,
        # The collective-semantics lint fails any collective touching an
        # axis name outside this set (jit_lint.py).
        mesh_axes=(REPLICA_AXIS, ELEMENT_AXIS),
    )


def _reg_gossip(name, kind, mk, call):
    _reg(name, kind, mk, call, n_donated=1)


def _reg_fold(name, kind, mk, call):
    _reg(name, kind, mk, call, n_donated=0)


_reg_gossip(
    "mesh_gossip", "orswot_gossip", _gs.mk_dense,
    lambda s, mesh: mesh_gossip(s, mesh, local_fold="tree", donate=True),
)
_reg_gossip(
    "mesh_gossip_map", "map_gossip", _gs.mk_map,
    lambda s, mesh: mesh_gossip_map(s, mesh, donate=True),
)
_reg_gossip(
    "mesh_gossip_map_orswot", "map_orswot_gossip", _gs.mk_map_orswot,
    lambda s, mesh: mesh_gossip_map_orswot(s, mesh, donate=True),
)
_reg_gossip(
    "mesh_gossip_nested_map", "nested_map_gossip", _gs.mk_nested_map,
    lambda s, mesh: mesh_gossip_nested_map(s, mesh, donate=True),
)
_reg_gossip(
    "mesh_gossip_map3", "map3_gossip", _gs.mk_map3,
    lambda s, mesh: mesh_gossip_map3(s, mesh, donate=True),
)
_reg_gossip(
    "mesh_gossip_sparse", "sparse_gossip", _gs.mk_sparse,
    lambda s, mesh: mesh_gossip_sparse(s, mesh, donate=True),
)
_reg_gossip(
    "mesh_gossip_sparse_mvmap", "sparse_mvmap_gossip_s4", _gs.mk_sparse_mvmap,
    lambda s, mesh: mesh_gossip_sparse_mvmap(s, mesh, donate=True),
)
_reg_gossip(
    "mesh_gossip_sparse_nested", f"sparse_nested_gossip_{_gs.GM}_s0",
    _gs.mk_sparse_nested,
    lambda s, mesh: mesh_gossip_sparse_nested(
        s, mesh, _gs.sparse_nested_level(), donate=True
    ),
)

_reg_fold(
    "mesh_fold", "orswot_fold", _gs.mk_dense,
    lambda s, mesh: mesh_fold(s, mesh, local_fold="tree"),
)
_reg_fold("mesh_fold_map", "map_fold", _gs.mk_map, mesh_fold_map)
_reg_fold(
    "mesh_fold_map_orswot", "map_orswot_fold", _gs.mk_map_orswot,
    mesh_fold_map_orswot,
)
_reg_fold(
    "mesh_fold_nested_map", "nested_map_fold", _gs.mk_nested_map,
    mesh_fold_nested_map,
)
_reg_fold("mesh_fold_map3", "map3_fold", _gs.mk_map3, mesh_fold_map3)
_reg_fold("mesh_fold_gset", "gset_fold", _gs.mk_gset, mesh_fold_gset)
_reg_fold("mesh_fold_lww", "lww_fold", _gs.mk_lww, mesh_fold_lww)
_reg_fold("mesh_fold_mvreg", "mvreg_fold", _gs.mk_mvreg, mesh_fold_mvreg)
_reg_fold(
    "mesh_fold_sparse", "sparse_orswot_fold", _gs.mk_sparse, mesh_fold_sparse
)
_reg_fold(
    "mesh_fold_sparse_mvmap", "sparse_mvmap_fold_s4", _gs.mk_sparse_mvmap,
    mesh_fold_sparse_mvmap,
)
_reg_fold(
    "mesh_fold_sparse_nested", f"sparse_nested_fold_{_gs.GM}_s0",
    _gs.mk_sparse_nested,
    lambda s, mesh: mesh_fold_sparse_nested(
        s, mesh, _gs.sparse_nested_level()
    ),
)
_reg_fold("mesh_fold_clocks", "clock_fold", _gs.mk_clocks, mesh_fold_clocks)

# Fault surfaces (crdt_tpu/faults/): every gossip entry above accepts
# faults=; registration is the coverage contract faults.static_checks
# enforces (an unregistered fault-capable public entry fails discovery).
from ..analysis.registry import register_fault_surface as _reg_fs  # noqa: E402

for _name in (
    "mesh_gossip", "mesh_gossip_map", "mesh_gossip_map_orswot",
    "mesh_gossip_nested_map", "mesh_gossip_map3", "mesh_gossip_sparse",
    "mesh_gossip_sparse_mvmap", "mesh_gossip_sparse_nested",
    "gossip_elastic",
):
    _reg_fs(_name, module=__name__)
