"""``mesh_fanout_push`` — the cohort δ fan-out dispatch (ISSUE 16).

One jitted shard_map computes a whole dispatch of per-cohort
join-irreducible δ payloads against the serve superblock: the lane
axis shards over the REPLICA mesh axis (cohorts are independent — zero
cross-cohort collectives), each device gathers its touched tenant
rows, vmap-decomposes them against the cohort base rows
(ops/fanout_kernels.cohort_deltas), and runs the WHOLE local batch
through ONE fused wire-pack pass (cohort_wire_encode — the PR 14
kernel generalized from P ring links to B·E client lanes).

Index convention matches ``mesh_serve_apply``: ``idx[B] int32``
carries LOCAL row indices — lane block ``[r·B/P, (r+1)·B/P)`` belongs
to mesh rank ``r`` and its values index that rank's local tenant rows
``[0, T/P)``; ``-1`` lanes are empty (their wire lanes zero and their
byte price drops). The host-side subscription plane
(crdt_tpu/fanout/plane.py) owns this layout via the superblock's
tenant→lane indirection. ``bases[B, ...]`` stacks each cohort's acked
base row (the plane's promote-on-ack copy — delta_opt/ackwin.py
semantics), sharded alongside the lanes; ``weights[B]`` carries cohort
sizes so the byte telemetry prices every subscriber delivery, not just
every cohort.

The dispatch only READS the superblock — nothing donates
(``n_donated=0``; the aliasing gate sees a pure read). ``telemetry=``
follows the house rules: off traces the byte-identical flag-free
program; on returns a Telemetry sidecar — ``cohorts_per_dispatch`` /
``delta_push_bytes`` psum'd over the replica axis, the per-cohort
prices observed into the ``hist_push_bytes`` in-kernel histogram in
one vectorized scatter (obs/hist.observe_vec). The host-owned
``subscribers_live`` gauge and ``resync_fallbacks`` counter are filled
by the plane (the ``stream_*``/``wal_*`` fill discipline).
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .. import telemetry as tele
from ..obs import hist as obs_hist
from ..ops import superblock as sb_ops
from ..ops.fanout_kernels import (
    cohort_deltas,
    cohort_push_bytes,
    cohort_wire_encode,
)
from .anti_entropy import _cached
from .mesh import REPLICA_AXIS


def _validate(state, bases, idx, p: int) -> None:
    t = jax.tree.leaves(state)[0].shape[0]
    b = jax.tree.leaves(bases)[0].shape[0]
    if t % p:
        raise ValueError(
            f"{t} tenant rows do not divide the {p}-way replica axis"
        )
    if b % p or idx.shape[0] != b:
        raise ValueError(
            f"base lanes ({b}) and idx ({idx.shape[0]}) must match and "
            f"divide the {p}-way replica axis"
        )


def _local_push(kind: str, state, bases, idx):
    """The per-device core (also traced under ``jax.eval_shape`` to
    derive the wire's out_specs): gather → vmapped decompose vs the
    cohort bases → one fused wire-pack over the local batch → the
    per-cohort byte price. Empty lanes (``idx < 0``) zero out of the
    lane mask, the wire, and the price."""
    from ..analysis.registry import get_decomposer
    from ..delta_opt.decompose import Decomposition

    tl = jax.tree.leaves(state)[0].shape[0]
    safe = jnp.clip(idx, 0, tl - 1)
    rows = jax.tree.map(lambda x: x[safe], state)
    lane_ok = idx >= 0
    d = cohort_deltas(kind, rows, bases)
    valid = d.valid & lane_ok[:, None]
    d = Decomposition(
        lanes=jax.tree.map(
            lambda x: jnp.where(
                valid.reshape(valid.shape + (1,) * (x.ndim - 2)),
                x, jnp.zeros_like(x),
            ),
            d.lanes,
        ),
        valid=valid,
        residual=d.residual,
    )
    base_rows, _ = get_decomposer(kind).split(bases)
    wire = cohort_wire_encode(d, jax.tree.leaves(base_rows)[0])
    pb = jnp.where(lane_ok, cohort_push_bytes(wire), 0.0)
    return wire, pb


def mesh_fanout_push(
    state,
    bases,
    idx,
    mesh: Mesh,
    *,
    kind: str = "orswot",
    weights=None,
    telemetry: bool = False,
):
    """Compute one dispatch of cohort δ pushes against a tenant
    superblock, sharded over the replica mesh axis. Returns
    ``(wire, push_bytes[B])`` — or ``(wire, push_bytes, Telemetry)``
    with ``telemetry=True`` (module docstring)."""
    sb_ops.tenant_kind(kind)  # fail fast on an unregistered kind
    p = mesh.shape[REPLICA_AXIS]
    idx = jnp.asarray(idx, jnp.int32)
    _validate(state, bases, idx, p)
    weights = (
        jnp.ones(idx.shape, jnp.float32) if weights is None
        else jnp.asarray(weights, jnp.float32)
    )

    # The wire's pytree structure (for out_specs): trace the core once
    # abstractly — scalar leaves (nnz/chk) replicate, batched leaves
    # shard over the replica axis like the lanes they price.
    wire_struct, _ = jax.eval_shape(
        lambda s, b, i: _local_push(kind, s, b, i), state, bases, idx
    )
    row_spec = P(REPLICA_AXIS)
    wire_spec = jax.tree.map(
        lambda s: row_spec if s.ndim else P(), wire_struct
    )

    def build():
        def body(state, bases, idx, wts):
            wire, pb = _local_push(kind, state, bases, idx)
            wire = wire._replace(
                nnz=lax.psum(wire.nnz, REPLICA_AXIS),
                chk=lax.psum(wire.chk, REPLICA_AXIS),
            )
            if not telemetry:
                return wire, pb
            lane_ok = idx >= 0
            h = obs_hist.observe_vec(obs_hist.zeros(), pb, lane_ok)
            tel = tele.zeros()._replace(
                cohorts_per_dispatch=lax.psum(
                    jnp.sum(lane_ok, dtype=jnp.uint32), REPLICA_AXIS
                ),
                # Price every subscriber DELIVERY: one cohort payload
                # fans out to `wts` clients.
                delta_push_bytes=lax.psum(
                    jnp.sum(pb * wts, dtype=jnp.float32), REPLICA_AXIS
                ),
                hist_push_bytes=obs_hist.psum(h, REPLICA_AXIS),
            )
            return wire, pb, tel

        in_specs = (
            jax.tree.map(lambda _: row_spec, state),
            jax.tree.map(lambda _: row_spec, bases),
            row_spec,
            row_spec,
        )
        out_specs = (wire_spec, row_spec) + (
            (tele.specs(),) if telemetry else ()
        )
        return jax.shard_map(
            body,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_vma=False,
        )

    fn = _cached(
        "fanout_push", (state, bases, idx, weights), mesh, build, kind,
        telemetry,
    )
    t0 = time.perf_counter()
    out = fn(state, bases, idx, weights)
    if telemetry:
        jax.block_until_ready(out)
        wire, pb, tel = out
        tel = tele.time_dispatch(tel, time.perf_counter() - t0)
        return wire, pb, tel
    return out


# ---- static-analysis registration (crdt_tpu.analysis) --------------------

def _example(mesh: Mesh, kind: str = "orswot"):
    p = mesh.shape[REPLICA_AXIS]
    caps = dict(n_elems=4, n_actors=2, deferred_cap=2)
    tk = sb_ops.tenant_kind(kind)
    t, b = p * 4, p * 2
    state = tk.empty(**caps, batch=(t,))
    bases = tk.empty(**caps, batch=(b,))
    import numpy as np

    idx = jnp.asarray(np.tile(np.arange(b // p, dtype=np.int32), p))
    # Weights ride as a positional example arg so the jit-lint/cost
    # gates trace the cached fn with the exact calling convention.
    return state, bases, idx, jnp.ones(idx.shape, jnp.float32)


def _register() -> None:
    from ..analysis.registry import register_entry_point

    register_entry_point(
        "mesh_fanout_push",
        kind="fanout_push",
        make_args=_example,
        invoke=lambda mesh, args: mesh_fanout_push(
            args[0], args[1], args[2], mesh, weights=args[3]
        ),
        n_donated=0,
    )


_register()

__all__ = ["mesh_fanout_push"]
