"""δ-state anti-entropy for ``Map<K1, Map<K2, Orswot<M>>>`` — the
delta induction applied once more: the depth-3 state is the map_orswot
delta machinery on its flat ``mo`` slab (cells over K1×K2×M) plus the
K1-level parked keyset buffer riding whole, settled through the shared
outer-level sequence and scrubbed at (K1,K2) and K1 granularity exactly
as ops/map3.join does."""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from ..ops import map3 as m3_ops
from ..ops.map3 import Map3State
from ..ops.outer_level import concat_outer, settle_outer_level
from .delta import close_top_orswot, interval_accumulate
from .delta_map_orswot import (
    MapOrswotDeltaPacket,
    apply_delta_mo,
    extract_delta_mo,
)
from .mesh import ELEMENT_AXIS, REPLICA_AXIS, map3_specs, pad_map3


class Map3DeltaPacket(NamedTuple):
    """The map_orswot packet on the flat slab + the K1 buffer."""

    mo: MapOrswotDeltaPacket
    odcl: jax.Array     # [D, A]
    odkeys: jax.Array   # [D, K1]
    odvalid: jax.Array  # [D]


def interval_accumulate_m3(
    dirty: jax.Array, fctx: jax.Array, old: Map3State, new: Map3State
) -> Tuple[jax.Array, jax.Array]:
    """delta.interval_accumulate on the flat leaf cells (K1×K2×M)."""
    return interval_accumulate(dirty, fctx, old.mo.core, new.mo.core)


def extract_delta_m3(
    state: Map3State, dirty: jax.Array, fctx: jax.Array, cap: int, start=0
) -> Tuple[Map3DeltaPacket, jax.Array, jax.Array]:
    mo_pkt, dirty, fctx = extract_delta_mo(state.mo, dirty, fctx, cap, start)
    return (
        Map3DeltaPacket(
            mo=mo_pkt,
            odcl=state.odcl,
            odkeys=state.odkeys,
            odvalid=state.odvalid,
        ),
        dirty,
        fctx,
    )


def apply_delta_m3(
    state: Map3State,
    pkt: Map3DeltaPacket,
    dirty: jax.Array,
    fctx: jax.Array,
    element_axis=None,
):
    """mo-delta apply on the flat slab, then the K1 buffer settle and
    dead-K1 scrub. Returns ``(state, dirty, fctx, overflow[3])``."""
    mo, dirty, fctx, mo_of = apply_delta_mo(
        state.mo, pkt.mo, dirty, fctx, element_axis=element_axis
    )

    before = mo.core.ctr
    st = Map3State(
        mo,
        *concat_outer(
            (state.odcl, state.odkeys, state.odvalid),
            (pkt.odcl, pkt.odkeys, pkt.odvalid),
        ),
    )
    st, outer_of = settle_outer_level(
        st,
        state.odcl.shape[-2],
        get_bufs=lambda s: (s.odcl, s.odkeys, s.odvalid),
        with_bufs=lambda s, cl, ks, v: s._replace(odcl=cl, odkeys=ks, odvalid=v),
        replay=m3_ops._replay_outer,
        scrub=m3_ops._scrub_dead1,
        element_axis=element_axis,
    )
    replay_changed = jnp.any(st.mo.core.ctr != before, axis=-1)
    dirty = dirty | replay_changed
    fctx = jnp.maximum(fctx, jnp.where(replay_changed[:, None], before, 0))
    return st, dirty, fctx, jnp.stack([mo_of[0], mo_of[1], outer_of])


def mesh_delta_gossip_map3(
    state: Map3State,
    dirty: jax.Array,
    fctx: jax.Array,
    mesh: Mesh,
    rounds: Optional[int] = None,
    cap: int = 64,
):
    """Ring δ anti-entropy for depth-3 map replica batches (see
    delta.mesh_delta_gossip for semantics and budgeting). ``dirty`` /
    ``fctx`` are at leaf (k1, k2, member) cell granularity. Returns
    ``(states [P, ...], dirty, overflow[3])``."""
    from functools import partial

    from .delta_ring import run_delta_ring

    state = pad_map3(state, mesh.shape[REPLICA_AXIS], mesh.shape[ELEMENT_AXIS])
    pad_r = state.mo.core.top.shape[0] - dirty.shape[0]
    pad_e = state.mo.core.ctr.shape[-2] - dirty.shape[-1]
    dirty = jnp.pad(dirty, ((0, pad_r), (0, pad_e)))
    fctx = jnp.pad(fctx, ((0, pad_r), (0, pad_e), (0, 0)))

    def close_top(folded: Map3State, top: jax.Array) -> Map3State:
        core = close_top_orswot(folded.mo.core, top)
        mo = folded.mo._replace(core=core)
        # K2-level replay drops its caught-up slots; then the K1 level.
        from ..ops import map_orswot as mo_ops

        mo = mo_ops._replay_outer(mo)
        st = m3_ops._replay_outer(folded._replace(mo=mo))
        return m3_ops._scrub_dead1(st, element_axis=ELEMENT_AXIS)

    return run_delta_ring(
        "map3_delta_gossip", state, dirty, fctx, mesh, rounds, cap,
        specs=map3_specs(),
        local_fold=partial(m3_ops.fold, element_axis=ELEMENT_AXIS),
        extract=extract_delta_m3,
        apply_fn=partial(apply_delta_m3, element_axis=ELEMENT_AXIS),
        close_top=close_top,
        top_of=lambda s: s.mo.core.top,
    )
