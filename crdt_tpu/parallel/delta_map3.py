"""δ-state anti-entropy for ``Map<K1, Map<K2, Orswot<M>>>`` — the
δ induction (``delta_nest.nested_delta``) applied once more: the
depth-3 flavor is the map_orswot delta machinery on the flat ``mo``
slab (cells over K1×K2×M) plus the K1-level parked keyset buffer riding
whole, settled and scrubbed exactly as ops/map3.join does."""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from ..ops import map3 as m3_ops
from ..ops.map3 import Map3State
from ..ops.orswot import changed_members
from .delta import interval_accumulate
from .delta_map_orswot import (
    MapOrswotDeltaPacket,
    apply_delta_mo,
    extract_delta_mo,
    gate_delta_mo,
)
from .delta_nest import close_top_nested, nested_delta, nested_gate
from .mesh import ELEMENT_AXIS, REPLICA_AXIS, map3_specs, pad_map3


class Map3DeltaPacket(NamedTuple):
    """The map_orswot packet on the flat slab + the K1 buffer."""

    mo: MapOrswotDeltaPacket
    odcl: jax.Array     # [D, A]
    odkeys: jax.Array   # [D, K1]
    odvalid: jax.Array  # [D]


def interval_accumulate_m3(
    dirty: jax.Array, fctx: jax.Array, old: Map3State, new: Map3State
) -> Tuple[jax.Array, jax.Array]:
    """delta.interval_accumulate on the flat leaf cells (K1×K2×M)."""
    return interval_accumulate(dirty, fctx, old.mo.core, new.mo.core)


extract_delta_m3, apply_delta_m3 = nested_delta(
    m3_ops.LEVEL,
    extract_delta_mo,
    apply_delta_mo,
    packet_cls=Map3DeltaPacket,
)
gate_delta_m3 = nested_gate(gate_delta_mo, Map3DeltaPacket)


def mesh_delta_gossip_map3(
    state: Map3State,
    dirty: jax.Array,
    fctx: jax.Array,
    mesh: Mesh,
    rounds: Optional[int] = None,
    cap: int = 64,
    telemetry: bool = False,
    pipeline: bool = True,
    digest: bool = True,
    donate: bool = False,
    faults=None,
    ack_window=False,
    wal=None,
    fused: bool = True,
):
    """Ring δ anti-entropy for depth-3 map replica batches (see
    delta.mesh_delta_gossip for semantics and the ROUNDS BUDGET
    warning). ``dirty`` / ``fctx`` are at leaf (k1, k2, member) cell
    granularity. Returns ``(states [P, ...], dirty, overflow[3],
    residue)`` — residue is the runtime convergence indicator (0 =
    provably converged; see delta_ring.run_delta_ring)."""
    from .delta_ring import run_delta_ring

    state = pad_map3(state, mesh.shape[REPLICA_AXIS], mesh.shape[ELEMENT_AXIS])
    pad_r = state.mo.core.top.shape[0] - dirty.shape[0]
    pad_e = state.mo.core.ctr.shape[-2] - dirty.shape[-1]
    if pad_r or pad_e:  # zero-pad copies would defeat donation
        dirty = jnp.pad(dirty, ((0, pad_r), (0, pad_e)))
        fctx = jnp.pad(fctx, ((0, pad_r), (0, pad_e), (0, 0)))

    return run_delta_ring(
        "map3_delta_gossip", state, dirty, fctx, mesh, rounds, cap,
        specs=map3_specs(),
        local_fold=partial(m3_ops.fold, element_axis=ELEMENT_AXIS),
        extract=extract_delta_m3,
        apply_fn=partial(apply_delta_m3, element_axis=ELEMENT_AXIS),
        close_top=partial(
            close_top_nested, m3_ops.LEVEL, element_axis=ELEMENT_AXIS
        ),
        top_of=lambda s: s.mo.core.top,
        telemetry=telemetry,
        slots_fn=lambda a, b: changed_members(a.mo.core, b.mo.core),
        pipeline=pipeline, digest=digest, gate=gate_delta_m3,
        donate=donate, faults=faults, ack_window=ack_window,
        wal=wal, wal_kind="map3", fused=fused,
    )


# ---- static-analysis registration (crdt_tpu.analysis) --------------------

def _register():
    from ..analysis import gate_states as gs
    from .delta import _reg_delta_ep

    _reg_delta_ep(
        "mesh_delta_gossip_map3", "map3_delta_gossip",
        gs.mk_map3, gs.GK1 * gs.GK2 * gs.GM,
        lambda s, d, f, mesh: mesh_delta_gossip_map3(
            s, d, f, mesh, donate=True
        ),
    )

    from ..analysis.registry import register_fault_surface

    register_fault_surface("mesh_delta_gossip_map3", module=__name__)

_register()
