"""Element sharding for the sparse (segment-encoded) backend.

Round 4's ``mesh_fold_sparse`` reduced the replica axis but left every
segment table replicated across the element axis (VERDICT r04 Missing
#2 / Weak #5) — the one representation built for huge universes didn't
scale by elements. This module is the missing SP analog: partition each
replica's segment table by ``eid % n_shards``. The restriction of a
sparse ORSWOT to an element subuniverse is itself a sparse ORSWOT, and
every join rule is per-element (cell matching, top subsumption, parked
replay, dedupe-by-clock), so

    restrict(join(a, b), s)  ==  join(restrict(a, s), restrict(b, s))

— shard-local joins are exact, no cross-shard traffic for the flat
type. Per-shard state: the shard's dot lanes, the shard's parked
member-remove entries, and a REPLICATED top clock [A] (tiny; every
shard computes the same max, so it stays consistent).

For the NESTED sparse type (ops/sparse_nest.py) the parked KEY lists
stay replicated across shards (a key's members span all shards) and the
only cross-shard coupling is the scrub's key-liveness test — a psum
over the element axis (``sparse_nest._ids_alive(element_axis=...)``),
mirroring the dense ``ops/nest._any_slots``. Everything else remains
shard-local.

Layout convention: axis 0 = replicas, axis 1 = element shards. Both
mesh axes shard (``P(REPLICA_AXIS, ELEMENT_AXIS)`` on every leaf; the
replicated pieces ride as per-shard copies, which the uniform layout
keeps trivially consistent).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import sparse_nest as nest
from ..ops import sparse_orswot as sp
from ..ops.sparse_nest import SparseNestState
from ..ops.sparse_orswot import SparseOrswotState, _canon, _canon_rmlist
from ..utils.metrics import metrics, observe_depth, state_nbytes
from .anti_entropy import _cached
from .mesh import ELEMENT_AXIS, REPLICA_AXIS


def split_segments(
    state: SparseOrswotState,
    n_shards: int,
    dot_cap: Optional[int] = None,
) -> SparseOrswotState:
    """Partition a (batched) segment table by ``eid % n_shards`` into
    per-shard restrictions: ``[R, ...] -> [R, S, ...]``. ``dot_cap``
    sizes the per-shard lane count (default: the full cap, conservative
    against skew; a uniform universe can safely use ~C/S + slack)."""
    cap = dot_cap or state.eid.shape[-1]

    def restrict(shard: int) -> SparseOrswotState:
        keep = state.valid & (state.eid % n_shards == shard)
        eid, act, ctr, valid, overflow = _canon(
            jnp.where(keep, state.eid, -1),
            jnp.where(keep, state.act, 0),
            jnp.where(keep, state.ctr, 0),
            keep,
            cap,
        )
        if bool(jnp.any(overflow)):
            raise ValueError(
                f"shard {shard}: live dots exceed the per-shard cap {cap}"
            )
        didx = _canon_rmlist(
            jnp.where(
                (state.didx >= 0) & (state.didx % n_shards == shard),
                state.didx,
                -1,
            )
        )
        dvalid = state.dvalid & jnp.any(didx >= 0, axis=-1)
        return SparseOrswotState(
            top=state.top,  # replicated per shard
            eid=eid, act=act, ctr=ctr, valid=valid,
            dcl=jnp.where(dvalid[..., None], state.dcl, 0),
            didx=jnp.where(dvalid[..., None], didx, -1),
            dvalid=dvalid,
        )

    shards = [restrict(s) for s in range(n_shards)]
    return jax.tree.map(lambda *xs: jnp.stack(xs, axis=1), *shards)


def split_nested(
    state: SparseNestState, n_shards: int, dot_cap: Optional[int] = None
) -> SparseNestState:
    """Partition a (batched) nested sparse state: leaf segments split by
    ``eid % n_shards``, parked KEY lists replicated to every shard
    (``[R, ...] -> [R, S, ...]`` on every leaf)."""
    if isinstance(state.core, SparseNestState):
        core = split_nested(state.core, n_shards, dot_cap)
    else:
        core = split_segments(state.core, n_shards, dot_cap)
    rep = lambda x: jnp.repeat(x[:, None], n_shards, axis=1)
    return SparseNestState(
        core=core, kcl=rep(state.kcl), kidx=rep(state.kidx),
        kdvalid=rep(state.kdvalid),
    )


def _all_specs(state, lead=(REPLICA_AXIS, ELEMENT_AXIS)):
    return jax.tree.map(lambda _: P(*lead), state)


def _pad_replica_axis(state, rsize: int, make_identity):
    lead = jax.tree.leaves(state)[0].shape[0]
    pad = (-lead) % rsize
    if not pad:
        return state
    ident = make_identity(pad)
    return jax.tree.map(
        lambda x, p: jnp.concatenate([x, p.astype(x.dtype)], axis=0),
        state, ident,
    )


def mesh_fold_sparse_sharded(
    states: SparseOrswotState, mesh: Mesh
) -> Tuple[SparseOrswotState, jax.Array]:
    """Converge an element-SHARDED sparse replica batch ``[R, S, ...]``
    (from ``split_segments``; S must equal the mesh's element-axis size)
    over the mesh. Shard-local joins are exact (restriction commutes
    with join), so the only collective is the replica-axis lattice
    all-reduce — per-device state and join cost drop by S. Returns
    ``(state [S, ...], overflow[2])`` with the element axis preserved."""
    s_axis = jax.tree.leaves(states)[0].shape[1]
    if s_axis != mesh.shape[ELEMENT_AXIS]:
        raise ValueError(
            f"state has {s_axis} element shards, mesh axis is "
            f"{mesh.shape[ELEMENT_AXIS]}"
        )
    states = _pad_replica_axis(
        states, mesh.shape[REPLICA_AXIS],
        lambda pad: jax.tree.map(
            lambda x: jnp.zeros((pad, *x.shape[1:]), x.dtype), states
        )._replace(
            eid=jnp.full((pad, *states.eid.shape[1:]), -1, jnp.int32),
            didx=jnp.full((pad, *states.didx.shape[1:]), -1, jnp.int32),
        ),
    )

    def build():
        @partial(
            jax.shard_map,
            mesh=mesh,
            in_specs=(_all_specs(states),),
            out_specs=(_all_specs(states, (ELEMENT_AXIS,)), P()),
            check_vma=False,
        )
        def fold_fn(local):
            local = jax.tree.map(lambda x: x[:, 0], local)  # drop shard axis
            folded, of_local = sp.fold(local)
            joined, of_cross = _lattice_allreduce(folded, sp.join, sp.fold)
            of = (
                lax.psum(of_local.astype(jnp.int32), REPLICA_AXIS) > 0
            ) | of_cross
            of = lax.psum(of.astype(jnp.int32), ELEMENT_AXIS) > 0
            return jax.tree.map(lambda x: x[None], joined), of

        return fold_fn

    metrics.count("anti_entropy.sparse_sharded_fold_rounds")
    metrics.observe("anti_entropy.state_bytes", state_nbytes(states))
    observe_depth("anti_entropy.sparse_sharded_fold", states)
    with metrics.time("anti_entropy.sparse_sharded_fold"):
        out = _cached("sparse_sharded_fold", states, mesh, build)(states)
        jax.block_until_ready(out)
    return out


def mesh_fold_sparse_map(
    states: SparseNestState, mesh: Mesh, span: int
) -> Tuple[SparseNestState, jax.Array]:
    """Converge an element-sharded SPARSE ``Map<K, Orswot>`` replica
    batch ``[R, S, ...]`` (from ``split_nested``) over the mesh. The
    nested join runs shard-local except the scrub's key-liveness psum
    across the element axis. ``span`` is the level's static leaf-ids-
    per-key constant (``BatchedSparseMapOrswot.span``). Returns
    ``(state [S, ...], overflow[3])``."""
    s_axis = jax.tree.leaves(states)[0].shape[1]
    if s_axis != mesh.shape[ELEMENT_AXIS]:
        raise ValueError(
            f"state has {s_axis} element shards, mesh axis is "
            f"{mesh.shape[ELEMENT_AXIS]}"
        )
    level = nest.level_map_orswot(span)
    states = _pad_replica_axis(
        states, mesh.shape[REPLICA_AXIS],
        lambda pad: jax.tree.map(
            lambda x: jnp.zeros((pad, *x.shape[1:]), x.dtype), states
        )._replace(
            core=jax.tree.map(
                lambda x: jnp.zeros((pad, *x.shape[1:]), x.dtype), states.core
            )._replace(
                eid=jnp.full((pad, *states.core.eid.shape[1:]), -1, jnp.int32),
                didx=jnp.full(
                    (pad, *states.core.didx.shape[1:]), -1, jnp.int32
                ),
            ),
            kidx=jnp.full((pad, *states.kidx.shape[1:]), -1, jnp.int32),
        ),
    )

    def build():
        @partial(
            jax.shard_map,
            mesh=mesh,
            in_specs=(_all_specs(states),),
            out_specs=(_all_specs(states, (ELEMENT_AXIS,)), P()),
            check_vma=False,
        )
        def fold_fn(local):
            local = jax.tree.map(lambda x: x[:, 0], local)
            folded, of_local = level.fold(local, element_axis=ELEMENT_AXIS)
            joined, of_cross = _lattice_allreduce(
                folded,
                partial(level.join, element_axis=ELEMENT_AXIS),
                partial(level.fold, element_axis=ELEMENT_AXIS),
            )
            of = (
                lax.psum(of_local.astype(jnp.int32), REPLICA_AXIS) > 0
            ) | of_cross
            of = lax.psum(of.astype(jnp.int32), ELEMENT_AXIS) > 0
            return jax.tree.map(lambda x: x[None], joined), of

        return fold_fn

    metrics.count("anti_entropy.sparse_map_fold_rounds")
    metrics.observe("anti_entropy.state_bytes", state_nbytes(states))
    observe_depth("anti_entropy.sparse_map_fold", states)
    with metrics.time("anti_entropy.sparse_map_fold"):
        out = _cached("sparse_map_fold", states, mesh, build, span)(states)
        jax.block_until_ready(out)
    return out


def _lattice_allreduce(local, join_fn, fold_fn):
    """all_reduce_lattice with array-valued overflow flags."""
    from .collectives import all_reduce_lattice

    return all_reduce_lattice(local, REPLICA_AXIS, join_fn, fold_fn)
