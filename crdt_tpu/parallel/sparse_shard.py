"""Element sharding for the sparse (segment-encoded) backend.

Round 4's ``mesh_fold_sparse`` reduced the replica axis but left every
segment table replicated across the element axis (VERDICT r04 Missing
#2 / Weak #5) — the one representation built for huge universes didn't
scale by elements. This module is the missing SP analog: partition each
replica's segment table by ``eid % n_shards``. The restriction of a
sparse ORSWOT to an element subuniverse is itself a sparse ORSWOT, and
every join rule is per-element (cell matching, top subsumption, parked
replay, dedupe-by-clock), so

    restrict(join(a, b), s)  ==  join(restrict(a, s), restrict(b, s))

— shard-local joins are exact, no cross-shard traffic for the flat
type. Per-shard state: the shard's dot lanes, the shard's parked
member-remove entries, and a REPLICATED top clock [A] (tiny; every
shard computes the same max, so it stays consistent).

For the NESTED sparse type (ops/sparse_nest.py) the parked KEY lists
stay replicated across shards (a key's members span all shards) and the
only cross-shard coupling is the scrub's key-liveness test — a psum
over the element axis (``sparse_nest._ids_alive(element_axis=...)``),
mirroring the dense ``ops/nest._any_slots``. Everything else remains
shard-local.

Layout convention: axis 0 = replicas, axis 1 = element shards. Both
mesh axes shard (``P(REPLICA_AXIS, ELEMENT_AXIS)`` on every leaf; the
replicated pieces ride as per-shard copies, which the uniform layout
keeps trivially consistent).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..ops import sparse_nest as nest
from ..ops import sparse_orswot as sp
from ..ops.sparse_nest import SparseNestState
from ..ops.sparse_orswot import SparseOrswotState, _canon, _canon_rmlist
from ..utils.metrics import metrics, observe_depth, state_nbytes
from .anti_entropy import _cached
from .mesh import ELEMENT_AXIS, REPLICA_AXIS


def split_segments(
    state: SparseOrswotState,
    n_shards: int,
    dot_cap: Optional[int] = None,
) -> SparseOrswotState:
    """Partition a (batched) segment table by ``eid % n_shards`` into
    per-shard restrictions: ``[R, ...] -> [R, S, ...]``. ``dot_cap``
    sizes the per-shard lane count (default: the full cap, conservative
    against skew; a uniform universe can safely use ~C/S + slack)."""
    cap = dot_cap or state.eid.shape[-1]

    def restrict(shard: int) -> SparseOrswotState:
        keep = state.valid & (state.eid % n_shards == shard)
        eid, act, ctr, valid, overflow = _canon(
            jnp.where(keep, state.eid, -1),
            jnp.where(keep, state.act, 0),
            jnp.where(keep, state.ctr, 0),
            keep,
            cap,
        )
        if bool(jnp.any(overflow)):
            raise ValueError(
                f"shard {shard}: live dots exceed the per-shard cap {cap}"
            )
        didx = _canon_rmlist(
            jnp.where(
                (state.didx >= 0) & (state.didx % n_shards == shard),
                state.didx,
                -1,
            )
        )
        dvalid = state.dvalid & jnp.any(didx >= 0, axis=-1)
        return SparseOrswotState(
            top=state.top,  # replicated per shard
            eid=eid, act=act, ctr=ctr, valid=valid,
            dcl=jnp.where(dvalid[..., None], state.dcl, 0),
            didx=jnp.where(dvalid[..., None], didx, -1),
            dvalid=dvalid,
        )

    shards = [restrict(s) for s in range(n_shards)]
    return jax.tree.map(lambda *xs: jnp.stack(xs, axis=1), *shards)


def split_nested(
    state: SparseNestState, n_shards: int, dot_cap: Optional[int] = None
) -> SparseNestState:
    """Partition a (batched) nested sparse state: the leaf table splits
    by ``id % n_shards`` (segment ``eid`` for the orswot leaf, cell
    ``kid`` for the register-map leaf), parked KEY lists replicated to
    every shard (``[R, ...] -> [R, S, ...]`` on every leaf)."""
    if isinstance(state.core, SparseNestState):
        core = split_nested(state.core, n_shards, dot_cap)
    elif hasattr(state.core, "kid"):  # sparse register-map leaf
        core = split_cells(state.core, n_shards, dot_cap)
    else:
        core = split_segments(state.core, n_shards, dot_cap)
    rep = lambda x: jnp.repeat(x[:, None], n_shards, axis=1)
    return SparseNestState(
        core=core, kcl=rep(state.kcl), kidx=rep(state.kidx),
        kdvalid=rep(state.kdvalid),
    )


def mesh_fold_sparse_nested_sharded(states, mesh: Mesh, level):
    """Converge a leaf-SHARDED sparse NESTED replica batch ``[R, S, ...]``
    (from ``split_nested``; works for any SparseNestLevel composition —
    orswot or register-map leaf) over the mesh. Shard-local joins are
    exact except the scrub's key-liveness test, which psums across the
    element axis. Returns ``(state [S, ...], flags[L+1])``."""
    spans, core = [], level
    while hasattr(core, "core"):
        spans.append(str(core.span))
        core = core.core
    return _sharded_fold(
        f"sparse_nested_sharded_{'x'.join(spans)}"
        f"_s{getattr(core, 'sibling_cap', 0)}",
        states, mesh,
        partial(level.join, element_axis=ELEMENT_AXIS),
        partial(level.fold, element_axis=ELEMENT_AXIS),
        nest._sparse_identity_like,
    )


def _all_specs(state, lead=(REPLICA_AXIS, ELEMENT_AXIS)):
    return jax.tree.map(lambda _: P(*lead), state)


def _pad_replica_axis(state, rsize: int, make_identity):
    lead = jax.tree.leaves(state)[0].shape[0]
    pad = (-lead) % rsize
    if not pad:
        return state
    ident = make_identity(pad)
    return jax.tree.map(
        lambda x, p: jnp.concatenate([x, p.astype(x.dtype)], axis=0),
        state, ident,
    )


def _sharded_fold(
    kind: str,
    states,
    mesh: Mesh,
    join_fn,
    fold_fn,
    identity_fix,
    cache_extra: tuple = (),
):
    """Shared scaffold for every element-sharded mesh fold: replica-axis
    identity padding, shard-axis check, shard-local fold + replica-axis
    lattice all-reduce inside shard_map, overflow psum over BOTH axes,
    metrics. ``identity_fix(tree)`` repairs -1 id-lane conventions on a
    zeros-built padding batch; ``join_fn``/``fold_fn`` may close over an
    ``element_axis`` for cross-shard scrubs."""
    s_axis = jax.tree.leaves(states)[0].shape[1]
    if s_axis != mesh.shape[ELEMENT_AXIS]:
        raise ValueError(
            f"state has {s_axis} element shards, mesh axis is "
            f"{mesh.shape[ELEMENT_AXIS]}"
        )
    states = _pad_replica_axis(
        states, mesh.shape[REPLICA_AXIS],
        lambda pad: identity_fix(jax.tree.map(
            lambda x: jnp.zeros((pad, *x.shape[1:]), x.dtype), states
        )),
    )

    def build():
        @partial(
            jax.shard_map,
            mesh=mesh,
            in_specs=(_all_specs(states),),
            out_specs=(_all_specs(states, (ELEMENT_AXIS,)), P()),
            check_vma=False,
        )
        def fold_fn_mesh(local):
            local = jax.tree.map(lambda x: x[:, 0], local)  # drop shard axis
            folded, of_local = fold_fn(local)
            joined, of_cross = _lattice_allreduce(folded, join_fn, fold_fn)
            of = (
                lax.psum(of_local.astype(jnp.int32), REPLICA_AXIS) > 0
            ) | of_cross
            of = lax.psum(of.astype(jnp.int32), ELEMENT_AXIS) > 0
            return jax.tree.map(lambda x: x[None], joined), of

        return fold_fn_mesh

    metrics.count(f"anti_entropy.{kind}_rounds")
    metrics.observe("anti_entropy.state_bytes", state_nbytes(states))
    observe_depth(f"anti_entropy.{kind}", states)
    with metrics.time(f"anti_entropy.{kind}"):
        out = _cached(kind, states, mesh, build, *cache_extra)(states)
        jax.block_until_ready(out)
    return out


def mesh_fold_sparse_sharded(
    states: SparseOrswotState, mesh: Mesh
) -> Tuple[SparseOrswotState, jax.Array]:
    """Converge an element-SHARDED sparse replica batch ``[R, S, ...]``
    (from ``split_segments``; S must equal the mesh's element-axis size)
    over the mesh. Shard-local joins are exact (restriction commutes
    with join), so the only collective is the replica-axis lattice
    all-reduce — per-device state and join cost drop by S. Returns
    ``(state [S, ...], overflow[2])`` with the element axis preserved."""
    return _sharded_fold(
        "sparse_sharded_fold", states, mesh, sp.join, sp.fold,
        nest._sparse_identity_like,
    )


def split_cells(
    states, n_shards: int, cell_cap: Optional[int] = None
):
    """Partition a (batched) sparse ``Map<K, MVReg>`` cell table
    (ops/sparse_mvmap.SparseMVMapState) by ``kid % n_shards``:
    ``[R, ...] -> [R, S, ...]``. Keys are wholly within one shard, so
    restriction commutes with the cellwise join — per-cell matching,
    payload winner-select, per-key sibling ranks, and parked keyset
    replay are all key-local; the top clock replicates per shard (every
    shard computes the same max). Parked key LISTS partition with their
    keys (an entry k only ever kills cells with kid == k)."""
    from ..ops import sparse_mvmap as smv

    cap = cell_cap or states.kid.shape[-1]

    def restrict(shard: int):
        keep = states.valid & (states.kid % n_shards == shard)
        kid, act, ctr, val, clk, valid, overflow = smv._canon(
            jnp.where(keep, states.kid, -1),
            jnp.where(keep, states.act, 0),
            jnp.where(keep, states.ctr, 0),
            jnp.where(keep, states.val, 0),
            jnp.where(keep[..., None], states.clk, 0),
            keep,
            cap,
        )
        if bool(jnp.any(overflow)):
            raise ValueError(
                f"shard {shard}: live cells exceed the per-shard cap {cap}"
            )
        kidx = _canon_rmlist(
            jnp.where(
                (states.kidx >= 0) & (states.kidx % n_shards == shard),
                states.kidx,
                -1,
            )
        )
        dvalid = states.dvalid & jnp.any(kidx >= 0, axis=-1)
        return smv.SparseMVMapState(
            top=states.top,  # replicated per shard
            kid=kid, act=act, ctr=ctr, val=val, clk=clk, valid=valid,
            dcl=jnp.where(dvalid[..., None], states.dcl, 0),
            kidx=jnp.where(dvalid[..., None], kidx, -1),
            dvalid=dvalid,
        )

    shards = [restrict(s_) for s_ in range(n_shards)]
    return jax.tree.map(lambda *xs: jnp.stack(xs, axis=1), *shards)


def mesh_fold_sparse_mvmap_sharded(
    states, mesh: Mesh, sibling_cap: int = 4
):
    """Converge a key-SHARDED sparse ``Map<K, MVReg>`` replica batch
    ``[R, S, ...]`` (from ``split_cells``) over the mesh — the SP
    analog for the register family. Shard-local joins are exact, so the
    only collective is the replica-axis lattice all-reduce; per-device
    state and join cost drop by S. Returns ``(state [S, ...],
    overflow[3])``."""
    from ..ops import sparse_mvmap as smv

    return _sharded_fold(
        f"sparse_mvmap_sharded_fold_s{sibling_cap}", states, mesh,
        partial(smv.join, sibling_cap=sibling_cap),
        partial(smv.fold, sibling_cap=sibling_cap),
        nest._sparse_identity_like,
    )


def mesh_fold_sparse_map(
    states: SparseNestState, mesh: Mesh, span: int
) -> Tuple[SparseNestState, jax.Array]:
    """Converge an element-sharded SPARSE ``Map<K, Orswot>`` replica
    batch ``[R, S, ...]`` (from ``split_nested``) over the mesh. The
    nested join runs shard-local except the scrub's key-liveness psum
    across the element axis. ``span`` is the level's static leaf-ids-
    per-key constant (``BatchedSparseMapOrswot.span``). Returns
    ``(state [S, ...], overflow[3])``."""
    level = nest.level_map_orswot(span)
    return _sharded_fold(
        "sparse_map_fold", states, mesh,
        partial(level.join, element_axis=ELEMENT_AXIS),
        partial(level.fold, element_axis=ELEMENT_AXIS),
        nest._sparse_identity_like,
        cache_extra=(span,),
    )


def _lattice_allreduce(local, join_fn, fold_fn):
    """all_reduce_lattice with array-valued overflow flags."""
    from .collectives import all_reduce_lattice

    return all_reduce_lattice(local, REPLICA_AXIS, join_fn, fold_fn)


# ---- static-analysis registration (crdt_tpu.analysis) --------------------

def _register():
    from ..analysis import gate_states as gs
    from ..analysis.registry import register_entry_point

    def shards(mesh):
        return mesh.shape[ELEMENT_AXIS]

    def reg(name, kind, make_args, invoke):
        register_entry_point(
            name, kind=kind, make_args=make_args, invoke=invoke, n_donated=0
        )

    reg(
        "mesh_fold_sparse_sharded", "sparse_sharded_fold",
        lambda mesh: (split_segments(gs.mk_sparse(gs.replicas(mesh)), shards(mesh)),),
        lambda mesh, args: mesh_fold_sparse_sharded(args[0], mesh),
    )
    reg(
        "mesh_fold_sparse_mvmap_sharded", "sparse_mvmap_sharded_fold_s4",
        lambda mesh: (split_cells(gs.mk_sparse_mvmap(gs.replicas(mesh)), shards(mesh)),),
        lambda mesh, args: mesh_fold_sparse_mvmap_sharded(args[0], mesh),
    )
    reg(
        "mesh_fold_sparse_nested_sharded", f"sparse_nested_sharded_{gs.GM}_s0",
        lambda mesh: (split_nested(gs.mk_sparse_nested(gs.replicas(mesh)), shards(mesh)),),
        lambda mesh, args: mesh_fold_sparse_nested_sharded(
            args[0], mesh, nest.level_map_orswot(gs.GM)
        ),
    )
    reg(
        "mesh_fold_sparse_map", "sparse_map_fold",
        lambda mesh: (split_nested(gs.mk_sparse_nested(gs.replicas(mesh)), shards(mesh)),),
        lambda mesh, args: mesh_fold_sparse_map(args[0], mesh, span=gs.GM),
    )


_register()
