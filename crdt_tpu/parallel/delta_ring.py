"""The shared ring scaffold for δ-state anti-entropy.

Both delta flavors (orswot rows — delta.py; map keys — delta_map.py)
run the identical mesh program: pad and shard (state, dirty, fctx),
locally fold the replica block (OR-folding dirty, max-folding
contexts), then ``rounds`` ppermute ring rounds of extract → shift →
apply, and finally the top-closure collective (tops stay FROZEN at
their local-fold values through the ring — see delta.py for why
contexts must never fold into them — so they lag the full-join top and
diverge across element shards; the union of the LOCAL-FOLD tops over
the whole mesh IS the full-join top, and once content has converged,
adopting it and re-replaying parked removes reproduces the full fold
exactly).

Only the type-specific pieces come in as closures: the local fold, the
extract/apply pair, the state specs, the post-closure replay — and,
for the zero-copy pipelined mode, the per-flavor digest gate.

Three orthogonal performance modes (all default-on where safe):

- ``donate=True`` — the jit donates (state, dirty); when the padded
  replica axis equals the mesh's the outputs alias those buffers in
  place (``input_output_alias``, gated by tools/check_aliasing.py), so
  the ring holds ONE copy of the state in HBM instead of two. ``fctx``
  is never donated: it has no matching output (the per-device fctx is
  loop-internal), so donating it would only trip XLA's unusable-
  donation warning.
- ``pipeline=True`` — double-buffered schedule: round r+1's packet is
  extracted from the pre-apply state and its ``ppermute`` put in
  flight BEFORE round r's packet merges, so the in-flight DMA crosses
  the loop edge and XLA's latency-hiding scheduler overlaps it with
  the merge kernels. The price is sends one apply stale: knowledge
  advances one hop per TWO rounds, so the default budget and the
  residue-certificate window widen to ``2*(P-1)-1`` (a pair of
  consecutive starvation-free rounds advances every mark one hop, and
  P-1 hops complete the ring). Same packets-per-round as the
  sequential schedule — latency is hidden, not bandwidth spent.
- ``digest=True`` — one tiny inverse-ring exchange of the FROZEN
  receiver tops before the loop (tops never change mid-ring, so one
  [A]-clock ppermute serves every round), then the flavor's ``gate``
  masks out packet slots whose whole knowledge the receiver's top
  already covers. Converged states are bit-identical — a covered
  slot's apply is a content no-op, and the tracking contract
  guarantees the covering device minted its own marks for those dots,
  so transitive delivery survives the dropped re-mark (delta.py
  ``gate_delta``). ``bytes_useful`` telemetry drops to O(changed
  lanes) while the wire shape (``bytes_exchanged``) stays static.

- ``ack_window=True`` — **ack-window back-propagation**
  (crdt_tpu/delta_opt/ackwin.py, Enes et al. 1803.02750 §4.2): each
  receiver ships one bool per applied packet slot back up-ring on the
  same inverse-ring channel the digest exchange uses; the sender
  promotes the confirmed slots into a per-link acked-interval window
  and masks every later δ whose content the peer has POSITIVELY
  confirmed joining under an equal-or-stronger context — including
  removals, which the stateless top digest can never vouch for (acks
  are positive knowledge of delivered content, not top inference, so
  the PR 3 wider-gate unsoundness does not arise). Layering: the
  digest gate needs no round-trip state and fires from round 0; the
  ack window needs per-link memory and starts paying once re-
  circulated knowledge comes back around — together they generalize
  ``gate_delta`` from "add-only slots under the frozen top" to
  arbitrary covered intervals. Converged states stay bit-identical;
  ``bytes_useful`` drops further and ``bytes_acked_skipped`` /
  ``ack_window_depth`` report the window's win (telemetry.py).

- ``fused=True`` — **one fused wire pass + bit-packed format**
  (crdt_tpu/parallel/wire.py over the Pallas kernel in
  crdt_tpu/ops/wire_kernels.py): the whole send side of a round —
  digest gate ∧ ack mask ∧ watermark encode ∧ checksum ∧ byte counts —
  executes as a single read of the packet lanes, and the packet ships
  as the all-u32 packed wire tree (bool planes as bitmaps, ids as u16
  pairs, clock lanes as biased-u16 deltas against the link watermark)
  instead of its in-memory pytree. Converged states are bit-identical
  to the layered path; slots outside the encoding window defer into
  the residue certificate and unencodable parked removes count as
  wire loss (wire.py documents the narrow-window soundness contract).
  ``fused=False`` traces the byte-identical layered (PR 12-era)
  program.

A sixth, non-performance mode is ``faults=`` (a
``crdt_tpu.faults.FaultPlan``, default None): seeded in-kernel fault
injection on every inbound link — drop / corrupt / delay draws minted
from ``jax.random`` inside the loop, an integrity checksum lane riding
each packet (corrupted arrivals are REJECTED, never joined), dead-rank
outbound drops, and eviction (the ring permutation rebuilt over live
ranks — still a true bijection — with evicted tops excluded from the
final closure). Two semantic consequences, both deliberate:

- lost packets VOID the residue certificate — the ring forces
  ``residue >= 1`` whenever anything dropped or was rejected, so a
  degraded run can never read as certified-converged; heal by
  state-driven resync (full-state gossip/fold over the returned rows —
  Almeida et al. 1603.01529, Enes et al. 1803.02750) or a fault-free δ
  re-run, and
- the final top-closure ADOPTS the mesh top only when the run lost
  nothing (adoption after loss would make receivers claim
  observed-and-removed for dots they never received — the delta.py
  inflated-context failure); lossy runs keep each device's own frozen
  top, leaving every row a valid, joinable partial state.

With every flag at its off value the traced program is byte-identical
to the pre-flag sequential ring (pinned by HLO comparison in
tests/test_zero_copy_ring.py, the PR-2 telemetry pattern; the
``faults=None`` pin lives in tests/test_faults.py)."""

from __future__ import annotations

import time
from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .. import telemetry as tele
from ..delta_opt import ackwin as _ackwin
from ..obs import hist as _hist
from ..ops import wire_kernels as _wk
from ..utils.metrics import metrics, state_nbytes
from . import wire as _wire
from .mesh import ELEMENT_AXIS, REPLICA_AXIS


def run_delta_ring(
    kind: str,
    state,
    dirty: jax.Array,
    fctx: jax.Array,
    mesh: Mesh,
    rounds: Optional[int],
    cap: int,
    specs,                    # PartitionSpec pytree for the state
    local_fold: Callable,     # local -> (folded, overflow)
    extract: Callable,        # (state, dirty, fctx, cap, start) -> (pkt, dirty, fctx)
    apply_fn: Callable,       # (state, pkt, dirty, fctx) -> (state, dirty, fctx, of)
    close_top: Callable,      # (state, full_top) -> state  (re-replay parked)
    top_of: Callable = lambda s: s.top,  # composed states nest their top
    cache_extra: tuple = (),
    telemetry: bool = False,
    slots_fn: Optional[Callable] = None,
    pipeline: bool = True,
    digest: bool = True,
    gate: Optional[Callable] = None,  # (pkt, digest_clock) -> pkt
    donate: bool = False,
    faults=None,                      # crdt_tpu.faults.FaultPlan
    ack_window=False,                 # delta_opt/ackwin.py (False/None off)
    wal=None,                         # crdt_tpu.durability.Wal
    wal_kind: Optional[str] = None,   # registry merge kind for δ records
    fused: bool = True,               # parallel/wire.py fused wire path
):
    """Run the δ ring program; ``state``/``dirty``/``fctx`` must already
    be padded to the mesh. Returns ``(states [P, ...], dirty, overflow,
    residue)`` — the first three with the same conventions as
    mesh_gossip; ``residue`` is the RUNTIME convergence indicator the
    ROUNDS BUDGET docstrings promise (int32 scalar): the mesh-wide count
    of slot-starved row-rounds WITHIN THE FINAL CERTIFICATE WINDOW —
    rows that wanted a packet slot but lost it to ``cap``. Extract
    clears every row it ships, so rows still dirty right after an
    extract ARE the round's unshipped backlog — domain-forwarding
    re-marks (added back at apply time) never inflate the count.

    Soundness: every ever-changed row keeps at least one circulating
    mark (digest gating retires a mark only at a device whose frozen
    top covers it — a device the tracking contract guarantees minted
    its own equivalent mark), and a starvation-free round advances
    every mark one hop — one hop per TWO rounds under ``pipeline=True``
    (sends are one apply stale). The certificate window is therefore
    ``P-1`` sequential rounds, ``2*(P-1)-1`` pipelined; that many
    consecutive starvation-free FINAL rounds walk every mark through
    all P devices — ``residue == 0`` means the gossip provably equals
    the full join. The indicator is ONE-SIDED: ``residue > 0`` does not
    prove divergence, it means the run cannot be certified — either
    genuine residue, or a ``cap`` too small to clear the circulating
    forwarding marks (ungated marks never die, they only coalesce, so a
    tight cap can starve forever even after content converges). Re-run
    with more rounds (the budget formula in delta.py — doubled under
    ``pipeline=True``) and a cap comfortably above the steady-state
    per-device mark count. Starvation in EARLIER rounds of an extended
    budget is expected drain behavior and deliberately not counted. A
    budget below the window cannot complete the ring's propagation at
    all, so residue is forced >= 1 there regardless of starvation.

    ``telemetry=True`` appends an in-kernel Telemetry pytree as a fifth
    output (telemetry.py): per-round packet wire AND post-mask payload
    bytes (``bytes_exchanged`` / ``bytes_useful``) and ``slots_fn``
    changed-lane counts accumulate in the loop carry, the final-state
    gauges read the post-closure fold, and ``residue`` mirrors the
    fourth output. ``pipeline`` / ``digest`` / ``donate`` are the
    zero-copy modes the module docstring describes; with every flag off
    the trace is the flag-free program.

    ``faults=`` (a ``crdt_tpu.faults.FaultPlan``) turns on in-kernel
    fault injection (module docstring): the ring runs over the plan's
    LIVE ranks, every packet carries a checksum lane, and a
    ``faults.FaultCounters`` pytree is appended as the LAST output
    (after the Telemetry pytree when both flags are on). Lost packets
    force ``residue >= 1`` and suppress top adoption — the returned
    rows are then valid partial states awaiting state-driven resync.

    ``ack_window=True`` (module docstring; crdt_tpu/delta_opt/ackwin.py)
    adds the per-link acked-interval window: one bool-per-slot ack
    ppermute per round on the inverse channel, sender-side masking of
    positively confirmed δs. Output arity is unchanged — the window
    lives and dies in the loop carry; its win shows up in
    ``bytes_useful`` / ``bytes_acked_skipped`` / ``ack_window_depth``
    under ``telemetry=True`` and the ``delta_opt.acked_skipped[.kind]``
    registry twins. Off (the default) traces the byte-identical
    pre-flag program, like every other mode flag.

    ``fused=True`` (the default) routes every packet through the ONE
    fused wire pass (crdt_tpu/parallel/wire.py over the Pallas kernel
    in crdt_tpu/ops/wire_kernels.py): digest gate ∧ ack mask ∧
    watermark encode ∧ checksum ∧ byte counts in a single read of the
    packet lanes, shipped as the bit-packed all-u32 wire tree (bool
    planes as bitmaps, ids as u16 pairs, clock lanes as biased-u16
    deltas against the link watermark). Converged states are
    bit-identical to the layered path; slots outside the encoding
    window DEFER (re-marked dirty before the round's backlog count, so
    the residue certificate prices them) and an unencodable parked
    remove counts as wire loss (residue forced ≥ 1, top adoption
    suppressed — wire.py documents the soundness contract).
    ``fused=False`` traces the byte-identical PR 12-era layered
    program (HLO-pinned in tests/test_wire.py) and marks its jit-cache
    entry with ``wire.WireKey`` so the analysis gates keep reading the
    default program.

    ``wal=`` (a ``crdt_tpu.durability.Wal``) makes the run DURABLE,
    host-side: the pre-run state seeds the log's diff base (a device
    copy, so ``donate=True`` stays sound), and after the run the
    converged rows append as ONE irreducible δ record
    (``delta_opt.decompose`` over the previous logged state) followed
    by a round barrier (``Wal.mark_round`` — the ``on_round`` fsync
    policy's one-barrier-per-round point). ``wal_kind`` names the
    registered merge kind the record decomposes under (the δ flavors
    pass their own). A crash then recovers to the last durable round
    via ``durability.recover`` — the traced program is UNTOUCHED (the
    append reads the returned arrays; flag off = no trace change by
    construction)."""
    from .anti_entropy import _cached, _ring_donate_argnums, _tel_reduced

    if wal is not None and wal_kind is None:
        raise ValueError(
            "wal= needs wal_kind= (the registered merge kind δ records "
            "decompose under)"
        )
    p = mesh.shape[REPLICA_AXIS]
    gated = digest and gate is not None
    faulted = faults is not None
    acked = bool(ack_window)
    # The fused wire path needs the flavor's registered codec (its
    # know function — parallel/wire.py); kinds without one (a future
    # flavor mid-bringup) fall back to the layered wire.
    fused_on = bool(fused) and kind in _wire.WIRE_SURFACES
    delay_mode = faulted and faults.delay > 0
    # Certificate window / propagation diameter: one hop per round
    # sequentially, one hop per two rounds pipelined (module docstring).
    win = (p - 1) if not pipeline else max(2 * (p - 1) - 1, 0)
    if rounds is None:
        rounds = win
    if faulted:
        from .. import faults as flt

        # The ring over LIVE ranks (evicted self-loop — still a true
        # bijection of the axis, so the collective lint holds).
        perm = flt.ring_perm(p, faults.evicted)
        inv_perm = flt.inv_ring_perm(p, faults.evicted)
        snd_tbl = flt.sender_of(p, faults.evicted)
    else:
        perm = [(i, (i + 1) % p) for i in range(p)]
        # Digest exchange runs AGAINST the ring: device i's packets land
        # on i+1, so i needs i+1's frozen top — ship tops one hop
        # down-ring.
        inv_perm = [(i, (i - 1) % p) for i in range(p)]
    argnums = _ring_donate_argnums(state, mesh, donate, n=2)

    def build():
        out_specs = (specs, P(REPLICA_AXIS, ELEMENT_AXIS), P(), P())
        if telemetry:
            out_specs = out_specs + (tele.specs(),)
        if faulted:
            out_specs = out_specs + (flt.counters_specs(),)
        slots_of = slots_fn or tele.generic_slots_changed
        # Telemetry loop-carry width: slots, shipped, useful, plus the
        # two in-kernel histograms (per-round backlog and per-round
        # useful bytes — obs/hist.py Hist subtrees riding the carry);
        # the fused wire adds the packed-bytes scalar and its
        # histogram (wire_packed_bytes / hist_packed_bytes).
        n_tel = (7 if fused_on else 5) if telemetry else 0

        @partial(
            jax.shard_map,
            mesh=mesh,
            in_specs=(
                specs,
                P(REPLICA_AXIS, ELEMENT_AXIS),
                P(REPLICA_AXIS, ELEMENT_AXIS, None),
            ),
            out_specs=out_specs,
            check_vma=False,
        )
        def gossip_fn(local, local_dirty, local_fctx):
            folded, of = local_fold(local)
            d = jnp.any(local_dirty, axis=0)
            f = jnp.max(local_fctx, axis=0)
            if gated:
                rtop = lax.ppermute(top_of(folded), REPLICA_AXIS, inv_perm)

            # ---- fault helpers (traced ONLY when faults is not None;
            # the flag-off program below is byte-identical pre-flag) --

            def ship(pkt):
                """Put one packet on the wire — with the integrity
                checksum lane riding the same ppermute when faulted."""
                if not faulted:
                    return jax.tree.map(
                        lambda x: lax.ppermute(x, REPLICA_AXIS, perm), pkt
                    )
                return jax.tree.map(
                    lambda x: lax.ppermute(x, REPLICA_AXIS, perm),
                    (pkt, flt.checksum(pkt)),
                )

            def receive(wire, r, final=False):
                """Receiver side of the wire for the packet applied at
                round ``r`` (faults.receive_wire: draws, evicted
                self-loop masking, corruption, checksum verify).
                ``final=True`` (ring epilogue) delivers a would-be-
                delayed packet now — no later round to hold it for."""
                if not faulted:
                    return wire, None, None
                pkt, chk_in = wire
                return flt.receive_wire(
                    faults, r, REPLICA_AXIS, snd_tbl, pkt, chk_in,
                    delay_ok=delay_mode and not final,
                )

            def select_apply(applied, prior, keep):
                """Discard a dropped/rejected/held delivery: the apply
                ran, its outputs are deselected (no traced branch)."""
                st2, d2, f2, of_r = applied
                st0, d0, f0 = prior
                return (
                    flt.tree_select(keep, st2, st0),
                    jnp.where(keep, d2, d0),
                    jnp.where(keep, f2, f0),
                    of_r & keep,
                )

            def tick(fc, fates):
                # The shared 4-lane update plus the ring's `lost` lane
                # (the residue-voiding quantity).
                out = flt.tick_counters(fc, fates)
                lostq = fates[0] | fates[1]
                return out[:4] + (fc[4] + lostq.astype(jnp.int32),)

            if faulted:
                fc0 = (
                    jnp.zeros((), jnp.uint32), jnp.zeros((), jnp.uint32),
                    jnp.zeros((), jnp.uint32), jnp.zeros((), jnp.int32),
                    jnp.zeros((), jnp.int32),
                )
            if delay_mode or acked or fused_on:
                pkt_shape = jax.eval_shape(
                    lambda s, dd, ff: extract(s, dd, ff, cap, start=0)[0],
                    folded, d, f,
                )
            if delay_mode:
                held0 = jax.tree.map(
                    lambda a: jnp.zeros(a.shape, a.dtype), pkt_shape
                )
            if acked:
                awin0 = _ackwin.init_window(pkt_shape, d.shape[-1])
                slot_price = jnp.float32(_ackwin.slot_bytes(pkt_shape))

                def ack_exchange(awin, sent, rcvd, keep):
                    """Back-propagate one applied packet's per-slot
                    confirmation one inverse hop and promote the
                    sender's own shipped copy into its window (ackwin
                    module docstring: bits follow the DATA packet's
                    fate, the ack lane itself rides the un-faulted
                    inverse channel). Under the fused wire the bits
                    ship as a u32 bitmap (8× the bool lane density);
                    the receiver's bits also promote its watermark
                    MIRROR (wire.py) before they leave."""
                    bits = _ackwin.ack_bits(rcvd, keep)
                    if fused_on:
                        bw = _wk.pack_bits(bits)
                        bw = lax.ppermute(bw, REPLICA_AXIS, inv_perm)
                        return (
                            _ackwin.update_window(
                                awin, sent,
                                _wk.unpack_bits(bw, bits.shape[0]),
                            ),
                            bw,
                        )
                    bits = lax.ppermute(bits, REPLICA_AXIS, inv_perm)
                    return _ackwin.update_window(awin, sent, bits), bits
            # Ack carry width: window (+ sender's in-flight copy under
            # pipelining, + the skipped-bytes scalar and the per-round
            # window-depth histogram under telemetry).
            pipe_on = pipeline and rounds > 0
            n_ack = (
                ((2 if pipe_on else 1) + (2 if telemetry else 0))
                if acked else 0
            )
            # Fused-wire carry width: the parked narrow-loss counter,
            # plus the receiver's ack-watermark mirror (and its lagged
            # copy under pipelining — wire.py's lockstep discipline).
            if fused_on:
                wcodec = _wire.WireCodec(
                    pkt_shape, d.shape[-1], _wire.WIRE_SURFACES[kind],
                    gated=gated, acked=acked,
                )
                mctx0 = jnp.zeros(
                    (d.shape[-1], wcodec.a), wcodec.ct
                )
                n_wire = 1 + (
                    (2 if pipe_on else 1) if acked else 0
                )

                def pack_ship(pkt, awin):
                    """The fused send: ONE kernel pass (gate ∧ mask ∧
                    encode ∧ checksum ∧ count), then the ppermute of
                    the packed wire — with the kernel's checksum as
                    the integrity lane when faulted."""
                    w, aux = wcodec.pack(
                        pkt,
                        rtop=rtop if gated else None,
                        win=awin if acked else None,
                    )
                    if faulted:
                        wired = jax.tree.map(
                            lambda x: lax.ppermute(x, REPLICA_AXIS, perm),
                            (w, aux.checksum),
                        )
                    else:
                        wired = jax.tree.map(
                            lambda x: lax.ppermute(x, REPLICA_AXIS, perm),
                            w,
                        )
                    return wired, aux

                def unpack_in(w, st, mctx):
                    return wcodec.unpack(
                        w,
                        own_top=top_of(st) if gated else None,
                        mirror_ctx=mctx if acked else None,
                    )
            else:
                n_wire = 0

            def deliver_held(st, d, f, of, held, heldv):
                """The one-round-late link buffer lands (delay faults)."""
                applied = apply_fn(st, held, d, f)
                st, d, f, of_h = select_apply(applied, (st, d, f), heldv)
                return st, d, f, of | of_h

            def round_body(r, carry):
                if delay_mode:
                    fc, held, heldv = carry[5 + n_tel + n_ack + n_wire:]
                elif faulted:
                    (fc,) = carry[5 + n_tel + n_ack + n_wire:]
                if acked:
                    awin = carry[5 + n_tel]
                    if telemetry:
                        skip = carry[5 + n_tel + n_ack - 2]
                        hack = carry[5 + n_tel + n_ack - 1]
                if fused_on:
                    woff = 5 + n_tel + n_ack
                    if acked:
                        mctx = carry[woff]
                    nlost = carry[woff + n_wire - 1]
                if telemetry:
                    (st, d, f, of, starved, slots, shipped, useful,
                     hresid, huseful) = carry[:10]
                    if fused_on:
                        wpacked, hpacked = carry[10], carry[11]
                    u0 = useful
                else:
                    st, d, f, of, starved = carry[:5]
                pkt, d, f = extract(st, d, f, cap, start=r * cap)
                if fused_on:
                    # The fused send: one kernel pass replaces the
                    # gate/ack/checksum/count layers. Deferred slots
                    # re-mark dirty BEFORE the backlog count so the
                    # residue certificate prices them (wire.py).
                    wired, waux = pack_ship(
                        pkt, awin if acked else None
                    )
                    d = _wire.remark_deferred(
                        d, _wire.core_idx(pkt), waux.defer
                    )
                    nlost = nlost + waux.parked_lost
                in_window = r >= rounds - win
                # Explicit accumulator dtype: without it jnp.sum widens
                # int32 -> int64 under x64 mode (counter_dtype="uint64")
                # and the fori_loop carry type changes mid-loop.
                backlog = jnp.sum(d, dtype=jnp.int32)
                starved = starved + jnp.where(in_window, backlog, 0)
                if telemetry:
                    # Per-round residue-quantity distribution: the rows
                    # still dirty right after the extract ARE the
                    # round's unshipped backlog (observed EVERY round —
                    # the drain curve, not just the certificate window).
                    hresid = _hist.observe(hresid, backlog)
                if fused_on:
                    if acked:
                        sent = wcodec.mask(pkt, waux.keep)
                        if telemetry:
                            skip = skip + jnp.sum(
                                waux.covered, dtype=jnp.float32
                            ) * slot_price
                    if telemetry:
                        before = st
                        shipped = shipped + jnp.float32(
                            tele.shipped_bytes(wired)
                        )
                        useful = useful + wcodec.useful_bytes(
                            pkt, waux.keep
                        ) + jnp.float32(4.0 if faulted else 0.0)
                        wpacked = wpacked + 4.0 * (
                            waux.packed_words
                            + jnp.uint32(1 if faulted else 0)
                        ).astype(jnp.float32)
                    pkt = wired
                else:
                    if gated:
                        pkt = gate(pkt, rtop)
                    if acked:
                        # Layering: the digest gate fired first
                        # (stateless top inference); the window masks
                        # what the peer has POSITIVELY confirmed —
                        # including removals.
                        pkt, covered = _ackwin.gate_window(pkt, awin)
                        sent = pkt
                        if telemetry:
                            skip = skip + jnp.sum(
                                covered, dtype=jnp.float32
                            ) * slot_price
                    pkt = ship(pkt)
                    if telemetry:
                        before = st
                        shipped = shipped + jnp.float32(
                            tele.shipped_bytes(pkt)
                        )
                        if faulted:
                            useful = useful + tele.packet_useful_bytes(
                                pkt[0]
                            ) + jnp.float32(tele.shipped_bytes(pkt[1]))
                        else:
                            useful = useful + tele.packet_useful_bytes(pkt)
                pkt, keep, fates = receive(pkt, r)
                if fused_on:
                    # Decode with the receiver's copy of the watermark
                    # (own frozen top + the ack mirror — sequential
                    # schedule: the mirror BEFORE this round's
                    # promotion matches the sender's encode state).
                    pkt = unpack_in(pkt, st, mctx if acked else None)
                if delay_mode:
                    st, d, f, of = deliver_held(st, d, f, of, held, heldv)
                applied = apply_fn(st, pkt, d, f)
                if faulted:
                    st, d, f, of_r = select_apply(applied, (st, d, f), keep)
                    fc = tick(fc, fates)
                    if delay_mode:
                        held = flt.tree_select(fates[2], pkt, held0)
                        heldv = fates[2]
                        tail = (fc, held, heldv)
                    else:
                        tail = (fc,)
                else:
                    st, d, f, of_r = applied
                    tail = ()
                if acked:
                    if fused_on:
                        mctx = _wire.mirror_promote(
                            mctx, pkt, _ackwin.ack_bits(pkt, keep),
                            jnp.ones((), bool),
                        )
                    awin, bits = ack_exchange(awin, sent, pkt, keep)
                    if telemetry:
                        ab = jnp.float32(tele.shipped_bytes(bits))
                        shipped, useful = shipped + ab, useful + ab
                        if fused_on:
                            wpacked = wpacked + 4.0 * jnp.sum(
                                (bits != 0).astype(jnp.uint32),
                                dtype=jnp.uint32,
                            ).astype(jnp.float32)
                        hack = _hist.observe(
                            hack, _ackwin.window_depth(awin)
                        )
                    ack_tail = (awin, skip, hack) if telemetry else (awin,)
                else:
                    ack_tail = ()
                if fused_on:
                    wire_tail = ((mctx,) if acked else ()) + (nlost,)
                else:
                    wire_tail = ()
                if telemetry:
                    slots = slots + slots_of(before, st)
                    huseful = _hist.observe(huseful, useful - u0)
                    tel_mid = (slots, shipped, useful, hresid, huseful)
                    if fused_on:
                        hpacked = _hist.observe(
                            hpacked, wpacked - carry[10]
                        )
                        tel_mid = tel_mid + (wpacked, hpacked)
                    return ((st, d, f, of | of_r, starved) + tel_mid
                            + ack_tail + wire_tail + tail)
                return ((st, d, f, of | of_r, starved) + ack_tail
                        + wire_tail + tail)

            def pipe_body(r, carry):
                # Double-buffered round: extract round r+1's packet
                # from the PRE-apply state and put its ppermute in
                # flight, THEN merge round r's in-flight packet — the
                # send crosses the loop edge, so its DMA overlaps the
                # merge kernels (module docstring; stale by one apply).
                if delay_mode:
                    fc, held, heldv = carry[6 + n_tel + n_ack + n_wire:]
                elif faulted:
                    (fc,) = carry[6 + n_tel + n_ack + n_wire:]
                if acked:
                    awin, sent = carry[6 + n_tel], carry[6 + n_tel + 1]
                    if telemetry:
                        skip = carry[6 + n_tel + n_ack - 2]
                        hack = carry[6 + n_tel + n_ack - 1]
                if fused_on:
                    woff = 6 + n_tel + n_ack
                    if acked:
                        mctx, mctx_prev = carry[woff], carry[woff + 1]
                    nlost = carry[woff + n_wire - 1]
                if telemetry:
                    (st, d, f, of, starved, flight, slots, shipped,
                     useful, hresid, huseful) = carry[:11]
                    if fused_on:
                        wpacked, hpacked = carry[11], carry[12]
                    u0 = useful
                else:
                    st, d, f, of, starved, flight = carry[:6]
                pkt, d, f = extract(st, d, f, cap, start=(r + 1) * cap)
                if fused_on:
                    # Encode against the CURRENT window state — the
                    # receiver decodes with its one-promotion-lagged
                    # mirror, matching this exact state (wire.py's
                    # pipelined lockstep discipline).
                    wired, waux = pack_ship(
                        pkt, awin if acked else None
                    )
                    d = _wire.remark_deferred(
                        d, _wire.core_idx(pkt), waux.defer
                    )
                    nlost = nlost + waux.parked_lost
                backlog = jnp.sum(d, dtype=jnp.int32)
                starved = starved + jnp.where(
                    (r + 1) >= rounds - win, backlog, 0
                )
                if telemetry:
                    hresid = _hist.observe(hresid, backlog)
                if fused_on:
                    if acked:
                        if telemetry:
                            skip = skip + jnp.sum(
                                waux.covered, dtype=jnp.float32
                            ) * slot_price
                    if telemetry:
                        before = st
                        shipped = shipped + jnp.float32(
                            tele.shipped_bytes(wired)
                        )
                        useful = useful + wcodec.useful_bytes(
                            pkt, waux.keep
                        ) + jnp.float32(4.0 if faulted else 0.0)
                        wpacked = wpacked + 4.0 * (
                            waux.packed_words
                            + jnp.uint32(1 if faulted else 0)
                        ).astype(jnp.float32)
                    nxt = wired
                else:
                    if gated:
                        pkt = gate(pkt, rtop)
                    if acked:
                        pkt, covered = _ackwin.gate_window(pkt, awin)
                        if telemetry:
                            skip = skip + jnp.sum(
                                covered, dtype=jnp.float32
                            ) * slot_price
                    nxt = ship(pkt)
                    if telemetry:
                        before = st
                        shipped = shipped + jnp.float32(
                            tele.shipped_bytes(nxt)
                        )
                        if faulted:
                            useful = useful + tele.packet_useful_bytes(
                                nxt[0]
                            ) + jnp.float32(tele.shipped_bytes(nxt[1]))
                        else:
                            useful = useful + tele.packet_useful_bytes(nxt)
                flight, keep, fates = receive(flight, r)
                if fused_on:
                    flight = unpack_in(
                        flight, st, mctx_prev if acked else None
                    )
                if delay_mode:
                    st, d, f, of = deliver_held(st, d, f, of, held, heldv)
                applied = apply_fn(st, flight, d, f)
                if faulted:
                    st, d, f, of_r = select_apply(applied, (st, d, f), keep)
                    fc = tick(fc, fates)
                    if delay_mode:
                        held = flt.tree_select(fates[2], flight, held0)
                        heldv = fates[2]
                        tail = (fc, held, heldv)
                    else:
                        tail = (fc,)
                else:
                    st, d, f, of_r = applied
                    tail = ()
                if acked:
                    # The ack is for the packet applied THIS round —
                    # shipped LAST round, whose pre-ship copy rides the
                    # carry (the window lags one extra round under
                    # pipelining, like knowledge itself).
                    if fused_on:
                        mctx_prev, mctx = mctx, _wire.mirror_promote(
                            mctx, flight,
                            _ackwin.ack_bits(flight, keep),
                            jnp.ones((), bool),
                        )
                    awin, bits = ack_exchange(awin, sent, flight, keep)
                    sent = (
                        wcodec.mask(pkt, waux.keep) if fused_on else pkt
                    )
                    if telemetry:
                        ab = jnp.float32(tele.shipped_bytes(bits))
                        shipped, useful = shipped + ab, useful + ab
                        if fused_on:
                            wpacked = wpacked + 4.0 * jnp.sum(
                                (bits != 0).astype(jnp.uint32),
                                dtype=jnp.uint32,
                            ).astype(jnp.float32)
                        hack = _hist.observe(
                            hack, _ackwin.window_depth(awin)
                        )
                    ack_tail = (
                        (awin, sent, skip, hack) if telemetry
                        else (awin, sent)
                    )
                else:
                    ack_tail = ()
                if fused_on:
                    wire_tail = (
                        ((mctx, mctx_prev) if acked else ()) + (nlost,)
                    )
                else:
                    wire_tail = ()
                if telemetry:
                    slots = slots + slots_of(before, st)
                    huseful = _hist.observe(huseful, useful - u0)
                    tel_mid = (slots, shipped, useful, hresid, huseful)
                    if fused_on:
                        hpacked = _hist.observe(
                            hpacked, wpacked - carry[11]
                        )
                        tel_mid = tel_mid + (wpacked, hpacked)
                    return ((st, d, f, of | of_r, starved, nxt) + tel_mid
                            + ack_tail + wire_tail + tail)
                return ((st, d, f, of | of_r, starved, nxt) + ack_tail
                        + wire_tail + tail)

            zeros_tel = (
                jnp.zeros((), jnp.uint32),   # slots
                jnp.zeros((), jnp.float32),  # shipped (wire)
                jnp.zeros((), jnp.float32),  # useful (post-mask)
            )
            fault_tail = ()
            if delay_mode:
                fault_tail = (fc0, held0, jnp.zeros((), bool))
            elif faulted:
                fault_tail = (fc0,)
            if pipeline and rounds > 0:
                # Prologue: round 0's packet goes in flight pre-loop.
                pkt, d, f = extract(folded, d, f, cap, start=0)
                if fused_on:
                    # The round-0 window is empty, so the watermark is
                    # the digest alone — the receiver's round-0 mirror
                    # matches by construction.
                    wired0, waux0 = pack_ship(
                        pkt, awin0 if acked else None
                    )
                    d = _wire.remark_deferred(
                        d, _wire.core_idx(pkt), waux0.defer
                    )
                backlog0 = jnp.sum(d, dtype=jnp.int32)
                starved = jnp.where(
                    jnp.asarray(0 >= rounds - win), backlog0, 0,
                )
                if fused_on:
                    flight = wired0
                else:
                    if gated:
                        pkt = gate(pkt, rtop)
                    # The round-0 window is empty — nothing to mask; the
                    # pre-ship copy seeds the carry as the first ackable
                    # send.
                    flight = ship(pkt)
                init = (folded, d, f, of, starved, flight)
                if telemetry:
                    if fused_on:
                        useful0 = wcodec.useful_bytes(
                            pkt, waux0.keep
                        ) + jnp.float32(4.0 if faulted else 0.0)
                        wpacked0 = 4.0 * (
                            waux0.packed_words
                            + jnp.uint32(1 if faulted else 0)
                        ).astype(jnp.float32)
                    elif faulted:
                        useful0 = (
                            tele.packet_useful_bytes(flight[0])
                            + jnp.float32(tele.shipped_bytes(flight[1]))
                        )
                    else:
                        useful0 = tele.packet_useful_bytes(flight)
                    init = init + (
                        zeros_tel[0],
                        zeros_tel[1]
                        + jnp.float32(tele.shipped_bytes(flight)),
                        zeros_tel[2] + useful0,
                        _hist.observe(_hist.zeros(), backlog0),
                        _hist.observe(_hist.zeros(), useful0),
                    )
                    if fused_on:
                        init = init + (
                            wpacked0,
                            _hist.observe(_hist.zeros(), wpacked0),
                        )
                if acked:
                    sent0 = (
                        wcodec.mask(pkt, waux0.keep) if fused_on else pkt
                    )
                    init = init + (
                        (awin0, sent0, jnp.zeros((), jnp.float32),
                         _hist.zeros())
                        if telemetry else (awin0, sent0)
                    )
                if fused_on:
                    init = init + (
                        ((mctx0, mctx0) if acked else ())
                        + (waux0.parked_lost,)
                    )
                init = init + fault_tail
                carry = lax.fori_loop(0, rounds - 1, pipe_body, init)
                folded, d, f, of, starved, flight = carry[:6]
                if acked:
                    awin = carry[6 + n_tel]
                if fused_on:
                    woff = 6 + n_tel + n_ack
                    if acked:
                        mctx_prev = carry[woff + 1]
                    nlost = carry[woff + n_wire - 1]
                if delay_mode:
                    fc, held, heldv = carry[6 + n_tel + n_ack + n_wire:]
                elif faulted:
                    (fc,) = carry[6 + n_tel + n_ack + n_wire:]
                # Epilogue: merge the final in-flight packet.
                if telemetry:
                    before = folded
                flight, keep, fates = receive(flight, rounds - 1, final=True)
                if fused_on:
                    flight = unpack_in(
                        flight, folded, mctx_prev if acked else None
                    )
                if delay_mode:
                    folded, d, f, of = deliver_held(
                        folded, d, f, of, held, heldv
                    )
                applied = apply_fn(folded, flight, d, f)
                if faulted:
                    folded, d, f, of_r = select_apply(
                        applied, (folded, d, f), keep
                    )
                    fc = tick(fc, fates)
                else:
                    folded, d, f, of_r = applied
                of = of | of_r
                if telemetry:
                    slots, shipped, useful, hresid, huseful = carry[6:11]
                    if fused_on:
                        wpacked, hpacked = carry[11], carry[12]
                    slots = slots + slots_of(before, folded)
                    if acked:
                        skip = carry[6 + n_tel + n_ack - 2]
                        hack = carry[6 + n_tel + n_ack - 1]
            else:
                init = (folded, d, f, of, jnp.zeros((), jnp.int32))
                if telemetry:
                    init = init + zeros_tel + (_hist.zeros(), _hist.zeros())
                    if fused_on:
                        init = init + (
                            jnp.zeros((), jnp.float32), _hist.zeros()
                        )
                if acked:
                    init = init + (
                        (awin0, jnp.zeros((), jnp.float32), _hist.zeros())
                        if telemetry else (awin0,)
                    )
                if fused_on:
                    init = init + (
                        ((mctx0,) if acked else ())
                        + (jnp.zeros((), jnp.int32),)
                    )
                init = init + fault_tail
                carry = lax.fori_loop(0, rounds, round_body, init)
                folded, d, f, of, starved = carry[:5]
                if telemetry:
                    slots, shipped, useful, hresid, huseful = carry[5:10]
                    if fused_on:
                        wpacked, hpacked = carry[10], carry[11]
                if acked:
                    awin = carry[5 + n_tel]
                    if telemetry:
                        skip = carry[5 + n_tel + n_ack - 2]
                        hack = carry[5 + n_tel + n_ack - 1]
                if fused_on:
                    nlost = carry[5 + n_tel + n_ack + n_wire - 1]
                if delay_mode:
                    fc, held, heldv = carry[5 + n_tel + n_ack + n_wire:]
                    # A packet still held when the loop ends arrives now
                    # (one round late past the ring edge, not lost).
                    folded, d, f, of = deliver_held(
                        folded, d, f, of, held, heldv
                    )
                elif faulted:
                    (fc,) = carry[5 + n_tel + n_ack + n_wire:]
            if telemetry and gated:
                # The digest exchange itself rides the wire once.
                dig = jnp.float32(tele.shipped_bytes(rtop))
                shipped, useful = shipped + dig, useful + dig
            if fused_on:
                # Unencodable parked removes never reached the wire:
                # count them as loss mesh-wide (wire.py's narrow-window
                # contract — residue forced below, adoption gated
                # here).
                nlost_tot = lax.psum(
                    nlost, (REPLICA_AXIS, ELEMENT_AXIS)
                )
            if faulted:
                # Adopt the mesh top ONLY when the run lost nothing:
                # adoption after loss makes receivers claim
                # observed-and-removed for dots they never received (the
                # delta.py inflated-context failure). Evicted ranks are
                # excluded from the live pmax and never adopt.
                own_top = top_of(folded)
                ev = flt.evicted_mask(faults, REPLICA_AXIS)
                top_live = lax.pmax(
                    lax.pmax(jnp.where(ev, 0, own_top), REPLICA_AXIS),
                    ELEMENT_AXIS,
                )
                lost_tot = lax.psum(fc[4], REPLICA_AXIS)
                adopt = (lost_tot == 0) & ~ev
                if fused_on:
                    adopt = adopt & (nlost_tot == 0)
                top = jnp.where(adopt, top_live, own_top)
            elif fused_on:
                # Same adoption guard for narrow-lost parked removes on
                # a fault-free ring; with nothing lost this selects the
                # mesh top bit-identically to the unconditional path.
                own_top = top_of(folded)
                top_live = lax.pmax(
                    lax.pmax(own_top, REPLICA_AXIS), ELEMENT_AXIS
                )
                top = jnp.where(nlost_tot == 0, top_live, own_top)
            else:
                top = lax.pmax(
                    lax.pmax(top_of(folded), REPLICA_AXIS), ELEMENT_AXIS
                )
            folded = close_top(folded, top)
            of = (
                lax.psum(of.astype(jnp.int32), (REPLICA_AXIS, ELEMENT_AXIS))
                > 0
            )
            residue = lax.psum(starved, (REPLICA_AXIS, ELEMENT_AXIS))
            if faulted:
                # Lost packets void the certificate: a degraded run must
                # never read as certified-converged (module docstring).
                residue = jnp.maximum(
                    residue, (lost_tot > 0).astype(jnp.int32)
                )
            if fused_on:
                # Narrow-lost parked removes are wire loss too
                # (wire.py): the certificate must not be issuable when
                # removal knowledge never shipped.
                residue = jnp.maximum(
                    residue, (nlost_tot > 0).astype(jnp.int32)
                )
            if rounds < win:
                # A budget below the certificate window can never
                # complete the ring's propagation; the certificate must
                # not be issuable no matter the cap.
                residue = jnp.maximum(residue, 1)
            outs = (
                jax.tree.map(lambda x: x[None], folded), d[None], of, residue
            )
            if telemetry:
                local_rows = jax.tree.leaves(local)[0].shape[0]
                tel = _tel_reduced(
                    folded, slots,
                    max(local_rows - 1, 0) + rounds, shipped,
                    (REPLICA_AXIS, ELEMENT_AXIS), residue=residue,
                    useful_per_dev=useful,
                )
                # The in-kernel distributions: per-(round, device)
                # samples psum into one mesh-wide histogram, like the
                # scalar throughput counters (obs/hist.py).
                tel = tel._replace(
                    hist_residue=_hist.psum(
                        hresid, (REPLICA_AXIS, ELEMENT_AXIS)
                    ),
                    hist_useful_bytes=_hist.psum(
                        huseful, (REPLICA_AXIS, ELEMENT_AXIS)
                    ),
                )
                if fused_on:
                    tel = tel._replace(
                        wire_packed_bytes=lax.psum(
                            wpacked, (REPLICA_AXIS, ELEMENT_AXIS)
                        ),
                        hist_packed_bytes=_hist.psum(
                            hpacked, (REPLICA_AXIS, ELEMENT_AXIS)
                        ),
                    )
                if acked:
                    tel = tel._replace(
                        bytes_acked_skipped=lax.psum(
                            skip, (REPLICA_AXIS, ELEMENT_AXIS)
                        ),
                        ack_window_depth=lax.pmax(
                            _ackwin.window_depth(awin),
                            (REPLICA_AXIS, ELEMENT_AXIS),
                        ),
                        hist_ack_depth=_hist.psum(
                            hack, (REPLICA_AXIS, ELEMENT_AXIS)
                        ),
                    )
                if faulted:
                    tel = tel._replace(
                        faults_dropped=lax.psum(fc[0], REPLICA_AXIS),
                        faults_rejected=lax.psum(fc[1], REPLICA_AXIS),
                        faults_delayed=lax.psum(fc[2], REPLICA_AXIS),
                    )
                outs = outs + (tel,)
            if faulted:
                # Packet counters psum over the REPLICA axis only: the
                # fault draw is per logical link (element shards share
                # the fate), so a replica-axis sum counts packets, not
                # device shards.
                outs = outs + (flt.FaultCounters(
                    packets_dropped=lax.psum(fc[0], REPLICA_AXIS),
                    packets_rejected=lax.psum(fc[1], REPLICA_AXIS),
                    packets_delayed=lax.psum(fc[2], REPLICA_AXIS),
                    miss_streak=fc[3].reshape(1),
                ),)
            return outs

        return gossip_fn

    metrics.count(f"anti_entropy.{kind}_rounds", rounds)
    metrics.observe("anti_entropy.state_bytes", state_nbytes(state))
    if (wal is not None and wal.tail is None
            and not isinstance(
                jax.tree.leaves(state)[0], jax.core.Tracer
            )):
        # Seed the diff base BEFORE the jitted call — donation consumes
        # the input buffers; attach takes a device copy. Skipped under
        # an outer jit (tracers must never leak into the log's diff
        # base) — the append below is skipped symmetrically.
        wal.attach(state)
    t0 = time.perf_counter()
    with metrics.time(f"anti_entropy.{kind}"):
        out = _cached(
            kind, state, mesh, build, rounds, cap, telemetry, pipeline,
            gated, faults, _ackwin.AckWindowKey() if acked else None,
            # A fused=False run is the LEGACY program: mark its cache
            # entry so the analysis gates keep reading the default
            # (fused) trace — the FaultPlan/AckWindowKey discipline.
            None if fused_on else _wire.WireKey(),
            *cache_extra, donate_argnums=argnums,
        )(state, dirty, fctx)
        jax.block_until_ready(out)
    if telemetry and tele.is_concrete(out[4]):
        out = out[:4] + (tele.time_dispatch(
            out[4], time.perf_counter() - t0
        ),) + out[5:]
    if donate:
        # Free whatever the donation did not consume in place: the
        # unaliasable fallback, and originals implicitly resharded onto
        # the mesh (the executable then donated the committed copies).
        from .anti_entropy import _consume

        _consume(True, state, dirty)
    # A faulted run's residue is forced >= 1 BY DESIGN (lost packets
    # void the certificate) — the budget warning would misdiagnose it
    # and burn the once-per-kind dedupe a genuine under-budget run
    # needs; the gauge still records, the fault counters are the signal.
    _warn_residue(kind, out, warn=not faulted)
    if wal is not None and not isinstance(
        jax.tree.leaves(out[0])[0], jax.core.Tracer
    ):
        # Host-side durability append (skipped under an outer jit —
        # like tele.record, the caller then owns persistence).
        b0, f0 = wal.bytes_appended, wal.fsyncs
        with metrics.time("durability.wal_append"):
            wal.append_state(wal_kind, out[0])
            wal.mark_round()
        if telemetry and tele.is_concrete(out[4]):
            out = out[:4] + (out[4]._replace(
                wal_bytes=jnp.float32(wal.bytes_appended - b0),
                wal_fsyncs=jnp.uint32(wal.fsyncs - f0),
            ),) + out[5:]
    if acked:
        metrics.count("delta_opt.ack_window_runs")
        if telemetry and tele.is_concrete(out[4]):
            skipped = int(out[4].bytes_acked_skipped)
            metrics.count("delta_opt.acked_skipped", skipped)
            metrics.count(f"delta_opt.acked_skipped.{kind}", skipped)
    if fused_on:
        metrics.count("wire.fused_runs")
        if telemetry and tele.is_concrete(out[4]):
            # The registry twins of the in-kernel packed-bytes counter
            # (tools/telemetry_schema.json `wire_packed_bytes`).
            pb = int(out[4].wire_packed_bytes)
            metrics.count("wire.packed_bytes", pb)
            metrics.count(f"wire.packed_bytes.{kind}", pb)
    if telemetry and tele.is_concrete(out[4]):
        tele.record(kind, out[4])
    if faulted:
        from .. import faults as flt

        flt.record(out[-1])  # no-op under tracing, like tele.record
    return out


# Kinds whose residue warning already fired this process — repeats only
# count in the registry (see _warn_residue).
_RESIDUE_WARNED: set = set()


def reset_residue_warnings() -> None:
    """Re-arm the once-per-kind residue warning (tests; or after an
    operator fixed the budget and wants fresh signal)."""
    _RESIDUE_WARNED.clear()


def _warn_residue(kind: str, out, warn: bool = True) -> None:
    if not isinstance(out[3], jax.core.Tracer):
        # Host-side residue accounting — skipped when the ring runs
        # under an outer jit (callers then read the returned residue).
        # ``warn=False`` (faulted runs) records the gauge only: their
        # residue is injected loss, not an under-budgeted ring.
        residue = int(out[3])
        metrics.observe(f"anti_entropy.{kind}.residue", float(residue))
        if residue and warn:
            # Every occurrence counts in the registry; the warning
            # itself fires once per kind per process — an under-budgeted
            # ring in a loop would otherwise emit one warning per round
            # (the repeat count lives in the counter, where operators
            # can actually read a rate).
            metrics.count(f"anti_entropy.{kind}.residue_runs")
            if kind in _RESIDUE_WARNED:
                return
            _RESIDUE_WARNED.add(kind)
            import warnings

            warnings.warn(
                f"{kind}: round budget left residue ({residue} slot-starved "
                f"row-rounds) — the ring is NOT guaranteed converged; raise "
                f"`rounds` (see the ROUNDS BUDGET note in parallel/delta.py; "
                f"pipeline=True budgets are ~2x) or `cap`. Warned once per "
                f"kind; repeats count in anti_entropy.{kind}.residue_runs",
                # _warn_residue -> run_delta_ring -> mesh entry -> user.
                stacklevel=4,
            )


def delta_gossip_elastic(
    model,
    dirty: jax.Array,
    fctx: jax.Array,
    mesh: Mesh,
    rounds: Optional[int] = None,
    cap: int = 64,
    local_fold: str = "auto",
    policy=None,
    telemetry: bool = False,
    pipeline: bool = True,
    digest: bool = True,
    donate: bool = False,
    reclaim=None,
    faults=None,
    ack_window=False,
    wal=None,
    fused: bool = True,
):
    """δ-ring anti-entropy with elastic capacity recovery for dense
    ORSWOT replica batches (``BatchedOrswot``): the mid-round
    overflow→widen→resume loop of ``anti_entropy.gossip_elastic``, δ
    flavored.

    When a ring run flags parked-buffer overflow, the run's result is
    discarded (the δ entry never commits to the model), the replica
    pauses while ``deferred_cap`` widens 2× (policy-configurable) with
    the live state re-encoded on device, and the ring re-enters with the
    SAME (dirty, fctx) tracking — sound because the rejected run
    mutated nothing, the widened state is bit-identical to a
    wider-born one, and the tracking contract (delta.py) binds dirty
    marks to dots, not to layout. Element/actor-axis growth composes
    the same way: ``mesh_delta_gossip`` re-pads dirty/fctx to the
    state's (post-migration) shape. The residue certificate is
    unchanged — the re-entered ring's ``residue == 0`` still proves the
    gossip equals the full join of the widened family.

    ``pipeline`` / ``digest`` thread through to every attempt
    (run_delta_ring). ``donate=True`` donates each attempt's
    (state, dirty) into the ring and restores ``model.state`` and the
    tracking pair from a pre-round device copy afterwards — the widen
    fallback needs the pre-round state alive across a failed attempt,
    so the wrapper trades the ring-internal second state copy for one
    explicit snapshot (net HBM even; the in-ring temporaries still
    shrink) while keeping the model coherent either way.

    Returns ``(states, dirty, overflow, residue, widened)`` — the
    ``mesh_delta_gossip`` tuple plus the dict of axes grown (empty when
    capacity sufficed). ``telemetry=True`` appends a Telemetry pytree
    folded across every attempt (``telemetry.combine``) as the last
    element.

    ``reclaim=`` takes an ``elastic.Hysteresis`` tracker — the shrink
    half of the elastic loop, composing here exactly as in
    ``anti_entropy.gossip_elastic``: after the successful attempt the
    tracker observes occupancy and narrows cleared axes in place (the
    δ path computes its frontier host-side —
    ``reclaim.host_frontier`` / ``reclaim.compact_model`` — since the
    residue-certificated ring has no spare output lane for it).

    ``faults=`` threads a ``crdt_tpu.faults.FaultPlan`` into every
    attempt (run_delta_ring); the LAST tuple element is then the
    ``FaultCounters`` pytree with packet counters summed across
    attempts (``faults.combine_counters``). ``ack_window=True`` threads
    the acked-interval masking into every attempt too — each attempt
    starts a fresh window (sound: the window is per-run positive
    knowledge, and a rejected overflowing attempt confirmed nothing it
    could carry over).

    ``wal=`` logs ONLY the committed attempt (a rejected overflowing
    run mutated nothing, so it must not reach the log either); a
    mid-loop widen changes the shapes, which the log absorbs as a
    full-``state`` record (``Wal.append_state``'s fallback) — replay
    re-anchors there, so recovery stays bit-identical across
    migrations."""
    from .. import elastic
    from .delta import mesh_delta_gossip

    if wal is not None and wal.tail is None:
        wal.attach(model.state)
    policy = policy or elastic.DEFAULT_POLICY
    widened: dict = {}
    migrations = 0
    tel = None
    fcs = None
    while True:
        if donate:
            snap = jax.tree.map(jnp.copy, model.state)
            snap_dirty = jnp.copy(dirty)
        out = mesh_delta_gossip(
            model.state, dirty, fctx, mesh, rounds, cap, local_fold,
            telemetry=telemetry, pipeline=pipeline, digest=digest,
            donate=donate, faults=faults, ack_window=ack_window,
            fused=fused,
        )
        if donate:
            model.state, dirty = snap, snap_dirty
        if faults is not None:
            from .. import faults as flt

            fcs = flt.accumulate_counters(fcs, out[-1])
            out = out[:-1]
        if telemetry:
            tel = out[4] if tel is None else tele.combine(tel, out[4])
        if not bool(jnp.any(out[2])):
            if reclaim is not None:
                from ..reclaim import compact_model
                from .anti_entropy import _commit_rows

                _commit_rows(model, out[0])
                # The δ ring has no spare output lane for an in-kernel
                # frontier; compact host-side against the committed
                # rows' own frontier (the batch IS the replica set)
                # so retired slots do not pin lanes the shrink needs.
                compact_model(model)
                reclaim.observe(model)
            if wal is not None:
                # The committed attempt is the durable transition.
                wal.append_state("orswot", out[0])
                wal.mark_round()
            ret = (*out[:4], widened)
            if telemetry:
                ret = ret + (tel,)
            if fcs is not None:
                ret = ret + (fcs,)
            return ret
        if migrations >= policy.max_migrations:
            raise RuntimeError(
                f"δ ring still overflowing after {migrations} migrations "
                f"(axes grown: {widened}) — raise policy.factor or "
                f"max_migrations"
            )
        metrics.count("elastic.delta_migrations")
        widened.update(elastic.widen(model, ("deferred_cap",), policy))
        migrations += 1


# ---- static-analysis registration (crdt_tpu.analysis) --------------------
# The generic ring engine and the elastic wrapper both expose faults=
# directly (the registered δ flavors thread through them); fault-surface
# registration is the coverage contract crdt_tpu.faults.static_checks
# enforces.

from ..analysis.registry import register_fault_surface as _reg_fs  # noqa: E402

_reg_fs("run_delta_ring", module=__name__)
_reg_fs("delta_gossip_elastic", module=__name__)
