"""The shared ring scaffold for δ-state anti-entropy.

Both delta flavors (orswot rows — delta.py; map keys — delta_map.py)
run the identical mesh program: pad and shard (state, dirty, fctx),
locally fold the replica block (OR-folding dirty, max-folding
contexts), then ``rounds`` ppermute ring rounds of extract → shift →
apply, and finally the top-closure collective (tops stay FROZEN at
their local-fold values through the ring — see delta.py for why
contexts must never fold into them — so they lag the full-join top and
diverge across element shards; the union of the LOCAL-FOLD tops over
the whole mesh IS the full-join top, and once content has converged,
adopting it and re-replaying parked removes reproduces the full fold
exactly).

Only the type-specific pieces come in as closures: the local fold, the
extract/apply pair, the state specs, the post-closure replay — and,
for the zero-copy pipelined mode, the per-flavor digest gate.

Three orthogonal performance modes (all default-on where safe):

- ``donate=True`` — the jit donates (state, dirty); when the padded
  replica axis equals the mesh's the outputs alias those buffers in
  place (``input_output_alias``, gated by tools/check_aliasing.py), so
  the ring holds ONE copy of the state in HBM instead of two. ``fctx``
  is never donated: it has no matching output (the per-device fctx is
  loop-internal), so donating it would only trip XLA's unusable-
  donation warning.
- ``pipeline=True`` — double-buffered schedule: round r+1's packet is
  extracted from the pre-apply state and its ``ppermute`` put in
  flight BEFORE round r's packet merges, so the in-flight DMA crosses
  the loop edge and XLA's latency-hiding scheduler overlaps it with
  the merge kernels. The price is sends one apply stale: knowledge
  advances one hop per TWO rounds, so the default budget and the
  residue-certificate window widen to ``2*(P-1)-1`` (a pair of
  consecutive starvation-free rounds advances every mark one hop, and
  P-1 hops complete the ring). Same packets-per-round as the
  sequential schedule — latency is hidden, not bandwidth spent.
- ``digest=True`` — one tiny inverse-ring exchange of the FROZEN
  receiver tops before the loop (tops never change mid-ring, so one
  [A]-clock ppermute serves every round), then the flavor's ``gate``
  masks out packet slots whose whole knowledge the receiver's top
  already covers. Converged states are bit-identical — a covered
  slot's apply is a content no-op, and the tracking contract
  guarantees the covering device minted its own marks for those dots,
  so transitive delivery survives the dropped re-mark (delta.py
  ``gate_delta``). ``bytes_useful`` telemetry drops to O(changed
  lanes) while the wire shape (``bytes_exchanged``) stays static.

With every flag at its off value the traced program is byte-identical
to the pre-flag sequential ring (pinned by HLO comparison in
tests/test_zero_copy_ring.py, the PR-2 telemetry pattern)."""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .. import telemetry as tele
from ..utils.metrics import metrics, state_nbytes
from .mesh import ELEMENT_AXIS, REPLICA_AXIS


def run_delta_ring(
    kind: str,
    state,
    dirty: jax.Array,
    fctx: jax.Array,
    mesh: Mesh,
    rounds: Optional[int],
    cap: int,
    specs,                    # PartitionSpec pytree for the state
    local_fold: Callable,     # local -> (folded, overflow)
    extract: Callable,        # (state, dirty, fctx, cap, start) -> (pkt, dirty, fctx)
    apply_fn: Callable,       # (state, pkt, dirty, fctx) -> (state, dirty, fctx, of)
    close_top: Callable,      # (state, full_top) -> state  (re-replay parked)
    top_of: Callable = lambda s: s.top,  # composed states nest their top
    cache_extra: tuple = (),
    telemetry: bool = False,
    slots_fn: Optional[Callable] = None,
    pipeline: bool = True,
    digest: bool = True,
    gate: Optional[Callable] = None,  # (pkt, digest_clock) -> pkt
    donate: bool = False,
):
    """Run the δ ring program; ``state``/``dirty``/``fctx`` must already
    be padded to the mesh. Returns ``(states [P, ...], dirty, overflow,
    residue)`` — the first three with the same conventions as
    mesh_gossip; ``residue`` is the RUNTIME convergence indicator the
    ROUNDS BUDGET docstrings promise (int32 scalar): the mesh-wide count
    of slot-starved row-rounds WITHIN THE FINAL CERTIFICATE WINDOW —
    rows that wanted a packet slot but lost it to ``cap``. Extract
    clears every row it ships, so rows still dirty right after an
    extract ARE the round's unshipped backlog — domain-forwarding
    re-marks (added back at apply time) never inflate the count.

    Soundness: every ever-changed row keeps at least one circulating
    mark (digest gating retires a mark only at a device whose frozen
    top covers it — a device the tracking contract guarantees minted
    its own equivalent mark), and a starvation-free round advances
    every mark one hop — one hop per TWO rounds under ``pipeline=True``
    (sends are one apply stale). The certificate window is therefore
    ``P-1`` sequential rounds, ``2*(P-1)-1`` pipelined; that many
    consecutive starvation-free FINAL rounds walk every mark through
    all P devices — ``residue == 0`` means the gossip provably equals
    the full join. The indicator is ONE-SIDED: ``residue > 0`` does not
    prove divergence, it means the run cannot be certified — either
    genuine residue, or a ``cap`` too small to clear the circulating
    forwarding marks (ungated marks never die, they only coalesce, so a
    tight cap can starve forever even after content converges). Re-run
    with more rounds (the budget formula in delta.py — doubled under
    ``pipeline=True``) and a cap comfortably above the steady-state
    per-device mark count. Starvation in EARLIER rounds of an extended
    budget is expected drain behavior and deliberately not counted. A
    budget below the window cannot complete the ring's propagation at
    all, so residue is forced >= 1 there regardless of starvation.

    ``telemetry=True`` appends an in-kernel Telemetry pytree as a fifth
    output (telemetry.py): per-round packet wire AND post-mask payload
    bytes (``bytes_exchanged`` / ``bytes_useful``) and ``slots_fn``
    changed-lane counts accumulate in the loop carry, the final-state
    gauges read the post-closure fold, and ``residue`` mirrors the
    fourth output. ``pipeline`` / ``digest`` / ``donate`` are the
    zero-copy modes the module docstring describes; with every flag off
    the trace is the flag-free program."""
    from .anti_entropy import _cached, _ring_donate_argnums, _tel_reduced

    p = mesh.shape[REPLICA_AXIS]
    gated = digest and gate is not None
    # Certificate window / propagation diameter: one hop per round
    # sequentially, one hop per two rounds pipelined (module docstring).
    win = (p - 1) if not pipeline else max(2 * (p - 1) - 1, 0)
    if rounds is None:
        rounds = win
    perm = [(i, (i + 1) % p) for i in range(p)]
    # Digest exchange runs AGAINST the ring: device i's packets land on
    # i+1, so i needs i+1's frozen top — ship tops one hop down-ring.
    inv_perm = [(i, (i - 1) % p) for i in range(p)]
    argnums = _ring_donate_argnums(state, mesh, donate, n=2)

    def build():
        out_specs = (specs, P(REPLICA_AXIS, ELEMENT_AXIS), P(), P())
        if telemetry:
            out_specs = out_specs + (tele.specs(),)
        slots_of = slots_fn or tele.generic_slots_changed

        @partial(
            jax.shard_map,
            mesh=mesh,
            in_specs=(
                specs,
                P(REPLICA_AXIS, ELEMENT_AXIS),
                P(REPLICA_AXIS, ELEMENT_AXIS, None),
            ),
            out_specs=out_specs,
            check_vma=False,
        )
        def gossip_fn(local, local_dirty, local_fctx):
            folded, of = local_fold(local)
            d = jnp.any(local_dirty, axis=0)
            f = jnp.max(local_fctx, axis=0)
            if gated:
                rtop = lax.ppermute(top_of(folded), REPLICA_AXIS, inv_perm)

            def round_body(r, carry):
                if telemetry:
                    st, d, f, of, starved, slots, shipped, useful = carry
                else:
                    st, d, f, of, starved = carry
                pkt, d, f = extract(st, d, f, cap, start=r * cap)
                in_window = r >= rounds - win
                # Explicit accumulator dtype: without it jnp.sum widens
                # int32 -> int64 under x64 mode (counter_dtype="uint64")
                # and the fori_loop carry type changes mid-loop.
                starved = starved + jnp.where(
                    in_window, jnp.sum(d, dtype=jnp.int32), 0
                )
                if gated:
                    pkt = gate(pkt, rtop)
                pkt = jax.tree.map(
                    lambda x: lax.ppermute(x, REPLICA_AXIS, perm), pkt
                )
                if telemetry:
                    before = st
                    shipped = shipped + jnp.float32(tele.shipped_bytes(pkt))
                    useful = useful + tele.packet_useful_bytes(pkt)
                st, d, f, of_r = apply_fn(st, pkt, d, f)
                if telemetry:
                    slots = slots + slots_of(before, st)
                    return st, d, f, of | of_r, starved, slots, shipped, useful
                return st, d, f, of | of_r, starved

            def pipe_body(r, carry):
                # Double-buffered round: extract round r+1's packet
                # from the PRE-apply state and put its ppermute in
                # flight, THEN merge round r's in-flight packet — the
                # send crosses the loop edge, so its DMA overlaps the
                # merge kernels (module docstring; stale by one apply).
                if telemetry:
                    st, d, f, of, starved, flight, slots, shipped, useful = (
                        carry
                    )
                else:
                    st, d, f, of, starved, flight = carry
                pkt, d, f = extract(st, d, f, cap, start=(r + 1) * cap)
                starved = starved + jnp.where(
                    (r + 1) >= rounds - win, jnp.sum(d, dtype=jnp.int32), 0
                )
                if gated:
                    pkt = gate(pkt, rtop)
                nxt = jax.tree.map(
                    lambda x: lax.ppermute(x, REPLICA_AXIS, perm), pkt
                )
                if telemetry:
                    before = st
                    shipped = shipped + jnp.float32(tele.shipped_bytes(nxt))
                    useful = useful + tele.packet_useful_bytes(nxt)
                st, d, f, of_r = apply_fn(st, flight, d, f)
                if telemetry:
                    slots = slots + slots_of(before, st)
                    return (st, d, f, of | of_r, starved, nxt, slots,
                            shipped, useful)
                return st, d, f, of | of_r, starved, nxt

            zeros_tel = (
                jnp.zeros((), jnp.uint32),   # slots
                jnp.zeros((), jnp.float32),  # shipped (wire)
                jnp.zeros((), jnp.float32),  # useful (post-mask)
            )
            if pipeline and rounds > 0:
                # Prologue: round 0's packet goes in flight pre-loop.
                pkt, d, f = extract(folded, d, f, cap, start=0)
                starved = jnp.where(
                    jnp.asarray(0 >= rounds - win),
                    jnp.sum(d, dtype=jnp.int32), 0,
                )
                if gated:
                    pkt = gate(pkt, rtop)
                flight = jax.tree.map(
                    lambda x: lax.ppermute(x, REPLICA_AXIS, perm), pkt
                )
                init = (folded, d, f, of, starved, flight)
                if telemetry:
                    init = init + (
                        zeros_tel[0],
                        zeros_tel[1] + jnp.float32(tele.shipped_bytes(flight)),
                        zeros_tel[2] + tele.packet_useful_bytes(flight),
                    )
                carry = lax.fori_loop(0, rounds - 1, pipe_body, init)
                folded, d, f, of, starved, flight = carry[:6]
                # Epilogue: merge the final in-flight packet.
                if telemetry:
                    before = folded
                folded, d, f, of_r = apply_fn(folded, flight, d, f)
                of = of | of_r
                if telemetry:
                    slots, shipped, useful = carry[6:]
                    slots = slots + slots_of(before, folded)
            else:
                init = (folded, d, f, of, jnp.zeros((), jnp.int32))
                if telemetry:
                    init = init + zeros_tel
                carry = lax.fori_loop(0, rounds, round_body, init)
                folded, d, f, of, starved = carry[:5]
                if telemetry:
                    slots, shipped, useful = carry[5:]
            if telemetry and gated:
                # The digest exchange itself rides the wire once.
                dig = jnp.float32(tele.shipped_bytes(rtop))
                shipped, useful = shipped + dig, useful + dig
            top = lax.pmax(
                lax.pmax(top_of(folded), REPLICA_AXIS), ELEMENT_AXIS
            )
            folded = close_top(folded, top)
            of = (
                lax.psum(of.astype(jnp.int32), (REPLICA_AXIS, ELEMENT_AXIS))
                > 0
            )
            residue = lax.psum(starved, (REPLICA_AXIS, ELEMENT_AXIS))
            if rounds < win:
                # A budget below the certificate window can never
                # complete the ring's propagation; the certificate must
                # not be issuable no matter the cap.
                residue = jnp.maximum(residue, 1)
            outs = (
                jax.tree.map(lambda x: x[None], folded), d[None], of, residue
            )
            if telemetry:
                local_rows = jax.tree.leaves(local)[0].shape[0]
                outs = outs + (_tel_reduced(
                    folded, slots,
                    max(local_rows - 1, 0) + rounds, shipped,
                    (REPLICA_AXIS, ELEMENT_AXIS), residue=residue,
                    useful_per_dev=useful,
                ),)
            return outs

        return gossip_fn

    metrics.count(f"anti_entropy.{kind}_rounds", rounds)
    metrics.observe("anti_entropy.state_bytes", state_nbytes(state))
    with metrics.time(f"anti_entropy.{kind}"):
        out = _cached(
            kind, state, mesh, build, rounds, cap, telemetry, pipeline,
            gated, *cache_extra, donate_argnums=argnums,
        )(state, dirty, fctx)
        jax.block_until_ready(out)
    if donate:
        # Free whatever the donation did not consume in place: the
        # unaliasable fallback, and originals implicitly resharded onto
        # the mesh (the executable then donated the committed copies).
        from .anti_entropy import _consume

        _consume(True, state, dirty)
    _warn_residue(kind, out)
    if telemetry and tele.is_concrete(out[4]):
        tele.record(kind, out[4])
    return out


# Kinds whose residue warning already fired this process — repeats only
# count in the registry (see _warn_residue).
_RESIDUE_WARNED: set = set()


def reset_residue_warnings() -> None:
    """Re-arm the once-per-kind residue warning (tests; or after an
    operator fixed the budget and wants fresh signal)."""
    _RESIDUE_WARNED.clear()


def _warn_residue(kind: str, out) -> None:
    if not isinstance(out[3], jax.core.Tracer):
        # Host-side residue accounting — skipped when the ring runs
        # under an outer jit (callers then read the returned residue).
        residue = int(out[3])
        metrics.observe(f"anti_entropy.{kind}.residue", float(residue))
        if residue:
            # Every occurrence counts in the registry; the warning
            # itself fires once per kind per process — an under-budgeted
            # ring in a loop would otherwise emit one warning per round
            # (the repeat count lives in the counter, where operators
            # can actually read a rate).
            metrics.count(f"anti_entropy.{kind}.residue_runs")
            if kind in _RESIDUE_WARNED:
                return
            _RESIDUE_WARNED.add(kind)
            import warnings

            warnings.warn(
                f"{kind}: round budget left residue ({residue} slot-starved "
                f"row-rounds) — the ring is NOT guaranteed converged; raise "
                f"`rounds` (see the ROUNDS BUDGET note in parallel/delta.py; "
                f"pipeline=True budgets are ~2x) or `cap`. Warned once per "
                f"kind; repeats count in anti_entropy.{kind}.residue_runs",
                # _warn_residue -> run_delta_ring -> mesh entry -> user.
                stacklevel=4,
            )


def delta_gossip_elastic(
    model,
    dirty: jax.Array,
    fctx: jax.Array,
    mesh: Mesh,
    rounds: Optional[int] = None,
    cap: int = 64,
    local_fold: str = "auto",
    policy=None,
    telemetry: bool = False,
    pipeline: bool = True,
    digest: bool = True,
    donate: bool = False,
    reclaim=None,
):
    """δ-ring anti-entropy with elastic capacity recovery for dense
    ORSWOT replica batches (``BatchedOrswot``): the mid-round
    overflow→widen→resume loop of ``anti_entropy.gossip_elastic``, δ
    flavored.

    When a ring run flags parked-buffer overflow, the run's result is
    discarded (the δ entry never commits to the model), the replica
    pauses while ``deferred_cap`` widens 2× (policy-configurable) with
    the live state re-encoded on device, and the ring re-enters with the
    SAME (dirty, fctx) tracking — sound because the rejected run
    mutated nothing, the widened state is bit-identical to a
    wider-born one, and the tracking contract (delta.py) binds dirty
    marks to dots, not to layout. Element/actor-axis growth composes
    the same way: ``mesh_delta_gossip`` re-pads dirty/fctx to the
    state's (post-migration) shape. The residue certificate is
    unchanged — the re-entered ring's ``residue == 0`` still proves the
    gossip equals the full join of the widened family.

    ``pipeline`` / ``digest`` thread through to every attempt
    (run_delta_ring). ``donate=True`` donates each attempt's
    (state, dirty) into the ring and restores ``model.state`` and the
    tracking pair from a pre-round device copy afterwards — the widen
    fallback needs the pre-round state alive across a failed attempt,
    so the wrapper trades the ring-internal second state copy for one
    explicit snapshot (net HBM even; the in-ring temporaries still
    shrink) while keeping the model coherent either way.

    Returns ``(states, dirty, overflow, residue, widened)`` — the
    ``mesh_delta_gossip`` tuple plus the dict of axes grown (empty when
    capacity sufficed). ``telemetry=True`` appends a Telemetry pytree
    folded across every attempt (``telemetry.combine``) as the last
    element.

    ``reclaim=`` takes an ``elastic.Hysteresis`` tracker — the shrink
    half of the elastic loop, composing here exactly as in
    ``anti_entropy.gossip_elastic``: after the successful attempt the
    tracker observes occupancy and narrows cleared axes in place (the
    δ path computes its frontier host-side —
    ``reclaim.host_frontier`` / ``reclaim.compact_model`` — since the
    residue-certificated ring has no spare output lane for it)."""
    from .. import elastic
    from .delta import mesh_delta_gossip

    policy = policy or elastic.DEFAULT_POLICY
    widened: dict = {}
    migrations = 0
    tel = None
    while True:
        if donate:
            snap = jax.tree.map(jnp.copy, model.state)
            snap_dirty = jnp.copy(dirty)
        out = mesh_delta_gossip(
            model.state, dirty, fctx, mesh, rounds, cap, local_fold,
            telemetry=telemetry, pipeline=pipeline, digest=digest,
            donate=donate,
        )
        if donate:
            model.state, dirty = snap, snap_dirty
        if telemetry:
            tel = out[4] if tel is None else tele.combine(tel, out[4])
        if not bool(jnp.any(out[2])):
            if reclaim is not None:
                from ..reclaim import compact_model
                from .anti_entropy import _commit_rows

                _commit_rows(model, out[0])
                # The δ ring has no spare output lane for an in-kernel
                # frontier; compact host-side against the committed
                # rows' own frontier (the batch IS the replica set)
                # so retired slots do not pin lanes the shrink needs.
                compact_model(model)
                reclaim.observe(model)
            if telemetry:
                return (*out[:4], widened, tel)
            return (*out, widened)
        if migrations >= policy.max_migrations:
            raise RuntimeError(
                f"δ ring still overflowing after {migrations} migrations "
                f"(axes grown: {widened}) — raise policy.factor or "
                f"max_migrations"
            )
        metrics.count("elastic.delta_migrations")
        widened.update(elastic.widen(model, ("deferred_cap",), policy))
        migrations += 1
