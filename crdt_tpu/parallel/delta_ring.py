"""The shared ring scaffold for δ-state anti-entropy.

Both delta flavors (orswot rows — delta.py; map keys — delta_map.py)
run the identical mesh program: pad and shard (state, dirty, fctx),
locally fold the replica block (OR-folding dirty, max-folding
contexts), then ``rounds`` ppermute ring rounds of extract → shift →
apply, and finally the top-closure collective (tops stay FROZEN at
their local-fold values through the ring — see delta.py for why
contexts must never fold into them — so they lag the full-join top and
diverge across element shards; the union of the LOCAL-FOLD tops over
the whole mesh IS the full-join top, and once content has converged,
adopting it and re-replaying parked removes reproduces the full fold
exactly).

Only the type-specific pieces come in as closures: the local fold, the
extract/apply pair, the state specs, and the post-closure replay."""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .. import telemetry as tele
from ..utils.metrics import metrics, state_nbytes
from .mesh import ELEMENT_AXIS, REPLICA_AXIS


def run_delta_ring(
    kind: str,
    state,
    dirty: jax.Array,
    fctx: jax.Array,
    mesh: Mesh,
    rounds: Optional[int],
    cap: int,
    specs,                    # PartitionSpec pytree for the state
    local_fold: Callable,     # local -> (folded, overflow)
    extract: Callable,        # (state, dirty, fctx, cap, start) -> (pkt, dirty, fctx)
    apply_fn: Callable,       # (state, pkt, dirty, fctx) -> (state, dirty, fctx, of)
    close_top: Callable,      # (state, full_top) -> state  (re-replay parked)
    top_of: Callable = lambda s: s.top,  # composed states nest their top
    cache_extra: tuple = (),
    telemetry: bool = False,
    slots_fn: Optional[Callable] = None,
):
    """Run the δ ring program; ``state``/``dirty``/``fctx`` must already
    be padded to the mesh. Returns ``(states [P, ...], dirty, overflow,
    residue)`` — the first three with the same conventions as
    mesh_gossip; ``residue`` is the RUNTIME convergence indicator the
    ROUNDS BUDGET docstrings promise (int32 scalar): the mesh-wide count
    of slot-starved row-rounds WITHIN THE FINAL P-1 ROUNDS — rows that
    wanted a packet slot but lost it to ``cap``. Extract clears every
    row it ships, so rows still dirty right after an extract ARE the
    round's unshipped backlog — domain-forwarding re-marks (added back
    at apply time) never inflate the count. Soundness: every
    ever-changed row keeps at least one circulating mark, and a
    starvation-free round advances every mark one hop, so P-1
    consecutive starvation-free FINAL rounds walk every mark through all
    P devices — ``residue == 0`` means the gossip provably equals the
    full join. The indicator is ONE-SIDED: ``residue > 0`` does not
    prove divergence, it means the run cannot be certified — either
    genuine residue, or a ``cap`` too small to clear the circulating
    forwarding marks (marks never die, they only coalesce, so a tight
    cap can starve forever even after content converges). Re-run with
    more rounds (the budget formula in delta.py) and a cap comfortably
    above the steady-state per-device mark count. Starvation in EARLIER
    rounds of an extended budget is expected drain behavior and
    deliberately not counted. A budget below P-1 rounds cannot complete
    a ring loop at all, so residue is forced >= 1 there regardless of
    starvation.

    ``telemetry=True`` appends an in-kernel Telemetry pytree as a fifth
    output (telemetry.py): per-round packet bytes and ``slots_fn``
    changed-lane counts accumulate in the fori_loop carry, the
    final-state gauges read the post-closure fold, and ``residue``
    mirrors the fourth output. The flag off traces exactly the
    flag-free program."""
    from .anti_entropy import _cached, _tel_reduced

    p = mesh.shape[REPLICA_AXIS]
    if rounds is None:
        rounds = p - 1
    perm = [(i, (i + 1) % p) for i in range(p)]

    def build():
        out_specs = (specs, P(REPLICA_AXIS, ELEMENT_AXIS), P(), P())
        if telemetry:
            out_specs = out_specs + (tele.specs(),)
        slots_of = slots_fn or tele.generic_slots_changed

        @partial(
            jax.shard_map,
            mesh=mesh,
            in_specs=(
                specs,
                P(REPLICA_AXIS, ELEMENT_AXIS),
                P(REPLICA_AXIS, ELEMENT_AXIS, None),
            ),
            out_specs=out_specs,
            check_vma=False,
        )
        def gossip_fn(local, local_dirty, local_fctx):
            folded, of = local_fold(local)
            d = jnp.any(local_dirty, axis=0)
            f = jnp.max(local_fctx, axis=0)

            def round_body(r, carry):
                if telemetry:
                    st, d, f, of, starved, slots, shipped = carry
                else:
                    st, d, f, of, starved = carry
                pkt, d, f = extract(st, d, f, cap, start=r * cap)
                in_window = r >= rounds - (p - 1)
                # Explicit accumulator dtype: without it jnp.sum widens
                # int32 -> int64 under x64 mode (counter_dtype="uint64")
                # and the fori_loop carry type changes mid-loop.
                starved = starved + jnp.where(
                    in_window, jnp.sum(d, dtype=jnp.int32), 0
                )
                pkt = jax.tree.map(
                    lambda x: lax.ppermute(x, REPLICA_AXIS, perm), pkt
                )
                if telemetry:
                    before = st
                    shipped = shipped + jnp.float32(tele.shipped_bytes(pkt))
                st, d, f, of_r = apply_fn(st, pkt, d, f)
                if telemetry:
                    slots = slots + slots_of(before, st)
                    return st, d, f, of | of_r, starved, slots, shipped
                return st, d, f, of | of_r, starved

            init = (folded, d, f, of, jnp.zeros((), jnp.int32))
            if telemetry:
                init = init + (
                    jnp.zeros((), jnp.uint32), jnp.zeros((), jnp.float32)
                )
            carry = lax.fori_loop(0, rounds, round_body, init)
            folded, d, f, of, starved = carry[:5]
            top = lax.pmax(
                lax.pmax(top_of(folded), REPLICA_AXIS), ELEMENT_AXIS
            )
            folded = close_top(folded, top)
            of = (
                lax.psum(of.astype(jnp.int32), (REPLICA_AXIS, ELEMENT_AXIS))
                > 0
            )
            residue = lax.psum(starved, (REPLICA_AXIS, ELEMENT_AXIS))
            if rounds < p - 1:
                # A budget below P-1 can never complete a ring loop; the
                # certificate must not be issuable no matter the cap.
                residue = jnp.maximum(residue, 1)
            outs = (
                jax.tree.map(lambda x: x[None], folded), d[None], of, residue
            )
            if telemetry:
                slots, shipped = carry[5], carry[6]
                local_rows = jax.tree.leaves(local)[0].shape[0]
                outs = outs + (_tel_reduced(
                    folded, slots,
                    max(local_rows - 1, 0) + rounds, shipped,
                    (REPLICA_AXIS, ELEMENT_AXIS), residue=residue,
                ),)
            return outs

        return gossip_fn

    metrics.count(f"anti_entropy.{kind}_rounds", rounds)
    metrics.observe("anti_entropy.state_bytes", state_nbytes(state))
    with metrics.time(f"anti_entropy.{kind}"):
        out = _cached(
            kind, state, mesh, build, rounds, cap, telemetry, *cache_extra
        )(state, dirty, fctx)
        jax.block_until_ready(out)
    _warn_residue(kind, out)
    if telemetry and tele.is_concrete(out[4]):
        tele.record(kind, out[4])
    return out


def _warn_residue(kind: str, out) -> None:
    if not isinstance(out[3], jax.core.Tracer):
        # Host-side residue accounting — skipped when the ring runs
        # under an outer jit (callers then read the returned residue).
        residue = int(out[3])
        metrics.observe(f"anti_entropy.{kind}.residue", float(residue))
        if residue:
            import warnings

            warnings.warn(
                f"{kind}: round budget left residue ({residue} slot-starved "
                f"row-rounds) — the ring is NOT guaranteed converged; raise "
                f"`rounds` (see the ROUNDS BUDGET note in parallel/delta.py) "
                f"or `cap`",
                # _warn_residue -> run_delta_ring -> mesh entry -> user.
                stacklevel=4,
            )


def delta_gossip_elastic(
    model,
    dirty: jax.Array,
    fctx: jax.Array,
    mesh: Mesh,
    rounds: Optional[int] = None,
    cap: int = 64,
    local_fold: str = "auto",
    policy=None,
    telemetry: bool = False,
):
    """δ-ring anti-entropy with elastic capacity recovery for dense
    ORSWOT replica batches (``BatchedOrswot``): the mid-round
    overflow→widen→resume loop of ``anti_entropy.gossip_elastic``, δ
    flavored.

    When a ring run flags parked-buffer overflow, the run's result is
    discarded (the δ entry never commits to the model), the replica
    pauses while ``deferred_cap`` widens 2× (policy-configurable) with
    the live state re-encoded on device, and the ring re-enters with the
    SAME (dirty, fctx) tracking — sound because the rejected run
    mutated nothing, the widened state is bit-identical to a
    wider-born one, and the tracking contract (delta.py) binds dirty
    marks to dots, not to layout. Element/actor-axis growth composes
    the same way: ``mesh_delta_gossip`` re-pads dirty/fctx to the
    state's (post-migration) shape. The residue certificate is
    unchanged — the re-entered ring's ``residue == 0`` still proves the
    gossip equals the full join of the widened family.

    Returns ``(states, dirty, overflow, residue, widened)`` — the
    ``mesh_delta_gossip`` tuple plus the dict of axes grown (empty when
    capacity sufficed). ``telemetry=True`` appends a Telemetry pytree
    folded across every attempt (``telemetry.combine``) as the last
    element."""
    from .. import elastic
    from .delta import mesh_delta_gossip

    policy = policy or elastic.DEFAULT_POLICY
    widened: dict = {}
    migrations = 0
    tel = None
    while True:
        out = mesh_delta_gossip(
            model.state, dirty, fctx, mesh, rounds, cap, local_fold,
            telemetry=telemetry,
        )
        if telemetry:
            tel = out[4] if tel is None else tele.combine(tel, out[4])
        if not bool(jnp.any(out[2])):
            if telemetry:
                return (*out[:4], widened, tel)
            return (*out, widened)
        if migrations >= policy.max_migrations:
            raise RuntimeError(
                f"δ ring still overflowing after {migrations} migrations "
                f"(axes grown: {widened}) — raise policy.factor or "
                f"max_migrations"
            )
        metrics.count("elastic.delta_migrations")
        widened.update(elastic.widen(model, ("deferred_cap",), policy))
        migrations += 1
