"""Mesh construction and sharding layouts for batched CRDT state.

The layouts put the *replica* axis and the *element* axis on the mesh and
keep the (small) actor axis and deferred-buffer axis replicated — exactly
the layout under which the ORSWOT join (ops/orswot.py) is element-wise
per shard: entry survival depends only on that entry's birth clock and
the two top clocks, so sharding E needs no communication at all, and the
only collective anti-entropy needs is over the replica axis
(SURVEY.md §6.7–6.8).
"""

from __future__ import annotations

from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.map import MapState
from ..ops.map_map import NestedMapState
from ..ops.map_orswot import MapOrswotState
from ..ops.mvreg import MVRegState
from ..ops.orswot import OrswotState

REPLICA_AXIS = "replica"
ELEMENT_AXIS = "element"


def make_mesh(n_replica_shards: int, n_element_shards: int = 1, devices: Sequence = None) -> Mesh:
    """A ``(replica, element)`` device mesh.

    Within one slice both axes ride ICI; multi-slice/multi-host
    deployments should put ``replica`` on the DCN-facing (outer) axis —
    replica-join traffic is one state per round, while element shards
    never communicate.
    """
    if devices is None:
        devices = jax.devices()
    n = n_replica_shards * n_element_shards
    if len(devices) < n:
        raise ValueError(f"need {n} devices, have {len(devices)}")
    grid = np.asarray(devices[:n]).reshape(n_replica_shards, n_element_shards)
    return Mesh(grid, (REPLICA_AXIS, ELEMENT_AXIS))


def orswot_specs() -> OrswotState:
    """PartitionSpecs for a batched ``OrswotState`` [R, ...]: replicas and
    elements on the mesh, actor lanes and deferred slots replicated."""
    return OrswotState(
        top=P(REPLICA_AXIS, None),
        ctr=P(REPLICA_AXIS, ELEMENT_AXIS, None),
        dcl=P(REPLICA_AXIS, None, None),
        dmask=P(REPLICA_AXIS, None, ELEMENT_AXIS),
        dvalid=P(REPLICA_AXIS, None),
    )


def orswot_out_specs() -> OrswotState:
    """Specs for the *converged* (replica-reduced) state: replicated over
    the replica axis, still element-sharded."""
    return OrswotState(
        top=P(None),
        ctr=P(ELEMENT_AXIS, None),
        dcl=P(None, None),
        dmask=P(None, ELEMENT_AXIS),
        dvalid=P(None),
    )


def pad_replicas(state: OrswotState, multiple: int) -> OrswotState:
    """Pad the replica axis up to a multiple with join identities (the
    empty state) so it divides the mesh's replica axis. Identity rows are
    absorbed by the join without affecting the result."""
    import jax.numpy as jnp

    from ..ops.orswot import empty

    pad = (-state.top.shape[0]) % multiple
    if pad == 0:
        return state
    ident = empty(
        state.ctr.shape[-2], state.ctr.shape[-1], state.dcl.shape[-2], batch=(pad,)
    )
    return jax.tree.map(
        lambda x, p: jnp.concatenate([x, p.astype(x.dtype)], axis=0), state, ident
    )


def pad_elements(state: OrswotState, multiple: int) -> OrswotState:
    """Pad the element axis with never-present slots so it divides the
    mesh's element axis. Padded slots hold no dots and are never read."""
    import jax.numpy as jnp

    pad = (-state.ctr.shape[-2]) % multiple
    if pad == 0:
        return state
    return state._replace(
        ctr=jnp.pad(state.ctr, ((0, 0), (0, pad), (0, 0))),
        dmask=jnp.pad(state.dmask, ((0, 0), (0, 0), (0, pad))),
    )


def map_specs() -> MapState:
    """PartitionSpecs for a batched ``MapState`` [R, ...]: replicas and
    *keys* on the mesh (keys are the Map's element axis — BASELINE
    config 4 at 1M keys), actor lanes / sibling / deferred slots
    replicated. The map join is key-wise independent (content survival
    reads only per-key slots plus the replicated top clocks), so key
    shards never communicate."""
    return MapState(
        top=P(REPLICA_AXIS, None),
        child=MVRegState(
            wact=P(REPLICA_AXIS, ELEMENT_AXIS, None),
            wctr=P(REPLICA_AXIS, ELEMENT_AXIS, None),
            clk=P(REPLICA_AXIS, ELEMENT_AXIS, None, None),
            val=P(REPLICA_AXIS, ELEMENT_AXIS, None),
            valid=P(REPLICA_AXIS, ELEMENT_AXIS, None),
        ),
        dcl=P(REPLICA_AXIS, None, None),
        dkeys=P(REPLICA_AXIS, None, ELEMENT_AXIS),
        dvalid=P(REPLICA_AXIS, None),
    )


def map_out_specs() -> MapState:
    """Specs for the converged (replica-reduced) map state."""
    return MapState(
        top=P(None),
        child=MVRegState(
            wact=P(ELEMENT_AXIS, None),
            wctr=P(ELEMENT_AXIS, None),
            clk=P(ELEMENT_AXIS, None, None),
            val=P(ELEMENT_AXIS, None),
            valid=P(ELEMENT_AXIS, None),
        ),
        dcl=P(None, None),
        dkeys=P(None, ELEMENT_AXIS),
        dvalid=P(None),
    )


def pad_replicas_map(state: MapState, multiple: int) -> MapState:
    """Pad the replica axis with join identities (see ``pad_replicas``)."""
    import jax.numpy as jnp

    from ..ops.map import empty

    pad = (-state.top.shape[0]) % multiple
    if pad == 0:
        return state
    ident = empty(
        state.dkeys.shape[-1],
        state.top.shape[-1],
        state.child.wact.shape[-1],
        state.dcl.shape[-2],
        batch=(pad,),
    )
    return jax.tree.map(
        lambda x, p: jnp.concatenate([x, p.astype(x.dtype)], axis=0), state, ident
    )


def pad_keys(state: MapState, multiple: int) -> MapState:
    """Pad the key axis with never-written slots so it divides the
    mesh's element axis (padded keys hold no dots, so the join never
    surfaces them)."""
    import jax.numpy as jnp

    pad = (-state.dkeys.shape[-1]) % multiple
    if pad == 0:
        return state
    kpad = lambda x: jnp.pad(x, ((0, 0), (0, pad)) + ((0, 0),) * (x.ndim - 2))
    return state._replace(
        child=jax.tree.map(kpad, state.child),
        dkeys=jnp.pad(state.dkeys, ((0, 0), (0, 0), (0, pad))),
    )


def shard_map_state(state: MapState, mesh: Mesh) -> MapState:
    """Place a batched map state onto the mesh with the canonical layout
    (replica × key), padding both axes to divisibility."""
    state = pad_replicas_map(state, mesh.shape[REPLICA_AXIS])
    state = pad_keys(state, mesh.shape[ELEMENT_AXIS])
    return jax.tree.map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)),
        state,
        map_specs(),
    )


def map_orswot_specs() -> MapOrswotState:
    """PartitionSpecs for a batched ``MapOrswotState`` [R, ...]: the
    K*M product element axis shards in whole-key blocks (pad_keys keeps
    K divisible by the element axis, so every shard's chunk is a
    multiple of M), the outer keyset buffer shards over K."""
    return MapOrswotState(
        core=orswot_specs(),
        kdcl=P(REPLICA_AXIS, None, None),
        kdkeys=P(REPLICA_AXIS, None, ELEMENT_AXIS),
        kdvalid=P(REPLICA_AXIS, None),
    )


def map_orswot_out_specs() -> MapOrswotState:
    return MapOrswotState(
        core=orswot_out_specs(),
        kdcl=P(None, None),
        kdkeys=P(None, ELEMENT_AXIS),
        kdvalid=P(None),
    )


def pad_map_orswot(state: MapOrswotState, rmult: int, kmult: int) -> MapOrswotState:
    """Pad replicas with join identities and keys (in whole K*M blocks)
    with never-present slots, to mesh-axis divisibility."""
    import jax.numpy as jnp

    nk = state.kdkeys.shape[-1]
    m = state.core.ctr.shape[-2] // nk

    pad_r = (-state.core.top.shape[0]) % rmult
    if pad_r:
        from ..ops.map_orswot import empty

        ident = empty(nk, m, state.core.top.shape[-1], state.kdcl.shape[-2], batch=(pad_r,))
        state = jax.tree.map(
            lambda x, p: jnp.concatenate([x, p.astype(x.dtype)], axis=0), state, ident
        )
    pad_k = (-nk) % kmult
    if pad_k:
        state = state._replace(
            core=state.core._replace(
                ctr=jnp.pad(state.core.ctr, ((0, 0), (0, pad_k * m), (0, 0))),
                dmask=jnp.pad(state.core.dmask, ((0, 0), (0, 0), (0, pad_k * m))),
            ),
            kdkeys=jnp.pad(state.kdkeys, ((0, 0), (0, 0), (0, pad_k))),
        )
    return state


def shard_map_orswot(state: MapOrswotState, mesh: Mesh) -> MapOrswotState:
    """Place a batched Map<K, Orswot> state onto the mesh (replica ×
    key) with the canonical layout."""
    state = pad_map_orswot(
        state, mesh.shape[REPLICA_AXIS], mesh.shape[ELEMENT_AXIS]
    )
    return jax.tree.map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)),
        state,
        map_orswot_specs(),
    )


def nested_map_specs() -> NestedMapState:
    """PartitionSpecs for a batched ``NestedMapState`` [R, ...]: the
    K1*K2 product key axis shards in whole-K1 blocks, the outer keyset
    buffer shards over K1."""
    return NestedMapState(
        m=map_specs(),
        odcl=P(REPLICA_AXIS, None, None),
        odkeys=P(REPLICA_AXIS, None, ELEMENT_AXIS),
        odvalid=P(REPLICA_AXIS, None),
    )


def nested_map_out_specs() -> NestedMapState:
    return NestedMapState(
        m=map_out_specs(),
        odcl=P(None, None),
        odkeys=P(None, ELEMENT_AXIS),
        odvalid=P(None),
    )


def pad_nested_map(state: NestedMapState, rmult: int, kmult: int) -> NestedMapState:
    """Pad replicas with join identities and K1 (in whole K1*K2 blocks)
    with never-written slots, to mesh-axis divisibility."""
    import jax.numpy as jnp

    nk1 = state.odkeys.shape[-1]
    k2 = state.m.dkeys.shape[-1] // nk1

    pad_r = (-state.m.top.shape[0]) % rmult
    if pad_r:
        from ..ops.map_map import empty

        ident = empty(
            nk1, k2,
            state.m.top.shape[-1],
            state.m.child.wact.shape[-1],
            state.odcl.shape[-2],
            batch=(pad_r,),
        )
        state = jax.tree.map(
            lambda x, p: jnp.concatenate([x, p.astype(x.dtype)], axis=0), state, ident
        )
    pad_k = (-nk1) % kmult
    if pad_k:
        kpad = lambda x: jnp.pad(
            x, ((0, 0), (0, pad_k * k2)) + ((0, 0),) * (x.ndim - 2)
        )
        state = state._replace(
            m=state.m._replace(
                child=jax.tree.map(kpad, state.m.child),
                dkeys=jnp.pad(state.m.dkeys, ((0, 0), (0, 0), (0, pad_k * k2))),
            ),
            odkeys=jnp.pad(state.odkeys, ((0, 0), (0, 0), (0, pad_k))),
        )
    return state


def shard_nested_map(state: NestedMapState, mesh: Mesh) -> NestedMapState:
    """Place a batched Map<K1, Map<K2, MVReg>> state onto the mesh
    (replica × outer key) with the canonical layout."""
    state = pad_nested_map(
        state, mesh.shape[REPLICA_AXIS], mesh.shape[ELEMENT_AXIS]
    )
    return jax.tree.map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)),
        state,
        nested_map_specs(),
    )


def shard_orswot(state: OrswotState, mesh: Mesh) -> OrswotState:
    """Place a batched state onto the mesh with the canonical layout,
    padding both batch axes to divisibility (see pad_replicas /
    pad_elements — padding is absorbed by the join)."""
    state = pad_replicas(state, mesh.shape[REPLICA_AXIS])
    state = pad_elements(state, mesh.shape[ELEMENT_AXIS])
    return jax.tree.map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)),
        state,
        orswot_specs(),
    )


def map3_specs():
    """PartitionSpecs for a batched ``Map3State`` [R, ...]
    (``Map<K1, Map<K2, Orswot<M>>>``): the K1×K2×M product element axis
    shards in whole-K1 blocks (pad_map3 keeps K1 divisible by the
    element axis), the K2 buffer shards over K1×K2, the K1 buffer over
    K1."""
    from ..ops.map3 import Map3State

    return Map3State(
        mo=map_orswot_specs(),
        odcl=P(REPLICA_AXIS, None, None),
        odkeys=P(REPLICA_AXIS, None, ELEMENT_AXIS),
        odvalid=P(REPLICA_AXIS, None),
    )


def map3_out_specs():
    from ..ops.map3 import Map3State

    return Map3State(
        mo=map_orswot_out_specs(),
        odcl=P(None, None),
        odkeys=P(None, ELEMENT_AXIS),
        odvalid=P(None),
    )


def pad_map3(state, rmult: int, k1mult: int):
    """Pad replicas with join identities and K1 (in whole K1×K2×M
    blocks) with never-present slots, to mesh-axis divisibility."""
    import jax.numpy as jnp

    nk1 = state.odkeys.shape[-1]
    k2 = state.mo.kdkeys.shape[-1] // nk1
    m = state.mo.core.ctr.shape[-2] // state.mo.kdkeys.shape[-1]

    pad_r = (-state.mo.core.top.shape[0]) % rmult
    if pad_r:
        from ..ops.map3 import empty

        ident = empty(
            nk1, k2, m,
            state.mo.core.top.shape[-1],
            state.odcl.shape[-2],
            batch=(pad_r,),
        )
        state = jax.tree.map(
            lambda x, p: jnp.concatenate([x, p.astype(x.dtype)], axis=0), state, ident
        )
    pad_k = (-nk1) % k1mult
    if pad_k:
        # Pad whole K1 blocks: on the inner map_orswot slab that is
        # exactly pad_map_orswot's key padding at k1mult*k2 granularity
        # ((-nk1*k2) % (k1mult*k2) == pad_k*k2); only the K1-level
        # buffer mask is map3-specific.
        state = state._replace(
            mo=pad_map_orswot(state.mo, 1, k1mult * k2),
            odkeys=jnp.pad(state.odkeys, ((0, 0), (0, 0), (0, pad_k))),
        )
    return state


def shard_map3(state, mesh: Mesh):
    """Place a batched Map<K1, Map<K2, Orswot>> state onto the mesh
    (replica × outer key) with the canonical layout."""
    state = pad_map3(state, mesh.shape[REPLICA_AXIS], mesh.shape[ELEMENT_AXIS])
    return jax.tree.map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)),
        state,
        map3_specs(),
    )
