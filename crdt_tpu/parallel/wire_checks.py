"""The ``wire`` static-check section: the fused wire path's gates.

Three detectors, each with a committed broken twin in
``analysis/fixtures.py`` proving it fires (the repo's
registration-is-the-coverage-contract discipline, applied to the wire):

1. **surface coverage** — every δ ring kind must have a registered
   wire surface (``analysis.registry.register_wire_surface`` — the
   codec know-function table in :mod:`.wire`); a new flavor that never
   wired its packets through the fused codec fails discovery here.
2. **fused gate soundness** — the in-kernel digest verdict, proven on
   the SAME committed three-slot fixture the layered ``gate_delta``
   detector uses (``jit_lint.check_orswot_gate``): the
   removal-carrying covered slot must SHIP (a top digest can never
   vouch for a removal — the PR 3 wider-gate lesson), the covered
   add-only slot must MASK, the uncovered slot must SHIP. The broken
   twin ``fixtures.fused_mask_drops_removals`` (the wider gate rebuilt
   as a know function) must fail this.
3. **wire round-trip** — pack → unpack must land the gated packet
   bit-identically (bitmaps, u16-pair ids, watermark-encoded clock
   lanes) and the kernel's in-pass checksum must equal
   ``faults.integrity.checksum`` of the wire tree; the bitmap
   truncation twin ``fixtures.bitmap_truncates_lanes`` must fail the
   bitmap property.
"""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp

from ..analysis.report import Finding


def _fixture_packet():
    """The committed three-slot dense fixture (jit_lint's gate
    geometry): slot 0 removal-carrying but top-covered, slot 1
    add-only covered, slot 2 uncovered — plus one VALID parked remove
    so the parked lanes exercise the wire too."""
    from ..ops.orswot import DTYPE
    from ..parallel.delta import DeltaPacket

    pkt = DeltaPacket(
        idx=jnp.arange(3, dtype=jnp.int32),
        rows=jnp.array([[1, 0], [1, 0], [7, 0]], DTYPE),
        ctxs=jnp.array([[2, 0], [1, 0], [7, 0]], DTYPE),
        valid=jnp.ones((3,), bool),
        dcl=jnp.array([[3, 1], [0, 0]], DTYPE),
        dmask=jnp.array(
            [[True, False, True, False], [False] * 4], bool
        ),
        dvalid=jnp.array([True, False]),
    )
    return pkt, jnp.array([5, 5], DTYPE)


def _codec(pkt, know_fn, gated=True):
    from . import wire

    return wire.WireCodec(
        jax.eval_shape(lambda: pkt), 4, know_fn,
        gated=gated, acked=False, interpret=True,
    )


def check_fused_gate(know_fn=None, label="wire.fused_gate"
                     ) -> List[Finding]:
    """Detector 2: the fused kernel's keep verdicts on the committed
    fixture (expected [ship, mask, ship])."""
    from . import wire

    pkt, digest = _fixture_packet()
    codec = _codec(pkt, know_fn or wire.know_dense)
    _, aux = codec.pack(pkt, rtop=digest)
    keep = [bool(k) for k in aux.keep]
    findings: List[Finding] = []
    if not keep[0]:
        findings.append(Finding(
            "wire-removal-dropped", label,
            "the fused gate masked a REMOVAL-CARRYING covered slot "
            "(ctx above rows under a covering top) — a top digest can "
            "never vouch for a removal; receivers would keep dead "
            "members live (the PR 3 wider-gate unsoundness, inside "
            "the kernel)",
        ))
    if keep[1]:
        findings.append(Finding(
            "wire-gate-dead", label,
            "a digest-covered add-only slot was NOT masked — the "
            "fused gate never strips redundant payload, so the wire "
            "pass is dead weight",
        ))
    if not keep[2]:
        findings.append(Finding(
            "wire-novelty-dropped", label,
            "an UNCOVERED slot was masked — novel content never "
            "reaches the wire and the ring cannot converge",
        ))
    return findings


def check_roundtrip(label="wire.roundtrip") -> List[Finding]:
    """Detector 3: pack → unpack bit-identity against the layered
    gate's output, and kernel-checksum parity with the stock
    integrity lane."""
    import numpy as np

    from ..faults.integrity import checksum
    from ..parallel.delta import gate_delta
    from . import wire

    pkt, digest = _fixture_packet()
    codec = _codec(pkt, wire.know_dense)
    w, aux = codec.pack(pkt, rtop=digest)
    dec = codec.unpack(w, own_top=digest)
    ref = gate_delta(pkt, digest)
    findings: List[Finding] = []
    keep = np.asarray(aux.keep)
    for (name, a), b in zip(
        wire._named_leaves(ref), jax.tree.leaves(dec)
    ):
        a, b = np.asarray(a), np.asarray(b)
        if name in ("dcl", "dmask"):
            dv = np.asarray(pkt.dvalid)
            a = np.where(
                dv.reshape((-1,) + (1,) * (a.ndim - 1)), a, 0
            )
        if name == "idx":
            # Masked slots ship zero indices; the receiver fills
            # DISTINCT no-op targets — equality holds on kept slots,
            # distinctness over all (wire.fill_invalid_idx).
            if len(set(b.tolist())) != b.shape[0]:
                findings.append(Finding(
                    "wire-roundtrip", label,
                    "reconstructed slot indices collide — duplicate "
                    "scatter targets make the apply order-dependent",
                ))
            a, b = a[keep], b[keep]
        if not np.array_equal(a, b):
            findings.append(Finding(
                "wire-roundtrip", label,
                f"decoded plane {name!r} differs from the gated "
                "packet — the wire format does not round-trip and "
                "converged states would diverge from the layered "
                "oracle",
            ))
    if int(aux.checksum) != int(checksum(w)):
        findings.append(Finding(
            "wire-checksum-drift", label,
            "the kernel's in-pass checksum differs from "
            "integrity.checksum of the wire tree — receivers would "
            "reject every intact packet (or accept corrupt ones)",
        ))
    return findings


def check_bitmaps(packer=None, label="wire.bitmaps") -> List[Finding]:
    """The bitmap pack/unpack property at awkward widths (word
    boundaries ± 1); ``packer`` is the injection seam the broken twin
    ``fixtures.bitmap_truncates_lanes`` fails through."""
    import numpy as np

    from ..ops import wire_kernels as wk

    packer = packer or wk.pack_bits
    rng = np.random.RandomState(7)
    findings: List[Finding] = []
    for n in (1, 31, 32, 33, 63, 64, 65, 200):
        bits = jnp.array(rng.rand(n) > 0.5)
        try:
            back = wk.unpack_bits(packer(bits), n)
            ok = bool(jnp.all(back == bits))
        except Exception:
            ok = False
        if not ok:
            findings.append(Finding(
                "wire-bitmap-truncated", label,
                f"a {n}-bool plane does not survive the bitmap "
                "round-trip — presence masks shorter than the packet's "
                "bool lanes turn valid slots invisible on the wire",
            ))
            break
    return findings


def static_checks() -> List[Finding]:
    """The ``wire`` section (Finding list, empty = clean): coverage +
    fused-gate soundness + wire round-trip, each detector proven
    firing by its committed broken twin."""
    from ..analysis import fixtures
    from ..analysis.registry import unwired_delta_kinds

    findings: List[Finding] = [
        Finding(
            "wire-coverage", kind,
            "δ ring kind has no registered wire surface — register "
            "its codec know function in parallel/wire.py "
            "(analysis.registry.register_wire_surface)",
        )
        for kind in unwired_delta_kinds()
    ]
    findings += check_fused_gate()
    findings += check_roundtrip()
    findings += check_bitmaps()

    # Broken twins must fire — a detector that passes its committed
    # twin has no teeth.
    broken = check_fused_gate(
        know_fn=fixtures.fused_mask_drops_removals,
        label="fixtures.fused_mask_drops_removals",
    )
    if not any(f.check == "wire-removal-dropped" for f in broken):
        findings.append(Finding(
            "broken-fixture-missed", "fused_mask_drops_removals",
            "the wider-gate-as-know-function twin PASSED the fused "
            "gate detector — the removal-preservation gate is not "
            "actually firing",
        ))
    broken = check_bitmaps(
        packer=fixtures.bitmap_truncates_lanes,
        label="fixtures.bitmap_truncates_lanes",
    )
    if not any(f.check == "wire-bitmap-truncated" for f in broken):
        findings.append(Finding(
            "broken-fixture-missed", "bitmap_truncates_lanes",
            "the word-dropping bit-packer twin PASSED the bitmap "
            "round-trip detector — the truncation gate is not "
            "actually firing",
        ))
    return findings


__all__ = [
    "check_bitmaps", "check_fused_gate", "check_roundtrip",
    "static_checks",
]
