"""Lattice-join collectives — usable only inside ``jax.shard_map``.

The reference ships serde bytes and lets the caller transport them
(SURVEY.md §3.1); here replica exchange is an XLA collective over the
mesh's ICI links. Because the ORSWOT join is associative, commutative
and idempotent (a true lattice join — property-tested bit-identical to
the oracle), a full mesh of N pairwise anti-entropy sessions collapses
into ONE all-reduce with the join as the monoid:

- power-of-two axis: **recursive doubling** — log2(P) rounds of
  ``ppermute`` with partner ``rank ^ 2^k`` + local join; every device
  ends with the global join (idempotence makes the overlap harmless,
  which is exactly why this is sound for joins and unsound for sums).
- any axis size: ``all_gather`` + local reduction tree.

``ring_round`` is the incremental alternative: one neighbor exchange per
call (gossip). P-1 rounds converge the whole ring — use when per-round
bandwidth must stay at one state, e.g. across DCN.
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..ops import orswot as ops
from ..ops.orswot import OrswotState


def _axis_size(axis_name: str) -> int:
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    # jax < 0.5 has no lax.axis_size; psum of a python literal stays a
    # static int under tracing, which the round-count loops need.
    return lax.psum(1, axis_name)


def all_reduce_lattice(
    local: Any,
    axis_name: str,
    join_fn: Callable[[Any, Any], Tuple[Any, jax.Array]],
    fold_fn: Callable[[Any], Tuple[Any, jax.Array]],
) -> Tuple[Any, jax.Array]:
    """All-reduce with an arbitrary lattice-join monoid over a mesh axis
    (the generic core of ``all_reduce_join``; works for any CRDT state
    pytree whose ``join_fn`` is associative/commutative/idempotent and
    returns ``(joined, flag)``)."""
    size = _axis_size(axis_name)
    overflow = jnp.zeros((), bool)
    if size & (size - 1) == 0 and size > 1:
        k = 1
        while k < size:
            perm = [(i, i ^ k) for i in range(size)]
            other = jax.tree.map(
                lambda x: lax.ppermute(x, axis_name, perm), local
            )
            local, of = join_fn(local, other)
            overflow = overflow | of
            k *= 2
    elif size > 1:
        gathered = jax.tree.map(
            lambda x: lax.all_gather(x, axis_name, axis=0), local
        )
        local, overflow = fold_fn(gathered)
    # Reduce the per-device overflow flags so the output is truly
    # replicated (recursive-doubling pairings differ per device).
    overflow = lax.psum(overflow.astype(jnp.int32), axis_name) > 0
    return local, overflow


def all_reduce_clock(clock: jax.Array, axis_name: str) -> jax.Array:
    """All-reduce with the VClock join monoid (element-wise max): this is
    just ``lax.pmax`` — XLA's native max-allreduce rides ICI directly.
    Covers VClock / GCounter / PNCounter anti-entropy (BASELINE configs
    1–2). Reference: src/vclock.rs ``CvRDT::merge`` folded over replicas.
    """
    return lax.pmax(clock, axis_name)


def all_reduce_join(
    local: OrswotState, axis_name: str
) -> Tuple[OrswotState, jax.Array]:
    """All-reduce with the ORSWOT lattice-join monoid over a mesh axis.

    ``local`` is one (unbatched) state per device. Returns the global
    join (replicated across the axis) and a replicated overflow flag
    (True if any deferred buffer overflowed anywhere — callers surface
    it as ``DeferredOverflow``).

    Reference semantics: src/orswot.rs ``CvRDT::merge`` applied along
    every edge of the full replica mesh (SURVEY.md §4.2) — collapsed to
    one collective per the north star.
    """
    return all_reduce_lattice(local, axis_name, ops.join, ops.fold)


def ring_round(
    local: OrswotState,
    axis_name: str,
    shift: int = 1,
    reduce_overflow: bool = True,
    join_fn: Callable[[Any, Any], Tuple[Any, jax.Array]] = ops.join,
) -> Tuple[OrswotState, jax.Array]:
    """One gossip round: receive the state of the neighbor ``shift``
    positions up-ring and join it in. P-1 unit-shift rounds converge all
    devices (each accumulates every other's history transitively).
    Per-round traffic: exactly one state per link — the bounded-bandwidth
    anti-entropy mode (vs the log-round burst of ``all_reduce_join``).

    With ``reduce_overflow=False`` the overflow flag is the raw
    device-local one (callers looping rounds should accumulate raw flags
    and reduce once at the end instead of paying a collective per round).
    """
    size = _axis_size(axis_name)
    perm = [(i, (i + shift) % size) for i in range(size)]
    other = jax.tree.map(lambda x: lax.ppermute(x, axis_name, perm), local)
    joined, of = join_fn(local, other)
    if reduce_overflow:
        of = lax.psum(of.astype(jnp.int32), axis_name) > 0
    return joined, of
